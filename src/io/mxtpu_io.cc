// mxtpu_io: native IO runtime for the TPU-native framework.
//
// TPU-native equivalent of the reference's C++ data pipeline
// (ref: src/io/iter_image_recordio_2.cc:880, src/io/iter_prefetcher.h,
// dmlc-core recordio). The reference builds a chain of
// recordio-chunk-reader -> threaded JPEG decode/augment -> batcher ->
// prefetcher; this file implements the same stages with a reorder-buffer
// worker pool feeding pre-allocated host batch buffers, exposed through a
// flat C ABI consumed via ctypes (no pybind11 in the image).
//
// Framing is binary-compatible with dmlc recordio:
//   [magic u32 = 0xced7230a][lrec u32: cflag<<29 | len][payload][pad to 4B]
// Image records carry an IRHeader {flag u32, label f32, id u64, id2 u64}
// followed by `flag` extra f32 labels, then JPEG bytes.

#include <atomic>
#include <algorithm>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <map>
#include <memory>
#include <mutex>
#include <random>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

#include <csetjmp>
#include <jpeglib.h>

namespace {

constexpr uint32_t kMagic = 0xced7230a;

#pragma pack(push, 1)
struct IRHeader {
  uint32_t flag;
  float label;
  uint64_t id;
  uint64_t id2;
};
#pragma pack(pop)
static_assert(sizeof(IRHeader) == 24, "IRHeader layout");

// ---------------------------------------------------------------------------
// RecordIO writer / reader
// ---------------------------------------------------------------------------

struct RecordIOWriter {
  FILE* fp = nullptr;
  uint64_t nrecords = 0;
};

struct RecordIOReader {
  FILE* fp = nullptr;
  std::vector<char> buf;
};

bool write_record(FILE* fp, const char* data, uint32_t len) {
  uint32_t head[2] = {kMagic, len & ((1u << 29) - 1)};
  if (fwrite(head, 4, 2, fp) != 2) return false;
  if (len && fwrite(data, 1, len, fp) != len) return false;
  uint32_t pad = (4 - len % 4) % 4;
  static const char zeros[4] = {0, 0, 0, 0};
  if (pad && fwrite(zeros, 1, pad, fp) != pad) return false;
  return true;
}

// Reads one framed record into out. Returns 0 on success, -1 on clean
// EOF, -2 on corruption (bad magic / truncated payload) — callers must
// not conflate truncation with end-of-data.
int read_record(FILE* fp, std::vector<char>* out) {
  uint32_t head[2];
  size_t got = fread(head, 4, 2, fp);
  if (got == 0 && feof(fp)) return -1;
  if (got != 2) return -2;
  if (head[0] != kMagic) return -2;
  uint32_t len = head[1] & ((1u << 29) - 1);
  out->resize(len);
  if (len && fread(out->data(), 1, len, fp) != len) return -2;
  uint32_t pad = (4 - len % 4) % 4;
  if (pad) fseek(fp, pad, SEEK_CUR);
  return 0;
}

// ---------------------------------------------------------------------------
// JPEG decode (libjpeg) + bilinear resize
// ---------------------------------------------------------------------------

struct JpegErr {
  jpeg_error_mgr mgr;
  jmp_buf jb;
};

void jpeg_err_exit(j_common_ptr cinfo) {
  JpegErr* err = reinterpret_cast<JpegErr*>(cinfo->err);
  longjmp(err->jb, 1);
}

// Decodes JPEG to RGB u8 HWC. Returns false on failure.
// target_short > 0 enables decode-time scaling: libjpeg's M/8 IDCT
// scaling decodes directly at reduced resolution, so a 360x480 source
// headed for resize_short=256 never pays for full-res IDCT — the same
// trick behind the reference's ~3000 img/s OpenCV path (cv::IMREAD +
// JPEG scale_denom; ref: src/io/image_recordio pipeline,
// docs note_data_loading.md:181).
bool decode_jpeg(const uint8_t* src, size_t len,
                 std::vector<uint8_t>* out, int* h, int* w,
                 int target_short = 0) {
  jpeg_decompress_struct cinfo;
  JpegErr jerr;
  cinfo.err = jpeg_std_error(&jerr.mgr);
  jerr.mgr.error_exit = jpeg_err_exit;
  if (setjmp(jerr.jb)) {
    jpeg_destroy_decompress(&cinfo);
    return false;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, src, len);
  if (jpeg_read_header(&cinfo, TRUE) != JPEG_HEADER_OK) {
    jpeg_destroy_decompress(&cinfo);
    return false;
  }
  cinfo.out_color_space = JCS_RGB;
  if (target_short > 0) {
    int shorter = std::min<int>(cinfo.image_height, cinfo.image_width);
    if (shorter > target_short) {
      // largest M/8 (M in 1..8) whose result still covers target_short
      int m = 8;
      while (m > 1 && (shorter * (m - 1)) / 8 >= target_short) --m;
      cinfo.scale_num = m;
      cinfo.scale_denom = 8;
      // approximations are fine here: a bilinear resize follows, which
      // washes out IFAST/plain-upsampling error. The unscaled path
      // keeps ISLOW + fancy upsampling for exact-decode parity
      // (tests/test_io_native.py decode_correct).
      cinfo.dct_method = JDCT_IFAST;
      cinfo.do_fancy_upsampling = FALSE;
    }
  }
  jpeg_start_decompress(&cinfo);
  *w = cinfo.output_width;
  *h = cinfo.output_height;
  out->resize(size_t(*w) * (*h) * 3);
  // hand libjpeg a whole batch of row pointers per call — per-scanline
  // calls pay the library's dispatch overhead height times
  std::vector<uint8_t*> rows(*h);
  for (int y = 0; y < *h; ++y)
    rows[y] = out->data() + size_t(y) * (*w) * 3;
  while (cinfo.output_scanline < cinfo.output_height) {
    jpeg_read_scanlines(&cinfo, rows.data() + cinfo.output_scanline,
                        cinfo.output_height - cinfo.output_scanline);
  }
  jpeg_finish_decompress(&cinfo);
  jpeg_destroy_decompress(&cinfo);
  return true;
}

// Bilinear RGB u8 HWC resize, fixed-point 8.8. For mild rescales
// (sh < 2*dh — the resize-short-side-then-crop regime) the horizontal
// lerp of each source row is computed ONCE into a u16 buffer and the
// vertical pass lerps between those rows: the naive per-output-pixel
// form recomputes each source row's horizontal lerp for every output
// row that touches it (~2*dh row-lerps vs sh here). Both paths produce
// bit-identical output — the separable pass stores the exact integer
// `top`/`bot` intermediates of the naive form.
void resize_bilinear(const uint8_t* src, int sh, int sw,
                     uint8_t* dst, int dh, int dw) {
  const float ry = dh > 1 ? float(sh - 1) / (dh - 1) : 0.f;
  const float rx = dw > 1 ? float(sw - 1) / (dw - 1) : 0.f;
  std::vector<int> x0s(dw), x1s(dw), wxs(dw);
  for (int x = 0; x < dw; ++x) {
    float fx = rx * x;
    int x0 = int(fx);
    x0s[x] = x0;
    x1s[x] = std::min(x0 + 1, sw - 1);
    wxs[x] = int((fx - x0) * 256.f + 0.5f);
  }
  if (sh < 2 * dh) {
    // separable: horizontal pass over all source rows, then vertical
    std::vector<uint16_t> hbuf(size_t(sh) * dw * 3);
    for (int y = 0; y < sh; ++y) {
      const uint8_t* row = src + size_t(y) * sw * 3;
      uint16_t* hrow = hbuf.data() + size_t(y) * dw * 3;
      for (int x = 0; x < dw; ++x) {
        const int o0 = x0s[x] * 3, o1 = x1s[x] * 3, wx = wxs[x];
        for (int c = 0; c < 3; ++c)
          hrow[x * 3 + c] =
              uint16_t((row[o0 + c] << 8) + (row[o1 + c] - row[o0 + c]) * wx);
      }
    }
    for (int y = 0; y < dh; ++y) {
      float fy = ry * y;
      int y0 = int(fy);
      int y1 = std::min(y0 + 1, sh - 1);
      int wy = int((fy - y0) * 256.f + 0.5f);
      const uint16_t* r0 = hbuf.data() + size_t(y0) * dw * 3;
      const uint16_t* r1 = hbuf.data() + size_t(y1) * dw * 3;
      uint8_t* drow = dst + size_t(y) * dw * 3;
      for (int k = 0; k < dw * 3; ++k) {
        int top = r0[k], bot = r1[k];
        drow[k] = uint8_t(((top << 8) + (bot - top) * wy + (1 << 15)) >> 16);
      }
    }
    return;
  }
  // strong downscale: most source rows are never sampled — lerp per
  // output pixel so skipped rows cost nothing
  for (int y = 0; y < dh; ++y) {
    float fy = ry * y;
    int y0 = int(fy);
    int y1 = std::min(y0 + 1, sh - 1);
    int wy = int((fy - y0) * 256.f + 0.5f);
    const uint8_t* r0 = src + size_t(y0) * sw * 3;
    const uint8_t* r1 = src + size_t(y1) * sw * 3;
    uint8_t* drow = dst + size_t(y) * dw * 3;
    for (int x = 0; x < dw; ++x) {
      const int o0 = x0s[x] * 3, o1 = x1s[x] * 3, wx = wxs[x];
      for (int c = 0; c < 3; ++c) {
        int top = (r0[o0 + c] << 8) + (r0[o1 + c] - r0[o0 + c]) * wx;
        int bot = (r1[o0 + c] << 8) + (r1[o1 + c] - r1[o0 + c]) * wx;
        drow[x * 3 + c] =
            uint8_t(((top << 8) + (bot - top) * wy + (1 << 15)) >> 16);
      }
    }
  }
}

// ---------------------------------------------------------------------------
// ImageRecordPipeline: offsets scan -> worker pool decode -> reorder queue
// ---------------------------------------------------------------------------

struct PipelineConfig {
  int batch_size;
  int height, width;       // output HW (channels fixed at 3)
  int label_width;
  int num_threads;
  int prefetch_depth;      // max in-flight decoded batches
  int resize_short;        // resize shorter side to this before crop (<=0 off)
  int shuffle;
  int rand_crop;
  int rand_mirror;
  uint64_t seed;
  float mean[3];
  float std[3];
  int output_u8;           // 1: emit decoded u8 NHWC, normalization deferred
                           // to the consumer (device-side); 0: f32 NCHW
                           // normalized on the host (legacy path)
  uint64_t cache_bytes;    // decode-cache budget (0 = off): decoded +
                           // short-side-resized images are kept across
                           // epochs up to this many bytes, so steady-state
                           // epochs skip JPEG decode entirely. Crop,
                           // mirror and normalization stay per-epoch.
};

// One decode-cache entry: the post-resize_short, pre-crop image (the
// last deterministic point of the augmentation chain) plus its labels.
struct CachedImage {
  std::vector<uint8_t> img;   // HWC u8
  int h = 0, w = 0;
  std::vector<float> label;
};

struct Batch {
  std::vector<float> data;    // f32 mode: batch*3*H*W, CHW per image
  std::vector<uint8_t> u8;    // u8 mode: batch*H*W*3, HWC per image
  std::vector<float> label;   // batch*label_width
  int count = 0;
};

struct Pipeline {
  PipelineConfig cfg;
  std::string path;
  std::vector<std::pair<uint64_t, uint32_t>> offsets;  // (pos, payload len)
  std::vector<uint32_t> order;
  uint64_t epoch = 0;

  std::vector<std::thread> workers;
  std::atomic<int> next_batch_to_claim{0};
  int num_batches = 0;

  std::mutex mu;
  std::condition_variable cv_ready, cv_space;
  std::map<int, Batch> ready;   // reorder buffer keyed by batch index
  int next_batch_out = 0;
  bool stopping = false;
  std::string error;            // first worker error, reported at next()

  Batch current;                // last batch handed to the caller (next())
  // leased batches: handed to the caller zero-copy, owned here until
  // mxt_pipeline_return — the caller wraps the buffer without copying
  std::map<uint64_t, Batch> leased;
  uint64_t next_lease_id = 1;

  // decode cache (immutable entries, shared_ptr so readers never hold
  // the lock while using one)
  std::mutex cache_mu;
  std::unordered_map<uint32_t, std::shared_ptr<const CachedImage>> cache;
  uint64_t cache_used = 0;
  std::atomic<uint64_t> cache_hits{0}, cache_misses{0};
};

std::shared_ptr<const CachedImage> cache_get(Pipeline* p, uint32_t rec) {
  if (p->cfg.cache_bytes == 0) return nullptr;
  std::lock_guard<std::mutex> lk(p->cache_mu);
  auto it = p->cache.find(rec);
  if (it == p->cache.end()) return nullptr;
  return it->second;
}

void cache_put(Pipeline* p, uint32_t rec,
               std::shared_ptr<const CachedImage> entry) {
  if (p->cfg.cache_bytes == 0) return;
  uint64_t sz = entry->img.size() + entry->label.size() * 4 + 64;
  std::lock_guard<std::mutex> lk(p->cache_mu);
  if (p->cache_used + sz > p->cfg.cache_bytes) return;  // budget full
  if (p->cache.emplace(rec, std::move(entry)).second) p->cache_used += sz;
}

// Scans the .rec file once, recording payload offsets (the analog of the
// reference's .idx file, built on the fly so one works without an index).
// A file that does not terminate at a clean record boundary is rejected
// (create fails, Python falls back to its raising reader) rather than
// silently truncated.
bool scan_offsets(Pipeline* p) {
  FILE* fp = fopen(p->path.c_str(), "rb");
  if (!fp) return false;
  fseek(fp, 0, SEEK_END);
  const uint64_t fsize = ftell(fp);
  fseek(fp, 0, SEEK_SET);
  uint32_t head[2];
  bool clean_end = false;
  for (;;) {
    uint64_t pos = ftell(fp);
    size_t got = fread(head, 4, 2, fp);
    if (got == 0 && feof(fp)) {
      clean_end = true;
      break;
    }
    if (got != 2 || head[0] != kMagic) break;
    uint32_t len = head[1] & ((1u << 29) - 1);
    uint32_t skip = len + (4 - len % 4) % 4;
    if (pos + 8 + skip > fsize) break;  // payload truncated (fseek past
                                        // EOF would not detect this)
    if (fseek(fp, skip, SEEK_CUR) != 0) break;
    p->offsets.emplace_back(pos + 8, len);
  }
  fclose(fp);
  return clean_end && !p->offsets.empty();
}

void set_error(Pipeline* p, const std::string& msg) {
  std::lock_guard<std::mutex> lk(p->mu);
  if (p->error.empty()) p->error = msg;
  p->cv_ready.notify_all();
}

// Crop/mirror/emit one decoded (and short-side-resized) image into slot
// i of the batch — the per-epoch tail of the augmentation chain, shared
// by the decode path and the decode-cache hit path.
bool finish_record(Pipeline* p, const CachedImage& ci, Batch* b,
                   int i, std::mt19937* rng) {
  const PipelineConfig& c = p->cfg;
  const std::vector<uint8_t>& img = ci.img;
  const int h = ci.h, w = ci.w;

  float* lbl = b->label.data() + size_t(i) * c.label_width;
  memcpy(lbl, ci.label.data(), size_t(c.label_width) * 4);

  // crop to target (random or center), resizing up if the source is smaller
  int th = c.height, tw = c.width;

  if (c.output_u8) {
    // u8 transport: crop/mirror straight into the batch's HWC slot —
    // no per-image temp, no normalize (deferred to the device)
    uint8_t* out = b->u8.data() + size_t(i) * th * tw * 3;
    if (h >= th && w >= tw) {
      int y0, x0;
      if (c.rand_crop) {
        y0 = int((*rng)() % (h - th + 1));
        x0 = int((*rng)() % (w - tw + 1));
      } else {
        y0 = (h - th) / 2;
        x0 = (w - tw) / 2;
      }
      for (int y = 0; y < th; ++y)
        memcpy(out + size_t(y) * tw * 3,
               img.data() + (size_t(y0 + y) * w + x0) * 3, size_t(tw) * 3);
    } else {
      resize_bilinear(img.data(), h, w, out, th, tw);
    }
    if (c.rand_mirror && ((*rng)() & 1)) {
      for (int y = 0; y < th; ++y) {
        uint8_t* row = out + size_t(y) * tw * 3;
        for (int x = 0; x < tw / 2; ++x) {
          uint8_t* a = row + x * 3;
          uint8_t* z = row + (tw - 1 - x) * 3;
          std::swap(a[0], z[0]);
          std::swap(a[1], z[1]);
          std::swap(a[2], z[2]);
        }
      }
    }
    b->count = std::max(b->count, i + 1);
    return true;
  }

  std::vector<uint8_t> crop(size_t(th) * tw * 3);
  if (h >= th && w >= tw) {
    int y0, x0;
    if (c.rand_crop) {
      y0 = int((*rng)() % (h - th + 1));
      x0 = int((*rng)() % (w - tw + 1));
    } else {
      y0 = (h - th) / 2;
      x0 = (w - tw) / 2;
    }
    for (int y = 0; y < th; ++y)
      memcpy(crop.data() + size_t(y) * tw * 3,
             img.data() + (size_t(y0 + y) * w + x0) * 3, size_t(tw) * 3);
  } else {
    resize_bilinear(img.data(), h, w, crop.data(), th, tw);
  }

  bool mirror = c.rand_mirror && ((*rng)() & 1);

  // HWC u8 -> CHW f32 normalized
  float* out = b->data.data() + size_t(i) * 3 * th * tw;
  for (int ch = 0; ch < 3; ++ch) {
    float m = c.mean[ch], s = c.std[ch];
    float inv = s != 0.f ? 1.f / s : 1.f;
    float* plane = out + size_t(ch) * th * tw;
    for (int y = 0; y < th; ++y) {
      for (int x = 0; x < tw; ++x) {
        int sx = mirror ? (tw - 1 - x) : x;
        plane[size_t(y) * tw + x] =
            (float(crop[(size_t(y) * tw + sx) * 3 + ch]) - m) * inv;
      }
    }
  }
  b->count = std::max(b->count, i + 1);
  return true;
}

// Decodes one record into slot i of the batch, populating the decode
// cache (budget permitting) so later epochs skip straight to
// finish_record.
bool process_record(Pipeline* p, uint32_t rec_idx,
                    const std::vector<char>& rec, Batch* b,
                    int i, std::mt19937* rng) {
  const PipelineConfig& c = p->cfg;
  if (rec.size() < sizeof(IRHeader)) return false;
  IRHeader hdr;
  memcpy(&hdr, rec.data(), sizeof(hdr));
  const uint8_t* payload =
      reinterpret_cast<const uint8_t*>(rec.data()) + sizeof(hdr);
  size_t payload_len = rec.size() - sizeof(hdr);

  auto entry = std::make_shared<CachedImage>();
  entry->label.assign(size_t(c.label_width), 0.f);
  if (hdr.flag > 0) {
    size_t nl = std::min<size_t>(hdr.flag, c.label_width);
    if (payload_len < hdr.flag * 4) return false;
    memcpy(entry->label.data(), payload, nl * 4);
    payload += hdr.flag * 4;
    payload_len -= hdr.flag * 4;
  } else {
    entry->label[0] = hdr.label;
  }

  std::vector<uint8_t> img;
  int h = 0, w = 0;
  // decode-time scaling only when a resize step follows: the scaled
  // decode feeds the same resize_bilinear, so output semantics are
  // unchanged; without resize_short, crops must come from the full-res
  // image, so decode full size
  if (!decode_jpeg(payload, payload_len, &img, &h, &w, c.resize_short))
    return false;

  if (c.resize_short > 0) {
    int shorter = std::min(h, w);
    if (shorter != c.resize_short) {
      int nh = int(int64_t(h) * c.resize_short / shorter);
      int nw = int(int64_t(w) * c.resize_short / shorter);
      std::vector<uint8_t> resized(size_t(nh) * nw * 3);
      resize_bilinear(img.data(), h, w, resized.data(), nh, nw);
      img.swap(resized);
      h = nh; w = nw;
    }
  }

  entry->img = std::move(img);
  entry->h = h;
  entry->w = w;
  bool ok = finish_record(p, *entry, b, i, rng);
  cache_put(p, rec_idx, std::move(entry));
  return ok;
}

void worker_loop(Pipeline* p, int worker_id) {
  FILE* fp = fopen(p->path.c_str(), "rb");
  if (!fp) {
    set_error(p, "worker failed to open " + p->path);
    return;
  }
  const PipelineConfig& c = p->cfg;
  std::mt19937 rng(uint32_t(c.seed + p->epoch * 1315423911u + worker_id));
  std::vector<char> rec;

  for (;;) {
    int bidx = p->next_batch_to_claim.fetch_add(1);
    if (bidx >= p->num_batches) break;
    {
      // bounded prefetch: don't run ahead of the consumer by > depth
      std::unique_lock<std::mutex> lk(p->mu);
      p->cv_space.wait(lk, [&] {
        return p->stopping || bidx < p->next_batch_out + c.prefetch_depth;
      });
      if (p->stopping) break;
    }
    Batch b;
    if (c.output_u8)
      b.u8.assign(size_t(c.batch_size) * c.height * c.width * 3, 0);
    else
      b.data.resize(size_t(c.batch_size) * 3 * c.height * c.width);
    b.label.assign(size_t(c.batch_size) * c.label_width, 0.f);
    int start = bidx * c.batch_size;
    int end = std::min<int>(start + c.batch_size, int(p->order.size()));
    int slot = 0;
    for (int k = start; k < end; ++k) {
      uint32_t rec_idx = p->order[k];
      if (auto cached = cache_get(p, rec_idx)) {
        p->cache_hits.fetch_add(1, std::memory_order_relaxed);
        if (finish_record(p, *cached, &b, slot, &rng)) ++slot;
        continue;
      }
      p->cache_misses.fetch_add(1, std::memory_order_relaxed);
      auto [pos, len] = p->offsets[rec_idx];
      rec.resize(len);
      if (fseek(fp, long(pos), SEEK_SET) != 0 ||
          fread(rec.data(), 1, len, fp) != len) {
        set_error(p, "short read in " + p->path);
        fclose(fp);
        return;
      }
      if (process_record(p, rec_idx, rec, &b, slot, &rng)) {
        ++slot;   // undecodable records are skipped, batch shrinks
      }
    }
    b.count = slot;
    {
      std::unique_lock<std::mutex> lk(p->mu);
      p->ready.emplace(bidx, std::move(b));
      p->cv_ready.notify_all();
    }
  }
  fclose(fp);
}

void stop_workers(Pipeline* p) {
  {
    std::lock_guard<std::mutex> lk(p->mu);
    p->stopping = true;
  }
  p->cv_space.notify_all();
  p->cv_ready.notify_all();
  for (auto& t : p->workers) t.join();
  p->workers.clear();
  p->stopping = false;
}

// Moves the next in-order non-empty batch into *out.
// Returns 1 on success, 0 at epoch end, -1 on error.
int take_next(Pipeline* p, Batch* out) {
  std::unique_lock<std::mutex> lk(p->mu);
  // a batch whose records all failed decode is skipped, not surfaced as
  // count==0 (which means epoch end to the caller)
  for (;;) {
    if (p->next_batch_out >= p->num_batches) return 0;
    p->cv_ready.wait(lk, [&] {
      return !p->error.empty() || p->ready.count(p->next_batch_out) > 0;
    });
    if (!p->error.empty()) return -1;
    auto it = p->ready.find(p->next_batch_out);
    *out = std::move(it->second);
    p->ready.erase(it);
    ++p->next_batch_out;
    p->cv_space.notify_all();
    if (out->count > 0) return 1;
  }
}

void start_epoch(Pipeline* p) {
  stop_workers(p);
  p->ready.clear();
  p->leased.clear();  // a reset invalidates outstanding leases
  p->next_batch_out = 0;
  p->next_batch_to_claim = 0;
  p->num_batches =
      int((p->order.size() + p->cfg.batch_size - 1) / p->cfg.batch_size);
  if (p->cfg.shuffle) {
    std::mt19937_64 rng(p->cfg.seed + p->epoch);
    std::shuffle(p->order.begin(), p->order.end(), rng);
  }
  int n = std::max(1, p->cfg.num_threads);
  for (int i = 0; i < n; ++i)
    p->workers.emplace_back(worker_loop, p, i);
}

}  // namespace

// ---------------------------------------------------------------------------
// C ABI
// ---------------------------------------------------------------------------

extern "C" {

void* mxt_recordio_writer_create(const char* path) {
  FILE* fp = fopen(path, "wb");
  if (!fp) return nullptr;
  auto* w = new RecordIOWriter();
  w->fp = fp;
  return w;
}

int mxt_recordio_writer_write(void* handle, const char* buf, uint32_t len,
                              uint64_t* out_pos) {
  auto* w = static_cast<RecordIOWriter*>(handle);
  if (out_pos) *out_pos = ftell(w->fp);
  if (!write_record(w->fp, buf, len)) return -1;
  ++w->nrecords;
  return 0;
}

void mxt_recordio_writer_free(void* handle) {
  auto* w = static_cast<RecordIOWriter*>(handle);
  if (w->fp) fclose(w->fp);
  delete w;
}

void* mxt_recordio_reader_create(const char* path) {
  FILE* fp = fopen(path, "rb");
  if (!fp) return nullptr;
  auto* r = new RecordIOReader();
  r->fp = fp;
  return r;
}

// Returns payload length (>=0) with *out pointing at an internal buffer
// valid until the next call, -1 at clean EOF, -2 on a corrupt record.
int64_t mxt_recordio_reader_read(void* handle, const char** out) {
  auto* r = static_cast<RecordIOReader*>(handle);
  int rc = read_record(r->fp, &r->buf);
  if (rc != 0) return rc;
  *out = r->buf.data();
  return int64_t(r->buf.size());
}

uint64_t mxt_recordio_reader_tell(void* handle) {
  return ftell(static_cast<RecordIOReader*>(handle)->fp);
}

int mxt_recordio_reader_seek(void* handle, uint64_t pos) {
  return fseek(static_cast<RecordIOReader*>(handle)->fp, long(pos), SEEK_SET);
}

void mxt_recordio_reader_free(void* handle) {
  auto* r = static_cast<RecordIOReader*>(handle);
  if (r->fp) fclose(r->fp);
  delete r;
}

// --- image pipeline --------------------------------------------------------

void* mxt_pipeline_create(const char* rec_path, int batch_size, int height,
                          int width, int label_width, int num_threads,
                          int prefetch_depth, int resize_short, int shuffle,
                          int rand_crop, int rand_mirror, uint64_t seed,
                          const float* mean, const float* stdv,
                          int output_u8, uint64_t cache_bytes) {
  auto* p = new Pipeline();
  p->path = rec_path;
  p->cfg = PipelineConfig{batch_size, height, width, label_width,
                          num_threads, std::max(1, prefetch_depth),
                          resize_short, shuffle, rand_crop, rand_mirror,
                          seed, {mean[0], mean[1], mean[2]},
                          {stdv[0], stdv[1], stdv[2]}, output_u8,
                          cache_bytes};
  if (!scan_offsets(p)) {
    delete p;
    return nullptr;
  }
  // probe: the first record must JPEG-decode, otherwise this dataset is
  // not ours to serve (e.g. PNG payloads) — fail so the caller can fall
  // back to a decoder that handles it, instead of yielding empty epochs
  {
    FILE* fp = fopen(p->path.c_str(), "rb");
    std::vector<char> rec(p->offsets[0].second);
    bool ok = fp != nullptr &&
              fseek(fp, long(p->offsets[0].first), SEEK_SET) == 0 &&
              fread(rec.data(), 1, rec.size(), fp) == rec.size();
    if (fp) fclose(fp);
    if (ok && rec.size() > sizeof(IRHeader)) {
      IRHeader hdr;
      memcpy(&hdr, rec.data(), sizeof(hdr));
      size_t off = sizeof(hdr) + size_t(hdr.flag) * 4;
      std::vector<uint8_t> img;
      int h = 0, w = 0;
      ok = off < rec.size() &&
           decode_jpeg(reinterpret_cast<const uint8_t*>(rec.data()) + off,
                       rec.size() - off, &img, &h, &w);
    }
    if (!ok) {
      delete p;
      return nullptr;
    }
  }
  p->order.resize(p->offsets.size());
  for (uint32_t i = 0; i < p->order.size(); ++i) p->order[i] = i;
  start_epoch(p);
  return p;
}

int64_t mxt_pipeline_num_records(void* handle) {
  return int64_t(static_cast<Pipeline*>(handle)->offsets.size());
}

// Blocks for the next decoded batch. Returns count (0 = epoch end, -1 =
// error; message via mxt_pipeline_error). Pointers valid until the next
// next()/reset()/free(). f32 mode only — u8 batches go through the
// lease API below.
int mxt_pipeline_next(void* handle, const float** data, const float** label) {
  auto* p = static_cast<Pipeline*>(handle);
  if (p->cfg.output_u8) {
    set_error(p, "mxt_pipeline_next: pipeline is in u8 mode, use "
                 "mxt_pipeline_next_lease");
    return -1;
  }
  int rc = take_next(p, &p->current);
  if (rc <= 0) return rc;
  *data = p->current.data.data();
  *label = p->current.label.data();
  return p->current.count;
}

// Zero-copy variant: the batch buffer stays owned by the pipeline until
// mxt_pipeline_return(lease_id) — the caller may wrap it (numpy
// as_array) without a defensive copy and hold it across further
// next_lease calls. *data points at u8 NHWC (u8 mode) or f32 NCHW (f32
// mode). Returns count (0 = epoch end, -1 = error).
int mxt_pipeline_next_lease(void* handle, const void** data,
                            const float** label, uint64_t* lease_id) {
  auto* p = static_cast<Pipeline*>(handle);
  Batch b;
  int rc = take_next(p, &b);
  if (rc <= 0) return rc;
  std::lock_guard<std::mutex> lk(p->mu);
  uint64_t lid = p->next_lease_id++;
  Batch& slot = p->leased[lid];
  slot = std::move(b);
  *data = p->cfg.output_u8
              ? static_cast<const void*>(slot.u8.data())
              : static_cast<const void*>(slot.data.data());
  *label = slot.label.data();
  *lease_id = lid;
  return slot.count;
}

// Releases a leased batch buffer. Returns 0, or -1 for an unknown id
// (double return / id from before a reset).
int mxt_pipeline_return(void* handle, uint64_t lease_id) {
  auto* p = static_cast<Pipeline*>(handle);
  std::lock_guard<std::mutex> lk(p->mu);
  return p->leased.erase(lease_id) ? 0 : -1;
}

// Number of batches currently leased out (telemetry / leak checks).
int mxt_pipeline_leased(void* handle) {
  auto* p = static_cast<Pipeline*>(handle);
  std::lock_guard<std::mutex> lk(p->mu);
  return int(p->leased.size());
}

// Decode-cache counters (telemetry): lifetime hits/misses and bytes
// currently held.
void mxt_pipeline_cache_stats(void* handle, uint64_t* hits,
                              uint64_t* misses, uint64_t* bytes) {
  auto* p = static_cast<Pipeline*>(handle);
  if (hits) *hits = p->cache_hits.load(std::memory_order_relaxed);
  if (misses) *misses = p->cache_misses.load(std::memory_order_relaxed);
  if (bytes) {
    std::lock_guard<std::mutex> lk(p->cache_mu);
    *bytes = p->cache_used;
  }
}

const char* mxt_pipeline_error(void* handle) {
  return static_cast<Pipeline*>(handle)->error.c_str();
}

// Rewinds to a fresh epoch (reshuffling if configured).
void mxt_pipeline_reset(void* handle) {
  auto* p = static_cast<Pipeline*>(handle);
  ++p->epoch;
  start_epoch(p);
}

void mxt_pipeline_free(void* handle) {
  auto* p = static_cast<Pipeline*>(handle);
  stop_workers(p);
  delete p;
}

}  // extern "C"
