/* Example external operator library (analog of the reference's
 * example/extensions/lib_custom_op): builds against mxtpu_lib_api.h only.
 *
 *   my_relu   — elementwise max(x, 0), any supported dtype
 *   my_gemm   — (M,K)x(K,N) float32 matmul
 *   my_split2 — splits (N, 2C) into two (N, C) halves (multi-output)
 */
#include <cstring>
#include <string>

#include "mxtpu_lib_api.h"

namespace {

std::string g_err;

struct OpDef {
  const char* name;
  int n_out;
};

const OpDef kOps[] = {
    {"my_relu", 1},
    {"my_gemm", 1},
    {"my_split2", 2},
};
const int kNumOps = sizeof(kOps) / sizeof(kOps[0]);

int fail(const std::string& msg) {
  g_err = msg;
  return 1;
}

int64_t numel(const MXTPUTensor& t) {
  int64_t n = 1;
  for (int i = 0; i < t.ndim; ++i) n *= t.shape[i];
  return n;
}

int dtype_size(int dtype) {
  switch (dtype) {
    case kMXTPUFloat64: case kMXTPUInt64: return 8;
    case kMXTPUFloat32: case kMXTPUInt32: return 4;
    case kMXTPUFloat16: return 2;
    case kMXTPUUint8: case kMXTPUInt8: return 1;
    default: return -1;
  }
}

template <typename T>
void relu(const T* in, T* out, int64_t n) {
  for (int64_t i = 0; i < n; ++i) out[i] = in[i] > T(0) ? in[i] : T(0);
}

}  // namespace

extern "C" {

int MXTPULibVersion(void) { return MXTPU_LIB_API_VERSION; }

int MXTPULibOpCount(void) { return kNumOps; }

const char* MXTPULibOpName(int idx) {
  return (idx >= 0 && idx < kNumOps) ? kOps[idx].name : nullptr;
}

int MXTPULibOpNumOutputs(int idx) {
  return (idx >= 0 && idx < kNumOps) ? kOps[idx].n_out : -1;
}

const char* MXTPULibLastError(void) { return g_err.c_str(); }

int MXTPULibOpInferShape(int idx, const MXTPUTensor* ins, int n_in,
                         MXTPUTensor* outs, int n_out) {
  switch (idx) {
    case 0:  /* my_relu: shape/dtype pass-through */
      if (n_in != 1 || n_out != 1) return fail("my_relu: arity");
      outs[0].ndim = ins[0].ndim;
      std::memcpy(outs[0].shape, ins[0].shape, sizeof(ins[0].shape));
      outs[0].dtype = ins[0].dtype;
      return 0;
    case 1:  /* my_gemm: (M,K)x(K,N) -> (M,N) */
      if (n_in != 2 || n_out != 1) return fail("my_gemm: arity");
      if (ins[0].ndim != 2 || ins[1].ndim != 2 ||
          ins[0].shape[1] != ins[1].shape[0])
        return fail("my_gemm: need (M,K)x(K,N)");
      if (ins[0].dtype != kMXTPUFloat32 || ins[1].dtype != kMXTPUFloat32)
        return fail("my_gemm: float32 only");
      outs[0].ndim = 2;
      outs[0].shape[0] = ins[0].shape[0];
      outs[0].shape[1] = ins[1].shape[1];
      outs[0].dtype = kMXTPUFloat32;
      return 0;
    case 2:  /* my_split2: (N, 2C) -> 2x (N, C) */
      if (n_in != 1 || n_out != 2) return fail("my_split2: arity");
      if (ins[0].ndim != 2 || ins[0].shape[1] % 2 != 0)
        return fail("my_split2: need (N, even)");
      for (int o = 0; o < 2; ++o) {
        outs[o].ndim = 2;
        outs[o].shape[0] = ins[0].shape[0];
        outs[o].shape[1] = ins[0].shape[1] / 2;
        outs[o].dtype = ins[0].dtype;
      }
      return 0;
    default:
      return fail("bad op index");
  }
}

int MXTPULibOpCompute(int idx, const MXTPUTensor* ins, int n_in,
                      MXTPUTensor* outs, int n_out) {
  switch (idx) {
    case 0: {
      const int64_t n = numel(ins[0]);
      switch (ins[0].dtype) {
        case kMXTPUFloat32:
          relu(static_cast<const float*>(ins[0].data),
               static_cast<float*>(outs[0].data), n);
          return 0;
        case kMXTPUFloat64:
          relu(static_cast<const double*>(ins[0].data),
               static_cast<double*>(outs[0].data), n);
          return 0;
        case kMXTPUInt32:
          relu(static_cast<const int32_t*>(ins[0].data),
               static_cast<int32_t*>(outs[0].data), n);
          return 0;
        default:
          return fail("my_relu: unsupported dtype");
      }
    }
    case 1: {
      const int64_t M = ins[0].shape[0], K = ins[0].shape[1],
                    N = ins[1].shape[1];
      const float* a = static_cast<const float*>(ins[0].data);
      const float* b = static_cast<const float*>(ins[1].data);
      float* c = static_cast<float*>(outs[0].data);
      for (int64_t i = 0; i < M; ++i)
        for (int64_t j = 0; j < N; ++j) {
          float acc = 0.f;
          for (int64_t k = 0; k < K; ++k) acc += a[i * K + k] * b[k * N + j];
          c[i * N + j] = acc;
        }
      return 0;
    }
    case 2: {
      const int64_t N = ins[0].shape[0], C2 = ins[0].shape[1];
      const int64_t C = C2 / 2;
      const int esize = dtype_size(ins[0].dtype);
      if (esize < 0) return fail("my_split2: unsupported dtype");
      const char* src = static_cast<const char*>(ins[0].data);
      for (int o = 0; o < 2; ++o) {
        char* dst = static_cast<char*>(outs[o].data);
        for (int64_t i = 0; i < N; ++i)
          std::memcpy(dst + i * C * esize,
                      src + (i * C2 + o * C) * esize, C * esize);
      }
      return 0;
    }
    default:
      return fail("bad op index");
  }
}

}  /* extern "C" */
