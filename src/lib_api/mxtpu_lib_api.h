/* MXTPU external operator library ABI.
 *
 * TPU-native analog of the reference's runtime op-library interface
 * (ref: include/mxnet/lib_api.h:626 REGISTER_OP and the MXLoadLib C API):
 * a shared object built against ONLY this header can be loaded at runtime
 * with `mxnet_tpu.library.load("libfoo.so")` — no framework recompile.
 * Loaded ops register into the op registry; their compute runs on the
 * host via jax.pure_callback (inside or outside jit), with shapes/dtypes
 * resolved at trace time through MXTPULibOpInferShape.
 *
 * ABI rules: plain C, no callbacks across the boundary; the framework
 * drives everything through the five exported functions below. Tensors
 * are dense, row-major, host memory. dtype codes match the framework's
 * (and the reference's) NDArray type codes.
 */
#ifndef MXTPU_LIB_API_H_
#define MXTPU_LIB_API_H_

#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

#define MXTPU_LIB_API_VERSION 1
#define MXTPU_MAX_NDIM 8

/* NDArray dtype codes (parity with the reference's mshadow type flags) */
enum MXTPUDType {
  kMXTPUFloat32 = 0,
  kMXTPUFloat64 = 1,
  kMXTPUFloat16 = 2,
  kMXTPUUint8 = 3,
  kMXTPUInt32 = 4,
  kMXTPUInt8 = 5,
  kMXTPUInt64 = 6,
};

typedef struct {
  void* data;                   /* host pointer; NULL during shape infer */
  int64_t shape[MXTPU_MAX_NDIM];
  int32_t ndim;
  int32_t dtype;                /* MXTPUDType */
} MXTPUTensor;

/* A conforming library exports these five symbols.
 * All int-returning entry points: 0 = success, nonzero = failure
 * (use MXTPULibLastError for the message, may return NULL). */

/* ABI version — must equal MXTPU_LIB_API_VERSION. */
int MXTPULibVersion(void);

/* Number of operators provided. */
int MXTPULibOpCount(void);

/* Name of operator `idx` (static storage). */
const char* MXTPULibOpName(int idx);

/* Number of outputs of operator `idx`. */
int MXTPULibOpNumOutputs(int idx);

/* Fill outs[i].shape/ndim/dtype from the input shapes/dtypes.
 * ins[i].data is NULL here (trace time). */
int MXTPULibOpInferShape(int idx, const MXTPUTensor* ins, int n_in,
                         MXTPUTensor* outs, int n_out);

/* Run the operator on host buffers. outs are pre-allocated per the
 * shapes produced by MXTPULibOpInferShape. */
int MXTPULibOpCompute(int idx, const MXTPUTensor* ins, int n_in,
                      MXTPUTensor* outs, int n_out);

/* Optional: last error message (static storage), or NULL. */
const char* MXTPULibLastError(void);

#ifdef __cplusplus
}
#endif

#endif  /* MXTPU_LIB_API_H_ */
