// C training API implementation — embeds CPython and drives
// mxnet_tpu._train_embed (see c_api_train.h for the contract; ref:
// src/c_api/c_api.cc autograd/cachedop/kvstore groups).
//
// Thread-model identical to the predict lib: every entry point takes
// the GIL via PyGILState_Ensure, so it works both inside an existing
// Python process (ctypes hosts) and from a standalone C program (lazy
// Py_InitializeEx).

#include "c_api_train.h"

#include <Python.h>

#include <cstring>
#include <mutex>
#include <string>
#include <vector>

namespace {

thread_local std::string g_last_error;

void set_error(const std::string &msg) { g_last_error = msg; }

void set_error_from_python() {
  PyObject *type = nullptr, *value = nullptr, *tb = nullptr;
  PyErr_Fetch(&type, &value, &tb);
  PyErr_NormalizeException(&type, &value, &tb);
  std::string msg = "python error";
  if (value) {
    PyObject *s = PyObject_Str(value);
    if (s) {
      const char *utf8 = PyUnicode_AsUTF8(s);
      if (utf8) msg = utf8;
      else PyErr_Clear();
      Py_DECREF(s);
    }
  }
  Py_XDECREF(type);
  Py_XDECREF(value);
  Py_XDECREF(tb);
  set_error(msg);
}

std::once_flag g_init_flag;

void ensure_python() {
  std::call_once(g_init_flag, []() {
    if (!Py_IsInitialized()) {
      Py_InitializeEx(0);
      PyEval_SaveThread();
    }
  });
}

class GIL {
 public:
  GIL() { state_ = PyGILState_Ensure(); }
  ~GIL() { PyGILState_Release(state_); }

 private:
  PyGILState_STATE state_;
};

PyObject *embed_module() {
  static PyObject *mod = nullptr;
  if (mod == nullptr) {
    mod = PyImport_ImportModule("mxnet_tpu._train_embed");
  }
  return mod;
}

// Handles are owned PyObject references; a Symbol handle additionally
// owns the C-string block ListInputs may have handed out.
struct SymbolBox {
  PyObject *obj = nullptr;
  std::vector<std::string> input_names;
  std::vector<const char *> input_ptrs;
};

PyObject *as_py(NDArrayHandle h) { return static_cast<PyObject *>(h); }

PyObject *handle_list(uint32_t n, NDArrayHandle *hs) {
  PyObject *lst = PyList_New(n);
  for (uint32_t i = 0; i < n; ++i) {
    PyObject *o = as_py(hs[i]);
    Py_INCREF(o);
    PyList_SetItem(lst, i, o);
  }
  return lst;
}

// Unpack a python list of NDArrays into caller-provided handle slots
// (each slot becomes an owned reference the caller frees with
// MXTrainNDArrayFree).
int unpack_outputs(PyObject *res, uint32_t *num_outputs,
                   NDArrayHandle *outputs, uint32_t max_outputs) {
  if (!PyList_Check(res)) {
    set_error("embed call did not return a list");
    return -1;
  }
  Py_ssize_t n = PyList_Size(res);
  if (static_cast<uint32_t>(n) > max_outputs) {
    set_error("output buffer too small: need " + std::to_string(n) +
              " slots, got " + std::to_string(max_outputs));
    return -1;
  }
  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject *o = PyList_GetItem(res, i);
    Py_INCREF(o);
    outputs[i] = o;
  }
  *num_outputs = static_cast<uint32_t>(n);
  return 0;
}

}  // namespace

extern "C" {

const char *MXTrainGetLastError(void) { return g_last_error.c_str(); }

/* ---------------- NDArray ---------------- */

int MXTrainNDArrayCreate(const uint32_t *shape, uint32_t ndim, int dtype,
                         NDArrayHandle *out) {
  ensure_python();
  GIL gil;
  PyObject *mod = embed_module();
  if (!mod) { set_error_from_python(); return -1; }
  PyObject *shp = PyTuple_New(ndim);
  for (uint32_t i = 0; i < ndim; ++i)
    PyTuple_SetItem(shp, i, PyLong_FromUnsignedLong(shape[i]));
  PyObject *res = PyObject_CallMethod(mod, "create_ndarray", "Oi", shp,
                                      dtype);
  Py_DECREF(shp);
  if (!res) { set_error_from_python(); return -1; }
  *out = res;
  return 0;
}

int MXTrainNDArrayFree(NDArrayHandle h) {
  if (!h) return 0;
  GIL gil;
  Py_DECREF(as_py(h));
  return 0;
}

int MXTrainNDArraySyncCopyFromCPU(NDArrayHandle h, const void *data,
                                  size_t nbytes) {
  GIL gil;
  PyObject *mod = embed_module();
  PyObject *buf = PyBytes_FromStringAndSize(
      static_cast<const char *>(data), static_cast<Py_ssize_t>(nbytes));
  PyObject *res = PyObject_CallMethod(mod, "copy_from_bytes", "OO",
                                      as_py(h), buf);
  Py_DECREF(buf);
  if (!res) { set_error_from_python(); return -1; }
  Py_DECREF(res);
  return 0;
}

int MXTrainNDArraySyncCopyToCPU(NDArrayHandle h, void *data, size_t nbytes) {
  GIL gil;
  PyObject *mod = embed_module();
  PyObject *arr = PyObject_CallMethod(mod, "copy_to_numpy", "O", as_py(h));
  if (!arr) { set_error_from_python(); return -1; }
  PyObject *bytes = PyObject_CallMethod(arr, "tobytes", nullptr);
  Py_DECREF(arr);
  if (!bytes) { set_error_from_python(); return -1; }
  char *src = nullptr;
  Py_ssize_t len = 0;
  PyBytes_AsStringAndSize(bytes, &src, &len);
  if (static_cast<size_t>(len) != nbytes) {
    Py_DECREF(bytes);
    set_error("size mismatch: array holds " + std::to_string(len) +
              " bytes, caller buffer is " + std::to_string(nbytes) +
              " (dtype or shape disagreement)");
    return -1;
  }
  memcpy(data, src, static_cast<size_t>(len));
  Py_DECREF(bytes);
  return 0;
}

int MXTrainNDArrayGetShape(NDArrayHandle h, uint32_t *out_ndim,
                           uint32_t *out_shape) {
  GIL gil;
  PyObject *mod = embed_module();
  PyObject *shp = PyObject_CallMethod(mod, "get_shape", "O", as_py(h));
  if (!shp) { set_error_from_python(); return -1; }
  Py_ssize_t n = PyTuple_Size(shp);
  if (n > 8) {
    Py_DECREF(shp);
    set_error("ndim " + std::to_string(n) +
              " exceeds the 8-slot shape buffer contract");
    return -1;
  }
  *out_ndim = static_cast<uint32_t>(n);
  for (Py_ssize_t i = 0; i < n; ++i)
    out_shape[i] = static_cast<uint32_t>(
        PyLong_AsUnsignedLong(PyTuple_GetItem(shp, i)));
  Py_DECREF(shp);
  return 0;
}

/* ---------------- imperative invoke ---------------- */

int MXTrainImperativeInvoke(const char *op_name, uint32_t num_inputs,
                            NDArrayHandle *inputs, uint32_t *num_outputs,
                            NDArrayHandle *outputs, uint32_t max_outputs,
                            uint32_t num_params, const char **param_keys,
                            const char **param_vals) {
  ensure_python();
  GIL gil;
  PyObject *mod = embed_module();
  if (!mod) { set_error_from_python(); return -1; }
  PyObject *ins = handle_list(num_inputs, inputs);
  PyObject *keys = PyList_New(num_params);
  PyObject *vals = PyList_New(num_params);
  for (uint32_t i = 0; i < num_params; ++i) {
    PyList_SetItem(keys, i, PyUnicode_FromString(param_keys[i]));
    PyList_SetItem(vals, i, PyUnicode_FromString(param_vals[i]));
  }
  PyObject *res = PyObject_CallMethod(mod, "imperative_invoke", "sOOO",
                                      op_name, ins, keys, vals);
  Py_DECREF(ins);
  Py_DECREF(keys);
  Py_DECREF(vals);
  if (!res) { set_error_from_python(); return -1; }
  int rc = unpack_outputs(res, num_outputs, outputs, max_outputs);
  Py_DECREF(res);
  return rc;
}

/* ---------------- autograd ---------------- */

int MXTrainAutogradSetIsRecording(int is_recording, int *prev) {
  ensure_python();
  GIL gil;
  PyObject *mod = embed_module();
  PyObject *res = PyObject_CallMethod(mod, "set_recording", "i",
                                      is_recording);
  if (!res) { set_error_from_python(); return -1; }
  if (prev) *prev = static_cast<int>(PyLong_AsLong(res));
  Py_DECREF(res);
  return 0;
}

int MXTrainAutogradSetIsTraining(int is_training, int *prev) {
  ensure_python();
  GIL gil;
  PyObject *mod = embed_module();
  PyObject *res = PyObject_CallMethod(mod, "set_training", "i",
                                      is_training);
  if (!res) { set_error_from_python(); return -1; }
  if (prev) *prev = static_cast<int>(PyLong_AsLong(res));
  Py_DECREF(res);
  return 0;
}

int MXTrainAutogradMarkVariables(uint32_t num, NDArrayHandle *vars,
                                 const uint32_t *grad_reqs,
                                 NDArrayHandle *grads) {
  GIL gil;
  PyObject *mod = embed_module();
  PyObject *vs = handle_list(num, vars);
  PyObject *gs = handle_list(num, grads);
  PyObject *reqs = PyList_New(num);
  for (uint32_t i = 0; i < num; ++i)
    PyList_SetItem(reqs, i, PyLong_FromUnsignedLong(
        grad_reqs ? grad_reqs[i] : 1));
  PyObject *res = PyObject_CallMethod(mod, "mark_variables", "OOO", vs,
                                      reqs, gs);
  Py_DECREF(vs);
  Py_DECREF(gs);
  Py_DECREF(reqs);
  if (!res) { set_error_from_python(); return -1; }
  Py_DECREF(res);
  return 0;
}

int MXTrainAutogradBackward(uint32_t num_outputs, NDArrayHandle *outputs,
                            NDArrayHandle *out_grads, int retain_graph) {
  GIL gil;
  PyObject *mod = embed_module();
  PyObject *outs = handle_list(num_outputs, outputs);
  PyObject *ogs = out_grads ? handle_list(num_outputs, out_grads)
                            : (Py_INCREF(Py_None), Py_None);
  PyObject *res = PyObject_CallMethod(mod, "backward", "OOi", outs, ogs,
                                      retain_graph);
  Py_DECREF(outs);
  Py_DECREF(ogs);
  if (!res) { set_error_from_python(); return -1; }
  Py_DECREF(res);
  return 0;
}

int MXTrainNDArrayGetGrad(NDArrayHandle h, NDArrayHandle *out) {
  GIL gil;
  PyObject *mod = embed_module();
  PyObject *res = PyObject_CallMethod(mod, "get_grad", "O", as_py(h));
  if (!res) { set_error_from_python(); return -1; }
  if (res == Py_None) {
    Py_DECREF(res);
    set_error("array has no gradient (not marked / backward not run)");
    return -1;
  }
  *out = res;
  return 0;
}

/* ---------------- symbol + CachedOp ---------------- */

int MXTrainSymbolCreateFromJSON(const char *json, SymbolHandle *out) {
  ensure_python();
  GIL gil;
  PyObject *mod = embed_module();
  if (!mod) { set_error_from_python(); return -1; }
  PyObject *res = PyObject_CallMethod(mod, "symbol_from_json", "s", json);
  if (!res) { set_error_from_python(); return -1; }
  SymbolBox *box = new SymbolBox();
  box->obj = res;
  *out = box;
  return 0;
}

int MXTrainSymbolFree(SymbolHandle h) {
  if (!h) return 0;
  GIL gil;
  SymbolBox *box = static_cast<SymbolBox *>(h);
  Py_XDECREF(box->obj);
  delete box;
  return 0;
}

int MXTrainSymbolGetNumOutputs(SymbolHandle h, uint32_t *out) {
  GIL gil;
  PyObject *mod = embed_module();
  SymbolBox *box = static_cast<SymbolBox *>(h);
  PyObject *res = PyObject_CallMethod(mod, "symbol_num_outputs", "O",
                                      box->obj);
  if (!res) { set_error_from_python(); return -1; }
  *out = static_cast<uint32_t>(PyLong_AsUnsignedLong(res));
  Py_DECREF(res);
  return 0;
}

int MXTrainSymbolListInputs(SymbolHandle h, uint32_t *num,
                            const char ***out_names) {
  GIL gil;
  PyObject *mod = embed_module();
  SymbolBox *box = static_cast<SymbolBox *>(h);
  PyObject *res = PyObject_CallMethod(mod, "symbol_list_inputs", "O",
                                      box->obj);
  if (!res) { set_error_from_python(); return -1; }
  Py_ssize_t n = PySequence_Size(res);
  box->input_names.clear();
  box->input_ptrs.clear();
  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject *item = PySequence_GetItem(res, i);
    const char *s = PyUnicode_AsUTF8(item);
    box->input_names.emplace_back(s ? s : "");
    Py_DECREF(item);
  }
  Py_DECREF(res);
  for (auto &s : box->input_names) box->input_ptrs.push_back(s.c_str());
  *num = static_cast<uint32_t>(n);
  *out_names = box->input_ptrs.data();
  return 0;
}

int MXTrainCreateCachedOp(SymbolHandle sym, CachedOpHandle *out) {
  GIL gil;
  PyObject *mod = embed_module();
  SymbolBox *box = static_cast<SymbolBox *>(sym);
  PyObject *res = PyObject_CallMethod(mod, "create_cached_op", "O",
                                      box->obj);
  if (!res) { set_error_from_python(); return -1; }
  *out = res;
  return 0;
}

int MXTrainFreeCachedOp(CachedOpHandle h) {
  if (!h) return 0;
  GIL gil;
  Py_DECREF(as_py(h));
  return 0;
}

int MXTrainInvokeCachedOp(CachedOpHandle h, uint32_t num_inputs,
                          NDArrayHandle *inputs, uint32_t *num_outputs,
                          NDArrayHandle *outputs, uint32_t max_outputs) {
  GIL gil;
  PyObject *mod = embed_module();
  PyObject *ins = handle_list(num_inputs, inputs);
  PyObject *res = PyObject_CallMethod(mod, "invoke_cached_op", "OO",
                                      as_py(h), ins);
  Py_DECREF(ins);
  if (!res) { set_error_from_python(); return -1; }
  int rc = unpack_outputs(res, num_outputs, outputs, max_outputs);
  Py_DECREF(res);
  return rc;
}

/* ---------------- KVStore ---------------- */

int MXTrainKVStoreCreate(const char *type, KVStoreHandle *out) {
  ensure_python();
  GIL gil;
  PyObject *mod = embed_module();
  if (!mod) { set_error_from_python(); return -1; }
  PyObject *res = PyObject_CallMethod(mod, "kvstore_create", "s", type);
  if (!res) { set_error_from_python(); return -1; }
  *out = res;
  return 0;
}

int MXTrainKVStoreFree(KVStoreHandle h) {
  if (!h) return 0;
  GIL gil;
  Py_DECREF(as_py(h));
  return 0;
}

namespace {
int kv_call(const char *method, KVStoreHandle h, uint32_t num,
            const int *keys, NDArrayHandle *vals, int priority,
            bool with_priority) {
  GIL gil;
  PyObject *mod = embed_module();
  PyObject *ks = PyList_New(num);
  for (uint32_t i = 0; i < num; ++i)
    PyList_SetItem(ks, i, PyLong_FromLong(keys[i]));
  PyObject *vs = handle_list(num, vals);
  PyObject *res = with_priority
      ? PyObject_CallMethod(mod, method, "OOOi", as_py(h), ks, vs,
                            priority)
      : PyObject_CallMethod(mod, method, "OOO", as_py(h), ks, vs);
  Py_DECREF(ks);
  Py_DECREF(vs);
  if (!res) { set_error_from_python(); return -1; }
  Py_DECREF(res);
  return 0;
}
}  // namespace

int MXTrainKVStoreInit(KVStoreHandle h, uint32_t num, const int *keys,
                       NDArrayHandle *vals) {
  return kv_call("kvstore_init", h, num, keys, vals, 0, false);
}

int MXTrainKVStorePush(KVStoreHandle h, uint32_t num, const int *keys,
                       NDArrayHandle *vals, int priority) {
  return kv_call("kvstore_push", h, num, keys, vals, priority, true);
}

int MXTrainKVStorePull(KVStoreHandle h, uint32_t num, const int *keys,
                       NDArrayHandle *outs, int priority) {
  return kv_call("kvstore_pull", h, num, keys, outs, priority, true);
}

}  // extern "C"
