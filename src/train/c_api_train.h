/*
 * C TRAINING API — the reference c_api.h groups a C embedder needs to
 * train: NDArray create/copy, imperative op invocation, autograd
 * record/mark/backward, CachedOp over a symbol JSON, and KVStore
 * init/push/pull (ref: include/mxnet/c_api.h:1251 MXAutogradBackwardEx,
 * :1341 MXInvokeCachedOpEx, :1405 MXImperativeInvokeEx, :2670
 * MXKVStorePush).
 *
 * Implementation embeds CPython and drives mxnet_tpu._train_embed, so C
 * training runs the exact same registry/vjp/kvstore as the Python
 * frontend (the TPU-native analog of the reference C API sitting on its
 * C++ engine). Handles are opaque; every function returns 0 on success,
 * -1 on failure with MXTrainGetLastError() describing the fault.
 *
 * NOTE: this library's NDArrayHandle wraps the runtime's live NDArray
 * (autograd-capable, device-backed). The separate libmxtpu_ndarray.so
 * is the dependency-free offline file inspector; the two do not mix.
 */
#ifndef MXTPU_C_API_TRAIN_H_
#define MXTPU_C_API_TRAIN_H_

#include <stddef.h>
#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

typedef void *NDArrayHandle;
typedef void *SymbolHandle;
typedef void *CachedOpHandle;
typedef void *KVStoreHandle;

const char *MXTrainGetLastError(void);

/* ---- NDArray ---- */
int MXTrainNDArrayCreate(const uint32_t *shape, uint32_t ndim, int dtype,
                         NDArrayHandle *out);
int MXTrainNDArrayFree(NDArrayHandle h);
int MXTrainNDArraySyncCopyFromCPU(NDArrayHandle h, const void *data,
                                  size_t nbytes);
int MXTrainNDArraySyncCopyToCPU(NDArrayHandle h, void *data, size_t nbytes);
int MXTrainNDArrayGetShape(NDArrayHandle h, uint32_t *out_ndim,
                           uint32_t *out_shape /* >= 8 slots */);

/* ---- imperative ops (any registered op or reference alias name) ---- */
int MXTrainImperativeInvoke(const char *op_name, uint32_t num_inputs,
                            NDArrayHandle *inputs, uint32_t *num_outputs,
                            NDArrayHandle *outputs /* caller buffer */,
                            uint32_t max_outputs, uint32_t num_params,
                            const char **param_keys,
                            const char **param_vals);

/* ---- autograd ---- */
int MXTrainAutogradSetIsRecording(int is_recording, int *prev);
int MXTrainAutogradSetIsTraining(int is_training, int *prev);
/* grad_reqs: 0 = null, 1 = write (per variable); grads are caller-made
 * NDArrays that receive the gradients */
int MXTrainAutogradMarkVariables(uint32_t num, NDArrayHandle *vars,
                                 const uint32_t *grad_reqs,
                                 NDArrayHandle *grads);
int MXTrainAutogradBackward(uint32_t num_outputs, NDArrayHandle *outputs,
                            NDArrayHandle *out_grads /* or NULL */,
                            int retain_graph);
int MXTrainNDArrayGetGrad(NDArrayHandle h, NDArrayHandle *out);

/* ---- symbol + CachedOp ---- */
int MXTrainSymbolCreateFromJSON(const char *json, SymbolHandle *out);
int MXTrainSymbolFree(SymbolHandle h);
int MXTrainSymbolGetNumOutputs(SymbolHandle h, uint32_t *out);
/* inputs bind positionally in list_inputs() order; call
 * MXTrainSymbolListInputs to discover it */
int MXTrainSymbolListInputs(SymbolHandle h, uint32_t *num,
                            const char ***out_names /* freed by lib on
                                                       symbol free */);
int MXTrainCreateCachedOp(SymbolHandle sym, CachedOpHandle *out);
int MXTrainFreeCachedOp(CachedOpHandle h);
int MXTrainInvokeCachedOp(CachedOpHandle h, uint32_t num_inputs,
                          NDArrayHandle *inputs, uint32_t *num_outputs,
                          NDArrayHandle *outputs /* caller buffer */,
                          uint32_t max_outputs);

/* ---- KVStore ---- */
int MXTrainKVStoreCreate(const char *type, KVStoreHandle *out);
int MXTrainKVStoreFree(KVStoreHandle h);
int MXTrainKVStoreInit(KVStoreHandle h, uint32_t num, const int *keys,
                       NDArrayHandle *vals);
int MXTrainKVStorePush(KVStoreHandle h, uint32_t num, const int *keys,
                       NDArrayHandle *vals, int priority);
int MXTrainKVStorePull(KVStoreHandle h, uint32_t num, const int *keys,
                       NDArrayHandle *outs, int priority);

#ifdef __cplusplus
}
#endif

#endif  /* MXTPU_C_API_TRAIN_H_ */
