// Symbol C API over the framework's JSON graph format
// (ref: include/mxnet/c_api.h MXSymbol* block; the graph JSON is what
// mxnet_tpu/symbol.py tojson() writes and sym.load reads).
//
// Pure C++ — no Python embedding: a deployment process can load, inspect
// and re-serialize model graphs with only this .so. The JSON subset
// parsed here is the machine-generated symbol format: one object with
// "nodes" (array of {op, name, attrs, inputs}) and "heads".
//
// Build: src/Makefile -> mxnet_tpu/_lib/libmxtpu_symbol.so
#include <array>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <fstream>
#include <map>
#include <memory>
#include <sstream>
#include <string>
#include <vector>

namespace {

thread_local std::string g_last_error;

struct Node {
  std::string op;      // "null" => variable
  std::string name;
  std::map<std::string, std::string> attrs;
  std::vector<std::array<int64_t, 3>> inputs;
};

struct Symbol {
  std::vector<Node> nodes;
  std::vector<std::array<int64_t, 3>> heads;
  std::string json;  // canonical serialization cache
  // storage backing the const char** views handed to callers
  std::vector<std::string> str_store;
  std::vector<const char*> ptr_store;
};

// ---------------------------------------------------------------------------
// minimal JSON parser for the constrained, machine-generated format
// ---------------------------------------------------------------------------

struct Parser {
  const char* p;
  const char* end;
  bool ok = true;
  std::string err;

  explicit Parser(const std::string& s) : p(s.data()), end(s.data() + s.size()) {}

  void fail(const std::string& m) {
    if (ok) {
      ok = false;
      err = m;
    }
  }

  void skip_ws() {
    while (p < end && (*p == ' ' || *p == '\n' || *p == '\t' || *p == '\r'))
      ++p;
  }

  bool consume(char c) {
    skip_ws();
    if (p < end && *p == c) {
      ++p;
      return true;
    }
    fail(std::string("expected '") + c + "'");
    return false;
  }

  bool peek(char c) {
    skip_ws();
    return p < end && *p == c;
  }

  uint32_t parse_hex4() {
    uint32_t v = 0;
    for (int i = 0; i < 4; ++i) {
      if (p >= end) { fail("truncated \\u escape"); return 0; }
      char c = *p++;
      v <<= 4;
      if (c >= '0' && c <= '9') v |= c - '0';
      else if (c >= 'a' && c <= 'f') v |= c - 'a' + 10;
      else if (c >= 'A' && c <= 'F') v |= c - 'A' + 10;
      else { fail("bad \\u escape"); return 0; }
    }
    return v;
  }

  void append_utf8(std::string* out, uint32_t cp) {
    if (cp < 0x80) {
      *out += static_cast<char>(cp);
    } else if (cp < 0x800) {
      *out += static_cast<char>(0xC0 | (cp >> 6));
      *out += static_cast<char>(0x80 | (cp & 0x3F));
    } else if (cp < 0x10000) {
      *out += static_cast<char>(0xE0 | (cp >> 12));
      *out += static_cast<char>(0x80 | ((cp >> 6) & 0x3F));
      *out += static_cast<char>(0x80 | (cp & 0x3F));
    } else {
      *out += static_cast<char>(0xF0 | (cp >> 18));
      *out += static_cast<char>(0x80 | ((cp >> 12) & 0x3F));
      *out += static_cast<char>(0x80 | ((cp >> 6) & 0x3F));
      *out += static_cast<char>(0x80 | (cp & 0x3F));
    }
  }

  std::string parse_string() {
    skip_ws();
    std::string out;
    if (p >= end || *p != '"') {
      fail("expected string");
      return out;
    }
    ++p;
    while (p < end && *p != '"') {
      if (*p == '\\' && p + 1 < end) {
        ++p;
        switch (*p) {
          case 'n': out += '\n'; ++p; break;
          case 't': out += '\t'; ++p; break;
          case 'r': out += '\r'; ++p; break;
          case 'b': out += '\b'; ++p; break;
          case 'f': out += '\f'; ++p; break;
          case '"': out += '"'; ++p; break;
          case '\\': out += '\\'; ++p; break;
          case '/': out += '/'; ++p; break;
          case 'u': {
            // json.dumps ensure_ascii emits \uXXXX for any non-ASCII
            // char, so full decoding (incl. surrogate pairs) is required
            ++p;
            uint32_t cp = parse_hex4();
            if (ok && cp >= 0xD800 && cp <= 0xDBFF && p + 1 < end &&
                p[0] == '\\' && p[1] == 'u') {
              p += 2;
              uint32_t lo = parse_hex4();
              if (ok && lo >= 0xDC00 && lo <= 0xDFFF)
                cp = 0x10000 + ((cp - 0xD800) << 10) + (lo - 0xDC00);
              else
                fail("unpaired surrogate in \\u escape");
            }
            if (ok) append_utf8(&out, cp);
            break;
          }
          default:
            fail("unknown escape");
            ++p;
        }
      } else {
        out += *p;
        ++p;
      }
    }
    if (p >= end) {
      fail("unterminated string");
      return out;
    }
    ++p;
    return out;
  }

  double parse_number() {
    skip_ws();
    char* q = nullptr;
    double v = std::strtod(p, &q);
    if (q == p) fail("expected number");
    p = q;
    return v;
  }

  void skip_value();  // fwd

  void skip_object() {
    consume('{');
    if (peek('}')) { ++p; return; }
    while (ok) {
      parse_string();
      consume(':');
      skip_value();
      skip_ws();
      if (peek(',')) { ++p; continue; }
      consume('}');
      break;
    }
  }

  void skip_array() {
    consume('[');
    if (peek(']')) { ++p; return; }
    while (ok) {
      skip_value();
      if (peek(',')) { ++p; continue; }
      consume(']');
      break;
    }
  }
};

void Parser::skip_value() {
  skip_ws();
  if (p >= end) { fail("eof"); return; }
  if (*p == '"') { parse_string(); return; }
  if (*p == '{') { skip_object(); return; }
  if (*p == '[') { skip_array(); return; }
  if (!std::strncmp(p, "true", 4)) { p += 4; return; }
  if (!std::strncmp(p, "false", 5)) { p += 5; return; }
  if (!std::strncmp(p, "null", 4)) { p += 4; return; }
  parse_number();
}

std::array<int64_t, 3> parse_ref(Parser* ps) {
  std::array<int64_t, 3> ref{0, 0, 0};
  ps->consume('[');
  for (int i = 0; i < 3 && ps->ok; ++i) {
    ref[i] = static_cast<int64_t>(ps->parse_number());
    if (i < 2) ps->consume(',');
  }
  ps->consume(']');
  return ref;
}

bool parse_node(Parser* ps, Node* node) {
  ps->consume('{');
  while (ps->ok) {
    std::string key = ps->parse_string();
    ps->consume(':');
    if (key == "op") {
      node->op = ps->parse_string();
    } else if (key == "name") {
      node->name = ps->parse_string();
    } else if (key == "attrs") {
      ps->consume('{');
      if (ps->peek('}')) {
        ++ps->p;
      } else {
        while (ps->ok) {
          std::string k = ps->parse_string();
          ps->consume(':');
          node->attrs[k] = ps->parse_string();
          if (ps->peek(',')) { ++ps->p; continue; }
          ps->consume('}');
          break;
        }
      }
    } else if (key == "inputs") {
      ps->consume('[');
      if (ps->peek(']')) {
        ++ps->p;
      } else {
        while (ps->ok) {
          node->inputs.push_back(parse_ref(ps));
          if (ps->peek(',')) { ++ps->p; continue; }
          ps->consume(']');
          break;
        }
      }
    } else {
      ps->skip_value();
    }
    if (ps->peek(',')) { ++ps->p; continue; }
    ps->consume('}');
    break;
  }
  return ps->ok;
}

bool parse_symbol(const std::string& json, Symbol* sym, std::string* err) {
  Parser ps(json);
  ps.consume('{');
  while (ps.ok) {
    std::string key = ps.parse_string();
    ps.consume(':');
    if (key == "nodes") {
      ps.consume('[');
      if (ps.peek(']')) {
        ++ps.p;
      } else {
        while (ps.ok) {
          Node n;
          if (!parse_node(&ps, &n)) break;
          sym->nodes.push_back(std::move(n));
          if (ps.peek(',')) { ++ps.p; continue; }
          ps.consume(']');
          break;
        }
      }
    } else if (key == "heads") {
      ps.consume('[');
      if (ps.peek(']')) {
        ++ps.p;
      } else {
        while (ps.ok) {
          sym->heads.push_back(parse_ref(&ps));
          if (ps.peek(',')) { ++ps.p; continue; }
          ps.consume(']');
          break;
        }
      }
    } else {
      ps.skip_value();
    }
    ps.skip_ws();
    if (ps.peek(',')) { ++ps.p; continue; }
    ps.consume('}');
    break;
  }
  if (!ps.ok) {
    *err = ps.err;
    return false;
  }
  if (sym->nodes.empty()) {
    *err = "no nodes in graph";
    return false;
  }
  for (const auto& n : sym->nodes) {
    for (const auto& ref : n.inputs) {
      if (ref[0] < 0 || ref[0] >= static_cast<int64_t>(sym->nodes.size())) {
        *err = "input index out of range";
        return false;
      }
    }
  }
  for (const auto& h : sym->heads) {
    if (h[0] < 0 || h[0] >= static_cast<int64_t>(sym->nodes.size())) {
      *err = "head index out of range";
      return false;
    }
  }
  return true;
}

std::string escape(const std::string& s) {
  std::string out;
  for (char c : s) {
    switch (c) {
      case '"': out += "\\\""; break;
      case '\\': out += "\\\\"; break;
      case '\n': out += "\\n"; break;
      case '\t': out += "\\t"; break;
      case '\r': out += "\\r"; break;
      default: out += c;
    }
  }
  return out;
}

void serialize(Symbol* sym) {
  std::ostringstream os;
  os << "{\n  \"nodes\": [\n";
  for (size_t i = 0; i < sym->nodes.size(); ++i) {
    const Node& n = sym->nodes[i];
    os << "    {\"op\": \"" << escape(n.op) << "\", \"name\": \""
       << escape(n.name) << "\", \"attrs\": {";
    bool first = true;
    for (const auto& kv : n.attrs) {
      if (!first) os << ", ";
      first = false;
      os << "\"" << escape(kv.first) << "\": \"" << escape(kv.second)
         << "\"";
    }
    os << "}, \"inputs\": [";
    for (size_t j = 0; j < n.inputs.size(); ++j) {
      if (j) os << ", ";
      os << "[" << n.inputs[j][0] << ", " << n.inputs[j][1] << ", "
         << n.inputs[j][2] << "]";
    }
    os << "]}" << (i + 1 < sym->nodes.size() ? "," : "") << "\n";
  }
  os << "  ],\n  \"heads\": [";
  for (size_t i = 0; i < sym->heads.size(); ++i) {
    if (i) os << ", ";
    os << "[" << sym->heads[i][0] << ", " << sym->heads[i][1] << ", "
       << sym->heads[i][2] << "]";
  }
  os << "],\n  \"mxnet_tpu_version\": 2\n}";
  sym->json = os.str();
}

int fail(const std::string& msg) {
  g_last_error = msg;
  return -1;
}

}  // namespace

extern "C" {

typedef void* SymbolHandle;

const char* MXGetLastError() { return g_last_error.c_str(); }

int MXSymbolCreateFromJSON(const char* json, SymbolHandle* out) {
  if (!json || !out) return fail("null argument");
  auto sym = std::make_unique<Symbol>();
  std::string err;
  if (!parse_symbol(json, sym.get(), &err))
    return fail("invalid symbol JSON: " + err);
  serialize(sym.get());
  *out = sym.release();
  return 0;
}

int MXSymbolCreateFromFile(const char* fname, SymbolHandle* out) {
  if (!fname || !out) return fail("null argument");
  std::ifstream f(fname);
  if (!f) return fail(std::string("cannot open ") + fname);
  std::stringstream ss;
  ss << f.rdbuf();
  return MXSymbolCreateFromJSON(ss.str().c_str(), out);
}

int MXSymbolSaveToJSON(SymbolHandle handle, const char** out) {
  if (!handle || !out) return fail("null argument");
  auto* sym = static_cast<Symbol*>(handle);
  *out = sym->json.c_str();
  return 0;
}

int MXSymbolSaveToFile(SymbolHandle handle, const char* fname) {
  if (!handle || !fname) return fail("null argument");
  auto* sym = static_cast<Symbol*>(handle);
  std::ofstream f(fname);
  if (!f) return fail(std::string("cannot write ") + fname);
  f << sym->json;
  return 0;
}

int MXSymbolListArguments(SymbolHandle handle, uint32_t* out_size,
                          const char*** out_array) {
  if (!handle || !out_size || !out_array) return fail("null argument");
  auto* sym = static_cast<Symbol*>(handle);
  sym->str_store.clear();
  sym->ptr_store.clear();
  for (const auto& n : sym->nodes)
    if (n.op == "null") sym->str_store.push_back(n.name);
  for (const auto& s : sym->str_store) sym->ptr_store.push_back(s.c_str());
  *out_size = static_cast<uint32_t>(sym->ptr_store.size());
  *out_array = sym->ptr_store.data();
  return 0;
}

int MXSymbolListOutputs(SymbolHandle handle, uint32_t* out_size,
                        const char*** out_array) {
  if (!handle || !out_size || !out_array) return fail("null argument");
  auto* sym = static_cast<Symbol*>(handle);
  sym->str_store.clear();
  sym->ptr_store.clear();
  for (const auto& h : sym->heads)
    sym->str_store.push_back(sym->nodes[h[0]].name + "_output");
  for (const auto& s : sym->str_store) sym->ptr_store.push_back(s.c_str());
  *out_size = static_cast<uint32_t>(sym->ptr_store.size());
  *out_array = sym->ptr_store.data();
  return 0;
}

int MXSymbolGetName(SymbolHandle handle, const char** out, int* success) {
  if (!handle || !out || !success) return fail("null argument");
  auto* sym = static_cast<Symbol*>(handle);
  if (sym->heads.empty()) {
    *success = 0;
    *out = nullptr;
    return 0;
  }
  *success = 1;
  *out = sym->nodes[sym->heads[0][0]].name.c_str();
  return 0;
}

int MXSymbolGetNumNodes(SymbolHandle handle, uint32_t* out) {
  if (!handle || !out) return fail("null argument");
  *out = static_cast<uint32_t>(static_cast<Symbol*>(handle)->nodes.size());
  return 0;
}

int MXSymbolGetAttr(SymbolHandle handle, const char* node_name,
                    const char* key, const char** out, int* success) {
  if (!handle || !node_name || !key || !out || !success)
    return fail("null argument");
  auto* sym = static_cast<Symbol*>(handle);
  *success = 0;
  *out = nullptr;
  for (const auto& n : sym->nodes) {
    if (n.name == node_name) {
      auto it = n.attrs.find(key);
      if (it != n.attrs.end()) {
        *success = 1;
        *out = it->second.c_str();
      }
      return 0;
    }
  }
  return fail(std::string("no node named ") + node_name);
}

int MXSymbolFree(SymbolHandle handle) {
  delete static_cast<Symbol*>(handle);
  return 0;
}

}  // extern "C"
