/*
 * NDArray C API for mxnet_tpu (ref: include/mxnet/c_api.h NDArray block,
 * src/c_api/c_api.cc MXNDArray*).
 *
 * A pure-C ABI over host tensors plus the dmlc-stream binary container
 * (ref: src/ndarray/ndarray.cc NDArray::Save/Load), byte-compatible with
 * the Python serializer (mxnet_tpu/serialization.py) and with files the
 * reference ecosystem publishes. No Python, no device runtime: this is
 * the artifact/interchange layer a C/C++ application links to create,
 * fill, save and load .params/.ndarray blobs; compute stays with XLA via
 * the predict API (c_predict_api.cc) or the Python frontend.
 */
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <vector>

namespace {

thread_local std::string g_last_error;

void set_error(const std::string &msg) { g_last_error = msg; }

void clear_error() { g_last_error.clear(); }

constexpr uint64_t kListMagic = 0x112;
constexpr uint32_t kV2Magic = 0xF993FAC9;
constexpr uint32_t kV3Magic = 0xF993FACA;

/* mshadow type flags (ref: mshadow/base.h:333-345) */
int dtype_size(int flag) {
  switch (flag) {
    case 0: return 4;   /* float32 */
    case 1: return 8;   /* float64 */
    case 2: return 2;   /* float16 */
    case 3: return 1;   /* uint8 */
    case 4: return 4;   /* int32 */
    case 5: return 1;   /* int8 */
    case 6: return 8;   /* int64 */
    case 7: return 1;   /* bool */
    case 8: return 2;   /* int16 */
    case 12: return 2;  /* bfloat16 */
    default: return -1;
  }
}

struct Tensor {
  std::vector<int64_t> shape;
  int dtype = 0;
  bool is_none = false;   /* "none array" list entry (np semantics) */
  std::vector<uint8_t> data;

  int64_t num_elems() const {
    int64_t n = 1;
    for (int64_t d : shape) n *= d;
    return n;
  }
  size_t nbytes() const {
    return static_cast<size_t>(num_elems()) * dtype_size(dtype);
  }
};

bool write_all(FILE *f, const void *p, size_t n) {
  return fwrite(p, 1, n, f) == n;
}

bool read_all(FILE *f, void *p, size_t n) {
  return fread(p, 1, n, f) == n;
}

bool write_tensor(FILE *f, const Tensor &t) {
  if (t.is_none) {
    uint32_t magic = kV3Magic;
    int32_t stype = 0, ndim = -1;
    return write_all(f, &magic, 4) && write_all(f, &stype, 4) &&
           write_all(f, &ndim, 4);
  }
  uint32_t magic = t.shape.empty() ? kV3Magic : kV2Magic;
  int32_t stype = 0, dev_type = 1, dev_id = 0;
  int32_t ndim = static_cast<int32_t>(t.shape.size());
  if (!write_all(f, &magic, 4) || !write_all(f, &stype, 4) ||
      !write_all(f, &ndim, 4))
    return false;
  for (int64_t d : t.shape)
    if (!write_all(f, &d, 8)) return false;
  int32_t flag = t.dtype;
  if (!write_all(f, &dev_type, 4) || !write_all(f, &dev_id, 4) ||
      !write_all(f, &flag, 4))
    return false;
  return write_all(f, t.data.data(), t.data.size());
}

constexpr int32_t kMaxNdim = 32;          /* reference caps shapes here */
constexpr int64_t kMaxElems = int64_t(1) << 40;

bool read_tensor(FILE *f, Tensor *t) {
  uint32_t magic;
  if (!read_all(f, &magic, 4)) return false;
  if (magic != kV2Magic && magic != kV3Magic) {
    set_error("unsupported NDArray magic (legacy V1/pre-V1 streams are "
              "handled by the python reader)");
    return false;
  }
  int32_t stype;
  if (!read_all(f, &stype, 4)) return false;
  if (stype != 0) {
    set_error("sparse payloads not supported by the C loader");
    return false;
  }
  int32_t ndim;
  if (!read_all(f, &ndim, 4)) return false;
  /* none-array entries: unknown shape under V3, empty shape under V2 —
   * the stream carries NO further fields for them (matches the python
   * reader, serialization.py read_ndarray, and NDArray::Load's early
   * return) */
  if (ndim < 0 || (magic == kV2Magic && ndim == 0)) {
    t->is_none = true;
    return true;
  }
  if (ndim > kMaxNdim) {
    set_error("corrupt NDArray stream: ndim " + std::to_string(ndim));
    return false;
  }
  t->shape.assign(ndim, 0);
  int64_t elems = 1;
  for (auto &d : t->shape) {
    if (!read_all(f, &d, 8)) return false;
    if (d < 0 || (d > 0 && elems > kMaxElems / d)) {
      set_error("corrupt NDArray stream: bad dimension " +
                std::to_string(d));
      return false;
    }
    elems *= d;
  }
  int32_t dev_type, dev_id, flag;
  if (!read_all(f, &dev_type, 4) || !read_all(f, &dev_id, 4) ||
      !read_all(f, &flag, 4))
    return false;
  if (dtype_size(flag) < 0) {
    set_error("unknown dtype flag " + std::to_string(flag));
    return false;
  }
  t->dtype = flag;
  t->data.assign(t->nbytes(), 0);
  return read_all(f, t->data.data(), t->data.size());
}

}  // namespace

extern "C" {

typedef void *NDArrayHandle;

const char *MXGetLastError() { return g_last_error.c_str(); }

int MXGetVersion(int *out) {
  *out = 20000;  /* 2.0.0 */
  return 0;
}

int MXNotifyShutdown() { return 0; }

int MXNDArrayCreate(const uint32_t *shape, uint32_t ndim, int dev_type,
                    int dev_id, int delay_alloc, int dtype,
                    NDArrayHandle *out) {
  clear_error();
  (void)dev_type; (void)dev_id; (void)delay_alloc;
  if (dtype_size(dtype) < 0) {
    set_error("unknown dtype flag " + std::to_string(dtype));
    return -1;
  }
  try {
    Tensor *t = new Tensor();
    t->dtype = dtype;
    t->shape.assign(shape, shape + ndim);
    t->data.assign(t->nbytes(), 0);
    *out = t;
    return 0;
  } catch (const std::exception &e) {
    set_error(std::string("allocation failed: ") + e.what());
    return -1;
  }
}

int MXNDArrayCreateEx(const uint32_t *shape, uint32_t ndim, int dev_type,
                      int dev_id, int delay_alloc, int dtype,
                      NDArrayHandle *out) {
  return MXNDArrayCreate(shape, ndim, dev_type, dev_id, delay_alloc,
                         dtype, out);
}

int MXNDArrayFree(NDArrayHandle handle) {
  delete static_cast<Tensor *>(handle);
  return 0;
}

int MXNDArrayGetShape(NDArrayHandle handle, uint32_t *out_dim,
                      const int64_t **out_pdata) {
  Tensor *t = static_cast<Tensor *>(handle);
  *out_dim = static_cast<uint32_t>(t->shape.size());
  *out_pdata = t->shape.data();
  return 0;
}

int MXNDArrayGetDType(NDArrayHandle handle, int *out) {
  *out = static_cast<Tensor *>(handle)->dtype;
  return 0;
}

int MXNDArrayGetData(NDArrayHandle handle, void **out) {
  *out = static_cast<Tensor *>(handle)->data.data();
  return 0;
}

int MXNDArraySyncCopyFromCPU(NDArrayHandle handle, const void *data,
                             size_t size) {
  clear_error();
  Tensor *t = static_cast<Tensor *>(handle);
  size_t bytes = size * dtype_size(t->dtype);
  if (bytes != t->data.size()) {
    set_error("size mismatch in SyncCopyFromCPU");
    return -1;
  }
  std::memcpy(t->data.data(), data, bytes);
  return 0;
}

int MXNDArraySyncCopyToCPU(NDArrayHandle handle, void *data, size_t size) {
  clear_error();
  Tensor *t = static_cast<Tensor *>(handle);
  size_t bytes = size * dtype_size(t->dtype);
  if (bytes != t->data.size()) {
    set_error("size mismatch in SyncCopyToCPU");
    return -1;
  }
  std::memcpy(data, t->data.data(), bytes);
  return 0;
}

int MXNDArraySave(const char *fname, uint32_t num_args,
                  NDArrayHandle *args, const char **keys) try {
  clear_error();
  FILE *f = fopen(fname, "wb");
  if (!f) {
    set_error(std::string("cannot open ") + fname);
    return -1;
  }
  uint64_t magic = kListMagic, reserved = 0, n = num_args;
  uint64_t m = keys ? num_args : 0;
  bool ok = write_all(f, &magic, 8) && write_all(f, &reserved, 8) &&
            write_all(f, &n, 8);
  for (uint32_t i = 0; ok && i < num_args; ++i)
    ok = write_tensor(f, *static_cast<Tensor *>(args[i]));
  ok = ok && write_all(f, &m, 8);
  for (uint64_t i = 0; ok && i < m; ++i) {
    uint64_t len = std::strlen(keys[i]);
    ok = write_all(f, &len, 8) && write_all(f, keys[i], len);
  }
  /* buffered writes surface ENOSPC at flush time — fclose failing means
   * the file on disk is NOT the file we think we wrote */
  ok = (fclose(f) == 0) && ok;
  if (!ok) set_error("write failed");
  return ok ? 0 : -1;
} catch (const std::exception &e) {
  set_error(std::string("save failed: ") + e.what());
  return -1;
}

int MXNDArrayIsNone(NDArrayHandle handle, int *out) {
  *out = static_cast<Tensor *>(handle)->is_none ? 1 : 0;
  return 0;
}

int MXNDArrayLoad(const char *fname, uint32_t *out_size,
                  NDArrayHandle **out_arr, uint32_t *out_name_size,
                  const char ***out_names) try {
  clear_error();
  FILE *f = fopen(fname, "rb");
  if (!f) {
    set_error(std::string("cannot open ") + fname);
    return -1;
  }
  uint64_t magic, reserved, n;
  if (!read_all(f, &magic, 8) || magic != kListMagic ||
      !read_all(f, &reserved, 8) || !read_all(f, &n, 8)) {
    set_error("not an NDArray list file");
    fclose(f);
    return -1;
  }
  std::vector<Tensor *> arrays;
  bool ok = true;
  try {
    for (uint64_t i = 0; ok && i < n; ++i) {
      Tensor *t = new Tensor();
      try {
        ok = read_tensor(f, t);
      } catch (...) {
        delete t;
        throw;
      }
      if (ok) arrays.push_back(t);
      else delete t;
    }
  } catch (...) {
    /* allocation failures (corrupt sizes) must not leak the file handle
     * or the tensors read so far */
    for (Tensor *t : arrays) delete t;
    fclose(f);
    throw;  /* function-level catch converts to -1 */
  }
  uint64_t m = 0;
  std::vector<std::string> names;
  /* the name block is mandatory in the container — a missing count means
   * a truncated file (the python reader raises FormatError here too) */
  ok = ok && read_all(f, &m, 8);
  constexpr uint64_t kMaxNameLen = uint64_t(1) << 20;
  for (uint64_t i = 0; ok && i < m; ++i) {
    uint64_t len;
    ok = read_all(f, &len, 8);
    if (ok && len > kMaxNameLen) {
      set_error("corrupt NDArray list: name length " +
                std::to_string(len));
      ok = false;
    }
    if (ok) {
      std::string s(len, '\0');
      ok = read_all(f, s.data(), len);
      if (ok) names.push_back(std::move(s));
    }
  }
  fclose(f);
  if (!ok) {
    for (Tensor *t : arrays) delete t;
    if (g_last_error.empty()) set_error("truncated NDArray list file");
    return -1;
  }
  /* caller frees via MXNDArrayFree + the handle/name blocks stay owned
   * by a per-load allocation released on MXNDArrayFree of... keep it
   * simple: leak-free contract is MXNDArrayListFree below. */
  NDArrayHandle *harr = new NDArrayHandle[arrays.size()];
  for (size_t i = 0; i < arrays.size(); ++i) harr[i] = arrays[i];
  const char **nm = nullptr;
  if (!names.empty()) {
    nm = new const char *[names.size()];
    for (size_t i = 0; i < names.size(); ++i) {
      char *c = new char[names[i].size() + 1];
      std::memcpy(c, names[i].c_str(), names[i].size() + 1);
      nm[i] = c;
    }
  }
  *out_size = static_cast<uint32_t>(arrays.size());
  *out_arr = harr;
  *out_name_size = static_cast<uint32_t>(names.size());
  *out_names = nm;
  return 0;
} catch (const std::exception &e) {
  /* exceptions must not cross the C ABI */
  set_error(std::string("load failed: ") + e.what());
  return -1;
}

int MXNDArrayListFree(uint32_t size, NDArrayHandle *arr,
                      uint32_t name_size, const char **names) {
  /* releases the blocks MXNDArrayLoad allocated (handles themselves are
   * freed individually with MXNDArrayFree) */
  (void)size;
  delete[] arr;
  for (uint32_t i = 0; i < name_size; ++i) delete[] names[i];
  delete[] names;
  return 0;
}

}  /* extern "C" */
