/*
 * C predict API for mxnet_tpu — native deployment surface
 * (ref: include/mxnet/c_predict_api.h).
 *
 * A C/C++ application links libmxtpu_predict.so, loads a model exported by
 * HybridBlock.export (symbol JSON + params file bytes), and runs inference.
 * The implementation embeds CPython and drives the same jit-compiled
 * executor the Python frontend uses — one runtime, one compiler, one
 * numerical path (vs the reference's separate amalgamation build).
 */
#ifndef MXTPU_C_PREDICT_API_H_
#define MXTPU_C_PREDICT_API_H_

#ifdef __cplusplus
extern "C" {
#endif

typedef void *PredictorHandle;

/* All functions return 0 on success, -1 on failure (see MXGetLastError). */

/* Create a predictor.
 * symbol_json_str : contents of the *-symbol.json file
 * param_bytes/param_size : contents of the *-0000.params file
 * dev_type : 1 = cpu, 2 = gpu (ignored), 3 = tpu  (ref: c_predict_api.h)
 * num_input_nodes / input_keys : graph input names (e.g. {"data"})
 * input_shape_indptr / input_shape_data : CSR-packed input shapes
 */
int MXPredCreate(const char *symbol_json_str, const void *param_bytes,
                 int param_size, int dev_type, int dev_id,
                 unsigned num_input_nodes, const char **input_keys,
                 const unsigned *input_shape_indptr,
                 const unsigned *input_shape_data, PredictorHandle *out);

int MXPredSetInput(PredictorHandle handle, const char *key,
                   const float *data, unsigned size);

int MXPredForward(PredictorHandle handle);

int MXPredGetOutputShape(PredictorHandle handle, unsigned index,
                         unsigned **shape_data, unsigned *shape_ndim);

int MXPredGetOutput(PredictorHandle handle, unsigned index, float *data,
                    unsigned size);

int MXPredFree(PredictorHandle handle);

const char *MXGetLastError(void);

#ifdef __cplusplus
}
#endif

#endif  /* MXTPU_C_PREDICT_API_H_ */
