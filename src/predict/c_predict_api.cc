// C predict API implementation — embeds CPython and drives
// mxnet_tpu._predict_embed (ref: src/c_api/c_predict_api.cc).
//
// Thread-model: every entry point takes the GIL via PyGILState_Ensure, so
// the library works both inside an existing Python process (ctypes/pybind
// hosts) and from a standalone C program (lazy Py_InitializeEx).

#include "c_predict_api.h"

#include <Python.h>

#include <mutex>
#include <string>
#include <vector>

namespace {

thread_local std::string g_last_error;

void set_error(const std::string &msg) { g_last_error = msg; }

void set_error_from_python() {
  PyObject *type = nullptr, *value = nullptr, *tb = nullptr;
  PyErr_Fetch(&type, &value, &tb);
  PyErr_NormalizeException(&type, &value, &tb);
  std::string msg = "python error";
  if (value) {
    PyObject *s = PyObject_Str(value);
    if (s) {
      const char *utf8 = PyUnicode_AsUTF8(s);
      if (utf8) msg = utf8;
      else PyErr_Clear();  // non-UTF8-representable error text
      Py_DECREF(s);
    }
  }
  Py_XDECREF(type);
  Py_XDECREF(value);
  Py_XDECREF(tb);
  set_error(msg);
}

struct Predictor {
  PyObject *py_predictor = nullptr;          // _predict_embed.Predictor
  std::vector<std::vector<unsigned>> out_shapes;  // filled by GetOutputShape
};

std::once_flag g_init_flag;

void ensure_python() {
  std::call_once(g_init_flag, []() {
    if (!Py_IsInitialized()) {
      Py_InitializeEx(0);
      // release the GIL acquired by Py_InitializeEx so PyGILState_Ensure
      // works uniformly below
      PyEval_SaveThread();
    }
  });
}

class GIL {
 public:
  GIL() { state_ = PyGILState_Ensure(); }
  ~GIL() { PyGILState_Release(state_); }

 private:
  PyGILState_STATE state_;
};

PyObject *embed_module() {
  static PyObject *mod = nullptr;
  if (mod == nullptr) {
    mod = PyImport_ImportModule("mxnet_tpu._predict_embed");
  }
  return mod;
}

}  // namespace

extern "C" {

const char *MXGetLastError(void) { return g_last_error.c_str(); }

int MXPredCreate(const char *symbol_json_str, const void *param_bytes,
                 int param_size, int dev_type, int dev_id,
                 unsigned num_input_nodes, const char **input_keys,
                 const unsigned *input_shape_indptr,
                 const unsigned *input_shape_data, PredictorHandle *out) {
  ensure_python();
  GIL gil;
  PyObject *mod = embed_module();
  if (mod == nullptr) {
    set_error_from_python();
    return -1;
  }
  PyObject *keys = PyList_New(num_input_nodes);
  PyObject *shapes = PyList_New(num_input_nodes);
  for (unsigned i = 0; i < num_input_nodes; ++i) {
    PyList_SetItem(keys, i, PyUnicode_FromString(input_keys[i]));
    unsigned lo = input_shape_indptr[i], hi = input_shape_indptr[i + 1];
    PyObject *shape = PyTuple_New(hi - lo);
    for (unsigned j = lo; j < hi; ++j) {
      PyTuple_SetItem(shape, j - lo, PyLong_FromUnsignedLong(
          input_shape_data[j]));
    }
    PyList_SetItem(shapes, i, shape);
  }
  PyObject *params = PyBytes_FromStringAndSize(
      static_cast<const char *>(param_bytes), param_size);
  PyObject *res = PyObject_CallMethod(
      mod, "create", "sOOOi", symbol_json_str, params, keys, shapes,
      dev_type);
  Py_DECREF(params);
  Py_DECREF(keys);
  Py_DECREF(shapes);
  if (res == nullptr) {
    set_error_from_python();
    return -1;
  }
  Predictor *p = new Predictor();
  p->py_predictor = res;
  *out = p;
  return 0;
}

int MXPredSetInput(PredictorHandle handle, const char *key, const float *data,
                   unsigned size) {
  GIL gil;
  Predictor *p = static_cast<Predictor *>(handle);
  PyObject *buf = PyBytes_FromStringAndSize(
      reinterpret_cast<const char *>(data),
      static_cast<Py_ssize_t>(size) * sizeof(float));
  PyObject *res = PyObject_CallMethod(p->py_predictor, "set_input", "sO",
                                      key, buf);
  Py_DECREF(buf);
  if (res == nullptr) {
    set_error_from_python();
    return -1;
  }
  Py_DECREF(res);
  return 0;
}

int MXPredForward(PredictorHandle handle) {
  GIL gil;
  Predictor *p = static_cast<Predictor *>(handle);
  PyObject *res = PyObject_CallMethod(p->py_predictor, "forward", nullptr);
  if (res == nullptr) {
    set_error_from_python();
    return -1;
  }
  Py_DECREF(res);
  return 0;
}

int MXPredGetOutputShape(PredictorHandle handle, unsigned index,
                         unsigned **shape_data, unsigned *shape_ndim) {
  GIL gil;
  Predictor *p = static_cast<Predictor *>(handle);
  PyObject *res = PyObject_CallMethod(p->py_predictor, "output_shape", "I",
                                      index);
  if (res == nullptr) {
    set_error_from_python();
    return -1;
  }
  Py_ssize_t n = PyTuple_Size(res);
  if (p->out_shapes.size() <= index) p->out_shapes.resize(index + 1);
  auto &dims = p->out_shapes[index];
  dims.resize(n);
  for (Py_ssize_t i = 0; i < n; ++i) {
    dims[i] = static_cast<unsigned>(
        PyLong_AsUnsignedLong(PyTuple_GetItem(res, i)));
  }
  Py_DECREF(res);
  *shape_data = dims.data();
  *shape_ndim = static_cast<unsigned>(n);
  return 0;
}

int MXPredGetOutput(PredictorHandle handle, unsigned index, float *data,
                    unsigned size) {
  GIL gil;
  Predictor *p = static_cast<Predictor *>(handle);
  PyObject *res = PyObject_CallMethod(p->py_predictor, "output_bytes", "I",
                                      index);
  if (res == nullptr) {
    set_error_from_python();
    return -1;
  }
  char *buf = nullptr;
  Py_ssize_t len = 0;
  if (PyBytes_AsStringAndSize(res, &buf, &len) != 0) {
    Py_DECREF(res);
    set_error_from_python();
    return -1;
  }
  if (static_cast<Py_ssize_t>(size) * sizeof(float) <
      static_cast<size_t>(len)) {
    Py_DECREF(res);
    set_error("MXPredGetOutput: buffer too small");
    return -1;
  }
  memcpy(data, buf, len);
  Py_DECREF(res);
  return 0;
}

int MXPredFree(PredictorHandle handle) {
  GIL gil;
  Predictor *p = static_cast<Predictor *>(handle);
  Py_XDECREF(p->py_predictor);
  delete p;
  return 0;
}

}  // extern "C"
