"""Wide & Deep CTR model on the RowSparse embedding fast path.

The recsys shape the sparse path exists for: embedding tables hold
almost all the parameters, but each step touches only the rows its
batch's categorical features hit. With ``sparse_grad=True`` the tables
carry RowSparse gradients — the one pjit train step dedups the batch's
ids, updates only the live rows (lazy adam), and the analytic
``sparse_report()`` shows the update-bytes shrink vs dense.

Run (synthetic CTR data; any host):
  python examples/train_wide_deep.py --steps 20

Shard the deep table over a model axis (needs a multi-device mesh):
  XLA_FLAGS=--xla_force_host_platform_device_count=8 \
  JAX_PLATFORMS=cpu MXTPU_SPARSE_TABLE_AXIS=tp \
  python examples/train_wide_deep.py --tp 4
"""
import argparse
import time

import numpy as onp

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.gluon import loss as gloss
from mxnet_tpu.gluon import nn
from mxnet_tpu.io import NDArrayIter
from mxnet_tpu.parallel import make_mesh, ShardedTrainStep


class WideDeep(nn.HybridBlock):
    """Cheng et al. 2016: a wide (linear-in-crosses) head plus a deep
    MLP over shared categorical fields, summed into one CTR logit.
    Both tables are ``sparse_grad`` — the wide one is vocab x 1."""

    def __init__(self, vocab, dim=16, hidden=64, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.wide = nn.Embedding(vocab, 1, sparse_grad=True)
            self.deep = nn.Embedding(vocab, dim, sparse_grad=True)
            self.mlp = nn.HybridSequential()
            with self.mlp.name_scope():
                self.mlp.add(nn.Dense(hidden, activation='relu'))
                self.mlp.add(nn.Dense(hidden // 2, activation='relu'))
                self.mlp.add(nn.Dense(1))

    def hybrid_forward(self, F, x):
        wide = self.wide(x).sum(axis=(1, 2))         # (B,)
        deep = self.mlp(self.deep(x))                # (B, 1), flattened in
        return wide + deep.reshape((-1,))            # CTR logit


def synthetic_ctr(n_rows, fields, vocab, hot_fraction, seed=0):
    """Synthetic impressions: ids zipf-ish concentrated in the hot
    prefix of the vocabulary, labels from a hidden linear model."""
    rng = onp.random.RandomState(seed)
    hot = max(fields, int(vocab * hot_fraction))
    ids = rng.randint(0, hot, size=(n_rows, fields))
    w = rng.randn(vocab) * 0.3
    logits = w[ids].sum(axis=1)
    y = (rng.rand(n_rows) < 1.0 / (1.0 + onp.exp(-logits)))
    return ids.astype('float32'), y.astype('float32')


def main():
    p = argparse.ArgumentParser()
    p.add_argument('--vocab', type=int, default=100000)
    p.add_argument('--fields', type=int, default=20)
    p.add_argument('--dim', type=int, default=16)
    p.add_argument('--batch-size', type=int, default=128)
    p.add_argument('--steps', type=int, default=20)
    p.add_argument('--hot-fraction', type=float, default=0.05)
    p.add_argument('--tp', type=int, default=1,
                   help='model-axis extent for MXTPU_SPARSE_TABLE_AXIS')
    args = p.parse_args()

    mx.random.seed(0)
    model = WideDeep(args.vocab, args.dim)
    model.initialize(mx.init.Normal(0.01))

    import jax
    n_dev = len(jax.devices())
    if args.tp > 1:
        mesh = make_mesh((n_dev // args.tp, args.tp), ('dp', 'tp'))
    else:
        mesh = make_mesh((n_dev,), ('dp',))
    bce = gloss.SigmoidBinaryCrossEntropyLoss()
    step = ShardedTrainStep(model, lambda o, y: bce(o, y), 'adam',
                            {'learning_rate': 0.01}, mesh=mesh)

    ids, y = synthetic_ctr(args.batch_size * args.steps, args.fields,
                           args.vocab, args.hot_fraction)
    train = NDArrayIter(ids, y, args.batch_size)

    t0, losses = time.time(), []
    for i, batch in enumerate(train):
        loss = step(batch.data[0], batch.label[0])
        losses.append(float(loss.asnumpy()))
        if i % 5 == 0:
            print(f"step {i:4d}  loss {losses[-1]:.4f}")
    dt = time.time() - t0

    rep = step.sparse_report()
    print(f"\n{len(losses)} steps in {dt:.1f}s "
          f"({dt / max(1, len(losses)) * 1e3:.1f} ms/step), "
          f"loss {losses[0]:.4f} -> {losses[-1]:.4f}")
    if rep:
        print(f"sparse mode={rep['mode']} "
              f"tables={list(rep['tables'])} "
              f"update {rep['update_bytes_per_step']} B/step vs dense "
              f"{rep['dense_update_bytes_per_step']} "
              f"({rep['update_shrink']:.1f}x shrink)")
        for axis, hop in rep['exchange_bytes_per_hop'].items():
            print(f"  grad hop [{axis}]: {hop['bytes']} B/step "
                  f"(dense-equiv {hop['dense_bytes']})")
    else:
        print("sparse path off (MXTPU_SPARSE=0 or no sparse tables)")


if __name__ == '__main__':
    main()
