"""BERT pretraining example — the flagship path.

One ShardedTrainStep call = forward + backward + AdamW update + gradient
all-reduce as a single pjit-compiled XLA program over the device mesh.
The MLM decoder runs only on the masked positions (GluonNLP recipe) and
attention routes through the Pallas flash kernel on TPU.

Run (synthetic data):
  python examples/pretrain_bert.py --layers 2 --hidden 128 --steps 10
"""
import argparse
import time

import numpy as onp

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.models import BertForPretraining, bert_pretrain_loss
from mxnet_tpu.parallel import make_mesh, ShardedTrainStep


def main():
    p = argparse.ArgumentParser()
    p.add_argument('--layers', type=int, default=12)
    p.add_argument('--hidden', type=int, default=768)
    p.add_argument('--heads', type=int, default=12)
    p.add_argument('--seq', type=int, default=512)
    p.add_argument('--batch-size', type=int, default=32)
    p.add_argument('--steps', type=int, default=30)
    p.add_argument('--vocab', type=int, default=30522)
    p.add_argument('--bf16', action='store_true')
    args = p.parse_args()

    cfg = dict(vocab_size=args.vocab, hidden=args.hidden,
               layers=args.layers, heads=args.heads,
               intermediate=4 * args.hidden, max_len=args.seq,
               type_vocab=2)
    mx.random.seed(0)
    model = BertForPretraining(cfg)
    model.initialize(mx.init.Normal(0.02))
    if args.bf16:
        model.cast('bfloat16')

    import jax
    mesh = make_mesh((len(jax.devices()),), ('dp',))
    step = ShardedTrainStep(model, bert_pretrain_loss, 'adamw',
                            {'learning_rate': 1e-4}, mesh=mesh)

    rng = onp.random.RandomState(0)
    B, T = args.batch_size, args.seq
    M = max(8, int(0.15 * T) // 8 * 8)          # masked positions
    tokens = nd.array(rng.randint(0, args.vocab, (B, T)).astype('int32'))
    types = nd.array(onp.zeros((B, T), 'int32'))
    valid = nd.array(rng.randint(T // 2, T + 1, (B,)).astype('int32'))
    mpos = nd.array(onp.stack([rng.choice(T, M, replace=False)
                               for _ in range(B)]).astype('int32'))
    labels = nd.array(rng.randint(0, args.vocab, (B, M)).astype('int32'))
    nsp = nd.array(rng.randint(0, 2, (B,)).astype('int32'))

    inputs, targets = [tokens, types, valid, mpos], [labels, nsp]
    loss = step(inputs, targets)                # compile
    print(f"step 0: loss={float(loss.asscalar()):.4f}")
    t0 = time.time()
    for i in range(1, args.steps):
        loss = step(inputs, targets)
    l = float(loss.asscalar())
    dt = (time.time() - t0) / max(args.steps - 1, 1)
    print(f"step {args.steps - 1}: loss={l:.4f}  "
          f"{dt * 1e3:.1f} ms/step  "
          f"{B / dt:.1f} samples/sec")


if __name__ == '__main__':
    main()
