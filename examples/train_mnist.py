"""Gluon training example: MLP on synthetic MNIST-shaped data.

The canonical user loop (ref: example/gluon/mnist.py): HybridBlock +
Trainer + autograd. Trainer.step compiles every parameter update into one
XLA program; hybridize() compiles the forward.

Run: python examples/train_mnist.py [--epochs 3]
"""
import argparse

import numpy as onp

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd
from mxnet_tpu.gluon import nn


def make_data(n=2048, seed=0):
    rng = onp.random.RandomState(seed)
    x = rng.rand(n, 1, 28, 28).astype(onp.float32)
    w = rng.randn(784, 10).astype(onp.float32)
    y = (x.reshape(n, 784) @ w).argmax(1).astype(onp.int32)
    return x, y


def main():
    p = argparse.ArgumentParser()
    p.add_argument('--epochs', type=int, default=3)
    p.add_argument('--batch-size', type=int, default=128)
    p.add_argument('--lr', type=float, default=1e-3)
    args = p.parse_args()

    net = nn.HybridSequential()
    net.add(nn.Dense(256, activation='relu'),
            nn.Dense(128, activation='relu'),
            nn.Dense(10))
    net.initialize(mx.init.Xavier())
    net.hybridize()

    x, y = make_data()
    dataset = gluon.data.ArrayDataset(nd.array(x), nd.array(y))
    loader = gluon.data.DataLoader(dataset, batch_size=args.batch_size,
                                   shuffle=True)
    trainer = gluon.Trainer(net.collect_params(), 'adam',
                            {'learning_rate': args.lr})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    metric = mx.metric.Accuracy()

    import time
    for epoch in range(args.epochs):
        metric.reset()
        tic = time.time()
        for data, label in loader:
            with autograd.record():
                out = net(data)
                loss = loss_fn(out, label)
            loss.backward()
            trainer.step(data.shape[0])
            metric.update([label], [out])
        print(f"Epoch[{epoch}] Train-accuracy={metric.get()[1]:.4f}")
        print(f"Epoch[{epoch}] Time cost={time.time() - tic:.2f}")


if __name__ == '__main__':
    main()
