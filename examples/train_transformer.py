"""Transformer encoder-decoder training example (ref: the WMT
transformer-big verification config, BASELINE.json; model in
models/transformer.py).

Trains seq2seq on a synthetic reversal task (target = reversed source) —
the standard smoke objective for enc-dec attention: the decoder must
attend across the whole source. Runs through the fused ShardedTrainStep
(one XLA program per step). Use --big for the transformer-big
(1024/16/4096) configuration.

Run: python examples/train_transformer.py [--steps 30] [--big]
"""
import argparse

import numpy as onp

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.models import TransformerModel
from mxnet_tpu.models.bert import masked_cross_entropy
from mxnet_tpu.parallel import make_mesh, ShardedTrainStep


def make_batch(rng, batch, seq, vocab):
    src = rng.randint(4, vocab, (batch, seq)).astype(onp.int32)
    tgt_out = src[:, ::-1].copy()
    # teacher forcing: decoder input is <bos>=1 + shifted target
    tgt_in = onp.concatenate(
        [onp.ones((batch, 1), onp.int32), tgt_out[:, :-1]], axis=1)
    return nd.array(src), nd.array(tgt_in), nd.array(tgt_out)


def main():
    p = argparse.ArgumentParser()
    p.add_argument('--steps', type=int, default=30)
    p.add_argument('--batch-size', type=int, default=16)
    p.add_argument('--seq', type=int, default=24)
    p.add_argument('--vocab', type=int, default=64)
    p.add_argument('--big', action='store_true',
                   help='transformer-big dims (1024/16/4096, 6+6 layers)')
    args = p.parse_args()

    if args.big:
        cfg = dict(hidden=1024, enc_layers=6, dec_layers=6, heads=16,
                   ffn_hidden=4096)
    else:
        cfg = dict(hidden=64, enc_layers=2, dec_layers=2, heads=4,
                   ffn_hidden=128)
    net = TransformerModel(args.vocab, args.vocab, max_len=256,
                           dropout=0.1, **cfg)
    net.initialize(mx.init.Xavier())

    def loss_fn(logits, labels):
        return masked_cross_entropy(logits, labels)

    import jax
    mesh = make_mesh((len(jax.devices()),), ('dp',))
    step = ShardedTrainStep(net, loss_fn, 'adam',
                            {'learning_rate': 3e-4}, mesh=mesh)

    assert args.steps > 0, "--steps must be positive"
    rng = onp.random.RandomState(0)
    first = None
    for i in range(args.steps):
        src, tgt_in, tgt_out = make_batch(rng, args.batch_size, args.seq,
                                          args.vocab)
        loss = float(step([src, tgt_in], [tgt_out]).asnumpy())
        if first is None:
            first = loss
        if i % 10 == 0 or i == args.steps - 1:
            print(f"step {i}: loss {loss:.4f}")
    print(f"loss {first:.4f} -> {loss:.4f}")


if __name__ == '__main__':
    main()
