"""Pipeline-parallel BERT pretraining over a 'pp' mesh axis.

Demonstrates the round-5 public pipeline API (beyond the reference —
its model parallelism is manual layer placement with no schedule):

    BertForPretraining  --bert_pipeline_funcs-->  embed/stages/head
    PipelineTrainStep: one jit step, stage params sharded over pp,
    GPipe microbatch schedule as a lax.scan over ppermute.

Runs anywhere: on a CPU-only host use the virtual mesh —

    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    JAX_PLATFORMS=cpu python examples/train_bert_pipeline.py --pp 2
"""
import argparse

import numpy as onp


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument('--pp', type=int, default=2, help='pipeline stages')
    ap.add_argument('--layers', type=int, default=4)
    ap.add_argument('--hidden', type=int, default=128)
    ap.add_argument('--microbatches', type=int, default=4)
    ap.add_argument('--microbatch-size', type=int, default=2)
    ap.add_argument('--seq', type=int, default=64)
    ap.add_argument('--steps', type=int, default=20)
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp
    import mxnet_tpu as mx
    from mxnet_tpu.models import BertForPretraining
    from mxnet_tpu.models.bert import bert_pipeline_funcs
    from mxnet_tpu.parallel import PipelineTrainStep, make_mesh

    assert args.layers % args.pp == 0, 'layers must divide into stages'
    cfg = dict(vocab_size=1000, hidden=args.hidden, layers=args.layers,
               heads=max(2, args.hidden // 64), intermediate=args.hidden * 4,
               max_len=args.seq, type_vocab=2, dropout=0.0)
    mx.random.seed(0)
    model = BertForPretraining(config=cfg)
    model.initialize(mx.init.Normal(0.02))

    params, embed_fn, stage_fn, head_fn, loss_fn = \
        bert_pipeline_funcs(model, n_stages=args.pp)
    mesh = make_mesh((args.pp,), ('pp',))
    step = PipelineTrainStep(params, embed_fn, stage_fn, head_fn, loss_fn,
                             'adamw', {'learning_rate': 1e-3}, mesh=mesh)

    M, mb, T = args.microbatches, args.microbatch_size, args.seq
    rng = onp.random.RandomState(0)
    tokens = jnp.asarray(rng.randint(0, cfg['vocab_size'], (M, mb, T)),
                         jnp.int32)
    labels = jnp.asarray(rng.randint(0, cfg['vocab_size'], (M, mb, T)),
                         jnp.int32)
    nsp = jnp.asarray(rng.randint(0, 2, (M, mb)), jnp.int32)

    print(f'mesh {dict(mesh.shape)}  stages={args.pp}  '
          f'microbatches={M}x{mb}  seq={T}')
    for i in range(args.steps):
        loss = float(step(tokens, (labels, nsp)))
        if i % 5 == 0 or i == args.steps - 1:
            print(f'step {i:3d}  loss {loss:.4f}')


if __name__ == '__main__':
    main()
