/*
 * Standalone C embedder: trains an MLP end-to-end through the
 * libmxtpu_train.so C ABI (src/train/c_api_train.h) with NO Python
 * code in this file — CPython is embedded by the library itself.
 *
 * Build + run (see Makefile):
 *     make -C examples/c_embedder run
 *
 * The loop: create NDArrays -> mark parameters -> CachedOp forward
 * under recording -> softmax cross-entropy via imperative invoke ->
 * backward -> per-parameter sgd_update. Prints the loss trajectory.
 */
#include <stdio.h>
#include <stdlib.h>
#include <string.h>

#include "../../src/train/c_api_train.h"

#define CHECK(rc)                                                     \
  do {                                                                \
    if ((rc) != 0) {                                                  \
      fprintf(stderr, "error at %s:%d: %s\n", __FILE__, __LINE__,     \
              MXTrainGetLastError());                                 \
      exit(1);                                                        \
    }                                                                 \
  } while (0)

/* tiny xorshift for reproducible synthetic data */
static unsigned int rng_state = 42;
static float frand(void) {
  rng_state ^= rng_state << 13;
  rng_state ^= rng_state >> 17;
  rng_state ^= rng_state << 5;
  return (float)(rng_state % 10000) / 10000.0f - 0.5f;
}

static NDArrayHandle nd_new(const uint32_t *shape, uint32_t ndim) {
  NDArrayHandle h;
  CHECK(MXTrainNDArrayCreate(shape, ndim, 0 /*f32*/, &h));
  return h;
}

static void nd_fill(NDArrayHandle h, const float *data, size_t n) {
  CHECK(MXTrainNDArraySyncCopyFromCPU(h, data, n * sizeof(float)));
}

static void nd_read(NDArrayHandle h, float *out, size_t n) {
  CHECK(MXTrainNDArraySyncCopyToCPU(h, out, n * sizeof(float)));
}

static NDArrayHandle invoke1(const char *op, NDArrayHandle *ins,
                             uint32_t nin, const char **keys,
                             const char **vals, uint32_t nparams) {
  NDArrayHandle outs[4];
  uint32_t nout = 0;
  CHECK(MXTrainImperativeInvoke(op, nin, ins, &nout, outs, 4, nparams,
                                keys, vals));
  return outs[0];
}

int main(void) {
  enum { B = 32, D = 16, H = 24, C = 3, STEPS = 40 };

  /* ---- parameters + grads ---- */
  const uint32_t w1s[] = {H, D}, b1s[] = {H}, w2s[] = {C, H},
                 b2s[] = {C};
  NDArrayHandle w1 = nd_new(w1s, 2), b1 = nd_new(b1s, 1);
  NDArrayHandle w2 = nd_new(w2s, 2), b2 = nd_new(b2s, 1);
  NDArrayHandle g1 = nd_new(w1s, 2), gb1 = nd_new(b1s, 1);
  NDArrayHandle g2 = nd_new(w2s, 2), gb2 = nd_new(b2s, 1);

  float tmp[H * D];
  for (int i = 0; i < H * D; ++i) tmp[i] = frand() * 0.6f;
  nd_fill(w1, tmp, H * D);
  for (int i = 0; i < C * H; ++i) tmp[i] = frand() * 0.6f;
  nd_fill(w2, tmp, C * H);
  memset(tmp, 0, sizeof(tmp));
  nd_fill(b1, tmp, H);
  nd_fill(b2, tmp, C);

  NDArrayHandle params[] = {w1, b1, w2, b2};
  NDArrayHandle grads[] = {g1, gb1, g2, gb2};
  const uint32_t reqs[] = {1, 1, 1, 1};
  CHECK(MXTrainAutogradMarkVariables(4, params, reqs, grads));

  /* ---- synthetic 3-class problem: argmax of a fixed projection ---- */
  static float x[B * D], labels[B];
  float proj[D * C];
  for (int i = 0; i < D * C; ++i) proj[i] = frand();
  for (int b = 0; b < B; ++b) {
    float score[C] = {0};
    for (int d = 0; d < D; ++d) {
      x[b * D + d] = frand();
      for (int c = 0; c < C; ++c)
        score[c] += x[b * D + d] * proj[d * C + c];
    }
    int best = 0;
    for (int c = 1; c < C; ++c)
      if (score[c] > score[best]) best = c;
    labels[b] = (float)best;
  }
  const uint32_t xs[] = {B, D}, ls[] = {B};
  NDArrayHandle xh = nd_new(xs, 2), lh = nd_new(ls, 1);
  nd_fill(xh, x, B * D);
  nd_fill(lh, labels, B);

  /* ---- training loop ---- */
  const char *nh_keys[] = {"num_hidden"};
  const char *nh_h[] = {"24"};
  const char *nh_c[] = {"3"};
  const char *act_keys[] = {"act_type"};
  const char *act_vals[] = {"relu"};
  const char *sgd_keys[] = {"lr", "rescale_grad"};
  const char *sgd_vals[] = {"0.4", "0.03125"};

  int prev;
  float first = 0, last = 0;
  for (int step = 0; step < STEPS; ++step) {
    CHECK(MXTrainAutogradSetIsRecording(1, &prev));
    CHECK(MXTrainAutogradSetIsTraining(1, &prev));

    NDArrayHandle fc1_in[] = {xh, w1, b1};
    NDArrayHandle h1 = invoke1("fully_connected", fc1_in, 3, nh_keys,
                               nh_h, 1);
    NDArrayHandle a1 = invoke1("activation", &h1, 1, act_keys, act_vals,
                               1);
    NDArrayHandle fc2_in[] = {a1, w2, b2};
    NDArrayHandle logits = invoke1("fully_connected", fc2_in, 3, nh_keys,
                                   nh_c, 1);
    NDArrayHandle ce_in[] = {logits, lh};
    NDArrayHandle loss = invoke1("softmax_cross_entropy", ce_in, 2, NULL,
                                 NULL, 0);

    CHECK(MXTrainAutogradSetIsRecording(0, &prev));
    CHECK(MXTrainAutogradBackward(1, &loss, NULL, 0));

    float lv;
    nd_read(loss, &lv, 1);
    if (step == 0) first = lv;
    last = lv;
    if (step % 10 == 0) printf("step %2d  loss %.4f\n", step, lv);

    for (int p = 0; p < 4; ++p) {
      NDArrayHandle gh;
      CHECK(MXTrainNDArrayGetGrad(params[p], &gh));
      NDArrayHandle upd_in[] = {params[p], gh};
      NDArrayHandle newp = invoke1("sgd_update", upd_in, 2, sgd_keys,
                                   sgd_vals, 2);
      /* copy the updated values back into the live (marked) handle */
      uint32_t nd_, shp[8];
      CHECK(MXTrainNDArrayGetShape(params[p], &nd_, shp));
      size_t n = 1;
      for (uint32_t i = 0; i < nd_; ++i) n *= shp[i];
      float *buf = (float *)malloc(n * sizeof(float));
      nd_read(newp, buf, n);
      nd_fill(params[p], buf, n);
      free(buf);
      MXTrainNDArrayFree(newp);
      MXTrainNDArrayFree(gh);
    }
    MXTrainNDArrayFree(h1);
    MXTrainNDArrayFree(a1);
    MXTrainNDArrayFree(logits);
    MXTrainNDArrayFree(loss);
  }
  CHECK(MXTrainAutogradSetIsTraining(0, &prev));

  printf("loss %.4f -> %.4f\n", first, last);
  if (!(last < first * 0.5f)) {
    fprintf(stderr, "FAIL: loss did not halve\n");
    return 1;
  }
  printf("C EMBEDDER TRAIN OK\n");
  return 0;
}
