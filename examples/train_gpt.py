"""GPT-style causal LM training example.

Decoder-only transformer over the flash kernel's causal path; next-token
loss; one compiled train step per iteration.

Run (synthetic data):
  python examples/train_gpt.py --layers 2 --hidden 128 --steps 20
"""
import argparse
import time

import numpy as onp

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.models import GPTModel, gpt_lm_loss
from mxnet_tpu.parallel import make_mesh, ShardedTrainStep


def main():
    p = argparse.ArgumentParser()
    p.add_argument('--layers', type=int, default=12)
    p.add_argument('--hidden', type=int, default=768)
    p.add_argument('--heads', type=int, default=12)
    p.add_argument('--seq', type=int, default=1024)
    p.add_argument('--batch-size', type=int, default=8)
    p.add_argument('--steps', type=int, default=20)
    p.add_argument('--vocab', type=int, default=50257)
    args = p.parse_args()

    mx.random.seed(0)
    model = GPTModel(vocab_size=args.vocab, hidden=args.hidden,
                     layers=args.layers, heads=args.heads,
                     max_len=args.seq)
    model.initialize(mx.init.Normal(0.02))

    import jax
    mesh = make_mesh((len(jax.devices()),), ('dp',))
    step = ShardedTrainStep(model, gpt_lm_loss, 'adamw',
                            {'learning_rate': 3e-4}, mesh=mesh)

    rng = onp.random.RandomState(0)
    B, T = args.batch_size, args.seq
    toks = rng.randint(0, args.vocab, (B, T)).astype('int32')
    labels = onp.full_like(toks, -1)
    labels[:, :-1] = toks[:, 1:]
    tokens, labels = nd.array(toks), nd.array(labels)

    loss = step([tokens], [labels])
    print(f"step 0: loss={float(loss.asscalar()):.4f}")
    t0 = time.time()
    for i in range(1, args.steps):
        loss = step([tokens], [labels])
    l = float(loss.asscalar())
    dt = (time.time() - t0) / max(args.steps - 1, 1)
    tps = B * T / dt
    print(f"step {args.steps - 1}: loss={l:.4f}  "
          f"{dt * 1e3:.1f} ms/step  {tps / 1e3:.1f}k tokens/sec")


if __name__ == '__main__':
    main()
