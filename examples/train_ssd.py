"""SSD detection training example on synthetic boxes (ref: example/ssd).

Drives the SSD model family end to end: multibox anchors + targets,
mined classification + smooth-L1 box loss, fused Trainer updates, and
NMS-decoded detections. Synthetic data (one colored rectangle per image)
keeps it runnable anywhere; swap in ImageDetIter/ImageRecordIter for VOC.

Run: python examples/train_ssd.py [--steps 20] [--size 128]
"""
import argparse

import numpy as onp

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd
from mxnet_tpu.models import SSD, ssd_train_loss


def make_batch(rng, batch, size, num_classes):
    """Images with one axis-aligned bright rectangle; label is its class
    (by color channel) and normalized corner box, padded to M=4 rows."""
    x = rng.rand(batch, 3, size, size).astype(onp.float32) * 0.1
    label = onp.full((batch, 4, 5), -1.0, onp.float32)
    for i in range(batch):
        cls = rng.randint(num_classes)
        w, h = rng.randint(size // 4, size // 2, 2)
        x0, y0 = rng.randint(0, size - w), rng.randint(0, size - h)
        x[i, cls, y0:y0 + h, x0:x0 + w] += 0.8
        label[i, 0] = [cls, x0 / size, y0 / size,
                       (x0 + w) / size, (y0 + h) / size]
    return nd.array(x), nd.array(label)


def main():
    p = argparse.ArgumentParser()
    p.add_argument('--steps', type=int, default=20)
    p.add_argument('--batch-size', type=int, default=8)
    p.add_argument('--size', type=int, default=128,
                   help='input resolution (512 = the reference config)')
    p.add_argument('--lr', type=float, default=1e-3)
    args = p.parse_args()

    num_classes = 3
    net = SSD(num_classes=num_classes, image_size=args.size,
              sizes=[(.15, .25), (.35, .45), (.6, .7)],
              ratios=[[1, 2, .5]] * 3)
    net.initialize(mx.init.Xavier())
    trainer = gluon.Trainer(net.collect_params(), 'adam',
                            {'learning_rate': args.lr})

    rng = onp.random.RandomState(0)
    for step in range(args.steps):
        x, label = make_batch(rng, args.batch_size, args.size, num_classes)
        with autograd.record():
            anchor, cls_pred, loc_pred = net(x)
            loss = ssd_train_loss(anchor, cls_pred, loc_pred, label)
        loss.backward()
        trainer.step(args.batch_size)
        if step % 5 == 0 or step == args.steps - 1:
            print(f"step {step}: loss {float(loss.asnumpy()):.4f}")

    x, _ = make_batch(rng, 2, args.size, num_classes)
    det = net.detect(x, threshold=0.1)
    d = det.asnumpy()
    kept = d[0][d[0, :, 0] >= 0]
    print(f"detections on image 0: {len(kept)} boxes, "
          f"top score {kept[:, 1].max() if len(kept) else 0:.3f}")


if __name__ == '__main__':
    main()
