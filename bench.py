"""Benchmark: BERT-base pretraining MFU (the north-star metric).

Baseline: the driver-defined north star is >=35% MFU for BERT-base
pretraining (BASELINE.md north-star table); vs_baseline = mfu / 35.

Robustness contract (this script is a driver artifact): it ALWAYS prints
exactly ONE JSON line on stdout, with "metric"/"value"/"unit"/
"vs_baseline" plus "backend" fields. Top-level "error" appears ONLY
when no metric line could be produced at all: probe state lives in the
"probe" field and earlier measurement-attempt failures in
"attempts_failed" — a valid smoke line never carries a top-level
"error" (the BENCH_r05 leak, tests/test_bench_contract.py).

Schedule (worst case ~16 min, under any sane driver timeout):
  1. PROBE child (<=60 s, one retry after 10 s backoff): import jax,
     list devices, one tiny matmul on the accelerator. A wedged TPU
     tunnel fails here cheaply; its state is reported in the final
     JSON's "probe" field, never in top-level "error".
  2. If the probe saw an accelerator: ONE measurement child (<=540 s)
     with the JAX persistent compilation cache enabled, so a BERT-base
     compile paid once is never paid again. No identical retry.
  3. CPU smoke fallback (<=240 s) if either of the above failed.

The measured step is the framework's hot path: fwd+bwd+AdamW update as ONE
pjit program (ShardedTrainStep), BERT-base seq 512 in bf16 WITH a padding
mask (the flagship config — the Pallas flash kernel handles the mask).
The accel child also records a pallas-vs-XLA attention timing + parity
check (compiled, not interpreted) in the same JSON.
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import time

import numpy as onp

_CACHE_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          '.jax_compile_cache')


def _log(msg):
    print(f"[bench {time.strftime('%H:%M:%S')}] {msg}", file=sys.stderr,
          flush=True)


def _enable_compile_cache():
    import jax
    try:
        jax.config.update('jax_compilation_cache_dir', _CACHE_DIR)
        jax.config.update('jax_persistent_cache_min_entry_size_bytes', -1)
        jax.config.update('jax_persistent_cache_min_compile_time_secs', 0.0)
    except Exception as e:  # older jax: cache flags absent — not fatal
        _log(f"compile cache unavailable: {e!r}")


# ---------------------------------------------------------------------------
# bf16 peak FLOP/s per chip, keyed on substrings of jax device_kind
# ---------------------------------------------------------------------------
_PEAK_BF16 = [
    ('v6', 918e12), ('trillium', 918e12),
    ('v5p', 459e12),
    ('v5e', 197e12), ('v5 lite', 197e12), ('v5lite', 197e12),
    ('v4', 275e12),
    ('v3', 123e12),
    ('v2', 45e12),
]
_DEFAULT_PEAK = 197e12  # assume v5e-class if the kind string is unknown


def _peak_flops(device) -> float:
    kind = (getattr(device, 'device_kind', '') or '').lower()
    for sub, peak in _PEAK_BF16:
        if sub in kind:
            return peak
    return _DEFAULT_PEAK


# ---------------------------------------------------------------------------
# probe child: cheap backend liveness check
# ---------------------------------------------------------------------------

def _probe() -> None:
    import jax
    import jax.numpy as jnp
    devices = jax.devices()
    accel = [d for d in devices if d.platform != 'cpu']
    target = accel[0] if accel else devices[0]
    x = jax.device_put(jnp.ones((128, 128), jnp.float32), target)
    y = jnp.dot(x, x)
    jax.block_until_ready(y)
    print(json.dumps({
        "probe": "ok",
        "platform": target.platform,
        "device_kind": getattr(target, 'device_kind', '?'),
        "n_devices": len(accel) or len(devices),
    }), flush=True)


# ---------------------------------------------------------------------------
# pallas-vs-XLA attention micro-benchmark (accel child only)
# ---------------------------------------------------------------------------

def _pallas_report(batch: int) -> dict:
    """Compile the Pallas flash kernels on the real chip at the TRUE
    flagship shape (B=batch, not a cut-down), check fwd parity vs the XLA
    path, and time fwd and fwd+bwd-with-dropout (the training
    configuration) for both paths. Timings chain iterations through a data
    dependency — the tunnel's block_until_ready alone under-reports."""
    import jax
    import jax.numpy as jnp
    from mxnet_tpu.ops.pallas_attention import flash_attention

    B, H, T, D = batch, 12, 512, 64
    rng = onp.random.RandomState(0)
    q = jnp.asarray(rng.randn(B, H, T, D), jnp.bfloat16)
    k = jnp.asarray(rng.randn(B, H, T, D), jnp.bfloat16)
    v = jnp.asarray(rng.randn(B, H, T, D), jnp.bfloat16)
    valid = rng.randint(T // 2, T, (B,))
    kmask = jnp.asarray(onp.arange(T)[None, :] < valid[:, None])
    seed = jnp.full((1, 1), 7, jnp.uint32)

    def xla_ref(q, k, v, m):
        s = jnp.einsum('bhqd,bhkd->bhqk', q, k,
                       preferred_element_type=jnp.float32) / (D ** 0.5)
        s = jnp.where(m[:, None, None, :], s, -1e30)
        return jnp.einsum('bhqk,bhkd->bhqd',
                          jax.nn.softmax(s, -1).astype(q.dtype), v)

    def xla_train_loss(q):
        # like-for-like training workload: dropout on the materialized
        # probability tensor, exactly what the Pallas kernel avoids
        s = jnp.einsum('bhqd,bhkd->bhqk', q, k,
                       preferred_element_type=jnp.float32) / (D ** 0.5)
        s = jnp.where(kmask[:, None, None, :], s, -1e30)
        a = jax.nn.softmax(s, -1).astype(q.dtype)
        keep = jax.random.bernoulli(jax.random.PRNGKey(7), 0.9, a.shape)
        a = jnp.where(keep, a / 0.9, 0).astype(q.dtype)
        return jnp.sum(jnp.einsum('bhqk,bhkd->bhqd', a, v)
                       .astype(jnp.float32))

    pall = jax.jit(lambda q: flash_attention(
        q, k, v, key_mask=kmask, interpret=False))
    ref = jax.jit(lambda q: xla_ref(q, k, v, kmask))
    pall_t = jax.jit(jax.grad(lambda q: jnp.sum(flash_attention(
        q, k, v, key_mask=kmask, dropout_p=0.1, dropout_seed=seed,
        interpret=False).astype(jnp.float32))))
    ref_t = jax.jit(jax.grad(xla_train_loss))

    o_p = jax.block_until_ready(pall(q))
    o_r = jax.block_until_ready(ref(q))
    err = float(jnp.max(jnp.abs(o_p.astype(jnp.float32)
                                - o_r.astype(jnp.float32))))

    def _time(fn, iters=15):
        # warm up the full pipeline incl. the sum+fetch sync, then time a
        # data-dependency-chained loop (independent dispatches through the
        # tunnel pipeline and under-report with block_until_ready alone)
        float(jnp.sum(fn(q).astype(jnp.float32)))
        t0 = time.time()
        out = q
        for _ in range(iters):
            out = fn(out)
        float(jnp.sum(out.astype(jnp.float32)))
        return (time.time() - t0) / iters * 1e3

    t_pallas, t_xla = _time(pall), _time(ref)
    t_pallas_t, t_xla_t = _time(pall_t), _time(ref_t)
    return {"shape": [B, H, T, D], "max_abs_err": round(err, 4),
            "fwd_pallas_ms": round(t_pallas, 3),
            "fwd_xla_ms": round(t_xla, 3),
            "train_pallas_ms": round(t_pallas_t, 3),
            "train_xla_ms": round(t_xla_t, 3),
            "train_speedup_vs_xla": round(
                t_xla_t / max(t_pallas_t, 1e-9), 3)}


# ---------------------------------------------------------------------------
# ResNet-50 secondary metric (BASELINE.md: images/sec/chip tracked;
# reference's own headline table is example/image-classification README)
# ---------------------------------------------------------------------------

def _resnet_report(batch=64):
    """ResNet-50 v1 training throughput: hybridized gluon zoo model,
    bf16, fused fwd+bwd+SGD step, batch sliced to the reference's
    224x224 config."""
    import jax
    import mxnet_tpu as mx
    from mxnet_tpu import nd
    from mxnet_tpu.gluon.model_zoo.vision import resnet50_v1
    from mxnet_tpu.parallel import make_mesh, ShardedTrainStep

    net = resnet50_v1(classes=1000)
    net.initialize(mx.init.Xavier())
    net.cast('bfloat16')

    def loss_fn(logits, labels):
        logp = nd.log_softmax(logits, axis=-1)
        return -nd.mean(nd.pick(logp, labels, axis=-1))

    devices = [d for d in jax.devices() if d.platform != 'cpu'] \
        or jax.devices()
    mesh = make_mesh((len(devices),), ('dp',), devices=devices)
    step = ShardedTrainStep(net, loss_fn, 'sgd',
                            {'learning_rate': 0.1, 'momentum': 0.9},
                            mesh=mesh)
    rng = onp.random.RandomState(0)
    x = nd.array(rng.randn(batch, 3, 224, 224).astype(onp.float32))
    y = nd.array(rng.randint(0, 1000, (batch,)).astype(onp.int32))
    for _ in range(2):
        v = float(step([x], [y]).asnumpy())
        assert onp.isfinite(v), "non-finite resnet loss"
    steps = 8
    t0 = time.time()
    for _ in range(steps):
        loss = step([x], [y])
    float(loss.asnumpy())
    dt = (time.time() - t0) / steps
    return {"batch": batch, "step_ms": round(dt * 1000, 1),
            "images_per_sec_per_chip":
                round(batch / dt / len(devices), 1),
            "ref_baseline_images_per_sec": 109,
            "ref_baseline_hw": "1x K80 (example/image-classification)"}


# ---------------------------------------------------------------------------
# Data-IO secondary metric: decode+augment throughput of the native
# libjpeg pipeline (src/io/mxtpu_io.cc). The reference publishes
# ~3000 images/sec for its decode+augment loop
# (ref: docs/static_site/src/pages/api/architecture/note_data_loading.md:181)
# — host-side work, so this is CPU-measurable regardless of the tunnel.
# ---------------------------------------------------------------------------

def _io_report(n_images=384, src_hw=(360, 480), out_hw=224):
    """images/sec through ImageRecordIter: JPEG decode,
    resize-shorter-side, random crop to out_hw², mirror, mean/std.

    A/B across the host-boundary transports (ISSUE 3):
      f32-copy                 C++ normalizes to f32 NCHW, batch copied out
      u8-lease                 zero-copy uint8 NHWC buffer lease, mean/std
                               + NCHW conversion jitted on device
      u8-lease+device-prefetch same, plus 2 batches kept in flight on
                               device via async device_put
    Bytes through host per image come from the
    mxnet_tpu_io_host_bytes_total counter (u8 moves ~4x less than f32).
    """
    import io as pyio
    import tempfile

    from PIL import Image
    from mxnet_tpu import recordio, telemetry
    from mxnet_tpu.io import ImageRecordIter, DevicePrefetchIter

    with tempfile.TemporaryDirectory() as td:
        rec_path = os.path.join(td, 'bench.rec')
        rec = recordio.MXRecordIO(rec_path, 'w')
        rng = onp.random.RandomState(0)
        for i in range(n_images):
            img = (rng.rand(src_hw[0], src_hw[1], 3) * 255).astype(onp.uint8)
            buf = pyio.BytesIO()
            Image.fromarray(img).save(buf, format='JPEG', quality=90)
            rec.write(recordio.pack(
                recordio.IRHeader(0, float(i % 10), i, 0), buf.getvalue()))
        rec.close()

        batch = 64
        threads = os.cpu_count() or 4
        native = None

        def run(transport, device_prefetch, epochs=3):
            nonlocal native
            it = ImageRecordIter(
                path_imgrec=rec_path, data_shape=(3, out_hw, out_hw),
                batch_size=batch, resize=256, rand_crop=True,
                rand_mirror=True, mean_r=123.68, mean_g=116.78,
                mean_b=103.94, std_r=58.4, std_g=57.1, std_b=57.4,
                preprocess_threads=threads, transport=transport)
            native = getattr(it, '_pipe', None) is not None
            src = DevicePrefetchIter(it, depth=2) if device_prefetch else it
            # cold epoch (thread spin-up, jit trace, decode-cache fill),
            # timed separately — the timed epochs then measure the
            # steady-state transport path, which is what the A/B is
            # about; sync the last batch so async device work is inside
            # the measurement
            t0 = time.time()
            cold_seen = 0
            for batch_data in src:
                cold_seen += batch_data.data[0].shape[0]
            onp.asarray(batch_data.data[0].asnumpy())
            cold_ips = round(cold_seen / (time.time() - t0), 1)
            was_on = telemetry.enabled()
            telemetry.enable()
            bytes0 = telemetry.counter(
                'mxnet_tpu_io_host_bytes_total').value() or 0
            seen = 0
            t0 = time.time()
            for _ in range(epochs):
                src.reset()
                for batch_data in src:
                    seen += batch_data.data[0].shape[0]
            onp.asarray(batch_data.data[0].asnumpy())
            dt = time.time() - t0
            host_bytes = (telemetry.counter(
                'mxnet_tpu_io_host_bytes_total').value() or 0) - bytes0
            if not was_on:
                telemetry.disable()
            out = {"images_per_sec": round(seen / dt, 1),
                   "cold_epoch_images_per_sec": cold_ips,
                   "host_bytes_per_image": round(host_bytes / max(seen, 1))}
            if native:
                hits, misses, cache_bytes = it._pipe.cache_stats()
                out["decode_cache"] = {
                    "hits": int(hits), "misses": int(misses),
                    "bytes": int(cache_bytes)}
            return out

        ab = {"f32-copy": run('f32', False),
              "u8-lease": run('u8', False),
              "u8-lease+device-prefetch": run('u8', True)}
        best = ab["u8-lease+device-prefetch"]["images_per_sec"]
        return {"images_per_sec": best,
                "native_pipeline": native,
                "ab": ab,
                "u8_lease_speedup_vs_f32_copy": round(
                    ab["u8-lease"]["images_per_sec"]
                    / max(ab["f32-copy"]["images_per_sec"], 1e-9), 2),
                "host_bytes_ratio_f32_over_u8": round(
                    ab["f32-copy"]["host_bytes_per_image"]
                    / max(ab["u8-lease"]["host_bytes_per_image"], 1), 2),
                "decode": f"jpeg {src_hw[0]}x{src_hw[1]} -> resize256 -> "
                          f"crop{out_hw} + mirror + mean/std",
                "note": "timed epochs are steady-state (decode cache "
                        "warm); cold_epoch_images_per_sec is the "
                        "decode-bound first epoch",
                "decode_cache_mb": float(os.environ.get(
                    'MXNET_TPU_IO_DECODE_CACHE_MB', '256')),
                "threads": threads,
                "ref_baseline_images_per_sec": 3000}


def _zero_probe_child() -> None:
    """``--zero-probe``: one JSON line with the ZeRO memory trajectory
    on a forced 8-device host-CPU mesh — the tiny-BERT pjit step at
    stage off/1/3, param + master + optimizer bytes per device, gather
    wire bytes per step, and the 3-step loss parity across stages.
    Runs as its own process because the host device count must be fixed
    before jax initializes."""
    os.environ['JAX_PLATFORMS'] = 'cpu'
    prev = os.environ.get('XLA_FLAGS', '')
    if '--xla_force_host_platform_device_count' not in prev:
        os.environ['XLA_FLAGS'] = \
            (prev + ' --xla_force_host_platform_device_count=8').strip()
    import mxnet_tpu as mx
    from mxnet_tpu import nd
    from mxnet_tpu.models import BertForPretraining
    from mxnet_tpu.models.bert import bert_pretrain_loss
    from mxnet_tpu.parallel import make_mesh, ShardedTrainStep

    cfg = dict(vocab_size=1024, hidden=128, layers=2, heads=4,
               intermediate=256, max_len=128, type_vocab=2, dropout=0.0)
    mesh = make_mesh((8,), ('dp',))
    rng = onp.random.RandomState(0)
    batch, seq = 8, 64
    tokens = nd.array(rng.randint(0, cfg['vocab_size'], (batch, seq))
                      .astype(onp.int32))
    types = nd.array(onp.zeros((batch, seq), onp.int32))
    labels = onp.full((batch, seq), -1, onp.int32)
    labels[:, :8] = rng.randint(0, cfg['vocab_size'], (batch, 8))
    labels = nd.array(labels)
    nsp = nd.array(rng.randint(0, 2, batch).astype(onp.int32))

    rep, losses = {'dp': 8}, {}
    for stage in (0, 1, 3):
        mx.random.seed(0)
        model = BertForPretraining(cfg)
        model.initialize(mx.init.Normal(0.02))
        step = ShardedTrainStep(model, bert_pretrain_loss, 'adamw',
                                {'learning_rate': 1e-4}, mesh=mesh,
                                zero=stage)
        losses[stage] = [
            float(step([tokens, types], [labels, nsp]).asscalar())
            for _ in range(3)]
        pb = step.param_bytes_per_device()
        sb = step.opt_state_bytes_per_device()
        rep[f'stage{stage}'] = {
            'param_bytes_per_device': pb,
            'opt_state_bytes_per_device': sb,
            'persistent_bytes_per_device': pb + sb,
            'gather_bytes_per_step': step.gather_bytes_per_step(),
            'comm_bytes_per_step': {k: int(v[0]) for k, v in
                                    step._comm_plan.items()},
        }
    rep['loss_max_diff_3v1'] = max(
        abs(a - b) for a, b in zip(losses[3], losses[1]))
    rep['loss_max_diff_3v0'] = max(
        abs(a - b) for a, b in zip(losses[3], losses[0]))
    print(json.dumps(rep), flush=True)


def _zero_report(step, timeout=240.0):
    """The ``"zero"`` field: the live bench step's ZeRO stage and
    residency numbers, plus — when the live mesh has no >1-device dp
    axis (the 1-device CPU smoke) — a ``--zero-probe`` subprocess on a
    forced 8-device mesh so BENCH rounds capture the off/1/3 memory
    trajectory either way."""
    live = {
        'stage': getattr(step, 'zero_stage', 1 if step.zero else 0),
        'dp': step._dp_size,
        'param_bytes_per_device': step.param_bytes_per_device(),
        'opt_state_bytes_per_device': step.opt_state_bytes_per_device(),
        'gather_bytes_per_step': step.gather_bytes_per_step(),
        'comm_bytes_per_step': {k: int(v[0]) for k, v in
                                (step._comm_plan or {}).items()},
    }
    if step._dp_size > 1:
        return live
    # never let the probe blow the child's overall budget (same contract
    # as the resnet report): clamp to the remaining deadline and skip
    # when too little is left for three stage compiles
    child_deadline = float(os.environ.get('BENCH_CHILD_DEADLINE', '0'))
    if child_deadline:
        timeout = min(timeout, child_deadline - time.time() - 30)
        if timeout < 45:
            live['dp8_probe'] = {'skipped': 'child deadline too close'}
            return live
    try:
        res = subprocess.run(
            [sys.executable, os.path.abspath(__file__), '--zero-probe'],
            capture_output=True, text=True, timeout=timeout)
        for line in reversed(res.stdout.strip().splitlines()):
            try:
                live['dp8_probe'] = json.loads(line)
                break
            except ValueError:
                continue
        else:
            live['dp8_probe'] = {
                'error': f'no JSON line (rc={res.returncode}): '
                         f'{res.stderr[-200:]}'}
    except subprocess.TimeoutExpired:
        live['dp8_probe'] = {'error': f'timeout after {timeout}s'}
    return live


def _compile_probe_child() -> None:
    """``--compile-probe``: one JSON line with the compile ledger of a
    tiny-BERT pjit step built FROM SCRATCH in this process, the compile
    plane armed over ``BENCH_COMPILE_LEDGER`` and the persistent XLA
    cache over ``BENCH_COMPILE_CACHE_DIR``. ``_compile_report`` runs it
    twice against one shared cache dir: the first process pays the full
    cold XLA backend compile, the second must hit the cache — the
    process-level cold-vs-warm A/B (a fresh process is the only honest
    cold start: jax's in-memory caches die with it)."""
    os.environ['JAX_PLATFORMS'] = 'cpu'
    prev = os.environ.get('XLA_FLAGS', '')
    if '--xla_force_host_platform_device_count' not in prev:
        os.environ['XLA_FLAGS'] = \
            (prev + ' --xla_force_host_platform_device_count=8').strip()
    import mxnet_tpu as mx
    from mxnet_tpu import nd
    from mxnet_tpu.models import BertForPretraining
    from mxnet_tpu.models.bert import bert_pretrain_loss
    from mxnet_tpu.parallel import make_mesh, ShardedTrainStep
    from mxnet_tpu.telemetry import compile as _compile

    _compile.enable()
    _compile.clear(
        ledger=os.environ.get('BENCH_COMPILE_LEDGER', ''),
        cache_dir=os.environ.get('BENCH_COMPILE_CACHE_DIR', ''))
    cfg = dict(vocab_size=1024, hidden=128, layers=2, heads=4,
               intermediate=256, max_len=128, type_vocab=2, dropout=0.0)
    mesh = make_mesh((8,), ('dp',))
    rng = onp.random.RandomState(0)
    batch, seq = 8, 64
    tokens = nd.array(rng.randint(0, cfg['vocab_size'], (batch, seq))
                      .astype(onp.int32))
    types = nd.array(onp.zeros((batch, seq), onp.int32))
    labels = onp.full((batch, seq), -1, onp.int32)
    labels[:, :8] = rng.randint(0, cfg['vocab_size'], (batch, 8))
    labels = nd.array(labels)
    nsp = nd.array(rng.randint(0, 2, batch).astype(onp.int32))

    mx.random.seed(0)
    # auto-named: the step jit boundary is name-stable (positional
    # token aliases), so A/B processes share cache entries regardless
    # of where the gluon naming counter sits
    model = BertForPretraining(cfg)
    model.initialize(mx.init.Normal(0.02))
    step = ShardedTrainStep(model, bert_pretrain_loss, 'adamw',
                            {'learning_rate': 1e-4}, mesh=mesh)
    loss = float(step([tokens, types], [labels, nsp]).asscalar())

    sites = {}
    for e in _compile.ledger():
        sites[e['site']] = round(
            sites.get(e['site'], 0.0) + e['seconds']['total'], 4)
    ent = [e for e in _compile.ledger()
           if e['site'] == 'step:train_step']
    sec = ent[-1]['seconds'] if ent else {}
    pc = _compile.persistent_cache_stats()
    rep = {
        'loss': round(loss, 6),
        'site_seconds': sites,
        'step': {k: round(v, 4) for k, v in sec.items()},
        'cache': {'hits': pc['hits'], 'misses': pc['misses'],
                  'saved_seconds_est': round(pc['saved_seconds_est'], 4),
                  'bytes': pc['bytes'], 'files': pc['files']},
        'ledger_entries': len(_compile.ledger()),
    }
    print(json.dumps(rep), flush=True)


def _run_compile_probe(cache_dir, ledger, timeout):
    """One ``--compile-probe`` child sharing cache_dir + ledger; the
    parsed JSON dict (module-level so the bench contract test can stub
    the subprocess away)."""
    env = dict(os.environ, BENCH_COMPILE_CACHE_DIR=cache_dir,
               BENCH_COMPILE_LEDGER=ledger)
    res = subprocess.run(
        [sys.executable, os.path.abspath(__file__), '--compile-probe'],
        capture_output=True, text=True, timeout=timeout, env=env)
    for line in reversed((res.stdout or '').strip().splitlines()):
        try:
            return json.loads(line)
        except ValueError:
            continue
    raise RuntimeError(f'no JSON from compile probe '
                       f'(rc={res.returncode}): {res.stderr[-200:]}')


def _compile_report(timeout=240.0):
    """The ``"compile"`` field (ISSUE 16): the live process's per-site
    compile seconds from the in-memory ledger (when the plane is
    armed), plus the cold-vs-warm persistent-cache A/B — two
    ``--compile-probe`` child processes sharing one XLA cache dir and
    one on-disk ledger, so the warm child's saved-seconds estimate is
    priced from the cold child's recorded compile time."""
    import tempfile
    from mxnet_tpu.telemetry import compile as _compile
    out = {'enabled': _compile.enabled(),
           'ledger_path': _compile.ledger_path() or None}
    if _compile.enabled():
        sites = {}
        for e in _compile.ledger():
            sites[e['site']] = round(
                sites.get(e['site'], 0.0) + e['seconds']['total'], 4)
        out['site_seconds'] = sites
    # same deadline contract as the zero/resnet reports: each A/B child
    # gets an equal slice of what's left, and too-little-left skips
    child_deadline = float(os.environ.get('BENCH_CHILD_DEADLINE', '0'))
    if child_deadline:
        timeout = min(timeout, (child_deadline - time.time() - 30) / 2)
        if timeout < 45:
            out['cache_ab'] = {'skipped': 'child deadline too close'}
            return out
    with tempfile.TemporaryDirectory() as td:
        cache = os.path.join(td, 'xla_cache')
        ledger = os.path.join(td, 'ledger.jsonl')
        cold = _run_compile_probe(cache, ledger, timeout)
        warm = _run_compile_probe(cache, ledger, timeout)
    ab = {'cold': cold, 'warm': warm,
          'warm_hit': bool((warm.get('cache') or {}).get('hits'))}
    cb = (cold.get('step') or {}).get('backend')
    wb = (warm.get('step') or {}).get('backend')
    if cb and wb:
        ab['backend_speedup'] = round(cb / max(wb, 1e-9), 1)
    out['cache_ab'] = ab
    return out


def _serving_report(requests=60, deadlines=(0.0, 2.0, 8.0),
                    fleet_timeout=180.0):
    """The ``"serving"`` field (ISSUE 17): measured predict QPS and
    p50/p99 latency vs the batch-formation deadline on one replica
    (same compiled programs across the sweep — the engines share one
    warmed runner), an int8-quantized A/B on the same traffic, and the
    two-replica fleet drill's numbers (failover storm QPS, drain MTTR,
    cold-vs-warm AOT warmup seconds)."""
    import tempfile
    import threading

    import numpy as onp

    from mxnet_tpu import nd, serving
    from mxnet_tpu.gluon import nn
    from mxnet_tpu.telemetry import compile as _compile

    _compile.enable()     # the warmup report's compile count reads it

    class _Tok(nn.HybridBlock):
        def __init__(self, **kw):
            super().__init__(**kw)
            with self.name_scope():
                self.embed = nn.Embedding(64, 32)
                self.proj = nn.Dense(8, flatten=False)

        def forward(self, x):
            return self.proj(self.embed(x))

    def _storm(engine, seqs):
        errs = []

        def client(seq):
            try:
                engine.submit(seq, timeout=60.0)
            except Exception as e:                    # noqa: BLE001
                errs.append(repr(e))
        t0 = time.perf_counter()
        threads = [threading.Thread(target=client, args=(s,))
                   for s in seqs]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        wall = time.perf_counter() - t0
        st = engine.stats()
        return {'qps': round(len(seqs) / max(wall, 1e-9), 1),
                'p50_ms': st['p50_ms'], 'p99_ms': st['p99_ms'],
                'batches': st['batches'],
                'fill': round(len(seqs) / max(st['batches'], 1), 2),
                'errors': errs[:3]}

    rng = onp.random.RandomState(11)
    seqs = [[int(v) for v in rng.randint(0, 64, rng.randint(1, 33))]
            for _ in range(requests)]
    net = _Tok()
    net.initialize()
    runner = serving.BlockRunner(net)
    out = {'requests': requests, 'seq_buckets': [16, 32],
           'batch_buckets': [1, 2, 4, 8]}
    sweep = {}
    for i, dl in enumerate(deadlines):
        eng = serving.InferenceEngine(
            runner, seq_buckets='16,32', batch_buckets='1,2,4,8',
            deadline_ms=dl)
        if i == 0:
            # one warmup covers the whole sweep: every engine rides the
            # same block's CachedOp programs
            warm = serving.warmup(eng)
            out['warmup'] = {'total_seconds': warm['total_seconds'],
                             'compiles': warm['compiles']}
        sweep[f'{dl:g}ms'] = _storm(eng, seqs)
        eng.drain()
    out['deadline_sweep'] = sweep
    # int8 weights A/B on the same traffic (PR 11 codec grid): the
    # latency delta and the worst-case output drift on a fixed probe
    probe = [1, 2, 3, 5, 7]
    base = onp.asarray(runner(onp.asarray(
        [probe + [0] * 11], 'int32')))[0, :5]
    qnet = _Tok()
    qnet.initialize()
    qnet(nd.array(onp.zeros((1, 16), 'int32')))
    fd, tmp = tempfile.mkstemp(suffix='.params')
    os.close(fd)
    try:
        net.save_parameters(tmp)
        qnet.load_parameters(tmp)
    finally:
        os.unlink(tmp)
    serving.quantize_weights(qnet, 'int8')
    qrunner = serving.BlockRunner(qnet)
    qeng = serving.InferenceEngine(qrunner, seq_buckets='16,32',
                                   batch_buckets='1,2,4,8',
                                   deadline_ms=2.0)
    serving.warmup(qeng)
    qab = _storm(qeng, seqs)
    qeng.drain()
    qout = onp.asarray(qrunner(onp.asarray(
        [probe + [0] * 11], 'int32')))[0, :5]
    qab['max_output_drift'] = round(
        float(onp.max(onp.abs(qout - base))), 5)
    out['int8_ab'] = qab
    # the fleet half: 2 replica processes + router, SIGTERM mid-storm
    child_deadline = float(os.environ.get('BENCH_CHILD_DEADLINE', '0'))
    if child_deadline and child_deadline - time.time() < 90:
        out['fleet'] = {'skipped': 'child deadline too close'}
        return out
    from mxnet_tpu.resilience.drill import run_serving_drill
    with tempfile.TemporaryDirectory() as td:
        drill = run_serving_drill(td, timeout=fleet_timeout)
    out['fleet'] = {
        'requests': drill['requests'], 'failed': drill['failed'],
        'failovers': drill['failovers'],
        'mttr_seconds': drill['mttr_seconds'],
        'warmup_cold_seconds': drill['warmup'][1]['total_seconds'],
        'warmup_warm_seconds': drill['warmup'][2]['total_seconds'],
        'warm_cache_hits': drill['warmup'][2]['cache']['hits'],
        'p50_ms': {r: s['p50_ms'] for r, s in drill['stats'].items()},
    }
    return out


def _run_autotune_sweep(db_dir, heads=12, seq=512, head_dim=64):
    """One flash-attention autotune sweep at the flagship BERT shape
    into ``db_dir`` (module-level so the contract tests stub it)."""
    import jax.numpy as jnp

    from mxnet_tpu.ops import autotune
    return autotune.sweep_flash_attention(
        batch=1, heads=heads, seq=seq, head_dim=head_dim,
        dtype=jnp.float32, db_dir=db_dir)


def _autotune_report(timeout=120.0):
    """The ``"autotune"`` field (ISSUE 18): the flash-attention block
    sweep at the flagship shape — measured on TPU, analytic ranking on
    CPU — plus the round-trip proof: a fresh ``_block_sizes`` resolve
    consumes the winner the sweep just persisted (source ``db``), which
    is exactly what the compile-ledger signature records in training."""
    import tempfile

    import jax.numpy as jnp

    from mxnet_tpu import config as _mxcfg
    from mxnet_tpu.ops import autotune

    child_deadline = float(os.environ.get('BENCH_CHILD_DEADLINE', '0'))
    if child_deadline and child_deadline - time.time() < 90:
        return {'skipped': 'child deadline too close'}
    out = {'remat_policy': _mxcfg.get('MXTPU_REMAT')}
    prev_dir = os.environ.get('MXTPU_AUTOTUNE_DIR')
    with tempfile.TemporaryDirectory() as td:
        try:
            rep = _run_autotune_sweep(td)
            out['mode'] = rep.get('mode')
            out['sweep_seconds'] = rep.get('sweep_seconds')
            for kind in ('fwd', 'bwd'):
                r = rep.get(kind)
                if r:
                    out[kind] = {'winner': r['winner'],
                                 'source': r['source'],
                                 'candidates': r['candidates'],
                                 'pruned': r['pruned'],
                                 'signature': r['signature']}
            # consumption round trip: a clean resolve state + the DB dir
            # in the env must route _block_sizes to the persisted winner
            os.environ['MXTPU_AUTOTUNE_DIR'] = td
            autotune.clear()
            from mxnet_tpu.ops.pallas_attention import _block_sizes
            got = _block_sizes(12, 512, 512, 64, jnp.float32, 'fwd')
            out['consumed'] = {'blocks': list(got),
                               'decisions': autotune.decision_flags()}
        finally:
            if prev_dir is None:
                os.environ.pop('MXTPU_AUTOTUNE_DIR', None)
            else:
                os.environ['MXTPU_AUTOTUNE_DIR'] = prev_dir
            autotune.clear()
    return out


def _run_sparse_drill(hot_fractions=(1.0, 0.1, 0.02), vocab=20000,
                      dim=32, batch=64, seq=8, steps=3):
    """One dense-vs-sparse embedding drill (module-level so the
    contract tests stub it): build one sparse and one dense step over
    the same wide-table model, then at each hot fraction draw batches
    from the first ``hot_fraction * vocab`` rows and time both paths.
    Returns the sweep rows plus the sparse step's analytic report."""
    import numpy as onp

    import mxnet_tpu as mx
    from mxnet_tpu import nd
    from mxnet_tpu.gluon import nn
    from mxnet_tpu.parallel import ShardedTrainStep

    def loss_fn(out, label):
        return (out - label) ** 2

    lab_np = onp.random.RandomState(1).randn(
        batch, seq, 8).astype('float32')
    warm_np = onp.random.RandomState(2).randint(
        0, vocab, size=(batch, seq)).astype('float32')

    def build(sparse):
        # the step builds lazily on its first call, so the env knob
        # must still hold when the warmup step runs — warm up here,
        # inside the knob's scope (also moves compile off the timers)
        os.environ['MXTPU_SPARSE'] = '1' if sparse else '0'
        mx.random.seed(7)
        net = nn.HybridSequential()
        net.add(nn.Embedding(vocab, dim, sparse_grad=True))
        net.add(nn.Dense(8, flatten=False))
        net.initialize()
        step = ShardedTrainStep(net, loss_fn, 'adam',
                                {'learning_rate': 0.01})
        step(nd.array(warm_np), nd.array(lab_np)).asnumpy()
        return step

    prev = os.environ.get('MXTPU_SPARSE')
    try:
        s_step = build(True)
        d_step = build(False)
        lab = nd.array(lab_np)
        sweep = []
        for frac in hot_fractions:
            hot = max(1, int(vocab * frac))
            rng = onp.random.RandomState(3)
            row = {'hot_fraction': frac}
            for tag, st in (('sparse', s_step), ('dense', d_step)):
                times = []
                for _ in range(steps):
                    ids = nd.array(rng.randint(
                        0, hot, size=(batch, seq)).astype('float32'))
                    t0 = time.perf_counter()
                    st(ids, lab).asnumpy()
                    times.append((time.perf_counter() - t0) * 1e3)
                row[f'{tag}_p50_ms'] = sorted(times)[len(times) // 2]
            stats = getattr(s_step, '_sparse_prev_stats', None) or {}
            live = sum(int(v) for v in stats.values())
            row['live_rows'] = live
            row['update_bytes'] = live * dim * 4
            row['dedup_ratio'] = round(batch * seq / max(1, live), 2)
            sweep.append(row)
        return {'report': s_step.sparse_report(), 'sweep': sweep}
    finally:
        if prev is None:
            os.environ.pop('MXTPU_SPARSE', None)
        else:
            os.environ['MXTPU_SPARSE'] = prev


def _sparse_report():
    """The ``"sparse"`` field (ISSUE 19): update-bytes/step and step
    time, sparse vs dense, across hot-fraction sweeps — the RowSparse
    fast path's shrink measured end to end on the live step."""
    child_deadline = float(os.environ.get('BENCH_CHILD_DEADLINE', '0'))
    if child_deadline and child_deadline - time.time() < 90:
        return {'skipped': 'child deadline too close'}
    drill = _run_sparse_drill()
    rep = drill['report'] or {}
    return {
        'mode': rep.get('mode'),
        'tables': rep.get('tables'),
        'update_bytes_per_step': rep.get('update_bytes_per_step'),
        'dense_update_bytes_per_step':
            rep.get('dense_update_bytes_per_step'),
        'update_shrink': rep.get('update_shrink'),
        'exchange_bytes_per_hop': rep.get('exchange_bytes_per_hop'),
        'sweep': drill['sweep'],
    }


def _memory_report(step, run_step, steps=4):
    """The ``"memory"`` field (ISSUE 14): live/peak watermark over a few
    sampled steps (the backend allocator's ``memory_stats`` where it
    exists, the deterministic tracked-array fallback otherwise), the
    ``memory_analysis()`` per-device bucket table whose sum
    reconstructs the measured peak, and whether XLA's compiled-program
    memory analysis was available on this backend — so every BENCH
    round pins the memory trajectory next to the time one."""
    from mxnet_tpu.telemetry import memory

    was = memory.enabled()
    memory.clear()                       # samples only; pools survive
    memory.enable()
    # idempotent re-registration: the report must measure THIS step's
    # residency even if something earlier in the child wiped the
    # registry (clear(pools=True))
    memory.register_provider(step)
    memory.set_analysis_provider(step.memory_analysis, owner=step)
    try:
        for _ in range(steps):
            run_step()
        rep = step.memory_analysis()
        wm = memory.watermarks()
        out = {
            'samples': len(wm),
            'live_bytes_per_device': wm[-1]['device_bytes'] if wm
            else None,
            'peak_bytes_per_device': memory.peak_bytes(),
            'host_rss_bytes': memory.host_rss_bytes(),
            'source': wm[-1]['source'] if wm else None,
            'memory_analysis_available': rep is not None,
            'xla_memory_analysis_available':
                bool(rep and rep.get('xla')),
        }
        if rep:
            out['buckets_bytes'] = rep['buckets_bytes']
            out['bucket_sum_over_peak'] = rep['bucket_sum_over_peak']
            out['measured_fraction'] = rep['measured_fraction']
            out['zero_stage'] = rep['zero_stage']
            if rep.get('xla'):
                out['xla'] = rep['xla']
        return out
    finally:
        memory.clear()
        (memory.enable if was else memory.disable)()


def _attribution_report(step, model, run_step, flops, peak_total,
                        steps=8):
    """Per-step attribution (ISSUE 6): arm span tracing, run a few
    synced steps, and decompose wall time into input / h2d / compute /
    collective / host-sync buckets joined with XLA cost_analysis — so
    BENCH_r06+ carries fractions, not just img/s and step ms.

    When the run itself was launched with MXTPU_TRACE=1, also save one
    checkpoint inside the traced window (covering the checkpoint.*
    spans) and leave `bench_trace.json` behind — a single
    chrome://tracing-loadable timeline of the whole traced segment.
    """
    from mxnet_tpu import config as _mxcfg
    from mxnet_tpu.telemetry import attribution, flight, trace

    armed_by_env = _mxcfg.get('MXTPU_TRACE')
    trace.enable()
    flight.get().clear()
    for _ in range(steps):
        run_step()
    if armed_by_env:
        import tempfile
        from mxnet_tpu.checkpoint import CheckpointManager
        with tempfile.TemporaryDirectory() as td:
            mgr = CheckpointManager(td, params=model, async_save=False)
            mgr.save(steps)
    comm_plan = getattr(step, '_comm_plan', None) or {}
    rep = attribution.report(
        flight.get().steps(), flops_per_step=flops,
        peak_flops=peak_total,
        collective_bytes={k: v[0] for k, v in comm_plan.items()},
        gather_layers=getattr(step, '_gather_plan', None))
    xla = step.cost_analysis()
    if xla:
        rep['xla_cost_per_step'] = xla
    rep['subsystems'] = attribution.subsystems(
        {e['name'] for e in trace.chrome_events()}
        | {n for r in flight.get().steps() for n in r['spans_ms']})
    if armed_by_env:
        rep['trace_dump'] = trace.dump('bench_trace.json')
    else:
        trace.disable()
    return rep


def _fleet_report(run_step, steps=6):
    """Endpoint-armed vs disarmed step-time A/B (ISSUE 13): the same
    step timed with everything observability off, then with telemetry +
    tracing armed, the /metrics //healthz endpoint up AND a scraper
    hammering it concurrently — plus the wire size of one heartbeat
    telemetry snapshot. The PERF_NOTES "what does watching cost" row."""
    import threading
    import urllib.request
    from mxnet_tpu import telemetry
    from mxnet_tpu.base import telem_flags
    from mxnet_tpu.telemetry import fleet, server, trace

    was_telem, was_trace = telem_flags['on'], trace.enabled()

    def timed(n):
        t0 = time.time()
        for _ in range(n):
            run_step()
        return (time.time() - t0) / n * 1e3

    srv = None
    stop = threading.Event()
    scrapes = [0]
    t = None
    try:
        telemetry.disable()
        trace.disable()
        run_step()                               # settle / recompile
        disarmed_ms = timed(steps)
        telemetry.enable()
        trace.enable()
        srv = server.TelemetryServer(port=0)

        def _scrape():
            base = f'http://127.0.0.1:{srv.port}'
            while not stop.is_set():
                try:
                    urllib.request.urlopen(base + '/metrics',
                                           timeout=2).read()
                    urllib.request.urlopen(base + '/healthz',
                                           timeout=2).read()
                    scrapes[0] += 1
                except Exception:
                    pass
                stop.wait(0.05)

        t = threading.Thread(target=_scrape, daemon=True)
        t.start()
        run_step()                               # settle under arming
        armed_ms = timed(steps)
        snap_bytes = fleet.snapshot_bytes()
    finally:
        # a mid-A/B failure must not leave the child's telemetry/trace
        # disarmed (the atexit flight dump would be empty) or leak the
        # scraper + server for the rest of the process
        stop.set()
        if t is not None:
            t.join(timeout=2)
        if srv is not None:
            srv.stop()
        (telemetry.enable if was_telem else telemetry.disable)()
        (trace.enable if was_trace else trace.disable)()
    return {
        'steps': steps,
        'step_ms_disarmed': round(disarmed_ms, 2),
        'step_ms_armed': round(armed_ms, 2),
        'overhead_pct': round(
            (armed_ms - disarmed_ms) / disarmed_ms * 100.0, 2)
        if disarmed_ms else None,
        'snapshot_bytes_per_beat': snap_bytes,
        'scrapes_during_armed_window': scrapes[0],
    }


# ---------------------------------------------------------------------------
# measurement child
# ---------------------------------------------------------------------------

def _child(mode: str) -> None:
    if mode == 'cpu':
        os.environ['JAX_PLATFORMS'] = 'cpu'
    import jax
    if mode == 'cpu':
        jax.config.update('jax_platforms', 'cpu')
    _enable_compile_cache()

    import mxnet_tpu as mx
    from mxnet_tpu import nd
    from mxnet_tpu.models import BertForPretraining
    from mxnet_tpu.models.bert import bert_base_config, bert_pretrain_loss
    from mxnet_tpu.parallel import make_mesh, ShardedTrainStep

    devices = [d for d in jax.devices() if d.platform != 'cpu'] \
        or jax.devices()
    on_accel = devices[0].platform != 'cpu'
    _log(f"child backend={devices[0].platform} "
         f"kind={getattr(devices[0], 'device_kind', '?')} n={len(devices)}")

    if on_accel:
        cfg = bert_base_config()
        batch = int(os.environ.get('BENCH_BATCH', '32'))
        seq, steps, warmup = 512, 10, 3
        dtype = 'bfloat16'
    else:
        # smoke scale: proves the path end-to-end anywhere
        cfg = dict(vocab_size=4096, hidden=256, layers=4, heads=4,
                   intermediate=1024, max_len=128, type_vocab=2)
        batch, seq, steps, warmup = 8, 128, 3, 1
        dtype = 'float32'

    model = BertForPretraining(cfg)
    model.initialize(mx.init.Normal(0.02))
    if dtype != 'float32':
        model.cast(dtype)

    mesh = make_mesh((len(devices),), ('dp',), devices=devices)
    step = ShardedTrainStep(model, bert_pretrain_loss, 'adamw',
                            {'learning_rate': 1e-4}, mesh=mesh)

    rng = onp.random.RandomState(0)
    tokens = nd.array(rng.randint(0, cfg['vocab_size'], (batch, seq))
                      .astype(onp.int32))
    types = nd.array(onp.zeros((batch, seq), onp.int32))
    # flagship config trains WITH a padding mask (sequences padded to 512)
    valid_length = nd.array(rng.randint(seq // 2, seq + 1, (batch,))
                            .astype(onp.int32))
    # GluonNLP recipe: the MLM decoder runs only on the masked positions
    # (max_predictions_per_seq), not all T of them
    nmask = max(8, int(0.15 * seq) // 8 * 8)
    mpos = onp.stack([rng.choice(seq, nmask, replace=False)
                      for _ in range(batch)]).astype(onp.int32)
    masked_positions = nd.array(mpos)
    labels = nd.array(rng.randint(0, cfg['vocab_size'], (batch, nmask))
                      .astype(onp.int32))
    nsp = nd.array(rng.randint(0, 2, (batch,)).astype(onp.int32))

    from mxnet_tpu.ops import attention as attn_ops
    inputs = [tokens, types, valid_length, masked_positions]
    for i in range(warmup):
        v = float(step(inputs, [labels, nsp]).asnumpy())
        _log(f"warmup {i}: loss={v:.4f}")
        assert onp.isfinite(v), "non-finite loss"
    route = dict(attn_ops.route_counts)
    _log(f"attention routing (trace-time): {route}")
    t0 = time.time()
    for _ in range(steps):
        loss = step(inputs, [labels, nsp])
    float(loss.asnumpy())  # sync the whole chain
    dt = (time.time() - t0) / steps

    # Honest MFU accounting: lookup-only embedding tables do no matmul
    # FLOPs; the MLM head (dense+ln+decoder) touches only the nmask masked
    # positions; pooler+nsp touch one position per sequence.
    params = model.collect_params()
    P = sum(int(onp.prod(p.shape)) for p in params.values())
    def _psize(names):
        return sum(int(onp.prod(p.shape)) for n, p in params.items()
                   if any(s in n for s in names))
    P_embed = _psize(['word_embed', 'pos_embed', 'type_embed',
                      'embedding'])
    P_head = _psize(['mlm_'])
    P_pool = _psize(['pooler', 'nsp'])
    P_body = P - P_embed - P_head - P_pool
    tokens_per_step = batch * seq
    # PaLM-appendix accounting: 6*P per processed token (fwd+bwd) + the
    # O(T^2) attention term 12*L*h*T per token
    flops = (6 * P_body * tokens_per_step
             + 6 * P_head * batch * nmask
             + 6 * P_pool * batch
             + 12 * cfg['layers'] * cfg['hidden'] * seq * tokens_per_step)
    sps_chip = batch / dt / len(devices)
    _log(f"params={P / 1e6:.1f}M (matmul-active body={P_body / 1e6:.1f}M "
         f"head={P_head / 1e6:.1f}M embed={P_embed / 1e6:.1f}M) "
         f"step={dt * 1000:.1f}ms samples/sec/chip={sps_chip:.2f}")

    if on_accel:
        peak = _peak_flops(devices[0])
        mfu = flops / dt / (peak * len(devices)) * 100.0
        out = {
            "metric": "bert_base_pretrain_mfu",
            "value": round(mfu, 2),
            "unit": "% MFU",
            "vs_baseline": round(mfu / 35.0, 3),
            "backend": devices[0].platform,
            "device_kind": getattr(devices[0], 'device_kind', '?'),
            "samples_per_sec_per_chip": round(sps_chip, 2),
            "step_ms": round(dt * 1000, 1),
            "batch": batch, "seq": seq, "dtype": dtype, "masked": True,
            "mlm_positions": int(nmask),
            "flop_accounting": "honest: embeddings excluded, MLM head "
                               "counted on masked positions only",
            "attn_route": route,
            "peak_flops_assumed": peak,
        }
        # the flagship metric is safe from here on: print it NOW, then
        # enrich with the optional reports and print a final line — the
        # parent takes the LAST parseable JSON line, and on a child
        # timeout it salvages this one from partial stdout
        print(json.dumps(out), flush=True)
        try:
            out["pallas"] = _pallas_report(batch)
            _log(f"pallas report: {out['pallas']}")
        except Exception as e:  # flagship number still lands
            out["pallas"] = {"error": repr(e)[:300]}
            _log(f"pallas report failed: {e!r}")
        # checkpoint the enriched line: if the resnet report overruns the
        # child timeout, the salvaged line still carries the pallas data
        print(json.dumps(out), flush=True)
        deadline = float(os.environ.get('BENCH_CHILD_DEADLINE', '0'))
        if deadline and time.time() > deadline - 180:
            out["resnet50"] = {"skipped": "child deadline too close"}
            _log("resnet50 report skipped: deadline")
        else:
            try:
                out["resnet50"] = _resnet_report()
                _log(f"resnet50 report: {out['resnet50']}")
            except Exception as e:
                out["resnet50"] = {"error": repr(e)[:300]}
                _log(f"resnet50 report failed: {e!r}")
        print(json.dumps(out), flush=True)
        try:
            out["io"] = _io_report()
            _log(f"io report: {out['io']}")
        except Exception as e:
            out["io"] = {"error": repr(e)[:300]}
            _log(f"io report failed: {e!r}")
    else:
        out = {
            "metric": "bert_smoke_samples_per_sec_per_chip",
            "value": round(sps_chip, 2),
            "unit": "samples/sec/chip",
            "vs_baseline": 0.0,
            "backend": "cpu",
            "samples_per_sec_per_chip": round(sps_chip, 2),
            "step_ms": round(dt * 1000, 1),
            "batch": batch, "seq": seq, "dtype": dtype, "masked": True,
            "note": "cpu smoke scale (tiny config) — not an MFU measurement",
        }
        # the IO pipeline is host-side: a wedged-tunnel round still
        # produces a real decode+augment throughput number
        print(json.dumps(out), flush=True)
        try:
            out["io"] = _io_report()
            _log(f"io report: {out['io']}")
        except Exception as e:
            out["io"] = {"error": repr(e)[:300]}
            _log(f"io report failed: {e!r}")
    # ZeRO memory trajectory (ISSUE 7): stage + bytes/device + gather
    # wire bytes on the live step, with an 8-device probe when the live
    # mesh is single-device
    try:
        out["zero"] = _zero_report(step)
        _log(f"zero report: {out['zero']}")
    except Exception as e:
        out["zero"] = {"error": repr(e)[:300]}
        _log(f"zero report failed: {e!r}")
    print(json.dumps(out), flush=True)
    # memory watermark + bucket attribution (ISSUE 14): the memory half
    # of the trajectory every BENCH round pins
    try:
        out["memory"] = _memory_report(
            step, lambda: float(step(inputs, [labels, nsp]).asnumpy()))
        _log(f"memory report: {out['memory']}")
    except Exception as e:
        out["memory"] = {"error": repr(e)[:300]}
        _log(f"memory report failed: {e!r}")
    print(json.dumps(out), flush=True)
    # attribution LAST: with MXTPU_TRACE=1 the whole child traced from
    # import, so the dumped timeline also carries the io report's spans
    try:
        peak_total = _peak_flops(devices[0]) * len(devices) if on_accel \
            else None
        out["attribution"] = _attribution_report(
            step, model,
            lambda: float(step(inputs, [labels, nsp]).asnumpy()),
            flops, peak_total)
        _log(f"attribution: {out['attribution']}")
    except Exception as e:
        out["attribution"] = {"error": repr(e)[:300]}
        _log(f"attribution report failed: {e!r}")
    print(json.dumps(out), flush=True)
    # fleet observability overhead A/B (ISSUE 13): endpoint armed +
    # scraped vs everything disarmed, on the same compiled step
    try:
        out["fleet"] = _fleet_report(
            lambda: float(step(inputs, [labels, nsp]).asnumpy()))
        _log(f"fleet report: {out['fleet']}")
    except Exception as e:
        out["fleet"] = {"error": repr(e)[:300]}
        _log(f"fleet report failed: {e!r}")
    print(json.dumps(out), flush=True)
    # compile observability (ISSUE 16): per-site compile seconds + the
    # cold-vs-warm persistent-cache A/B across two probe processes
    try:
        out["compile"] = _compile_report()
        _log(f"compile report: {out['compile']}")
    except Exception as e:
        out["compile"] = {"error": repr(e)[:300]}
        _log(f"compile report failed: {e!r}")
    print(json.dumps(out), flush=True)
    # inference serving (ISSUE 17): predict QPS + p50/p99 vs the batch
    # deadline, int8 A/B, and the two-replica failover drill
    try:
        out["serving"] = _serving_report()
        _log(f"serving report: {out['serving']}")
    except Exception as e:
        out["serving"] = {"error": repr(e)[:300]}
        _log(f"serving report failed: {e!r}")
    print(json.dumps(out), flush=True)
    # kernel autotuning (ISSUE 18): the flash-attention block sweep +
    # the DB-consumption round trip _block_sizes proves per process
    try:
        out["autotune"] = _autotune_report()
        _log(f"autotune report: {out['autotune']}")
    except Exception as e:
        out["autotune"] = {"error": repr(e)[:300]}
        _log(f"autotune report failed: {e!r}")
    print(json.dumps(out), flush=True)
    # sparse embeddings (ISSUE 19): update-bytes + step-time shrink of
    # the RowSparse fast path across hot-fraction sweeps
    try:
        out["sparse"] = _sparse_report()
        _log(f"sparse report: {out['sparse']}")
    except Exception as e:
        out["sparse"] = {"error": repr(e)[:300]}
        _log(f"sparse report failed: {e!r}")
    print(json.dumps(out), flush=True)


# ---------------------------------------------------------------------------
# parent: orchestration with timeouts; always emits one JSON line
# ---------------------------------------------------------------------------

def _run_child(mode: str, timeout: float):
    """Returns (json_dict | None, error_str | None)."""
    cmd = [sys.executable, os.path.abspath(__file__), '--child', mode]
    env = dict(os.environ,
               BENCH_CHILD_DEADLINE=str(time.time() + timeout))
    try:
        res = subprocess.run(cmd, capture_output=True, text=True,
                             timeout=timeout, env=env)
    except subprocess.TimeoutExpired as te:
        # the child prints the flagship JSON before optional reports —
        # salvage it from partial stdout if the extras overran
        partial = te.stdout or b''
        if isinstance(partial, bytes):
            partial = partial.decode(errors='replace')
        for line in reversed(partial.strip().splitlines()):
            line = line.strip()
            if line.startswith('{'):
                try:
                    d = json.loads(line)
                    d['note_timeout'] = (f"optional reports cut off at "
                                         f"{timeout:.0f}s (mode={mode})")
                    return d, None
                except json.JSONDecodeError:
                    continue
        return None, f"timeout after {timeout:.0f}s (mode={mode})"
    sys.stderr.write(res.stderr[-4000:])
    if res.returncode != 0:
        tail = (res.stderr or '').strip().splitlines()[-3:]
        return None, f"rc={res.returncode} (mode={mode}): " + ' | '.join(tail)
    for line in reversed((res.stdout or '').strip().splitlines()):
        line = line.strip()
        if line.startswith('{'):
            try:
                return json.loads(line), None
            except json.JSONDecodeError:
                continue
    return None, f"no JSON line in child output (mode={mode})"


def main():
    if len(sys.argv) >= 2 and sys.argv[1] == '--zero-probe':
        _zero_probe_child()
        return
    if len(sys.argv) >= 2 and sys.argv[1] == '--compile-probe':
        _compile_probe_child()
        return
    if len(sys.argv) >= 3 and sys.argv[1] == '--child':
        if sys.argv[2] == 'probe':
            _probe()
        else:
            _child(sys.argv[2])
        return

    # Probe state rides in the separate "probe" field of the final JSON —
    # NEVER in top-level "error": a wedged-tunnel probe timeout on an
    # otherwise-valid CPU smoke line previously leaked as "error" and
    # dirtied the parsed metric (BENCH_r05). One retry with backoff
    # covers the transient tunnel hiccup case.
    errors = []   # measurement-child failures only
    probe, perr = None, None
    attempts_made = 0
    for attempt in range(2):
        attempts_made = attempt + 1
        _log(f"probe attempt {attempts_made}: backend liveness (<=60s)")
        probe, perr = _run_child('probe', 60.0)
        if probe is not None:
            _log(f"probe: {probe}")
            break
        _log(f"probe failed: {perr}")
        if attempt == 0:
            _log("probe retry in 10s (tunnel may be transiently wedged)")
            time.sleep(10.0)
    probe_info = dict(probe) if probe is not None else {}
    probe_info['state'] = 'ok' if probe is not None else 'wedged'
    probe_info['attempts'] = attempts_made
    if probe is None:
        probe_info['error'] = perr
    accel_alive = probe is not None and probe.get('platform') != 'cpu'

    attempts = []
    if accel_alive:
        attempts.append(('auto', 540.0))
    attempts.append(('cpu', 240.0))

    for mode, timeout in attempts:
        _log(f"attempt mode={mode} timeout={timeout:.0f}s")
        out, err = _run_child(mode, timeout)
        if out is not None:
            out['probe'] = probe_info
            if errors:
                # earlier measurement-child failures (e.g. the accel
                # child timing out on a wedged tunnel before the CPU
                # smoke succeeded) are tunnel/attempt state, NOT an
                # error of THIS valid metric line — the PR 4 contract
                # (BENCH_r05 leak) says top-level "error" appears only
                # when no metric was produced at all
                out['attempts_failed'] = list(errors)
            print(json.dumps(out), flush=True)
            return
        errors.append(err)
        _log(f"attempt failed: {err}")

    print(json.dumps({
        "metric": "bert_base_pretrain_mfu",
        "value": 0.0,
        "unit": "% MFU",
        "vs_baseline": 0.0,
        "backend": "none",
        "probe": probe_info,
        "error": '; '.join(errors),
    }), flush=True)


if __name__ == '__main__':
    main()
