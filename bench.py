"""Benchmark: ResNet-50 training throughput (images/sec/chip).

Reference baseline: 109 images/sec training ResNet-50, batch 32, 1x K80
(example/image-classification/README.md:154). vs_baseline = ours / 109.

The whole train step (fwd+bwd+SGD update) is one compiled XLA program via
ShardedTrainStep — the framework's hot path. Prints ONE JSON line.
"""
from __future__ import annotations

import json
import sys
import time

import numpy as onp


def main():
    import jax

    on_accel = any(d.platform != 'cpu' for d in jax.devices())
    import mxnet_tpu as mx
    from mxnet_tpu import nd, gluon
    from mxnet_tpu.gluon.model_zoo.vision import resnet50_v1
    from mxnet_tpu.parallel import make_mesh, ShardedTrainStep

    if on_accel:
        batch, img, steps, warmup = 64, 224, 10, 3
        devices = [d for d in jax.devices() if d.platform != 'cpu']
    else:
        # smoke-scale on CPU so the script stays runnable anywhere
        batch, img, steps, warmup = 8, 64, 3, 1
        devices = jax.devices()

    mesh = make_mesh((len(devices),), ('dp',), devices=devices)

    net = resnet50_v1(classes=1000)
    net.initialize(mx.init.Xavier())
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    step = ShardedTrainStep(net, loss_fn, 'sgd',
                            {'learning_rate': 0.1, 'momentum': 0.9},
                            mesh=mesh)

    rng = onp.random.RandomState(0)
    x = nd.array(rng.rand(batch, 3, img, img).astype(onp.float32))
    y = nd.array(rng.randint(0, 1000, batch).astype(onp.float32))

    for _ in range(warmup):
        # host read forces execution: block_until_ready alone does not
        # drain tunneled/async backends
        float(step(x, y).asnumpy())
    t0 = time.time()
    for _ in range(steps):
        loss = step(x, y)
    float(loss.asnumpy())  # syncs the whole dependency chain
    dt = time.time() - t0

    ips = batch * steps / dt
    ips_per_chip = ips / len(devices)
    baseline = 109.0  # reference resnet-50 images/sec (1x K80, batch 32)
    print(json.dumps({
        "metric": "resnet50_train_images_per_sec_per_chip",
        "value": round(ips_per_chip, 2),
        "unit": "images/sec/chip",
        "vs_baseline": round(ips_per_chip / baseline, 3),
    }))


if __name__ == '__main__':
    main()
