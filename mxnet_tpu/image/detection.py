"""Detection-specific image iterator + augmenters
(ref: python/mxnet/image/detection.py).

Labels follow the reference's packed format: per-image label =
[header_width, object_width, (extra header...), obj0, obj1, ...] where each
object is [class_id, xmin, ymin, xmax, ymax, ...] with coordinates
normalized to [0, 1].
"""
from __future__ import annotations

import random as pyrandom

import numpy as onp

from ..ndarray.ndarray import NDArray, array as _nd_array
from .image import (Augmenter, HorizontalFlipAug, ImageIter, _to_np,
                    fixed_crop, imresize)

__all__ = ['DetAugmenter', 'DetBorrowAug', 'DetRandomSelectAug',
           'DetHorizontalFlipAug', 'DetRandomCropAug', 'DetRandomPadAug',
           'CreateDetAugmenter', 'ImageDetIter']


class DetAugmenter:
    """Detection augmenter: __call__(src, label) -> (src, label)
    (ref: detection.py DetAugmenter)."""

    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def __call__(self, src, label):
        raise NotImplementedError


class DetBorrowAug(DetAugmenter):
    """Wrap an image-only Augmenter for detection (ref: DetBorrowAug)."""

    def __init__(self, augmenter):
        super().__init__(augmenter=augmenter.__class__.__name__)
        self.augmenter = augmenter

    def __call__(self, src, label):
        return self.augmenter(src), label


class DetRandomSelectAug(DetAugmenter):
    """Randomly select one augmenter to apply (ref: DetRandomSelectAug)."""

    def __init__(self, aug_list, skip_prob=0.0):
        super().__init__(skip_prob=skip_prob)
        self.aug_list = aug_list
        self.skip_prob = skip_prob

    def __call__(self, src, label):
        if pyrandom.random() < self.skip_prob or not self.aug_list:
            return src, label
        return pyrandom.choice(self.aug_list)(src, label)


class DetHorizontalFlipAug(DetAugmenter):
    """Flip image and mirror box x-coords (ref: DetHorizontalFlipAug)."""

    def __init__(self, p):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src, label):
        if pyrandom.random() < self.p:
            src = _nd_array(onp.ascontiguousarray(_to_np(src)[:, ::-1]))
            label = label.copy()
            tmp = 1.0 - label[:, 1]
            label[:, 1] = 1.0 - label[:, 3]
            label[:, 3] = tmp
        return src, label


class DetRandomCropAug(DetAugmenter):
    """SSD-style random crop constrained by min IOU with objects
    (ref: DetRandomCropAug)."""

    def __init__(self, min_object_covered=0.1, aspect_ratio_range=(0.75, 1.33),
                 area_range=(0.05, 1.0), min_eject_coverage=0.3,
                 max_attempts=50):
        super().__init__()
        self.min_object_covered = min_object_covered
        self.aspect_ratio_range = aspect_ratio_range
        self.area_range = area_range
        self.min_eject_coverage = min_eject_coverage
        self.max_attempts = max_attempts

    def __call__(self, src, label):
        arr = _to_np(src)
        h, w = arr.shape[:2]
        for _ in range(self.max_attempts):
            ratio = pyrandom.uniform(*self.aspect_ratio_range)
            area = pyrandom.uniform(*self.area_range)
            cw = min(1.0, onp.sqrt(area * ratio))
            ch = min(1.0, onp.sqrt(area / ratio))
            x0 = pyrandom.uniform(0, 1 - cw)
            y0 = pyrandom.uniform(0, 1 - ch)
            crop = onp.array([x0, y0, x0 + cw, y0 + ch])
            if label.shape[0]:
                # acceptance gate: every object the crop intersects must be
                # covered at least min_object_covered (reference semantics)
                ix = onp.maximum(0, onp.minimum(crop[2], label[:, 3])
                                 - onp.maximum(crop[0], label[:, 1]))
                iy = onp.maximum(0, onp.minimum(crop[3], label[:, 4])
                                 - onp.maximum(crop[1], label[:, 2]))
                obj_area = onp.maximum(
                    (label[:, 3] - label[:, 1]) * (label[:, 4] - label[:, 2]),
                    1e-12)
                coverage = (ix * iy) / obj_area
                touched = coverage > 0
                if not touched.any():
                    continue
                if coverage[touched].min() < self.min_object_covered:
                    continue
            new_label = self._update_labels(label, crop)
            if label.shape[0] and new_label.shape[0] == 0:
                continue
            px0, py0 = int(x0 * w), int(y0 * h)
            pw, ph = max(1, int(cw * w)), max(1, int(ch * h))
            out = fixed_crop(arr, px0, py0, pw, ph)
            return out, new_label
        return (src if isinstance(src, NDArray) else _nd_array(arr)), label

    def _update_labels(self, label, crop):
        if label.shape[0] == 0:
            return label
        x0, y0, x1, y1 = crop
        cw, ch = x1 - x0, y1 - y0
        out = label.copy()
        # clip boxes to crop, re-normalize to crop frame
        out[:, 1] = onp.clip((label[:, 1] - x0) / cw, 0, 1)
        out[:, 2] = onp.clip((label[:, 2] - y0) / ch, 0, 1)
        out[:, 3] = onp.clip((label[:, 3] - x0) / cw, 0, 1)
        out[:, 4] = onp.clip((label[:, 4] - y0) / ch, 0, 1)
        # eject boxes whose visible area in the crop is too small
        orig_area = onp.maximum(
            (label[:, 3] - label[:, 1]) * (label[:, 4] - label[:, 2]), 1e-12)
        new_area = (out[:, 3] - out[:, 1]) * (out[:, 4] - out[:, 2]) * cw * ch
        keep = (new_area / orig_area) >= self.min_eject_coverage
        keep &= (out[:, 3] > out[:, 1]) & (out[:, 4] > out[:, 2])
        return out[keep]


class DetRandomPadAug(DetAugmenter):
    """Random expand/pad with fill value, shrinking boxes
    (ref: DetRandomPadAug)."""

    def __init__(self, aspect_ratio_range=(0.75, 1.33), area_range=(1.0, 3.0),
                 max_attempts=50, pad_val=(127, 127, 127)):
        super().__init__()
        self.aspect_ratio_range = aspect_ratio_range
        self.area_range = area_range
        self.max_attempts = max_attempts
        self.pad_val = pad_val

    def __call__(self, src, label):
        arr = _to_np(src)
        h, w = arr.shape[:2]
        for _ in range(self.max_attempts):
            ratio = pyrandom.uniform(*self.aspect_ratio_range)
            area = pyrandom.uniform(*self.area_range)
            if area < 1.0:
                continue
            nw = int(w * onp.sqrt(area * ratio))
            nh = int(h * onp.sqrt(area / ratio))
            if nw < w or nh < h:
                continue
            x0 = pyrandom.randint(0, nw - w)
            y0 = pyrandom.randint(0, nh - h)
            out = onp.empty((nh, nw, arr.shape[2]), arr.dtype)
            out[...] = onp.asarray(self.pad_val, arr.dtype)[:arr.shape[2]]
            out[y0:y0 + h, x0:x0 + w] = arr
            new_label = label.copy()
            if label.shape[0]:
                new_label[:, 1] = (label[:, 1] * w + x0) / nw
                new_label[:, 2] = (label[:, 2] * h + y0) / nh
                new_label[:, 3] = (label[:, 3] * w + x0) / nw
                new_label[:, 4] = (label[:, 4] * h + y0) / nh
            return _nd_array(out), new_label
        return (src if isinstance(src, NDArray) else _nd_array(arr)), label


def CreateDetAugmenter(data_shape, resize=0, rand_crop=0, rand_pad=0,
                       rand_gray=0, rand_mirror=False, mean=None, std=None,
                       brightness=0, contrast=0, saturation=0, pca_noise=0,
                       hue=0, inter_method=2, min_object_covered=0.1,
                       aspect_ratio_range=(0.75, 1.33),
                       area_range=(0.05, 3.0), min_eject_coverage=0.3,
                       max_attempts=50, pad_val=(127, 127, 127)):
    """Build the standard detection augmenter list
    (ref: detection.py CreateDetAugmenter)."""
    from .image import (BrightnessJitterAug, CastAug, ColorJitterAug,
                        ColorNormalizeAug, ForceResizeAug, HueJitterAug,
                        LightingAug, RandomGrayAug, ResizeAug)
    auglist = []
    if resize > 0:
        auglist.append(DetBorrowAug(ResizeAug(resize, inter_method)))
    if rand_crop > 0:
        crop_augs = [DetRandomCropAug(min_object_covered, aspect_ratio_range,
                                      (area_range[0], min(1.0, area_range[1])),
                                      min_eject_coverage, max_attempts)]
        auglist.append(DetRandomSelectAug(crop_augs, 1 - rand_crop))
    if rand_pad > 0:
        pad_aug = DetRandomPadAug(aspect_ratio_range,
                                  (1.0, max(1.0, area_range[1])),
                                  max_attempts, pad_val)
        auglist.append(DetRandomSelectAug([pad_aug], 1 - rand_pad))
    if rand_mirror:
        auglist.append(DetHorizontalFlipAug(0.5))
    auglist.append(DetBorrowAug(ForceResizeAug(
        (data_shape[2], data_shape[1]), inter_method)))
    auglist.append(DetBorrowAug(CastAug()))
    if brightness or contrast or saturation:
        auglist.append(DetBorrowAug(
            ColorJitterAug(brightness, contrast, saturation)))
    if hue:
        auglist.append(DetBorrowAug(HueJitterAug(hue)))
    if pca_noise > 0:
        eigval = onp.array([55.46, 4.794, 1.148])
        eigvec = onp.array([[-0.5675, 0.7192, 0.4009],
                            [-0.5808, -0.0045, -0.8140],
                            [-0.5836, -0.6948, 0.4203]])
        auglist.append(DetBorrowAug(LightingAug(pca_noise, eigval, eigvec)))
    if rand_gray > 0:
        auglist.append(DetBorrowAug(RandomGrayAug(rand_gray)))
    if mean is True:
        mean = onp.array([123.68, 116.28, 103.53])
    if std is True:
        std = onp.array([58.395, 57.12, 57.375])
    if mean is not None or std is not None:
        auglist.append(DetBorrowAug(ColorNormalizeAug(mean, std)))
    return auglist


class ImageDetIter(ImageIter):
    """Detection iterator: yields (NCHW data, padded [B, max_objs, obj_width]
    labels) (ref: detection.py ImageDetIter)."""

    def __init__(self, batch_size, data_shape, path_imgrec=None,
                 path_imglist=None, path_root='', path_imgidx=None,
                 shuffle=False, part_index=0, num_parts=1, aug_list=None,
                 imglist=None, object_width=5, max_objects=50,
                 dtype='float32', last_batch_handle='pad', **kwargs):
        aug_keys = ('resize', 'rand_crop', 'rand_pad', 'rand_gray',
                    'rand_mirror', 'mean', 'std', 'brightness', 'contrast',
                    'saturation', 'pca_noise', 'hue', 'inter_method',
                    'min_object_covered', 'aspect_ratio_range', 'area_range',
                    'min_eject_coverage', 'max_attempts', 'pad_val')
        unknown = set(kwargs) - set(aug_keys)
        if unknown:
            raise TypeError(
                f"ImageDetIter got unknown kwargs: {sorted(unknown)}")
        if aug_list is None:
            aug_list = CreateDetAugmenter(data_shape, **kwargs)
        self.object_width = object_width
        self.max_objects = max_objects
        super().__init__(batch_size, data_shape, label_width=1,
                         path_imgrec=path_imgrec, path_imglist=path_imglist,
                         path_root=path_root, path_imgidx=path_imgidx,
                         shuffle=shuffle, part_index=part_index,
                         num_parts=num_parts, aug_list=aug_list,
                         imglist=imglist, dtype=dtype,
                         last_batch_handle=last_batch_handle)
        from ..io.io import DataDesc
        self.provide_label = [DataDesc(
            'label', (batch_size, max_objects, object_width), onp.float32)]

    def _parse_label(self, label):
        """Decode the packed header format into an [N, object_width] array
        (ref: detection.py ImageDetIter._parse_label)."""
        raw = onp.asarray(label, onp.float32).reshape(-1)
        if raw.size < 2:
            return onp.zeros((0, self.object_width), onp.float32)
        header_width = int(raw[0])
        obj_width = int(raw[1])
        objs = raw[header_width:]
        n = objs.size // obj_width
        objs = objs[:n * obj_width].reshape(n, obj_width)
        return objs[:, :self.object_width].astype(onp.float32)

    def next(self):
        from ..io.io import DataBatch
        c, h, w = self.data_shape
        batch_data = onp.zeros((self.batch_size, c, h, w), self.dtype)
        batch_label = onp.full(
            (self.batch_size, self.max_objects, self.object_width), -1.0,
            onp.float32)
        i = 0
        try:
            while i < self.batch_size:
                label, img = self.next_sample()
                objs = self._parse_label(label)
                for aug in self.auglist:
                    img, objs = aug(img, objs)
                arr = _to_np(img)
                if arr.shape[:2] != (h, w):
                    raise ValueError(
                        f"augmented image shape {arr.shape[:2]} != "
                        f"data_shape {(h, w)}")
                batch_data[i] = arr.astype(self.dtype).transpose(2, 0, 1)
                n = min(objs.shape[0], self.max_objects)
                batch_label[i, :n] = objs[:n]
                i += 1
        except StopIteration:
            if i == 0 or self.last_batch_handle == 'discard':
                raise
        pad = self.batch_size - i
        return DataBatch(data=[_nd_array(batch_data)],
                         label=[_nd_array(batch_label)], pad=pad)
