"""Image IO + augmentation pipeline (ref: python/mxnet/image/image.py).

The reference decodes/augments on the host with OpenCV and feeds NHWC uint8
NDArrays; device copy overlaps compute via the engine. Here decode/augment is
host-side too (PIL + numpy — augmentation is branchy, per-image, and
shape-changing, exactly what should NOT go through XLA), and the batched
output lands on device as one contiguous array per batch, which jax
dispatches asynchronously — same overlap, no dependency engine needed.

Augmenter classes keep the reference's names and call signature
(`aug(src) -> NDArray` with HWC float32 data).
"""
from __future__ import annotations

import io as _pyio
import logging
import os
import random as pyrandom

import numpy as onp

from ..ndarray.ndarray import NDArray, array as _nd_array

__all__ = [
    'imread', 'imdecode', 'imresize', 'scale_down', 'resize_short',
    'fixed_crop', 'random_crop', 'center_crop', 'random_size_crop',
    'color_normalize',
    'Augmenter', 'SequentialAug', 'RandomOrderAug', 'CastAug', 'ResizeAug',
    'ForceResizeAug', 'RandomCropAug', 'RandomSizedCropAug', 'CenterCropAug',
    'BrightnessJitterAug', 'ContrastJitterAug', 'SaturationJitterAug',
    'HueJitterAug', 'ColorJitterAug', 'LightingAug', 'ColorNormalizeAug',
    'RandomGrayAug', 'HorizontalFlipAug', 'CreateAugmenter', 'ImageIter',
]


def _to_np(img):
    if isinstance(img, NDArray):
        return img.asnumpy()
    return onp.asarray(img)


def imdecode(buf, flag=1, to_rgb=True, **kwargs):
    """Decode an image byte buffer to an HWC NDArray
    (ref: python/mxnet/image/image.py imdecode; decode backend is PIL
    instead of OpenCV)."""
    from PIL import Image
    if isinstance(buf, NDArray):
        buf = buf.asnumpy().tobytes()
    img = Image.open(_pyio.BytesIO(bytes(buf)))
    if flag == 0:
        img = img.convert('L')
        arr = onp.asarray(img)[:, :, None]
    else:
        img = img.convert('RGB')
        arr = onp.asarray(img)
        if not to_rgb:
            arr = arr[:, :, ::-1]
    return _nd_array(onp.ascontiguousarray(arr))


def imread(filename, flag=1, to_rgb=True, **kwargs):
    """Read an image file into an HWC NDArray (ref: image.py imread)."""
    with open(filename, 'rb') as f:
        return imdecode(f.read(), flag=flag, to_rgb=to_rgb)


def imresize(src, w, h, interp=1):
    """Resize to (w, h), preserving dtype (ref: image.py imresize)."""
    arr = _to_np(src)
    if arr.dtype == onp.uint8:
        from PIL import Image
        squeeze = arr.shape[2] == 1
        mode_arr = arr[:, :, 0] if squeeze else arr
        resample = {0: Image.NEAREST, 1: Image.BILINEAR, 2: Image.BICUBIC,
                    3: Image.NEAREST, 4: Image.LANCZOS}.get(
                        interp, Image.BILINEAR)
        out = onp.asarray(Image.fromarray(mode_arr).resize((w, h), resample))
        if squeeze:
            out = out[:, :, None]
        return _nd_array(out)
    # float data: interpolate without quantizing (reference cv2.resize
    # keeps dtype)
    import jax.image
    method = {0: 'nearest', 1: 'bilinear', 2: 'bicubic',
              3: 'nearest', 4: 'lanczos5'}.get(interp, 'bilinear')
    out = jax.image.resize(arr.astype(onp.float32),
                           (h, w, arr.shape[2]), method=method)
    return _nd_array(onp.asarray(out).astype(arr.dtype, copy=False))


def scale_down(src_size, size):
    """Scale target size down so a crop fits inside src (ref: scale_down)."""
    w, h = size
    sw, sh = src_size
    if sh < h:
        w, h = float(w * sh) / h, sh
    if sw < w:
        w, h = sw, float(h * sw) / w
    return int(w), int(h)


def resize_short(src, size, interp=2):
    """Resize so the shorter edge == size, keeping aspect (ref: resize_short)."""
    arr = _to_np(src)
    h, w = arr.shape[:2]
    if h > w:
        new_w, new_h = size, int(h * size / w)
    else:
        new_w, new_h = int(w * size / h), size
    return imresize(arr, new_w, new_h, interp)


def fixed_crop(src, x0, y0, w, h, size=None, interp=2):
    """Crop at (x0, y0, w, h), optionally resizing to `size` (ref: fixed_crop)."""
    arr = _to_np(src)
    out = arr[y0:y0 + h, x0:x0 + w]
    if size is not None and (w, h) != size:
        return imresize(out, size[0], size[1], interp)
    return _nd_array(onp.ascontiguousarray(out))


def random_crop(src, size, interp=2):
    """Random crop of `size`, scaled down to fit (ref: random_crop)."""
    arr = _to_np(src)
    h, w = arr.shape[:2]
    new_w, new_h = scale_down((w, h), size)
    x0 = pyrandom.randint(0, w - new_w)
    y0 = pyrandom.randint(0, h - new_h)
    out = fixed_crop(arr, x0, y0, new_w, new_h, size, interp)
    return out, (x0, y0, new_w, new_h)


def center_crop(src, size, interp=2):
    """Center crop of `size` (ref: center_crop)."""
    arr = _to_np(src)
    h, w = arr.shape[:2]
    new_w, new_h = scale_down((w, h), size)
    x0 = (w - new_w) // 2
    y0 = (h - new_h) // 2
    out = fixed_crop(arr, x0, y0, new_w, new_h, size, interp)
    return out, (x0, y0, new_w, new_h)


def random_size_crop(src, size, area, ratio, interp=2, **kwargs):
    """Random crop with area/aspect jitter, as in Inception training
    (ref: random_size_crop)."""
    arr = _to_np(src)
    h, w = arr.shape[:2]
    src_area = h * w
    if 'min_area' in kwargs:
        area = (kwargs.pop('min_area'), 1.0)
    if not isinstance(area, (tuple, list)):
        area = (area, 1.0)

    for _ in range(10):
        target_area = pyrandom.uniform(area[0], area[1]) * src_area
        log_ratio = (onp.log(ratio[0]), onp.log(ratio[1]))
        new_ratio = onp.exp(pyrandom.uniform(*log_ratio))
        new_w = int(round(onp.sqrt(target_area * new_ratio)))
        new_h = int(round(onp.sqrt(target_area / new_ratio)))
        if new_w <= w and new_h <= h:
            x0 = pyrandom.randint(0, w - new_w)
            y0 = pyrandom.randint(0, h - new_h)
            out = fixed_crop(arr, x0, y0, new_w, new_h, size, interp)
            return out, (x0, y0, new_w, new_h)
    return center_crop(arr, size, interp)


def color_normalize(src, mean, std=None):
    """(src - mean) / std on HWC float data (ref: color_normalize)."""
    arr = _to_np(src).astype(onp.float32)
    mean = _to_np(mean) if mean is not None else None
    std = _to_np(std) if std is not None else None
    if mean is not None:
        arr = arr - mean
    if std is not None:
        arr = arr / std
    return _nd_array(arr)


class Augmenter:
    """Image augmenter base (ref: image.py Augmenter)."""

    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def dumps(self):
        import json
        return json.dumps([self.__class__.__name__.lower(), self._kwargs])

    def __call__(self, src):
        raise NotImplementedError


class SequentialAug(Augmenter):
    def __init__(self, ts):
        super().__init__()
        self.ts = ts

    def __call__(self, src):
        for t in self.ts:
            src = t(src)
        return src


class RandomOrderAug(Augmenter):
    def __init__(self, ts):
        super().__init__()
        self.ts = ts

    def __call__(self, src):
        ts = list(self.ts)
        pyrandom.shuffle(ts)
        for t in ts:
            src = t(src)
        return src


class CastAug(Augmenter):
    def __init__(self, typ='float32'):
        super().__init__(type=typ)
        self.typ = typ

    def __call__(self, src):
        return _nd_array(_to_np(src).astype(self.typ))


class ResizeAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size = size
        self.interp = interp

    def __call__(self, src):
        return resize_short(src, self.size, self.interp)


class ForceResizeAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size = size
        self.interp = interp

    def __call__(self, src):
        return imresize(src, self.size[0], self.size[1], self.interp)


class RandomCropAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size = size
        self.interp = interp

    def __call__(self, src):
        return random_crop(src, self.size, self.interp)[0]


class RandomSizedCropAug(Augmenter):
    def __init__(self, size, area, ratio, interp=2, **kwargs):
        super().__init__(size=size, area=area, ratio=ratio, interp=interp)
        self.size = size
        self.area = area
        self.ratio = ratio
        self.interp = interp

    def __call__(self, src):
        return random_size_crop(src, self.size, self.area, self.ratio,
                                self.interp)[0]


class CenterCropAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size = size
        self.interp = interp

    def __call__(self, src):
        return center_crop(src, self.size, self.interp)[0]


class BrightnessJitterAug(Augmenter):
    def __init__(self, brightness):
        super().__init__(brightness=brightness)
        self.brightness = brightness

    def __call__(self, src):
        alpha = 1.0 + pyrandom.uniform(-self.brightness, self.brightness)
        return _nd_array(_to_np(src).astype(onp.float32) * alpha)


class ContrastJitterAug(Augmenter):
    _coef = onp.array([[[0.299, 0.587, 0.114]]], onp.float32)

    def __init__(self, contrast):
        super().__init__(contrast=contrast)
        self.contrast = contrast

    def __call__(self, src):
        arr = _to_np(src).astype(onp.float32)
        alpha = 1.0 + pyrandom.uniform(-self.contrast, self.contrast)
        gray = (arr * self._coef[..., :arr.shape[2]]).sum() * (
            3.0 / arr.size)
        return _nd_array(arr * alpha + gray * (1.0 - alpha))


class SaturationJitterAug(Augmenter):
    _coef = onp.array([[[0.299, 0.587, 0.114]]], onp.float32)

    def __init__(self, saturation):
        super().__init__(saturation=saturation)
        self.saturation = saturation

    def __call__(self, src):
        arr = _to_np(src).astype(onp.float32)
        alpha = 1.0 + pyrandom.uniform(-self.saturation, self.saturation)
        gray = (arr * self._coef).sum(axis=2, keepdims=True)
        return _nd_array(arr * alpha + gray * (1.0 - alpha))


class HueJitterAug(Augmenter):
    """Hue jitter in YIQ space (ref: image.py HueJitterAug)."""
    _tyiq = onp.array([[0.299, 0.587, 0.114],
                       [0.596, -0.274, -0.321],
                       [0.211, -0.523, 0.311]], onp.float32)
    _ityiq = onp.array([[1.0, 0.956, 0.621],
                        [1.0, -0.272, -0.647],
                        [1.0, -1.107, 1.705]], onp.float32)

    def __init__(self, hue):
        super().__init__(hue=hue)
        self.hue = hue

    def __call__(self, src):
        arr = _to_np(src).astype(onp.float32)
        alpha = pyrandom.uniform(-self.hue, self.hue)
        u = onp.cos(alpha * onp.pi)
        w = onp.sin(alpha * onp.pi)
        bt = onp.array([[1.0, 0.0, 0.0],
                        [0.0, u, -w],
                        [0.0, w, u]], onp.float32)
        t = onp.dot(onp.dot(self._ityiq, bt), self._tyiq).T
        return _nd_array(onp.dot(arr, t))


class ColorJitterAug(RandomOrderAug):
    def __init__(self, brightness, contrast, saturation):
        ts = []
        if brightness > 0:
            ts.append(BrightnessJitterAug(brightness))
        if contrast > 0:
            ts.append(ContrastJitterAug(contrast))
        if saturation > 0:
            ts.append(SaturationJitterAug(saturation))
        super().__init__(ts)


class LightingAug(Augmenter):
    """PCA-based lighting jitter (AlexNet-style) (ref: LightingAug)."""

    def __init__(self, alphastd, eigval, eigvec):
        super().__init__(alphastd=alphastd)
        self.alphastd = alphastd
        self.eigval = _to_np(eigval)
        self.eigvec = _to_np(eigvec)

    def __call__(self, src):
        arr = _to_np(src).astype(onp.float32)
        alpha = onp.random.normal(0, self.alphastd, size=(3,))
        rgb = onp.dot(self.eigvec * alpha, self.eigval)
        return _nd_array(arr + rgb)


class ColorNormalizeAug(Augmenter):
    def __init__(self, mean, std):
        super().__init__()
        self.mean = _to_np(mean) if mean is not None else None
        self.std = _to_np(std) if std is not None else None

    def __call__(self, src):
        return color_normalize(src, self.mean, self.std)


class RandomGrayAug(Augmenter):
    _coef = onp.array([[[0.299, 0.587, 0.114]]], onp.float32)

    def __init__(self, p):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src):
        if pyrandom.random() < self.p:
            arr = _to_np(src).astype(onp.float32)
            gray = (arr * self._coef).sum(axis=2, keepdims=True)
            return _nd_array(onp.broadcast_to(gray, arr.shape).copy())
        return src if isinstance(src, NDArray) else _nd_array(src)


class HorizontalFlipAug(Augmenter):
    def __init__(self, p):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src):
        if pyrandom.random() < self.p:
            return _nd_array(onp.ascontiguousarray(_to_np(src)[:, ::-1]))
        return src if isinstance(src, NDArray) else _nd_array(src)


def CreateAugmenter(data_shape, resize=0, rand_crop=False, rand_resize=False,
                    rand_mirror=False, mean=None, std=None, brightness=0,
                    contrast=0, saturation=0, hue=0, pca_noise=0,
                    rand_gray=0, inter_method=2):
    """Build the standard augmenter list (ref: image.py CreateAugmenter)."""
    auglist = []
    if resize > 0:
        auglist.append(ResizeAug(resize, inter_method))
    crop_size = (data_shape[2], data_shape[1])
    if rand_resize:
        assert rand_crop
        auglist.append(RandomSizedCropAug(crop_size, (0.08, 1.0),
                                          (3.0 / 4.0, 4.0 / 3.0),
                                          inter_method))
    elif rand_crop:
        auglist.append(RandomCropAug(crop_size, inter_method))
    else:
        auglist.append(CenterCropAug(crop_size, inter_method))
    if rand_mirror:
        auglist.append(HorizontalFlipAug(0.5))
    auglist.append(CastAug())
    if brightness or contrast or saturation:
        auglist.append(ColorJitterAug(brightness, contrast, saturation))
    if hue:
        auglist.append(HueJitterAug(hue))
    if pca_noise > 0:
        eigval = onp.array([55.46, 4.794, 1.148])
        eigvec = onp.array([[-0.5675, 0.7192, 0.4009],
                            [-0.5808, -0.0045, -0.8140],
                            [-0.5836, -0.6948, 0.4203]])
        auglist.append(LightingAug(pca_noise, eigval, eigvec))
    if rand_gray > 0:
        auglist.append(RandomGrayAug(rand_gray))
    if mean is True:
        mean = onp.array([123.68, 116.28, 103.53])
    if std is True:
        std = onp.array([58.395, 57.12, 57.375])
    if mean is not None or std is not None:
        auglist.append(ColorNormalizeAug(mean, std))
    return auglist


class ImageIter:
    """Image data iterator over RecordIO packs or image lists with python
    augmenters (ref: python/mxnet/image/image.py ImageIter). Yields
    `DataBatch` of NCHW float32 data.
    """

    def __init__(self, batch_size, data_shape, label_width=1,
                 path_imgrec=None, path_imglist=None, path_root='',
                 path_imgidx=None, shuffle=False, part_index=0, num_parts=1,
                 aug_list=None, imglist=None, dtype='float32',
                 last_batch_handle='pad', **kwargs):
        from ..io.io import DataDesc
        assert len(data_shape) == 3 and data_shape[0] in (1, 3)
        self.batch_size = batch_size
        self.data_shape = tuple(data_shape)
        self.label_width = label_width
        self.path_root = path_root
        self.shuffle = shuffle
        self.dtype = dtype
        self.last_batch_handle = last_batch_handle

        self.imgrec = None
        self.imglist = None
        self.seq = None
        if path_imgrec:
            from ..recordio import MXIndexedRecordIO, MXRecordIO
            if path_imgidx is None:
                guess = os.path.splitext(path_imgrec)[0] + '.idx'
                path_imgidx = guess if os.path.exists(guess) else None
            if path_imgidx:
                self.imgrec = MXIndexedRecordIO(path_imgidx, path_imgrec, 'r')
                self.seq = list(self.imgrec.keys)
            else:
                if shuffle or num_parts > 1:
                    raise ValueError(
                        "shuffle/num_parts on a .rec file require a .idx "
                        "index (pass path_imgidx); sequential readers "
                        "cannot shuffle or shard")
                self.imgrec = MXRecordIO(path_imgrec, 'r')
        elif path_imglist:
            imglist_d = {}
            with open(path_imglist) as fin:
                for line in fin:
                    parts = line.strip().split('\t')
                    label = onp.array(parts[1:-1], dtype=onp.float32)
                    imglist_d[int(parts[0])] = (label, parts[-1])
            self.imglist = imglist_d
            self.seq = sorted(imglist_d.keys())
        elif imglist is not None:
            imglist_d = {}
            for i, item in enumerate(imglist):
                label = onp.array(item[0], dtype=onp.float32).reshape(-1)
                imglist_d[i] = (label, item[1])
            self.imglist = imglist_d
            self.seq = sorted(imglist_d.keys())
        else:
            raise ValueError(
                "ImageIter needs path_imgrec, path_imglist, or imglist")

        if self.seq is not None and num_parts > 1:
            n = len(self.seq) // num_parts
            self.seq = self.seq[part_index * n:(part_index + 1) * n]

        aug_keys = ('resize', 'rand_crop', 'rand_resize', 'rand_mirror',
                    'mean', 'std', 'brightness', 'contrast', 'saturation',
                    'hue', 'pca_noise', 'rand_gray', 'inter_method')
        unknown = set(kwargs) - set(aug_keys)
        if unknown:
            raise TypeError(f"ImageIter got unknown kwargs: {sorted(unknown)}")
        if aug_list is None:
            aug_list = CreateAugmenter(data_shape, **kwargs)
        self.auglist = aug_list

        label_shape = (batch_size,) if label_width == 1 \
            else (batch_size, label_width)
        self.provide_data = [DataDesc('data',
                                      (batch_size,) + self.data_shape, dtype)]
        self.provide_label = [DataDesc('softmax_label', label_shape,
                                       onp.float32)]
        self._cursor = 0
        self.reset()

    def reset(self):
        if self.shuffle and self.seq is not None:
            pyrandom.shuffle(self.seq)
        if self.imgrec is not None and self.seq is None:
            self.imgrec.reset()
        self._cursor = 0
        self._exhausted = False

    def next_sample(self):
        """Returns (label, decoded HWC image array)."""
        from ..recordio import unpack
        if self.seq is not None:
            if self._cursor >= len(self.seq):
                raise StopIteration
            idx = self.seq[self._cursor]
            self._cursor += 1
            if self.imgrec is not None:
                header, img_bytes = unpack(self.imgrec.read_idx(idx))
                label = header.label
                return label, imdecode(img_bytes)
            label, fname = self.imglist[idx]
            return label, imread(os.path.join(self.path_root, fname))
        s = self.imgrec.read()
        if s is None:
            raise StopIteration
        header, img_bytes = unpack(s)
        return header.label, imdecode(img_bytes)

    def next(self):
        from ..io.io import DataBatch
        if getattr(self, '_exhausted', False):
            # the previous batch consumed the tail and pad-wrapped; the
            # epoch is over even though the cursor sits mid-sequence
            self._exhausted = False
            raise StopIteration
        c, h, w = self.data_shape
        batch_data = onp.zeros((self.batch_size, c, h, w), self.dtype)
        batch_label = onp.zeros((self.batch_size, self.label_width),
                                onp.float32)
        i = 0
        try:
            while i < self.batch_size:
                label, img = self.next_sample()
                for aug in self.auglist:
                    img = aug(img)
                arr = _to_np(img)
                if arr.shape[:2] != (h, w):
                    raise ValueError(
                        f"augmented image shape {arr.shape[:2]} != "
                        f"data_shape {(h, w)}; add a crop/resize augmenter")
                batch_data[i] = arr.astype(self.dtype).transpose(2, 0, 1)
                label = onp.asarray(label, onp.float32).reshape(-1)
                batch_label[i, :self.label_width] = label[:self.label_width]
                i += 1
        except StopIteration:
            if i == 0:
                raise
            if self.last_batch_handle == 'discard':
                raise
        pad = self.batch_size - i
        if pad and self.last_batch_handle == 'pad':
            # reference semantics: the padded tail wraps around with real
            # samples from the start of the (re-shuffled) sequence, so
            # consumers that ignore DataBatch.pad never see fabricated
            # zero-image/label-0 rows. Datasets smaller than the pad wrap
            # repeatedly.
            self.reset()
            start_i = i
            while i < self.batch_size:
                try:
                    label, img = self.next_sample()
                except StopIteration:
                    if i == start_i:  # empty dataset: cannot pad
                        break
                    self.reset()
                    start_i = i
                    continue
                for aug in self.auglist:
                    img = aug(img)
                arr = _to_np(img)
                batch_data[i] = arr.astype(self.dtype).transpose(2, 0, 1)
                label = onp.asarray(label, onp.float32).reshape(-1)
                batch_label[i, :self.label_width] = label[:self.label_width]
                i += 1
            self._exhausted = True
        if self.label_width == 1:
            batch_label = batch_label[:, 0]
        return DataBatch(data=[_nd_array(batch_data)],
                         label=[_nd_array(batch_label)], pad=pad)

    def __next__(self):
        return self.next()

    def __iter__(self):
        return self
