"""Random state management.

Ref: src/common/random_generator.{h,cu} and python/mxnet/random.py — the
reference keeps per-device stateful generators. JAX randomness is functional
(explicit PRNG keys), so we bridge the two worlds with a *key provider*
stack:

- eager mode: a process-global counter-based key stream (stateful facade over
  counter-based splitting — deterministic under `seed()`);
- traced/compiled mode (CachedOp / hybridize): the compiled step takes an
  explicit key argument and pushes a functional provider, so RNG ops inside
  jit draw fresh keys every call instead of baking one in as a constant.
"""
from __future__ import annotations

import threading

import jax
import numpy as _onp


class _KeyProvider:
    def next_key(self):
        raise NotImplementedError


class _GlobalKeyProvider(_KeyProvider):
    """Lazily materializes the base key: creating a PRNGKey initializes the
    XLA backend, which must not happen at import time (it would break
    jax.distributed.initialize in multi-process jobs)."""

    def __init__(self, seed_val: int = 0):
        self._lock = threading.Lock()
        self._seed_val = seed_val
        self._base = None
        self._counter = 0

    def seed(self, seed_val: int):
        with self._lock:
            self._seed_val = seed_val
            self._base = None
            self._counter = 0

    def next_key(self):
        with self._lock:
            if self._base is None:
                self._base = jax.random.PRNGKey(self._seed_val)
            self._counter += 1
            return jax.random.fold_in(self._base, self._counter)


class TraceKeyProvider(_KeyProvider):
    """Functional provider used while tracing a compiled step: splits a key
    argument so every RNG op in the graph gets an independent stream."""

    def __init__(self, key):
        self._key = key

    def next_key(self):
        self._key, sub = jax.random.split(self._key)
        return sub


_global_provider = _GlobalKeyProvider(0)
_tls = threading.local()


def _stack():
    if not hasattr(_tls, 'stack'):
        _tls.stack = []
    return _tls.stack


class key_provider:
    """Context manager installing a key provider (used by CachedOp tracing)."""

    def __init__(self, provider: _KeyProvider):
        self.provider = provider

    def __enter__(self):
        _stack().append(self.provider)
        return self.provider

    def __exit__(self, *exc):
        _stack().pop()


def next_key():
    stack = _stack()
    if stack:
        return stack[-1].next_key()
    return _global_provider.next_key()


def in_traced_rng() -> bool:
    return bool(_stack())


def seed(seed_state: int, ctx=None):
    """Seed the global generator (ref: python/mxnet/random.py seed)."""
    _global_provider.seed(int(seed_state))
    _onp.random.seed(int(seed_state) % (2 ** 31))


def get_state() -> dict:
    """JSON-serializable snapshot of the global RNG stream — the key
    provider's (seed, counter) plus the global numpy generator state —
    so a restored checkpoint resumes the exact random stream
    (checkpoint.CheckpointManager stores this in the manifest)."""
    with _global_provider._lock:
        st = {'seed': _global_provider._seed_val,
              'counter': _global_provider._counter}
    kind, keys, pos, has_gauss, cached = _onp.random.get_state()
    st['numpy'] = {'kind': kind, 'keys': [int(k) for k in keys],
                   'pos': int(pos), 'has_gauss': int(has_gauss),
                   'cached_gaussian': float(cached)}
    return st


def set_state(state: dict) -> None:
    """Restore a get_state() snapshot (counter-based, so the base key is
    rebuilt lazily exactly as after the original seed())."""
    with _global_provider._lock:
        _global_provider._seed_val = int(state['seed'])
        _global_provider._counter = int(state['counter'])
        _global_provider._base = None
    np_st = state.get('numpy')
    if np_st:
        _onp.random.set_state((
            np_st['kind'],
            _onp.asarray(np_st['keys'], dtype=_onp.uint32),
            int(np_st['pos']), int(np_st['has_gauss']),
            float(np_st['cached_gaussian'])))
