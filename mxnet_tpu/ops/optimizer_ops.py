"""Optimizer update ops.

Ref: src/operator/optimizer_op.cc (+ contrib/adamw.cc, multi_lamb.cc). In the
reference, updates are ops inside the engine graph; here they are pure
functions fused by XLA into the compiled train step — the whole update for a
parameter is one fused HBM pass.

All take/return jax arrays; multi-precision (mp_*) variants carry an fp32
master copy of bf16/fp16 weights.
"""
from __future__ import annotations

import jax.numpy as jnp

from ..base import register_op

__all__ = []


def _reg(fn):
    register_op(fn.__name__)(fn)
    __all__.append(fn.__name__)
    return fn


def _grad_prep(grad, rescale_grad, clip_gradient, wd=0.0, weight=None):
    g = grad.astype(jnp.float32) * rescale_grad
    if clip_gradient is not None and clip_gradient > 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    if wd and weight is not None:
        g = g + wd * weight.astype(jnp.float32)
    return g


def _row_mask(grad):
    """Rows of a row-sparse gradient that are actually present. The dense
    payload loses explicit indices, so presence == any nonzero in the row
    (ref: the FComputeEx lazy paths key off grad.aux_data(kIdx))."""
    axes = tuple(range(1, grad.ndim))
    present = (grad != 0).any(axis=axes) if axes else (grad != 0)
    return present.reshape((-1,) + (1,) * (grad.ndim - 1))


@_reg
def sgd_update(weight, grad, lr=0.01, wd=0.0, rescale_grad=1.0,
               clip_gradient=-1.0, lazy_update=False):
    """lazy_update: only rows with a present (nonzero) row-sparse gradient
    are updated (ref: sgd_update FComputeEx in optimizer_op.cc); callers
    enable it only when grad.stype == 'row_sparse'."""
    g = _grad_prep(grad, rescale_grad, clip_gradient, wd, weight)
    new_w = (weight.astype(jnp.float32) - lr * g).astype(weight.dtype)
    if lazy_update:
        new_w = jnp.where(_row_mask(grad), new_w, weight)
    return new_w


@_reg
def sgd_mom_update(weight, grad, mom, lr=0.01, momentum=0.0, wd=0.0,
                   rescale_grad=1.0, clip_gradient=-1.0, lazy_update=False):
    g = _grad_prep(grad, rescale_grad, clip_gradient, wd, weight)
    new_mom = momentum * mom - lr * g
    new_w = (weight.astype(jnp.float32) + new_mom).astype(weight.dtype)
    if lazy_update:
        mask = _row_mask(grad)
        new_w = jnp.where(mask, new_w, weight)
        new_mom = jnp.where(mask, new_mom, mom)
    return new_w, new_mom


@_reg
def mp_sgd_update(weight, grad, weight32, lr=0.01, wd=0.0, rescale_grad=1.0,
                  clip_gradient=-1.0):
    g = _grad_prep(grad, rescale_grad, clip_gradient, wd, weight32)
    new_w32 = weight32 - lr * g
    return new_w32.astype(weight.dtype), new_w32


@_reg
def mp_sgd_mom_update(weight, grad, mom, weight32, lr=0.01, momentum=0.0,
                      wd=0.0, rescale_grad=1.0, clip_gradient=-1.0):
    g = _grad_prep(grad, rescale_grad, clip_gradient, wd, weight32)
    new_mom = momentum * mom - lr * g
    new_w32 = weight32 + new_mom
    return new_w32.astype(weight.dtype), new_mom, new_w32


@_reg
def nag_mom_update(weight, grad, mom, lr=0.01, momentum=0.0, wd=0.0,
                   rescale_grad=1.0, clip_gradient=-1.0):
    g = _grad_prep(grad, rescale_grad, clip_gradient, wd, weight)
    new_mom = momentum * mom + g
    new_w = weight.astype(jnp.float32) - lr * (g + momentum * new_mom)
    return new_w.astype(weight.dtype), new_mom


@_reg
def adam_update(weight, grad, mean, var, lr=0.001, beta1=0.9, beta2=0.999,
                epsilon=1e-8, wd=0.0, rescale_grad=1.0, clip_gradient=-1.0,
                lazy_update=False):
    g = _grad_prep(grad, rescale_grad, clip_gradient, wd, weight)
    new_mean = beta1 * mean + (1 - beta1) * g
    new_var = beta2 * var + (1 - beta2) * jnp.square(g)
    new_w = weight.astype(jnp.float32) - lr * new_mean / (jnp.sqrt(new_var) + epsilon)
    new_w = new_w.astype(weight.dtype)
    if lazy_update:
        mask = _row_mask(grad)
        new_w = jnp.where(mask, new_w, weight)
        new_mean = jnp.where(mask, new_mean, mean)
        new_var = jnp.where(mask, new_var, var)
    return new_w, new_mean, new_var


@_reg
def adamw_update(weight, grad, mean, var, rescale_grad=1.0, lr=0.001, eta=1.0,
                 beta1=0.9, beta2=0.999, epsilon=1e-8, wd=0.0, clip_gradient=-1.0):
    """Ref: src/operator/contrib/adamw.cc — decoupled weight decay."""
    g = _grad_prep(grad, rescale_grad, clip_gradient)
    new_mean = beta1 * mean + (1 - beta1) * g
    new_var = beta2 * var + (1 - beta2) * jnp.square(g)
    w32 = weight.astype(jnp.float32)
    new_w = w32 - eta * (lr * new_mean / (jnp.sqrt(new_var) + epsilon) + wd * lr * w32)
    return new_w.astype(weight.dtype), new_mean, new_var


@_reg
def ftrl_update(weight, grad, z, n, lr=0.1, lamda1=0.01, beta=1.0, wd=0.0,
                rescale_grad=1.0, clip_gradient=-1.0):
    g = _grad_prep(grad, rescale_grad, clip_gradient)
    w32 = weight.astype(jnp.float32)
    new_n = n + jnp.square(g)
    sigma = (jnp.sqrt(new_n) - jnp.sqrt(n)) / lr
    new_z = z + g - sigma * w32
    new_w = jnp.where(
        jnp.abs(new_z) <= lamda1, 0.0,
        -(new_z - jnp.sign(new_z) * lamda1)
        / ((beta + jnp.sqrt(new_n)) / lr + wd))
    return new_w.astype(weight.dtype), new_z, new_n


@_reg
def rmsprop_update(weight, grad, n, lr=0.001, gamma1=0.9, epsilon=1e-8,
                   wd=0.0, rescale_grad=1.0, clip_gradient=-1.0, clip_weights=-1.0):
    g = _grad_prep(grad, rescale_grad, clip_gradient, wd, weight)
    new_n = (1 - gamma1) * jnp.square(g) + gamma1 * n
    new_w = weight.astype(jnp.float32) - lr * g / jnp.sqrt(new_n + epsilon)
    if clip_weights is not None and clip_weights > 0:
        new_w = jnp.clip(new_w, -clip_weights, clip_weights)
    return new_w.astype(weight.dtype), new_n


@_reg
def rmspropalex_update(weight, grad, n, g_acc, delta, lr=0.001, gamma1=0.95,
                       gamma2=0.9, epsilon=1e-8, wd=0.0, rescale_grad=1.0,
                       clip_gradient=-1.0, clip_weights=-1.0):
    g = _grad_prep(grad, rescale_grad, clip_gradient, wd, weight)
    new_n = (1 - gamma1) * jnp.square(g) + gamma1 * n
    new_g = (1 - gamma1) * g + gamma1 * g_acc
    new_delta = gamma2 * delta - lr * g / jnp.sqrt(new_n - jnp.square(new_g) + epsilon)
    new_w = weight.astype(jnp.float32) + new_delta
    if clip_weights is not None and clip_weights > 0:
        new_w = jnp.clip(new_w, -clip_weights, clip_weights)
    return new_w.astype(weight.dtype), new_n, new_g, new_delta


@_reg
def signsgd_update(weight, grad, lr=0.01, wd=0.0, rescale_grad=1.0,
                   clip_gradient=-1.0):
    g = _grad_prep(grad, rescale_grad, clip_gradient)
    w32 = weight.astype(jnp.float32)
    new_w = w32 - lr * (jnp.sign(g) + wd * w32)
    return new_w.astype(weight.dtype)


@_reg
def signum_update(weight, grad, mom, lr=0.01, momentum=0.9, wd=0.0,
                  rescale_grad=1.0, clip_gradient=-1.0, wd_lh=0.0):
    g = _grad_prep(grad, rescale_grad, clip_gradient, wd, weight)
    new_mom = momentum * mom - (1 - momentum) * g
    w32 = weight.astype(jnp.float32)
    new_w = (1 - lr * wd_lh) * w32 + lr * jnp.sign(new_mom)
    return new_w.astype(weight.dtype), new_mom


@_reg
def lamb_update_phase1(weight, grad, mean, var, beta1=0.9, beta2=0.999,
                       epsilon=1e-6, t=1, bias_correction=True, wd=0.0,
                       rescale_grad=1.0, clip_gradient=-1.0):
    """Ref: src/operator/optimizer_op.cc lamb_update_phase1."""
    g = _grad_prep(grad, rescale_grad, clip_gradient)
    new_mean = beta1 * mean + (1 - beta1) * g
    new_var = beta2 * var + (1 - beta2) * jnp.square(g)
    m_hat, v_hat = new_mean, new_var
    if bias_correction:
        m_hat = new_mean / (1 - beta1 ** t)
        v_hat = new_var / (1 - beta2 ** t)
    w32 = weight.astype(jnp.float32)
    update = m_hat / (jnp.sqrt(v_hat) + epsilon) + wd * w32
    return update, new_mean, new_var


@_reg
def lamb_update_phase2(weight, g_update, r1, r2, lr=0.01, lower_bound=-1.0,
                       upper_bound=-1.0):
    r1v = r1
    r2v = r2
    if lower_bound is not None and lower_bound > 0:
        r1v = jnp.maximum(r1v, lower_bound)
    if upper_bound is not None and upper_bound > 0:
        r1v = jnp.minimum(r1v, upper_bound)
    ratio = jnp.where(jnp.logical_and(r1v > 0, r2v > 0), r1v / r2v, 1.0)
    new_w = weight.astype(jnp.float32) - lr * ratio * g_update
    return new_w.astype(weight.dtype)


@_reg
def adagrad_update(weight, grad, history, lr=0.01, epsilon=1e-7, wd=0.0,
                   rescale_grad=1.0, clip_gradient=-1.0):
    g = _grad_prep(grad, rescale_grad, clip_gradient, wd, weight)
    new_hist = history + jnp.square(g)
    new_w = weight.astype(jnp.float32) - lr * g / (jnp.sqrt(new_hist) + epsilon)
    return new_w.astype(weight.dtype), new_hist


@_reg
def adadelta_update(weight, grad, acc_g, acc_delta, rho=0.9, epsilon=1e-5,
                    wd=0.0, rescale_grad=1.0, clip_gradient=-1.0):
    g = _grad_prep(grad, rescale_grad, clip_gradient, wd, weight)
    new_acc_g = rho * acc_g + (1 - rho) * jnp.square(g)
    delta = jnp.sqrt(acc_delta + epsilon) / jnp.sqrt(new_acc_g + epsilon) * g
    new_acc_delta = rho * acc_delta + (1 - rho) * jnp.square(delta)
    new_w = weight.astype(jnp.float32) - delta
    return new_w.astype(weight.dtype), new_acc_g, new_acc_delta


@_reg
def ftml_update(weight, grad, d, v, z, lr=0.01, beta1=0.6, beta2=0.999,
                epsilon=1e-8, t=1, wd=0.0, rescale_grad=1.0, clip_grad=-1.0):
    g = _grad_prep(grad, rescale_grad, clip_grad, wd, weight)
    new_v = beta2 * v + (1 - beta2) * jnp.square(g)
    d_t = (1 - beta1 ** t) / lr * (jnp.sqrt(new_v / (1 - beta2 ** t)) + epsilon)
    sigma = d_t - beta1 * d
    new_z = beta1 * z + (1 - beta1) * g - sigma * weight.astype(jnp.float32)
    new_d = d_t
    new_w = -new_z / new_d
    return new_w.astype(weight.dtype), new_d, new_v, new_z


@_reg
def multi_sum_sq(*arrays):
    """Ref: src/operator/contrib/multi_sum_sq.cc — per-array sum of squares."""
    return tuple(jnp.sum(jnp.square(a.astype(jnp.float32))) for a in arrays)


@_reg
def all_finite(*arrays):
    """Ref: src/operator/contrib/all_finite.cc — 1.0 if every element finite."""
    ok = jnp.array(True)
    for a in arrays:
        ok = jnp.logical_and(ok, jnp.all(jnp.isfinite(a.astype(jnp.float32))))
    return ok.astype(jnp.float32)


# ---------------------------------------------------------------------------
# multi-tensor fused updates (ref: src/operator/optimizer_op.cc
# multi_sgd_update family; src/operator/contrib/preloaded_multi_sgd.cc;
# contrib/multi_lamb.cc; contrib/multi_lans.cc). The reference batches many
# small parameter updates into one kernel launch; here one call produces a
# single XLA program over every tensor — same dispatch-amortization, and
# inside a jitted train step XLA fuses it with the backward pass.
# ---------------------------------------------------------------------------

def _as_list(x):
    return list(x) if isinstance(x, (list, tuple)) else [x]


@_reg
def multi_sgd_update(weights, grads, lrs, wds, rescale_grad=1.0,
                     clip_gradient=-1.0):
    """SGD over N tensors at once. weights/grads: lists; lrs/wds: per-tensor
    scalars (ref: optimizer_op.cc multi_sgd_update)."""
    weights, grads = _as_list(weights), _as_list(grads)
    return [sgd_update(w, g, lr=lr, wd=wd, rescale_grad=rescale_grad,
                       clip_gradient=clip_gradient)
            for w, g, lr, wd in zip(weights, grads, lrs, wds)]


@_reg
def multi_sgd_mom_update(weights, grads, moms, lrs, wds, momentum=0.0,
                         rescale_grad=1.0, clip_gradient=-1.0):
    weights, grads, moms = _as_list(weights), _as_list(grads), _as_list(moms)
    outs = [sgd_mom_update(w, g, m, lr=lr, momentum=momentum, wd=wd,
                           rescale_grad=rescale_grad,
                           clip_gradient=clip_gradient)
            for w, g, m, lr, wd in zip(weights, grads, moms, lrs, wds)]
    return [o[0] for o in outs], [o[1] for o in outs]


@_reg
def multi_mp_sgd_update(weights, grads, weights32, lrs, wds,
                        rescale_grad=1.0, clip_gradient=-1.0):
    weights, grads = _as_list(weights), _as_list(grads)
    weights32 = _as_list(weights32)
    outs = [mp_sgd_update(w, g, w32, lr=lr, wd=wd,
                          rescale_grad=rescale_grad,
                          clip_gradient=clip_gradient)
            for w, g, w32, lr, wd in zip(weights, grads, weights32, lrs,
                                         wds)]
    return [o[0] for o in outs], [o[1] for o in outs]


@_reg
def multi_mp_sgd_mom_update(weights, grads, moms, weights32, lrs, wds,
                            momentum=0.0, rescale_grad=1.0,
                            clip_gradient=-1.0):
    weights, grads = _as_list(weights), _as_list(grads)
    moms, weights32 = _as_list(moms), _as_list(weights32)
    outs = [mp_sgd_mom_update(w, g, m, w32, lr=lr, momentum=momentum,
                              wd=wd, rescale_grad=rescale_grad,
                              clip_gradient=clip_gradient)
            for w, g, m, w32, lr, wd in zip(weights, grads, moms,
                                            weights32, lrs, wds)]
    return ([o[0] for o in outs], [o[1] for o in outs],
            [o[2] for o in outs])


def _grad_prep_preloaded(grad, rescale_grad, clip_gradient, wd, weight):
    """_grad_prep for the preloaded_* contract: lr/wd are DEVICE tensors
    (possibly traced), so the weight-decay add is unconditional — no
    python control flow on wd (ref: contrib/preloaded_multi_sgd.cc, where
    lrs/wds are kernel inputs, not attributes)."""
    g = grad.astype(jnp.float32) * rescale_grad
    if clip_gradient is not None and clip_gradient > 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    return g + wd * weight.astype(jnp.float32)


@_reg
def preloaded_multi_sgd_update(weights, grads, lrs, wds, rescale_grad=1.0,
                               clip_gradient=-1.0):
    """Like multi_sgd_update but lrs/wds arrive as device tensors (the
    'preloaded' variant avoids host scalars entirely,
    ref: contrib/preloaded_multi_sgd.cc); safe under jit."""
    weights, grads = _as_list(weights), _as_list(grads)
    new_w = []
    for i, (w, g) in enumerate(zip(weights, grads)):
        g32 = _grad_prep_preloaded(g, rescale_grad, clip_gradient, wds[i], w)
        new_w.append((w.astype(jnp.float32) - lrs[i] * g32).astype(w.dtype))
    return new_w


@_reg
def preloaded_multi_sgd_mom_update(weights, grads, moms, lrs, wds,
                                   momentum=0.0, rescale_grad=1.0,
                                   clip_gradient=-1.0):
    weights, grads, moms = _as_list(weights), _as_list(grads), _as_list(moms)
    new_w, new_m = [], []
    for i, (w, g, m) in enumerate(zip(weights, grads, moms)):
        g32 = _grad_prep_preloaded(g, rescale_grad, clip_gradient, wds[i], w)
        nm = momentum * m - lrs[i] * g32
        new_m.append(nm)
        new_w.append((w.astype(jnp.float32) + nm).astype(w.dtype))
    return new_w, new_m


@_reg
def preloaded_multi_mp_sgd_update(weights, grads, weights32, lrs, wds,
                                  rescale_grad=1.0, clip_gradient=-1.0):
    weights, grads = _as_list(weights), _as_list(grads)
    weights32 = _as_list(weights32)
    new_w, new_w32 = [], []
    for i, (w, g, w32) in enumerate(zip(weights, grads, weights32)):
        g32 = _grad_prep_preloaded(g, rescale_grad, clip_gradient, wds[i],
                                   w32)
        nw32 = w32 - lrs[i] * g32
        new_w32.append(nw32)
        new_w.append(nw32.astype(w.dtype))
    return new_w, new_w32


@_reg
def preloaded_multi_mp_sgd_mom_update(weights, grads, moms, weights32,
                                      lrs, wds, momentum=0.0,
                                      rescale_grad=1.0,
                                      clip_gradient=-1.0):
    weights, grads = _as_list(weights), _as_list(grads)
    moms, weights32 = _as_list(moms), _as_list(weights32)
    new_w, new_m, new_w32 = [], [], []
    for i, (w, g, m, w32) in enumerate(zip(weights, grads, moms,
                                           weights32)):
        g32 = _grad_prep_preloaded(g, rescale_grad, clip_gradient, wds[i],
                                   w32)
        nm = momentum * m - lrs[i] * g32
        nw32 = w32 + nm
        new_m.append(nm)
        new_w32.append(nw32)
        new_w.append(nw32.astype(w.dtype))
    return new_w, new_m, new_w32


def _lamb_one(w, g, m, v, lr, wd, beta1, beta2, epsilon, t, bias_correction,
              rescale_grad, clip_gradient, lower_bound, upper_bound):
    # one tensor of the multi-tensor op == phase1 + norms + phase2 (the
    # same kernels the LAMB optimizer class uses — single source of truth)
    update, m_new, v_new = lamb_update_phase1(
        w, g, m, v, beta1=beta1, beta2=beta2, epsilon=epsilon, t=t,
        bias_correction=bias_correction, wd=wd, rescale_grad=rescale_grad,
        clip_gradient=-1.0 if clip_gradient is None else clip_gradient)
    r1 = jnp.linalg.norm(w.astype(jnp.float32).reshape(-1))
    r2 = jnp.linalg.norm(update.reshape(-1))
    new_w = lamb_update_phase2(
        w, update, r1, r2, lr=lr,
        lower_bound=-1.0 if lower_bound is None else lower_bound,
        upper_bound=-1.0 if upper_bound is None else upper_bound)
    return new_w, m_new, v_new


@_reg
def multi_lamb_update(weights, grads, means, vars_, lrs, wds, step_count,
                      beta1=0.9, beta2=0.999, epsilon=1e-6,
                      bias_correction=True, rescale_grad=1.0,
                      clip_gradient=-1.0, lower_bound=-1.0,
                      upper_bound=-1.0):
    """LAMB over N tensors (ref: contrib/multi_lamb.cc)."""
    weights, grads = _as_list(weights), _as_list(grads)
    means, vars_ = _as_list(means), _as_list(vars_)
    outs = [_lamb_one(w, g, m, v, lrs[i], wds[i], beta1, beta2, epsilon,
                      step_count[i], bias_correction, rescale_grad,
                      None if clip_gradient is None or clip_gradient < 0
                      else clip_gradient,
                      None if lower_bound < 0 else lower_bound,
                      None if upper_bound < 0 else upper_bound)
            for i, (w, g, m, v) in enumerate(zip(weights, grads, means,
                                                 vars_))]
    return ([o[0] for o in outs], [o[1] for o in outs],
            [o[2] for o in outs])


def _lans_one(w, g, m, v, lr, wd, beta1, beta2, epsilon, t,
              rescale_grad, clip_gradient):
    g32 = _grad_prep(g, rescale_grad, clip_gradient)
    g32 = g32 / jnp.maximum(jnp.linalg.norm(g32.reshape(-1)), 1e-12)
    w32 = w.astype(jnp.float32)
    m_new = beta1 * m + (1 - beta1) * g32
    v_new = beta2 * v + (1 - beta2) * jnp.square(g32)
    mhat = m_new / (1 - beta1 ** t)
    vhat = v_new / (1 - beta2 ** t)
    r1 = jnp.linalg.norm(w32.reshape(-1))
    upd_m = mhat / (jnp.sqrt(vhat) + epsilon) + wd * w32
    upd_g = g32 / (jnp.sqrt(vhat) + epsilon) + wd * w32
    rm = jnp.linalg.norm(upd_m.reshape(-1))
    rg = jnp.linalg.norm(upd_g.reshape(-1))
    ratio_m = jnp.where((r1 > 0) & (rm > 0), r1 / rm, 1.0)
    ratio_g = jnp.where((r1 > 0) & (rg > 0), r1 / rg, 1.0)
    new_w = (w32 - lr * (beta1 * ratio_m * upd_m
                         + (1 - beta1) * ratio_g * upd_g)).astype(w.dtype)
    return new_w, m_new, v_new


@_reg
def multi_lans_update(weights, grads, means, vars_, lrs, wds, step_count,
                      beta1=0.9, beta2=0.999, epsilon=1e-6,
                      rescale_grad=1.0, clip_gradient=-1.0):
    """LANS over N tensors (ref: contrib/multi_lans.cc)."""
    weights, grads = _as_list(weights), _as_list(grads)
    means, vars_ = _as_list(means), _as_list(vars_)
    outs = [_lans_one(w, g, m, v, lrs[i], wds[i], beta1, beta2, epsilon,
                      step_count[i], rescale_grad,
                      None if clip_gradient is None or clip_gradient < 0
                      else clip_gradient)
            for i, (w, g, m, v) in enumerate(zip(weights, grads, means,
                                                 vars_))]
    return ([o[0] for o in outs], [o[1] for o in outs],
            [o[2] for o in outs])


@_reg
def multi_adamw_update(weights, grads, means, vars_, rescale_grad, lrs,
                       etas, wds, beta1=0.9, beta2=0.999, epsilon=1e-8,
                       clip_gradient=-1.0):
    """AdamW over N tensors (ref: contrib/adamw.cc _multi_adamw_update).
    rescale_grad arrives as a tensor; a non-finite value skips the update
    (the reference's dynamic-loss-scale overflow protocol)."""
    weights, grads = _as_list(weights), _as_list(grads)
    means, vars_ = _as_list(means), _as_list(vars_)
    scale = jnp.asarray(rescale_grad, jnp.float32).reshape(())
    ok = jnp.isfinite(scale)
    safe = jnp.where(ok, scale, 0.0)
    new_ws, new_ms, new_vs = [], [], []
    for i, (w, g, m, v) in enumerate(zip(weights, grads, means, vars_)):
        g32 = g.astype(jnp.float32) * safe
        if clip_gradient is not None and clip_gradient > 0:
            g32 = jnp.clip(g32, -clip_gradient, clip_gradient)
        m_new = beta1 * m + (1 - beta1) * g32
        v_new = beta2 * v + (1 - beta2) * jnp.square(g32)
        w32 = w.astype(jnp.float32)
        upd = lrs[i] * (etas[i] * m_new / (jnp.sqrt(v_new) + epsilon)
                        + wds[i] * w32)
        new_w = (w32 - upd).astype(w.dtype)
        new_ws.append(jnp.where(ok, new_w, w))
        new_ms.append(jnp.where(ok, m_new, m))
        new_vs.append(jnp.where(ok, v_new, v))
    return new_ws, new_ms, new_vs
