"""Pallas fused FFN1 epilogue for TPU: gelu(x @ W.T + b) in one kernel.

The span attribution at the flagship BERT-base shape puts the encoder's
XLA-side FFN block next on the headroom list after the flash-attention
and residual+LN kernels landed (PERF_NOTES r4): the FFN1 matmul's bias
add and exact GELU are a separate HBM round trip over the (tokens,
intermediate) activation — 4x the hidden width, the fattest tensor in
the layer. This kernel runs the matmul on the MXU with the bias+GELU
epilogue applied in VMEM before the block ever leaves the core, the
same fused-epilogue ethos as ops/pallas_layernorm.py (ref: the
hand-fused transformer ops in src/operator/contrib/transformer.cc).

Grid (M/bm, N/bn); K (the contraction dim — BERT hidden 768) rides
whole in each block's lane dim, so every block is trailing-tile legal
by the block==array-dim rule and no cross-step accumulator is needed.
fp32 accumulation via preferred_element_type, exact (erf) GELU to match
ops/nn.py activation(act_type='gelu') bit-for-bit semantics.

Backward is the standard dense+GELU gradient in plain jnp (custom_vjp):
it recomputes the pre-activation from the saved (x, W, b) instead of
saving the (M, N) intermediate — deliberately, because that tensor is
exactly the HBM spend the fusion exists to avoid.

Routing: models/bert.py's layers call ops.nn.dense_gelu, which routes
here when ``MXTPU_PALLAS_FFN=1`` and a TPU is present (default OFF
until measured on-chip — flag-gated exactly like MXTPU_PALLAS_LN).
``interpret=True`` runs the identical kernel on CPU for parity tests.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from .pallas_attention import pallas_available  # shared TPU probe

_INV_SQRT2 = 1.0 / math.sqrt(2.0)


def _gelu_f32(s):
    # exact GELU, f32: matches jax.nn.gelu(approximate=False)
    return 0.5 * s * (1.0 + jax.lax.erf(s * _INV_SQRT2))


def _ffn_kernel(x_ref, w_ref, b_ref, o_ref):
    """One (bm, bn) output tile: gelu(x_blk @ w_blk.T + b_blk).
    x (bm, K), w (bn, K), b (1, bn) — K whole in the lane dim."""
    s = jax.lax.dot_general(
        x_ref[...], w_ref[...], (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)
    s = s + b_ref[...].astype(jnp.float32)
    o_ref[...] = _gelu_f32(s).astype(o_ref.dtype)


def _shrink_to_divisor(block, dim):
    b = min(block, dim)
    while dim % b:
        b -= 1
    return b


def _fwd_impl(x, w, b, block_m, block_n, interpret):
    orig_shape = x.shape
    K = orig_shape[-1]
    N = w.shape[0]
    x2 = x.reshape(-1, K)
    M = x2.shape[0]
    bm = _shrink_to_divisor(block_m, M)
    bn = _shrink_to_divisor(block_n, N)
    b2 = b.reshape(1, N)
    out = pl.pallas_call(
        _ffn_kernel,
        grid=(M // bm, N // bn),
        in_specs=[
            pl.BlockSpec((bm, K), lambda i, j: (i, 0)),
            pl.BlockSpec((bn, K), lambda i, j: (j, 0)),
            pl.BlockSpec((1, bn), lambda i, j: (0, j)),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j: (i, j)),
        out_shape=jax.ShapeDtypeStruct((M, N), x.dtype),
        interpret=interpret,
    )(x2, w, b2)
    return out.reshape(orig_shape[:-1] + (N,))


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def fused_dense_gelu(x, w, b, block_m=256, block_n=256, interpret=False):
    """gelu(x @ w.T + b) with the bias+GELU epilogue fused into the
    matmul kernel (see module doc). w: (N, K) Dense weight layout."""
    return _fwd_impl(x, w, b, block_m, block_n, interpret)


def _fwd(x, w, b, block_m, block_n, interpret):
    return _fwd_impl(x, w, b, block_m, block_n, interpret), (x, w, b)


def _bwd(block_m, block_n, interpret, saved, g):
    x, w, b = saved
    K = x.shape[-1]
    x2 = x.reshape(-1, K).astype(jnp.float32)
    w32 = w.astype(jnp.float32)
    g2 = g.reshape(-1, w.shape[0]).astype(jnp.float32)
    # recompute the pre-activation (remat) rather than saving the
    # (M, N) intermediate the fusion exists to keep out of HBM
    s = x2 @ w32.T + b.astype(jnp.float32)
    pdf = jnp.exp(-0.5 * s * s) * (1.0 / math.sqrt(2.0 * math.pi))
    dgelu = 0.5 * (1.0 + jax.lax.erf(s * _INV_SQRT2)) + s * pdf
    ds = g2 * dgelu
    dx = (ds @ w32).reshape(x.shape).astype(x.dtype)
    dw = (ds.T @ x2).astype(w.dtype)
    db = jnp.sum(ds, axis=0).astype(b.dtype)
    return dx, dw, db


fused_dense_gelu.defvjp(_fwd, _bwd)
