"""Contrib / vision / detection ops.

Ref: src/operator/contrib/ (bounding_box.cc, multibox_*.cc, roi_align.cc,
bilinear_resize.cc, adaptive_avg_pooling.cc...) and src/operator/image/.
Vectorised lax/jnp formulations; NMS uses a lax.fori_loop suppression sweep
(static shapes, TPU-friendly).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..base import register_op

__all__ = []


def _reg(fn):
    register_op(fn.__name__)(fn)
    __all__.append(fn.__name__)
    return fn


def _iou_corner(a, b):
    """a: (..., M, 4), b: (..., K, 4) corner format → (..., M, K)."""
    tl = jnp.maximum(a[..., :, None, :2], b[..., None, :, :2])
    br = jnp.minimum(a[..., :, None, 2:4], b[..., None, :, 2:4])
    wh = jnp.maximum(br - tl, 0.0)
    inter = wh[..., 0] * wh[..., 1]
    area_a = jnp.maximum(a[..., 2] - a[..., 0], 0) * jnp.maximum(a[..., 3] - a[..., 1], 0)
    area_b = jnp.maximum(b[..., 2] - b[..., 0], 0) * jnp.maximum(b[..., 3] - b[..., 1], 0)
    union = area_a[..., :, None] + area_b[..., None, :] - inter
    return jnp.where(union > 0, inter / union, 0.0)


@_reg
def box_iou(lhs, rhs, format='corner'):
    """Ref: src/operator/contrib/bounding_box.cc box_iou."""
    if format == 'center':
        def c2c(x):
            xy = x[..., :2]
            wh = x[..., 2:4] / 2
            return jnp.concatenate([xy - wh, xy + wh], axis=-1)
        lhs, rhs = c2c(lhs), c2c(rhs)
    return _iou_corner(lhs, rhs)


@_reg
def box_nms(data, overlap_thresh=0.5, valid_thresh=0.0, topk=-1, coord_start=2,
            score_index=1, id_index=-1, background_id=-1, force_suppress=False,
            in_format='corner', out_format='corner'):
    """Batched NMS (ref: bounding_box.cc box_nms). data: (..., N, K>=6).

    Greedy suppression implemented as a fixed-length fori_loop over
    score-sorted candidates — static shapes so XLA compiles one kernel.
    Suppressed entries get score -1 (reference semantics)."""
    orig_shape = data.shape
    x = data.reshape((-1,) + orig_shape[-2:])
    B, N, K = x.shape
    scores = x[..., score_index]
    boxes = x[..., coord_start:coord_start + 4]
    if in_format == 'center':
        xy = boxes[..., :2]
        wh = boxes[..., 2:4] / 2
        boxes = jnp.concatenate([xy - wh, xy + wh], axis=-1)
    cls_id = x[..., id_index] if id_index >= 0 else jnp.zeros((B, N))
    valid = scores > valid_thresh
    if background_id >= 0 and id_index >= 0:
        valid = jnp.logical_and(valid, cls_id != background_id)
    order = jnp.argsort(-jnp.where(valid, scores, -jnp.inf), axis=-1)
    if topk > 0:
        keep_n = min(topk, N)
    else:
        keep_n = N
    sorted_boxes = jnp.take_along_axis(boxes, order[..., None], axis=1)
    sorted_valid = jnp.take_along_axis(valid, order, axis=1)
    sorted_cls = jnp.take_along_axis(cls_id, order, axis=1)
    iou = _iou_corner(sorted_boxes, sorted_boxes)  # (B, N, N)
    if not force_suppress and id_index >= 0:
        same = sorted_cls[..., :, None] == sorted_cls[..., None, :]
        iou = jnp.where(same, iou, 0.0)

    def body(i, keep):
        active = keep[:, i] & sorted_valid[:, i] & (i < keep_n)
        sup = (iou[:, i, :] > overlap_thresh) & (jnp.arange(N)[None, :] > i)
        return jnp.where(active[:, None] & sup, False, keep)

    keep = lax.fori_loop(0, N, body, jnp.ones((B, N), bool))
    keep = keep & sorted_valid & (jnp.arange(N)[None, :] < keep_n)
    new_scores = jnp.where(keep, jnp.take_along_axis(scores, order, axis=1), -1.0)
    sorted_x = jnp.take_along_axis(x, order[..., None], axis=1)
    out = sorted_x.at[..., score_index].set(new_scores)
    return out.reshape(orig_shape)


@_reg
def bilinear_resize2d(data, height=None, width=None, scale_height=None,
                      scale_width=None, mode='size', align_corners=True):
    """Ref: src/operator/contrib/bilinear_resize.cc. NCHW."""
    n, c, h, w = data.shape
    if height is None:
        height = int(h * scale_height)
        width = int(w * scale_width)
    if align_corners and height > 1 and width > 1:
        ys = jnp.linspace(0, h - 1, height)
        xs = jnp.linspace(0, w - 1, width)
    else:
        ys = (jnp.arange(height) + 0.5) * h / height - 0.5
        xs = (jnp.arange(width) + 0.5) * w / width - 0.5
    y0 = jnp.clip(jnp.floor(ys), 0, h - 1).astype(jnp.int32)
    x0 = jnp.clip(jnp.floor(xs), 0, w - 1).astype(jnp.int32)
    y1 = jnp.clip(y0 + 1, 0, h - 1)
    x1 = jnp.clip(x0 + 1, 0, w - 1)
    wy = jnp.clip(ys - y0, 0, 1)
    wx = jnp.clip(xs - x0, 0, 1)
    top = data[:, :, y0][:, :, :, x0] * (1 - wx) + data[:, :, y0][:, :, :, x1] * wx
    bot = data[:, :, y1][:, :, :, x0] * (1 - wx) + data[:, :, y1][:, :, :, x1] * wx
    return top * (1 - wy[:, None]) + bot * wy[:, None]


@_reg
def adaptive_avg_pooling2d(data, output_size=(1, 1)):
    """Ref: src/operator/contrib/adaptive_avg_pooling.cc."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    oh, ow = output_size
    n, c, h, w = data.shape
    if h % oh == 0 and w % ow == 0:
        x = data.reshape(n, c, oh, h // oh, ow, w // ow)
        return x.mean(axis=(3, 5))
    # general: interpolation-style averaging via cumulative sums
    ys = jnp.linspace(0, h, oh + 1)
    xs = jnp.linspace(0, w, ow + 1)
    out = jnp.zeros((n, c, oh, ow), data.dtype)
    rows = []
    for i in range(oh):
        y0, y1 = int(ys[i]), int(jnp.ceil(ys[i + 1]))
        cols = []
        for j in range(ow):
            x0, x1 = int(xs[j]), int(jnp.ceil(xs[j + 1]))
            cols.append(data[:, :, y0:y1, x0:x1].mean(axis=(2, 3)))
        rows.append(jnp.stack(cols, axis=-1))
    return jnp.stack(rows, axis=-2)


@_reg
def roi_align(data, rois, pooled_size=(7, 7), spatial_scale=1.0,
              sample_ratio=-1, position_sensitive=False, aligned=False):
    """Ref: src/operator/contrib/roi_align.cc. data NCHW; rois (R,5)=[b,x1,y1,x2,y2]."""
    ph, pw = pooled_size
    n, c, h, w = data.shape
    offset = 0.5 if aligned else 0.0
    sr = sample_ratio if sample_ratio > 0 else 2

    def one_roi(roi):
        bidx = roi[0].astype(jnp.int32)
        x1, y1, x2, y2 = roi[1] * spatial_scale - offset, roi[2] * spatial_scale - offset, \
            roi[3] * spatial_scale - offset, roi[4] * spatial_scale - offset
        rw = jnp.maximum(x2 - x1, 1.0 if not aligned else 1e-6)
        rh = jnp.maximum(y2 - y1, 1.0 if not aligned else 1e-6)
        bh, bw = rh / ph, rw / pw
        # sample grid: (ph*sr, pw*sr)
        gy = y1 + (jnp.arange(ph * sr) + 0.5) * bh / sr
        gx = x1 + (jnp.arange(pw * sr) + 0.5) * bw / sr
        img = data[bidx]  # (C, H, W)
        y0i = jnp.clip(jnp.floor(gy), 0, h - 1).astype(jnp.int32)
        x0i = jnp.clip(jnp.floor(gx), 0, w - 1).astype(jnp.int32)
        y1i = jnp.clip(y0i + 1, 0, h - 1)
        x1i = jnp.clip(x0i + 1, 0, w - 1)
        wy = jnp.clip(gy - y0i, 0, 1)
        wx = jnp.clip(gx - x0i, 0, 1)
        tl = img[:, y0i][:, :, x0i]
        tr = img[:, y0i][:, :, x1i]
        bl = img[:, y1i][:, :, x0i]
        br = img[:, y1i][:, :, x1i]
        top = tl * (1 - wx) + tr * wx
        bot = bl * (1 - wx) + br * wx
        samples = top * (1 - wy[:, None]) + bot * wy[:, None]  # (C, ph*sr, pw*sr)
        samples = samples.reshape(c, ph, sr, pw, sr)
        valid = jnp.logical_and(gy >= -1, gy <= h).astype(data.dtype)
        return samples.mean(axis=(2, 4))

    return jax.vmap(one_roi)(rois)


@_reg
def multibox_prior(data, sizes=(1.0,), ratios=(1.0,), clip=False, steps=(-1.0, -1.0),
                   offsets=(0.5, 0.5)):
    """SSD anchor generation (ref: src/operator/contrib/multibox_prior.cc)."""
    h, w = data.shape[2], data.shape[3]
    sizes = list(sizes)
    ratios = list(ratios)
    step_y = steps[0] if steps[0] > 0 else 1.0 / h
    step_x = steps[1] if steps[1] > 0 else 1.0 / w
    cy = (jnp.arange(h) + offsets[0]) * step_y
    cx = (jnp.arange(w) + offsets[1]) * step_x
    cyg, cxg = jnp.meshgrid(cy, cx, indexing='ij')
    num = len(sizes) + len(ratios) - 1
    ws, hs = [], []
    for i in range(num):
        if i < len(sizes):
            s = sizes[i]
            r = ratios[0]
        else:
            s = sizes[0]
            r = ratios[i - len(sizes) + 1]
        sr = jnp.sqrt(r)
        ws.append(s * sr / 2 * (h / w if False else 1.0))
        hs.append(s / sr / 2)
    anchors = []
    for wv, hv in zip(ws, hs):
        anchors.append(jnp.stack([cxg - wv, cyg - hv, cxg + wv, cyg + hv], axis=-1))
    out = jnp.stack(anchors, axis=2).reshape(1, -1, 4)
    if clip:
        out = jnp.clip(out, 0.0, 1.0)
    return out


@_reg
def smooth_l1(data, scalar=1.0):
    """Ref: src/operator/tensor/elemwise_unary_op_basic.cc smooth_l1."""
    s2 = scalar * scalar
    return jnp.where(jnp.abs(data) < 1.0 / s2,
                     0.5 * s2 * jnp.square(data),
                     jnp.abs(data) - 0.5 / s2)


@_reg
def arange_like(data, start=0.0, step=1.0, repeat=1, axis=None):
    if axis is None:
        n = data.size
        return (start + step * jnp.arange(n)).reshape(data.shape)
    n = data.shape[axis]
    return start + step * jnp.arange(n)


@_reg
def image_normalize(data, mean=(0, 0, 0), std=(1, 1, 1)):
    """Ref: src/operator/image/image_random.cc Normalize; CHW or NCHW."""
    mean = jnp.asarray(mean, dtype=data.dtype)
    std = jnp.asarray(std, dtype=data.dtype)
    if data.ndim == 3:
        return (data - mean[:, None, None]) / std[:, None, None]
    return (data - mean[None, :, None, None]) / std[None, :, None, None]


@_reg
def image_to_tensor(data):
    """HWC uint8 → CHW float [0,1] (ref: src/operator/image/image_random.cc)."""
    if data.ndim == 3:
        return data.transpose(2, 0, 1).astype(jnp.float32) / 255.0
    return data.transpose(0, 3, 1, 2).astype(jnp.float32) / 255.0


@_reg
def image_resize(data, size=(224, 224), keep_ratio=False, interp=1):
    """HWC / NHWC resize via jax.image (ref: src/operator/image/resize.cc)."""
    if isinstance(size, int):
        size = (size, size)
    w, h = size
    method = 'nearest' if interp == 0 else 'bilinear'
    if data.ndim == 3:
        return jax.image.resize(data, (h, w, data.shape[2]), method=method)
    return jax.image.resize(data, (data.shape[0], h, w, data.shape[3]),
                            method=method)


@_reg
def image_crop(data, x=0, y=0, width=1, height=1):
    if data.ndim == 3:
        return data[y:y + height, x:x + width, :]
    return data[:, y:y + height, x:x + width, :]


@_reg
def image_flip_left_right(data):
    return jnp.flip(data, axis=-2)


@_reg
def image_flip_top_bottom(data):
    return jnp.flip(data, axis=-3)


@_reg
def spatial_transformer(data, loc, target_shape=None, transform_type='affine',
                        sampler_type='bilinear'):
    """Affine grid + bilinear sample (ref: src/operator/spatial_transformer.cc)."""
    n, c, h, w = data.shape
    th, tw = target_shape if target_shape else (h, w)
    theta = loc.reshape(n, 2, 3)
    ys = jnp.linspace(-1, 1, th)
    xs = jnp.linspace(-1, 1, tw)
    gy, gx = jnp.meshgrid(ys, xs, indexing='ij')
    grid = jnp.stack([gx.ravel(), gy.ravel(), jnp.ones(th * tw)], axis=0)
    src = jnp.einsum('nij,jk->nik', theta, grid)  # (n, 2, th*tw)
    sx = (src[:, 0] + 1) * (w - 1) / 2
    sy = (src[:, 1] + 1) * (h - 1) / 2

    def sample_one(img, sx, sy):
        x0 = jnp.clip(jnp.floor(sx), 0, w - 1).astype(jnp.int32)
        y0 = jnp.clip(jnp.floor(sy), 0, h - 1).astype(jnp.int32)
        x1 = jnp.clip(x0 + 1, 0, w - 1)
        y1 = jnp.clip(y0 + 1, 0, h - 1)
        wx = jnp.clip(sx - x0, 0, 1)
        wy = jnp.clip(sy - y0, 0, 1)
        tl = img[:, y0, x0]
        tr = img[:, y0, x1]
        bl = img[:, y1, x0]
        br = img[:, y1, x1]
        out = (tl * (1 - wx) * (1 - wy) + tr * wx * (1 - wy)
               + bl * (1 - wx) * wy + br * wx * wy)
        return out.reshape(c, th, tw)

    return jax.vmap(sample_one)(data, sx, sy)


@_reg
def grid_generator(data, transform_type='affine', target_shape=None):
    n = data.shape[0]
    th, tw = target_shape
    theta = data.reshape(n, 2, 3)
    ys = jnp.linspace(-1, 1, th)
    xs = jnp.linspace(-1, 1, tw)
    gy, gx = jnp.meshgrid(ys, xs, indexing='ij')
    grid = jnp.stack([gx.ravel(), gy.ravel(), jnp.ones(th * tw)], axis=0)
    src = jnp.einsum('nij,jk->nik', theta, grid)
    return src.reshape(n, 2, th, tw)


@_reg
def bilinear_sampler(data, grid):
    """Ref: src/operator/bilinear_sampler.cc. grid in [-1,1], (N,2,H,W)."""
    n, c, h, w = data.shape
    gh, gw = grid.shape[2], grid.shape[3]
    sx = (grid[:, 0] + 1) * (w - 1) / 2
    sy = (grid[:, 1] + 1) * (h - 1) / 2

    def sample_one(img, sx, sy):
        x0 = jnp.floor(sx).astype(jnp.int32)
        y0 = jnp.floor(sy).astype(jnp.int32)
        x1, y1 = x0 + 1, y0 + 1
        wx = sx - x0
        wy = sy - y0

        def at(yy, xx):
            valid = (yy >= 0) & (yy < h) & (xx >= 0) & (xx < w)
            yc = jnp.clip(yy, 0, h - 1)
            xc = jnp.clip(xx, 0, w - 1)
            return img[:, yc, xc] * valid

        out = (at(y0, x0) * (1 - wx) * (1 - wy) + at(y0, x1) * wx * (1 - wy)
               + at(y1, x0) * (1 - wx) * wy + at(y1, x1) * wx * wy)
        return out

    return jax.vmap(sample_one)(data, sx, sy)
