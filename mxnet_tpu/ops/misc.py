"""Misc contrib + legacy v1 ops.

Ref: src/operator/contrib/{fft.cc,count_sketch.cc,krprod.cc,hawkes_ll.cc,
quadratic_op.cc,gradient_multiplier_op.cc,stes_op.cc,nnz.cc,allclose_op.cc},
src/operator/{l2_normalization.cc,instance_norm.cc,make_loss.cc,
softmax_output.cc,slice_channel.cc}.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from ..base import register_op

__all__ = []


def _reg(fn):
    register_op(fn.__name__)(fn)
    __all__.append(fn.__name__)
    return fn


@_reg
def fft(data, compute_size=128):
    """FFT of the last axis; real input → interleaved [re, im] output of
    width 2*d (ref: src/operator/contrib/fft.cc layout)."""
    out = jnp.fft.fft(data.astype(jnp.complex64), axis=-1)
    inter = jnp.stack([out.real, out.imag], axis=-1)
    return inter.reshape(*data.shape[:-1], data.shape[-1] * 2)


@_reg
def ifft(data, compute_size=128):
    """Inverse of `fft`: interleaved complex (…, 2d) → real (…, d)
    (ref: src/operator/contrib/ifft.cc; like the reference, output is the
    unnormalized IFFT — scale by 1/d to recover the original signal)."""
    d = data.shape[-1] // 2
    c = data.reshape(*data.shape[:-1], d, 2)
    comp = c[..., 0] + 1j * c[..., 1]
    return jnp.fft.ifft(comp, axis=-1).real.astype(data.dtype) * d


@_reg
def count_sketch(data, h, s, out_dim):
    """Count-sketch projection: out[:, h[i]] += s[i] * data[:, i]
    (ref: src/operator/contrib/count_sketch.cc). Scatter-add lowers to one
    XLA scatter instead of the reference's per-element CUDA kernel."""
    n, in_dim = data.shape
    hh = h.reshape(-1)[:in_dim].astype(jnp.int32)
    ss = s.reshape(-1)[:in_dim].astype(data.dtype)
    out = jnp.zeros((n, out_dim), data.dtype)
    return out.at[:, hh].add(data * ss[None, :])


@_reg
def khatri_rao(*matrices):
    """Column-wise Kronecker (Khatri-Rao) product
    (ref: src/operator/contrib/krprod.cc)."""
    out = matrices[0]
    for m in matrices[1:]:
        k = out.shape[1]
        assert m.shape[1] == k, "khatri_rao: column counts must match"
        out = (out[:, None, :] * m[None, :, :]).reshape(-1, k)
    return out


@_reg
def quadratic(data, a=0.0, b=0.0, c=0.0):
    """a*x^2 + b*x + c — the tutorial op
    (ref: src/operator/contrib/quadratic_op.cc)."""
    return a * data * data + b * data + c


@jax.custom_vjp
def _grad_mult(data, scalar):
    return data


def _grad_mult_fwd(data, scalar):
    return data, scalar


def _grad_mult_bwd(scalar, ct):
    return (ct * scalar, None)


_grad_mult.defvjp(_grad_mult_fwd, _grad_mult_bwd)


@_reg
def gradient_multiplier(data, scalar=1.0):
    """Identity forward, gradient scaled by `scalar` (gradient reversal when
    negative) (ref: src/operator/contrib/gradient_multiplier_op.cc)."""
    return _grad_mult(data, jnp.asarray(scalar, data.dtype))


@jax.custom_vjp
def _round_ste_p(x):
    return jnp.round(x)


_round_ste_p.defvjp(lambda x: (jnp.round(x), None), lambda _, ct: (ct,))


@jax.custom_vjp
def _sign_ste_p(x):
    return jnp.sign(x)


_sign_ste_p.defvjp(lambda x: (jnp.sign(x), None), lambda _, ct: (ct,))


@_reg
def round_ste(data):
    """Straight-through rounding (ref: src/operator/contrib/stes_op.cc)."""
    return _round_ste_p(data)


@_reg
def sign_ste(data):
    """Straight-through sign (ref: src/operator/contrib/stes_op.cc)."""
    return _sign_ste_p(data)


@_reg
def hawkes_ll(lda, alpha, beta, state, lags, marks, valid_length, max_time):
    """Log-likelihood of a marked self-exciting Hawkes process, one sample
    per row (ref: src/operator/contrib/hawkes_ll.cc).

    lda: (N, K) background intensity, alpha/beta: (K,), state: (N, K)
    initial excitation, lags/marks: (N, T), valid_length: (N,),
    max_time: (N,). Returns (ll (N,), new_state (N, K)).

    The reference loops timesteps in a CUDA kernel; here the recurrence is
    a lax.scan over T with everything batched — same O(N*T*K) work, fully
    on-device.
    """
    N, T = lags.shape
    K = lda.shape[1]
    marks_i = marks.astype(jnp.int32)

    def step(carry, t):
        ll, rem, elapsed = carry
        lag = lags[:, t]
        mark = marks_i[:, t]
        valid = (t < valid_length).astype(lda.dtype)

        elapsed_new = elapsed + lag
        decay = jnp.exp(-beta[None, :] * lag[:, None])
        rem_decayed = rem * decay
        intensity = lda + alpha[None, :] * rem_decayed
        lam = jnp.take_along_axis(intensity, mark[:, None], axis=1)[:, 0]
        ll_t = jnp.log(jnp.maximum(lam, 1e-20))

        # compensator increment for the interval (integral of intensity)
        comp = (lda * lag[:, None]
                + (alpha / beta)[None, :] * rem * (1.0 - decay)).sum(1)
        ll = ll + valid * (ll_t - comp)
        rem_new = rem_decayed + jax.nn.one_hot(mark, K, dtype=lda.dtype)
        rem = jnp.where(valid[:, None] > 0, rem_new, rem)
        elapsed = jnp.where(valid > 0, elapsed_new, elapsed)
        return (ll, rem, elapsed), None

    init = (jnp.zeros((N,), lda.dtype), state, jnp.zeros((N,), lda.dtype))
    (ll, rem, elapsed), _ = lax.scan(step, init, jnp.arange(T))

    # tail compensator from last event to max_time
    tail = jnp.maximum(max_time - elapsed, 0.0)
    decay_tail = 1.0 - jnp.exp(-beta[None, :] * tail[:, None])
    comp_tail = (lda * tail[:, None]
                 + (alpha / beta)[None, :] * rem * decay_tail).sum(1)
    ll = ll - comp_tail
    new_state = rem * jnp.exp(-beta[None, :] * tail[:, None])
    return ll, new_state


@_reg
def nnz(data, axis=None):
    """Number of stored non-zeros (ref: src/operator/contrib/nnz.cc)."""
    return jnp.count_nonzero(data, axis=axis).astype(jnp.int64)


@_reg
def allclose(a, b, rtol=1e-05, atol=1e-08, equal_nan=True):
    """Scalar 0/1 allclose (ref: src/operator/contrib/allclose_op.cc)."""
    return jnp.allclose(a, b, rtol=rtol, atol=atol,
                        equal_nan=equal_nan).astype(jnp.float32)


@_reg
def L2Normalization(data, eps=1e-10, mode='instance'):
    """x / sqrt(sum(x^2) + eps) (ref: src/operator/l2_normalization.cc).

    mode: 'instance' (over all but batch), 'channel' (over axis 1),
    'spatial' (over trailing spatial axes)."""
    if mode == 'instance':
        axes = tuple(range(1, data.ndim))
    elif mode == 'channel':
        axes = (1,)
    elif mode == 'spatial':
        axes = tuple(range(2, data.ndim))
    else:
        raise ValueError(f"unknown L2Normalization mode {mode!r}")
    norm = jnp.sqrt(jnp.sum(data * data, axis=axes, keepdims=True) + eps)
    return data / norm


@_reg
def l2_normalization(data, eps=1e-10, mode='instance'):
    return L2Normalization(data, eps=eps, mode=mode)


@_reg
def InstanceNorm(data, gamma, beta, eps=1e-3):
    """Per-sample, per-channel normalization over spatial axes
    (ref: src/operator/instance_norm.cc)."""
    axes = tuple(range(2, data.ndim))
    mean = data.mean(axis=axes, keepdims=True)
    var = data.var(axis=axes, keepdims=True)
    xhat = (data - mean) / jnp.sqrt(var + eps)
    shape = (1, -1) + (1,) * (data.ndim - 2)
    return xhat * gamma.reshape(shape) + beta.reshape(shape)


@jax.custom_vjp
def _make_loss_p(data, grad_scale):
    return data


def _make_loss_fwd(data, grad_scale):
    return data, grad_scale


def _make_loss_bwd(grad_scale, ct):
    # loss op: gradient is grad_scale regardless of the head gradient
    # (ref: src/operator/make_loss.cc MakeLossGrad)
    return (jnp.broadcast_to(grad_scale, ct.shape).astype(ct.dtype), None)


_make_loss_p.defvjp(_make_loss_fwd, _make_loss_bwd)


@_reg
def MakeLoss(data, grad_scale=1.0, valid_thresh=0.0, normalization='null'):
    """Mark an output as a loss: identity forward, constant grad_scale
    backward (ref: src/operator/make_loss.cc)."""
    scale = grad_scale
    if normalization == 'batch':
        scale = scale / data.shape[0]
    elif normalization == 'valid':
        scale = scale / jnp.maximum(
            (data > valid_thresh).sum().astype(data.dtype), 1.0)
    return _make_loss_p(data, jnp.asarray(scale, data.dtype))


@_reg
def make_loss(data, grad_scale=1.0, valid_thresh=0.0, normalization='null'):
    return MakeLoss(data, grad_scale, valid_thresh, normalization)


# ignore_label/use_ignore/multi_output are static config, not primals:
# as nondiff_argnums they stay python values under jit/vjp (a traced
# bool here raised TracerBoolConversionError on the inference path)
@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _softmax_output_p(data, label, grad_scale, ignore_label, use_ignore,
                      multi_output):
    return _softmax_fwd(data, multi_output)


def _softmax_fwd(data, multi_output):
    axis = 1 if multi_output and data.ndim > 2 else -1
    return jax.nn.softmax(data, axis=axis)


def _softmax_output_fwd(data, label, grad_scale, ignore_label, use_ignore,
                        multi_output):
    out = _softmax_fwd(data, multi_output)
    return out, (out, label, grad_scale)


def _softmax_output_bwd(ignore_label, use_ignore, multi_output, res, ct):
    out, label, grad_scale = res
    # gradient = (softmax - onehot(label)) * scale, head grad ignored
    # (ref: src/operator/softmax_output.cc SoftmaxOutputGrad)
    axis = 1 if multi_output and out.ndim > 2 else -1
    n_cls = out.shape[axis]
    lab = label.astype(jnp.int32)
    onehot = jax.nn.one_hot(lab, n_cls, dtype=out.dtype)
    if axis == 1 and out.ndim > 2:
        onehot = jnp.moveaxis(onehot, -1, 1)
    g = (out - onehot) * grad_scale
    if use_ignore:
        mask = (lab != ignore_label)
        if axis == 1 and out.ndim > 2:
            mask = mask[:, None]
        else:
            mask = mask[..., None]
        g = g * mask.astype(out.dtype)
    return (g, None, None)


_softmax_output_p.defvjp(_softmax_output_fwd, _softmax_output_bwd)


@_reg
def SoftmaxOutput(data, label, grad_scale=1.0, ignore_label=-1,
                  use_ignore=False, multi_output=False,
                  normalization='null', **kwargs):
    """Legacy softmax + cross-entropy-gradient output op
    (ref: src/operator/softmax_output.cc)."""
    scale = grad_scale
    if normalization == 'batch':
        scale = scale / data.shape[0]
    return _softmax_output_p(data, label, jnp.asarray(scale, data.dtype),
                             ignore_label, bool(use_ignore),
                             bool(multi_output))


@_reg
def softmax_output(data, label, **kwargs):
    return SoftmaxOutput(data, label, **kwargs)


@_reg
def SliceChannel(data, num_outputs, axis=1, squeeze_axis=False):
    """Split along an axis into num_outputs parts
    (ref: src/operator/slice_channel.cc)."""
    parts = jnp.split(data, num_outputs, axis=axis)
    if squeeze_axis:
        parts = [p.squeeze(axis) for p in parts]
    return tuple(parts)


@_reg
def slice_channel(data, num_outputs, axis=1, squeeze_axis=False):
    return SliceChannel(data, num_outputs, axis=axis,
                        squeeze_axis=squeeze_axis)
