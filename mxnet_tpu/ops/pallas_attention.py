"""Pallas flash-attention kernel for TPU.

The fused MHA op (ops/attention.py multi_head_attention) routes here. This
is the TPU-native realisation of the reference's interleaved_matmul
attention kernels (ref: src/operator/contrib/transformer.cc:650-828): one
hand-written kernel instead of two batched-GEMM ops, with the T×T score
matrix living only in VMEM.

Layout: grid (B*H, Tq/BQ, Tk/BK), k-block dimension innermost. Scratch
(VMEM) carries the online-softmax state (running max m, running sum l,
f32 accumulator) across k-blocks; the final k-block normalises and writes
the output block plus the logsumexp (saved for the backward pass).

The backward is a blockwise lax.scan over k-blocks using the saved LSE —
same O(T) memory behavior, XLA-fused matmuls on the MXU.

`flash_attention(..., interpret=True)` runs the identical kernel through
the Pallas interpreter so CPU tests exercise the real kernel code.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

try:
    from jax.experimental.pallas import tpu as pltpu
    _HAS_PLTPU = True
except Exception:  # pragma: no cover
    pltpu = None
    _HAS_PLTPU = False

_NEG_INF = -1e30


def pallas_available() -> bool:
    if not _HAS_PLTPU:
        return False
    try:
        return any(d.platform == 'tpu' for d in jax.devices())
    except Exception:
        return False


def _block_sizes(Tq, Tk, D, dtype):
    """Pick MXU/VPU-aligned block sizes. Sublane minimum is 8 (f32) /
    16 (bf16); lanes are 128."""
    min_sub = 16 if dtype == jnp.bfloat16 else 8
    bq = max(min_sub, min(128, Tq))
    bk = max(min_sub, min(512, Tk))
    return bq, bk


def _fa_kernel(q_ref, k_ref, v_ref, kmask_ref, o_ref, lse_ref,
               acc_ref, m_ref, l_ref, *, scale, causal, bq, bk,
               q_len, k_len):
    """One (q-block, k-block) cell. Refs are VMEM blocks:
    q (1, bq, D), k/v (1, bk, D), kmask (1, bk) additive f32,
    o (1, bq, D), lse (1, bq); scratch acc (bq, D) f32, m/l (bq, 128)."""
    kb = pl.program_id(2)
    nkb = pl.num_programs(2)

    @pl.when(kb == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    q = q_ref[0]                                     # (bq, D)
    k = k_ref[0]                                     # (bk, D)
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * scale  # (bq, bk)

    # key-side validity: padding beyond k_len + user key mask
    k_pos = kb * bk + jax.lax.broadcasted_iota(jnp.int32, (1, bk), 1)
    s = jnp.where(k_pos < k_len, s, _NEG_INF)
    if kmask_ref is not None:
        s = s + kmask_ref[0][None, :]
    if causal:
        q_pos = pl.program_id(1) * bq + jax.lax.broadcasted_iota(
            jnp.int32, (bq, 1), 0)
        s = jnp.where(q_pos >= k_pos, s, _NEG_INF)

    m_prev = m_ref[:, :1]                            # (bq, 1)
    l_prev = l_ref[:, :1]
    m_cur = jnp.max(s, axis=-1, keepdims=True)
    m_new = jnp.maximum(m_prev, m_cur)
    p = jnp.exp(s - m_new)                           # (bq, bk) f32
    alpha = jnp.exp(m_prev - m_new)                  # (bq, 1)
    l_new = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
    acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
        p.astype(v_ref.dtype), v_ref[0], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
    l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(kb == nkb - 1)
    def _finalize():
        l = l_ref[:, :1]
        safe_l = jnp.maximum(l, 1e-30)
        o_ref[0] = (acc_ref[:] / safe_l).astype(o_ref.dtype)
        lse_ref[0] = (m_ref[:, :1] + jnp.log(safe_l))[:, 0]


def _fa_forward(q, k, v, kmask, causal, interpret):
    """q/k/v: (BH, T, D) flattened over batch*heads.
    kmask: (BH, Tk) additive f32 or None. Returns (out, lse)."""
    BH, Tq, D = q.shape
    Tk = k.shape[1]
    scale = 1.0 / math.sqrt(D)
    bq, bk = _block_sizes(Tq, Tk, D, q.dtype)
    nq, nk = pl.cdiv(Tq, bq), pl.cdiv(Tk, bk)
    pq, pk = nq * bq - Tq, nk * bk - Tk
    if pq:
        q = jnp.pad(q, ((0, 0), (0, pq), (0, 0)))
    if pk:
        k = jnp.pad(k, ((0, 0), (0, pk), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, pk), (0, 0)))
        if kmask is not None:
            kmask = jnp.pad(kmask, ((0, 0), (0, pk)))

    kernel = functools.partial(
        _fa_kernel, scale=scale, causal=causal, bq=bq, bk=bk,
        q_len=Tq, k_len=Tk)
    in_specs = [
        pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0)),
        pl.BlockSpec((1, bk, D), lambda b, i, j: (b, j, 0)),
        pl.BlockSpec((1, bk, D), lambda b, i, j: (b, j, 0)),
    ]
    args = [q, k, v]
    if kmask is not None:
        in_specs.append(pl.BlockSpec((1, bk), lambda b, i, j: (b, j)))
        args.append(kmask.astype(jnp.float32))
        krn = kernel
    else:
        krn = functools.partial(_wrap_no_mask, kernel)
    scratch = [pltpu.VMEM((bq, D), jnp.float32),
               pltpu.VMEM((bq, 128), jnp.float32),
               pltpu.VMEM((bq, 128), jnp.float32)]
    out, lse = pl.pallas_call(
        krn,
        grid=(BH, nq, nk),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bq), lambda b, i, j: (b, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, nq * bq, D), q.dtype),
            jax.ShapeDtypeStruct((BH, nq * bq), jnp.float32),
        ],
        scratch_shapes=scratch,
        interpret=interpret,
    )(*args)
    if pq:
        out = out[:, :Tq]
        lse = lse[:, :Tq]
    return out, lse


def _wrap_no_mask(kernel, q_ref, k_ref, v_ref, o_ref, lse_ref,
                  acc_ref, m_ref, l_ref):
    kernel(q_ref, k_ref, v_ref, None, o_ref, lse_ref,
           acc_ref, m_ref, l_ref)


def _fa_backward(q, k, v, kmask, causal, out, lse, do):
    """Blockwise backward over k-blocks using the saved LSE (flash
    attention backward recurrence); O(T) live memory, MXU matmuls."""
    BH, Tq, D = q.shape
    Tk = k.shape[1]
    scale = 1.0 / math.sqrt(D)
    bk = max(8, min(512, Tk))
    nk = (Tk + bk - 1) // bk
    pk = nk * bk - Tk
    if pk:
        k = jnp.pad(k, ((0, 0), (0, pk), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, pk), (0, 0)))
        if kmask is not None:
            kmask = jnp.pad(kmask, ((0, 0), (0, pk)),
                            constant_values=_NEG_INF)
    q32, do32 = q.astype(jnp.float32), do.astype(jnp.float32)
    delta = jnp.sum(do32 * out.astype(jnp.float32), axis=-1)  # (BH, Tq)
    kb = k.reshape(BH, nk, bk, D).transpose(1, 0, 2, 3)
    vb = v.reshape(BH, nk, bk, D).transpose(1, 0, 2, 3)
    mb = (kmask.reshape(BH, nk, bk).transpose(1, 0, 2)
          if kmask is not None else None)
    q_pos = jnp.arange(Tq)

    def body(dq_acc, blk):
        idx, k_cur, v_cur, m_cur = blk
        s = jnp.einsum('bqd,bkd->bqk', q32, k_cur.astype(jnp.float32),
                       preferred_element_type=jnp.float32) * scale
        k_pos = idx * bk + jnp.arange(bk)
        s = jnp.where((k_pos < Tk)[None, None, :], s, _NEG_INF)
        if m_cur is not None:
            s = s + m_cur[:, None, :]
        if causal:
            s = jnp.where(q_pos[None, :, None] >= k_pos[None, None, :],
                          s, _NEG_INF)
        p = jnp.exp(s - lse[:, :, None])                     # (BH, Tq, bk)
        dv = jnp.einsum('bqk,bqd->bkd', p, do32,
                        preferred_element_type=jnp.float32)
        dp = jnp.einsum('bqd,bkd->bqk', do32, v_cur.astype(jnp.float32),
                        preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, :, None]) * scale
        dq_acc = dq_acc + jnp.einsum('bqk,bkd->bqd', ds,
                                     k_cur.astype(jnp.float32),
                                     preferred_element_type=jnp.float32)
        dk = jnp.einsum('bqk,bqd->bkd', ds, q32,
                        preferred_element_type=jnp.float32)
        return dq_acc, (dk, dv)

    idxs = jnp.arange(nk)
    blks = (idxs, kb, vb) if mb is None else (idxs, kb, vb, mb)

    def scan_body(dq_acc, xs):
        if mb is None:
            i, kc, vc = xs
            return body(dq_acc, (i, kc, vc, None))
        i, kc, vc, mc = xs
        return body(dq_acc, (i, kc, vc, mc))

    dq, (dks, dvs) = lax.scan(scan_body, jnp.zeros_like(q32), blks)
    dk = dks.transpose(1, 0, 2, 3).reshape(BH, nk * bk, D)[:, :Tk]
    dv = dvs.transpose(1, 0, 2, 3).reshape(BH, nk * bk, D)[:, :Tk]
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5))
def _flash(q, k, v, kmask, causal, interpret):
    out, _ = _fa_forward(q, k, v, kmask, causal, interpret)
    return out


def _flash_fwd(q, k, v, kmask, causal, interpret):
    out, lse = _fa_forward(q, k, v, kmask, causal, interpret)
    return out, (q, k, v, kmask, out, lse)


def _flash_bwd(causal, interpret, res, do):
    q, k, v, kmask, out, lse = res
    dq, dk, dv = _fa_backward(q, k, v, kmask, causal, out, lse, do)
    dmask = None if kmask is None else jnp.zeros_like(kmask)
    return dq, dk, dv, dmask


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q, k, v, key_mask=None, causal=False, block_k=None,
                    interpret=False):
    """Flash attention. q/k/v: (B, H, T, D). key_mask: optional (B, Tk)
    additive f32 mask (0 = keep, large-negative = drop) or boolean
    (True = keep). Returns (B, H, Tq, D).

    On TPU this is a Pallas kernel (VMEM online softmax); on CPU backends
    the same kernel runs through the Pallas interpreter (tests exercise
    the real kernel code)."""
    if not interpret:
        try:
            interpret = jax.default_backend() == 'cpu'
        except Exception:
            interpret = True
    B, H, Tq, D = q.shape
    Tk = k.shape[2]
    qf = q.reshape(B * H, Tq, D)
    kf = k.reshape(B * H, Tk, D)
    vf = v.reshape(B * H, Tk, D)
    km = None
    if key_mask is not None:
        if key_mask.dtype == jnp.bool_:
            key_mask = jnp.where(key_mask, 0.0, _NEG_INF)
        key_mask = key_mask.astype(jnp.float32)
        if key_mask.shape[0] == B * H:
            km = key_mask
        elif key_mask.shape[0] == B:
            km = jnp.broadcast_to(key_mask[:, None, :],
                                  (B, H, Tk)).reshape(B * H, Tk)
        else:
            raise ValueError(
                f"key_mask leading dim {key_mask.shape[0]} matches neither "
                f"batch {B} nor batch*heads {B * H}")
    out = _flash(qf, kf, vf, km, causal, interpret)
    return out.reshape(B, H, Tq, D)
