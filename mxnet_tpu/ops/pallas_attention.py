"""Pallas flash-attention kernels for TPU (forward AND backward).

The fused MHA op (ops/attention.py multi_head_attention) routes here. This
is the TPU-native realisation of the reference's interleaved_matmul
attention kernels (ref: src/operator/contrib/transformer.cc:650-828): one
hand-written kernel instead of two batched-GEMM ops, with the T×T score
matrix living only in VMEM.

Forward: grid (B*H/G, Tq/BQ, Tk/BK) — each invocation processes G
batch·head slices (per-invocation overhead on the TPU is tens of
microseconds, so tiny per-head grids are dispatch-bound; G amortises it).
Scratch (VMEM) carries the online-softmax state (running max m, running
sum l, f32 accumulator) across k-blocks; the final k-block normalises and
writes the output block plus the logsumexp (saved for the backward pass).

Backward: two Pallas kernels — dq (grid (BH/G, Tq/BQ, Tk/BK), accumulating
over k-blocks) and dk/dv (grid (BH/G, Tk/BK, Tq/BQ), accumulating over
q-blocks) — both recompute the probability block from the saved LSE
(flash-attention backward recurrence), so live memory stays O(T).

Attention dropout runs INSIDE the kernels: the keep mask is a
counter-based hash (murmur3 finalizer) of the global (batch·head, q, k)
element coordinates mixed with a per-call seed, so the forward and both
backward kernels regenerate bit-identical masks with no T×T tensor ever
materialised, and the same bits fall out in Mosaic and interpreter modes.
Softmax statistics (m, l) are computed on the UNdropped probabilities —
dropout scales only the value accumulation — matching the standard
softmax→dropout→matmul recipe.

Mosaic layout constraints honoured throughout: every block's trailing two
dims are (multiple-of-8, multiple-of-128) or equal to the array dims —
the key-mask rides as (BH, 1, Tk) with (G, 1, bk) blocks and the LSE as
(BH, Tq, 1) with (G, bq, 1) blocks (round 3's compile failure was a
(1, bk) 2-D mask block).

`flash_attention(..., interpret=True)` runs the identical kernels through
the Pallas interpreter so CPU tests exercise the real kernel code.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
import numpy as onp
from jax import lax
from jax.experimental import pallas as pl

try:
    from jax.experimental.pallas import tpu as pltpu
    _HAS_PLTPU = True
except Exception:  # pragma: no cover
    pltpu = None
    _HAS_PLTPU = False

_NEG_INF = -1e30


def pallas_available() -> bool:
    if not _HAS_PLTPU:
        return False
    try:
        return any(d.platform == 'tpu' for d in jax.devices())
    except Exception:
        return False


def _compiler_params():
    if pltpu is None:
        return {}
    try:
        return {'compiler_params': pltpu.CompilerParams(
            dimension_semantics=('parallel', 'parallel', 'arbitrary'))}
    except Exception:  # pragma: no cover - older pallas API
        return {}


def _block_sizes(BH, Tq, Tk, D, dtype, kind='fwd'):
    """(G, bq, bk): head-group size and MXU/VPU-aligned seq blocks.
    Sublane minimum is 8 (f32) / 16 (bf16); lanes are 128. G amortises
    the per-invocation kernel overhead over several batch·head slices.

    kind='bwd' sizes the backward kernels, whose per-cell stack holds
    ~6 live (bq, bk) f32 temporaries (s, p, dp, ds, keep, pv) vs the
    forward's ~3 — at (512, 512) blocks that alone is 6MB and the dk/dv
    kernel blows Mosaic's 16MB scoped-VMEM stack limit, so backward
    defaults to 256-wide blocks.

    The defaults computed here are only the LAST rung of the ISSUE 18
    precedence ladder, applied by ops/autotune.resolve: explicit env
    override (registered MXTPU_FA_{G,BQ,BK} / MXTPU_FA_BWD_* knobs) >
    tuning-DB winner (MXTPU_AUTOTUNE_DIR, keyed by device kind +
    shape signature) > these defaults — with the divisor/VMEM clamps
    applied to whatever won, and the decision recorded for the
    compile-ledger signature."""
    min_sub = 16 if dtype == jnp.bfloat16 else 8
    cap = 512 if kind == 'fwd' else 256
    bq = max(min_sub, min(cap, Tq))
    bk = max(min_sub, min(cap, Tk))
    G = 1
    for cand in (4, 8, 2):    # 4 measured best on v5e at BERT-base shape
        if BH % cand == 0:
            G = cand
            break
    from . import autotune
    return autotune.resolve(autotune.KERNEL_FA, BH, Tq, Tk, D,
                            jnp.dtype(dtype), kind, default=(G, bq, bk))


# ---------------------------------------------------------------------------
# portable counter-based dropout bits
# ---------------------------------------------------------------------------

def _dropout_keep(seed, bh, q_base, k_base, bq, bk, rate):
    """(bq, bk) float32 keep/(1-rate) multiplier for one attention block.

    Hash of (seed, global element id) through the murmur3 finalizer.
    uint32 arithmetic wraps identically in Mosaic, XLA and the Pallas
    interpreter, so forward and backward kernels regenerate the same
    mask from coordinates alone — grid iteration order is irrelevant,
    and the row mixing uses a CONSTANT odd multiplier (not the padded
    key length) so the backward kernels may tile the sequence
    differently from the forward and still reproduce bit-identical
    masks. The odd multiplier is a bijection on uint32, so no two rows
    ever share a whole mask row (a power-of-two stride would duplicate
    rows every 2^32/stride queries)."""
    rows = q_base + lax.broadcasted_iota(jnp.uint32, (bq, bk), 0)
    cols = k_base + lax.broadcasted_iota(jnp.uint32, (bq, bk), 1)
    return _counter_keep(seed, bh.astype(jnp.uint32), rows, cols, rate)


def _counter_keep(seed, bh, rows, cols, rate):
    """The shared hash core: keep/(1-rate) multipliers from broadcastable
    uint32 (bh, rows, cols) index arrays. Used by the Pallas kernels via
    _dropout_keep and by ring attention (parallel/ring_attention.py) with
    GLOBAL sequence positions, so both regenerate identical masks from
    coordinates alone."""
    h = rows * jnp.uint32(0x9E3779B1) + cols
    h = h + bh * jnp.uint32(0x9e3779b9)
    h = h ^ seed
    h = h ^ (h >> jnp.uint32(16))
    h = h * jnp.uint32(0x85ebca6b)
    h = h ^ (h >> jnp.uint32(13))
    h = h * jnp.uint32(0xc2b2ae35)
    h = h ^ (h >> jnp.uint32(16))
    thresh = jnp.uint32(min(int(rate * 2.0**32), 2**32 - 1))
    keep = (h >= thresh).astype(jnp.float32)
    return keep * jnp.float32(1.0 / (1.0 - rate))


def _masked_scores(q, k, kmask_row, qb, kb, bq, bk, scale, causal, k_len):
    """(bq, bk) f32 scores for one (q-block, k-block) cell of one head:
    QK^T * scale, key-padding cut at k_len, additive user mask, causal."""
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * scale
    k_pos = kb * bk + jax.lax.broadcasted_iota(jnp.int32, (1, bk), 1)
    s = jnp.where(k_pos < k_len, s, _NEG_INF)
    s = s + kmask_row
    if causal:
        q_pos = qb * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, 1), 0)
        s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
    return s


# ---------------------------------------------------------------------------
# forward kernel
# ---------------------------------------------------------------------------

def _fa_fwd_kernel(q_ref, k_ref, v_ref, kmask_ref, seed_ref,
                   o_ref, lse_ref, acc_ref, m_ref, l_ref, *,
                   scale, causal, G, bq, bk, k_len, dropout_p):
    """One (head-group, q-block, k-block) cell. Refs are VMEM blocks:
    q (G, bq, D), k/v (G, bk, D), kmask (G, 1, bk) additive f32,
    seed (1, 1) uint32, o (G, bq, D), lse (G, bq, 1);
    scratch acc (G, bq, D) f32, m/l (G, bq, 128) f32."""
    qb = pl.program_id(1)
    kb = pl.program_id(2)
    nkb = pl.num_programs(2)

    @pl.when(kb == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    for g in range(G):
        s = _masked_scores(q_ref[g], k_ref[g], kmask_ref[g], qb, kb,
                           bq, bk, scale, causal, k_len)
        m_prev = m_ref[g, :, :1]                         # (bq, 1)
        l_prev = l_ref[g, :, :1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)                           # (bq, bk) f32
        alpha = jnp.exp(m_prev - m_new)                  # (bq, 1)
        l_new = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
        if dropout_p > 0.0:
            bh = pl.program_id(0) * G + g
            keep = _dropout_keep(seed_ref[0, 0], jnp.uint32(bh),
                                 jnp.uint32(qb * bq), jnp.uint32(kb * bk),
                                 bq, bk, dropout_p)
            pv = p * keep
        else:
            pv = p
        acc_ref[g] = acc_ref[g] * alpha + jax.lax.dot_general(
            pv.astype(v_ref.dtype), v_ref[g], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[g] = jnp.broadcast_to(m_new, m_ref.shape[1:])
        l_ref[g] = jnp.broadcast_to(l_new, l_ref.shape[1:])

    @pl.when(kb == nkb - 1)
    def _finalize():
        for g in range(G):
            l = l_ref[g, :, :1]
            safe_l = jnp.maximum(l, 1e-30)
            o_ref[g] = (acc_ref[g] / safe_l).astype(o_ref.dtype)
            lse_ref[g] = m_ref[g, :, :1] + jnp.log(safe_l)


def _fa_forward(q, k, v, kmask, seed, causal, dropout_p, interpret):
    """q/k/v: (BH, T, D) flattened over batch*heads.
    kmask: (BH, Tk) additive f32 or None. seed: (1, 1) uint32.
    Returns (out, lse), both sliced back to (BH, Tq[, D]) — the backward
    re-pads them for its own (possibly different) tiling."""
    BH, Tq, D = q.shape
    Tk = k.shape[1]
    scale = 1.0 / math.sqrt(D)
    G, bq, bk = _block_sizes(BH, Tq, Tk, D, q.dtype)
    nq, nk = pl.cdiv(Tq, bq), pl.cdiv(Tk, bk)
    pq, pk = nq * bq - Tq, nk * bk - Tk
    if pq:
        q = jnp.pad(q, ((0, 0), (0, pq), (0, 0)))
    if pk:
        k = jnp.pad(k, ((0, 0), (0, pk), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, pk), (0, 0)))
        if kmask is not None:
            kmask = jnp.pad(kmask, ((0, 0), (0, pk)))
    tk_pad = nk * bk
    if kmask is None:
        km3 = jnp.zeros((BH, 1, tk_pad), jnp.float32)
    else:
        km3 = kmask.astype(jnp.float32).reshape(BH, 1, tk_pad)

    kernel = functools.partial(
        _fa_fwd_kernel, scale=scale, causal=causal, G=G, bq=bq, bk=bk,
        k_len=Tk, dropout_p=float(dropout_p))
    out, lse = pl.pallas_call(
        kernel,
        grid=(BH // G, nq, nk),
        in_specs=[
            pl.BlockSpec((G, bq, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((G, bk, D), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((G, bk, D), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((G, 1, bk), lambda b, i, j: (b, 0, j)),
            pl.BlockSpec((1, 1), lambda b, i, j: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((G, bq, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((G, bq, 1), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, nq * bq, D), q.dtype),
            jax.ShapeDtypeStruct((BH, nq * bq, 1), jnp.float32),
        ],
        scratch_shapes=[pltpu.VMEM((G, bq, D), jnp.float32),
                        pltpu.VMEM((G, bq, 128), jnp.float32),
                        pltpu.VMEM((G, bq, 128), jnp.float32)],
        interpret=interpret,
        **_compiler_params(),
    )(q, k, v, km3, seed)
    lse = lse[..., 0]
    if pq:
        out = out[:, :Tq]
        lse = lse[:, :Tq]
    return out, lse


# ---------------------------------------------------------------------------
# backward kernels
# ---------------------------------------------------------------------------

def _fa_dq_kernel(q_ref, k_ref, v_ref, kmask_ref, seed_ref, do_ref,
                  lse_ref, delta_ref, dq_ref, dq_acc, *,
                  scale, causal, G, bq, bk, k_len, dropout_p):
    """dq for one q-block, accumulated over k-blocks (grid (BH/G, nq, nk))."""
    qb = pl.program_id(1)
    kb = pl.program_id(2)
    nkb = pl.num_programs(2)

    @pl.when(kb == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    for g in range(G):
        s = _masked_scores(q_ref[g], k_ref[g], kmask_ref[g], qb, kb,
                           bq, bk, scale, causal, k_len)
        p = jnp.exp(s - lse_ref[g])                   # (bq, bk), lse (bq,1)
        dp = jax.lax.dot_general(
            do_ref[g].astype(jnp.float32), v_ref[g].astype(jnp.float32),
            (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)       # (bq, bk)
        if dropout_p > 0.0:
            bh = pl.program_id(0) * G + g
            keep = _dropout_keep(seed_ref[0, 0], jnp.uint32(bh),
                                 jnp.uint32(qb * bq), jnp.uint32(kb * bk),
                                 bq, bk, dropout_p)
            dp = dp * keep
        ds = p * (dp - delta_ref[g]) * scale          # (bq, bk)
        dq_acc[g] = dq_acc[g] + jax.lax.dot_general(
            ds, k_ref[g].astype(jnp.float32), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(kb == nkb - 1)
    def _finalize():
        dq_ref[:] = dq_acc[:]


def _fa_dkv_kernel(q_ref, k_ref, v_ref, kmask_ref, seed_ref, do_ref,
                   lse_ref, delta_ref, dk_ref, dv_ref, dk_acc, dv_acc, *,
                   scale, causal, G, bq, bk, k_len, dropout_p):
    """dk/dv for one k-block, accumulated over q-blocks
    (grid (BH/G, nk, nq): k-block is program 1, q-block is program 2)."""
    kb = pl.program_id(1)
    qb = pl.program_id(2)
    nqb = pl.num_programs(2)

    @pl.when(qb == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    for g in range(G):
        s = _masked_scores(q_ref[g], k_ref[g], kmask_ref[g], qb, kb,
                           bq, bk, scale, causal, k_len)
        p = jnp.exp(s - lse_ref[g])                   # (bq, bk)
        do32 = do_ref[g].astype(jnp.float32)          # (bq, D)
        if dropout_p > 0.0:
            bh = pl.program_id(0) * G + g
            keep = _dropout_keep(seed_ref[0, 0], jnp.uint32(bh),
                                 jnp.uint32(qb * bq), jnp.uint32(kb * bk),
                                 bq, bk, dropout_p)
            pv = p * keep
        else:
            keep = None
            pv = p
        # dv_j += sum_i P_drop_ij dO_i
        dv_acc[g] = dv_acc[g] + jax.lax.dot_general(
            pv, do32, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)       # (bk, D)
        dp = jax.lax.dot_general(
            do32, v_ref[g].astype(jnp.float32), (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)       # (bq, bk)
        if keep is not None:
            dp = dp * keep
        ds = p * (dp - delta_ref[g]) * scale          # (bq, bk)
        dk_acc[g] = dk_acc[g] + jax.lax.dot_general(
            ds, q_ref[g].astype(jnp.float32), (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)       # (bk, D)

    @pl.when(qb == nqb - 1)
    def _finalize():
        dk_ref[:] = dk_acc[:]
        dv_ref[:] = dv_acc[:]


def _fa_backward(q, k, v, kmask, seed, causal, dropout_p, interpret,
                 out, lse, do):
    """Pallas backward: recompute probability blocks from the saved LSE.
    Returns (dq, dk, dv) in the input dtypes."""
    BH, Tq, D = q.shape
    Tk = k.shape[1]
    scale = 1.0 / math.sqrt(D)
    G, bq, bk = _block_sizes(BH, Tq, Tk, D, q.dtype, kind='bwd')
    nq, nk = pl.cdiv(Tq, bq), pl.cdiv(Tk, bk)
    pq, pk = nq * bq - Tq, nk * bk - Tk
    if pq:
        # padded q rows contribute nothing: their dO is zero, so dv += p·0
        # and ds = p·(0 - 0) vanish; lse pads as 0 harmlessly
        q = jnp.pad(q, ((0, 0), (0, pq), (0, 0)))
        do = jnp.pad(do, ((0, 0), (0, pq), (0, 0)))
        out = jnp.pad(out, ((0, 0), (0, pq), (0, 0)))
        lse = jnp.pad(lse, ((0, 0), (0, pq)))
    if pk:
        k = jnp.pad(k, ((0, 0), (0, pk), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, pk), (0, 0)))
        if kmask is not None:
            kmask = jnp.pad(kmask, ((0, 0), (0, pk)))
    tk_pad = nk * bk
    if kmask is None:
        km3 = jnp.zeros((BH, 1, tk_pad), jnp.float32)
    else:
        km3 = kmask.astype(jnp.float32).reshape(BH, 1, tk_pad)

    # delta_i = dO_i · O_i (rowwise) — cheap XLA preprocessing
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1, keepdims=True)            # (BH, Tq_pad, 1)
    lse3 = lse.reshape(BH, nq * bq, 1)

    kw = dict(scale=scale, causal=causal, G=G, bq=bq, bk=bk, k_len=Tk,
              dropout_p=float(dropout_p))
    qspec_i = pl.BlockSpec((G, bq, D), lambda b, i, j: (b, i, 0))
    kspec_j = pl.BlockSpec((G, bk, D), lambda b, i, j: (b, j, 0))
    col1_i = pl.BlockSpec((G, bq, 1), lambda b, i, j: (b, i, 0))
    mspec_j = pl.BlockSpec((G, 1, bk), lambda b, i, j: (b, 0, j))
    sspec = pl.BlockSpec((1, 1), lambda b, i, j: (0, 0))

    dq = pl.pallas_call(
        functools.partial(_fa_dq_kernel, **kw),
        grid=(BH // G, nq, nk),
        in_specs=[qspec_i, kspec_j, kspec_j, mspec_j, sspec,
                  qspec_i, col1_i, col1_i],
        out_specs=pl.BlockSpec((G, bq, D), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((BH, nq * bq, D), jnp.float32),
        scratch_shapes=[pltpu.VMEM((G, bq, D), jnp.float32)],
        interpret=interpret,
        **_compiler_params(),
    )(q, k, v, km3, seed, do, lse3, delta)

    # dk/dv grid permutes (q-block, k-block): q innermost
    qspec_2 = pl.BlockSpec((G, bq, D), lambda b, j, i: (b, i, 0))
    kspec_1 = pl.BlockSpec((G, bk, D), lambda b, j, i: (b, j, 0))
    col1_2 = pl.BlockSpec((G, bq, 1), lambda b, j, i: (b, i, 0))
    mspec_1 = pl.BlockSpec((G, 1, bk), lambda b, j, i: (b, 0, j))
    dk, dv = pl.pallas_call(
        functools.partial(_fa_dkv_kernel, **kw),
        grid=(BH // G, nk, nq),
        in_specs=[qspec_2, kspec_1, kspec_1, mspec_1, sspec,
                  qspec_2, col1_2, col1_2],
        out_specs=[pl.BlockSpec((G, bk, D), lambda b, j, i: (b, j, 0)),
                   pl.BlockSpec((G, bk, D), lambda b, j, i: (b, j, 0))],
        out_shape=[jax.ShapeDtypeStruct((BH, nk * bk, D), jnp.float32),
                   jax.ShapeDtypeStruct((BH, nk * bk, D), jnp.float32)],
        scratch_shapes=[pltpu.VMEM((G, bk, D), jnp.float32),
                        pltpu.VMEM((G, bk, D), jnp.float32)],
        interpret=interpret,
        **_compiler_params(),
    )(q, k, v, km3, seed, do, lse3, delta)

    dq = dq[:, :Tq].astype(q.dtype)
    dk = dk[:, :Tk].astype(k.dtype)
    dv = dv[:, :Tk].astype(v.dtype)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# custom-vjp wrapper
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7))
def _flash(q, k, v, kmask, seed, causal, dropout_p, interpret):
    out, _ = _fa_forward(q, k, v, kmask, seed, causal, dropout_p, interpret)
    return out


def _flash_fwd(q, k, v, kmask, seed, causal, dropout_p, interpret):
    out, lse = _fa_forward(q, k, v, kmask, seed, causal, dropout_p,
                           interpret)
    return out, (q, k, v, kmask, seed, out, lse)


def _flash_bwd(causal, dropout_p, interpret, res, do):
    q, k, v, kmask, seed, out, lse = res
    dq, dk, dv = _fa_backward(q, k, v, kmask, seed, causal, dropout_p,
                              interpret, out, lse, do)
    dmask = None if kmask is None else jnp.zeros_like(kmask)
    dseed = onp.zeros((1, 1), jax.dtypes.float0)
    return dq, dk, dv, dmask, dseed


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q, k, v, key_mask=None, causal=False, dropout_p=0.0,
                    dropout_seed=None, block_k=None, interpret=False):
    """Flash attention. q/k/v: (B, H, T, D). key_mask: optional (B, Tk)
    additive f32 mask (0 = keep, large-negative = drop) or boolean
    (True = keep). dropout_p: in-kernel attention-probability dropout;
    dropout_seed: uint32 scalar/array seeding the kernel PRNG (required
    when dropout_p > 0). Returns (B, H, Tq, D).

    On TPU this is a Pallas kernel (VMEM online softmax, Pallas backward);
    on CPU backends the same kernels run through the Pallas interpreter
    (tests exercise the real kernel code)."""
    if not interpret:
        try:
            interpret = jax.default_backend() == 'cpu'
        except Exception:
            interpret = True
    B, H, Tq, D = q.shape
    Tk = k.shape[2]
    qf = q.reshape(B * H, Tq, D)
    kf = k.reshape(B * H, Tk, D)
    vf = v.reshape(B * H, Tk, D)
    km = None
    if key_mask is not None:
        if key_mask.dtype == jnp.bool_:
            key_mask = jnp.where(key_mask, 0.0, _NEG_INF)
        key_mask = key_mask.astype(jnp.float32)
        if key_mask.shape[0] == B * H:
            km = key_mask
        elif key_mask.shape[0] == B:
            km = jnp.broadcast_to(key_mask[:, None, :],
                                  (B, H, Tk)).reshape(B * H, Tk)
        else:
            raise ValueError(
                f"key_mask leading dim {key_mask.shape[0]} matches neither "
                f"batch {B} nor batch*heads {B * H}")
    dropout_p = float(dropout_p)
    if dropout_p > 0.0 and dropout_seed is None:
        raise ValueError("dropout_p > 0 requires dropout_seed")
    if dropout_seed is None:
        seed = jnp.zeros((1, 1), jnp.uint32)
    else:
        seed = jnp.asarray(dropout_seed, jnp.uint32).reshape(1, 1)
    out = _flash(qf, kf, vf, km, seed, causal, dropout_p, interpret)
    return out.reshape(B, H, Tq, D)
