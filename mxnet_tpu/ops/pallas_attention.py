"""Pallas flash-attention kernel for TPU (placeholder-free entry point).

The fused MHA op (ops/attention.py multi_head_attention) routes here for
long sequences on TPU. `flash_attention` currently delegates to a
blockwise-XLA implementation with online softmax (same memory behavior as
flash attention: no T×T materialisation in HBM thanks to XLA fusion over
the scan); a hand-written Pallas kernel drops in behind the same signature.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax


def pallas_available() -> bool:
    try:
        return any(d.platform not in ('cpu',) for d in jax.devices())
    except Exception:
        return False


@functools.partial(jax.jit, static_argnames=('causal', 'block_k'))
def flash_attention(q, k, v, causal=False, block_k=512):
    """q/k/v: (B, H, T, D). Blockwise attention with online softmax — scans
    over K/V blocks so the T×T score matrix never hits HBM."""
    B, H, Tq, D = q.shape
    Tk = k.shape[2]
    scale = 1.0 / math.sqrt(D)
    block_k = min(block_k, Tk)
    nblocks = (Tk + block_k - 1) // block_k
    pad = nblocks * block_k - Tk
    if pad:
        k = jnp.pad(k, ((0, 0), (0, 0), (0, pad), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, 0), (0, pad), (0, 0)))
    kb = k.reshape(B, H, nblocks, block_k, D).transpose(2, 0, 1, 3, 4)
    vb = v.reshape(B, H, nblocks, block_k, D).transpose(2, 0, 1, 3, 4)

    q32 = q.astype(jnp.bfloat16) if q.dtype == jnp.bfloat16 else q

    def body(carry, kv):
        acc, m_prev, l_prev, blk = carry
        k_cur, v_cur = kv
        scores = jnp.einsum('bhqd,bhkd->bhqk', q32, k_cur,
                            preferred_element_type=jnp.float32) * scale
        k_pos = blk * block_k + jnp.arange(block_k)
        valid = k_pos < Tk
        if causal:
            q_pos = jnp.arange(Tq)
            cmask = q_pos[:, None] >= k_pos[None, :]
            scores = jnp.where(cmask & valid[None, :], scores, -1e30)
        else:
            scores = jnp.where(valid[None, :], scores, -1e30)
        m_cur = jnp.max(scores, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(scores - m_new)
        l_cur = jnp.sum(p, axis=-1, keepdims=True)
        alpha = jnp.exp(m_prev - m_new)
        acc = acc * alpha + jnp.einsum('bhqk,bhkd->bhqd',
                                       p.astype(v_cur.dtype), v_cur)
        l_new = l_prev * alpha + l_cur
        return (acc, m_new, l_new, blk + 1), None

    acc0 = jnp.zeros((B, H, Tq, D), jnp.float32)
    m0 = jnp.full((B, H, Tq, 1), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((B, H, Tq, 1), jnp.float32)
    (acc, m, l, _), _ = lax.scan(body, (acc0, m0, l0, 0), (kb, vb))
    out = acc / jnp.maximum(l, 1e-30)
    return out.astype(q.dtype)
