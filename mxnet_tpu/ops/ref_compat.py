"""Reference-parity ops that close the long tail of the audit.

Round 5's op-name parity audit (tools/extract_ref_ops.py →
tests/fixtures/reference_op_names.txt) surfaced reference-registered ops
with no equivalent here.  This module implements them TPU-natively: each
is a pure jnp/lax function (XLA fuses and tiles), with jax.custom_vjp
where the reference defines a non-autodiff gradient (regression outputs,
KL sparse-reg identity).  Host/numpy is used only for calibration- and
sampling-utility ops the reference also runs on CPU.

Reference anchors are cited per op; no reference code is copied — the
semantics come from the op documentation and well-known formulas.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as onp

from ..base import register_op
from .. import random as _random

__all__ = []


def _reg(fn=None, *, name=None, nograd=False, num_outputs=1,
         mutate_inputs=()):
    def deco(f):
        register_op(name or f.__name__, nograd=nograd,
                    num_outputs=num_outputs, mutate_inputs=mutate_inputs)(f)
        __all__.append(f.__name__)
        return f
    return deco(fn) if fn is not None else deco


# ---------------------------------------------------------------------------
# Small tensor ops (ref: src/operator/tensor/)
# ---------------------------------------------------------------------------

@_reg
def stop_gradient(data):
    """Identity forward, zero gradient (ref: tensor/elemwise_unary_op_basic.cc
    BlockGrad; aliased as `BlockGrad` / `stop_gradient`)."""
    return jax.lax.stop_gradient(data)


@_reg(name='round')
def round_op(data):
    """Round half away from zero, matching the reference's ::round
    (ref: tensor/elemwise_unary_op_basic.cc round) — NOT numpy's
    round-half-to-even (that one is `_npi_around`)."""
    return jnp.sign(data) * jnp.floor(jnp.abs(data) + 0.5)


@_reg
def reshape_like(lhs, rhs, lhs_begin=None, lhs_end=None, rhs_begin=None,
                 rhs_end=None):
    """Reshape lhs to rhs's shape, optionally only over an axis range
    (ref: tensor/elemwise_unary_op_basic.cc reshape_like)."""
    lshape, rshape = list(lhs.shape), list(rhs.shape)
    if lhs_begin is None and lhs_end is None and rhs_begin is None \
            and rhs_end is None:
        return jnp.reshape(lhs, rhs.shape)
    lb = 0 if lhs_begin is None else lhs_begin % (len(lshape) + 1)
    le = len(lshape) if lhs_end is None else lhs_end % (len(lshape) + 1)
    rb = 0 if rhs_begin is None else rhs_begin % (len(rshape) + 1)
    re_ = len(rshape) if rhs_end is None else rhs_end % (len(rshape) + 1)
    new_shape = lshape[:lb] + rshape[rb:re_] + lshape[le:]
    return jnp.reshape(lhs, new_shape)


@_reg
def argmax_channel(data):
    """Argmax over axis 1, float output (ref: tensor/broadcast_reduce_op_index.cc
    argmax_channel)."""
    return jnp.argmax(data, axis=1).astype(data.dtype)


@_reg
def square_sum(data, axis=None, keepdims=False):
    """sum(data**2) along axis — the reference's fused `_square_sum`
    for row_sparse gradients (ref: tensor/square_sum.cc)."""
    return jnp.sum(jnp.square(data), axis=axis, keepdims=keepdims)


@_reg
def identity_with_attr_like_rhs(lhs, rhs):
    """Identity of lhs carrying rhs's storage attrs (ref:
    tensor/elemwise_unary_op_basic.cc _identity_with_attr_like_rhs).
    Storage is uniform dense here, so it reduces to identity."""
    return lhs


@_reg
def split_v2(data, indices=(), axis=0, squeeze_axis=False, sections=0):
    """Split at explicit indices or into equal sections
    (ref: tensor/matrix_op.cc _split_v2)."""
    if sections:
        pieces = jnp.split(data, sections, axis=axis)
    else:
        pieces = jnp.split(data, list(indices), axis=axis)
    if squeeze_axis:
        pieces = [jnp.squeeze(p, axis=axis) for p in pieces]
    return tuple(pieces)


def _normalize_begin_end(shape, begin, end, step=None):
    import builtins
    ndim = len(shape)
    begin = list(begin) + [None] * (ndim - len(begin))
    end = list(end) + [None] * (ndim - len(end))
    step = list(step or []) + [None] * (ndim - len(step or []))
    return tuple(builtins.slice(b, e, s)
                 for b, e, s in zip(begin, end, step))


@_reg
def slice_assign(lhs, rhs, begin=(), end=(), step=None):
    """Return lhs with lhs[begin:end:step] = rhs (ref: tensor/matrix_op.cc
    _slice_assign; functional — the mutable-handle NDArray layer maps
    in-place `x[a:b] = y` onto this)."""
    idx = _normalize_begin_end(lhs.shape, begin, end, step)
    return lhs.at[idx].set(rhs)


@_reg
def slice_assign_scalar(data, scalar=0.0, begin=(), end=(), step=None):
    """Ref: tensor/matrix_op.cc _slice_assign_scalar."""
    idx = _normalize_begin_end(data.shape, begin, end, step)
    return data.at[idx].set(jnp.asarray(scalar, data.dtype))


@_reg
def scatter_set_nd(lhs, rhs, indices, shape=None):
    """lhs with lhs[indices] = rhs — the set-variant of scatter_nd
    (ref: tensor/indexing_op.cc _scatter_set_nd)."""
    idx = tuple(indices[i] for i in range(indices.shape[0]))
    return lhs.at[idx].set(rhs)


# `_scatter_plus_scalar` etc. exist in the reference so that sparse
# gradient flows keep storage type; payloads are dense here, so the
# scatter_* arithmetic collapses to the dense op (documented design:
# ndarray/sparse.py).
@_reg
def scatter_plus_scalar(data, scalar=0.0):
    """Ref: tensor/elemwise_binary_scalar_op_basic.cc _scatter_plus_scalar."""
    return data + jnp.asarray(scalar, data.dtype)


@_reg
def scatter_minus_scalar(data, scalar=0.0):
    """Ref: _scatter_minus_scalar."""
    return data - jnp.asarray(scalar, data.dtype)


@_reg
def scatter_elemwise_div(lhs, rhs):
    """Ref: tensor/elemwise_binary_op_basic.cc _scatter_elemwise_div."""
    return lhs / rhs


# ---------------------------------------------------------------------------
# im2col / col2im (ref: src/operator/nn/im2col.cc)
# ---------------------------------------------------------------------------

def _tuple2(v):
    if v is None:
        return (1, 1)
    if isinstance(v, int):
        return (v, v)
    t = tuple(int(x) for x in v)
    return t * 2 if len(t) == 1 else t


@_reg
def im2col(data, kernel, stride=(1, 1), dilate=(1, 1), pad=(0, 0)):
    """Rearrange NCHW image blocks into columns: (N, C*kh*kw, L)
    (ref: nn/im2col.cc im2col). Lowered with
    conv_general_dilated_patches so XLA tiles it like a conv."""
    kh, kw = _tuple2(kernel)
    sh, sw = _tuple2(stride)
    dh, dw = _tuple2(dilate)
    ph, pw = _tuple2(pad)
    patches = jax.lax.conv_general_dilated_patches(
        data, (kh, kw), (sh, sw), [(ph, ph), (pw, pw)],
        rhs_dilation=(dh, dw),
        dimension_numbers=('NCHW', 'OIHW', 'NCHW'))
    n = data.shape[0]
    return patches.reshape(n, patches.shape[1], -1)


@_reg
def col2im(data, output_size, kernel, stride=(1, 1), dilate=(1, 1),
           pad=(0, 0)):
    """Inverse of im2col: scatter-add columns back into (N, C, H, W)
    (ref: nn/im2col.cc col2im)."""
    kh, kw = _tuple2(kernel)
    sh, sw = _tuple2(stride)
    dh, dw = _tuple2(dilate)
    ph, pw = _tuple2(pad)
    oh, ow = _tuple2(output_size)
    n = data.shape[0]
    c = data.shape[1] // (kh * kw)
    l_h = (oh + 2 * ph - (dh * (kh - 1) + 1)) // sh + 1
    l_w = (ow + 2 * pw - (dw * (kw - 1) + 1)) // sw + 1
    cols = data.reshape(n, c, kh, kw, l_h, l_w)
    out = jnp.zeros((n, c, oh + 2 * ph, ow + 2 * pw), data.dtype)
    # scatter-add each kernel tap's strided window; kh*kw is a static,
    # small trip count so the unrolled loop stays XLA-friendly
    for i in range(kh):
        for j in range(kw):
            out = out.at[:, :, i * dh:i * dh + l_h * sh:sh,
                         j * dw:j * dw + l_w * sw:sw].add(cols[:, :, i, j])
    return out[:, :, ph:ph + oh, pw:pw + ow]


# ---------------------------------------------------------------------------
# linalg long tail (ref: src/operator/tensor/la_op.cc)
# ---------------------------------------------------------------------------

@_reg
def linalg_gelqf(a):
    """LQ factorization A = L·Q with Q orthonormal rows, for m <= n
    (ref: la_op.cc _linalg_gelqf). Lowered via QR of Aᵀ."""
    q, r = jnp.linalg.qr(jnp.swapaxes(a, -1, -2), mode='reduced')
    # normalize so L has a non-negative diagonal (LAPACK convention is
    # sign-free; fixing the sign makes results deterministic/testable).
    # A = L·Q = (L·D)(D·Q) for D = diag(sign(diag(L))), D² = I: scale
    # the COLUMNS of L (rows of r before the transpose) and the rows of
    # Q (columns of q) by the same D so the product is unchanged.
    d = jnp.sign(jnp.diagonal(r, axis1=-2, axis2=-1))
    d = jnp.where(d == 0, 1.0, d).astype(a.dtype)
    l_mat = jnp.swapaxes(r * d[..., :, None], -1, -2)
    q_mat = jnp.swapaxes(q * d[..., None, :], -1, -2)
    return l_mat, q_mat


@_reg
def linalg_syevd(a):
    """Symmetric eigendecomposition A = Uᵀ·diag(L)·U with eigenvectors in
    the ROWS of U, matching the reference's layout
    (ref: la_op.cc _linalg_syevd)."""
    w, v = jnp.linalg.eigh(a)
    return jnp.swapaxes(v, -1, -2), w


@_reg
def linalg_extracttrian(a, offset=0, lower=True):
    """Extract a triangle of each batched square matrix into a packed
    vector (ref: la_op.cc _linalg_extracttrian)."""
    n = a.shape[-1]
    rows, cols = onp.tril_indices(n, k=offset) if lower \
        else onp.triu_indices(n, k=offset)
    return a[..., rows, cols]


@_reg
def linalg_maketrian(a, offset=0, lower=True):
    """Inverse of extracttrian: unpack a vector into a triangular matrix
    (ref: la_op.cc _linalg_maketrian)."""
    k = a.shape[-1]
    # recover n from the packed length (static shape → host-side search)
    n = 1
    while True:
        rows, cols = onp.tril_indices(n, k=offset) if lower \
            else onp.triu_indices(n, k=offset)
        if len(rows) == k:
            break
        if len(rows) > k or n > 16384:
            raise ValueError(
                f"maketrian: packed length {k} does not correspond to a "
                f"triangle with offset {offset}")
        n += 1
    out = jnp.zeros(a.shape[:-1] + (n, n), a.dtype)
    return out.at[..., rows, cols].set(a)


# ---------------------------------------------------------------------------
# Regression outputs (ref: src/operator/regression_output.cc:29-80)
# ---------------------------------------------------------------------------

# The reference's XXXRegressionOutput ops ignore the incoming head
# gradient and write (link(pred) − label)·grad_scale into the backward —
# they are loss layers, not differentiable links. custom_vjp reproduces
# exactly that (ref: regression_output.cc:29-80).

def _regression(link, grad_fn):
    def op(data, label, grad_scale=1.0):
        @jax.custom_vjp
        def core(pred, lab):
            return link(pred)

        def fwd(pred, lab):
            return link(pred), (link(pred), lab)

        def bwd(res, g):
            out, lab = res
            gs = jnp.asarray(grad_scale, out.dtype)
            return grad_fn(out, lab.reshape(out.shape)) * gs, \
                jnp.zeros_like(lab)

        core.defvjp(fwd, bwd)
        return core(data, label.astype(data.dtype))
    return op


_linear_core = _regression(lambda x: x, lambda out, lab: out - lab)
_mae_core = _regression(lambda x: x, lambda out, lab: jnp.sign(out - lab))
_logistic_core = _regression(jax.nn.sigmoid, lambda out, lab: out - lab)


@_reg
def linear_regression_output(data, label, grad_scale=1.0):
    """Identity forward; backward = (pred - label)·grad_scale
    (ref: regression_output.cc LinearRegressionOutput)."""
    return _linear_core(data, label, grad_scale)


@_reg
def mae_regression_output(data, label, grad_scale=1.0):
    """Identity forward; backward = sign(pred - label)·grad_scale
    (ref: regression_output.cc MAERegressionOutput)."""
    return _mae_core(data, label, grad_scale)


@_reg
def logistic_regression_output(data, label, grad_scale=1.0):
    """Sigmoid forward; backward = (sigmoid(x) - label)·grad_scale
    (ref: regression_output.cc LogisticRegressionOutput)."""
    return _logistic_core(data, label, grad_scale)


@_reg
def softmax_activation(data, mode='instance'):
    """Softmax over channels (mode='channel', axis 1) or over all
    non-batch dims (mode='instance') (ref: nn/softmax_activation.cc)."""
    if mode == 'channel':
        return jax.nn.softmax(data, axis=1)
    flat = data.reshape(data.shape[0], -1)
    return jax.nn.softmax(flat, axis=-1).reshape(data.shape)


@_reg
def identity_attach_kl_sparse_reg(data, sparseness_target=0.1, penalty=0.001,
                                  momentum=0.9):
    """Identity forward; backward adds the KL-sparsity penalty gradient
    β·(−ρ/ρ̂ + (1−ρ)/(1−ρ̂)) on the batch-mean activation
    (ref: identity_attach_KL_sparse_reg.cc). The reference keeps ρ̂ as a
    momentum-smoothed aux state; functionally we use the current batch's
    mean (momentum is accepted for signature parity)."""
    @jax.custom_vjp
    def core(x):
        return x

    def fwd(x):
        rho_hat = jnp.clip(jnp.mean(x, axis=0), 1e-6, 1 - 1e-6)
        return x, (jnp.zeros_like(x), rho_hat)

    def bwd(res, g):
        zero, rho_hat = res
        rho = jnp.asarray(sparseness_target, rho_hat.dtype)
        kl_grad = jnp.asarray(penalty, rho_hat.dtype) * (
            -rho / rho_hat + (1 - rho) / (1 - rho_hat))
        n = zero.shape[0]
        return (g + (zero + kl_grad) / n,)

    core.defvjp(fwd, bwd)
    return core(data)


# ---------------------------------------------------------------------------
# ROI pooling (ref: src/operator/roi_pooling.cc) and rotated ROI align
# (ref: src/operator/contrib/rroi_align.cc)
# ---------------------------------------------------------------------------

@_reg
def roi_pooling(data, rois, pooled_size=(7, 7), spatial_scale=1.0):
    """Max-pool each ROI into a fixed (ph, pw) grid
    (ref: roi_pooling.cc ROIPooling). rois: (R, 5) [batch, x1, y1, x2, y2]
    in image coords. Bin membership is computed as dense masks over the
    feature map — static shapes, no gathers, so XLA vectorises it."""
    ph, pw = _tuple2(pooled_size)
    n, c, h, w = data.shape
    batch_idx = rois[:, 0].astype(jnp.int32)
    x1 = jnp.floor(rois[:, 1] * spatial_scale + 0.5)
    y1 = jnp.floor(rois[:, 2] * spatial_scale + 0.5)
    x2 = jnp.floor(rois[:, 3] * spatial_scale + 0.5)
    y2 = jnp.floor(rois[:, 4] * spatial_scale + 0.5)
    roi_h = jnp.maximum(y2 - y1 + 1, 1.0)
    roi_w = jnp.maximum(x2 - x1 + 1, 1.0)
    bin_h = roi_h / ph            # (R,)
    bin_w = roi_w / pw

    ys = jnp.arange(h, dtype=data.dtype)          # feature-map coords
    xs = jnp.arange(w, dtype=data.dtype)
    py = jnp.arange(ph, dtype=data.dtype)
    px = jnp.arange(pw, dtype=data.dtype)

    # (R, ph, h): is feature row y inside bin py of roi r?
    hstart = jnp.floor(py[None, :] * bin_h[:, None]) + y1[:, None]
    hend = jnp.ceil((py[None, :] + 1) * bin_h[:, None]) + y1[:, None]
    ymask = (ys[None, None, :] >= hstart[..., None]) & \
            (ys[None, None, :] < hend[..., None])
    wstart = jnp.floor(px[None, :] * bin_w[:, None]) + x1[:, None]
    wend = jnp.ceil((px[None, :] + 1) * bin_w[:, None]) + x1[:, None]
    xmask = (xs[None, None, :] >= wstart[..., None]) & \
            (xs[None, None, :] < wend[..., None])

    feat = data[batch_idx]                         # (R, C, H, W)
    neg = jnp.asarray(-onp.inf, data.dtype)
    # (R, 1, ph, 1, H, 1) & (R, 1, 1, pw, 1, W) → mask (R,1,ph,pw,H,W)
    mask = ymask[:, None, :, None, :, None] & xmask[:, None, None, :, None, :]
    vals = jnp.where(mask, feat[:, :, None, None, :, :], neg)
    out = jnp.max(vals, axis=(-2, -1))
    # empty bins produce -inf in the reference too (then 0 via is_empty);
    # match the is_empty→0 behavior
    return jnp.where(jnp.isfinite(out), out, 0.0)


@_reg
def rroi_align(data, rois, pooled_size=(7, 7), spatial_scale=1.0,
               sampling_ratio=2):
    """Rotated ROI align (ref: contrib/rroi_align.cc _contrib_RROIAlign).
    rois: (R, 6) [batch, cx, cy, w, h, angle_deg]; bilinear sampling on a
    rotated grid, averaged per bin."""
    ph, pw = _tuple2(pooled_size)
    n, c, h, w = data.shape
    s = max(int(sampling_ratio), 1)
    batch_idx = rois[:, 0].astype(jnp.int32)
    cx = rois[:, 1] * spatial_scale
    cy = rois[:, 2] * spatial_scale
    rw = jnp.maximum(rois[:, 3] * spatial_scale, 1.0)
    rh = jnp.maximum(rois[:, 4] * spatial_scale, 1.0)
    theta = rois[:, 5] * onp.pi / 180.0

    # sample grid in roi-local coords: (ph*s, pw*s) points in [-.5, .5]
    gy = (jnp.arange(ph * s) + 0.5) / (ph * s) - 0.5
    gx = (jnp.arange(pw * s) + 0.5) / (pw * s) - 0.5
    # build (R, ph*s, pw*s) absolute coords
    yy = gy[None, :, None] * rh[:, None, None]
    xx = gx[None, None, :] * rw[:, None, None]
    cos_t, sin_t = jnp.cos(theta), jnp.sin(theta)
    sx = cx[:, None, None] + xx * cos_t[:, None, None] \
        - yy * sin_t[:, None, None]
    sy = cy[:, None, None] + xx * sin_t[:, None, None] \
        + yy * cos_t[:, None, None]

    x0 = jnp.floor(sx)
    y0 = jnp.floor(sy)
    fx = (sx - x0).astype(data.dtype)
    fy = (sy - y0).astype(data.dtype)

    def gather(yi, xi):
        yi = jnp.clip(yi.astype(jnp.int32), 0, h - 1)
        xi = jnp.clip(xi.astype(jnp.int32), 0, w - 1)
        feat = data[batch_idx]                     # (R, C, H, W)
        r = jnp.arange(rois.shape[0])[:, None, None]
        return feat[r, :, yi, xi]                  # (R, ph*s, pw*s, C)

    v00 = gather(y0, x0)
    v01 = gather(y0, x0 + 1)
    v10 = gather(y0 + 1, x0)
    v11 = gather(y0 + 1, x0 + 1)
    fx = fx[..., None]
    fy = fy[..., None]
    val = (v00 * (1 - fx) * (1 - fy) + v01 * fx * (1 - fy) +
           v10 * (1 - fx) * fy + v11 * fx * fy)   # (R, ph*s, pw*s, C)
    inb = ((sx >= -1) & (sx <= w) & (sy >= -1) & (sy <= h))[..., None]
    val = jnp.where(inb, val, 0.0)
    r_ = val.reshape(val.shape[0], ph, s, pw, s, -1)
    out = jnp.mean(r_, axis=(2, 4))               # (R, ph, pw, C)
    return jnp.moveaxis(out, -1, 1)


# ---------------------------------------------------------------------------
# contrib utilities
# ---------------------------------------------------------------------------

@_reg(nograd=True)
def index_array(data, axes=None):
    """Return the index grid of `data`: shape data.shape + (len(axes),)
    (ref: contrib/index_array.cc _contrib_index_array)."""
    nd = data.ndim
    axes = tuple(range(nd)) if axes is None else tuple(axes)
    grids = jnp.meshgrid(*[jnp.arange(s) for s in data.shape],
                         indexing='ij')
    return jnp.stack([grids[a % nd] for a in axes], axis=-1) \
        .astype(jnp.int32)


@_reg(nograd=True)
def getnnz(data, axis=None):
    """Count stored (non-zero) values (ref: contrib/nnz.cc _contrib_getnnz;
    CSR-only there — dense payloads count actual non-zeros)."""
    nz = (data != 0)
    if axis is None:
        return jnp.sum(nz).astype(jnp.int32)
    return jnp.sum(nz, axis=axis).astype(jnp.int32)


@_reg(nograd=True)
def bipartite_matching(data, is_ascend=False, threshold=0.0, topk=-1):
    """Greedy bipartite matching over a (..., N, M) score matrix, the
    reference's anchor-assignment primitive
    (ref: contrib/bounding_box.cc _contrib_bipartite_matching).
    Returns (row_assignment (...,N), col_assignment (...,M)).
    Sequential greedy selection is a lax.scan over min(N, topk) steps."""
    scores = data
    n, m = scores.shape[-2], scores.shape[-1]
    steps = n if topk < 0 else min(topk, n)
    big = jnp.asarray(onp.inf, scores.dtype)
    sign = 1.0 if is_ascend else -1.0
    work = scores * sign                                   # minimise
    thresh = threshold * sign

    def body(carry, _):
        work, row_asg, col_asg = carry
        flat = work.reshape(work.shape[:-2] + (n * m,))
        idx = jnp.argmin(flat, axis=-1)
        best = jnp.take_along_axis(flat, idx[..., None], axis=-1)[..., 0]
        r, c = idx // m, idx % m
        ok = best <= thresh
        row_asg = jnp.where(
            ok[..., None] & (jnp.arange(n) == r[..., None]),
            c[..., None].astype(row_asg.dtype), row_asg)
        col_asg = jnp.where(
            ok[..., None] & (jnp.arange(m) == c[..., None]),
            r[..., None].astype(col_asg.dtype), col_asg)
        rowmask = (jnp.arange(n) == r[..., None])[..., None]
        colmask = (jnp.arange(m) == c[..., None])[..., None, :]
        work = jnp.where(ok[..., None, None] & (rowmask | colmask),
                         big, work)
        return (work, row_asg, col_asg), None

    row0 = jnp.full(scores.shape[:-1], -1.0, scores.dtype)
    col0 = jnp.full(scores.shape[:-2] + (m,), -1.0, scores.dtype)
    (_, row_asg, col_asg), _ = jax.lax.scan(
        body, (work, row0, col0), None, length=steps)
    return row_asg, col_asg


@_reg(nograd=True)
def calibrate_entropy(hist, hist_edges, num_quantized_bins=255):
    """KL-divergence threshold calibration for INT8 quantization
    (ref: quantization/calibrate.cc _contrib_calibrate_entropy). Host-side
    numpy (the reference also runs it once, offline, on CPU): sweep
    thresholds, pick the one minimising KL(P‖Q) between the clipped
    distribution and its quantized re-expansion.
    Returns (threshold, divergence)."""
    hist = onp.asarray(hist, dtype=onp.float64)
    edges = onp.asarray(hist_edges, dtype=onp.float64)
    num_bins = hist.size
    assert num_bins + 1 == edges.size
    zero_bin = onp.argmax(edges >= 0) - 1 if (edges < 0).any() else 0

    def kl(p, q):
        p = p / max(p.sum(), 1e-12)
        q = q / max(q.sum(), 1e-12)
        mask = p > 0
        qq = onp.where(q > 0, q, 1e-12)
        return float((p[mask] * onp.log(p[mask] / qq[mask])).sum())

    best_t, best_d = float(edges[-1]), onp.inf
    # candidate thresholds: bin upper edges from num_quantized_bins//2 out
    start = max(num_quantized_bins // 2, 1)
    for i in range(start, num_bins + 1):
        # symmetric window of i bins around the zero point
        lo = max(zero_bin - i, 0)
        hi = min(zero_bin + i, num_bins)
        p = hist[lo:hi].copy()
        if p.sum() == 0:
            continue
        # outliers clip into the edge bins
        p[0] += hist[:lo].sum()
        p[-1] += hist[hi:].sum()
        # quantize the window into num_quantized_bins then re-expand
        chunks = onp.array_split(p, num_quantized_bins)
        q = onp.concatenate([
            onp.full(len(ch), (ch.sum() / max((ch > 0).sum(), 1)))
            * (ch > 0) for ch in chunks])
        d = kl(p, q)
        t = float(max(abs(edges[lo]), abs(edges[hi])))
        if d < best_d:
            best_d, best_t = d, t
    return (jnp.asarray(best_t, jnp.float32),
            jnp.asarray(best_d if onp.isfinite(best_d) else 0.0,
                        jnp.float32))


# ---------------------------------------------------------------------------
# Quantized op variants (ref: src/operator/quantization/)
# ---------------------------------------------------------------------------

def _dequant(x, mn, mx):
    scale = jnp.maximum(jnp.maximum(jnp.abs(mn), jnp.abs(mx)), 1e-12) / 127.0
    return x.astype(jnp.float32) * scale


def _requant(x):
    mx = jnp.maximum(jnp.max(jnp.abs(x)), 1e-12)
    q = jnp.clip(jnp.round(x / mx * 127.0), -127, 127).astype(jnp.int8)
    return q, -mx, mx


@_reg(num_outputs=3)
def quantized_act(data, min_data, max_data, act_type='relu'):
    """INT8 activation; relu passes quantized values through with range
    clipped at zero (ref: quantization/quantized_activation.cc)."""
    if act_type != 'relu':
        x = _dequant(data, min_data, max_data)
        y = {'sigmoid': jax.nn.sigmoid, 'tanh': jnp.tanh,
             'softrelu': jax.nn.softplus}[act_type](x)
        return _requant(y)
    out = jnp.maximum(data, 0)
    return out, jnp.maximum(min_data, 0.0), jnp.maximum(max_data, 0.0)


@_reg(num_outputs=3)
def quantized_batch_norm(data, gamma, beta, moving_mean, moving_var,
                         min_data, max_data, eps=1e-3, **_ignored):
    """INT8 inference batch norm: dequantize → affine normalise →
    requantize (ref: quantization/quantized_batch_norm.cc)."""
    x = _dequant(data, min_data, max_data)
    inv = gamma / jnp.sqrt(moving_var + eps)
    y = (x - moving_mean[None, :, None, None]) * inv[None, :, None, None] \
        + beta[None, :, None, None]
    return _requant(y)


@_reg(num_outputs=3)
def quantized_elemwise_mul(lhs, rhs, lhs_min, lhs_max, rhs_min, rhs_max):
    """Ref: quantization/quantized_elemwise_mul.cc."""
    y = _dequant(lhs, lhs_min, lhs_max) * _dequant(rhs, rhs_min, rhs_max)
    return _requant(y)


@_reg(num_outputs=3)
def quantized_embedding(data, weight, min_weight, max_weight,
                        input_dim=None, output_dim=None, dtype='int8'):
    """INT8 embedding lookup: rows stay quantized, range passes through
    (ref: quantization/quantized_indexing_op.cc)."""
    rows = weight[data.astype(jnp.int32)]
    return rows, min_weight, max_weight


# ---------------------------------------------------------------------------
# AMP / multi-tensor utilities (ref: src/operator/tensor/amp_cast.cc,
# contrib/all_finite.cc, contrib/reset_arrays.cc)
# ---------------------------------------------------------------------------

@_reg
def amp_multicast(*data, num_outputs=None, cast_narrow=False):
    """Cast all inputs to a common width: widest by default, narrowest
    with cast_narrow (ref: amp_cast.cc amp_multicast)."""
    dtypes = [d.dtype for d in data]
    key = min if cast_narrow else max
    target = key(dtypes, key=lambda t: jnp.dtype(t).itemsize)
    return tuple(d.astype(target) for d in data)


@_reg(nograd=True)
def multi_all_finite(*arrays, num_arrays=None, init_output=True):
    """1.0 iff every element of every input is finite
    (ref: contrib/all_finite.cc multi_all_finite)."""
    ok = jnp.asarray(True)
    for a in arrays:
        ok = ok & jnp.all(jnp.isfinite(a))
    return ok.astype(jnp.float32).reshape(1)


@_reg(nograd=True, mutate_inputs='all')
def reset_arrays(*arrays, num_arrays=None):
    """Zero every input array (ref: contrib/reset_arrays.cc — EVERY
    input is mutated, not just the first). Functional form: returns the
    zeroed arrays; the NDArray layer rebinds handles."""
    return tuple(jnp.zeros_like(a) for a in arrays)


@_reg(nograd=True)
def multi_lars(lrs, weights_sum_sq, grads_sum_sq, wds, eta=0.001,
               eps=1e-8, rescale_grad=1.0):
    """LARS learning-rate coefficients from per-layer ‖w‖² and ‖g‖²
    (ref: contrib/multi_lars.cc multi_lars)."""
    w_norm = jnp.sqrt(weights_sum_sq)
    g_norm = jnp.sqrt(grads_sum_sq) * rescale_grad
    trust = eta * w_norm / (g_norm + wds * w_norm + eps)
    return jnp.where((w_norm > 0) & (g_norm > 0), lrs * trust, lrs)


# ---------------------------------------------------------------------------
# Optimizer update long tail (ref: src/operator/optimizer_op.cc,
# contrib/optimizer_op.cc, contrib/adamw.cc)
# ---------------------------------------------------------------------------

def _prep(grad, rescale_grad, clip_gradient, wd=0.0, weight=None):
    g = grad * rescale_grad
    if clip_gradient is not None and clip_gradient > 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    if wd and weight is not None:
        g = g + wd * weight
    return g


@_reg
def mp_nag_mom_update(weight, grad, mom, weight32, lr=0.01, momentum=0.0,
                      wd=0.0, rescale_grad=1.0, clip_gradient=-1.0):
    """Mixed-precision NAG: math in the fp32 master copy, bf16/fp16 view
    out (ref: optimizer_op.cc mp_nag_mom_update)."""
    g = _prep(grad.astype(jnp.float32), rescale_grad, clip_gradient, wd,
              weight32)
    new_mom = momentum * mom + g
    w32 = weight32 - lr * (g + momentum * new_mom)
    return w32.astype(weight.dtype), new_mom, w32


@_reg
def mp_lamb_update_phase1(weight, grad, mean, var, weight32, beta1=0.9,
                          beta2=0.999, epsilon=1e-6, t=1, bias_correction=True,
                          wd=0.0, rescale_grad=1.0, clip_gradient=-1.0):
    """LAMB phase 1 on the fp32 master weight
    (ref: optimizer_op.cc mp_lamb_update_phase1)."""
    g = _prep(grad.astype(jnp.float32), rescale_grad, clip_gradient)
    m = beta1 * mean + (1 - beta1) * g
    v = beta2 * var + (1 - beta2) * jnp.square(g)
    gh = m / (jnp.sqrt(v) + epsilon)
    if bias_correction:
        gh = (m / (1 - beta1 ** t)) / \
            (jnp.sqrt(v / (1 - beta2 ** t)) + epsilon)
    return gh + wd * weight32, m, v


@_reg
def mp_lamb_update_phase2(weight, g_update, r1, r2, weight32, lr=0.01,
                          lower_bound=-1.0, upper_bound=-1.0):
    """LAMB phase 2: trust-ratio scaling applied to the master weight
    (ref: optimizer_op.cc mp_lamb_update_phase2)."""
    r1c = r1
    if lower_bound > 0:
        r1c = jnp.maximum(r1c, lower_bound)
    if upper_bound > 0:
        r1c = jnp.minimum(r1c, upper_bound)
    ratio = jnp.where(r2 > 0, jnp.where(r1c > 0, r1c / r2, 1.0), 1.0)
    w32 = weight32 - lr * ratio * g_update
    return w32.astype(weight.dtype), w32


@_reg
def mp_adamw_update(weight, grad, mean, var, weight32, rescale_grad=1.0,
                    lr=0.001, eta=1.0, beta1=0.9, beta2=0.999, epsilon=1e-8,
                    wd=0.0, clip_gradient=-1.0):
    """Mixed-precision AdamW (ref: contrib/adamw.cc _mp_adamw_update)."""
    g = _prep(grad.astype(jnp.float32), rescale_grad, clip_gradient)
    m = beta1 * mean + (1 - beta1) * g
    v = beta2 * var + (1 - beta2) * jnp.square(g)
    w32 = weight32 - eta * (lr * m / (jnp.sqrt(v) + epsilon)
                            + lr * wd * weight32)
    return w32.astype(weight.dtype), m, v, w32


@_reg
def multi_mp_adamw_update(weights, grads, means, vars_, weights32,
                          rescale_grad=1.0, lrs=(), etas=(), wds=(),
                          beta1=0.9, beta2=0.999, epsilon=1e-8,
                          clip_gradient=-1.0):
    """Multi-tensor mixed-precision AdamW (ref: contrib/adamw.cc
    _multi_mp_adamw_update)."""
    outs = []
    for w, g, m, v, w32, lr, eta, wd in zip(weights, grads, means, vars_,
                                            weights32, lrs, etas, wds):
        outs.append(mp_adamw_update(w, g, m, v, w32,
                                    rescale_grad=rescale_grad, lr=lr,
                                    eta=eta, beta1=beta1, beta2=beta2,
                                    epsilon=epsilon, wd=wd,
                                    clip_gradient=clip_gradient))
    return tuple(outs)


@_reg
def multi_mp_lamb_update(weights, grads, means, vars_, weights32, lrs=(),
                         wds=(), step_count=(), beta1=0.9, beta2=0.999,
                         epsilon=1e-6, bias_correction=True,
                         rescale_grad=1.0, lower_bound=-1.0,
                         upper_bound=-1.0, clip_gradient=-1.0):
    """Multi-tensor mixed-precision LAMB (ref: contrib/multi_lamb.cc)."""
    outs = []
    for w, g, m, v, w32, lr, wd, t in zip(weights, grads, means, vars_,
                                          weights32, lrs, wds, step_count):
        gh, m2, v2 = mp_lamb_update_phase1(
            w, g, m, v, w32, beta1=beta1, beta2=beta2, epsilon=epsilon,
            t=t, bias_correction=bias_correction, wd=wd,
            rescale_grad=rescale_grad, clip_gradient=clip_gradient)
        r1 = jnp.linalg.norm(w32)
        r2 = jnp.linalg.norm(gh)
        wnew, w32n = mp_lamb_update_phase2(
            w, gh, r1, r2, w32, lr=lr, lower_bound=lower_bound,
            upper_bound=upper_bound)
        outs.append((wnew, m2, v2, w32n))
    return tuple(outs)


@_reg
def sparse_adagrad_update(weight, grad, history, lr=0.01, epsilon=1e-7,
                          wd=0.0, rescale_grad=1.0, clip_gradient=-1.0):
    """Row-sparse AdaGrad: rows with all-zero gradient are untouched
    (ref: optimizer_op.cc _sparse_adagrad_update; dense payload, the
    row mask recovers the lazy-update semantics)."""
    g = _prep(grad, rescale_grad, clip_gradient, wd, weight)
    row_nz = jnp.any(grad != 0, axis=tuple(range(1, grad.ndim)),
                     keepdims=True) if grad.ndim > 1 else (grad != 0)
    new_hist = jnp.where(row_nz, history + jnp.square(g), history)
    new_w = jnp.where(row_nz,
                      weight - lr * g / (jnp.sqrt(new_hist) + epsilon),
                      weight)
    return new_w, new_hist


@_reg
def group_adagrad_update(weight, grad, history, lr=0.01, rescale_grad=1.0,
                         clip_gradient=-1.0, epsilon=1e-5):
    """Group (per-row) AdaGrad — history has shape (rows, 1)
    (ref: contrib/optimizer_op.cc _contrib_group_adagrad_update)."""
    g = _prep(grad, rescale_grad, clip_gradient)
    axes = tuple(range(1, g.ndim))
    msq = jnp.mean(jnp.square(g), axis=axes, keepdims=True)
    # canonical history is (rows, 1); accept (rows,) or grad-shaped too
    h = history + msq.reshape((history.shape[0],) +
                              (1,) * (history.ndim - 1))
    hb = h.reshape((h.shape[0],) + (1,) * (g.ndim - 1)) if h.ndim == 1 else h
    return weight - lr * g / (jnp.sqrt(hb) + epsilon), h


# ---------------------------------------------------------------------------
# Random *_like family + unique zipfian
# (ref: src/operator/random/sample_op.cc:62, unique_sample_op.cc)
# ---------------------------------------------------------------------------

def _make_like(base_fn, name):
    def op(data, **kwargs):
        kwargs.pop('shape', None)
        return base_fn(shape=data.shape, dtype=str(data.dtype), **kwargs)
    op.__name__ = name
    op.__doc__ = (f"Shape/dtype-from-input variant of {base_fn.__name__} "
                  "(ref: random/sample_op.cc:62 "
                  "MXNET_OPERATOR_REGISTER_SAMPLE_LIKE).")
    return op


def _register_like_ops():
    from . import random_ops as rops
    pairs = [
        (rops.random_uniform, 'random_uniform_like'),
        (rops.random_normal, 'random_normal_like'),
        (rops.random_gamma, 'random_gamma_like'),
        (rops.random_exponential, 'random_exponential_like'),
        (rops.random_poisson, 'random_poisson_like'),
        (rops.random_negative_binomial, 'random_negative_binomial_like'),
        (rops.random_generalized_negative_binomial,
         'random_generalized_negative_binomial_like'),
    ]
    for base, name in pairs:
        op = _make_like(base, name)
        register_op(name, nograd=True)(op)
        __all__.append(name)


_register_like_ops()


@_reg(nograd=True, num_outputs=2)
def sample_unique_zipfian(range_max, shape=()):
    """Approximately-unique samples from a Zipfian(range_max) distribution,
    plus the number of trials drawn — the sampled-softmax candidate
    sampler (ref: random/unique_sample_op.cc _sample_unique_zipfian).
    Host-side numpy like the reference's CPU-only kernel."""
    n = int(onp.prod(shape)) if shape else 1
    rng = onp.random.default_rng(
        int(jax.device_get(_random.next_key())[-1]))
    seen, out, tries = set(), [], 0
    log_range = onp.log(range_max + 1)
    while len(out) < n:
        u = rng.random()
        v = int(onp.exp(u * log_range)) - 1
        v = min(v, range_max - 1)
        tries += 1
        if v not in seen:
            seen.add(v)
            out.append(v)
    arr = onp.asarray(out, dtype=onp.int32).reshape(shape if shape else (1,))
    return jnp.asarray(arr), jnp.asarray([tries], dtype=jnp.int32)


# ---------------------------------------------------------------------------
# Image random-augmentation ops (ref: src/operator/image/image_random.cc)
# ---------------------------------------------------------------------------

def _u(low, high):
    return float(jax.device_get(
        jax.random.uniform(_random.next_key(), (), minval=low, maxval=high)))


def _blend(a, b, alpha):
    return a * alpha + b * (1.0 - alpha)


def _to_float(img):
    return img.astype(jnp.float32)


def _gray(img):
    # HWC or CHW? the reference image ops take HWC (or NHWC)
    r, g, b = img[..., 0:1], img[..., 1:2], img[..., 2:3]
    return 0.299 * r + 0.587 * g + 0.114 * b


@_reg(nograd=True)
def image_adjust_lighting(data, alpha=(0.0, 0.0, 0.0)):
    """AlexNet-style PCA lighting with explicit alpha
    (ref: image/image_random.cc _image_adjust_lighting)."""
    eigval = jnp.asarray([55.46, 4.794, 1.148], jnp.float32)
    eigvec = jnp.asarray([[-0.5675, 0.7192, 0.4009],
                          [-0.5808, -0.0045, -0.814],
                          [-0.5836, -0.6948, 0.4203]], jnp.float32)
    alpha = jnp.asarray(alpha, jnp.float32)
    delta = eigvec @ (alpha * eigval)
    return (_to_float(data) + delta).astype(data.dtype) \
        if jnp.issubdtype(data.dtype, jnp.floating) \
        else jnp.clip(_to_float(data) + delta, 0, 255).astype(data.dtype)


@_reg(nograd=True)
def image_random_lighting(data, alpha_std=0.05):
    """Ref: image_random.cc _image_random_lighting."""
    a = jax.device_get(jax.random.normal(_random.next_key(), (3,))) \
        * alpha_std
    return image_adjust_lighting(data, tuple(float(x) for x in a))


@_reg(nograd=True)
def image_random_brightness(data, min_factor=0.5, max_factor=1.5):
    """Scale by U(min, max) (ref: image_random.cc _image_random_brightness)."""
    f = _u(min_factor, max_factor)
    out = _to_float(data) * f
    if not jnp.issubdtype(data.dtype, jnp.floating):
        out = jnp.clip(out, 0, 255)
    return out.astype(data.dtype)


@_reg(nograd=True)
def image_random_contrast(data, min_factor=0.5, max_factor=1.5):
    """Blend with the global gray mean (ref: _image_random_contrast)."""
    f = _u(min_factor, max_factor)
    x = _to_float(data)
    mean = jnp.mean(_gray(x))
    out = _blend(x, mean, f)
    if not jnp.issubdtype(data.dtype, jnp.floating):
        out = jnp.clip(out, 0, 255)
    return out.astype(data.dtype)


@_reg(nograd=True)
def image_random_saturation(data, min_factor=0.5, max_factor=1.5):
    """Blend with the per-pixel gray value (ref: _image_random_saturation)."""
    f = _u(min_factor, max_factor)
    x = _to_float(data)
    out = _blend(x, _gray(x), f)
    if not jnp.issubdtype(data.dtype, jnp.floating):
        out = jnp.clip(out, 0, 255)
    return out.astype(data.dtype)


@_reg(nograd=True)
def image_random_hue(data, min_factor=0.5, max_factor=1.5):
    """Rotate hue in YIQ space by U(min,max)-derived angle
    (ref: _image_random_hue)."""
    f = _u(min_factor, max_factor)
    x = _to_float(data)
    t_yiq = jnp.asarray([[0.299, 0.587, 0.114],
                         [0.596, -0.274, -0.321],
                         [0.211, -0.523, 0.311]], jnp.float32)
    t_rgb = jnp.linalg.inv(t_yiq)
    u, w_ = onp.cos(f * onp.pi), onp.sin(f * onp.pi)
    rot = jnp.asarray([[1, 0, 0], [0, u, -w_], [0, w_, u]], jnp.float32)
    m = t_rgb @ rot @ t_yiq
    out = jnp.einsum('...c,dc->...d', x, m)
    if not jnp.issubdtype(data.dtype, jnp.floating):
        out = jnp.clip(out, 0, 255)
    return out.astype(data.dtype)


@_reg(nograd=True)
def image_random_color_jitter(data, brightness=0.0, contrast=0.0,
                              saturation=0.0, hue=0.0):
    """Compose brightness/contrast/saturation/hue in random order
    (ref: _image_random_color_jitter)."""
    jitters = []
    if brightness > 0:
        jitters.append(lambda d: image_random_brightness(
            d, 1 - brightness, 1 + brightness))
    if contrast > 0:
        jitters.append(lambda d: image_random_contrast(
            d, 1 - contrast, 1 + contrast))
    if saturation > 0:
        jitters.append(lambda d: image_random_saturation(
            d, 1 - saturation, 1 + saturation))
    if hue > 0:
        jitters.append(lambda d: image_random_hue(d, -hue, hue))
    order = onp.random.permutation(len(jitters))
    for i in order:
        data = jitters[int(i)](data)
    return data


@_reg(nograd=True)
def image_random_flip_left_right(data, p=0.5):
    """Ref: _image_random_flip_left_right."""
    if _u(0.0, 1.0) < p:
        return jnp.flip(data, axis=-2)
    return data


@_reg(nograd=True)
def image_random_flip_top_bottom(data, p=0.5):
    """Ref: _image_random_flip_top_bottom."""
    if _u(0.0, 1.0) < p:
        return jnp.flip(data, axis=-3)
    return data


# ---------------------------------------------------------------------------
# Custom-op dispatch + control flow as registered ops
# ---------------------------------------------------------------------------

@_reg
def custom(*data, op_type=None, **kwargs):
    """Dispatch to a user CustomOpProp registered via mx.operator.register
    (ref: src/operator/custom/custom.cc Custom). The bridge in
    operator.py handles trace-time pure_callback + custom_vjp."""
    from .. import operator as _operator
    return _operator.invoke_custom(list(data), op_type=op_type, **kwargs)


def _register_control_flow():
    from . import control_flow as cf
    register_op('cond')(cf.cond)
    register_op('foreach')(cf.foreach)
    register_op('while_loop')(cf.while_loop)


_register_control_flow()
