"""DGL graph ops (ref: src/operator/contrib/dgl_graph.cc).

Graphs ride in CSR matrices whose stored values are edge ids (the DGL
convention). `edge_id`, `dgl_adjacency` and `dgl_subgraph` are pure
gathers and lower through XLA; the neighbor samplers and graph
compaction have value-dependent output structure, so — exactly like the
reference's CPU kernels (dgl_graph.cc runs them on the host and syncs) —
they execute eagerly over host numpy and are not jit-traceable.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as onp

from ..base import register_op
from .. import random as _random

__all__ = []


def _reg(fn, **kw):
    register_op(fn.__name__, **kw)(fn)
    __all__.append(fn.__name__)
    return fn


def _host(x):
    return onp.asarray(jax.device_get(x))


@_reg
def edge_id(data, u, v):
    """out[i] = data[u[i], v[i]] if that edge exists else -1
    (ref: dgl_graph.cc:1300 _contrib_edge_id)."""
    vals = data[u.astype(jnp.int32), v.astype(jnp.int32)]
    return jnp.where(vals != 0, vals, -jnp.ones_like(vals))


@_reg
def dgl_adjacency(data):
    """Adjacency matrix (all stored edges become weight 1.0) of an
    edge-id CSR (ref: dgl_graph.cc:1376)."""
    return (data != 0).astype(jnp.float32)


def dgl_subgraph(graph, *vertex_lists, return_mapping=False):
    """Induced subgraphs on the given vertex sets (ref:
    dgl_graph.cc:1115). Returns one (sub)graph per vertex list, each
    followed by its edge-id mapping matrix when return_mapping=True."""
    g = _host(graph)
    outs = []
    for vl in vertex_lists:
        idx = _host(vl).astype(onp.int64)
        sub = g[onp.ix_(idx, idx)]
        # renumber edges consecutively like the reference (ids start at 1)
        mask = sub != 0
        new = onp.zeros_like(sub)
        new[mask] = onp.arange(1, int(mask.sum()) + 1)
        outs.append(jnp.asarray(new))
        if return_mapping:
            mapping = onp.where(mask, sub, 0)
            outs.append(jnp.asarray(mapping))
    return tuple(outs)


register_op('dgl_subgraph', num_outputs=-1, nograd=True)(dgl_subgraph)
__all__.append('dgl_subgraph')


def _neighbor_sample(csr, seeds, num_hops, num_neighbor,
                     max_num_vertices, probability=None):
    """Shared body of the two samplers (ref: dgl_graph.cc SampleSubgraph):
    BFS from the seed set, keeping <=num_neighbor sampled neighbors per
    vertex per hop; emits (vertices, sampled-edge csr payload, layers)."""
    g = _host(csr)
    n = g.shape[0]
    rng = onp.random.RandomState(
        int(_host(jax.random.bits(_random.next_key(), (), jnp.uint32))))
    prob = None if probability is None else _host(probability)

    layer_of = {}
    frontier = []
    for s in _host(seeds).astype(onp.int64).ravel():
        if len(layer_of) >= max_num_vertices:
            break   # the cap applies to seeds too, not just neighbors
        if s >= 0 and s not in layer_of:
            layer_of[int(s)] = 0
            frontier.append(int(s))
    sampled = onp.zeros_like(g)
    for hop in range(1, num_hops + 1):
        nxt = []
        for u in frontier:
            nbrs = onp.nonzero(g[u])[0]
            if len(nbrs) == 0:
                continue
            if len(nbrs) > num_neighbor:
                if prob is not None:
                    p = prob[nbrs].astype(onp.float64)
                    p = p / p.sum()
                    pick = rng.choice(nbrs, num_neighbor, replace=False,
                                      p=p)
                else:
                    pick = rng.choice(nbrs, num_neighbor, replace=False)
            else:
                pick = nbrs
            for vtx in pick:
                if len(layer_of) >= max_num_vertices and \
                        int(vtx) not in layer_of:
                    continue
                sampled[u, vtx] = g[u, vtx]
                if int(vtx) not in layer_of:
                    layer_of[int(vtx)] = hop
                    nxt.append(int(vtx))
        frontier = nxt
    verts = sorted(layer_of)
    out_v = onp.full((max_num_vertices + 1,), -1, onp.int64)
    out_v[:len(verts)] = verts
    out_v[-1] = len(verts)
    out_l = onp.full((max_num_vertices,), -1, onp.int64)
    out_l[:len(verts)] = [layer_of[v] for v in verts]
    return (jnp.asarray(out_v), jnp.asarray(sampled), jnp.asarray(out_l))


def dgl_csr_neighbor_uniform_sample(csr, *seeds, num_hops=1,
                                    num_neighbor=2, max_num_vertices=100):
    """Uniform neighborhood sampling from an edge-id CSR graph (ref:
    dgl_graph.cc:744). One (vertices, subgraph-csr, layers) triple per
    seed array."""
    outs = []
    for s in seeds:
        outs.extend(_neighbor_sample(csr, s, num_hops, num_neighbor,
                                     max_num_vertices))
    return tuple(outs)


def dgl_csr_neighbor_non_uniform_sample(csr, probability, *seeds,
                                        num_hops=1, num_neighbor=2,
                                        max_num_vertices=100):
    """Probability-weighted neighborhood sampling
    (ref: dgl_graph.cc:838)."""
    outs = []
    for s in seeds:
        outs.extend(_neighbor_sample(csr, s, num_hops, num_neighbor,
                                     max_num_vertices, probability))
    return tuple(outs)


def dgl_graph_compact(*graphs, return_mapping=False, graph_sizes=()):
    """Drop unused vertex slots: each input graph keeps its first
    graph_sizes[i] vertices (ref: dgl_graph.cc:1551)."""
    outs = []
    for g, size in zip(graphs, graph_sizes):
        gh = _host(g)
        size = int(size)
        compact = gh[:size, :size]
        outs.append(jnp.asarray(compact))
        if return_mapping:
            outs.append(jnp.asarray((compact != 0).astype(gh.dtype)))
    return tuple(outs)


for _f in (dgl_csr_neighbor_uniform_sample,
           dgl_csr_neighbor_non_uniform_sample, dgl_graph_compact):
    register_op(_f.__name__, num_outputs=-1, nograd=True)(_f)
    __all__.append(_f.__name__)
