"""Indexing ops: take/gather/scatter/boolean_mask/where-family.

Ref: src/operator/tensor/indexing_op.cc, src/operator/contrib/{boolean_mask,
index_copy}.cc. All map to XLA gather/scatter which stay on-device.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..base import register_op
from . import rowsparse as _rowsparse

__all__ = []


def _reg(fn):
    register_op(fn.__name__)(fn)
    __all__.append(fn.__name__)
    return fn


@_reg
def take(a, indices, axis=0, mode='clip'):
    idx = indices.astype(jnp.int32)
    jmode = {'clip': 'clip', 'wrap': 'wrap', 'raise': 'clip'}[mode]
    if axis == 0 and a.ndim >= 2 and idx.size > 0:
        # table-style gather: dedup repeated ids so the backward
        # segment-sums into one row block per unique id before the
        # table-shaped scatter (ref TakeOpBackward row_sparse path)
        if jmode == 'wrap':
            idx = idx % a.shape[0]
        return _rowsparse.dedup_take(a, idx)
    return jnp.take(a, idx, axis=axis, mode=jmode)


@_reg
def batch_take(a, indices):
    idx = indices.astype(jnp.int32)
    return jnp.take_along_axis(a, idx[..., None] if idx.ndim < a.ndim else idx,
                               axis=-1).squeeze(-1)


@_reg
def pick(data, index, axis=-1, keepdims=False, mode='clip'):
    idx = jnp.clip(index.astype(jnp.int32), 0, data.shape[axis] - 1)
    out = jnp.take_along_axis(data, jnp.expand_dims(idx, axis % data.ndim),
                              axis=axis)
    if not keepdims:
        out = jnp.squeeze(out, axis=axis % data.ndim)
    return out


@_reg
def gather_nd(data, indices):
    idx = indices.astype(jnp.int32)
    m = idx.shape[0]
    return data[tuple(idx[i] for i in range(m))]


@_reg
def scatter_nd(data, indices, shape=None):
    idx = indices.astype(jnp.int32)
    m = idx.shape[0]
    out = jnp.zeros(tuple(shape), dtype=data.dtype)
    return out.at[tuple(idx[i] for i in range(m))].set(data)


@_reg
def index_copy(old_tensor, index_vector, new_tensor):
    idx = index_vector.astype(jnp.int32)
    return old_tensor.at[idx].set(new_tensor)


@_reg
def index_add(data, indices, values):
    idx = indices.astype(jnp.int32)
    return data.at[idx].add(values)


@_reg
def boolean_mask(data, index, axis=0):
    """Ref: src/operator/contrib/boolean_mask.cc. NOTE: output shape is
    data-dependent; on TPU we return a dense result where unselected rows are
    compacted to the front and the caller can use `sum(index)` for the count
    (XLA needs static shapes). Eager mode (outside jit) returns the exact
    dynamic result."""
    mask = index.astype(bool)
    if isinstance(data, jax.core.Tracer) or isinstance(index, jax.core.Tracer):
        order = jnp.argsort(~mask, stable=True)
        return jnp.take(data, order, axis=axis)
    import numpy as onp
    sel = onp.nonzero(onp.asarray(mask))[0]
    return jnp.take(data, jnp.asarray(sel), axis=axis)


@_reg
def sequence_mask_like(data, mask):
    return data * mask


@_reg
def ravel_multi_index(data, shape=None):
    idx = data.astype(jnp.int64)
    out = jnp.zeros(idx.shape[1:], dtype=jnp.int64)
    for i, s in enumerate(shape):
        out = out * s + idx[i]
    return out.astype(jnp.float32)


@_reg
def unravel_index(data, shape=None):
    idx = data.astype(jnp.int64)
    coords = []
    rem = idx
    for s in reversed(shape):
        coords.append(rem % s)
        rem = rem // s
    return jnp.stack(list(reversed(coords)), axis=0).astype(jnp.float32)
