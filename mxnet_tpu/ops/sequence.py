"""Sequence ops (ref: src/operator/sequence_{mask,last,reverse}.cc)."""
from __future__ import annotations

import jax.numpy as jnp

from ..base import register_op

__all__ = []


def _reg(fn):
    register_op(fn.__name__)(fn)
    __all__.append(fn.__name__)
    return fn


@_reg
def sequence_mask(data, sequence_length=None, use_sequence_length=False,
                  value=0.0, axis=0):
    """data is (T, N, ...) for axis=0 or (N, T, ...) for axis=1."""
    if not use_sequence_length or sequence_length is None:
        return data
    T = data.shape[axis]
    pos = jnp.arange(T)
    if axis == 0:
        shape = (T,) + (1,) * (data.ndim - 1)
        lshape = (1, -1) + (1,) * (data.ndim - 2)
    else:
        shape = (1, T) + (1,) * (data.ndim - 2)
        lshape = (-1, 1) + (1,) * (data.ndim - 2)
    mask = pos.reshape(shape) < sequence_length.reshape(lshape)
    return jnp.where(mask, data, value)


@_reg
def sequence_last(data, sequence_length=None, use_sequence_length=False, axis=0):
    if not use_sequence_length or sequence_length is None:
        return jnp.take(data, -1, axis=axis)
    idx = (sequence_length - 1).astype(jnp.int32)
    if axis == 0:
        moved = jnp.moveaxis(data, 0, 1)  # (N, T, ...)
    else:
        moved = data
    expand = idx.reshape((-1,) + (1,) * (moved.ndim - 1))
    out = jnp.take_along_axis(moved, expand.astype(jnp.int32), axis=1)
    return jnp.squeeze(out, axis=1)


@_reg
def sequence_reverse(data, sequence_length=None, use_sequence_length=False, axis=0):
    T = data.shape[axis]
    if not use_sequence_length or sequence_length is None:
        return jnp.flip(data, axis=axis)
    pos = jnp.arange(T)
    if axis != 0:
        data = jnp.moveaxis(data, axis, 0)
    # per-sequence reversal of the first L entries, rest unchanged
    L = sequence_length.astype(jnp.int32)  # (N,)
    rev_idx = jnp.where(pos[:, None] < L[None, :], L[None, :] - 1 - pos[:, None],
                        pos[:, None])  # (T, N)
    expand = rev_idx.reshape(rev_idx.shape + (1,) * (data.ndim - 2))
    out = jnp.take_along_axis(data, jnp.broadcast_to(expand, data.shape), axis=0)
    if axis != 0:
        out = jnp.moveaxis(out, 0, axis)
    return out
