"""Reduce and broadcast ops (ref: src/operator/tensor/broadcast_reduce_op.h)."""
from __future__ import annotations

import jax.numpy as jnp

from ..base import register_op

__all__ = []


def _reg(fn):
    register_op(fn.__name__)(fn)
    __all__.append(fn.__name__)
    return fn


def _norm_axis(axis):
    if axis is None:
        return None
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    return int(axis)


def _reduce(jfn, data, axis, keepdims, exclude=False):
    axis = _norm_axis(axis)
    if exclude and axis is not None:
        if isinstance(axis, int):
            axis = (axis,)
        axis = tuple(i for i in range(data.ndim) if i not in
                     tuple(a % data.ndim for a in axis))
    return jfn(data, axis=axis, keepdims=keepdims)


@_reg
def sum(data, axis=None, keepdims=False, exclude=False):
    return _reduce(jnp.sum, data, axis, keepdims, exclude)


@_reg
def mean(data, axis=None, keepdims=False, exclude=False):
    return _reduce(jnp.mean, data, axis, keepdims, exclude)


@_reg
def prod(data, axis=None, keepdims=False, exclude=False):
    return _reduce(jnp.prod, data, axis, keepdims, exclude)


@_reg
def nansum(data, axis=None, keepdims=False, exclude=False):
    return _reduce(jnp.nansum, data, axis, keepdims, exclude)


@_reg
def nanprod(data, axis=None, keepdims=False, exclude=False):
    return _reduce(jnp.nanprod, data, axis, keepdims, exclude)


@_reg
def max(data, axis=None, keepdims=False, exclude=False):
    return _reduce(jnp.max, data, axis, keepdims, exclude)


@_reg
def min(data, axis=None, keepdims=False, exclude=False):
    return _reduce(jnp.min, data, axis, keepdims, exclude)


@_reg
def argmax(data, axis=None, keepdims=False):
    out = jnp.argmax(data, axis=axis, keepdims=keepdims)
    return out.astype(jnp.float32)


@_reg
def argmin(data, axis=None, keepdims=False):
    out = jnp.argmin(data, axis=axis, keepdims=keepdims)
    return out.astype(jnp.float32)


@_reg
def norm(data, ord=2, axis=None, keepdims=False):
    axis = _norm_axis(axis)
    if ord == 1:
        return jnp.sum(jnp.abs(data), axis=axis, keepdims=keepdims)
    return jnp.sqrt(jnp.sum(jnp.square(data), axis=axis, keepdims=keepdims))


@_reg
def broadcast_to(data, shape=None):
    shape = tuple(int(s) if int(s) != 0 else data.shape[i]
                  for i, s in enumerate(shape))
    return jnp.broadcast_to(data, shape)


@_reg
def broadcast_like(lhs, rhs):
    return jnp.broadcast_to(lhs, rhs.shape)


@_reg
def broadcast_axis(data, axis=(), size=()):
    if isinstance(axis, int):
        axis, size = (axis,), (size,)
    shape = list(data.shape)
    for a, s in zip(axis, size):
        shape[a] = int(s)
    return jnp.broadcast_to(data, tuple(shape))


@_reg
def cumsum(a, axis=None, dtype=None):
    return jnp.cumsum(a, axis=axis, dtype=dtype)


@_reg
def cumprod(a, axis=None, dtype=None):
    return jnp.cumprod(a, axis=axis, dtype=dtype)


@_reg
def moments(data, axes=None, keepdims=False):
    """Mean and variance in one pass (ref: src/operator/nn/moments.cc)."""
    axes = _norm_axis(axes)
    mean_ = jnp.mean(data, axis=axes, keepdims=keepdims)
    var_ = jnp.var(data, axis=axes, keepdims=keepdims)
    return mean_, var_
