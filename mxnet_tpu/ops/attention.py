"""Attention kernels (ref: src/operator/contrib/transformer.cc:650-828).

The reference exposes interleaved-matmul ops over a packed (T, N, 3*H*D)
projection tensor. We keep that API for parity, plus a fused
`multi_head_attention` that is the TPU-preferred entry: one call that can be
swapped between the XLA path and a Pallas flash-attention kernel
(mxnet_tpu.ops.pallas_attention) by size heuristic.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ..base import register_op

__all__ = []


def _reg(fn):
    register_op(fn.__name__)(fn)
    __all__.append(fn.__name__)
    return fn


def _split_heads_interleaved(queries_keys_values, num_heads, parts):
    """(T, N, parts*H*D) interleaved per head → list of (N*H, T, D)."""
    T, N, tot = queries_keys_values.shape
    D = tot // (num_heads * parts)
    x = queries_keys_values.reshape(T, N, num_heads, parts, D)
    outs = []
    for p in range(parts):
        part = x[:, :, :, p, :]                       # (T, N, H, D)
        part = part.transpose(1, 2, 0, 3)             # (N, H, T, D)
        outs.append(part.reshape(N * num_heads, T, D))
    return outs


@_reg
def interleaved_matmul_selfatt_qk(queries_keys_values, heads=1):
    """scores = scaled Q·K^T from packed qkv (ref: transformer.cc:650)."""
    q, k, _ = _split_heads_interleaved(queries_keys_values, heads, 3)
    scale = 1.0 / math.sqrt(q.shape[-1])
    return jnp.matmul(q * scale, jnp.swapaxes(k, -1, -2))


@_reg
def interleaved_matmul_selfatt_valatt(queries_keys_values, attention, heads=1):
    """out = att·V, re-packed to (T, N, H*D) (ref: transformer.cc:708)."""
    _, _, v = _split_heads_interleaved(queries_keys_values, heads, 3)
    out = jnp.matmul(attention, v)                    # (N*H, T, D)
    NH, T, D = out.shape
    N = NH // heads
    out = out.reshape(N, heads, T, D).transpose(2, 0, 1, 3)
    return out.reshape(T, N, heads * D)


@_reg
def interleaved_matmul_encdec_qk(queries, keys_values, heads=1):
    """Ref: transformer.cc:766. queries (Tq, N, H*D); keys_values (Tk, N, 2*H*D)."""
    Tq, N, tot = queries.shape
    D = tot // heads
    q = queries.reshape(Tq, N, heads, D).transpose(1, 2, 0, 3).reshape(
        N * heads, Tq, D)
    k, _ = _split_heads_interleaved(keys_values, heads, 2)
    scale = 1.0 / math.sqrt(D)
    return jnp.matmul(q * scale, jnp.swapaxes(k, -1, -2))


@_reg
def interleaved_matmul_encdec_valatt(keys_values, attention, heads=1):
    _, v = _split_heads_interleaved(keys_values, heads, 2)
    out = jnp.matmul(attention, v)
    NH, T, D = out.shape
    N = NH // heads
    out = out.reshape(N, heads, T, D).transpose(2, 0, 1, 3)
    return out.reshape(T, N, heads * D)


@_reg
def div_sqrt_dim(data):
    """Ref: transformer.cc _contrib_div_sqrt_dim."""
    return data / math.sqrt(data.shape[-1])


def _as_key_padding_mask(mask, N, Tk):
    """If `mask` is a key-padding mask — broadcastable (N,1,1,Tk) or
    (N,Tk), boolean or additive — return it as (N, Tk); else None."""
    if mask is None:
        return None
    shp = tuple(mask.shape)
    if shp == (N, Tk):
        return mask
    if len(shp) == 4 and shp[0] in (1, N) and shp[1] == 1 and shp[2] == 1 \
            and shp[3] == Tk:
        m = mask.reshape(shp[0], Tk)
        if shp[0] == 1:
            m = jnp.broadcast_to(m, (N, Tk))
        return m
    return None


@_reg
def multi_head_attention(query, key, value, mask=None, num_heads=1,
                         dropout_p=0.0, causal=False, use_pallas='auto'):
    """Fused MHA on (N, T, H*D)-shaped q/k/v. The TPU-native attention entry.

    use_pallas: 'auto' routes through the Pallas flash kernel whenever an
    accelerator backend is active and the mask (if any) is a key-padding
    mask — this covers the flagship BERT@512-with-padding-mask config.
    Arbitrary (per-query) masks fall back to the XLA path.
    """
    N, Tq, tot = query.shape
    H = num_heads
    D = tot // H
    q = query.reshape(N, Tq, H, D).transpose(0, 2, 1, 3)
    k = key.reshape(N, key.shape[1], H, D).transpose(0, 2, 1, 3)
    v = value.reshape(N, value.shape[1], H, D).transpose(0, 2, 1, 3)

    if use_pallas in ('auto', True):
        from .pallas_attention import flash_attention, pallas_available
        kpm = _as_key_padding_mask(mask, N, k.shape[2])
        if (use_pallas is True or pallas_available()) and \
                (mask is None or kpm is not None):
            if kpm is not None:
                # same semantics as the XLA path below: truthy = keep
                kpm = kpm.astype(jnp.bool_)
            out = flash_attention(q, k, v, key_mask=kpm, causal=causal)
            return out.transpose(0, 2, 1, 3).reshape(N, Tq, tot)

    scale = 1.0 / math.sqrt(D)
    scores = jnp.einsum('nhqd,nhkd->nhqk', q * scale, k,
                        preferred_element_type=jnp.float32)
    if causal:
        Tk = k.shape[2]
        cmask = jnp.tril(jnp.ones((Tq, Tk), bool))
        scores = jnp.where(cmask, scores, -1e30)
    if mask is not None:
        scores = jnp.where(mask.astype(bool), scores, -1e30)
    att = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    out = jnp.einsum('nhqk,nhkd->nhqd', att, v)
    return out.transpose(0, 2, 1, 3).reshape(N, Tq, tot)
