"""Attention kernels (ref: src/operator/contrib/transformer.cc:650-828).

The reference exposes interleaved-matmul ops over a packed (T, N, 3*H*D)
projection tensor. We keep that API for parity, plus a fused
`multi_head_attention` that is the TPU-preferred entry: one call that can be
swapped between the XLA path and a Pallas flash-attention kernel
(mxnet_tpu.ops.pallas_attention) by size heuristic.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ..base import register_op, state as _flags
from .. import random as _random

__all__ = []


def _reg(fn):
    register_op(fn.__name__)(fn)
    __all__.append(fn.__name__)
    return fn


def _split_heads_interleaved(queries_keys_values, num_heads, parts):
    """(T, N, parts*H*D) interleaved per head → list of (N*H, T, D)."""
    T, N, tot = queries_keys_values.shape
    D = tot // (num_heads * parts)
    x = queries_keys_values.reshape(T, N, num_heads, parts, D)
    outs = []
    for p in range(parts):
        part = x[:, :, :, p, :]                       # (T, N, H, D)
        part = part.transpose(1, 2, 0, 3)             # (N, H, T, D)
        outs.append(part.reshape(N * num_heads, T, D))
    return outs


@_reg
def interleaved_matmul_selfatt_qk(queries_keys_values, heads=1):
    """scores = scaled Q·K^T from packed qkv (ref: transformer.cc:650)."""
    q, k, _ = _split_heads_interleaved(queries_keys_values, heads, 3)
    scale = 1.0 / math.sqrt(q.shape[-1])
    return jnp.matmul(q * scale, jnp.swapaxes(k, -1, -2))


@_reg
def interleaved_matmul_selfatt_valatt(queries_keys_values, attention, heads=1):
    """out = att·V, re-packed to (T, N, H*D) (ref: transformer.cc:708)."""
    _, _, v = _split_heads_interleaved(queries_keys_values, heads, 3)
    out = jnp.matmul(attention, v)                    # (N*H, T, D)
    NH, T, D = out.shape
    N = NH // heads
    out = out.reshape(N, heads, T, D).transpose(2, 0, 1, 3)
    return out.reshape(T, N, heads * D)


@_reg
def interleaved_matmul_encdec_qk(queries, keys_values, heads=1):
    """Ref: transformer.cc:766. queries (Tq, N, H*D); keys_values (Tk, N, 2*H*D)."""
    Tq, N, tot = queries.shape
    D = tot // heads
    q = queries.reshape(Tq, N, heads, D).transpose(1, 2, 0, 3).reshape(
        N * heads, Tq, D)
    k, _ = _split_heads_interleaved(keys_values, heads, 2)
    scale = 1.0 / math.sqrt(D)
    return jnp.matmul(q * scale, jnp.swapaxes(k, -1, -2))


@_reg
def interleaved_matmul_encdec_valatt(keys_values, attention, heads=1):
    _, v = _split_heads_interleaved(keys_values, heads, 2)
    out = jnp.matmul(attention, v)
    NH, T, D = out.shape
    N = NH // heads
    out = out.reshape(N, heads, T, D).transpose(2, 0, 1, 3)
    return out.reshape(T, N, heads * D)


@_reg
def div_sqrt_dim(data):
    """Ref: transformer.cc _contrib_div_sqrt_dim."""
    return data / math.sqrt(data.shape[-1])


def _as_key_padding_mask(mask, N, Tk):
    """If `mask` is a key-padding mask — broadcastable (N,1,1,Tk) or
    (N,Tk) — return it as (N, Tk) preserving its dtype; else None.
    Mask convention (both attention paths, torch-style): boolean/integer
    masks are keep/drop (truthy = keep); floating masks are ADDITIVE
    (0.0 = keep, large-negative = drop) and are added to the scores."""
    if mask is None:
        return None
    shp = tuple(mask.shape)
    if shp == (N, Tk):
        return mask
    if len(shp) == 4 and shp[0] in (1, N) and shp[1] == 1 and shp[2] == 1 \
            and shp[3] == Tk:
        m = mask.reshape(shp[0], Tk)
        if shp[0] == 1:
            m = jnp.broadcast_to(m, (N, Tk))
        return m
    return None


_pallas_fallback_warned = [False]

# trace-time routing telemetry: [pallas_hits, xla_hits]. Incremented when
# multi_head_attention picks a path (once per trace, not per step — jit
# caches the traced program). Lets benches/tests assert the flagship
# config really routes through the flash kernel.
route_counts = {'pallas': 0, 'xla': 0, 'ring': 0}

# active sequence-parallel config: (mesh, axis) or None
_seq_parallel = []


class sequence_parallel:
    """Context manager routing `multi_head_attention` through ring
    attention over `mesh`'s `axis` — transparent long-context support:
    models keep calling the fused op, the sequence dimension shards over
    the mesh and K/V blocks rotate on ICI neighbor links
    (parallel/ring_attention.py; no reference equivalent — it bucketed
    long sequences instead).

        with mx.ops.attention.sequence_parallel(mesh, 'sp'):
            out = model(tokens)          # attention is now ring attention
    """

    def __init__(self, mesh, axis='sp'):
        self._cfg = (mesh, axis)

    def __enter__(self):
        _seq_parallel.append(self._cfg)
        return self

    def __exit__(self, *exc):
        _seq_parallel.pop()


@_reg
def multi_head_attention(query, key, value, mask=None, num_heads=1,
                         dropout_p=0.0, causal=False, use_pallas='auto',
                         dropout_key=None):
    """Fused MHA on (N, T, H*D)-shaped q/k/v. The TPU-native attention entry.

    Mask convention (torch-style, identical on both paths): boolean/integer
    masks are keep/drop (truthy = keep); floating masks are ADDITIVE
    (0.0 = keep, large-negative = drop), added to the pre-softmax scores.

    use_pallas: 'auto' routes through the Pallas flash kernel whenever an
    accelerator backend is active and the mask (if any) is a key-padding
    mask — this covers the flagship BERT@512-with-padding-mask config.
    Arbitrary (per-query) masks fall back to the XLA path. Under 'auto' a
    Pallas trace failure degrades to the XLA path with a one-time warning;
    use_pallas=True raises.

    dropout_p: attention-probability dropout, applied after softmax (the
    standard transformer recipe), active in autograd training mode (same
    gate as the dropout op). The PRNG key comes from the framework key
    provider unless dropout_key overrides it. On the Pallas route the
    dropout keep-mask is generated INSIDE the kernel (counter-based PRNG
    seeded from the key), so the T×T probability matrix is never
    materialised even in training; the flagship BERT config (dropout=0.1)
    runs the flash kernel.
    """
    N, Tq, tot = query.shape
    H = num_heads
    D = tot // H
    q = query.reshape(N, Tq, H, D).transpose(0, 2, 1, 3)
    k = key.reshape(N, key.shape[1], H, D).transpose(0, 2, 1, 3)
    v = value.reshape(N, value.shape[1], H, D).transpose(0, 2, 1, 3)

    apply_dropout = dropout_p > 0.0 and (dropout_key is not None
                                         or _flags.is_training)

    # key-padding-mask normalization shared by the ring and Pallas
    # routes: (N, Tk), boolean truthy-keep (floating stays additive)
    kpm = _as_key_padding_mask(mask, N, k.shape[2])
    if kpm is not None and not jnp.issubdtype(kpm.dtype, jnp.floating):
        kpm = kpm.astype(jnp.bool_)

    if _seq_parallel:
        Tk = k.shape[2]
        # dropout no longer blocks the ring route: the ring kernel
        # regenerates the keep mask in-kernel from global coordinates
        # (same counter-based PRNG as the Pallas flash kernel), so the
        # flagship config (dropout=0.1) rides sequence parallelism
        routable = (Tq == Tk and (mask is None or kpm is not None))
        sp_mesh, sp_axis = _seq_parallel[-1]
        if routable and Tq % sp_mesh.shape[sp_axis] != 0:
            routable = False
        if routable:
            from ..parallel.ring_attention import ring_attention
            ring_kwargs = {}
            if apply_dropout:
                key_ = dropout_key if dropout_key is not None \
                    else _random.next_key()
                ring_kwargs = dict(
                    dropout_p=dropout_p,
                    dropout_seed=jax.random.bits(key_, (1,), jnp.uint32))
            out = ring_attention(q, k, v, sp_mesh, sp_axis=sp_axis,
                                 causal=causal, key_mask=kpm,
                                 **ring_kwargs)
            route_counts['ring'] += 1
            return out.transpose(0, 2, 1, 3).reshape(N, Tq, tot)
        # inside the context but unroutable (cross attention, per-query
        # mask, indivisible T): fall through to the dense path — loudly,
        # because the user asked for ring attention
        import warnings
        reason = ('cross-attention / per-query mask / sequence length '
                  'not divisible by the sp axis')
        warnings.warn(
            f"sequence_parallel: falling back to dense attention "
            f"({reason}); the T x T score tensor will be materialized.",
            RuntimeWarning)

    if use_pallas in ('auto', True):
        from .pallas_attention import flash_attention, pallas_available
        if (use_pallas is True or pallas_available()) and \
                (mask is None or kpm is not None):
            try:
                if apply_dropout:
                    key_ = dropout_key if dropout_key is not None \
                        else _random.next_key()
                    seed = jax.random.bits(key_, (1, 1), jnp.uint32)
                    out = flash_attention(q, k, v, key_mask=kpm,
                                          causal=causal,
                                          dropout_p=dropout_p,
                                          dropout_seed=seed)
                else:
                    out = flash_attention(q, k, v, key_mask=kpm,
                                          causal=causal)
                route_counts['pallas'] += 1
                return out.transpose(0, 2, 1, 3).reshape(N, Tq, tot)
            except Exception:
                if use_pallas is True:
                    raise
                if not _pallas_fallback_warned[0]:
                    _pallas_fallback_warned[0] = True
                    import warnings
                    warnings.warn(
                        "Pallas flash attention failed to trace; falling "
                        "back to the XLA attention path for this process.",
                        RuntimeWarning)

    route_counts['xla'] += 1
    scale = 1.0 / math.sqrt(D)
    scores = jnp.einsum('nhqd,nhkd->nhqk', q * scale, k,
                        preferred_element_type=jnp.float32)
    if causal:
        Tk = k.shape[2]
        cmask = jnp.tril(jnp.ones((Tq, Tk), bool))
        scores = jnp.where(cmask, scores, -1e30)
    if mask is not None:
        if jnp.issubdtype(mask.dtype, jnp.floating):
            scores = scores + mask.astype(scores.dtype)
        else:
            scores = jnp.where(mask.astype(bool), scores, -1e30)
    att = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    if apply_dropout:
        if dropout_key is None:
            dropout_key = _random.next_key()
        keep = jax.random.bernoulli(dropout_key, 1.0 - dropout_p, att.shape)
        att = jnp.where(keep, att / (1.0 - dropout_p),
                        jnp.zeros_like(att)).astype(q.dtype)
    out = jnp.einsum('nhqk,nhkd->nhqd', att, v)
    return out.transpose(0, 2, 1, 3).reshape(N, Tq, tot)
