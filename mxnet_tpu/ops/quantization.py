"""INT8 quantization ops (ref: src/operator/quantization/).

TPU-first design: the reference implements quantized kernels with MKLDNN /
cuDNN (quantized_conv.cc, quantized_fully_connected.cc, quantize_v2.cc,
dequantize.cc, requantize.cc).  On TPU the MXU multiplies int8 operands
natively with int32 accumulation, which XLA reaches through
``lax.dot_general(..., preferred_element_type=int32)`` on int8 inputs — so
quantized compute here is ordinary traced ops, fused and scheduled by XLA,
not hand-written kernels.

Quantization scheme (matches reference semantics):
  * int8: symmetric.  scale = 127 / max(|min|, |max|);  q = round(x * scale)
  * uint8: affine.    scale = 255 / (max - min);        q = round((x-min)*scale)
  * int8 x int8 matmul/conv accumulates to int32; the float range of the
    int32 output follows the reference's quantization_range_for_multiplication
    (quantization_utils.h): out_range = int32_range / (scale_data*scale_weight).
"""
from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from ..base import register_op
from .nn import _tup, _CONV_DN

__all__ = []

INT8_RANGE = 127.0
UINT8_RANGE = 255.0
INT32_RANGE = float(2 ** 31 - 1)


def _reg(fn, num_outputs=1):
    register_op(fn.__name__, num_outputs=num_outputs, nograd=True)(fn)
    __all__.append(fn.__name__)
    return fn


def _regn(n):
    return lambda fn: _reg(fn, num_outputs=n)


def _scalar(x):
    """Accept python floats or 1-element arrays for range arguments."""
    if hasattr(x, 'shape'):
        return jnp.reshape(x, ()).astype(jnp.float32)
    return jnp.float32(x)


def _rng(x):
    """Range argument that may be a scalar (tensor-wise) or a per-channel
    vector (channel-wise weight quantization)."""
    return jnp.asarray(x, jnp.float32)


def int8_scale(min_range, max_range):
    amax = jnp.maximum(jnp.abs(_rng(min_range)), jnp.abs(_rng(max_range)))
    return INT8_RANGE / jnp.maximum(amax, 1e-30)


@_regn(3)
def quantize(data, min_range, max_range, out_type='uint8'):
    """Affine/symmetric quantize with explicit range (ref: quantize.cc)."""
    lo, hi = _scalar(min_range), _scalar(max_range)
    if out_type == 'uint8':
        scale = UINT8_RANGE / jnp.maximum(hi - lo, 1e-30)
        q = jnp.clip(jnp.round((data.astype(jnp.float32) - lo) * scale),
                     0, 255).astype(jnp.uint8)
        return q, lo, hi
    scale = int8_scale(lo, hi)
    q = jnp.clip(jnp.round(data.astype(jnp.float32) * scale),
                 -127, 127).astype(jnp.int8)
    amax = INT8_RANGE / scale
    return q, -amax, amax


@_regn(3)
def quantize_v2(data, out_type='int8', min_calib_range=None,
                max_calib_range=None):
    """Quantize with calibrated or on-the-fly range (ref: quantize_v2.cc)."""
    if out_type == 'auto':
        out_type = 'int8'
    if min_calib_range is None or max_calib_range is None:
        lo = jnp.min(data).astype(jnp.float32)
        hi = jnp.max(data).astype(jnp.float32)
    else:
        lo, hi = _scalar(min_calib_range), _scalar(max_calib_range)
    return quantize(data, lo, hi, out_type=out_type)


@_reg
def dequantize(data, min_range, max_range, out_type='float32'):
    """Ref: dequantize.cc. Ranges broadcast against ``data``, so per-channel
    int32 accumulator ranges (channel-wise weights) dequantize correctly."""
    lo, hi = _rng(min_range), _rng(max_range)
    if data.dtype == jnp.uint8:
        scale = UINT8_RANGE / jnp.maximum(hi - lo, 1e-30)
        return (data.astype(jnp.float32) / scale + lo).astype(out_type)
    if data.dtype == jnp.int32:
        scale = INT32_RANGE / jnp.maximum(jnp.abs(lo), jnp.abs(hi))
        return (data.astype(jnp.float32) / scale).astype(out_type)
    scale = int8_scale(lo, hi)
    return (data.astype(jnp.float32) / scale).astype(out_type)


@_regn(3)
def requantize(data, min_range, max_range, min_calib_range=None,
               max_calib_range=None):
    """int32 -> int8 rescale (ref: requantize.cc). Accepts per-channel
    accumulator ranges (reduced to one output scale)."""
    f = dequantize(data, min_range, max_range)
    if min_calib_range is not None and max_calib_range is not None:
        lo = jnp.min(_rng(min_calib_range))
        hi = jnp.max(_rng(max_calib_range))
    else:
        lo = jnp.min(f)
        hi = jnp.max(f)
    return quantize(f, lo, hi, out_type='int8')


def _mul_out_range(min_d, max_d, min_w, max_w):
    """Float range represented by the int32 accumulator
    (ref: quantization_utils.h quantization_range_for_multiplication).
    ``min_w``/``max_w`` may be per-output-channel vectors."""
    sd = int8_scale(min_d, max_d)
    sw = int8_scale(min_w, max_w)
    amax = INT32_RANGE / (sd * sw)
    return -amax, amax, sd, sw


@_regn(3)
def quantized_fully_connected(data, weight, bias=None, min_data=None,
                              max_data=None, min_weight=None, max_weight=None,
                              min_bias=None, max_bias=None, num_hidden=None,
                              no_bias=False, flatten=True):
    """int8 x int8 -> int32 FC on the MXU (ref: quantized_fully_connected.cc).

    ``data``/``weight`` are int8; bias (if given) is int8 with its own range
    and is rescaled into the int32 accumulator's scale.
    """
    if flatten and data.ndim > 2:
        data = data.reshape(data.shape[0], -1)
    out = lax.dot_general(data, weight,
                          (((data.ndim - 1,), (1,)), ((), ())),
                          preferred_element_type=jnp.int32)
    lo, hi, sd, sw = _mul_out_range(min_data, max_data, min_weight, max_weight)
    if bias is not None and not no_bias:
        sb = int8_scale(min_bias, max_bias)
        bias32 = jnp.round(bias.astype(jnp.float32) / sb * (sd * sw))
        out = out + bias32.astype(jnp.int32)
    return out, lo, hi


@_regn(3)
def quantized_conv(data, weight, bias=None, min_data=None, max_data=None,
                   min_weight=None, max_weight=None, min_bias=None,
                   max_bias=None, kernel=None, stride=None, dilate=None,
                   pad=None, num_filter=0, num_group=1, no_bias=False,
                   layout='NCHW'):
    """int8 conv with int32 accumulation (ref: quantized_conv.cc)."""
    nd = data.ndim - 2
    stride = _tup(stride, nd) if stride is not None else (1,) * nd
    dilate = _tup(dilate, nd) if dilate is not None else (1,) * nd
    pad = _tup(pad, nd)
    dn = _CONV_DN[nd]
    out = lax.conv_general_dilated(
        data, weight, window_strides=stride,
        padding=[(p, p) for p in pad], rhs_dilation=dilate,
        dimension_numbers=dn, feature_group_count=num_group,
        preferred_element_type=jnp.int32)
    lo, hi, sd, sw = _mul_out_range(min_data, max_data, min_weight, max_weight)
    if getattr(lo, 'ndim', 0):
        # per-channel ranges must broadcast over the NCHW channel axis
        lo = lo.reshape((-1,) + (1,) * nd)
        hi = hi.reshape((-1,) + (1,) * nd)
    if bias is not None and not no_bias:
        sb = int8_scale(min_bias, max_bias)
        bias32 = jnp.round(bias.astype(jnp.float32) / sb * (sd * sw))
        out = out + bias32.astype(jnp.int32).reshape((1, -1) + (1,) * nd)
    return out, lo, hi


@_regn(3)
def quantized_pooling(data, min_data, max_data, kernel=None, stride=None,
                      pad=None, pool_type='max', global_pool=False):
    """Pooling runs directly on the int8 domain (ref: quantized_pooling.cc);
    max-pool is exact, avg-pool accumulates in int32 then rounds back."""
    nd = data.ndim - 2
    if global_pool:
        kernel = data.shape[2:]
        stride = (1,) * nd
        pad = (0,) * nd
    kernel = _tup(kernel, nd)
    stride = _tup(stride, nd) if stride is not None else (1,) * nd
    pad = _tup(pad, nd)
    dims = (1, 1) + kernel
    strides = (1, 1) + stride
    padding = ((0, 0), (0, 0)) + tuple((p, p) for p in pad)
    info = jnp.iinfo(data.dtype)
    if pool_type == 'max':
        out = lax.reduce_window(data, jnp.array(info.min, data.dtype),
                                lax.max, dims, strides, padding)
    else:
        s = lax.reduce_window(data.astype(jnp.int32), jnp.int32(0), lax.add,
                              dims, strides, padding)
        n = 1
        for k in kernel:
            n *= k
        out = jnp.clip(jnp.round(s / n), info.min, info.max).astype(data.dtype)
    return out, _rng(min_data), _rng(max_data)


@_regn(3)
def quantized_flatten(data, min_data, max_data):
    """Ref: quantized_flatten.cc. Per-channel ranges are reduced to one
    scale: flattening mixes channels, so a vector range no longer maps to
    an axis of the output."""
    lo, hi = _rng(min_data), _rng(max_data)
    return data.reshape(data.shape[0], -1), jnp.min(lo), jnp.max(hi)


def _abs_max(lo, hi):
    """Largest magnitude an input's (possibly per-channel) range spans."""
    return jnp.maximum(jnp.abs(_rng(lo)), jnp.abs(_rng(hi))).max()


@_regn(3)
def quantized_concat(*args, dim=1):
    """Concat int8 inputs after rescaling to a shared range
    (ref: quantized_concat.cc). Args: d0, min0, max0, d1, min1, max1, ..."""
    n = len(args) // 3
    datas = args[0::3][:n]
    mins = list(args[1::3][:n])
    maxs = list(args[2::3][:n])
    amax = jnp.stack([_abs_max(lo, hi)
                      for lo, hi in zip(mins, maxs)]).max()
    s_out = INT8_RANGE / amax
    parts = []
    for d, lo, hi in zip(datas, mins, maxs):
        s_in = int8_scale(lo, hi)   # may be per-channel; broadcasts below
        parts.append(jnp.clip(jnp.round(d.astype(jnp.float32) / s_in * s_out),
                              -127, 127).astype(jnp.int8))
    return jnp.concatenate(parts, axis=dim), -amax, amax


@_regn(3)
def quantized_elemwise_add(lhs, rhs, min_lhs, max_lhs, min_rhs, max_rhs):
    """Ref: quantized_elemwise_add.cc — add in the dequantized domain,
    re-quantize to the combined range (XLA fuses this into one kernel)."""
    fl = dequantize(lhs, min_lhs, max_lhs)
    fr = dequantize(rhs, min_rhs, max_rhs)
    out = fl + fr
    amax = _abs_max(min_lhs, max_lhs) + _abs_max(min_rhs, max_rhs)
    s = INT8_RANGE / jnp.maximum(amax, 1e-30)
    q = jnp.clip(jnp.round(out * s), -127, 127).astype(jnp.int8)
    return q, -amax, amax
