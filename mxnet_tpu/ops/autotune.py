"""Searched, not hardcoded: the Pallas kernel autotuner (ISSUE 18).

The flash-attention block shapes (G head-group, bq/bk sequence tiles)
were hand-picked constants in ``pallas_attention._block_sizes`` with raw
env overrides — exactly how round 3's Mosaic lowering failure (a 2-D
(1, bk) mask block violating the 8×128 trailing-tile rule) shipped.
This module converts that one hand-tuned hot path into a searched one:

1. **Legality enumerator** — :func:`legal_candidates` generates every
   (G, bq, bk) candidate for a (batch·heads, Tq, Tk, D, dtype, kind)
   kernel instance and statically rejects anything Mosaic would refuse
   to lower (the trailing-two-dims (sublane-multiple, 128-multiple)
   tile rule checked per operand block via :func:`tile_legal`), anything
   whose grid would strand head slices (G must divide BH), and anything
   over the ~16 MB scoped-VMEM budget (:func:`vmem_bytes`, the same
   arithmetic ``_block_sizes`` guards with). Illegal shapes are pruned
   BEFORE compile — never attempted.

2. **Measured sweep** — :func:`sweep_flash_attention` ranks survivors
   by the analytic cost model and, on a real TPU, AOT-compiles and
   times the top candidates (median of k reps; compile time excluded by
   timing only the pre-compiled executable, with each compile recorded
   through the PR 15 compile-ledger phases under the
   ``autotune:flash_attention`` site). On CPU backends the sweep
   degrades to legality-check + analytic ranking so the whole plumbing
   is testable chipless. Winners persist in an atomic JSON tuning DB
   keyed by (device_kind, kernel, shape-signature) under
   ``MXTPU_AUTOTUNE_DIR``.

3. **Build-time resolution** — ``_block_sizes`` calls :func:`resolve`,
   which applies the precedence **explicit env override > DB winner >
   caller defaults** (a sweep in progress force-feeds candidates at a
   higher, internal-only precedence), re-validates whatever won against
   the legality rules, clamps to the VMEM budget, and records the
   decision in a process-global registry. ``ShardedTrainStep`` folds
   :func:`decision_flags` into its compile-ledger signature, so a DB
   change that alters a consumed block shape is a named ``flag``
   recompile axis — not silent churn.

Telemetry: ``mxnet_tpu_autotune_*`` counters (candidates pruned/timed,
sweep seconds, DB hits/misses) and the ``autotune.sweep`` span, both
declared in tools/mxtpu_lint/contracts.py.
"""
from __future__ import annotations

import contextlib
import functools
import json
import math
import os
import threading
import time
import warnings

import jax
import jax.numpy as jnp

from ..base import MXNetError, telem_flags as _telem

__all__ = [
    'sublane_min', 'tile_legal', 'fa_block_layouts', 'vmem_bytes',
    'check_candidate', 'legal_candidates', 'analytic_cost', 'shape_sig',
    'db_path', 'load_db', 'db_lookup', 'record_winner', 'resolve',
    'decisions', 'decision_flags', 'clear', 'forced',
    'sweep_flash_attention',
]

KERNEL_FA = 'flash_attention'
DB_BASENAME = 'mxtpu_autotune.json'
DB_VERSION = 1

# Mosaic scoped-VMEM stack limit is 16 MB; _block_sizes has always
# budgeted 14 MB to leave headroom for the compiler's own spills.
VMEM_BUDGET = 14 * 2 ** 20

_LANE = 128


def _metrics_mod():
    from ..telemetry import metrics as _metrics
    return _metrics


# ---------------------------------------------------------------------------
# Mosaic legality rules
# ---------------------------------------------------------------------------

def sublane_min(dtype) -> int:
    """Minimum second-to-last (sublane) tile dim for ``dtype``: 8 for
    4-byte types, 16 for bf16/fp16, 32 for 1-byte types."""
    size = jnp.dtype(dtype).itemsize
    return {4: 8, 2: 16, 1: 32}.get(size, 8)


def tile_legal(array_shape, block_shape, dtype):
    """Mosaic trailing-tile rule for ONE operand: each of the block's
    trailing two dims must be a multiple of the minimum tile (sublane
    for the second-to-last, 128 lanes for the last) OR equal to the
    whole array dim. Returns (ok, reason-or-None).

    Round 3's failure shape is the canonical counterexample: a 2-D
    key-mask block (1, 512) over a (BH, Tk) array — 1 is neither a
    multiple of 8 nor equal to BH, so Mosaic refuses to lower it (the
    fix rides the mask as (BH, 1, Tk) with (G, 1, bk) blocks, whose
    trailing-two dims (1, bk) match the array's (1, Tk) leading dim
    exactly)."""
    if len(array_shape) != len(block_shape):
        return False, (f"rank mismatch: block {block_shape} vs array "
                       f"{array_shape}")
    if len(block_shape) >= 2:
        sub, lane = block_shape[-2], block_shape[-1]
        asub, alane = array_shape[-2], array_shape[-1]
        if sub % sublane_min(dtype) and sub != asub:
            return False, (f"sublane dim {sub} is not a multiple of "
                           f"{sublane_min(dtype)} and != array dim {asub}")
        if lane % _LANE and lane != alane:
            return False, (f"lane dim {lane} is not a multiple of "
                           f"{_LANE} and != array dim {alane}")
    elif block_shape:
        if block_shape[0] % _LANE and block_shape[0] != array_shape[0]:
            return False, (f"lane dim {block_shape[0]} is not a multiple "
                           f"of {_LANE} and != array dim {array_shape[0]}")
    return True, None


def _pad_up(n, b):
    return -(-n // b) * b


def fa_block_layouts(BH, Tq, Tk, D, kind, G, bq, bk):
    """(name, array_shape, block_shape) for every operand block the
    flash kernels of ``kind`` would instantiate at (G, bq, bk) — the
    exact layouts ``_fa_forward``/``_fa_backward`` build, including the
    bq/bk padding of the sequence dims."""
    tq, tk = _pad_up(Tq, bq), _pad_up(Tk, bk)
    layouts = [
        ('q', (BH, tq, D), (G, bq, D)),
        ('k', (BH, tk, D), (G, bk, D)),
        ('v', (BH, tk, D), (G, bk, D)),
        ('kmask', (BH, 1, tk), (G, 1, bk)),
        ('lse', (BH, tq, 1), (G, bq, 1)),
    ]
    if kind == 'fwd':
        layouts.append(('out', (BH, tq, D), (G, bq, D)))
    else:
        layouts += [('do', (BH, tq, D), (G, bq, D)),
                    ('delta', (BH, tq, 1), (G, bq, 1)),
                    ('dq', (BH, tq, D), (G, bq, D)),
                    ('dk', (BH, tk, D), (G, bk, D)),
                    ('dv', (BH, tk, D), (G, bk, D))]
    return layouts


def vmem_bytes(G, bq, bk, D, kind):
    """Scoped-VMEM estimate for one kernel invocation: double-buffered
    IO blocks + f32 scratch accumulators + the live (bq, bk) f32 stack
    temporaries (~3 forward: s/p/pv; ~6 backward: s/p/dp/ds/keep/pv).
    The same arithmetic ``_block_sizes`` has guarded with since round 4."""
    n_tmp = 3 if kind == 'fwd' else 6
    return (2 * G * (bq + 2 * bk) * D * 4
            + G * (bq + bk) * (D + 256) * 4
            + n_tmp * bq * bk * 4)


def check_candidate(BH, Tq, Tk, D, dtype, kind, G, bq, bk):
    """Full static legality of one (G, bq, bk) candidate. Returns
    (ok, reason-or-None); every reject reason names the rule so sweep
    reports and tests can assert WHY a shape was pruned."""
    sub = sublane_min(dtype)
    if G < 1 or BH % G:
        return False, f"G={G} does not divide BH={BH}"
    if bq < 1 or bk < 1:
        return False, f"non-positive block ({bq}, {bk})"
    if bq % sub or bk % sub:
        # padded seq dims are always bq/bk multiples, so a non-multiple
        # block can never equal its array dim — reject outright
        return False, (f"blocks ({bq}, {bk}) not multiples of the "
                       f"{sub}-row sublane tile")
    for name, ashape, bshape in fa_block_layouts(BH, Tq, Tk, D, kind,
                                                 G, bq, bk):
        ok, why = tile_legal(ashape, bshape, dtype)
        if not ok:
            return False, f"{name}: {why}"
    vb = vmem_bytes(G, bq, bk, D, kind)
    if vb > VMEM_BUDGET:
        return False, (f"VMEM estimate {vb} exceeds the "
                       f"{VMEM_BUDGET}-byte budget")
    return True, None


def legal_candidates(BH, Tq, Tk, D, dtype, kind='fwd'):
    """All statically legal (G, bq, bk) candidates for one kernel
    instance, plus the count of enumerated-but-pruned shapes. The
    candidate space is geometric (powers of two from the sublane
    minimum up to the per-kind cap, plus the exact sequence length when
    it is itself tile-aligned) over every divisor of BH up to 16."""
    sub = sublane_min(dtype)
    cap = 512 if kind == 'fwd' else 256

    def _seq_cands(T):
        vals = set()
        b = sub
        while b <= min(cap, _pad_up(T, sub)):
            vals.add(b)
            b *= 2
        if T % sub == 0 and T <= cap:
            vals.add(T)
        return sorted(vals)

    gs = [g for g in (1, 2, 4, 8, 16) if g <= BH and BH % g == 0]
    out, pruned = [], 0
    for G in gs:
        for bq in _seq_cands(Tq):
            for bk in _seq_cands(Tk):
                ok, _why = check_candidate(BH, Tq, Tk, D, dtype, kind,
                                           G, bq, bk)
                if ok:
                    out.append((G, bq, bk))
                else:
                    pruned += 1
    if _telem['on']:
        _metrics_mod().inc(
            'mxnet_tpu_autotune_candidates_pruned_total', pruned)
    return out, pruned


def analytic_cost(BH, Tq, Tk, D, dtype, kind, G, bq, bk):
    """Deterministic cost estimate (model-seconds) used to rank legal
    candidates: HBM block traffic over ~8e11 B/s + a fixed ~2 µs
    per-grid-step dispatch overhead (the term G amortises) + the
    padding waste of non-dividing blocks. A ranking heuristic, not a
    simulator — on TPU the sweep measures the top of this ranking; on
    CPU it IS the ranking."""
    item = jnp.dtype(dtype).itemsize
    nq, nk = -(-Tq // bq), -(-Tk // bk)
    steps = (BH // G) * nq * nk
    # per grid step: q block + k/v blocks stream in, o writes once per
    # q-row (amortise over nk), mask/lse are noise
    per_step = G * bq * D * item + 2 * G * bk * D * item \
        + (G * bq * D * item) / nk
    hbm_s = steps * per_step / 8e11
    dispatch_s = steps * 2e-6
    waste = (nq * bq * nk * bk) / float(Tq * Tk)
    mult = 2.5 if kind == 'bwd' else 1.0   # bwd ~2 kernels + recompute
    return (hbm_s + dispatch_s) * waste * mult


# ---------------------------------------------------------------------------
# shape signatures + tuning DB
# ---------------------------------------------------------------------------

def shape_sig(BH, Tq, Tk, D, dtype, kind):
    """Canonical shape-signature key: BH{.}Tq{.}Tk{.}D{.}dtype.kind."""
    return (f"BH{int(BH)}.Tq{int(Tq)}.Tk{int(Tk)}.D{int(D)}."
            f"{jnp.dtype(dtype).name}.{kind}")


def device_kind():
    try:
        return jax.devices()[0].device_kind.replace(' ', '_')
    except Exception:
        return 'unknown'


def db_path(dir_=None):
    """Path of the tuning DB under ``dir_`` (default: the registered
    ``MXTPU_AUTOTUNE_DIR`` knob), or None when no directory is set."""
    if dir_ is None:
        from .. import config as _config
        dir_ = _config.get('MXTPU_AUTOTUNE_DIR')
    if not dir_:
        return None
    return os.path.join(dir_, DB_BASENAME)


_lock = threading.Lock()
_db_cache = {}          # path -> (mtime, size, doc)
_corrupt_warned = set()  # paths already warned about


def load_db(path):
    """Parsed tuning DB at ``path`` ({} when absent). A corrupt or
    truncated DB falls back to {} — defaults stay in force — with ONE
    warning per path per process (an unreadable tuning cache must never
    take down training)."""
    try:
        st = os.stat(path)
    except OSError:
        return {}
    key = (st.st_mtime_ns, st.st_size)
    with _lock:
        cached = _db_cache.get(path)
        if cached is not None and cached[0] == key:
            return cached[1]
    doc = {}
    try:
        with open(path, 'rb') as f:
            raw = json.loads(f.read().decode('utf-8'))
        if not isinstance(raw, dict) or 'entries' not in raw \
                or not isinstance(raw['entries'], dict):
            raise ValueError('missing "entries" table')
        doc = raw
    except Exception as e:
        with _lock:
            first = path not in _corrupt_warned
            _corrupt_warned.add(path)
        if first:
            warnings.warn(
                f"autotune DB {path!r} is corrupt or truncated ({e}); "
                f"falling back to built-in block-size defaults",
                RuntimeWarning)
        return {}
    with _lock:
        _db_cache[path] = (key, doc)
    return doc


def db_lookup(kernel, sig, dir_=None):
    """DB winner blocks (G, bq, bk) for (device_kind, kernel, sig), or
    None. Counts mxnet_tpu_autotune_db_{hits,misses}_total."""
    path = db_path(dir_)
    if path is None:
        return None
    doc = load_db(path)
    entry = doc.get('entries', {}).get(f"{device_kind()}/{kernel}/{sig}")
    if entry is None:
        if _telem['on']:
            _metrics_mod().inc('mxnet_tpu_autotune_db_misses_total')
        return None
    try:
        g, bq, bk = (int(x) for x in entry['blocks'])
    except Exception:
        if _telem['on']:
            _metrics_mod().inc('mxnet_tpu_autotune_db_misses_total')
        return None
    if _telem['on']:
        _metrics_mod().inc('mxnet_tpu_autotune_db_hits_total')
    return g, bq, bk


def record_winner(kernel, sig, blocks, info=None, dir_=None):
    """Atomically merge one winner into the tuning DB (read-modify-
    write through serialization.atomic_write_file, so a concurrent
    reader sees either the old or the new complete file, never a torn
    one). Returns the DB path."""
    path = db_path(dir_)
    if path is None:
        raise MXNetError(
            "autotune: no tuning-DB directory — set MXTPU_AUTOTUNE_DIR "
            "or pass dir_= to record_winner()")
    os.makedirs(os.path.dirname(path) or '.', exist_ok=True)
    doc = load_db(path)
    if not doc:
        doc = {'version': DB_VERSION, 'entries': {}}
    entry = {'blocks': [int(b) for b in blocks]}
    if info:
        entry.update(info)
    doc['entries'][f"{device_kind()}/{kernel}/{sig}"] = entry
    from ..serialization import atomic_write_file
    atomic_write_file(path, json.dumps(doc, indent=1,
                                       sort_keys=True).encode('utf-8'))
    with _lock:
        _db_cache.pop(path, None)
    return path


# ---------------------------------------------------------------------------
# build-time resolution (the _block_sizes seam)
# ---------------------------------------------------------------------------

_forced = {}      # kernel-kind -> (G, bq, bk), sweep-internal precedence
_decisions = {}   # "kernel:sig" -> decision dict, process-global


@contextlib.contextmanager
def forced(kernel, kind, blocks):
    """Sweep-internal context: ``resolve`` returns ``blocks`` for every
    (kernel, kind) instance traced inside — how the sweep compiles each
    candidate without touching the user-facing env/DB precedence."""
    key = (kernel, kind)
    with _lock:
        prev = _forced.get(key)
        _forced[key] = tuple(int(b) for b in blocks)
    try:
        yield
    finally:
        with _lock:
            if prev is None:
                _forced.pop(key, None)
            else:
                _forced[key] = prev


def _env_overrides(kind):
    """Registered MXTPU_FA_{G,BQ,BK} / MXTPU_FA_BWD_* knob values
    (None when unset — 0 and negatives mean unset too, so a knob can be
    explicitly neutralised)."""
    from .. import config as _config
    pre = 'MXTPU_FA_BWD_' if kind == 'bwd' else 'MXTPU_FA_'
    out = {}
    for field in ('G', 'BQ', 'BK'):
        val = _config.get(pre + field)
        out[field.lower()] = int(val) if val and val > 0 else None
    return out


def resolve(kernel, BH, Tq, Tk, D, dtype, kind, default):
    """The block shapes a kernel build should use, with precedence
    (sweep-forced) > env override > DB winner > ``default``, followed
    by the safety clamps ``_block_sizes`` has always applied (G to a
    divisor of BH, then down until the VMEM estimate fits the budget).
    Records the decision — source included — for the compile-ledger
    signature (:func:`decision_flags`)."""
    sig = shape_sig(BH, Tq, Tk, D, dtype, kind)
    with _lock:
        force = _forced.get((kernel, kind))
    env = _env_overrides(kind)
    if force is not None:
        G, bq, bk = force
        source = 'forced'
    elif any(v is not None for v in env.values()):
        base = db_lookup(kernel, sig) or default
        G = env['g'] if env['g'] is not None else base[0]
        bq = env['bq'] if env['bq'] is not None else base[1]
        bk = env['bk'] if env['bk'] is not None else base[2]
        source = 'env'
    else:
        win = db_lookup(kernel, sig)
        if win is not None:
            G, bq, bk = win
            source = 'db'
        else:
            G, bq, bk = default
            source = 'default'
    # clamp G to a divisor of BH — a non-divisor would leave BH % G
    # head slices outside the grid with uninitialized outputs
    G = max(1, min(int(G), BH))
    while BH % G:
        G -= 1
    # scoped-VMEM guard: shrink G (to the next smaller divisor) until
    # the estimate fits — identical to the historical _block_sizes loop
    while G > 1 and vmem_bytes(G, bq, bk, D, kind) > VMEM_BUDGET:
        G -= 1
        while BH % G:
            G -= 1
    decision = {'blocks': (G, bq, bk), 'source': source}
    with _lock:
        _decisions[f"{kernel}:{sig}"] = decision
    return G, bq, bk


def decisions():
    """Snapshot of every block-shape decision made in this process:
    {"kernel:shape-sig": {'blocks': (G, bq, bk), 'source': ...}}."""
    with _lock:
        return {k: dict(v) for k, v in _decisions.items()}


def decision_flags():
    """The decisions as a flat {key: "source:GxBQxBK"} dict — the form
    ShardedTrainStep folds into its compile-ledger signature flags, so
    a DB change that alters a consumed shape surfaces as a named
    ``flag`` recompile axis in the forensics diff."""
    with _lock:
        return {k: f"{v['source']}:{'x'.join(map(str, v['blocks']))}"
                for k, v in sorted(_decisions.items())}


def clear():
    """Reset decision registry, DB cache and corrupt-DB warnings
    (tests; a fresh process starts clean anyway)."""
    with _lock:
        _decisions.clear()
        _db_cache.clear()
        _corrupt_warned.clear()
        _forced.clear()


# ---------------------------------------------------------------------------
# the sweep
# ---------------------------------------------------------------------------

def _time_candidate(fn, args, reps):
    """(compile_seconds, median_run_seconds) of ``fn`` at ``args``:
    AOT lower+compile first (wrapped in a compile-ledger window so the
    trace/lower/backend phase split lands in the PR 15 ledger), then
    time ``reps`` executions of the pre-compiled program — compile time
    is excluded from the medians by construction."""
    from ..telemetry import compile as _compile
    cctx = _compile.begin(f'autotune:{KERNEL_FA}')
    t0 = time.perf_counter()
    try:
        compiled = jax.jit(fn).lower(*args).compile()
    except BaseException:
        _compile.abort(cctx)
        raise
    _compile.end(cctx)
    compile_s = time.perf_counter() - t0
    jax.block_until_ready(compiled(*args))     # one warm run
    runs = []
    for _ in range(reps):
        t1 = time.perf_counter()
        jax.block_until_ready(compiled(*args))
        runs.append(time.perf_counter() - t1)
    runs.sort()
    return compile_s, runs[len(runs) // 2]


def sweep_flash_attention(batch=1, heads=12, seq=512, head_dim=64,
                          dtype=jnp.float32, kinds=('fwd', 'bwd'),
                          reps=5, max_timed=8, db_dir=None, measure=None,
                          causal=False):
    """Sweep the flash-attention block space for one shape and persist
    the winners in the tuning DB.

    measure: None (auto — time candidates only when a real TPU is
    present; CPU interpret-mode timings are meaningless so the sweep
    degrades to the analytic ranking), or an explicit bool. Only the
    ``max_timed`` analytically-best survivors are compiled and timed —
    the legality enumerator has already pruned everything Mosaic would
    reject, so every compile in the sweep is expected to succeed.

    Returns {kind: {winner, source, candidates, pruned, ranking}} plus
    a 'db' entry naming the persisted file."""
    from .pallas_attention import flash_attention, pallas_available
    from ..telemetry import trace as _trace
    if measure is None:
        measure = pallas_available()
    BH = batch * heads
    report = {'shape': {'batch': batch, 'heads': heads, 'seq': seq,
                        'head_dim': head_dim,
                        'dtype': jnp.dtype(dtype).name},
              'device_kind': device_kind(),
              'mode': 'measured' if measure else 'analytic'}
    t_sweep = time.perf_counter()
    with _trace.span('autotune.sweep', kernel=KERNEL_FA,
                     shape=f"b{batch}h{heads}s{seq}d{head_dim}"):
        for kind in kinds:
            cands, pruned = legal_candidates(BH, seq, seq, head_dim,
                                             dtype, kind)
            if not cands:
                raise MXNetError(
                    f"autotune: no legal ({kind}) candidate for "
                    f"BH={BH} T={seq} D={head_dim} — the shape cannot "
                    f"ride the flash kernel at all")
            ranked = sorted(
                cands, key=lambda c: analytic_cost(
                    BH, seq, seq, head_dim, dtype, kind, *c))
            rows = []
            if measure:
                q = jnp.zeros((batch, heads, seq, head_dim), dtype)
                timed = 0
                for cand in ranked[:max_timed]:
                    if kind == 'fwd':
                        def fn(q_, c=cand):
                            with forced(KERNEL_FA, 'fwd', c):
                                return flash_attention(q_, q_, q_,
                                                       causal=causal)
                    else:
                        def fn(q_, c=cand):
                            with forced(KERNEL_FA, 'bwd', c):
                                return jax.grad(
                                    lambda x: flash_attention(
                                        x, x, x,
                                        causal=causal).sum())(q_)
                    try:
                        compile_s, med = _time_candidate(fn, (q,), reps)
                    except Exception as e:  # pragma: no cover - chip only
                        rows.append({'blocks': list(cand),
                                     'error': str(e)[:200]})
                        continue
                    timed += 1
                    rows.append({'blocks': list(cand),
                                 'median_ms': round(med * 1e3, 4),
                                 'compile_s': round(compile_s, 3)})
                if _telem['on']:
                    _metrics_mod().inc(
                        'mxnet_tpu_autotune_candidates_timed_total',
                        timed)
                good = [r for r in rows if 'median_ms' in r]
                if not good:
                    raise MXNetError(
                        f"autotune: every timed ({kind}) candidate "
                        f"failed — see the sweep report rows")
                winner = min(good, key=lambda r: r['median_ms'])
                win_blocks = tuple(winner['blocks'])
                info = {'source': 'measured',
                        'median_ms': winner['median_ms'], 'reps': reps}
            else:
                for cand in ranked[:max_timed]:
                    rows.append({'blocks': list(cand),
                                 'analytic_ms': round(analytic_cost(
                                     BH, seq, seq, head_dim, dtype,
                                     kind, *cand) * 1e3, 4)})
                win_blocks = ranked[0]
                info = {'source': 'analytic',
                        'analytic_ms': rows[0]['analytic_ms']}
            sig = shape_sig(BH, seq, seq, head_dim, dtype, kind)
            path = record_winner(KERNEL_FA, sig, win_blocks, info,
                                 dir_=db_dir)
            report['db'] = path
            report[kind] = {'winner': list(win_blocks),
                            'source': info['source'],
                            'candidates': len(cands), 'pruned': pruned,
                            'signature': sig, 'ranking': rows}
    sweep_s = time.perf_counter() - t_sweep
    if _telem['on']:
        _metrics_mod().inc(
            'mxnet_tpu_autotune_sweep_seconds_total', sweep_s)
    report['sweep_seconds'] = round(sweep_s, 3)
    return report
