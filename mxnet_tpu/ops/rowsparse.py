"""RowSparse gradient kernels: id dedup + live-row lookup capture.

The reference treats ``row_sparse`` as a first-class gradient storage
type (ref: src/operator/tensor/indexing_op.cc EmbeddingOpBackwardEx,
``include/mxnet/ndarray.h kRowSparseStorage``): an Embedding/take
backward produces (unique row ids, row-block values) and the optimizer
touches only those rows. On the XLA path the same structure falls out
of a *dedup-first* lookup::

    uids, inv = unique_rows(ids)         # sort -> segment boundaries
    rows = weight[uids]                  # gather unique rows once
    out  = rows[inv]                     # fan back out to every slot

whose transpose segment-sums the per-occurrence cotangents into one
row block per unique id (the ``.at[inv].add`` scatter) BEFORE anything
touches table-shaped storage — the reference's AddTakeGradRspKernel
dedup, for free from autodiff.

Everything here is pure jnp over static shapes (jit/pjit safe). The
sentinel for unused slots in the fixed-size ``uids`` buffer is
``vocab`` (one past the last row): gathers clip it harmlessly and
scatters DROP it under jit (XLA out-of-bounds scatter semantics), so a
``.at[uids].set(rows)`` updates exactly the live rows.

``trace_capture`` is the seam ``parallel/step.py`` arms while tracing
the model forward: an ``embedding(..., sparse_grad=True)`` lookup on a
captured table routes through the dedup lookup, adds the step's
per-table row tangent (the differentiated leaf whose cotangent IS the
RowSparse row block), and records the live ids for the optimizer.
"""
from __future__ import annotations

import threading

import jax
import jax.numpy as jnp

__all__ = ['unique_rows', 'dedup_take', 'merge_row_blocks',
           'trace_capture', 'lookup_capture']


def unique_rows(flat_ids, budget, vocab):
    """Dedup a flat int vector of row ids into a fixed-size buffer.

    Returns ``(uids, inv, n_live)``:

    - ``uids``: ``(budget,)`` int32 — the unique ids in ascending
      order, padded with the sentinel ``vocab`` (slots past
      ``n_live``);
    - ``inv``: ``(flat_ids.size,)`` int32 — position of each input id
      inside ``uids`` (``uids[inv] == clip(flat_ids)``);
    - ``n_live``: ``()`` int32 — how many slots are real.

    ``budget`` must be static and >= the worst-case unique count
    (``min(flat_ids.size, vocab)`` is always safe — the caller sizes
    the buffer once at trace time, so the program shape never depends
    on the batch's actual id distribution).
    """
    ids = jnp.clip(flat_ids.reshape(-1).astype(jnp.int32), 0, vocab - 1)
    # value sort + searchsorted, NOT argsort + inverse-permutation
    # scatter: the variadic (key, iota) sort that argsort lowers to is
    # miscompiled by the GSPMD sort partitioner on multi-axis meshes
    # when the ids arrive batch-sharded (dp x tp CPU meshes produce
    # NaN losses once forward and backward compile together)
    sorted_ids = jnp.sort(ids)
    # segment boundaries of the sorted run -> dense unique-slot index
    first = jnp.concatenate([
        jnp.ones((1,), jnp.int32),
        (sorted_ids[1:] != sorted_ids[:-1]).astype(jnp.int32)])
    seg = jnp.cumsum(first) - 1
    n_live = seg[-1] + 1
    uids = jnp.full((budget,), vocab, jnp.int32).at[seg].set(
        sorted_ids, mode='drop')
    # every (clipped) id is present in uids and uids is ascending with
    # the sentinel past the live prefix, so the insertion point IS the
    # unique-slot index
    inv = jnp.searchsorted(uids, ids).astype(jnp.int32)
    return uids, inv, n_live


def dedup_take(a, indices, vocab=None):
    """``jnp.take(a, indices, axis=0, mode='clip')`` through the
    dedup-first lookup: forward values are bit-identical to the plain
    gather, backward segment-sums repeated ids into one row block
    before the table-shaped scatter (instead of scatter-adding one
    slice per occurrence)."""
    vocab = int(a.shape[0]) if vocab is None else int(vocab)
    idx = indices.astype(jnp.int32)
    n = int(idx.size)
    if n == 0 or vocab == 0:
        return jnp.take(a, idx, axis=0, mode='clip')
    budget = min(n, vocab)
    uids, inv, _ = unique_rows(idx, budget, vocab)
    rows = jnp.take(a, uids, axis=0, mode='clip')
    out = jnp.take(rows, inv, axis=0)
    return out.reshape(tuple(idx.shape) + tuple(a.shape[1:]))


def merge_row_blocks(uids, values, vocab, budget=None):
    """Merge possibly-overlapping ``(uids, row values)`` blocks (e.g.
    two lookups of the same table in one step) into one deduped block:
    duplicate ids segment-sum their rows; sentinel slots stay zero.
    ``budget`` defaults to ``min(uids.size, vocab)``."""
    uids = uids.reshape(-1)
    values = values.reshape((uids.shape[0],) + tuple(values.shape[1:]))
    if budget is None:
        budget = min(int(uids.shape[0]), int(vocab))
    # sentinel entries (uid == vocab) sort last; their merged group
    # either lands past the budget (scatter-dropped) or keeps the
    # sentinel uid (update-dropped) — their values are zero either way
    muids, minv, _ = unique_rows(uids, budget, vocab + 1)
    muids = jnp.minimum(muids, vocab)
    merged = jnp.zeros((budget,) + tuple(values.shape[1:]),
                       values.dtype).at[minv].add(values, mode='drop')
    n_live = jnp.sum((muids < vocab).astype(jnp.int32))
    return muids, merged, n_live


# ---------------------------------------------------------------------------
# trace-time capture: parallel/step.py arms a context keyed by the
# identity of each sparse table's traced array; the embedding op checks
# it and routes captured lookups through the dedup + tangent path.
# Thread-local so concurrent traces (tests build steps from several
# threads) never see each other's tables.
# ---------------------------------------------------------------------------

_TLS = threading.local()


class _TableSlot:
    """Per-table capture state for ONE trace."""

    def __init__(self, name, array, vocab, dim, tangent=None,
                 budgets=None):
        self.name = name
        self.array = array
        self.vocab = int(vocab)
        self.dim = int(dim)
        self.tangent = tangent          # (sum(budgets), dim) or None
        self.budgets = list(budgets or [])   # per-lookup row budgets
        self.call_sizes = []            # discover mode: flat id counts
        self.uids = []                  # per-lookup (budget,) id vectors
        self.n_live = []                # per-lookup live counts
        self._offset = 0

    def lookup(self, idx):
        n = int(idx.size)
        if self.tangent is None:
            # discover mode: record the lookup's id count; plain gather
            # keeps shapes flowing without needing a budget yet
            self.call_sizes.append(n)
            return jnp.take(self.array, idx, axis=0, mode='clip')
        k = len(self.uids)
        budget = self.budgets[k] if k < len(self.budgets) \
            else min(n, self.vocab)
        uids, inv, n_live = unique_rows(idx, budget, self.vocab)
        # stop_gradient: the table itself must receive NO table-shaped
        # cotangent — the row tangent added below is the only
        # differentiated leaf, and its cotangent is the deduped
        # RowSparse row block the optimizer consumes
        rows = jnp.take(jax.lax.stop_gradient(self.array), uids,
                        axis=0, mode='clip')
        rows = rows.astype(self.tangent.dtype) \
            + self.tangent[self._offset:self._offset + budget]
        self._offset += budget
        self.uids.append(uids)
        self.n_live.append(n_live)
        out = jnp.take(rows, inv, axis=0).astype(self.array.dtype)
        return out.reshape(tuple(idx.shape) + (self.dim,))


class _Capture:
    def __init__(self, slots):
        self.slots = slots                       # name -> _TableSlot
        self.by_id = {id(s.array): s for s in slots.values()}

    def __enter__(self):
        stack = getattr(_TLS, 'stack', None)
        if stack is None:
            stack = _TLS.stack = []
        stack.append(self)
        return self

    def __exit__(self, *exc):
        _TLS.stack.pop()
        return False

    def results(self):
        """{name: {'uids': (budget,) int32, 'n_live': () int32}} with
        multi-lookup tables concatenated (the update side re-dedups
        via merge_row_blocks)."""
        out = {}
        for n, s in self.slots.items():
            if not s.uids:
                continue
            out[n] = {
                'uids': jnp.concatenate(s.uids) if len(s.uids) > 1
                else s.uids[0],
                'n_live': sum(s.n_live[1:], s.n_live[0]),
            }
        return out


def trace_capture(tables, tangents=None, budgets=None):
    """Arm a capture for one trace of the model forward.

    ``tables``: {name: traced table array (vocab, dim)};
    ``tangents``: {name: (sum(budgets), dim) zero tangent} or None for
    discover mode (record per-lookup id counts only);
    ``budgets``: {name: [per-lookup row budget, ...]}.
    """
    slots = {}
    for n, arr in tables.items():
        slots[n] = _TableSlot(
            n, arr, arr.shape[0], arr.shape[1],
            tangent=None if tangents is None else tangents[n],
            budgets=None if budgets is None else budgets.get(n))
    return _Capture(slots)


def lookup_capture(weight):
    """The armed table slot for ``weight`` (matched by trace identity)
    or None — the hook ``ops.nn.embedding`` checks on every call."""
    stack = getattr(_TLS, 'stack', None)
    if not stack:
        return None
    return stack[-1].by_id.get(id(weight))
