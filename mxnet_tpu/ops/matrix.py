"""Shape manipulation, matmul, linalg, ordering ops.

Ref: src/operator/tensor/{matrix_op.cc,dot.cc,la_op.cc,ordering_op.cc}.
Matmuls are kept as single large `dot_general`s so XLA tiles them onto the
MXU; reshape/transpose are metadata-only for XLA.
"""
from __future__ import annotations

import builtins

import jax
import jax.numpy as jnp

from ..base import register_op, MXNetError

__all__ = []


def _reg(fn):
    register_op(fn.__name__)(fn)
    __all__.append(fn.__name__)
    return fn


@_reg
def reshape(data, shape=None, reverse=False):
    """MXNet reshape with special codes 0 (keep), -1 (infer), -2 (copy rest),
    -3 (merge two), -4 (split) (ref: matrix_op.cc Reshape)."""
    if shape is None:
        raise MXNetError("reshape needs a target shape")
    shape = tuple(int(s) for s in shape)
    if not any(s in (0, -2, -3, -4) for s in shape):
        return jnp.reshape(data, shape)
    src = list(data.shape)
    if reverse:
        src = src[::-1]
        shape = tuple(reversed(shape))
    out = []
    i = 0  # index into src
    j = 0
    while j < len(shape):
        s = shape[j]
        if s == 0:
            out.append(src[i]); i += 1
        elif s == -1:
            out.append(-1); i += 1
        elif s == -2:
            out.extend(src[i:]); i = len(src)
        elif s == -3:
            out.append(src[i] * src[i + 1]); i += 2
        elif s == -4:
            a, b = shape[j + 1], shape[j + 2]
            if a == -1:
                a = src[i] // b
            if b == -1:
                b = src[i] // a
            out.extend([a, b]); i += 1; j += 2
        else:
            out.append(s); i += 1
        j += 1
    if reverse:
        out = out[::-1]
    return jnp.reshape(data, tuple(out))


@_reg
def flatten(data):
    return jnp.reshape(data, (data.shape[0], -1))


@_reg
def transpose(data, axes=None):
    if axes is not None and len(axes) == 0:
        axes = None
    return jnp.transpose(data, axes)


@_reg
def expand_dims(data, axis=0):
    return jnp.expand_dims(data, axis)


@_reg
def squeeze(data, axis=None):
    return jnp.squeeze(data, axis=axis)


@_reg
def swapaxes(data, dim1=0, dim2=1):
    return jnp.swapaxes(data, dim1, dim2)


@_reg
def slice(data, begin=None, end=None, step=None):
    """General strided slice (ref: matrix_op.cc Slice); None entries mean full range."""
    ndim = data.ndim
    begin = list(begin) + [None] * (ndim - len(begin))
    end = list(end) + [None] * (ndim - len(end))
    step = list(step or []) + [None] * (ndim - len(step or []))
    idx = tuple(builtins_slice(b, e, s) for b, e, s in zip(begin, end, step))
    return data[idx]


builtins_slice = builtins.slice


@_reg
def slice_axis(data, axis=0, begin=0, end=None):
    idx = [builtins_slice(None)] * data.ndim
    idx[axis] = builtins_slice(begin, end)
    return data[tuple(idx)]


@_reg
def slice_like(data, shape_like, axes=()):
    axes = tuple(axes) or tuple(range(min(data.ndim, shape_like.ndim)))
    idx = [builtins_slice(None)] * data.ndim
    for a in axes:
        idx[a] = builtins_slice(0, shape_like.shape[a])
    return data[tuple(idx)]


@_reg
def concat(*args, dim=1):
    return jnp.concatenate(args, axis=dim)


@_reg
def stack(*args, axis=0):
    return jnp.stack(args, axis=axis)


def split(data, num_outputs=None, axis=1, squeeze_axis=False):
    """Ref: slice_channel.cc (SliceChannel)."""
    parts = jnp.split(data, num_outputs, axis=axis)
    if squeeze_axis:
        parts = [jnp.squeeze(p, axis=axis) for p in parts]
    return tuple(parts)


register_op("split", num_outputs=-1)(split)
__all__.append("split")


@_reg
def tile(data, reps=()):
    return jnp.tile(data, tuple(reps))


@_reg
def repeat(data, repeats=1, axis=None):
    return jnp.repeat(data, repeats, axis=axis)


@_reg
def flip(data, axis=()):
    return jnp.flip(data, axis=axis)


@_reg
def reverse(data, axis=()):
    return jnp.flip(data, axis=axis)


@_reg
def pad(data, mode='constant', pad_width=(), constant_value=0.0):
    pw = [(pad_width[2 * i], pad_width[2 * i + 1]) for i in range(len(pad_width) // 2)]
    jmode = {'constant': 'constant', 'edge': 'edge', 'reflect': 'reflect'}[mode]
    if jmode == 'constant':
        return jnp.pad(data, pw, mode='constant', constant_values=constant_value)
    return jnp.pad(data, pw, mode=jmode)


@_reg
def depth_to_space(data, block_size=2):
    n, c, h, w = data.shape
    b = block_size
    x = data.reshape(n, b, b, c // (b * b), h, w)
    x = x.transpose(0, 3, 4, 1, 5, 2)
    return x.reshape(n, c // (b * b), h * b, w * b)


@_reg
def space_to_depth(data, block_size=2):
    n, c, h, w = data.shape
    b = block_size
    x = data.reshape(n, c, h // b, b, w // b, b)
    x = x.transpose(0, 3, 5, 1, 2, 4)
    return x.reshape(n, c * b * b, h // b, w // b)


# --- matmul family ---------------------------------------------------------

@_reg
def dot(lhs, rhs, transpose_a=False, transpose_b=False):
    """MXNet dot: contracts last axis of lhs with first axis of rhs
    (ref: src/operator/tensor/dot.cc)."""
    if transpose_a:
        lhs = jnp.transpose(lhs)
    if transpose_b:
        rhs = jnp.transpose(rhs)
    if lhs.ndim == 1 and rhs.ndim == 1:
        return jnp.dot(lhs, rhs)
    return jnp.tensordot(lhs, rhs, axes=([lhs.ndim - 1], [0]))


@_reg
def batch_dot(lhs, rhs, transpose_a=False, transpose_b=False):
    """Batched matmul over leading dims (ref: dot.cc batch_dot); lowers to one
    dot_general so the MXU sees a single large batched contraction."""
    if transpose_a:
        lhs = jnp.swapaxes(lhs, -1, -2)
    if transpose_b:
        rhs = jnp.swapaxes(rhs, -1, -2)
    return jnp.matmul(lhs, rhs)


@_reg
def khatri_rao(*args):
    """Column-wise Khatri-Rao product (ref: src/operator/contrib/krprod.cc)."""
    out = args[0]
    for m in args[1:]:
        out = jnp.einsum('ik,jk->ijk', out, m).reshape(-1, out.shape[1])
    return out


# --- linalg (ref: src/operator/tensor/la_op.cc) ----------------------------

@_reg
def linalg_gemm(A, B, C, transpose_a=False, transpose_b=False, alpha=1.0, beta=1.0):
    a = jnp.swapaxes(A, -1, -2) if transpose_a else A
    b = jnp.swapaxes(B, -1, -2) if transpose_b else B
    return alpha * jnp.matmul(a, b) + beta * C


@_reg
def linalg_gemm2(A, B, transpose_a=False, transpose_b=False, alpha=1.0):
    a = jnp.swapaxes(A, -1, -2) if transpose_a else A
    b = jnp.swapaxes(B, -1, -2) if transpose_b else B
    return alpha * jnp.matmul(a, b)


@_reg
def linalg_potrf(A):
    return jnp.linalg.cholesky(A)


@_reg
def linalg_potri(A):
    L = jnp.linalg.cholesky(A)
    inv_l = jax.scipy.linalg.solve_triangular(
        L, jnp.broadcast_to(jnp.eye(A.shape[-1], dtype=A.dtype), A.shape), lower=True)
    return jnp.matmul(jnp.swapaxes(inv_l, -1, -2), inv_l)


@_reg
def linalg_trsm(A, B, transpose=False, rightside=False, lower=True, alpha=1.0):
    a = jnp.swapaxes(A, -1, -2) if transpose else A
    low = lower != transpose
    if rightside:
        x = jax.scipy.linalg.solve_triangular(
            jnp.swapaxes(a, -1, -2), jnp.swapaxes(B, -1, -2), lower=not low)
        x = jnp.swapaxes(x, -1, -2)
    else:
        x = jax.scipy.linalg.solve_triangular(a, B, lower=low)
    return alpha * x


@_reg
def linalg_trmm(A, B, transpose=False, rightside=False, lower=True, alpha=1.0):
    tri = jnp.tril(A) if lower else jnp.triu(A)
    if transpose:
        tri = jnp.swapaxes(tri, -1, -2)
    out = jnp.matmul(B, tri) if rightside else jnp.matmul(tri, B)
    return alpha * out


@_reg
def linalg_syrk(A, transpose=False, alpha=1.0):
    a = jnp.swapaxes(A, -1, -2) if transpose else A
    return alpha * jnp.matmul(a, jnp.swapaxes(a, -1, -2))


@_reg
def linalg_sumlogdiag(A):
    diag = jnp.diagonal(A, axis1=-2, axis2=-1)
    return jnp.sum(jnp.log(diag), axis=-1)


@_reg
def linalg_extractdiag(A, offset=0):
    return jnp.diagonal(A, offset=offset, axis1=-2, axis2=-1)


@_reg
def linalg_makediag(A, offset=0):
    return jnp.vectorize(lambda v: jnp.diag(v, k=offset),
                         signature='(n)->(m,m)')(A)


@_reg
def linalg_det(A):
    return jnp.linalg.det(A)


@_reg
def linalg_inverse(A):
    return jnp.linalg.inv(A)


@_reg
def linalg_slogdet(A):
    sign, logdet = jnp.linalg.slogdet(A)
    return sign, logdet


# --- ordering (ref: src/operator/tensor/ordering_op.cc) --------------------

@_reg
def sort(data, axis=-1, is_ascend=True):
    out = jnp.sort(data, axis=axis)
    if not is_ascend:
        out = jnp.flip(out, axis=axis)
    return out


@_reg
def argsort(data, axis=-1, is_ascend=True, dtype='float32'):
    out = jnp.argsort(data, axis=axis)
    if not is_ascend:
        out = jnp.flip(out, axis=axis)
    return out.astype(jnp.dtype(dtype))


def topk(data, axis=-1, k=1, ret_typ='indices', is_ascend=False, dtype='float32'):
    """Ref: ordering_op.cc TopK. ret_typ in {value, indices, mask, both}."""
    src = -data if is_ascend else data
    if axis != -1 and axis != data.ndim - 1:
        src_m = jnp.moveaxis(src, axis, -1)
    else:
        src_m = src
        axis = data.ndim - 1
    vals, idxs = jax.lax.top_k(src_m, k)
    if is_ascend:
        vals = -vals
    vals = jnp.moveaxis(vals, -1, axis)
    idxs = jnp.moveaxis(idxs, -1, axis)
    if ret_typ == 'value':
        return vals
    if ret_typ == 'indices':
        return idxs.astype(jnp.dtype(dtype))
    if ret_typ == 'mask':
        mask = jnp.zeros_like(jnp.moveaxis(data, axis, -1))
        mask = mask.at[..., :].set(0)
        one_hot = jax.nn.one_hot(jnp.moveaxis(idxs, axis, -1), data.shape[axis],
                                 dtype=data.dtype).sum(axis=-2)
        return jnp.moveaxis(one_hot, -1, axis)
    return vals, idxs.astype(jnp.dtype(dtype))


register_op("topk", num_outputs=-1)(topk)
__all__.append("topk")


@_reg
def shape_array(data):
    return jnp.array(data.shape, dtype=jnp.int64)


@_reg
def size_array(data):
    return jnp.array([data.size], dtype=jnp.int64)


@_reg
def zeros_like(data):
    return jnp.zeros_like(data)


@_reg
def ones_like(data):
    return jnp.ones_like(data)


@_reg
def diag(data, k=0):
    if data.ndim == 1:
        return jnp.diag(data, k)
    return jnp.diagonal(data, offset=k, axis1=-2, axis2=-1)


@_reg
def tril(data, k=0):
    return jnp.tril(data, k)


@_reg
def triu(data, k=0):
    return jnp.triu(data, k)


@_reg
def einsum(*args, subscripts=''):
    return jnp.einsum(subscripts, *args)


@_reg
def histogram(data, bin_cnt=10, range=None):
    hist, edges = jnp.histogram(data, bins=bin_cnt, range=range)
    return hist, edges
