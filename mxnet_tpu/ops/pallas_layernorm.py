"""Pallas fused residual-add + LayerNorm for TPU.

Round 4's profile-directed plan (VERDICT r4 #1) located the remaining
flagship-step headroom in the XLA-side encoder — LN/GELU/FFN — after
the attention kernel landed. The BERT layer computes `LN(x + sub(x))`
twice per layer; under XLA that is an HBM round-trip for the residual
add plus two reduction passes. This kernel does add + mean/var + scale
in ONE pass over VMEM rows, fp32 statistics, bf16-friendly output —
the same fused-epilogue ethos as the reference's hand-fused transformer
ops (ref: src/operator/contrib/transformer.cc:650-828).

Forward only, with a custom_vjp whose backward is the standard LN
gradient expressed in jnp (the backward is matmul-free and XLA fuses it
well; the forward's extra residual read is where the bandwidth win is).

Routing: models/bert.py's layers call ops.nn.add_layer_norm, which
routes here when `MXTPU_PALLAS_LN=1` and a TPU is present (default OFF
until measured on-chip — flag-gated exactly like the attention tuning
knobs, memory: tune via tools/tune_bert_step.py when the tunnel is up).
`interpret=True` runs the identical kernel on CPU for parity tests.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from .pallas_attention import pallas_available  # shared TPU probe


def _ln_kernel(x_ref, r_ref, g_ref, b_ref, o_ref, *, eps):
    """One (rows_block, C) tile: out = LN(x + r) * gamma + beta.

    C rides whole in the lane dim (BERT hidden 768 = 6*128); rows tile
    in the sublane dim. Stats in fp32 regardless of input dtype.
    """
    x = x_ref[...].astype(jnp.float32) + r_ref[...].astype(jnp.float32)
    mean = jnp.mean(x, axis=-1, keepdims=True)
    xc = x - mean
    var = jnp.mean(xc * xc, axis=-1, keepdims=True)
    inv = jax.lax.rsqrt(var + eps)
    out = xc * inv * g_ref[...].astype(jnp.float32) \
        + b_ref[...].astype(jnp.float32)
    o_ref[...] = out.astype(o_ref.dtype)


def _fwd_impl(x, res, gamma, beta, eps, block_rows, interpret):
    orig_shape = x.shape
    C = orig_shape[-1]
    x2 = x.reshape(-1, C)
    r2 = res.reshape(-1, C)
    N = x2.shape[0]
    br = min(block_rows, N)
    while N % br:
        br -= 1
    g2 = gamma.reshape(1, C)
    b2 = beta.reshape(1, C)
    out = pl.pallas_call(
        functools.partial(_ln_kernel, eps=eps),
        grid=(N // br,),
        in_specs=[
            pl.BlockSpec((br, C), lambda i: (i, 0)),
            pl.BlockSpec((br, C), lambda i: (i, 0)),
            pl.BlockSpec((1, C), lambda i: (0, 0)),
            pl.BlockSpec((1, C), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((br, C), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((N, C), x.dtype),
        interpret=interpret,
    )(x2, r2, g2, b2)
    return out.reshape(orig_shape)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def fused_add_layer_norm(x, res, gamma, beta, eps=1e-5, block_rows=256,
                         interpret=False):
    """LN(x + res) * gamma + beta in one fused pass (see module doc)."""
    return _fwd_impl(x, res, gamma, beta, eps, block_rows, interpret)


def _fwd(x, res, gamma, beta, eps, block_rows, interpret):
    out = _fwd_impl(x, res, gamma, beta, eps, block_rows, interpret)
    # save the SUM: the backward only ever uses x+res (dx == dres), and
    # saving x and res separately would double the residual footprint
    # on exactly the bandwidth-constrained path this kernel relieves
    return out, (x + res, gamma)


def _bwd(eps, block_rows, interpret, saved, g):
    s_in, gamma = saved
    s = s_in.astype(jnp.float32)
    mean = jnp.mean(s, axis=-1, keepdims=True)
    xc = s - mean
    var = jnp.mean(xc * xc, axis=-1, keepdims=True)
    inv = jax.lax.rsqrt(var + eps)
    xhat = xc * inv
    gf = g.astype(jnp.float32)
    dgamma = jnp.sum(gf * xhat, axis=tuple(range(g.ndim - 1)))
    dbeta = jnp.sum(gf, axis=tuple(range(g.ndim - 1)))
    gg = gf * gamma.astype(jnp.float32)
    dx = inv * (gg - jnp.mean(gg, axis=-1, keepdims=True)
                - xhat * jnp.mean(gg * xhat, axis=-1, keepdims=True))
    dx = dx.astype(s_in.dtype)
    return dx, dx, dgamma.astype(gamma.dtype), dbeta.astype(gamma.dtype)


fused_add_layer_norm.defvjp(_fwd, _bwd)
