"""Creation ops (ref: src/operator/tensor/init_op.cc)."""
from __future__ import annotations

import jax.numpy as jnp

from ..base import register_op

__all__ = []


def _reg(fn):
    register_op(fn.__name__, nograd=True)(fn)
    __all__.append(fn.__name__)
    return fn


@_reg
def zeros(shape=(), dtype='float32'):
    return jnp.zeros(shape, dtype=jnp.dtype(dtype))


@_reg
def ones(shape=(), dtype='float32'):
    return jnp.ones(shape, dtype=jnp.dtype(dtype))


@_reg
def full(shape=(), val=0.0, dtype='float32'):
    return jnp.full(shape, val, dtype=jnp.dtype(dtype))


@_reg
def arange(start=0, stop=None, step=1.0, repeat=1, dtype='float32'):
    out = jnp.arange(start, stop, step, dtype=jnp.dtype(dtype))
    if repeat > 1:
        out = jnp.repeat(out, repeat)
    return out


@_reg
def linspace(start=0, stop=1, num=50, endpoint=True, dtype='float32'):
    return jnp.linspace(start, stop, num, endpoint=endpoint,
                        dtype=jnp.dtype(dtype))


@_reg
def eye(N=0, M=0, k=0, dtype='float32'):
    return jnp.eye(int(N), int(M) or None, k=int(k), dtype=jnp.dtype(dtype))
