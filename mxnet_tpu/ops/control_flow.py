"""Control-flow operators: foreach / while_loop / cond.

TPU-native analog of the reference's subgraph-executing higher-order ops
(ref: src/operator/control_flow.cc:1089,1150,1211 — `_foreach`,
`_while_loop`, `_cond` — and the imperative frontends in
python/mxnet/ndarray/contrib.py). The reference runs a captured nnvm
subgraph per iteration; here the body is traced once into a
`lax.scan`/`lax.while_loop`/`lax.cond` so XLA compiles the whole loop as a
single program with static shapes — the idiomatic TPU formulation.

Gradients:
- `foreach` records ONE tape node whose vjp is `jax.vjp` over the whole
  scan (reverse-mode through `lax.scan` is native in XLA).
- eager `while_loop`/`cond` execute ops through the normal imperative
  path, so the autograd tape records every iteration (mirrors the
  reference's imperative fallback in python/mxnet/ndarray/contrib.py).
- traced `while_loop` lowers to a masked fixed-length scan
  (`max_iterations` steps with a live flag) so it stays reverse-mode
  differentiable — `lax.while_loop` itself is not.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import tree_util

__all__ = ['foreach', 'while_loop', 'cond']


def _is_nd(x):
    from ..ndarray.ndarray import NDArray
    return isinstance(x, NDArray)


def _flatten(tree):
    """Flatten a nested list/tuple of NDArrays into (leaves, treedef)."""
    leaves, treedef = tree_util.tree_flatten(tree, is_leaf=_is_nd)
    return leaves, treedef


def _leaf_data(leaves):
    return [x._data if _is_nd(x) else jnp.asarray(x) for x in leaves]


def _wrap_tree(treedef, datas):
    from ..ndarray.ndarray import NDArray
    return tree_util.tree_unflatten(treedef, [NDArray(d) for d in datas])


def _paused(fn):
    """Run fn with tape recording off (the subgraph is differentiated as a
    whole by jax, not op-by-op on the tape)."""
    from ..base import state

    def run(*a, **kw):
        prev = state.is_recording
        state.is_recording = False
        try:
            return fn(*a, **kw)
        finally:
            state.is_recording = prev
    return run


def _any_tracer(datas):
    return any(isinstance(d, jax.core.Tracer) for d in datas)


def foreach(body, data, init_states):
    """Scan `body` over the leading axis of `data`.

    body(data_slice, states) -> (outputs, new_states). Returns
    (stacked_outputs, final_states). Ref: control_flow.cc:1089 `_foreach`;
    lowered to one `lax.scan` (compiler-scheduled, MXU-friendly).

    When the autograd tape is recording we instead run a Python loop through
    the imperative path (mirroring python/mxnet/ndarray/contrib.py foreach):
    the scan formulation differentiates only the explicit data/state inputs,
    so parameters the body closes over (the standard RNN-cell pattern) would
    silently get zero gradients. Inside a jit/hybridize trace the whole
    program is differentiated by jax, so scan is used there.
    """
    from ..base import state as _state
    from ..ndarray.ndarray import _invoke

    data_leaves, data_def = _flatten(data)
    state_leaves, state_def = _flatten(init_states)
    n_data = len(data_leaves)
    out_struct = {}

    if _state.is_recording and not _any_tracer(_leaf_data(data_leaves)):
        states = init_states
        outputs = []
        length = data_leaves[0].shape[0]
        for t in range(length):
            slice_tree = tree_util.tree_unflatten(
                data_def, [d[t] for d in data_leaves])
            out, states = body(slice_tree, states)
            outputs.append(out)
        from . import matrix as _mat
        out_leaf_lists = [_flatten(o)[0] for o in outputs]
        out_def = _flatten(outputs[0])[1]
        stacked = [_invoke(_mat.stack, *[ol[i] for ol in out_leaf_lists])
                   for i in range(len(out_leaf_lists[0]))]
        return tree_util.tree_unflatten(out_def, stacked), states

    run_body = _paused(body)

    def g(*arrs):
        xs = arrs[:n_data]
        carry0 = arrs[n_data:]

        def step(carry, x):
            d_tree = _wrap_tree(data_def, x)
            s_tree = _wrap_tree(state_def, carry)
            outs, new_states = run_body(d_tree, s_tree)
            out_leaves, out_def = _flatten(outs)
            ns_leaves, _ = _flatten(new_states)
            out_struct['out_def'] = out_def
            out_struct['n_out'] = len(out_leaves)
            return tuple(_leaf_data(ns_leaves)), tuple(_leaf_data(out_leaves))

        final, ys = jax.lax.scan(step, tuple(carry0), tuple(xs))
        return tuple(ys) + tuple(final)

    res = _invoke(g, *(data_leaves + state_leaves))
    if not isinstance(res, tuple):
        res = (res,)
    n_out = out_struct['n_out']
    outs = tree_util.tree_unflatten(out_struct['out_def'], list(res[:n_out]))
    states = tree_util.tree_unflatten(state_def, list(res[n_out:]))
    return outs, states


def while_loop(cond, func, loop_vars, max_iterations=None):
    """Run `func` while `cond(loop_vars)` is true.

    func(loop_vars) -> (step_output, new_loop_vars); returns
    (stacked_outputs, final_loop_vars). Ref: control_flow.cc:1150
    `_while_loop` + python/mxnet/ndarray/contrib.py while_loop.

    Eager: a Python loop through the imperative path (tape-differentiable,
    unbounded unless max_iterations given); outputs are zero-padded to
    max_iterations when it is given, matching the reference and the traced
    path. Zero executed iterations returns [] for outputs (as the
    reference's imperative frontend does). Traced: a masked fixed-length
    `lax.scan` over max_iterations — reverse-differentiable, static shapes.
    """
    from ..ndarray.ndarray import _invoke
    from . import matrix as _mat

    lv_leaves, lv_def = _flatten(loop_vars)
    if _any_tracer(_leaf_data(lv_leaves)):
        if max_iterations is None:
            raise ValueError("while_loop under trace requires max_iterations")
        return _while_loop_traced(cond, func, loop_vars, max_iterations)

    steps = 0
    outputs = []
    while bool(_as_scalar(cond(loop_vars))):
        out, loop_vars = func(loop_vars)
        outputs.append(out)
        steps += 1
        if max_iterations is not None and steps >= max_iterations:
            break
    if not outputs:
        return [], loop_vars
    out_leaf_lists = [_flatten(o)[0] for o in outputs]
    out_def = _flatten(outputs[0])[1]
    pad = (max_iterations - steps) if max_iterations is not None else 0
    stacked = []
    for i in range(len(out_leaf_lists[0])):
        parts = [ol[i] for ol in out_leaf_lists]
        s = _invoke(_mat.stack, *parts)
        if pad:
            s = _invoke(lambda x, n=pad: jnp.concatenate(
                [x, jnp.zeros((n,) + x.shape[1:], x.dtype)]), s)
        stacked.append(s)
    return tree_util.tree_unflatten(out_def, stacked), loop_vars


def _while_loop_traced(cond, func, loop_vars, max_iterations):
    from ..ndarray.ndarray import _invoke

    lv_leaves, lv_def = _flatten(loop_vars)
    out_struct = {}
    run_cond = _paused(cond)
    run_func = _paused(func)

    def g(*arrs):
        def step(carry, _):
            alive, lv = carry
            lv_tree = _wrap_tree(lv_def, lv)
            pred = _leaf_data(_flatten(run_cond(lv_tree))[0])[0]
            alive_now = jnp.logical_and(alive, pred.astype(bool).reshape(()))
            out, new_lv = run_func(lv_tree)
            out_leaves, out_def = _flatten(out)
            nl_leaves, _ = _flatten(new_lv)
            out_struct['out_def'] = out_def
            out_struct['n_out'] = len(out_leaves)
            new_data = _leaf_data(nl_leaves)
            kept = tuple(jnp.where(alive_now, n, o)
                         for n, o in zip(new_data, lv))
            outs = tuple(jnp.where(alive_now, o, jnp.zeros_like(o))
                         for o in _leaf_data(out_leaves))
            return (alive_now, kept), outs

        (alive, final), ys = jax.lax.scan(
            step, (jnp.bool_(True), tuple(arrs)), None,
            length=max_iterations)
        return tuple(ys) + tuple(final)

    res = _invoke(g, *lv_leaves)
    if not isinstance(res, tuple):
        res = (res,)
    n_out = out_struct['n_out']
    outs = tree_util.tree_unflatten(out_struct['out_def'], list(res[:n_out]))
    final = tree_util.tree_unflatten(lv_def, list(res[n_out:]))
    return outs, final


def cond(pred, then_func, else_func, inputs=None):
    """Branch on a scalar predicate. Ref: control_flow.cc:1211 `_cond`.

    Eager: evaluates the predicate on host and runs one branch through the
    imperative path (tape-differentiable). Traced (pass `inputs`, the
    NDArrays the branches close over): lowers to `lax.cond`.
    """
    from ..ndarray.ndarray import _invoke

    pred_data = pred._data if _is_nd(pred) else jnp.asarray(pred)
    if inputs is None and not isinstance(pred_data, jax.core.Tracer):
        return then_func() if bool(_as_scalar(pred)) else else_func()

    in_leaves, in_def = _flatten(inputs if inputs is not None else [])
    out_struct = {}
    branches = [(_paused(then_func), _expects_arg(then_func)),
                (_paused(else_func), _expects_arg(else_func))]

    def g(p, *arrs):
        def branch(fn, takes_arg):
            def run(ops):
                wrapped = _wrap_tree(in_def, ops)
                outs = fn(wrapped) if takes_arg else fn()
                leaves, out_def = _flatten(outs)
                out_struct['out_def'] = out_def
                return tuple(_leaf_data(leaves))
            return run
        return jax.lax.cond(p.astype(bool).reshape(()),
                            branch(*branches[0]), branch(*branches[1]),
                            tuple(arrs))

    res = _invoke(g, pred if _is_nd(pred) else jnp.asarray(pred), *in_leaves)
    if not isinstance(res, tuple):
        res = (res,)
    return tree_util.tree_unflatten(out_struct['out_def'], list(res))


def _expects_arg(fn):
    import inspect
    try:
        sig = inspect.signature(fn)
    except (TypeError, ValueError):
        return False
    return len([p for p in sig.parameters.values()
                if p.default is p.empty
                and p.kind in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD)]) > 0


def _as_scalar(x):
    if _is_nd(x):
        return x.asnumpy().reshape(()).item() if hasattr(x, 'asnumpy') \
            else x._data.reshape(()).item()
    return jnp.asarray(x).reshape(()).item()
