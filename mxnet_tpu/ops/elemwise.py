"""Elementwise unary/binary/scalar ops.

Ref: src/operator/tensor/elemwise_*.cc families. On TPU these all lower to
XLA elementwise HLO and fuse into neighbouring matmuls/reductions for free,
replacing the reference's NVRTC pointwise-fusion pass.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..base import register_op

__all__ = []


def _export(fn):
    __all__.append(fn.__name__)
    return fn


def _reg(fn):
    register_op(fn.__name__)(fn)
    return _export(fn)


# --- binary broadcast (ref: elemwise_binary_broadcast_op_basic.cc) ---------

@_reg
def broadcast_add(lhs, rhs):
    return jnp.add(lhs, rhs)


@_reg
def broadcast_sub(lhs, rhs):
    return jnp.subtract(lhs, rhs)


@_reg
def broadcast_mul(lhs, rhs):
    return jnp.multiply(lhs, rhs)


@_reg
def broadcast_div(lhs, rhs):
    return jnp.divide(lhs, rhs)


@_reg
def broadcast_mod(lhs, rhs):
    return jnp.mod(lhs, rhs)


@_reg
def broadcast_power(lhs, rhs):
    return jnp.power(lhs, rhs)


@_reg
def broadcast_maximum(lhs, rhs):
    return jnp.maximum(lhs, rhs)


@_reg
def broadcast_minimum(lhs, rhs):
    return jnp.minimum(lhs, rhs)


@_reg
def broadcast_hypot(lhs, rhs):
    return jnp.hypot(lhs, rhs)


@_reg
def broadcast_equal(lhs, rhs):
    return (lhs == rhs).astype(jnp.result_type(lhs))


@_reg
def broadcast_not_equal(lhs, rhs):
    return (lhs != rhs).astype(jnp.result_type(lhs))


@_reg
def broadcast_greater(lhs, rhs):
    return (lhs > rhs).astype(jnp.result_type(lhs))


@_reg
def broadcast_greater_equal(lhs, rhs):
    return (lhs >= rhs).astype(jnp.result_type(lhs))


@_reg
def broadcast_lesser(lhs, rhs):
    return (lhs < rhs).astype(jnp.result_type(lhs))


@_reg
def broadcast_lesser_equal(lhs, rhs):
    return (lhs <= rhs).astype(jnp.result_type(lhs))


@_reg
def broadcast_logical_and(lhs, rhs):
    return jnp.logical_and(lhs, rhs).astype(jnp.result_type(lhs))


@_reg
def broadcast_logical_or(lhs, rhs):
    return jnp.logical_or(lhs, rhs).astype(jnp.result_type(lhs))


@_reg
def broadcast_logical_xor(lhs, rhs):
    return jnp.logical_xor(lhs, rhs).astype(jnp.result_type(lhs))


# aliases matching the non-broadcast elemwise names
@_reg
def elemwise_add(lhs, rhs):
    return jnp.add(lhs, rhs)


@_reg
def elemwise_sub(lhs, rhs):
    return jnp.subtract(lhs, rhs)


@_reg
def elemwise_mul(lhs, rhs):
    return jnp.multiply(lhs, rhs)


@_reg
def elemwise_div(lhs, rhs):
    return jnp.divide(lhs, rhs)


# --- unary math (ref: elemwise_unary_op_basic.cc, _trig.cc, _pow.cc, _logexp.cc)

_UNARY = {
    'abs': jnp.abs, 'sign': jnp.sign, 'rint': jnp.rint, 'ceil': jnp.ceil,
    'floor': jnp.floor, 'trunc': jnp.trunc, 'fix': jnp.trunc,
    'square': jnp.square, 'sqrt': jnp.sqrt, 'cbrt': jnp.cbrt,
    'exp': jnp.exp, 'log': jnp.log, 'log10': jnp.log10, 'log2': jnp.log2,
    'log1p': jnp.log1p, 'expm1': jnp.expm1,
    'sin': jnp.sin, 'cos': jnp.cos, 'tan': jnp.tan,
    'arcsin': jnp.arcsin, 'arccos': jnp.arccos, 'arctan': jnp.arctan,
    'sinh': jnp.sinh, 'cosh': jnp.cosh, 'tanh': jnp.tanh,
    'arcsinh': jnp.arcsinh, 'arccosh': jnp.arccosh, 'arctanh': jnp.arctanh,
    'degrees': jnp.degrees, 'radians': jnp.radians,
    'erf': jax.scipy.special.erf, 'erfinv': jax.scipy.special.erfinv,
    'gamma': lambda x: jnp.exp(jax.scipy.special.gammaln(x)),
    'gammaln': jax.scipy.special.gammaln,
    'logical_not': lambda x: jnp.logical_not(x).astype(jnp.result_type(x)),
}

for _name, _jfn in _UNARY.items():
    def _mk(jfn):
        def op(data):
            return jfn(data)
        return op
    _f = _mk(_jfn)
    _f.__name__ = _name
    globals()[_name] = _f
    register_op(_name)(_f)
    __all__.append(_name)


@_reg
def reciprocal(data):
    return 1.0 / data


@_reg
def rsqrt(data):
    return jax.lax.rsqrt(data)


@_reg
def rcbrt(data):
    return 1.0 / jnp.cbrt(data)


@_reg
def negative(data):
    return jnp.negative(data)


@_reg
def relu(data):
    return jnp.maximum(data, 0)


@_reg
def sigmoid(data):
    return jax.nn.sigmoid(data)


@_reg
def hard_sigmoid(data, alpha=0.2, beta=0.5):
    return jnp.clip(alpha * data + beta, 0.0, 1.0)


@_reg
def softsign(data):
    return data / (1.0 + jnp.abs(data))


@_reg
def gelu(data):
    return jax.nn.gelu(data, approximate=False)


@_reg
def gelu_tanh(data):
    return jax.nn.gelu(data, approximate=True)


@_reg
def clip(data, a_min=None, a_max=None):
    return jnp.clip(data, a_min, a_max)


# --- scalar ops (ref: elemwise_binary_scalar_op_basic.cc) ------------------

def _scalar(name, fn):
    def op(data, scalar=1.0):
        return fn(data, scalar)
    op.__name__ = name
    register_op(name)(op)
    globals()[name] = op
    __all__.append(name)


_scalar('plus_scalar', lambda x, s: x + s)
_scalar('minus_scalar', lambda x, s: x - s)
_scalar('rminus_scalar', lambda x, s: s - x)
_scalar('mul_scalar', lambda x, s: x * s)
_scalar('div_scalar', lambda x, s: x / s)
_scalar('rdiv_scalar', lambda x, s: s / x)
_scalar('mod_scalar', lambda x, s: jnp.mod(x, s))
_scalar('rmod_scalar', lambda x, s: jnp.mod(s, x))
_scalar('power_scalar', lambda x, s: jnp.power(x, s))
_scalar('rpower_scalar', lambda x, s: jnp.power(s, x))
_scalar('maximum_scalar', lambda x, s: jnp.maximum(x, s))
_scalar('minimum_scalar', lambda x, s: jnp.minimum(x, s))
_scalar('equal_scalar', lambda x, s: (x == s).astype(jnp.result_type(x)))
_scalar('not_equal_scalar', lambda x, s: (x != s).astype(jnp.result_type(x)))
_scalar('greater_scalar', lambda x, s: (x > s).astype(jnp.result_type(x)))
_scalar('greater_equal_scalar', lambda x, s: (x >= s).astype(jnp.result_type(x)))
_scalar('lesser_scalar', lambda x, s: (x < s).astype(jnp.result_type(x)))
_scalar('lesser_equal_scalar', lambda x, s: (x <= s).astype(jnp.result_type(x)))
_scalar('logical_and_scalar', lambda x, s: jnp.logical_and(x, s).astype(jnp.result_type(x)))
_scalar('logical_or_scalar', lambda x, s: jnp.logical_or(x, s).astype(jnp.result_type(x)))
_scalar('logical_xor_scalar', lambda x, s: jnp.logical_xor(x, s).astype(jnp.result_type(x)))


@_reg
def add_n(*args):
    """Sum of N arrays (ref: src/ndarray/ndarray_function.h ElementwiseSum)."""
    out = args[0]
    for a in args[1:]:
        out = out + a
    return out


@_reg
def cast(data, dtype='float32'):
    return data.astype(jnp.dtype(dtype))


@_reg
def amp_cast(data, dtype='float16'):
    """AMP cast (ref: src/operator/tensor/amp_cast.cc); bf16 is the TPU native."""
    return data.astype(jnp.dtype(dtype))


@_reg
def where(condition, x, y):
    return jnp.where(condition.astype(bool), x, y)


@_reg
def isnan(data):
    return jnp.isnan(data).astype(jnp.result_type(data))


@_reg
def isinf(data):
    return jnp.isinf(data).astype(jnp.result_type(data))


@_reg
def isfinite(data):
    return jnp.isfinite(data).astype(jnp.result_type(data))
