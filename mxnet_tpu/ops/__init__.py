"""Operator library: pure functions over jax arrays.

TPU-native analog of src/operator/ (ref: SURVEY §2.2). Each op is a pure,
traceable function lowered by XLA; there is no per-op CUDA kernel — XLA
fusion replaces the reference's pointwise-fusion RTC pass
(ref: src/operator/fusion/fused_op.h:58), and Pallas kernels cover the few
hand-tuned hot spots (attention, fused optimizer updates).
"""
from . import elemwise    # noqa: F401
from . import reduce      # noqa: F401
from . import matrix      # noqa: F401
from . import nn          # noqa: F401
from . import index       # noqa: F401
from . import init       # noqa: F401
from . import random_ops  # noqa: F401
from . import optimizer_ops  # noqa: F401
from . import sequence    # noqa: F401
from . import attention   # noqa: F401
from . import contrib     # noqa: F401
from . import detection   # noqa: F401
from . import misc        # noqa: F401
from . import control_flow  # noqa: F401
from . import quantization  # noqa: F401

from .elemwise import *     # noqa: F401,F403
from .reduce import *       # noqa: F401,F403
from .matrix import *       # noqa: F401,F403
from .nn import *           # noqa: F401,F403
from .index import *        # noqa: F401,F403
from .init import *         # noqa: F401,F403
from .random_ops import *   # noqa: F401,F403
from .optimizer_ops import *  # noqa: F401,F403
from .sequence import *     # noqa: F401,F403
from .attention import *    # noqa: F401,F403
from .contrib import *      # noqa: F401,F403
from .detection import *    # noqa: F401,F403
from .misc import *         # noqa: F401,F403
from .quantization import *  # noqa: F401,F403
