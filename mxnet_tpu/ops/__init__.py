"""Operator library: pure functions over jax arrays.

TPU-native analog of src/operator/ (ref: SURVEY §2.2). Each op is a pure,
traceable function lowered by XLA; there is no per-op CUDA kernel — XLA
fusion replaces the reference's pointwise-fusion RTC pass
(ref: src/operator/fusion/fused_op.h:58), and Pallas kernels cover the few
hand-tuned hot spots (attention, fused optimizer updates).
"""
from . import elemwise    # noqa: F401
from . import reduce      # noqa: F401
from . import matrix      # noqa: F401
from . import nn          # noqa: F401
from . import index       # noqa: F401
from . import init       # noqa: F401
from . import random_ops  # noqa: F401
from . import optimizer_ops  # noqa: F401
from . import sequence    # noqa: F401
from . import attention   # noqa: F401
from . import contrib     # noqa: F401
from . import detection   # noqa: F401
from . import misc        # noqa: F401
from . import control_flow  # noqa: F401
from . import quantization  # noqa: F401
from . import numpy_ops   # noqa: F401
from . import sparse_ops  # noqa: F401
from . import graph      # noqa: F401
from . import ref_compat  # noqa: F401
from . import ref_aliases  # noqa: F401  (must come after all op modules)

from .elemwise import *     # noqa: F401,F403
from .reduce import *       # noqa: F401,F403
from .matrix import *       # noqa: F401,F403
from .nn import *           # noqa: F401,F403
from .index import *        # noqa: F401,F403
from .init import *         # noqa: F401,F403
from .random_ops import *   # noqa: F401,F403
from .optimizer_ops import *  # noqa: F401,F403
from .sequence import *     # noqa: F401,F403
from .attention import *    # noqa: F401,F403
from .contrib import *      # noqa: F401,F403
from .detection import *    # noqa: F401,F403
from .misc import *         # noqa: F401,F403
from .quantization import *  # noqa: F401,F403

# Multi-output arity annotations for the Symbol frontend: the eager path
# returns real tuples, but Symbol needs static arity to build output views
# (-1 = attr-dependent, resolved in symbol._op_arity).
from ..base import _OP_REGISTRY, register_op as _rr


def _set_arity(name, n):
    od = _OP_REGISTRY.get(name)
    if od is not None:
        _rr(name, num_outputs=n, mutate_inputs=od.mutate_inputs,
            nograd=od.nograd)(od.fn)


for _name, _n in [
    ('batch_norm', 3), ('sync_batch_norm_op', 3), ('moments', 2),
    ('slogdet', 2), ('histogram', 2), ('hawkes_ll', 2),
    ('multibox_target', 3), ('box_encode', 2),
    ('sgd_mom_update', 2), ('adam_update', 3), ('rnn', -1),
    ('SliceChannel', -1), ('slice_channel', -1),
]:
    _set_arity(_name, _n)
