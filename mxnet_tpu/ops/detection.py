"""Detection model ops: SSD/R-CNN training & inference heads.

Ref: src/operator/contrib/multibox_target.cc, multibox_detection.cc,
proposal.cc, psroi_pooling.cc, deformable_convolution.cc, correlation.cc,
bounding_box.cc (box_encode/box_decode).

All ops are static-shape, vectorized lax/jnp formulations: anchor matching
is argmax-based (vs the reference's sequential bipartite loop), NMS reuses
the suppression sweep from contrib.box_nms, and ROI ops vmap over rois —
everything tiles onto the MXU/VPU instead of per-box scalar loops.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..base import register_op

__all__ = []


def _reg(fn):
    register_op(fn.__name__)(fn)
    __all__.append(fn.__name__)
    return fn


def _center(box):
    """corner (x0,y0,x1,y1) -> center (cx,cy,w,h)"""
    wh = box[..., 2:4] - box[..., 0:2]
    return jnp.concatenate([box[..., 0:2] + 0.5 * wh, wh], axis=-1)


def _corner(box):
    half = 0.5 * box[..., 2:4]
    return jnp.concatenate([box[..., 0:2] - half, box[..., 0:2] + half],
                           axis=-1)


from .contrib import _iou_corner as _pair_iou  # (A,4),(M,4) -> (A,M)


@_reg
def box_encode(samples, matches, anchors, refs, means=(0., 0., 0., 0.),
               stds=(0.1, 0.1, 0.2, 0.2)):
    """Encode matched boxes as regression targets
    (ref: src/operator/contrib/bounding_box.cc BoxEncode).

    samples: (B, A) 1=positive, refs: (B, M, 4) corner gt boxes,
    matches: (B, A) gt index per anchor, anchors: (B, A, 4) corner.
    Returns (targets (B, A, 4), masks (B, A, 4)).
    """
    means = jnp.asarray(means, anchors.dtype)
    stds = jnp.asarray(stds, anchors.dtype)
    g = jnp.take_along_axis(refs, matches[..., None].astype(jnp.int32)
                            .clip(0), axis=1)
    a_c = _center(anchors)
    g_c = _center(g)
    eps = 1e-8
    t_xy = (g_c[..., :2] - a_c[..., :2]) / jnp.maximum(a_c[..., 2:4], eps)
    t_wh = jnp.log(jnp.maximum(g_c[..., 2:4], eps)
                   / jnp.maximum(a_c[..., 2:4], eps))
    targets = (jnp.concatenate([t_xy, t_wh], -1) - means) / stds
    masks = jnp.broadcast_to((samples > 0.5)[..., None], targets.shape)
    return jnp.where(masks, targets, 0.0), masks.astype(targets.dtype)


@_reg
def box_decode(data, anchors, std0=0.1, std1=0.1, std2=0.2, std3=0.2,
               clip=-1.0, format='corner'):
    """Decode regression deltas against anchors
    (ref: bounding_box.cc BoxDecode)."""
    stds = jnp.asarray([std0, std1, std2, std3], data.dtype)
    a = _center(anchors) if format == 'corner' else anchors
    d = data * stds
    xy = d[..., :2] * a[..., 2:4] + a[..., :2]
    wh = jnp.exp(d[..., 2:4]) * a[..., 2:4]
    out = _corner(jnp.concatenate([xy, wh], -1))
    if clip > 0:
        out = jnp.clip(out, 0.0, clip)
    return out


@_reg
def multibox_target(anchor, label, cls_pred, overlap_threshold=0.5,
                    ignore_label=-1.0, negative_mining_ratio=-1.0,
                    negative_mining_thresh=0.5, minimum_negative_samples=0,
                    variances=(0.1, 0.1, 0.2, 0.2)):
    """SSD training targets: match anchors to ground truth
    (ref: src/operator/contrib/multibox_target.cc).

    anchor: (1, A, 4) corner, label: (B, M, 5) [cls x0 y0 x1 y1] padded
    with -1 rows, cls_pred: (B, num_cls+1, A) (used for hard negative
    mining scores).
    Returns (box_target (B, A*4), box_mask (B, A*4), cls_target (B, A)).

    Matching is vectorized: each gt's best anchor is force-matched, then
    remaining anchors take any gt with IOU > threshold — the parallel
    equivalent of the reference's greedy bipartite loop.
    """
    A = anchor.shape[1]
    anc = anchor.reshape(A, 4)
    variances = jnp.asarray(variances, anchor.dtype)

    def one(lab, scores):
        valid = lab[:, 0] >= 0                      # (M,)
        gt = lab[:, 1:5]
        ious = _pair_iou(anc, gt)                   # (A, M)
        ious = jnp.where(valid[None, :], ious, -1.0)

        # force-match: the best anchor for each valid gt (padded gt rows
        # scatter out-of-range and are dropped)
        best_anchor_per_gt = jnp.argmax(ious, axis=0)          # (M,)
        scatter_idx = jnp.where(valid, best_anchor_per_gt, A)
        forced = jnp.zeros((A,), jnp.int32) - 1
        forced = forced.at[scatter_idx].set(
            jnp.arange(gt.shape[0], dtype=jnp.int32), mode='drop')

        # threshold match for the rest
        best_gt = jnp.argmax(ious, axis=1)                     # (A,)
        best_iou = jnp.take_along_axis(ious, best_gt[:, None],
                                       axis=1)[:, 0]
        matched = jnp.where(forced >= 0, forced,
                            jnp.where(best_iou >= overlap_threshold,
                                      best_gt, -1))            # (A,)
        pos = matched >= 0

        cls_target = jnp.where(
            pos, jnp.take(lab[:, 0], matched.clip(0)) + 1.0, 0.0)

        if negative_mining_ratio > 0:
            # hard negatives: highest background-loss anchors
            bg_score = jax.nn.log_softmax(scores.T, axis=-1)[:, 0]  # (A,)
            neg_cand = (~pos) & (best_iou < negative_mining_thresh)
            n_pos = jnp.sum(pos)
            n_neg = jnp.maximum(
                (n_pos * negative_mining_ratio).astype(jnp.int32),
                minimum_negative_samples)
            order = jnp.argsort(jnp.where(neg_cand, bg_score, jnp.inf))
            rank = jnp.zeros((A,), jnp.int32).at[order].set(jnp.arange(A))
            keep_neg = neg_cand & (rank < n_neg)
            cls_target = jnp.where(pos, cls_target,
                                   jnp.where(keep_neg, 0.0, ignore_label))

        samples = pos.astype(anchor.dtype)[None]
        targets, masks = box_encode(samples, matched[None], anc[None],
                                    gt[None], (0., 0., 0., 0.),
                                    tuple(variances.tolist()))
        return targets[0].reshape(-1), masks[0].reshape(-1), cls_target

    bt, bm, ct = jax.vmap(one)(label, cls_pred)
    return bt, bm, ct


@_reg
def multibox_detection(cls_prob, loc_pred, anchor, clip=True, threshold=0.01,
                       background_id=0, nms_threshold=0.5, force_suppress=False,
                       variances=(0.1, 0.1, 0.2, 0.2), nms_topk=-1):
    """SSD inference: decode + confidence filter + NMS
    (ref: src/operator/contrib/multibox_detection.cc).

    cls_prob: (B, num_cls+1, A), loc_pred: (B, A*4), anchor: (1, A, 4).
    Returns (B, A, 6) rows [cls_id, score, x0, y0, x1, y1], -1 padded.
    """
    from .contrib import box_nms
    B, _, A = cls_prob.shape
    deltas = loc_pred.reshape(B, A, 4)
    v = jnp.asarray(variances, loc_pred.dtype)
    boxes = box_decode(deltas, anchor.reshape(A, 4)[None],
                       *[float(x) for x in v],
                       clip=1.0 if clip else -1.0)          # (B, A, 4)

    scores = jnp.moveaxis(cls_prob, 1, 2)                    # (B, A, C+1)
    fg = scores.at[..., background_id].set(-1.0)
    cls_id = jnp.argmax(fg, axis=-1).astype(loc_pred.dtype)  # (B, A)
    score = jnp.max(fg, axis=-1)
    keep = score > threshold
    cls_out = jnp.where(keep, cls_id - (cls_id > background_id), -1.0)
    score = jnp.where(keep, score, -1.0)

    det = jnp.concatenate([cls_out[..., None], score[..., None], boxes], -1)
    out = box_nms(det, overlap_thresh=nms_threshold, valid_thresh=0.0,
                  topk=nms_topk, coord_start=2, score_index=1, id_index=0,
                  force_suppress=force_suppress)
    # suppressed/invalid entries are marked id=-1 (reference semantics)
    return out.at[..., 0].set(jnp.where(out[..., 1] < 0, -1.0, out[..., 0]))


@_reg
def proposal(cls_prob, bbox_pred, im_info, rpn_pre_nms_top_n=6000,
             rpn_post_nms_top_n=300, threshold=0.7, rpn_min_size=16,
             scales=(4, 8, 16, 32), ratios=(0.5, 1, 2), feature_stride=16):
    """RPN proposal generation (ref: src/operator/contrib/proposal.cc).

    cls_prob: (B, 2*K, H, W), bbox_pred: (B, 4*K, H, W), im_info: (B, 3)
    [height, width, scale]. Returns (B, post_nms_top_n, 5) [batch_idx,
    x0, y0, x1, y1].
    """
    B, _, H, W = cls_prob.shape
    K = len(scales) * len(ratios)

    # generate base anchors (centered at stride/2) — ref: proposal.cc
    base = float(feature_stride)
    anchors = []
    for r in ratios:
        for s in scales:
            size = base * base / r
            w = jnp.sqrt(size) * s
            h = w * r
            anchors.append(jnp.stack([(base - w) / 2, (base - h) / 2,
                                      (base + w) / 2, (base + h) / 2]))
    base_anchors = jnp.stack(anchors)                        # (K, 4)

    shift_x = jnp.arange(W) * feature_stride
    shift_y = jnp.arange(H) * feature_stride
    sx, sy = jnp.meshgrid(shift_x, shift_y)
    shifts = jnp.stack([sx.ravel(), sy.ravel(), sx.ravel(), sy.ravel()],
                       axis=1).astype(cls_prob.dtype)        # (HW, 4)
    all_anchors = (base_anchors[None] + shifts[:, None]).reshape(-1, 4)

    def one(scores_k, deltas_k, info):
        # scores: fg channel block; layout (2K, H, W) → fg = last K
        fg = scores_k[K:].reshape(K, -1).T.reshape(-1)       # (HW*K,)
        d = deltas_k.reshape(K, 4, -1).transpose(2, 0, 1).reshape(-1, 4)
        widths = all_anchors[:, 2] - all_anchors[:, 0] + 1.0
        heights = all_anchors[:, 3] - all_anchors[:, 1] + 1.0
        ctr_x = all_anchors[:, 0] + 0.5 * (widths - 1)
        ctr_y = all_anchors[:, 1] + 0.5 * (heights - 1)
        px = d[:, 0] * widths + ctr_x
        py = d[:, 1] * heights + ctr_y
        pw = jnp.exp(jnp.clip(d[:, 2], -10, 10)) * widths
        ph = jnp.exp(jnp.clip(d[:, 3], -10, 10)) * heights
        boxes = jnp.stack([px - 0.5 * (pw - 1), py - 0.5 * (ph - 1),
                           px + 0.5 * (pw - 1), py + 0.5 * (ph - 1)], 1)
        boxes = jnp.stack([boxes[:, 0].clip(0, info[1] - 1),
                           boxes[:, 1].clip(0, info[0] - 1),
                           boxes[:, 2].clip(0, info[1] - 1),
                           boxes[:, 3].clip(0, info[0] - 1)], 1)
        ws = boxes[:, 2] - boxes[:, 0] + 1
        hs = boxes[:, 3] - boxes[:, 1] + 1
        min_size = rpn_min_size * info[2]
        valid = (ws >= min_size) & (hs >= min_size)
        fg = jnp.where(valid, fg, -1.0)

        n_pre = min(rpn_pre_nms_top_n, fg.shape[0])
        top_scores, top_idx = lax.top_k(fg, n_pre)
        top_boxes = boxes[top_idx]
        from .contrib import box_nms
        det = jnp.concatenate([jnp.zeros((n_pre, 1), boxes.dtype),
                               top_scores[:, None], top_boxes], 1)
        kept = box_nms(det[None], overlap_thresh=threshold,
                       valid_thresh=0.0, topk=-1, coord_start=2,
                       score_index=1, id_index=0)[0]
        n_post = rpn_post_nms_top_n
        out = kept[:n_post, 2:6]
        pad = n_post - out.shape[0]
        if pad > 0:
            out = jnp.concatenate([out, jnp.zeros((pad, 4), out.dtype)], 0)
        mask = (kept[:n_post, 1] >= 0)
        if pad > 0:
            mask = jnp.concatenate([mask, jnp.zeros((pad,), bool)], 0)
        return jnp.where(mask[:, None], out, 0.0)

    rois = jax.vmap(one)(cls_prob, bbox_pred, im_info)       # (B, N, 4)
    bidx = jnp.broadcast_to(
        jnp.arange(B, dtype=cls_prob.dtype)[:, None, None],
        (B, rois.shape[1], 1))
    return jnp.concatenate([bidx, rois], axis=-1)


@_reg
def psroi_pooling(data, rois, spatial_scale, output_dim, pooled_size,
                  group_size=0):
    """Position-sensitive ROI pooling (R-FCN head)
    (ref: src/operator/contrib/psroi_pooling.cc).

    data: (B, output_dim*group^2, H, W), rois: (R, 5) [bidx x0 y0 x1 y1].
    Returns (R, output_dim, pooled, pooled).
    """
    if group_size == 0:
        group_size = pooled_size
    B, C, H, W = data.shape
    P, G = pooled_size, group_size

    def one(roi):
        bidx = roi[0].astype(jnp.int32)
        img = data[bidx]                                     # (C, H, W)
        x0, y0, x1, y1 = roi[1] * spatial_scale, roi[2] * spatial_scale, \
            roi[3] * spatial_scale, roi[4] * spatial_scale
        rw = jnp.maximum(x1 - x0, 0.1)
        rh = jnp.maximum(y1 - y0, 0.1)
        bin_w, bin_h = rw / P, rh / P

        # sample a fixed 2x2 grid per bin (average) — static shapes
        py, px = jnp.meshgrid(jnp.arange(P), jnp.arange(P), indexing='ij')
        gy = (py * G) // P
        gx = (px * G) // P
        out = jnp.zeros((output_dim, P, P), data.dtype)
        offs = [(0.25, 0.25), (0.25, 0.75), (0.75, 0.25), (0.75, 0.75)]
        for oy, ox in offs:
            sy = jnp.clip(y0 + (py + oy) * bin_h, 0, H - 1)
            sx = jnp.clip(x0 + (px + ox) * bin_w, 0, W - 1)
            iy = sy.astype(jnp.int32)
            ix = sx.astype(jnp.int32)
            # channel index: c*G*G + gy*G + gx for each output channel c
            cidx = (jnp.arange(output_dim)[:, None, None] * G * G
                    + gy[None] * G + gx[None])               # (D, P, P)
            out = out + img[cidx, iy[None], ix[None]]
        return out / len(offs)

    return jax.vmap(one)(rois)


@_reg
def deformable_convolution(data, offset, weight, bias=None, kernel=(3, 3),
                           stride=(1, 1), pad=(1, 1), dilate=(1, 1),
                           num_filter=None, num_deformable_group=1,
                           num_group=1, no_bias=False):
    """Deformable convolution v1
    (ref: src/operator/contrib/deformable_convolution.cc).

    data: (B, C, H, W); offset: (B, 2*KH*KW*dg, OH, OW);
    weight: (F, C, KH, KW). Implemented as offset-shifted bilinear im2col
    followed by one big matmul — the gather feeds the MXU a single GEMM
    instead of the reference's per-sample CUDA kernel.
    """
    B, C, H, W = data.shape
    KH, KW = kernel
    F = weight.shape[0]
    OH = (H + 2 * pad[0] - (dilate[0] * (KH - 1) + 1)) // stride[0] + 1
    OW = (W + 2 * pad[1] - (dilate[1] * (KW - 1) + 1)) // stride[1] + 1
    dg = num_deformable_group
    Cg = C // dg

    oy, ox = jnp.meshgrid(jnp.arange(OH), jnp.arange(OW), indexing='ij')
    ky, kx = jnp.meshgrid(jnp.arange(KH), jnp.arange(KW), indexing='ij')
    # base sampling locations: (KH, KW, OH, OW)
    base_y = (oy[None, None] * stride[0] - pad[0]
              + ky[:, :, None, None] * dilate[0]).astype(data.dtype)
    base_x = (ox[None, None] * stride[1] - pad[1]
              + kx[:, :, None, None] * dilate[1]).astype(data.dtype)

    def one(img, off):
        # off: (2*KH*KW*dg, OH, OW) layout [dg, KH, KW, (y,x)]
        off = off.reshape(dg, KH, KW, 2, OH, OW)
        cols = []
        for g in range(dg):
            sy = base_y + off[g, :, :, 0]
            sx = base_x + off[g, :, :, 1]
            y0 = jnp.floor(sy)
            x0 = jnp.floor(sx)
            wy = sy - y0
            wx = sx - x0
            pieces = 0
            for dy, wyy in ((0, 1 - wy), (1, wy)):
                for dx, wxx in ((0, 1 - wx), (1, wx)):
                    yf = y0 + dy
                    xf = x0 + dx
                    inb = ((yf >= 0) & (yf <= H - 1) &
                           (xf >= 0) & (xf <= W - 1))
                    yy = jnp.clip(yf, 0, H - 1).astype(jnp.int32)
                    xx = jnp.clip(xf, 0, W - 1).astype(jnp.int32)
                    v = img[g * Cg:(g + 1) * Cg][:, yy, xx]  # (Cg,KH,KW,OH,OW)
                    pieces = pieces + v * (wyy * wxx * inb)[None]
            cols.append(pieces)
        col = jnp.concatenate(cols, 0)                       # (C,KH,KW,OH,OW)
        if num_group == 1:
            col2 = col.reshape(C * KH * KW, OH * OW)
            return (weight.reshape(F, -1) @ col2).reshape(F, OH, OW)
        # grouped conv: each filter group sees only its channel group
        Cpg = C // num_group
        Fpg = F // num_group
        outs = []
        for gi in range(num_group):
            colg = col[gi * Cpg:(gi + 1) * Cpg].reshape(
                Cpg * KH * KW, OH * OW)
            wg = weight[gi * Fpg:(gi + 1) * Fpg].reshape(Fpg, -1)
            outs.append((wg @ colg).reshape(Fpg, OH, OW))
        return jnp.concatenate(outs, 0)

    out = jax.vmap(one)(data, offset)
    if bias is not None and not no_bias:
        out = out + bias[None, :, None, None]
    return out


@_reg
def correlation(data1, data2, kernel_size=1, max_displacement=1, stride1=1,
                stride2=1, pad_size=0, is_multiply=True):
    """Correlation cost volume (FlowNet)
    (ref: src/operator/correlation.cc). Output (B, D*D, OH, OW) where
    D = 2*(max_displacement//stride2) + 1."""
    B, C, H, W = data1.shape
    p = pad_size
    d1 = jnp.pad(data1, ((0, 0), (0, 0), (p, p), (p, p)))
    d2 = jnp.pad(data2, ((0, 0), (0, 0), (p, p), (p, p)))
    n_disp = max_displacement // stride2
    disps = [i * stride2 for i in range(-n_disp, n_disp + 1)]
    K = kernel_size
    Hp, Wp = H + 2 * p, W + 2 * p
    OH = (Hp - K - 2 * max_displacement) // stride1 + 1
    OW = (Wp - K - 2 * max_displacement) // stride1 + 1

    box = jnp.ones((1, 1, K, K), data1.dtype) / (K * K)
    maps = []
    for dy in disps:
        for dx in disps:
            a = lax.dynamic_slice(
                d1, (0, 0, max_displacement, max_displacement),
                (B, C, Hp - 2 * max_displacement, Wp - 2 * max_displacement))
            b = lax.dynamic_slice(
                d2, (0, 0, max_displacement + dy, max_displacement + dx),
                (B, C, Hp - 2 * max_displacement, Wp - 2 * max_displacement))
            if is_multiply:
                m = (a * b).mean(axis=1, keepdims=True)
            else:
                m = -jnp.abs(a - b).mean(axis=1, keepdims=True)
            if K > 1:
                # aggregate over the KxK patch (reference patch average)
                m = lax.conv_general_dilated(m, box, (1, 1), 'VALID')
            m = m[:, 0]
            maps.append(m[:, ::stride1, ::stride1][:, :OH, :OW])
    return jnp.stack(maps, axis=1)
