"""The numpy (`_npi_*`/`_np_*`) operator namespace as registered ops.

Ref: src/operator/numpy/ (98 files — np_elemwise_broadcast_op.cc,
np_broadcast_reduce_op_value.cc, np_einsum_op.cc, np_insert_op_*.cc,
np_delete_op.cc, np_matrix_op.cc, np_init_op.cc, np_window_op.cc,
linalg/np_*.cc, random/np_*_op.cc ...). The reference implements each op
as a CUDA/CPU kernel pair with shape/type inference; here each op is a
jnp/lax lowering (XLA supplies the kernels, fusion and autodiff) behind
the same internal op name, and the `mx.np` frontend dispatches through
this registry exactly like `mx.nd` dispatches through the legacy one.

Pure-backward helper nodes of the reference (`_npi_backward_nan_to_num`,
`_npi_backward_polyval`, `_npi_hsplit_backward`) are deliberately absent:
gradients come from jax.vjp on the forward lowering.

Ops whose output shape depends on VALUES (`_npi_unique`, `_npi_nonzero`,
`_npi_delete`, boolean-mask assign) are eager-only under jit, exactly as
data-dependent shapes are unsupported by XLA; the reference pays a device
sync for them too (ref: np_unique_op.cc SyncCopyToCPU).
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as onp

from ..base import register_op
from .. import random as _random

__all__ = []


def _reg(name, num_outputs=1, nograd=False):
    def deco(fn):
        register_op(name, num_outputs=num_outputs, nograd=nograd)(fn)
        __all__.append(name)
        return fn
    return deco


def _dt(dtype, default='float32'):
    return jnp.dtype(dtype if dtype is not None else default)


def _shape(shape):
    return (shape,) if isinstance(shape, int) else tuple(shape)


# ---------------------------------------------------------------------------
# elemwise broadcast binary (+ scalar and reflected-scalar variants)
# ref: np_elemwise_broadcast_op.cc, np_elemwise_broadcast_op_extended.cc,
#      np_elemwise_broadcast_logic_op.cc
# ---------------------------------------------------------------------------

_BINARY = {
    'add': jnp.add, 'subtract': jnp.subtract, 'multiply': jnp.multiply,
    'mod': jnp.mod, 'power': jnp.power, 'true_divide': jnp.true_divide,
    'floor_divide': jnp.floor_divide, 'arctan2': jnp.arctan2,
    'hypot': jnp.hypot, 'copysign': jnp.copysign, 'ldexp':
        lambda a, b: a * jnp.power(2.0, b),
    'lcm': jnp.lcm, 'gcd': jnp.gcd,
    'bitwise_and': jnp.bitwise_and, 'bitwise_or': jnp.bitwise_or,
    'bitwise_xor': jnp.bitwise_xor,
    'bitwise_left_shift': jnp.left_shift,
    'bitwise_right_shift': jnp.right_shift,
    'maximum': jnp.maximum, 'minimum': jnp.minimum,
    'fmax': jnp.fmax, 'fmin': jnp.fmin, 'fmod': jnp.fmod,
}
_LOGIC = {
    'equal': jnp.equal, 'not_equal': jnp.not_equal,
    'greater': jnp.greater, 'greater_equal': jnp.greater_equal,
    'less': jnp.less, 'less_equal': jnp.less_equal,
    'logical_and': jnp.logical_and, 'logical_or': jnp.logical_or,
    'logical_xor': jnp.logical_xor,
}

for _n, _f in _BINARY.items():
    _reg(f'_npi_{_n}')(lambda lhs, rhs, _f=_f: _f(lhs, rhs))
    _reg(f'_npi_{_n}_scalar')(
        lambda data, scalar=1.0, _f=_f: _f(data, scalar))
for _n in ('subtract', 'mod', 'power', 'true_divide', 'floor_divide',
           'arctan2', 'copysign', 'ldexp'):
    _f = _BINARY[_n]
    _reg(f'_npi_r{_n}_scalar')(
        lambda data, scalar=1.0, _f=_f: _f(scalar, data))
for _n, _f in _LOGIC.items():
    _reg(f'_npi_{_n}', nograd=True)(lambda lhs, rhs, _f=_f: _f(lhs, rhs))
    _reg(f'_npi_{_n}_scalar', nograd=True)(
        lambda data, scalar=0.0, _f=_f: _f(data, scalar))


# ---------------------------------------------------------------------------
# elemwise unary (ref: np_elemwise_unary_op_basic.cc)
# ---------------------------------------------------------------------------

_UNARY = {
    'abs': jnp.abs, 'absolute': jnp.abs, 'negative': jnp.negative,
    'reciprocal': jnp.reciprocal, 'sign': jnp.sign, 'rint': jnp.rint,
    'ceil': jnp.ceil, 'floor': jnp.floor, 'trunc': jnp.trunc,
    'fix': jnp.trunc, 'square': jnp.square, 'sqrt': jnp.sqrt,
    'cbrt': jnp.cbrt, 'exp': jnp.exp, 'expm1': jnp.expm1, 'log': jnp.log,
    'log2': jnp.log2, 'log10': jnp.log10, 'log1p': jnp.log1p,
    'degrees': jnp.degrees, 'radians': jnp.radians, 'deg2rad': jnp.deg2rad,
    'rad2deg': jnp.rad2deg, 'sin': jnp.sin, 'cos': jnp.cos,
    'tan': jnp.tan, 'arcsin': jnp.arcsin, 'arccos': jnp.arccos,
    'arctan': jnp.arctan, 'sinh': jnp.sinh, 'cosh': jnp.cosh,
    'tanh': jnp.tanh, 'arcsinh': jnp.arcsinh, 'arccosh': jnp.arccosh,
    'arctanh': jnp.arctanh, 'invert': jnp.invert,
    'bitwise_not': jnp.invert, 'exp2': jnp.exp2,
    'positive': jnp.positive, 'conjugate': jnp.conjugate,
}
for _n, _f in _UNARY.items():
    _reg(f'_npi_{_n}')(lambda data, _f=_f: _f(data))
_reg('_npi_logical_not', nograd=True)(lambda data: jnp.logical_not(data))
for _n in ('isnan', 'isinf', 'isfinite', 'isposinf', 'isneginf'):
    _reg(f'_npi_{_n}', nograd=True)(
        lambda data, _f=getattr(jnp, _n): _f(data))


@_reg('_npi_around')
def _npi_around(data, decimals=0):
    return jnp.round(data, decimals)


@_reg('_npi_nan_to_num')
def _npi_nan_to_num(data, copy=True, nan=0.0, posinf=None, neginf=None):
    return jnp.nan_to_num(data, nan=nan, posinf=posinf, neginf=neginf)


@_reg('_np_copy')
def _np_copy(a):
    return jnp.asarray(a)


# ---------------------------------------------------------------------------
# reductions (ref: np_broadcast_reduce_op_value.cc, *_boolean.cc, *_index.cc)
# ---------------------------------------------------------------------------

def _red(name, fn, nograd=False):
    @_reg(name, nograd=nograd)
    def op(a, axis=None, dtype=None, keepdims=False, initial=None,
           where=None, fn=fn):
        kw = {}
        if dtype is not None:
            kw['dtype'] = jnp.dtype(dtype)
        if initial is not None:
            kw['initial'] = initial
        if where is not None:
            kw['where'] = where
        return fn(a, axis=axis, keepdims=keepdims, **kw)
    return op


_red('_np_sum', jnp.sum)
_red('_np_prod', jnp.prod)
_red('_np_max', lambda a, axis=None, keepdims=False: jnp.max(
    a, axis=axis, keepdims=keepdims))
_red('_np_min', lambda a, axis=None, keepdims=False: jnp.min(
    a, axis=axis, keepdims=keepdims))
_red('_np_any', lambda a, axis=None, keepdims=False: jnp.any(
    a, axis=axis, keepdims=keepdims), nograd=True)
_red('_np_all', lambda a, axis=None, keepdims=False: jnp.all(
    a, axis=axis, keepdims=keepdims), nograd=True)


@_reg('_npi_mean')
def _npi_mean(a, axis=None, dtype=None, keepdims=False):
    kw = {'dtype': jnp.dtype(dtype)} if dtype is not None else {}
    return jnp.mean(a, axis=axis, keepdims=keepdims, **kw)


@_reg('_npi_std')
def _npi_std(a, axis=None, dtype=None, ddof=0, keepdims=False):
    kw = {'dtype': jnp.dtype(dtype)} if dtype is not None else {}
    return jnp.std(a, axis=axis, ddof=ddof, keepdims=keepdims, **kw)


@_reg('_npi_var')
def _npi_var(a, axis=None, dtype=None, ddof=0, keepdims=False):
    kw = {'dtype': jnp.dtype(dtype)} if dtype is not None else {}
    return jnp.var(a, axis=axis, ddof=ddof, keepdims=keepdims, **kw)


@_reg('_npi_average')
def _npi_average(a, axis=None, weights=None, returned=False):
    if weights is None:
        avg = jnp.mean(a, axis=axis)
        scl = jnp.asarray(a.size if axis is None
                          else a.shape[axis], jnp.float32)
    else:
        scl = jnp.sum(weights, axis=axis)
        avg = jnp.sum(a * weights, axis=axis) / scl
    if returned:
        return avg, jnp.broadcast_to(scl, avg.shape)
    return avg


@_reg('_npi_norm')
def _npi_norm(a, ord=2, axis=None, keepdims=False, flag=0):
    return jnp.linalg.norm(a, ord=None if flag == 0 else ord,
                           axis=axis, keepdims=keepdims)


@_reg('_npi_argmax', nograd=True)
def _npi_argmax(a, axis=None, keepdims=False):
    out = jnp.argmax(a, axis=axis)
    if keepdims and axis is not None:
        out = jnp.expand_dims(out, axis)
    return out


@_reg('_npi_argmin', nograd=True)
def _npi_argmin(a, axis=None, keepdims=False):
    out = jnp.argmin(a, axis=axis)
    if keepdims and axis is not None:
        out = jnp.expand_dims(out, axis)
    return out


@_reg('_npi_percentile')
def _npi_percentile(a, q, axis=None, interpolation='linear',
                    keepdims=False):
    return jnp.percentile(a, jnp.asarray(q), axis=axis,
                          method=interpolation, keepdims=keepdims)


@_reg('_npi_quantile')
def _npi_quantile(a, q, axis=None, interpolation='linear', keepdims=False):
    return jnp.quantile(a, jnp.asarray(q), axis=axis,
                        method=interpolation, keepdims=keepdims)


@_reg('_np_cumsum')
def _np_cumsum(a, axis=None, dtype=None):
    kw = {'dtype': jnp.dtype(dtype)} if dtype is not None else {}
    return jnp.cumsum(a, axis=axis, **kw)


@_reg('_npi_diff')
def _npi_diff(a, n=1, axis=-1):
    return jnp.diff(a, n=n, axis=axis)


@_reg('_npi_ediff1d')
def _npi_ediff1d(a, to_end=None, to_begin=None):
    return jnp.ediff1d(a, to_end=to_end, to_begin=to_begin)


@_reg('_npi_bincount', nograd=True)
def _npi_bincount(a, weights=None, minlength=0):
    length = max(int(minlength), int(onp.asarray(jax.device_get(a)).max())
                 + 1 if a.size else 1)
    return jnp.bincount(a, weights=weights, length=length)


# ---------------------------------------------------------------------------
# matrix / shape manipulation (ref: np_matrix_op.cc)
# ---------------------------------------------------------------------------

@_reg('_np_reshape')
def _np_reshape(a, newshape=None, order='C'):
    return jnp.reshape(a, newshape, order=order)


@_reg('_np_transpose')
def _np_transpose(a, axes=None):
    return jnp.transpose(a, axes)


@_reg('_np_squeeze')
def _np_squeeze(a, axis=None):
    return jnp.squeeze(a, axis)


@_reg('_np_moveaxis')
def _np_moveaxis(a, source, destination):
    return jnp.moveaxis(a, source, destination)


@_reg('_npi_swapaxes')
def _npi_swapaxes(a, dim1=0, dim2=1):
    return jnp.swapaxes(a, dim1, dim2)


@_reg('_np_roll')
def _np_roll(a, shift, axis=None):
    return jnp.roll(a, shift, axis)


@_reg('_npi_flip')
def _npi_flip(a, axis=None):
    return jnp.flip(a, axis)


@_reg('_npi_rot90')
def _npi_rot90(a, k=1, axes=(0, 1)):
    return jnp.rot90(a, k, axes)


@_reg('_npi_broadcast_to')
def _npi_broadcast_to(a, shape=()):
    return jnp.broadcast_to(a, _shape(shape))


@_reg('_npi_expand_dims')
def _npi_expand_dims(a, axis=0):
    return jnp.expand_dims(a, axis)


@_reg('_npi_concatenate')
def _npi_concatenate(*data, axis=0):
    if axis is None:
        return jnp.concatenate([jnp.ravel(d) for d in data])
    return jnp.concatenate(data, axis=axis)


@_reg('_npi_stack')
def _npi_stack(*data, axis=0):
    return jnp.stack(data, axis=axis)


@_reg('_npi_vstack')
def _npi_vstack(*data):
    return jnp.vstack(data)


@_reg('_npi_hstack')
def _npi_hstack(*data):
    return jnp.hstack(data)


@_reg('_npi_dstack')
def _npi_dstack(*data):
    return jnp.dstack(data)


@_reg('_npi_column_stack')
def _npi_column_stack(*data):
    return jnp.column_stack(data)


def _split_indices(ary, indices_or_sections, axis):
    if isinstance(indices_or_sections, int):
        return indices_or_sections
    return tuple(indices_or_sections)


@_reg('_npi_split', num_outputs=-1)
def _npi_split(ary, indices_or_sections=1, axis=0):
    return tuple(jnp.split(ary, _split_indices(ary, indices_or_sections,
                                               axis), axis=axis))


@_reg('_npi_hsplit', num_outputs=-1)
def _npi_hsplit(ary, indices_or_sections=1):
    return tuple(jnp.hsplit(ary, _split_indices(ary, indices_or_sections,
                                                1)))


@_reg('_npi_vsplit', num_outputs=-1)
def _npi_vsplit(ary, indices_or_sections=1):
    return tuple(jnp.vsplit(ary, _split_indices(ary, indices_or_sections,
                                                0)))


@_reg('_npi_dsplit', num_outputs=-1)
def _npi_dsplit(ary, indices_or_sections=1):
    return tuple(jnp.dsplit(ary, _split_indices(ary, indices_or_sections,
                                                2)))


@_reg('_npi_array_split', num_outputs=-1)
def _npi_array_split(ary, indices_or_sections=1, axis=0):
    return tuple(jnp.array_split(
        ary, _split_indices(ary, indices_or_sections, axis), axis=axis))


@_reg('_np_atleast_1d', num_outputs=-1)
def _np_atleast_1d(*arys):
    out = jnp.atleast_1d(*arys)
    return out if isinstance(out, (list, tuple)) else (out,)


@_reg('_np_atleast_2d', num_outputs=-1)
def _np_atleast_2d(*arys):
    out = jnp.atleast_2d(*arys)
    return out if isinstance(out, (list, tuple)) else (out,)


@_reg('_np_atleast_3d', num_outputs=-1)
def _np_atleast_3d(*arys):
    out = jnp.atleast_3d(*arys)
    return out if isinstance(out, (list, tuple)) else (out,)


@_reg('_np_diag')
def _np_diag(v, k=0):
    return jnp.diag(v, k)


@_reg('_np_diagflat')
def _np_diagflat(v, k=0):
    return jnp.diagflat(v, k)


@_reg('_np_diagonal')
def _np_diagonal(a, offset=0, axis1=0, axis2=1):
    return jnp.diagonal(a, offset, axis1, axis2)


@_reg('_np_trace')
def _np_trace(a, offset=0, axis1=0, axis2=1):
    return jnp.trace(a, offset, axis1, axis2)


@_reg('_npi_tril')
def _npi_tril(m, k=0):
    return jnp.tril(m, k)


@_reg('_npi_triu')
def _npi_triu(m, k=0):
    return jnp.triu(m, k)


@_reg('_npi_diag_indices_from', nograd=True)
def _npi_diag_indices_from(a):
    return tuple(jnp.diag_indices_from(a))


@_reg('_npi_pad')
def _npi_pad(a, pad_width, mode='constant', constant_values=0, **kwargs):
    pw = tuple(tuple(p) for p in pad_width)
    if mode == 'constant':
        return jnp.pad(a, pw, mode=mode, constant_values=constant_values)
    return jnp.pad(a, pw, mode=mode)


@_reg('_npi_squeeze')
def _npi_squeeze(a, axis=None):
    return jnp.squeeze(a, axis)


@_reg('_npi_tile')
def _npi_tile(a, reps=(1,)):
    return jnp.tile(a, _shape(reps))


@_reg('_npi_repeat')
def _npi_repeat(a, repeats=1, axis=None):
    return jnp.repeat(a, repeats, axis=axis)


@_reg('_npi_ravel')
def _npi_ravel(a, order='C'):
    return jnp.ravel(a, order=order)


@_reg('_npi_share_memory', nograd=True)
def _npi_share_memory(a, b):
    # functional arrays never alias from the user's perspective
    return jnp.zeros((), jnp.bool_)


@_reg('_npi_insert_scalar')
def _npi_insert_scalar(arr, obj=0, values=0.0, axis=None):
    return jnp.insert(arr, int(obj), values, axis=axis)


@_reg('_npi_insert_slice')
def _npi_insert_slice(arr, values, start=None, stop=None, step=None,
                      axis=None):
    idx = onp.arange(*slice(start, stop, step).indices(
        arr.shape[axis if axis is not None else 0]
        if axis is not None else arr.size))
    return jnp.insert(arr, idx, values, axis=axis)


@_reg('_npi_insert_tensor')
def _npi_insert_tensor(arr, obj, values, axis=None):
    return jnp.insert(arr, onp.asarray(jax.device_get(obj)), values,
                      axis=axis)


@_reg('_npi_delete', nograd=True)
def _npi_delete(arr, obj=None, start=None, stop=None, step=None,
                axis=None):
    if obj is None:
        obj = onp.arange(*slice(start, stop, step).indices(
            arr.shape[axis if axis is not None else 0]
            if axis is not None else arr.size))
    elif hasattr(obj, 'shape'):
        obj = onp.asarray(jax.device_get(obj))
    else:
        obj = int(obj)
    return jnp.delete(arr, obj, axis=axis)


@_reg('_npi_unique', nograd=True, num_outputs=-1)
def _npi_unique(a, return_index=False, return_inverse=False,
                return_counts=False, axis=None):
    out = jnp.unique(a, return_index=return_index,
                     return_inverse=return_inverse,
                     return_counts=return_counts, axis=axis)
    return out if isinstance(out, tuple) else (out,)


@_reg('_npi_nonzero', nograd=True)
def _npi_nonzero(a):
    # reference returns an (ndim, nnz) index tensor (np_nonzero_op.cc)
    return jnp.stack(jnp.nonzero(a), axis=0)


@_reg('_npi_flatnonzero', nograd=True)
def _npi_flatnonzero(a):
    return jnp.flatnonzero(a)


@_reg('_npi_searchsorted', nograd=True)
def _npi_searchsorted(a, v, side='left'):
    return jnp.searchsorted(a, v, side=side)


@_reg('_npi_where')
def _npi_where(condition, x, y):
    return jnp.where(condition.astype(bool), x, y)


@_reg('_npi_where_lscalar')
def _npi_where_lscalar(condition, y, scalar=0.0):
    return jnp.where(condition.astype(bool), scalar, y)


@_reg('_npi_where_rscalar')
def _npi_where_rscalar(condition, x, scalar=0.0):
    return jnp.where(condition.astype(bool), x, scalar)


@_reg('_npi_where_scalar2')
def _npi_where_scalar2(condition, x=0.0, y=0.0):
    return jnp.where(condition.astype(bool), x, y)


@_reg('_npi_boolean_mask_assign_scalar')
def _npi_boolean_mask_assign_scalar(data, mask, value=0.0):
    return jnp.where(mask.astype(bool), value, data)


@_reg('_npi_boolean_mask_assign_tensor')
def _npi_boolean_mask_assign_tensor(data, mask, value):
    m = mask.astype(bool)
    if value.ndim == data.ndim:
        return jnp.where(m, value, data)
    # reference packs values for the True positions (row-major)
    idx = jnp.cumsum(m.ravel()) - 1
    picked = jnp.take(value.ravel(), jnp.clip(idx, 0, value.size - 1))
    return jnp.where(m, picked.reshape(data.shape), data)


@_reg('_npi_polyval')
def _npi_polyval(p, x):
    return jnp.polyval(p, x)


@_reg('_npi_constraint_check', nograd=True)
def _npi_constraint_check(data, msg="constraint violated"):
    # ref: np_constraint_check.cc — raises on False at sync time
    ok = bool(jnp.all(data))
    if not ok:
        raise ValueError(msg)
    return jnp.asarray(True)


# ---------------------------------------------------------------------------
# tensordot / matmul / einsum / kron
# ref: np_tensordot_op.cc, np_matmul_op.cc, np_einsum_op.cc, np_kron.cc
# ---------------------------------------------------------------------------

@_reg('_npi_matmul')
def _npi_matmul(a, b):
    return jnp.matmul(a, b)


@_reg('_np_dot')
def _np_dot(a, b):
    return jnp.dot(a, b)


@_reg('_npi_tensordot')
def _npi_tensordot(a, b, a_axes_summed=(), b_axes_summed=()):
    return jnp.tensordot(a, b, axes=(tuple(a_axes_summed),
                                     tuple(b_axes_summed)))


@_reg('_npi_tensordot_int_axes')
def _npi_tensordot_int_axes(a, b, axes=2):
    return jnp.tensordot(a, b, axes=int(axes))


@_reg('_npi_kron')
def _npi_kron(a, b):
    return jnp.kron(a, b)


@_reg('_npi_einsum')
def _npi_einsum(*operands, subscripts='', optimize=False):
    return jnp.einsum(subscripts, *operands,
                      optimize='optimal' if optimize else 'auto')


@_reg('_npi_cross')
def _npi_cross(a, b, axisa=-1, axisb=-1, axisc=-1):
    return jnp.cross(a, b, axisa=axisa, axisb=axisb, axisc=axisc)


@_reg('_npi_vdot')
def _npi_vdot(a, b):
    return jnp.vdot(a, b)


@_reg('_npi_inner')
def _npi_inner(a, b):
    return jnp.inner(a, b)


@_reg('_npi_outer')
def _npi_outer(a, b):
    return jnp.outer(a, b)


# ---------------------------------------------------------------------------
# linalg (ref: src/operator/numpy/linalg/np_*.cc)
# ---------------------------------------------------------------------------

@_reg('_npi_cholesky')
def _npi_cholesky(a, lower=True):
    L = jnp.linalg.cholesky(a)
    return L if lower else jnp.swapaxes(L, -1, -2)


@_reg('_npi_svd', num_outputs=3)
def _npi_svd(a):
    u, s, vh = jnp.linalg.svd(a, full_matrices=False)
    return u, s, vh


@_reg('_npi_eig', num_outputs=2, nograd=True)
def _npi_eig(a):
    w, v = jnp.linalg.eig(a)
    return w, v


@_reg('_npi_eigh', num_outputs=2)
def _npi_eigh(a, upper=False):
    return jnp.linalg.eigh(a, UPLO='U' if upper else 'L')


@_reg('_npi_eigvals', nograd=True)
def _npi_eigvals(a):
    return jnp.linalg.eigvals(a)


@_reg('_npi_eigvalsh')
def _npi_eigvalsh(a, upper=False):
    return jnp.linalg.eigvalsh(a, UPLO='U' if upper else 'L')


@_reg('_npi_solve')
def _npi_solve(a, b):
    return jnp.linalg.solve(a, b)


@_reg('_npi_lstsq', num_outputs=4, nograd=True)
def _npi_lstsq(a, b, rcond=None):
    x, res, rank, s = jnp.linalg.lstsq(a, b, rcond=rcond)
    return x, res, rank, s


@_reg('_npi_inv')
def _npi_inv(a):
    return jnp.linalg.inv(a)


@_reg('_npi_pinv')
def _npi_pinv(a, rcond):
    return jnp.linalg.pinv(a, rtol=rcond)


@_reg('_npi_pinv_scalar_rcond')
def _npi_pinv_scalar_rcond(a, rcond=1e-15):
    return jnp.linalg.pinv(a, rtol=rcond)


@_reg('_npi_tensorinv')
def _npi_tensorinv(a, ind=2):
    return jnp.linalg.tensorinv(a, ind=ind)


@_reg('_npi_tensorsolve')
def _npi_tensorsolve(a, b, a_axes=None):
    return jnp.linalg.tensorsolve(a, b, axes=a_axes)


@_reg('_npi_matrix_rank', nograd=True)
def _npi_matrix_rank(M, tol=None, hermitian=False):
    return jnp.linalg.matrix_rank(M, rtol=tol)


@_reg('_npi_det')
def _npi_det(a):
    return jnp.linalg.det(a)


@_reg('_npi_slogdet', num_outputs=2)
def _npi_slogdet(a):
    sign, logdet = jnp.linalg.slogdet(a)
    return sign, logdet


@_reg('_npi_qr', num_outputs=2)
def _npi_qr(a):
    q, r = jnp.linalg.qr(a)
    return q, r


@_reg('_npi_multi_dot')
def _npi_multi_dot(*arrays):
    return jnp.linalg.multi_dot(arrays)


@_reg('_npi_matrix_power')
def _npi_matrix_power(a, n=1):
    return jnp.linalg.matrix_power(a, n)


# ---------------------------------------------------------------------------
# init ops (ref: np_init_op.cc) and windows (np_window_op.cc)
# ---------------------------------------------------------------------------

@_reg('_npi_zeros', nograd=True)
def _npi_zeros(shape=(), dtype='float32'):
    return jnp.zeros(_shape(shape), _dt(dtype))


@_reg('_npi_ones', nograd=True)
def _npi_ones(shape=(), dtype='float32'):
    return jnp.ones(_shape(shape), _dt(dtype))


@_reg('_npi_full', nograd=True)
def _npi_full(shape=(), fill_value=0.0, dtype=None):
    return jnp.full(_shape(shape), fill_value, _dt(dtype))


@_reg('_npi_full_like', nograd=True)
def _npi_full_like(a, fill_value=0.0, dtype=None):
    return jnp.full_like(a, fill_value,
                         dtype=None if dtype is None else jnp.dtype(dtype))


@_reg('_npi_arange', nograd=True)
def _npi_arange(start=0, stop=None, step=1, dtype='float32'):
    return jnp.arange(start, stop, step, _dt(dtype))


@_reg('_npi_linspace', nograd=True)
def _npi_linspace(start=0.0, stop=1.0, num=50, endpoint=True,
                  dtype='float32'):
    return jnp.linspace(start, stop, int(num), endpoint=endpoint,
                        dtype=_dt(dtype))


@_reg('_npi_logspace', nograd=True)
def _npi_logspace(start=0.0, stop=1.0, num=50, endpoint=True, base=10.0,
                  dtype='float32'):
    return jnp.logspace(start, stop, int(num), endpoint=endpoint,
                        base=base, dtype=_dt(dtype))


@_reg('_npi_eye', nograd=True)
def _npi_eye(N=1, M=None, k=0, dtype='float32'):
    return jnp.eye(int(N), None if M is None else int(M), int(k),
                   dtype=_dt(dtype))


@_reg('_npi_identity', nograd=True)
def _npi_identity(n=1, dtype='float32'):
    return jnp.identity(int(n), _dt(dtype))


@_reg('_npi_indices', nograd=True)
def _npi_indices(dimensions=(), dtype='int32'):
    return jnp.stack(jnp.indices(_shape(dimensions), _dt(dtype, 'int32')))


@_reg('_npi_tri', nograd=True)
def _npi_tri(N=1, M=None, k=0, dtype='float32'):
    return jnp.tri(int(N), None if M is None else int(M), int(k),
                   dtype=_dt(dtype))


@_reg('_npi_hanning', nograd=True)
def _npi_hanning(M=1, dtype='float32'):
    return jnp.hanning(int(M)).astype(_dt(dtype))


@_reg('_npi_hamming', nograd=True)
def _npi_hamming(M=1, dtype='float32'):
    return jnp.hamming(int(M)).astype(_dt(dtype))


@_reg('_npi_blackman', nograd=True)
def _npi_blackman(M=1, dtype='float32'):
    return jnp.blackman(int(M)).astype(_dt(dtype))


@_reg('_npi_meshgrid', num_outputs=-1, nograd=True)
def _npi_meshgrid(*xi, indexing='xy'):
    return tuple(jnp.meshgrid(*xi, indexing=indexing))


# ---------------------------------------------------------------------------
# random samplers (ref: src/operator/numpy/random/np_*_op.cc); keys come
# from the framework provider stack like ops/random_ops.py
# ---------------------------------------------------------------------------

def _sample_shape(shape, *params):
    if shape is not None:
        return _shape(shape)
    shp = ()
    for p in params:
        if hasattr(p, 'shape'):
            shp = jnp.broadcast_shapes(shp, p.shape)
    return shp


@_reg('_npi_uniform', nograd=True)
def _npi_uniform(low=0.0, high=1.0, size=None, dtype='float32'):
    key = _random.next_key()
    shp = _sample_shape(size, low, high)
    u = jax.random.uniform(key, shp, _dt(dtype))
    return low + u * (jnp.asarray(high) - jnp.asarray(low))


@_reg('_npi_normal', nograd=True)
def _npi_normal(loc=0.0, scale=1.0, size=None, dtype='float32'):
    key = _random.next_key()
    shp = _sample_shape(size, loc, scale)
    return loc + scale * jax.random.normal(key, shp, _dt(dtype))


@_reg('_npi_gamma', nograd=True)
def _npi_gamma(shape=1.0, scale=1.0, size=None, dtype='float32'):
    key = _random.next_key()
    shp = _sample_shape(size, shape, scale)
    return scale * jax.random.gamma(key, shape, shp, _dt(dtype))


@_reg('_npi_bernoulli', nograd=True)
def _npi_bernoulli(prob=0.5, size=None, dtype='float32'):
    key = _random.next_key()
    shp = _sample_shape(size, prob)
    return jax.random.bernoulli(key, prob, shp).astype(_dt(dtype))


@_reg('_npi_exponential', nograd=True)
def _npi_exponential(scale=1.0, size=None, dtype='float32'):
    key = _random.next_key()
    shp = _sample_shape(size, scale)
    return scale * jax.random.exponential(key, shp, _dt(dtype))


@_reg('_npi_gumbel', nograd=True)
def _npi_gumbel(loc=0.0, scale=1.0, size=None, dtype='float32'):
    key = _random.next_key()
    shp = _sample_shape(size, loc, scale)
    return loc + scale * jax.random.gumbel(key, shp, _dt(dtype))


@_reg('_npi_logistic', nograd=True)
def _npi_logistic(loc=0.0, scale=1.0, size=None, dtype='float32'):
    key = _random.next_key()
    shp = _sample_shape(size, loc, scale)
    return loc + scale * jax.random.logistic(key, shp, _dt(dtype))


@_reg('_npi_laplace', nograd=True)
def _npi_laplace(loc=0.0, scale=1.0, size=None, dtype='float32'):
    key = _random.next_key()
    shp = _sample_shape(size, loc, scale)
    return loc + scale * jax.random.laplace(key, shp, _dt(dtype))


@_reg('_npi_rayleigh', nograd=True)
def _npi_rayleigh(scale=1.0, size=None, dtype='float32'):
    key = _random.next_key()
    shp = _sample_shape(size, scale)
    u = jax.random.uniform(key, shp, _dt(dtype), minval=1e-7)
    return scale * jnp.sqrt(-2.0 * jnp.log(u))


@_reg('_npi_weibull', nograd=True)
def _npi_weibull(a=1.0, size=None, dtype='float32'):
    key = _random.next_key()
    shp = _sample_shape(size, a)
    u = jax.random.uniform(key, shp, _dt(dtype), minval=1e-7)
    return jnp.power(-jnp.log(u), 1.0 / jnp.asarray(a))


@_reg('_npi_pareto', nograd=True)
def _npi_pareto(a=1.0, size=None, dtype='float32'):
    key = _random.next_key()
    shp = _sample_shape(size, a)
    u = jax.random.uniform(key, shp, _dt(dtype), minval=1e-7)
    return jnp.power(u, -1.0 / jnp.asarray(a)) - 1.0


@_reg('_npi_powerd', nograd=True)
def _npi_powerd(a=1.0, size=None, dtype='float32'):
    key = _random.next_key()
    shp = _sample_shape(size, a)
    u = jax.random.uniform(key, shp, _dt(dtype), minval=1e-7)
    return jnp.power(u, 1.0 / jnp.asarray(a))


@_reg('_npi_multinomial', nograd=True)
def _npi_multinomial(n=1, pvals=None, size=None):
    key = _random.next_key()
    pv = jnp.asarray(pvals)
    shp = () if size is None else tuple(size)
    pb = jnp.broadcast_to(pv, shp + pv.shape)
    if hasattr(jax.random, 'multinomial'):
        counts = jax.random.multinomial(key, float(n), pb)
    else:
        # jax < 0.4.31: n categorical draws histogrammed per batch row
        draws = jax.random.categorical(key, jnp.log(pb),
                                       shape=(int(n),) + pb.shape[:-1])
        counts = jax.nn.one_hot(draws, pb.shape[-1],
                                dtype=jnp.int32).sum(axis=0)
    return counts.astype(jnp.int64)


@_reg('_npi_choice', nograd=True)
def _npi_choice(a, size=None, replace=True, p=None):
    key = _random.next_key()
    shp = () if size is None else tuple(size)
    if not hasattr(a, 'shape') or getattr(a, 'ndim', 1) == 0:
        a = jnp.arange(int(a))
    return jax.random.choice(key, a, shp, replace=replace, p=p)


@_reg('_npi_shuffle', nograd=True)
def _npi_shuffle(a):
    key = _random.next_key()
    return jax.random.permutation(key, a)


@_reg('_npi_randint', nograd=True)
def _npi_randint(low=0, high=None, size=None, dtype='int32'):
    key = _random.next_key()
    if high is None:
        low, high = 0, low
    shp = () if size is None else tuple(size)
    return jax.random.randint(key, shp, low, high, _dt(dtype, 'int32'))
