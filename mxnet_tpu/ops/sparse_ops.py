"""Sparse storage ops.

Ref: src/operator/tensor/cast_storage.cc, sparse_retain.cc,
dot.cc (FComputeEx csr/row_sparse paths). The ndarray-level sparse API
(ndarray/sparse.py) keeps a dense payload — XLA has no general sparse
layout — so `cast_storage` is metadata at that level; the ops here supply
the compute-side pieces: retain-by-rows, and a genuinely sparse
matrix-multiply over jax.experimental.sparse BCOO for workloads where the
operand is sparse enough that the BCOO contraction beats the dense MXU
path (very high sparsity; on TPU the dense matmul usually wins, which is
why the BCOO route is opt-in exactly like the reference's FComputeEx
dispatch is storage-type driven).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..base import register_op, register_sparse_impl

__all__ = []

# storage-dispatch telemetry: which sparse kernels actually ran
# (trace-time counts, like ops.attention.route_counts)
route_counts = {'dot_csr_dense': 0}


def _reg(fn):
    register_op(fn.__name__)(fn)
    __all__.append(fn.__name__)
    return fn


@_reg
def cast_storage(data, stype='default'):
    """Storage-type cast (ref: cast_storage.cc). The dense payload is the
    canonical representation for every stype; values pass through
    unchanged — the stype tag lives on the NDArray wrapper."""
    return jnp.asarray(data)


@_reg
def sparse_retain(data, indices):
    """Zero every row not named in `indices`
    (ref: src/operator/tensor/sparse_retain.cc)."""
    idx = jnp.asarray(indices, jnp.int32)
    mask = jnp.zeros((data.shape[0],), bool).at[idx].set(True)
    shape = (data.shape[0],) + (1,) * (data.ndim - 1)
    return jnp.where(mask.reshape(shape), data, 0)


@_reg
def dot_csr_dense(lhs, rhs, nse=None):
    """lhs @ rhs with lhs contracted through a BCOO sparse representation
    (ref: dot.cc DotCsrDnsDnsImpl). `nse`: number of stored elements to
    allocate (static under jit); defaults to the dense element count,
    callers with known sparsity should pass the true nnz budget."""
    from jax.experimental import sparse as jsparse
    if nse is None:
        nse = int(lhs.shape[0]) * int(lhs.shape[1])
    sp = jsparse.BCOO.fromdense(lhs, nse=nse)
    return sp @ rhs


@register_sparse_impl('dot', ('csr', 'default'))
def _dot_csr_dense_dispatch(lhs, rhs, transpose_a=False,
                            transpose_b=False, nse=None):
    """FComputeEx route for nd.dot(csr, dense) (ref: dot.cc
    DotCsrDnsDnsImpl): contract through BCOO with the true nnz budget.
    `nse` arrives from __sparse_prepare__ below, computed eagerly from
    the concrete payload BEFORE tracing — under autograd the lhs seen
    here is a tracer, and BCOO needs a static budget. Differentiable:
    bcoo_dot_general carries transpose rules, so grad(W) of
    dot(csr_x, W) works.

    Only the 2-D x 2-D case takes the sparse path (the reference's CSR
    dot is likewise matrix-only); anything else defers to the dense op
    so both storages keep identical tensordot semantics."""
    if lhs.ndim != 2 or rhs.ndim != 2:
        from .matrix import dot as _dense_dot
        return _dense_dot(lhs, rhs, transpose_a=transpose_a,
                          transpose_b=transpose_b)
    if transpose_a:
        lhs = lhs.T
    if transpose_b:
        rhs = rhs.T
    if nse is None:
        nse = int(lhs.shape[0]) * int(lhs.shape[1])
    route_counts['dot_csr_dense'] += 1
    return dot_csr_dense(lhs, rhs, nse=nse)


def _dot_csr_prepare(args, kwargs):
    """nnz budget from the CONCRETE payload, cached on the wrapper so a
    training loop reusing one CSR matrix counts once, not per step. The
    cache holds a WEAK reference to the payload — replacing ._data must
    not pin the old device buffer alive."""
    import weakref
    import numpy as onp
    lhs = args[0]
    data = getattr(lhs, '_data', None)
    cached = getattr(lhs, '_nnz_cache', None)
    if cached is not None and data is not None and cached[0]() is data:
        return {'nse': cached[1]}
    payload = lhs.asnumpy() if hasattr(lhs, 'asnumpy') else onp.asarray(lhs)
    nse = max(1, int(onp.count_nonzero(payload)))
    if data is not None:
        try:
            lhs._nnz_cache = (weakref.ref(data), nse)
        except (AttributeError, TypeError):  # no slot / unweakrefable
            pass
    return {'nse': nse}


_dot_csr_dense_dispatch.__sparse_prepare__ = _dot_csr_prepare


@_reg
def storage_type(data):
    """Always 'default' at the payload level (kDefaultStorage=0 in the
    reference's stype enum); wrapper types carry csr/row_sparse tags."""
    return jnp.zeros((), jnp.int32)
