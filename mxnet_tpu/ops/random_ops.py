"""Random sampling ops (ref: src/operator/random/sample_op.cc et al).

Keys come from mxnet_tpu.random's provider stack (global stateful stream in
eager mode, functional split stream under tracing).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..base import register_op
from .. import random as _random

__all__ = []


def _reg(fn):
    register_op(fn.__name__, nograd=True)(fn)
    __all__.append(fn.__name__)
    return fn


@_reg
def random_uniform(low=0.0, high=1.0, shape=(), dtype='float32'):
    key = _random.next_key()
    return jax.random.uniform(key, tuple(shape), dtype=jnp.dtype(dtype),
                              minval=low, maxval=high)


@_reg
def random_normal(loc=0.0, scale=1.0, shape=(), dtype='float32'):
    key = _random.next_key()
    return loc + scale * jax.random.normal(key, tuple(shape),
                                           dtype=jnp.dtype(dtype))


@_reg
def random_gamma(alpha=1.0, beta=1.0, shape=(), dtype='float32'):
    key = _random.next_key()
    return beta * jax.random.gamma(key, alpha, tuple(shape),
                                   dtype=jnp.dtype(dtype))


@_reg
def random_exponential(lam=1.0, shape=(), dtype='float32'):
    key = _random.next_key()
    return jax.random.exponential(key, tuple(shape),
                                  dtype=jnp.dtype(dtype)) / lam


@_reg
def random_poisson(lam=1.0, shape=(), dtype='float32'):
    key = _random.next_key()
    return jax.random.poisson(key, lam, tuple(shape)).astype(jnp.dtype(dtype))


@_reg
def random_negative_binomial(k=1, p=1.0, shape=(), dtype='float32'):
    key1, key2 = jax.random.split(_random.next_key())
    g = jax.random.gamma(key1, k, tuple(shape)) * ((1 - p) / p)
    return jax.random.poisson(key2, g).astype(jnp.dtype(dtype))


@_reg
def random_generalized_negative_binomial(mu=1.0, alpha=1.0, shape=(), dtype='float32'):
    key1, key2 = jax.random.split(_random.next_key())
    g = jax.random.gamma(key1, 1.0 / alpha, tuple(shape)) * (alpha * mu)
    return jax.random.poisson(key2, g).astype(jnp.dtype(dtype))


@_reg
def random_randint(low=0, high=1, shape=(), dtype='int32'):
    key = _random.next_key()
    return jax.random.randint(key, tuple(shape), low, high,
                              dtype=jnp.dtype(dtype))


@_reg
def sample_multinomial(data, shape=(), get_prob=False, dtype='int32'):
    """Ref: src/operator/random/multisample_op.cc. data: (..., K) probabilities."""
    key = _random.next_key()
    n = 1
    for s in (shape if isinstance(shape, (tuple, list)) else (shape,)):
        n *= int(s) if s else 1
    logits = jnp.log(jnp.maximum(data, 1e-30))
    out_shape = data.shape[:-1] + (tuple(shape) if isinstance(shape, (tuple, list)) else (shape,) if shape else ())
    if not shape:
        samp = jax.random.categorical(key, logits, axis=-1)
        return samp.astype(jnp.dtype(dtype))
    samp = jax.random.categorical(key, logits[..., None, :], axis=-1,
                                  shape=data.shape[:-1] + (n,))
    return samp.reshape(out_shape).astype(jnp.dtype(dtype))


@_reg
def shuffle(data):
    key = _random.next_key()
    return jax.random.permutation(key, data, axis=0)


@_reg
def sample_uniform(low, high, shape=(), dtype='float32'):
    """Per-element distribution params (ref: src/operator/random/sample_op.cc)."""
    key = _random.next_key()
    sshape = low.shape + tuple(shape)
    u = jax.random.uniform(key, sshape, dtype=jnp.dtype(dtype))
    low_b = low.reshape(low.shape + (1,) * len(tuple(shape)))
    high_b = high.reshape(high.shape + (1,) * len(tuple(shape)))
    return low_b + u * (high_b - low_b)


@_reg
def sample_normal(mu, sigma, shape=(), dtype='float32'):
    key = _random.next_key()
    sshape = mu.shape + tuple(shape)
    z = jax.random.normal(key, sshape, dtype=jnp.dtype(dtype))
    mu_b = mu.reshape(mu.shape + (1,) * len(tuple(shape)))
    sig_b = sigma.reshape(sigma.shape + (1,) * len(tuple(shape)))
    return mu_b + z * sig_b


@_reg
def sample_gamma(alpha, beta, shape=(), dtype='float32'):
    key = _random.next_key()
    sshape = alpha.shape + tuple(shape)
    a_b = alpha.reshape(alpha.shape + (1,) * len(tuple(shape)))
    b_b = beta.reshape(beta.shape + (1,) * len(tuple(shape)))
    g = jax.random.gamma(key, jnp.broadcast_to(a_b, sshape),
                         dtype=jnp.dtype(dtype))
    return g * b_b
