"""Neural-network ops: FC, convolution, pooling, norms, softmax, dropout.

Ref: src/operator/nn/ (fully_connected.cc, convolution.cc, batch_norm.cc,
layer_norm.cc, softmax.cc, pooling.cc, dropout.cc, activation.cc ...).

Design notes (TPU-first):
- Convolutions use `lax.conv_general_dilated` with NCHW logical layout;
  XLA relayouts for the MXU internally, so we keep the reference's NCHW
  user-facing convention without a perf penalty.
- BatchNorm returns (out, new_running_mean, new_running_var): running stats
  are functional outputs (layers write them back), because everything must
  stay pure under jit.
- Dropout draws keys from mxnet_tpu.random's provider stack so it works in
  both eager and traced (hybridized) modes.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..base import register_op, MXNetError, state
from .. import random as _random
from . import rowsparse as _rowsparse

__all__ = []


def _reg(fn):
    register_op(fn.__name__)(fn)
    __all__.append(fn.__name__)
    return fn


def _tup(v, n):
    if v is None:
        return (0,) * n
    if isinstance(v, int):
        return (v,) * n
    v = tuple(int(x) for x in v)
    if len(v) == 1:
        return v * n
    return v


# conv dimension_numbers by spatial rank, shared with quantized_conv
_CONV_DN = {1: ('NCH', 'OIH', 'NCH'), 2: ('NCHW', 'OIHW', 'NCHW'),
            3: ('NCDHW', 'OIDHW', 'NCDHW')}


@_reg
def fully_connected(data, weight, bias=None, num_hidden=None, no_bias=False,
                    flatten=True):
    """Ref: src/operator/nn/fully_connected.cc. y = x W^T + b; weight is
    (num_hidden, in_dim) as in the reference. Single dot_general → MXU."""
    if flatten and data.ndim > 2:
        data = data.reshape(data.shape[0], -1)
    out = lax.dot_general(data, weight,
                          (((data.ndim - 1,), (1,)), ((), ())),
                          preferred_element_type=jnp.float32)
    out = out.astype(data.dtype)
    if bias is not None and not no_bias:
        out = out + bias
    return out


@_reg
def convolution(data, weight, bias=None, kernel=None, stride=None, dilate=None,
                pad=None, num_filter=0, num_group=1, no_bias=False, layout='NCHW'):
    """Ref: src/operator/nn/convolution.cc. Supports 1D/2D/3D via the same
    general conv; grouped conv maps to feature_group_count."""
    nd = data.ndim - 2
    stride = _tup(stride, nd) if stride is not None else (1,) * nd
    dilate = _tup(dilate, nd) if dilate is not None else (1,) * nd
    pad = _tup(pad, nd)
    dn = _CONV_DN[nd]
    out = lax.conv_general_dilated(
        data, weight, window_strides=stride,
        padding=[(p, p) for p in pad],
        rhs_dilation=dilate,
        dimension_numbers=dn,
        feature_group_count=num_group,
        preferred_element_type=jnp.float32).astype(data.dtype)
    if bias is not None and not no_bias:
        out = out + bias.reshape((1, -1) + (1,) * nd)
    return out


@_reg
def deconvolution(data, weight, bias=None, kernel=None, stride=None, dilate=None,
                  pad=None, adj=None, num_filter=0, num_group=1, no_bias=False,
                  target_shape=None, layout='NCHW'):
    """Transposed convolution (ref: src/operator/nn/deconvolution.cc)."""
    nd = data.ndim - 2
    stride = _tup(stride, nd) if stride is not None else (1,) * nd
    dilate = _tup(dilate, nd) if dilate is not None else (1,) * nd
    pad = _tup(pad, nd)
    adj = _tup(adj, nd) if adj is not None else (0,) * nd
    kshape = weight.shape[2:]
    # conv_transpose of the forward conv: use input dilation.
    padding = []
    for i in range(nd):
        k = (kshape[i] - 1) * dilate[i] + 1
        lo = k - 1 - pad[i]
        hi = k - 1 - pad[i] + adj[i]
        padding.append((lo, hi))
    dn = {1: ('NCH', 'IOH', 'NCH'), 2: ('NCHW', 'IOHW', 'NCHW'),
          3: ('NCDHW', 'IODHW', 'NCDHW')}[nd]
    if num_group > 1:
        # weight is (in_ch, out_ch/g, *k); split groups along in channel.
        ins = jnp.split(data, num_group, axis=1)
        ws = jnp.split(weight, num_group, axis=0)
        outs = [lax.conv_general_dilated(
            x, jnp.flip(w, axis=tuple(range(2, 2 + nd))),
            window_strides=(1,) * nd, padding=padding,
            lhs_dilation=stride, rhs_dilation=dilate, dimension_numbers=dn)
            for x, w in zip(ins, ws)]
        out = jnp.concatenate(outs, axis=1)
    else:
        out = lax.conv_general_dilated(
            data, jnp.flip(weight, axis=tuple(range(2, 2 + nd))),
            window_strides=(1,) * nd, padding=padding,
            lhs_dilation=stride, rhs_dilation=dilate, dimension_numbers=dn)
    out = out.astype(data.dtype)
    if bias is not None and not no_bias:
        out = out + bias.reshape((1, -1) + (1,) * nd)
    return out


@_reg
def pooling(data, kernel=None, pool_type='max', global_pool=False, stride=None,
            pad=None, pooling_convention='valid', count_include_pad=True,
            layout='NCHW'):
    """Ref: src/operator/nn/pooling.cc."""
    nd = data.ndim - 2
    if global_pool:
        axes = tuple(range(2, 2 + nd))
        if pool_type == 'max':
            return jnp.max(data, axis=axes, keepdims=True)
        return jnp.mean(data, axis=axes, keepdims=True)
    kernel = _tup(kernel, nd)
    stride = _tup(stride, nd) if stride is not None else (1,) * nd
    pad = _tup(pad, nd)
    window = (1, 1) + kernel
    strides = (1, 1) + stride
    spatial_pad = [(p, p) for p in pad]
    if pooling_convention == 'full':
        # ceil-mode: add extra right padding so ceil division is covered
        extra = []
        for i in range(nd):
            size = data.shape[2 + i]
            out_sz = -(-(size + 2 * pad[i] - kernel[i]) // stride[i]) + 1
            need = (out_sz - 1) * stride[i] + kernel[i] - (size + 2 * pad[i])
            extra.append(builtins_max(0, need))
        spatial_pad = [(p, p + e) for p, e in zip(pad, extra)]
    padding = [(0, 0), (0, 0)] + spatial_pad
    if pool_type == 'max':
        init = -jnp.inf if jnp.issubdtype(data.dtype, jnp.floating) else jnp.iinfo(data.dtype).min
        return lax.reduce_window(data, init, lax.max, window, strides, padding)
    if pool_type in ('avg', 'sum'):
        summed = lax.reduce_window(data, 0.0, lax.add, window, strides, padding)
        if pool_type == 'sum':
            return summed
        if count_include_pad:
            denom = 1.0
            for k in kernel:
                denom *= k
            return summed / denom
        ones = jnp.ones_like(data)
        counts = lax.reduce_window(ones, 0.0, lax.add, window, strides, padding)
        return summed / counts
    if pool_type == 'lp':
        p = 2.0
        summed = lax.reduce_window(jnp.abs(data) ** p, 0.0, lax.add, window,
                                   strides, padding)
        return summed ** (1.0 / p)
    raise MXNetError(f"unknown pool_type {pool_type}")


builtins_max = max


@_reg
def activation(data, act_type='relu'):
    """Ref: src/operator/nn/activation.cc."""
    acts = {
        'relu': lambda x: jnp.maximum(x, 0),
        'sigmoid': jax.nn.sigmoid,
        'tanh': jnp.tanh,
        'softrelu': jax.nn.softplus,
        'softsign': lambda x: x / (1 + jnp.abs(x)),
        'gelu': lambda x: jax.nn.gelu(x, approximate=False),
        'gelu_tanh': lambda x: jax.nn.gelu(x, approximate=True),
        'silu': jax.nn.silu,
    }
    if act_type not in acts:
        raise MXNetError(f"unknown act_type {act_type}")
    return acts[act_type](data)


@_reg
def leaky_relu(data, gamma=None, act_type='leaky', slope=0.25,
               lower_bound=0.125, upper_bound=0.334):
    """Ref: src/operator/leaky_relu.cc (leaky/prelu/elu/selu/rrelu/gelu)."""
    if act_type == 'leaky':
        return jnp.where(data >= 0, data, slope * data)
    if act_type == 'prelu':
        g = gamma
        if g.ndim < data.ndim and g.ndim == 1:
            g = g.reshape((1, -1) + (1,) * (data.ndim - 2))
        return jnp.where(data >= 0, data, g * data)
    if act_type == 'elu':
        return jnp.where(data >= 0, data, slope * jnp.expm1(data))
    if act_type == 'selu':
        alpha, scale = 1.6732632423543772, 1.0507009873554805
        return scale * jnp.where(data >= 0, data, alpha * jnp.expm1(data))
    if act_type == 'gelu':
        return jax.nn.gelu(data, approximate=False)
    if act_type == 'rrelu':
        if state.is_training:
            key = _random.next_key()
            s = jax.random.uniform(key, data.shape, dtype=data.dtype,
                                   minval=lower_bound, maxval=upper_bound)
        else:
            s = (lower_bound + upper_bound) / 2.0
        return jnp.where(data >= 0, data, s * data)
    raise MXNetError(f"unknown act_type {act_type}")


@_reg
def softmax(data, axis=-1, temperature=None, length=None):
    """Ref: src/operator/nn/softmax.cc; optional valid-length masking."""
    if temperature is not None and temperature != 1.0:
        data = data / temperature
    if length is not None:
        pos = jnp.arange(data.shape[axis])
        shape = [1] * data.ndim
        shape[axis] = data.shape[axis]
        mask = pos.reshape(shape) < jnp.expand_dims(length, axis=tuple(
            range(length.ndim, data.ndim)))
        data = jnp.where(mask, data, -jnp.inf)
        out = jax.nn.softmax(data, axis=axis)
        return jnp.where(mask, out, 0.0)
    return jax.nn.softmax(data, axis=axis)


@_reg
def log_softmax(data, axis=-1, temperature=None):
    if temperature is not None and temperature != 1.0:
        data = data / temperature
    return jax.nn.log_softmax(data, axis=axis)


@_reg
def softmin(data, axis=-1):
    return jax.nn.softmax(-data, axis=axis)


@_reg
def softmax_cross_entropy(data, label):
    """Ref: src/operator/softmax_output.cc semantics (sum CE over batch)."""
    logp = jax.nn.log_softmax(data, axis=-1)
    onehot = jax.nn.one_hot(label.astype(jnp.int32), data.shape[-1], dtype=data.dtype)
    return -jnp.sum(onehot * logp)


@_reg
def batch_norm(data, gamma, beta, moving_mean, moving_var, eps=1e-3,
               momentum=0.9, fix_gamma=True, use_global_stats=False,
               output_mean_var=False, axis=1):
    """Ref: src/operator/nn/batch_norm.cc.

    Returns (out, new_moving_mean, new_moving_var); the Gluon layer writes the
    new stats back into its parameters. In training mode batch stats are used;
    in inference (or use_global_stats) the moving stats are used.
    """
    if fix_gamma:
        gamma = jnp.ones_like(gamma)
    reduce_axes = tuple(i for i in range(data.ndim) if i != axis % data.ndim)
    bshape = [1] * data.ndim
    bshape[axis % data.ndim] = data.shape[axis % data.ndim]
    training = state.is_training and not use_global_stats
    if training:
        mean = jnp.mean(data, axis=reduce_axes)
        var = jnp.var(data, axis=reduce_axes)
        new_mean = momentum * moving_mean + (1 - momentum) * mean
        new_var = momentum * moving_var + (1 - momentum) * var
    else:
        mean, var = moving_mean, moving_var
        new_mean, new_var = moving_mean, moving_var
    inv = lax.rsqrt(var.astype(jnp.float32) + eps).astype(data.dtype)
    out = (data - mean.reshape(bshape)) * (inv * gamma).reshape(bshape) \
        + beta.reshape(bshape)
    return out, new_mean, new_var


@_reg
def layer_norm(data, gamma, beta, axis=-1, eps=1e-5):
    """Ref: src/operator/nn/layer_norm.cc. Normalises over `axis` only."""
    f32 = data.astype(jnp.float32)
    mean = jnp.mean(f32, axis=axis, keepdims=True)
    var = jnp.var(f32, axis=axis, keepdims=True)
    out = (f32 - mean) * lax.rsqrt(var + eps)
    out = out.astype(data.dtype)
    shape = [1] * data.ndim
    shape[axis % data.ndim] = data.shape[axis % data.ndim]
    return out * gamma.reshape(shape) + beta.reshape(shape)


def add_layer_norm(x, res, gamma, beta, eps=1e-5):
    """LN(x + res) — the transformer residual epilogue, twice per BERT
    layer. Routes to the fused Pallas kernel (ops/pallas_layernorm.py)
    when MXTPU_PALLAS_LN=1 and a TPU is present; default is the XLA
    path (flag-gated until measured on-chip, like the attention knobs)."""
    from .. import config as _config
    if _config.get('MXTPU_PALLAS_LN'):
        from .pallas_layernorm import fused_add_layer_norm, \
            pallas_available
        if pallas_available() and x.shape[-1] % 128 == 0:
            return fused_add_layer_norm(x, res, gamma, beta, eps)
    return layer_norm(x + res, gamma, beta, eps=eps)


def dense_gelu(x, weight, bias):
    """FFN1 GELU+bias epilogue: gelu(x @ W.T + b) through one seam so
    the fused Pallas matmul kernel (ops/pallas_ffn.py) can take it when
    MXTPU_PALLAS_FFN=1 and a TPU is present; default is the XLA path —
    identical math to Dense + activation('gelu') (flag-gated until
    measured on-chip, like MXTPU_PALLAS_LN and the attention knobs)."""
    from .. import config as _config
    if _config.get('MXTPU_PALLAS_FFN'):
        from .pallas_ffn import fused_dense_gelu, pallas_available
        if pallas_available() and x.shape[-1] % 128 == 0 \
                and weight.shape[0] % 128 == 0:
            return fused_dense_gelu(x, weight, bias)
    return activation(fully_connected(x, weight, bias,
                                      num_hidden=weight.shape[0],
                                      flatten=False), act_type='gelu')


@_reg
def group_norm(data, gamma, beta, num_groups=1, eps=1e-5):
    """Ref: src/operator/nn/group_norm.cc; input NC+spatial."""
    n, c = data.shape[:2]
    x = data.reshape((n, num_groups, c // num_groups) + data.shape[2:])
    axes = tuple(range(2, x.ndim))
    mean = jnp.mean(x, axis=axes, keepdims=True)
    var = jnp.var(x, axis=axes, keepdims=True)
    x = (x - mean) * lax.rsqrt(var + eps)
    x = x.reshape(data.shape)
    shape = (1, c) + (1,) * (data.ndim - 2)
    return x * gamma.reshape(shape) + beta.reshape(shape)


@_reg
def instance_norm(data, gamma, beta, eps=1e-3):
    """Ref: src/operator/instance_norm.cc."""
    axes = tuple(range(2, data.ndim))
    mean = jnp.mean(data, axis=axes, keepdims=True)
    var = jnp.var(data, axis=axes, keepdims=True)
    out = (data - mean) * lax.rsqrt(var + eps)
    shape = (1, data.shape[1]) + (1,) * (data.ndim - 2)
    return out * gamma.reshape(shape) + beta.reshape(shape)


@_reg
def l2_normalization(data, eps=1e-10, mode='instance'):
    """Ref: src/operator/l2_normalization.cc."""
    if mode == 'instance':
        axes = tuple(range(1, data.ndim))
    elif mode == 'channel':
        axes = (1,)
    else:  # spatial
        axes = tuple(range(2, data.ndim))
    norm = jnp.sqrt(jnp.sum(jnp.square(data), axis=axes, keepdims=True) + eps)
    return data / norm


@_reg
def lrn(data, alpha=1e-4, beta=0.75, knorm=2.0, nsize=5):
    """Local response norm across channels (ref: src/operator/nn/lrn.cc)."""
    sq = jnp.square(data)
    half = nsize // 2
    padded = jnp.pad(sq, ((0, 0), (half, half)) + ((0, 0),) * (data.ndim - 2))
    acc = jnp.zeros_like(data)
    for i in range(nsize):
        acc = acc + lax.dynamic_slice_in_dim(padded, i, data.shape[1], axis=1)
    return data / jnp.power(knorm + alpha / nsize * acc, beta)


@_reg
def dropout(data, p=0.5, mode='training', axes=(), cudnn_off=False):
    """Ref: src/operator/nn/dropout.cc. Active only in autograd train mode."""
    active = state.is_training or mode == 'always'
    if not active or p <= 0.0:
        return data
    keep = 1.0 - p
    shape = list(data.shape)
    for a in axes:
        shape[a] = 1
    key = _random.next_key()
    mask = jax.random.bernoulli(key, keep, tuple(shape)).astype(data.dtype)
    return data * mask / keep


@_reg
def embedding(data, weight, input_dim=0, output_dim=0, dtype='float32',
              sparse_grad=False):
    """Ref: src/operator/tensor/indexing_op.cc Embedding; a gather that XLA
    turns into a dynamic-slice — rows stay in HBM, no host round-trip.

    Backward dedups repeated ids via segment-sum before the table-shaped
    scatter (ref EmbeddingOpBackwardEx / AddTakeGradRspKernel) instead of
    scatter-adding one row slice per occurrence. When parallel/step.py has
    armed a RowSparse capture for this table (matched by trace identity),
    the lookup also records live row ids so the optimizer can update only
    the gathered rows."""
    idx = data.astype(jnp.int32)
    slot = _rowsparse.lookup_capture(weight)
    if slot is not None:
        return slot.lookup(idx)
    if weight.ndim == 2 and idx.size > 0:
        return _rowsparse.dedup_take(weight, idx)
    return jnp.take(weight, idx, axis=0, mode='clip')


@_reg
def one_hot(indices, depth=0, on_value=1.0, off_value=0.0, dtype='float32'):
    oh = jax.nn.one_hot(indices.astype(jnp.int32), depth, dtype=jnp.dtype(dtype))
    return oh * (on_value - off_value) + off_value


@_reg
def upsampling(data, scale=1, sample_type='nearest', num_filter=0):
    """Ref: src/operator/nn/upsampling.cc (nearest)."""
    n, c, h, w = data.shape
    x = data.reshape(n, c, h, 1, w, 1)
    x = jnp.broadcast_to(x, (n, c, h, scale, w, scale))
    return x.reshape(n, c, h * scale, w * scale)


@_reg
def softmax_output(data, label, grad_scale=1.0, ignore_label=-1,
                   use_ignore=False, multi_output=False, preserve_shape=False,
                   normalization='null', out_grad=False, smooth_alpha=0.0):
    """Legacy SoftmaxOutput forward = softmax (ref: src/operator/softmax_output.cc)."""
    return jax.nn.softmax(data, axis=-1)


@_reg
def make_loss(data, grad_scale=1.0, valid_thresh=0.0, normalization='null'):
    return data


@_reg
def blockgrad(data):
    return lax.stop_gradient(data)


@_reg
def identity(data):
    return data


@_reg
def ctc_loss(data, label, data_lengths=None, label_lengths=None,
             use_data_lengths=False, use_label_lengths=False, blank_label='first'):
    """CTC loss (ref: src/operator/nn/ctc_loss.cc). data: (T, N, C) alphabet
    logits (pre-softmax), label: (N, L) padded with -1 (or 0 for blank_label='last').

    Implemented with the standard log-alpha recursion over lax.scan — a
    compiler-friendly sequential loop on TPU.
    """
    T, N, C = data.shape
    L = label.shape[1]
    blank = 0 if blank_label == 'first' else C - 1
    lab = label.astype(jnp.int32)
    if blank_label == 'first':
        pad_val = 0
        lab_valid = lab >= 0
    else:
        pad_val = C - 1
        lab_valid = lab > 0
    if use_label_lengths and label_lengths is not None:
        lab_len = label_lengths.astype(jnp.int32)
    else:
        lab_len = jnp.sum(lab_valid.astype(jnp.int32), axis=1)
    lab = jnp.where(lab_valid, lab, pad_val)
    logp = jax.nn.log_softmax(data, axis=-1)  # (T, N, C)
    # extended label sequence: blank, l1, blank, l2, ... blank → length 2L+1
    S = 2 * L + 1
    ext = jnp.full((N, S), blank, dtype=jnp.int32)
    ext = ext.at[:, 1::2].set(lab)
    ext_len = 2 * lab_len + 1
    NEG = -1e30
    # init alpha
    alpha0 = jnp.full((N, S), NEG)
    alpha0 = alpha0.at[:, 0].set(logp[0, :, blank])
    alpha0 = alpha0.at[:, 1].set(jnp.take_along_axis(
        logp[0], ext[:, 1:2], axis=1)[:, 0])

    same_as_prev2 = jnp.concatenate(
        [jnp.ones((N, 2), bool), ext[:, 2:] == ext[:, :-2]], axis=1)

    def step(alpha, logp_t):
        a_prev = alpha
        a_shift1 = jnp.concatenate([jnp.full((N, 1), NEG), alpha[:, :-1]], axis=1)
        a_shift2 = jnp.concatenate([jnp.full((N, 2), NEG), alpha[:, :-2]], axis=1)
        a_shift2 = jnp.where(same_as_prev2, NEG, a_shift2)
        m = jnp.maximum(jnp.maximum(a_prev, a_shift1), a_shift2)
        m_safe = jnp.maximum(m, NEG)
        summed = (jnp.exp(a_prev - m_safe) + jnp.exp(a_shift1 - m_safe)
                  + jnp.exp(a_shift2 - m_safe))
        new = m_safe + jnp.log(summed)
        emit = jnp.take_along_axis(logp_t, ext, axis=1)
        new = new + emit
        return new, None

    if use_data_lengths and data_lengths is not None:
        dlen = data_lengths.astype(jnp.int32)

        def step_masked(carry, inp):
            alpha, t = carry
            logp_t = inp
            new, _ = step(alpha, logp_t)
            new = jnp.where((t < dlen)[:, None], new, alpha)
            return (new, t + 1), None

        (alphaT, _), _ = lax.scan(step_masked, (alpha0, jnp.ones((), jnp.int32)),
                                  logp[1:])
    else:
        alphaT, _ = lax.scan(step, alpha0, logp[1:])
    # loss = -log(alpha[ext_len-1] + alpha[ext_len-2])
    idx1 = (ext_len - 1)[:, None]
    idx2 = jnp.maximum(ext_len - 2, 0)[:, None]
    a1 = jnp.take_along_axis(alphaT, idx1, axis=1)[:, 0]
    a2 = jnp.take_along_axis(alphaT, idx2, axis=1)[:, 0]
    m = jnp.maximum(a1, a2)
    total = m + jnp.log(jnp.exp(a1 - m) + jnp.exp(a2 - m))
    return -total


@_reg
def sync_batch_norm_op(data, gamma, beta, moving_mean, moving_var,
                       axis_name=None, eps=1e-3, momentum=0.9,
                       fix_gamma=False, use_global_stats=False, axis=1):
    """Cross-device BatchNorm (ref: src/operator/contrib/sync_batch_norm.cc).

    Inside shard_map over a mesh data axis, batch statistics are psum-reduced
    over `axis_name` so every shard normalises with global-batch moments.
    """
    if fix_gamma:
        gamma = jnp.ones_like(gamma)
    reduce_axes = tuple(i for i in range(data.ndim) if i != axis % data.ndim)
    bshape = [1] * data.ndim
    bshape[axis % data.ndim] = data.shape[axis % data.ndim]
    training = state.is_training and not use_global_stats
    if training:
        n_local = 1.0
        for i in reduce_axes:
            n_local *= data.shape[i]
        s = jnp.sum(data, axis=reduce_axes)
        sq = jnp.sum(jnp.square(data), axis=reduce_axes)
        if axis_name is not None:
            s = jax.lax.psum(s, axis_name)
            sq = jax.lax.psum(sq, axis_name)
            n = n_local * jax.lax.psum(1.0, axis_name)
        else:
            n = n_local
        mean = s / n
        var = sq / n - jnp.square(mean)
        new_mean = momentum * moving_mean + (1 - momentum) * mean
        new_var = momentum * moving_var + (1 - momentum) * var
    else:
        mean, var = moving_mean, moving_var
        new_mean, new_var = moving_mean, moving_var
    inv = lax.rsqrt(var.astype(jnp.float32) + eps).astype(data.dtype)
    out = (data - mean.reshape(bshape)) * (inv * gamma).reshape(bshape) \
        + beta.reshape(bshape)
    return out, new_mean, new_var


@_reg
def rnn(data, params, state, state_cell=None, state_size=0, num_layers=1,
        mode='lstm', bidirectional=False, p=0.0, projection_size=None,
        lstm_state_clip_min=None, lstm_state_clip_max=None,
        use_sequence_length=False, sequence_length=None):
    """Fused multi-layer RNN (ref: src/operator/rnn.cc:299 NNVM_REGISTER_OP(RNN)).

    data: (T, N, I). params: flat vector packing per-layer/direction i2h/h2h
    weights then biases, in the reference's canonical order. state: (L*D, N, H)
    hidden; state_cell: (L*D, N, H) cell (lstm only).

    TPU-native: each layer is one `lax.scan` whose step does two MXU matmuls;
    time-major layout keeps the scan carry small and XLA pipelines the layers.
    """
    T, N, I = data.shape
    H = state_size
    L = num_layers
    D = 2 if bidirectional else 1
    ngates = {'rnn_relu': 1, 'rnn_tanh': 1, 'lstm': 4, 'gru': 3}[mode]

    # unpack parameter vector in the reference layout: all weights
    # (layer-major, direction-minor: i2h then h2h), then all biases.
    offset = 0
    weights = []
    for layer in range(L):
        layer_ws = []
        for d in range(D):
            in_size = I if layer == 0 else H * D
            w_i2h = jax.lax.dynamic_slice(params, (offset,), (ngates * H * in_size,)) \
                .reshape(ngates * H, in_size)
            offset += ngates * H * in_size
            w_h2h = jax.lax.dynamic_slice(params, (offset,), (ngates * H * H,)) \
                .reshape(ngates * H, H)
            offset += ngates * H * H
            layer_ws.append((w_i2h, w_h2h))
        weights.append(layer_ws)
    biases = []
    for layer in range(L):
        layer_bs = []
        for d in range(D):
            b_i2h = jax.lax.dynamic_slice(params, (offset,), (ngates * H,))
            offset += ngates * H
            b_h2h = jax.lax.dynamic_slice(params, (offset,), (ngates * H,))
            offset += ngates * H
            layer_bs.append((b_i2h, b_h2h))
        biases.append(layer_bs)

    def cell_step(mode, x_proj, h, c, w_h2h, b_h2h):
        gates = x_proj + jnp.dot(h, w_h2h.T) + b_h2h
        if mode == 'lstm':
            i, f, g, o = jnp.split(gates, 4, axis=-1)
            i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
            g = jnp.tanh(g)
            new_c = f * c + i * g
            if lstm_state_clip_min is not None:
                new_c = jnp.clip(new_c, lstm_state_clip_min, lstm_state_clip_max)
            new_h = o * jnp.tanh(new_c)
            return new_h, new_c
        if mode == 'gru':
            # MXNet gru gate order: r, z, n
            r, z, n = jnp.split(gates, 3, axis=-1)
            # n-gate needs r applied to the h2h part only: recompute
            xr, xz, xn = jnp.split(x_proj + b_h2h * 0, 3, axis=-1)
            hr, hz, hn = jnp.split(jnp.dot(h, w_h2h.T) + b_h2h, 3, axis=-1)
            r = jax.nn.sigmoid(xr + hr)
            z = jax.nn.sigmoid(xz + hz)
            n = jnp.tanh(xn + r * hn)
            new_h = (1 - z) * n + z * h
            return new_h, c
        act = jnp.tanh if mode == 'rnn_tanh' else lambda v: jnp.maximum(v, 0)
        new_h = act(gates)
        return new_h, c

    def run_layer(x, h0, c0, w_i2h, w_h2h, b_i2h, b_h2h, reverse=False):
        # x: (T, N, in); project all timesteps at once: one big MXU matmul
        x_proj = jnp.einsum('tni,gi->tng', x, w_i2h) + b_i2h

        def step(carry, xp):
            h, c = carry
            new_h, new_c = cell_step(mode, xp, h, c, w_h2h, b_h2h)
            return (new_h, new_c), new_h

        (hT, cT), ys = lax.scan(step, (h0, c0), x_proj, reverse=reverse)
        if reverse:
            pass  # lax.scan(reverse=True) already emits outputs in orig order
        return ys, hT, cT

    x = data
    h_states = []
    c_states = []
    for layer in range(L):
        outs = []
        for d in range(D):
            idx = layer * D + d
            h0 = state[idx]
            c0 = state_cell[idx] if state_cell is not None else jnp.zeros_like(h0)
            w_i2h, w_h2h = weights[layer][d]
            b_i2h, b_h2h = biases[layer][d]
            ys, hT, cT = run_layer(x, h0, c0, w_i2h, w_h2h, b_i2h, b_h2h,
                                   reverse=(d == 1))
            outs.append(ys)
            h_states.append(hT)
            c_states.append(cT)
        x = outs[0] if D == 1 else jnp.concatenate(outs, axis=-1)
        from ..base import state as _flags
        if p > 0 and layer < L - 1 and _flags.is_training:
            key = _random.next_key()
            keep = 1.0 - p
            mask = jax.random.bernoulli(key, keep, x.shape).astype(x.dtype)
            x = x * mask / keep
    out_h = jnp.stack(h_states, axis=0)
    if mode == 'lstm':
        out_c = jnp.stack(c_states, axis=0)
        return x, out_h, out_c
    return x, out_h
