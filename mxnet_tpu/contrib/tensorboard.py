"""Training-metric logging callback (ref: python/mxnet/contrib/tensorboard.py).

The reference forwards eval metrics to a TensorBoard SummaryWriter. Neither
tensorboard nor tensorboardX is baked into this image, so the callback
accepts any writer object with `add_scalar(tag, value, step)`; without one
it falls back to a JSONL file writer whose output is trivially convertible
(one `{"tag":…,"value":…,"step":…}` object per line).
"""
from __future__ import annotations

import json
import os
import time

__all__ = ['LogMetricsCallback', 'JSONLWriter']


class JSONLWriter:
    """Minimal SummaryWriter-compatible scalar logger."""

    def __init__(self, logdir):
        os.makedirs(logdir, exist_ok=True)
        self._f = open(os.path.join(logdir, 'scalars.jsonl'), 'a')

    def add_scalar(self, tag, value, step=0):
        self._f.write(json.dumps({'tag': tag, 'value': float(value),
                                  'step': int(step),
                                  'wall_time': time.time()}) + '\n')
        self._f.flush()

    def close(self):
        self._f.close()


class LogMetricsCallback:
    """Batch-end callback pushing metrics to a writer
    (ref: tensorboard.py LogMetricsCallback)."""

    def __init__(self, logging_dir=None, prefix=None, summary_writer=None):
        self.prefix = prefix
        self.step = 0
        if summary_writer is not None:
            self.summary_writer = summary_writer
        else:
            if logging_dir is None:
                raise ValueError(
                    "LogMetricsCallback needs logging_dir or summary_writer")
            try:
                from tensorboardX import SummaryWriter  # optional
                self.summary_writer = SummaryWriter(logging_dir)
            except ImportError:
                self.summary_writer = JSONLWriter(logging_dir)

    def __call__(self, param):
        self.step += 1
        if param.eval_metric is None:
            return
        for name, value in param.eval_metric.get_name_value():
            if self.prefix is not None:
                name = f"{self.prefix}-{name}"
            self.summary_writer.add_scalar(name, value, self.step)
