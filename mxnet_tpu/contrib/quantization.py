"""INT8 model quantization: calibration + network conversion.

Ref: python/mxnet/contrib/quantization.py (quantize_model / quantize_net),
src/operator/quantization/calibrate.cc (entropy calibration).

TPU-first shape of the subsystem: the reference rewrites the symbolic graph
with a quantize pass (src/operator/quantization/quantize_graph_pass.cc) and
runs MKLDNN/cuDNN int8 kernels.  Here conversion walks the Gluon block tree
and swaps Dense / Conv2D for Quantized* blocks whose forward is built from
the int8 ops in ops/quantization.py — int8 x int8 matmuls hit the MXU with
int32 accumulation, and XLA fuses the surrounding quantize / dequantize
arithmetic into the same program.  Calibration modes match the reference:
'naive' (min/max), 'entropy' (KL-optimal threshold), 'none' (dynamic ranges
computed in-graph at inference time).
"""
from __future__ import annotations

import copy
import logging

import numpy as onp

from ..gluon.block import Block, HybridBlock
from ..gluon import nn as _nn
from ..ndarray.ndarray import NDArray
from ..ndarray import array as _array

__all__ = ['quantize_net', 'quantize_model', 'QuantizedDense',
           'QuantizedConv2D', '_get_optimal_threshold']


# ---------------------------------------------------------------------------
# Entropy (KL-divergence) calibration — ref: calibrate.cc GetOptimalThreshold
# ---------------------------------------------------------------------------

def _smooth_distribution(p, eps=0.0001):
    is_zeros = (p == 0).astype(onp.float32)
    is_nonzeros = (p != 0).astype(onp.float32)
    n_zeros = is_zeros.sum()
    n_nonzeros = p.size - n_zeros
    if not n_nonzeros:
        return None
    eps1 = eps * float(n_zeros) / float(n_nonzeros)
    if eps1 >= 1.0:
        return None
    hist = p.astype(onp.float32)
    return hist + eps * is_zeros - eps1 * hist * is_nonzeros


def _kl_divergence(p, q):
    mask = p > 0
    if not mask.any():
        return onp.inf
    pm = p[mask] / p.sum()
    qm = onp.maximum(q[mask] / max(q.sum(), 1e-30), 1e-30)
    return float((pm * onp.log(pm / qm)).sum())


def _get_optimal_threshold(arr, num_bins=8001, num_quantized_bins=255):
    """KL-optimal symmetric threshold for int8 quantization of ``arr``.

    Returns (min_val, max_val, min_divergence_threshold, divergence) like the
    reference's GetOptimalThresholds output tuple.
    """
    arr = onp.asarray(arr).ravel().astype(onp.float32)
    min_val = float(arr.min())
    max_val = float(arr.max())
    th = max(abs(min_val), abs(max_val))
    if th == 0.0:
        return min_val, max_val, 1e-30, 0.0
    hist, edges = onp.histogram(arr, bins=num_bins, range=(-th, th))
    zero_bin = num_bins // 2
    half_q = num_quantized_bins // 2

    best_div = onp.inf
    best_th = th
    for i in range(half_q, zero_bin + 1):
        start, stop = zero_bin - i, zero_bin + i + 1
        sliced = hist[start:stop].astype(onp.float64)
        p = sliced.copy()
        p[0] += hist[:start].sum()
        p[-1] += hist[stop:].sum()
        threshold = float(edges[stop])

        # quantize the sliced distribution into num_quantized_bins
        nbins = sliced.size
        m = nbins // num_quantized_bins
        trimmed = sliced[:m * num_quantized_bins]
        q_merged = trimmed.reshape(num_quantized_bins, m).sum(axis=1)
        q_merged[-1] += sliced[m * num_quantized_bins:].sum()
        # expand back, distributing each merged bin over its nonzero members
        nz = (trimmed != 0).reshape(num_quantized_bins, m)
        counts = onp.maximum(nz.sum(axis=1), 1)
        expanded = onp.where(nz, (q_merged / counts)[:, None], 0.0).ravel()
        q = onp.zeros(nbins)
        q[:m * num_quantized_bins] = expanded

        sp = _smooth_distribution(p)
        sq = _smooth_distribution(q)
        if sp is None or sq is None:
            continue
        div = _kl_divergence(sp, sq)
        if div < best_div:
            best_div = div
            best_th = threshold
    return min_val, max_val, best_th, float(best_div)


# ---------------------------------------------------------------------------
# Quantized layers
# ---------------------------------------------------------------------------

def _quantize_weight(w, channel_wise=False):
    """Symmetric int8 weight quantization (ref: the quantize pass marks
    weights 'quantize offline' with min/max from the array). channel_wise
    uses one scale per output channel (axis 0) — the reference's
    'channel-wise' quantize_granularity — which typically recovers accuracy
    on convs with uneven filter magnitudes."""
    w = onp.asarray(w)
    if channel_wise:
        amax = onp.abs(w).reshape(w.shape[0], -1).max(axis=1)
        amax = onp.maximum(amax, 1e-30).astype('float32')
        scale = 127.0 / amax
        q = onp.round(w * scale.reshape((-1,) + (1,) * (w.ndim - 1)))
    else:
        amax = onp.float32(float(onp.abs(w).max()) or 1e-30)
        q = onp.round(w * (127.0 / amax))
    return onp.clip(q, -127, 127).astype(onp.int8), -amax, amax


class _QuantizedBase(HybridBlock):
    """Shared plumbing: int8 weight, its range, bias and the calibrated
    activation range are all registered as Constant parameters so
    save_parameters / load_parameters round-trip quantized nets."""

    def __init__(self, weight, bias, act_type, min_calib, max_calib,
                 channel_wise=False, **kw):
        super().__init__(**kw)
        qw, wlo, whi = _quantize_weight(weight, channel_wise)
        with self.name_scope():
            self.weight = self.params.get_constant('weight', qw)
            self.wrange = self.params.get_constant(
                'wrange', onp.array([wlo, whi], 'float32'))
            if bias is not None:
                self.bias = self.params.get_constant(
                    'bias', onp.asarray(bias, 'float32'))
            else:
                self.bias = None
            if min_calib is not None:
                self.calib = self.params.get_constant(
                    'calib', onp.array([min_calib, max_calib], 'float32'))
            else:
                self.calib = None   # dynamic range, computed in-graph
        self._act_type = act_type
        self.collect_params().initialize()

    @staticmethod
    def _quantize_input(F, x, calib):
        if calib is None:
            return F.quantize_v2(x, out_type='int8')
        return F.quantize_v2(x, out_type='int8', min_calib_range=calib[0],
                             max_calib_range=calib[1])


class QuantizedDense(_QuantizedBase):
    """int8 inference replacement for gluon.nn.Dense
    (ref: quantized_fully_connected.cc path of the quantize pass)."""

    def __init__(self, dense, min_calib=None, max_calib=None,
                 channel_wise=False, **kw):
        w = dense.weight.data().asnumpy()
        b = dense.bias.data().asnumpy() if dense.bias is not None else None
        super().__init__(w, b, dense._act_type, min_calib, max_calib,
                         channel_wise, **kw)
        self._units = dense._units
        self._flatten = dense._flatten

    def hybrid_forward(self, F, x, weight, wrange, bias=None, calib=None):
        q, lo, hi = self._quantize_input(F, x, calib)
        out32, olo, ohi = F.quantized_fully_connected(
            q, weight, None, lo, hi, wrange[0], wrange[1],
            num_hidden=self._units, no_bias=True, flatten=self._flatten)
        out = F.dequantize(out32, olo, ohi)
        if bias is not None:
            out = out + bias
        if self._act_type is not None:
            out = F.activation(out, act_type=self._act_type)
        return out

    def __repr__(self):
        return (f"QuantizedDense(-> {self._units}, int8, "
                f"calib={self.calib is not None})")


class QuantizedConv2D(_QuantizedBase):
    """int8 inference replacement for gluon.nn.Conv2D
    (ref: quantized_conv.cc path of the quantize pass)."""

    def __init__(self, conv, min_calib=None, max_calib=None,
                 channel_wise=False, **kw):
        w = conv.weight.data().asnumpy()
        b = conv.bias.data().asnumpy() if conv.bias is not None else None
        super().__init__(w, b, conv._act_type, min_calib, max_calib,
                         channel_wise, **kw)
        self._kwargs = dict(conv._kwargs)

    def hybrid_forward(self, F, x, weight, wrange, bias=None, calib=None):
        q, lo, hi = self._quantize_input(F, x, calib)
        kw = self._kwargs
        out32, olo, ohi = F.quantized_conv(
            q, weight, None, lo, hi, wrange[0], wrange[1],
            kernel=kw['kernel'], stride=kw['stride'], dilate=kw['dilate'],
            pad=kw['pad'], num_filter=kw['num_filter'],
            num_group=kw['num_group'], no_bias=True)
        out = F.dequantize(out32, olo, ohi)
        if bias is not None:
            out = out + bias.reshape((1, -1, 1, 1))
        if self._act_type is not None:
            out = F.activation(out, act_type=self._act_type)
        return out

    def __repr__(self):
        return (f"QuantizedConv2D({self._kwargs['num_filter']}ch, int8, "
                f"calib={self.calib is not None})")


_QUANTIZABLE = {}


def _register_quantizable():
    _QUANTIZABLE[_nn.Dense] = QuantizedDense
    _QUANTIZABLE[_nn.Conv2D] = QuantizedConv2D


_register_quantizable()


# ---------------------------------------------------------------------------
# Block-tree walking, observation, conversion
# ---------------------------------------------------------------------------

class _Observer(Block):
    """Wraps a layer during calibration, keeping a running min/max and (for
    entropy mode) a bounded random subsample of inputs — never the full
    calibration set (the reference's collectors likewise keep only
    min/max or histograms, calibrate.cc)."""

    MAX_KEPT = 1 << 22   # per-layer cap on retained float32 samples (16 MiB)

    def __init__(self, inner, stat, keep_samples):
        super().__init__()
        self._inner = inner
        self._stat = stat
        self._keep = keep_samples
        self._rs = onp.random.RandomState(0)

    def forward(self, x, *args):
        a = x.asnumpy()
        st = self._stat
        st['min'] = min(st['min'], float(a.min()))
        st['max'] = max(st['max'], float(a.max()))
        if self._keep:
            budget = self.MAX_KEPT - st['nkept']
            if budget > 0:
                flat = a.ravel().astype(onp.float32)
                if flat.size > budget:
                    flat = flat[self._rs.choice(flat.size, budget,
                                                replace=False)]
                st['samples'].append(flat)
                st['nkept'] += flat.size
        return self._inner(x, *args)


def _walk(block, path=''):
    for name, child in list(block._children.items()):
        cpath = f"{path}.{name}" if path else name
        yield block, name, cpath, child
        yield from _walk(child, cpath)


def _set_child(parent, name, new):
    parent._children[name] = new
    if parent.__dict__.get(name) is not None:
        parent.__dict__[name] = new
    if isinstance(parent, HybridBlock):
        parent._cached_op = None


def _clear_caches(net):
    """Drop every compiled trace in the tree: a cached op anywhere above a
    replaced child still closes over the old float layers."""
    if isinstance(net, HybridBlock):
        net._cached_op = None
    for _, _, _, child in _walk(net):
        if isinstance(child, HybridBlock):
            child._cached_op = None


def _deactivate_hybrid(net):
    saved = []
    for _, _, _, child in _walk(net):
        if isinstance(child, HybridBlock):
            saved.append((child, child._active))
            child._active = False
    if isinstance(net, HybridBlock):
        saved.append((net, net._active))
        net._active = False
    return saved


def _iter_calib_batches(calib_data, num_calib_batches):
    if isinstance(calib_data, NDArray):
        yield calib_data
        return
    for i, item in enumerate(calib_data):
        if num_calib_batches is not None and i >= num_calib_batches:
            return
        if isinstance(item, (tuple, list)):
            item = item[0]
        if not isinstance(item, NDArray):
            item = _array(onp.asarray(item))
        yield item


def quantize_net(network, quantized_dtype='int8', exclude_layers=None,
                 calib_data=None, calib_mode='naive', num_calib_batches=None,
                 quantize_granularity='tensor-wise', logger=None,
                 num_bins=8001):
    """Quantize a Gluon network to int8 (ref: contrib/quantization.py
    quantize_net_v2). Returns a new network with Dense/Conv2D replaced by
    int8 blocks; original is left untouched.

    calib_mode: 'naive' (min/max of observed inputs), 'entropy' (KL-optimal
    thresholds), 'none' (dynamic quantization — ranges computed in-graph).
    quantize_granularity: 'tensor-wise' (one weight scale per layer) or
    'channel-wise' (one per output channel).
    """
    log = logger or logging.getLogger(__name__)
    if quantized_dtype not in ('int8', 'auto'):
        raise ValueError(f"quantized_dtype {quantized_dtype!r}: TPU build "
                         "supports symmetric int8 ('int8'/'auto')")
    if quantize_granularity not in ('tensor-wise', 'channel-wise'):
        raise ValueError(
            f"quantize_granularity {quantize_granularity!r}: expected "
            "'tensor-wise' or 'channel-wise'")
    try:
        net = copy.deepcopy(network)
    except Exception:  # un-deepcopyable custom blocks: convert in place
        log.warning("quantize_net: deepcopy failed; converting in place")
        net = network

    exclude = set(exclude_layers or ())
    targets = [(parent, name, path, child)
               for parent, name, path, child in _walk(net)
               if type(child) in _QUANTIZABLE and path not in exclude]
    if not targets:
        return net

    ranges = {path: None for _, _, path, _ in targets}
    if calib_mode != 'none':
        if calib_mode not in ('naive', 'entropy'):
            raise ValueError(f"unknown calib_mode {calib_mode!r}")
        if calib_data is None:
            raise ValueError(f"calib_mode={calib_mode!r} requires calib_data")
        saved = _deactivate_hybrid(net)
        stats = {}
        for parent, name, path, child in targets:
            stats[path] = {'min': onp.inf, 'max': -onp.inf,
                           'samples': [], 'nkept': 0}
            _set_child(parent, name,
                       _Observer(child, stats[path],
                                 keep_samples=(calib_mode == 'entropy')))
        try:
            for batch in _iter_calib_batches(calib_data, num_calib_batches):
                net(batch)
        finally:
            for parent, name, path, child in targets:
                _set_child(parent, name, child)
            for blk, active in saved:
                blk._active = active
        for path, st in stats.items():
            if not onp.isfinite(st['min']):
                continue
            if calib_mode == 'naive':
                th = max(abs(st['min']), abs(st['max']))
            else:
                flat = onp.concatenate(st['samples'])
                _, _, th, div = _get_optimal_threshold(flat, num_bins=num_bins)
                log.debug("entropy calib %s: threshold=%g kl=%g",
                          path, th, div)
            ranges[path] = (-th, th)

    cw = quantize_granularity == 'channel-wise'
    for parent, name, path, child in targets:
        rng = ranges.get(path)
        lo, hi = rng if rng is not None else (None, None)
        qcls = _QUANTIZABLE[type(child)]
        _set_child(parent, name, qcls(child, min_calib=lo, max_calib=hi,
                                      channel_wise=cw))
    _clear_caches(net)
    return net


def quantize_model(network, **kwargs):
    """Alias kept for reference-API parity (ref: quantize_model works on
    Module/symbol; the TPU build's primary path is the Gluon one)."""
    return quantize_net(network, **kwargs)
