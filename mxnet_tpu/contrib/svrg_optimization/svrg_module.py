"""SVRG (Stochastic Variance-Reduced Gradient) training module
(ref: python/mxnet/contrib/svrg_optimization/svrg_module.py).

SVRG periodically snapshots the weights w̃ and the full-dataset gradient
ḡ(w̃); each minibatch update then uses the variance-reduced gradient
    g_svrg = g_B(w) − g_B(w̃) + ḡ(w̃)
(ref: _svrg_grads_update_rule, svrg_module.py:360). The reference splices
this into the Module/kvstore update path with a special SVRGOptimizer; here
the special-weight forward/backward reuses a second Executor on the same
Symbol, and the combined gradient goes through the regular updater — no
separate optimizer subclass needed since updates are pure functions.
"""
from __future__ import annotations

import numpy as onp

from ...module import Module

__all__ = ['SVRGModule']


class SVRGModule(Module):
    """Module with SVRG updates (ref: svrg_module.py:30 SVRGModule).

    update_freq: take a new full-gradient snapshot every `update_freq`
    epochs (call update_full_grads at epoch boundaries, as fit() does).
    """

    def __init__(self, symbol, data_names=('data',),
                 label_names=('softmax_label',), update_freq=2, **kwargs):
        super().__init__(symbol, data_names=data_names,
                         label_names=label_names, **kwargs)
        self.update_freq = update_freq
        self._special_params = None   # w̃ snapshot {name: NDArray}
        self._full_grads = None       # ḡ(w̃) {name: numpy}

    # -- snapshot ------------------------------------------------------------
    def update_full_grads(self, train_data):
        """Snapshot current weights as w̃ and accumulate the full-dataset
        gradient ḡ(w̃) (ref: svrg_module.py:292 update_full_grads)."""
        from ...ndarray.ndarray import NDArray
        arg_params, _ = self.get_params()
        self._special_params = {k: NDArray(v._data)
                                for k, v in arg_params.items()}
        sums = {k: onp.zeros(v.shape, onp.float32)
                for k, v in arg_params.items()}
        nbatch = 0
        train_data.reset()
        for batch in train_data:
            self.forward(batch, is_train=True)
            self.backward()
            for name in sums:
                grads = [e.grad_dict[name] for e in self._execs
                         if name in e.grad_dict]
                if grads:
                    total = grads[0].asnumpy()
                    for g in grads[1:]:
                        total = total + g.asnumpy()
                    sums[name] += total
            nbatch += 1
        train_data.reset()
        if nbatch == 0:
            raise ValueError("update_full_grads: empty data iterator")
        self._full_grads = {k: v / nbatch for k, v in sums.items()}

    def _special_batch_grads(self, data_batch):
        """Gradient of the current batch at the snapshot weights w̃, using
        a temporary weight swap on the same executors (ref:
        svrg_module.py mod_aux forward/backward)."""
        from ...ndarray.ndarray import NDArray
        current = {k: NDArray(v._data) for k, v in self._arg_params.items()}
        try:
            for k, v in self._special_params.items():
                self._arg_params[k]._data = v._data
                for e in self._execs:
                    e.arg_dict[k]._data = v._data
            self.forward(data_batch, is_train=True)
            self.backward()
            out = {}
            for name in self._arg_params:
                grads = [e.grad_dict[name] for e in self._execs
                         if name in e.grad_dict]
                if grads:
                    total = grads[0].asnumpy()
                    for g in grads[1:]:
                        total = total + g.asnumpy()
                    out[name] = total
            return out
        finally:
            for k, v in current.items():
                self._arg_params[k]._data = v._data
                for e in self._execs:
                    e.arg_dict[k]._data = v._data

    # -- training step -------------------------------------------------------
    def forward_backward_svrg(self, data_batch):
        """fwd+bwd at w, then at w̃, leaving the variance-reduced gradient
        staged for update()."""
        if self._special_params is None:
            raise ValueError("call update_full_grads() before SVRG steps")
        g_special = self._special_batch_grads(data_batch)
        self.forward(data_batch, is_train=True)
        self.backward()
        self._staged_special = g_special

    def update(self):
        """Apply g_B(w) − g_B(w̃) + ḡ(w̃) through the updater
        (ref: _svrg_grads_update_rule, svrg_module.py:360)."""
        if self._special_params is None or \
                getattr(self, '_staged_special', None) is None:
            super().update()
            return
        from ...ndarray.ndarray import array as nd_array
        param_names = list(self._arg_params)
        for idx, name in enumerate(param_names):
            if name in self._fixed_param_names:
                continue
            grads = [e.grad_dict[name] for e in self._execs
                     if name in e.grad_dict]
            if not grads:
                continue
            g_curr = grads[0].asnumpy()
            for g in grads[1:]:
                g_curr = g_curr + g.asnumpy()
            g_svrg = g_curr - self._staged_special[name] \
                + self._full_grads[name]
            weight = self._arg_params[name]
            self._updater(idx, nd_array(g_svrg), weight)
            for e in self._execs:
                e.arg_dict[name]._data = weight._data
        self._staged_special = None

    # -- fit loop ------------------------------------------------------------
    def fit(self, train_data, eval_data=None, eval_metric='acc',
            epoch_end_callback=None, batch_end_callback=None,
            kvstore='local', optimizer='sgd',
            optimizer_params=(('learning_rate', 0.01),),
            initializer=None, num_epoch=1, **kwargs):
        """SVRG fit: snapshot full grads every update_freq epochs
        (ref: svrg_module.py fit)."""
        from ... import metric as metric_mod
        from ... import initializer as init_mod
        if not self.binded:
            raise ValueError("call bind() before fit()")
        if not self.params_initialized:
            self.init_params(initializer or init_mod.Uniform(0.01))
        self.init_optimizer(kvstore=kvstore, optimizer=optimizer,
                            optimizer_params=optimizer_params)
        if isinstance(eval_metric, str):
            eval_metric = metric_mod.create(eval_metric)
        for epoch in range(num_epoch):
            if epoch % self.update_freq == 0:
                self.update_full_grads(train_data)
            eval_metric.reset()
            train_data.reset()
            for nbatch, batch in enumerate(train_data):
                self.forward_backward_svrg(batch)
                self.update()
                self.update_metric(eval_metric, batch.label)
                if batch_end_callback is not None:
                    batch_end_callback(type('P', (), {
                        'epoch': epoch, 'nbatch': nbatch,
                        'eval_metric': eval_metric})())
            if epoch_end_callback is not None:
                epoch_end_callback(epoch, self._symbol, *self.get_params())
        return eval_metric
