"""`mx.contrib` namespace (ref: python/mxnet/contrib/__init__.py)."""
from .. import amp  # noqa: F401
