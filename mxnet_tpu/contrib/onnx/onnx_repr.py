"""ONNX message builders/parsers over the wire layer
(field numbers per onnx/onnx.proto3)."""
from __future__ import annotations

import numpy as onp

from . import _proto as P

# TensorProto.DataType
DTYPE_TO_ONNX = {'float32': 1, 'uint8': 2, 'int8': 3, 'int32': 6,
                 'int64': 7, 'bool': 9, 'float16': 10, 'float64': 11,
                 'bfloat16': 16}
ONNX_TO_DTYPE = {v: k for k, v in DTYPE_TO_ONNX.items()}

# AttributeProto.AttributeType
A_FLOAT, A_INT, A_STRING, A_TENSOR = 1, 2, 3, 4
A_FLOATS, A_INTS, A_STRINGS = 6, 7, 8


def tensor(name: str, arr: onp.ndarray) -> bytes:
    """TensorProto: dims=1, data_type=2, name=8, raw_data=9."""
    arr = onp.ascontiguousarray(arr)
    dt = DTYPE_TO_ONNX[str(arr.dtype)]
    msg = b''.join(P.f_varint(1, d) for d in arr.shape)
    msg += P.f_varint(2, dt)
    msg += P.f_bytes(8, name)
    msg += P.f_bytes(9, arr.tobytes())
    return msg


def parse_tensor(buf: bytes):
    f = P.parse_message(buf)
    dims = P.get_repeated_ints(f, 1)
    dt = P.get_int(f, 2, 1)
    name = P.get_str(f, 8)
    dtype = onp.dtype(ONNX_TO_DTYPE.get(dt, 'float32'))
    if 9 in f:  # raw_data
        arr = onp.frombuffer(f[9][-1], dtype=dtype).reshape(dims)
    elif 4 in f and dt == 1:  # float_data
        arr = onp.array(P.get_repeated_floats(f, 4),
                        onp.float32).reshape(dims)
    elif 7 in f:  # int64_data
        arr = onp.array(P.get_repeated_ints(f, 7), onp.int64).reshape(dims)
    elif 5 in f:  # int32_data
        arr = onp.array(P.get_repeated_ints(f, 5), onp.int32).reshape(dims)
    else:
        arr = onp.zeros(dims, dtype)
    return name, arr


def attribute(name: str, value) -> bytes:
    """AttributeProto: name=1, f=2, i=3, s=4, t=5, floats=7, ints=8,
    strings=9, type=20."""
    msg = P.f_bytes(1, name)
    if isinstance(value, bool):
        msg += P.f_varint(3, int(value)) + P.f_varint(20, A_INT)
    elif isinstance(value, int):
        msg += P.f_varint(3, value) + P.f_varint(20, A_INT)
    elif isinstance(value, float):
        msg += P.f_float(2, value) + P.f_varint(20, A_FLOAT)
    elif isinstance(value, str):
        msg += P.f_bytes(4, value) + P.f_varint(20, A_STRING)
    elif isinstance(value, bytes):
        msg += P.f_bytes(4, value) + P.f_varint(20, A_STRING)
    elif isinstance(value, onp.ndarray):
        msg += P.f_bytes(5, tensor('', value)) + P.f_varint(20, A_TENSOR)
    elif isinstance(value, (list, tuple)):
        if all(isinstance(v, (int, bool)) for v in value):
            msg += b''.join(P.f_varint(8, int(v)) for v in value)
            msg += P.f_varint(20, A_INTS)
        elif all(isinstance(v, float) for v in value):
            msg += b''.join(P.f_float(7, v) for v in value)
            msg += P.f_varint(20, A_FLOATS)
        else:
            msg += b''.join(P.f_bytes(9, str(v)) for v in value)
            msg += P.f_varint(20, A_STRINGS)
    else:
        raise TypeError(f"unsupported attribute type for {name}: {value!r}")
    return msg


def parse_attribute(buf: bytes):
    f = P.parse_message(buf)
    name = P.get_str(f, 1)
    atype = P.get_int(f, 20, 0)
    if atype == A_FLOAT:
        return name, P.get_float(f, 2)
    if atype == A_INT:
        return name, P.get_int(f, 3)
    if atype == A_STRING:
        return name, P.get_str(f, 4)
    if atype == A_TENSOR:
        return name, parse_tensor(f[5][-1])[1]
    if atype == A_FLOATS:
        return name, P.get_repeated_floats(f, 7)
    if atype == A_INTS:
        return name, P.get_repeated_ints(f, 8)
    if atype == A_STRINGS:
        return name, [v.decode() for v in f.get(9, [])]
    # untyped (some writers omit type): infer
    if 3 in f:
        return name, P.get_int(f, 3)
    if 2 in f:
        return name, P.get_float(f, 2)
    if 8 in f:
        return name, P.get_repeated_ints(f, 8)
    return name, None


def node(op_type: str, inputs, outputs, name='', attrs=None,
         domain='') -> bytes:
    """NodeProto: input=1, output=2, name=3, op_type=4, attribute=5,
    domain=7."""
    msg = b''.join(P.f_bytes(1, i) for i in inputs)
    msg += b''.join(P.f_bytes(2, o) for o in outputs)
    if name:
        msg += P.f_bytes(3, name)
    msg += P.f_bytes(4, op_type)
    for k, v in (attrs or {}).items():
        msg += P.f_bytes(5, attribute(k, v))
    if domain:
        msg += P.f_bytes(7, domain)
    return msg


def parse_node(buf: bytes):
    f = P.parse_message(buf)
    inputs = [v.decode() for v in f.get(1, [])]
    outputs = [v.decode() for v in f.get(2, [])]
    name = P.get_str(f, 3)
    op_type = P.get_str(f, 4)
    attrs = dict(parse_attribute(a) for a in f.get(5, []))
    return {'op_type': op_type, 'name': name, 'inputs': inputs,
            'outputs': outputs, 'attrs': attrs}


def value_info(name: str, shape, elem_type=1) -> bytes:
    """ValueInfoProto{name=1, type=2}; TypeProto{tensor_type=1};
    Tensor{elem_type=1, shape=2}; TensorShapeProto{dim=1};
    Dimension{dim_value=1, dim_param=2}.

    shape=None omits the shape field entirely (unknown rank); an empty
    list declares a rank-0 scalar."""
    tt = P.f_varint(1, elem_type)
    if shape is not None:
        dims = b''
        for d in shape:
            if isinstance(d, int):
                dims += P.f_bytes(1, P.f_varint(1, d))
            else:
                dims += P.f_bytes(1, P.f_bytes(2, str(d)))
        tt += P.f_bytes(2, dims)
    tp = P.f_bytes(1, tt)
    return P.f_bytes(1, name) + P.f_bytes(2, tp)


def parse_value_info(buf: bytes):
    f = P.parse_message(buf)
    name = P.get_str(f, 1)
    shape = []
    elem_type = 1
    if 2 in f:
        tp = P.parse_message(f[2][-1])
        if 1 in tp:
            tt = P.parse_message(tp[1][-1])
            elem_type = P.get_int(tt, 1, 1)
            if 2 in tt:
                sh = P.parse_message(tt[2][-1])
                for d in sh.get(1, []):
                    df = P.parse_message(d)
                    if 1 in df:
                        shape.append(P.get_int(df, 1))
                    else:
                        shape.append(P.get_str(df, 2))
    return name, shape, elem_type


def graph(nodes, name, initializers, inputs, outputs) -> bytes:
    """GraphProto: node=1, name=2, initializer=5, input=11, output=12."""
    msg = b''.join(P.f_bytes(1, n) for n in nodes)
    msg += P.f_bytes(2, name)
    msg += b''.join(P.f_bytes(5, t) for t in initializers)
    msg += b''.join(P.f_bytes(11, vi) for vi in inputs)
    msg += b''.join(P.f_bytes(12, vi) for vi in outputs)
    return msg


def model(graph_msg: bytes, opset=17, producer='mxnet_tpu') -> bytes:
    """ModelProto: ir_version=1, producer_name=2, graph=7, opset_import=8."""
    opset_msg = P.f_varint(2, opset)  # OperatorSetIdProto{domain=1,version=2}
    msg = P.f_varint(1, 8)  # IR version 8
    msg += P.f_bytes(2, producer)
    msg += P.f_bytes(7, graph_msg)
    msg += P.f_bytes(8, opset_msg)
    return msg


def parse_model(buf: bytes):
    f = P.parse_message(buf)
    if 7 not in f:
        raise ValueError("not an ONNX ModelProto (no graph field)")
    g = P.parse_message(f[7][-1])
    nodes = [parse_node(n) for n in g.get(1, [])]
    initializers = dict(parse_tensor(t) for t in g.get(5, []))
    inputs = [parse_value_info(vi) for vi in g.get(11, [])]
    outputs = [parse_value_info(vi) for vi in g.get(12, [])]
    opset = 13
    for os_ in f.get(8, []):
        osf = P.parse_message(os_)
        if P.get_str(osf, 1) == '':
            opset = P.get_int(osf, 2, 13)
    return {'nodes': nodes, 'initializers': initializers, 'inputs': inputs,
            'outputs': outputs, 'opset': opset,
            'producer': P.get_str(f, 2)}
