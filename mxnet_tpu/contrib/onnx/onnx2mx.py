"""ONNX → Symbol import (ref: python/mxnet/contrib/onnx/onnx2mx/
_import_helper.py + _op_translations.py)."""
from __future__ import annotations

import numpy as onp

from . import onnx_repr as O

__all__ = ['import_model', 'import_to_gluon']


def _ints(v):
    return [int(x) for x in v]


class _Importer:
    def __init__(self, model):
        self.model = model
        self.inits = model['initializers']
        self.env = {}         # ONNX value name -> Symbol
        self.arg_params = {}  # var name -> numpy array
        self.consumed = set()

    def build(self):
        from ... import symbol as sym_mod
        self.sym_mod = sym_mod
        for name, shape, _ in self.model['inputs']:
            if name not in self.inits:
                self.env[name] = sym_mod.var(name)
        for node in self.model['nodes']:
            self._convert(node)
        outs = []
        for name, _, _ in self.model['outputs']:
            outs.append(self._get(name))
        return outs

    def _get(self, name):
        """Symbol for a value name; initializers become param vars."""
        if name in self.env:
            return self.env[name]
        if name in self.inits:
            v = self.sym_mod.var(name)
            self.arg_params[name] = self.inits[name]
            self.env[name] = v
            self.consumed.add(name)
            return v
        raise ValueError(f"ONNX import: undefined value '{name}'")

    def _const_value(self, name):
        """Numeric value of a name that must be a constant initializer."""
        if name in self.inits:
            self.consumed.add(name)
            return self.inits[name]
        raise ValueError(f"ONNX import: '{name}' must be a constant")

    def _convert(self, node):
        op = node['op_type']
        handler = getattr(self, f"_op_{op}", None)
        if handler is None:
            raise ValueError(f"ONNX import: unsupported op '{op}'")
        out = handler(node)
        outputs = node['outputs']
        if isinstance(out, (list, tuple)):
            for name, s in zip(outputs, out):
                self.env[name] = s
        else:
            self.env[outputs[0]] = out

    # ---- ops ---------------------------------------------------------------
    @staticmethod
    def _sym_pads(op_name, pads, nd):
        """ONNX pads = [begin..., end...]; the framework conv/pool take
        symmetric pads only — reject silent truncation."""
        begin, end = pads[:nd], pads[nd:2 * nd]
        if begin != end:
            raise ValueError(
                f"ONNX import: {op_name} with asymmetric pads {pads} is "
                "unsupported (begin != end); pad the input explicitly")
        return tuple(begin)

    def _op_Conv(self, n):
        a = n['attrs']
        ins = [self._get(x) for x in n['inputs']]
        kernel = _ints(a.get('kernel_shape', [1, 1]))
        pads = _ints(a.get('pads', [0] * 2 * len(kernel)))
        w = self.inits.get(n['inputs'][1])
        num_filter = int(w.shape[0]) if w is not None else 0
        return self.sym_mod.convolution(
            *ins, kernel=tuple(kernel),
            stride=tuple(_ints(a.get('strides', [1] * len(kernel)))),
            dilate=tuple(_ints(a.get('dilations', [1] * len(kernel)))),
            pad=self._sym_pads('Conv', pads, len(kernel)),
            num_filter=num_filter,
            num_group=int(a.get('group', 1)),
            no_bias=len(ins) < 3)

    def _op_Gemm(self, n):
        a = n['attrs']
        ins = [self._get(x) for x in n['inputs']]
        if not a.get('transB', 0):
            raise ValueError("ONNX import: Gemm without transB unsupported")
        if float(a.get('alpha', 1.0)) != 1.0 or \
                float(a.get('beta', 1.0)) != 1.0:
            raise ValueError(
                "ONNX import: Gemm with alpha/beta != 1 is unsupported")
        w = self.inits.get(n['inputs'][1])
        nh = int(w.shape[0]) if w is not None else 0
        return self.sym_mod.fully_connected(
            *ins, num_hidden=nh, no_bias=len(ins) < 3, flatten=False)

    def _op_MatMul(self, n):
        a_sym, b_sym = (self._get(x) for x in n['inputs'])
        return self.sym_mod.dot(a_sym, b_sym)

    def _op_BatchNormalization(self, n):
        a = n['attrs']
        ins = [self._get(x) for x in n['inputs']]
        out = self.sym_mod.batch_norm(
            *ins, eps=float(a.get('epsilon', 1e-5)),
            momentum=float(a.get('momentum', 0.9)), fix_gamma=False,
            use_global_stats=True)
        return out[0] if isinstance(out, tuple) else out

    def _op_LayerNormalization(self, n):
        a = n['attrs']
        ins = [self._get(x) for x in n['inputs']]
        return self.sym_mod.layer_norm(
            *ins, axis=int(a.get('axis', -1)),
            eps=float(a.get('epsilon', 1e-5)))

    def _pool(self, n, ptype, global_pool):
        a = n['attrs']
        x = self._get(n['inputs'][0])
        if global_pool:
            return self.sym_mod.pooling(x, pool_type=ptype, global_pool=True)
        kernel = _ints(a.get('kernel_shape', [1, 1]))
        pads = _ints(a.get('pads', [0] * 2 * len(kernel)))
        # ONNX spec defaults: strides = all 1s, count_include_pad = 0
        return self.sym_mod.pooling(
            x, kernel=tuple(kernel), pool_type=ptype,
            stride=tuple(_ints(a.get('strides', [1] * len(kernel)))),
            pad=self._sym_pads(f'{ptype}Pool', pads, len(kernel)),
            count_include_pad=bool(a.get('count_include_pad', 0)))

    def _op_MaxPool(self, n):
        return self._pool(n, 'max', False)

    def _op_AveragePool(self, n):
        return self._pool(n, 'avg', False)

    def _op_GlobalMaxPool(self, n):
        return self._pool(n, 'max', True)

    def _op_GlobalAveragePool(self, n):
        return self._pool(n, 'avg', True)

    def _act(self, n, act):
        return self.sym_mod.activation(self._get(n['inputs'][0]),
                                       act_type=act)

    def _op_Relu(self, n):
        return self._act(n, 'relu')

    def _op_Sigmoid(self, n):
        return self._act(n, 'sigmoid')

    def _op_Tanh(self, n):
        return self._act(n, 'tanh')

    def _op_Softplus(self, n):
        return self._act(n, 'softrelu')

    def _op_LeakyRelu(self, n):
        return self.sym_mod.leaky_relu(
            self._get(n['inputs'][0]), act_type='leaky',
            slope=float(n['attrs'].get('alpha', 0.01)))

    def _op_Elu(self, n):
        return self.sym_mod.leaky_relu(
            self._get(n['inputs'][0]), act_type='elu',
            slope=float(n['attrs'].get('alpha', 1.0)))

    def _op_PRelu(self, n):
        ins = [self._get(x) for x in n['inputs']]
        return self.sym_mod.leaky_relu(*ins, act_type='prelu')

    def _op_Erf(self, n):
        return self.sym_mod.erf(self._get(n['inputs'][0]))

    def _op_Flatten(self, n):
        return self.sym_mod.flatten(self._get(n['inputs'][0]))

    def _op_Softmax(self, n):
        return self.sym_mod.softmax(self._get(n['inputs'][0]),
                                    axis=int(n['attrs'].get('axis', -1)))

    def _op_LogSoftmax(self, n):
        return self.sym_mod.log_softmax(self._get(n['inputs'][0]),
                                        axis=int(n['attrs'].get('axis', -1)))

    def _op_Dropout(self, n):
        # inference: identity
        return self.sym_mod.identity(self._get(n['inputs'][0]))

    def _op_Identity(self, n):
        return self.sym_mod.identity(self._get(n['inputs'][0]))

    def _op_Reshape(self, n):
        shape = self._const_value(n['inputs'][1])
        return self.sym_mod.reshape(self._get(n['inputs'][0]),
                                    shape=tuple(int(x) for x in shape))

    def _op_Transpose(self, n):
        perm = n['attrs'].get('perm')
        x = self._get(n['inputs'][0])
        if perm is None:
            return self.sym_mod.transpose(x)
        return self.sym_mod.transpose(x, axes=tuple(_ints(perm)))

    def _op_Concat(self, n):
        ins = [self._get(x) for x in n['inputs']]
        return self.sym_mod.concat(*ins, dim=int(n['attrs'].get('axis', 0)))

    def _op_Gather(self, n):
        data = n['inputs'][0]
        idx = self._get(n['inputs'][1])
        axis = int(n['attrs'].get('axis', 0))
        if data in self.inits and axis == 0:
            w = self.inits[data]
            return self.sym_mod.embedding(
                idx, self._get(data), input_dim=int(w.shape[0]),
                output_dim=int(w.shape[1]) if w.ndim > 1 else 1)
        return self.sym_mod.take(self._get(data), idx, axis=axis)

    def _op_Cast(self, n):
        to = int(n['attrs'].get('to', 1))
        return self.sym_mod.cast(self._get(n['inputs'][0]),
                                 dtype=O.ONNX_TO_DTYPE.get(to, 'float32'))

    def _binary(self, n, opname):
        a_name, b_name = n['inputs'][:2]
        # scalar constant operand → scalar op
        for name, scalar_op, sym_first in (
                (b_name, opname, True), (a_name, opname, False)):
            if name in self.inits and self.inits[name].ndim == 0:
                scalar = float(self.inits[name])
                other = self._get(a_name if sym_first else b_name)
                self.consumed.add(name)
                table = {'broadcast_add': 'plus_scalar',
                         'broadcast_sub': ('minus_scalar' if sym_first
                                           else 'rminus_scalar'),
                         'broadcast_mul': 'mul_scalar',
                         'broadcast_div': ('div_scalar' if sym_first
                                           else 'rdiv_scalar'),
                         'broadcast_power': 'power_scalar'}
                sop = table.get(opname)
                if sop:
                    return getattr(self.sym_mod, sop)(other, scalar=scalar)
        ins = [self._get(a_name), self._get(b_name)]
        return getattr(self.sym_mod, opname)(*ins)

    def _op_Add(self, n):
        return self._binary(n, 'broadcast_add')

    def _op_Sub(self, n):
        return self._binary(n, 'broadcast_sub')

    def _op_Mul(self, n):
        return self._binary(n, 'broadcast_mul')

    def _op_Div(self, n):
        return self._binary(n, 'broadcast_div')

    def _op_Pow(self, n):
        return self._binary(n, 'broadcast_power')

    def _op_Max(self, n):
        return self._binary(n, 'broadcast_maximum')

    def _op_Min(self, n):
        return self._binary(n, 'broadcast_minimum')

    def _unary(self, n, opname):
        return getattr(self.sym_mod, opname)(self._get(n['inputs'][0]))

    def _op_Exp(self, n):
        return self._unary(n, 'exp')

    def _op_Log(self, n):
        return self._unary(n, 'log')

    def _op_Sqrt(self, n):
        return self._unary(n, 'sqrt')

    def _op_Abs(self, n):
        return self._unary(n, 'abs')

    def _op_Neg(self, n):
        return self._unary(n, 'negative')

    def _op_Floor(self, n):
        return self._unary(n, 'floor')

    def _op_Ceil(self, n):
        return self._unary(n, 'ceil')

    def _reduce(self, n, opname, axes_as_input=False):
        a = n['attrs']
        x = self._get(n['inputs'][0])
        kw = {'keepdims': bool(a.get('keepdims', 1))}
        axes = None
        if axes_as_input and len(n['inputs']) > 1:
            axes = [int(v) for v in self._const_value(n['inputs'][1])]
        elif 'axes' in a:
            axes = _ints(a['axes'])
        if axes is not None:
            kw['axis'] = tuple(axes)
        return getattr(self.sym_mod, opname)(x, **kw)

    def _op_ReduceMean(self, n):
        return self._reduce(n, 'mean')

    def _op_ReduceSum(self, n):
        return self._reduce(n, 'sum', axes_as_input=True)

    def _op_ReduceMax(self, n):
        return self._reduce(n, 'max')

    def _op_ReduceMin(self, n):
        return self._reduce(n, 'min')

    def _op_ReduceProd(self, n):
        return self._reduce(n, 'prod')

    def _op_Clip(self, n):
        x = self._get(n['inputs'][0])
        lo = float(self._const_value(n['inputs'][1])) \
            if len(n['inputs']) > 1 else -onp.inf
        hi = float(self._const_value(n['inputs'][2])) \
            if len(n['inputs']) > 2 else onp.inf
        return self.sym_mod.clip(x, a_min=lo, a_max=hi)

    def _op_Unsqueeze(self, n):
        x = self._get(n['inputs'][0])
        if len(n['inputs']) > 1:
            axes = [int(v) for v in self._const_value(n['inputs'][1])]
        else:
            axes = _ints(n['attrs'].get('axes', [0]))
        for ax in axes:
            x = self.sym_mod.expand_dims(x, axis=ax)
        return x

    def _op_Squeeze(self, n):
        x = self._get(n['inputs'][0])
        if len(n['inputs']) > 1:
            axes = tuple(int(v) for v in self._const_value(n['inputs'][1]))
            return self.sym_mod.squeeze(x, axis=axes)
        if 'axes' in n['attrs']:
            return self.sym_mod.squeeze(
                x, axis=tuple(_ints(n['attrs']['axes'])))
        return self.sym_mod.squeeze(x)

    def _op_Constant(self, n):
        val = n['attrs'].get('value')
        if val is None:
            raise ValueError("ONNX import: Constant without tensor value")
        name = n['outputs'][0]
        self.inits[name] = onp.asarray(val)
        return self._get(name)


def import_model(model_file):
    """Import an ONNX file → (sym, arg_params, aux_params)
    (ref: onnx2mx/_import_helper.py import_model)."""
    from ...ndarray.ndarray import array as nd_array
    with open(model_file, 'rb') as f:
        buf = f.read()
    model = O.parse_model(buf)
    imp = _Importer(model)
    outs = imp.build()
    sym = outs[0] if len(outs) == 1 else outs
    arg_params = {k: nd_array(onp.ascontiguousarray(v))
                  for k, v in imp.arg_params.items()}
    return sym, arg_params, {}


def import_to_gluon(model_file, ctx=None):
    """Import an ONNX file into a Gluon SymbolBlock (ref:
    contrib/onnx/onnx2mx import_to_gluon)."""
    from ...gluon.block import SymbolBlock
    from ... import symbol as sym_mod
    sym, arg_params, aux_params = import_model(model_file)
    param_names = set(arg_params)
    input_names = [n for n in sym.list_arguments() if n not in param_names]
    inputs = [sym_mod.var(n) for n in input_names]
    net = SymbolBlock(sym, inputs)
    net._load_arg_dict({**arg_params, **aux_params}, ctx=ctx)
    return net
