"""ONNX interop (ref: python/mxnet/contrib/onnx/).

Works without the `onnx` package: the protobuf wire format is emitted and
parsed directly (see _proto.py)."""
from .mx2onnx import export_model  # noqa: F401
from .onnx2mx import import_model, import_to_gluon  # noqa: F401
