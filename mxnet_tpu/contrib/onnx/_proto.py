"""Minimal protobuf wire-format encode/decode for ONNX interop.

The environment has no `onnx` package, so the exporter emits (and the
importer parses) the protobuf wire format directly — the format is simple:
varints, fixed32/64, and length-delimited fields. Only the subset of
onnx.proto needed for ModelProto round-trips is modeled (ref message/field
numbers: onnx/onnx.proto3).
"""
from __future__ import annotations

import struct
from typing import Dict, List, Tuple, Union

# wire types
VARINT, FIXED64, BYTES, FIXED32 = 0, 1, 2, 5


def write_varint(n: int) -> bytes:
    if n < 0:
        n &= (1 << 64) - 1  # two's-complement 64-bit, 10-byte varint
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _tag(field: int, wire: int) -> bytes:
    return write_varint((field << 3) | wire)


def f_varint(field: int, value: int) -> bytes:
    return _tag(field, VARINT) + write_varint(int(value))


def f_bytes(field: int, data: Union[bytes, str]) -> bytes:
    if isinstance(data, str):
        data = data.encode('utf-8')
    return _tag(field, BYTES) + write_varint(len(data)) + data


def f_float(field: int, value: float) -> bytes:
    return _tag(field, FIXED32) + struct.pack('<f', float(value))


def f_packed_varints(field: int, values) -> bytes:
    payload = b''.join(write_varint(int(v)) for v in values)
    return f_bytes(field, payload)


def f_packed_floats(field: int, values) -> bytes:
    payload = b''.join(struct.pack('<f', float(v)) for v in values)
    return f_bytes(field, payload)


# ---- decoding ---------------------------------------------------------------

def read_varint(buf: bytes, pos: int) -> Tuple[int, int]:
    result = 0
    shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not (b & 0x80):
            break
        shift += 7
    return result, pos


def to_signed(n: int) -> int:
    """Interpret a varint as a signed int64 (protobuf int32/int64)."""
    if n >= (1 << 63):
        n -= (1 << 64)
    return n


def parse_message(buf: bytes) -> Dict[int, List]:
    """Parse one message into {field_number: [raw values in order]}.
    VARINT → int, FIXED32 → 4 bytes, FIXED64 → 8 bytes, BYTES → bytes."""
    fields: Dict[int, List] = {}
    pos = 0
    n = len(buf)
    while pos < n:
        key, pos = read_varint(buf, pos)
        field, wire = key >> 3, key & 7
        if wire == VARINT:
            val, pos = read_varint(buf, pos)
        elif wire == BYTES:
            ln, pos = read_varint(buf, pos)
            val = buf[pos:pos + ln]
            pos += ln
        elif wire == FIXED32:
            val = buf[pos:pos + 4]
            pos += 4
        elif wire == FIXED64:
            val = buf[pos:pos + 8]
            pos += 8
        else:
            raise ValueError(f"unsupported protobuf wire type {wire}")
        fields.setdefault(field, []).append(val)
    return fields


def get_str(fields, num, default='') -> str:
    if num in fields:
        return fields[num][-1].decode('utf-8')
    return default


def get_int(fields, num, default=0) -> int:
    if num in fields:
        return to_signed(fields[num][-1])
    return default


def get_float(fields, num, default=0.0) -> float:
    if num in fields:
        return struct.unpack('<f', fields[num][-1])[0]
    return default


def get_repeated_ints(fields, num) -> List[int]:
    """Repeated int64 field: either packed (one bytes blob) or repeated
    varints."""
    out = []
    for v in fields.get(num, []):
        if isinstance(v, int):
            out.append(to_signed(v))
        else:  # packed
            pos = 0
            while pos < len(v):
                val, pos = read_varint(v, pos)
                out.append(to_signed(val))
    return out


def get_repeated_floats(fields, num) -> List[float]:
    out = []
    for v in fields.get(num, []):
        if isinstance(v, bytes) and len(v) == 4:
            out.append(struct.unpack('<f', v)[0])
        elif isinstance(v, bytes):  # packed
            out.extend(struct.unpack(f'<{len(v)//4}f', v))
    return out
