"""Symbol/Gluon → ONNX export (ref: python/mxnet/contrib/onnx/mx2onnx/
export_model.py + _op_translations.py).

Walks the Symbol DAG and emits ONNX nodes; parameters become graph
initializers. Produces the protobuf bytes directly (no onnx package needed)
at opset 17.
"""
from __future__ import annotations

import numpy as onp

from . import onnx_repr as O

__all__ = ['export_model']


def _tuple(v, n=2):
    if v is None:
        return (1,) * n
    if isinstance(v, int):
        return (v,) * n
    return tuple(int(x) for x in v)


class _Ctx:
    def __init__(self, params):
        self.nodes = []          # NodeProto bytes, topo order
        self.initializers = []   # TensorProto bytes
        self.init_names = set()
        self.params = params
        self.counter = 0

    def uniq(self, base):
        self.counter += 1
        return f"{base}_{self.counter}"

    def add_init(self, name, arr):
        if name not in self.init_names:
            self.initializers.append(O.tensor(name, onp.asarray(arr)))
            self.init_names.add(name)
        return name

    def const(self, base, arr):
        return self.add_init(self.uniq(base), arr)

    def emit(self, op_type, inputs, outputs, attrs=None, name=''):
        self.nodes.append(O.node(op_type, inputs, outputs,
                                 name or self.uniq(op_type), attrs))


def _conv(ctx, s, ins, out):
    a = s.attrs
    kernel = _tuple(a.get('kernel'))
    nd = len(kernel)
    pad = _tuple(a.get('pad', 0), nd)
    attrs = {'kernel_shape': list(kernel),
             'strides': list(_tuple(a.get('stride', 1), nd)),
             'dilations': list(_tuple(a.get('dilate', 1), nd)),
             'pads': list(pad) * 2,
             'group': int(a.get('num_group', 1))}
    ctx.emit('Conv', ins, [out], attrs)


def _fc(ctx, s, ins, out):
    a = s.attrs
    flatten = a.get('flatten', True)
    x, w = ins[0], ins[1]
    b = ins[2] if len(ins) > 2 and not a.get('no_bias', False) else None
    if flatten:
        flat = ctx.uniq('flatten_out')
        ctx.emit('Flatten', [x], [flat], {'axis': 1})
        gemm_in = [flat, w] + ([b] if b else [])
        if not b:
            zeros = ctx.const('fc_zero_bias',
                              onp.zeros((int(a['num_hidden']),), onp.float32))
            gemm_in = [flat, w, zeros]
        ctx.emit('Gemm', gemm_in, [out], {'transB': 1, 'alpha': 1.0,
                                          'beta': 1.0})
    else:
        # y = x @ W.T (+ b) on the last axis
        wt = ctx.uniq('weight_T')
        ctx.emit('Transpose', [w], [wt], {'perm': [1, 0]})
        mm = ctx.uniq('matmul_out') if b else out
        ctx.emit('MatMul', [x, wt], [mm])
        if b:
            ctx.emit('Add', [mm, b], [out])


def _act(ctx, s, ins, out):
    table = {'relu': 'Relu', 'sigmoid': 'Sigmoid', 'tanh': 'Tanh',
             'softrelu': 'Softplus', 'softsign': 'Softsign'}
    act = s.attrs.get('act_type', 'relu')
    if act not in table:
        raise ValueError(f"ONNX export: unsupported activation {act}")
    ctx.emit(table[act], ins, [out])


def _leaky(ctx, s, ins, out):
    act = s.attrs.get('act_type', 'leaky')
    if act == 'leaky':
        ctx.emit('LeakyRelu', [ins[0]], [out],
                 {'alpha': float(s.attrs.get('slope', 0.25))})
    elif act == 'elu':
        ctx.emit('Elu', [ins[0]], [out],
                 {'alpha': float(s.attrs.get('slope', 0.25))})
    elif act == 'prelu':
        ctx.emit('PRelu', ins[:2], [out])
    elif act == 'gelu':
        # erf-formulation: x * 0.5 * (1 + erf(x / sqrt(2)))
        div = ctx.const('gelu_sqrt2', onp.array(onp.sqrt(2.0), onp.float32))
        xd = ctx.uniq('gelu_xd')
        ctx.emit('Div', [ins[0], div], [xd])
        er = ctx.uniq('gelu_erf')
        ctx.emit('Erf', [xd], [er])
        one = ctx.const('gelu_one', onp.array(1.0, onp.float32))
        half = ctx.const('gelu_half', onp.array(0.5, onp.float32))
        p1 = ctx.uniq('gelu_p1')
        ctx.emit('Add', [er, one], [p1])
        ph = ctx.uniq('gelu_ph')
        ctx.emit('Mul', [p1, half], [ph])
        ctx.emit('Mul', [ins[0], ph], [out])
    else:
        raise ValueError(f"ONNX export: unsupported leaky_relu {act}")


def _bn(ctx, s, ins, out):
    if s.out_index != 0:
        raise ValueError("ONNX export: running-stat outputs of batch_norm "
                         "are not exportable")
    attrs = {'epsilon': float(s.attrs.get('eps', 1e-3)),
             'momentum': float(s.attrs.get('momentum', 0.9))}
    ins = list(ins[:5])
    if s.attrs.get('fix_gamma', True):
        # mx fix_gamma treats gamma as ones; ONNX BN always applies scale,
        # so bake in a ones tensor shaped like beta/gamma
        gamma_arr = ctx.params.get(ins[1])
        shape = (gamma_arr.shape if gamma_arr is not None
                 else ctx.params[ins[2]].shape)
        ins[1] = ctx.const('bn_fixed_gamma', onp.ones(shape, onp.float32))
    ctx.emit('BatchNormalization', ins, [out], attrs)


def _pool(ctx, s, ins, out):
    a = s.attrs
    ptype = a.get('pool_type', 'max')
    if a.get('global_pool', False):
        op = {'max': 'GlobalMaxPool', 'avg': 'GlobalAveragePool'}.get(ptype)
        if op is None:
            raise ValueError(f"ONNX export: global {ptype} pool unsupported")
        ctx.emit(op, ins, [out])
        return
    kernel = _tuple(a.get('kernel'))
    nd = len(kernel)
    # a pooling symbol without a 'stride' attr computes stride=1
    # (ops/nn.py pooling default) — export must match, not kernel-stride
    attrs = {'kernel_shape': list(kernel),
             'strides': list(_tuple(a.get('stride', 1), nd)),
             'pads': list(_tuple(a.get('pad', 0), nd)) * 2}
    if ptype == 'avg':
        attrs['count_include_pad'] = int(a.get('count_include_pad', True))
    op = {'max': 'MaxPool', 'avg': 'AveragePool'}.get(ptype)
    if op is None:
        raise ValueError(f"ONNX export: pool_type {ptype} unsupported")
    ctx.emit(op, ins, [out], attrs)


def _reshape(ctx, s, ins, out):
    shape = s.attrs.get('shape')
    if shape is None:
        raise ValueError("ONNX export: reshape needs a static shape attr")
    shape = [int(x) for x in (shape if isinstance(shape, (list, tuple))
                              else [shape])]
    if any(x in (-2, -3, -4) for x in shape):
        raise ValueError("ONNX export: reshape special codes -2/-3/-4 "
                         "unsupported")
    shp = ctx.const('reshape_shape', onp.array(shape, onp.int64))
    ctx.emit('Reshape', [ins[0], shp], [out])


def _scalar_arith(onnx_op, reverse=False):
    def h(ctx, s, ins, out):
        c = ctx.const('scalar', onp.array(float(s.attrs.get('scalar', 0.0)),
                                          onp.float32))
        args = [c, ins[0]] if reverse else [ins[0], c]
        ctx.emit(onnx_op, args, [out])
    return h


def _binary(onnx_op):
    def h(ctx, s, ins, out):
        ctx.emit(onnx_op, ins[:2], [out])
    return h


def _unary(onnx_op, **fixed):
    def h(ctx, s, ins, out):
        ctx.emit(onnx_op, [ins[0]], [out], fixed or None)
    return h


def _softmax(ctx, s, ins, out):
    ctx.emit('Softmax', [ins[0]], [out],
             {'axis': int(s.attrs.get('axis', -1))})


def _transpose(ctx, s, ins, out):
    axes = s.attrs.get('axes')
    attrs = {'perm': [int(x) for x in axes]} if axes else None
    ctx.emit('Transpose', [ins[0]], [out], attrs)


def _concat(ctx, s, ins, out):
    ctx.emit('Concat', ins, [out],
             {'axis': int(s.attrs.get('dim', s.attrs.get('axis', 1)))})


def _dropout(ctx, s, ins, out):
    ratio = ctx.const('dropout_ratio',
                      onp.array(float(s.attrs.get('p', 0.5)), onp.float32))
    train = ctx.const('dropout_training', onp.array(False))
    ctx.emit('Dropout', [ins[0], ratio, train], [out])


def _embedding(ctx, s, ins, out):
    # mx: embedding(data=indices, weight); ONNX: Gather(weight, indices)
    idx64 = ctx.uniq('emb_idx64')
    ctx.emit('Cast', [ins[0]], [idx64], {'to': 7})
    ctx.emit('Gather', [ins[1], idx64], [out], {'axis': 0})


def _layer_norm(ctx, s, ins, out):
    ctx.emit('LayerNormalization', ins[:3], [out],
             {'axis': int(s.attrs.get('axis', -1)),
              'epsilon': float(s.attrs.get('eps', 1e-5))})


def _reduce(onnx_op):
    def h(ctx, s, ins, out):
        a = s.attrs
        axis = a.get('axis')
        attrs = {'keepdims': int(a.get('keepdims', False))}
        if axis is not None:
            axes = [int(axis)] if isinstance(axis, int) else \
                [int(x) for x in axis]
            attrs['axes'] = axes
        ctx.emit(onnx_op, [ins[0]], [out], attrs)
    return h


def _clip(ctx, s, ins, out):
    lo = ctx.const('clip_min',
                   onp.array(float(s.attrs.get('a_min', 0.0)), onp.float32))
    hi = ctx.const('clip_max',
                   onp.array(float(s.attrs.get('a_max', 0.0)), onp.float32))
    ctx.emit('Clip', [ins[0], lo, hi], [out])


def _cast(ctx, s, ins, out):
    dt = O.DTYPE_TO_ONNX[str(onp.dtype(s.attrs.get('dtype', 'float32')))]
    ctx.emit('Cast', [ins[0]], [out], {'to': dt})


def _flatten(ctx, s, ins, out):
    ctx.emit('Flatten', [ins[0]], [out], {'axis': 1})


def _expand_dims(ctx, s, ins, out):
    ax = ctx.const('unsq_axes',
                   onp.array([int(s.attrs.get('axis', 0))], onp.int64))
    ctx.emit('Unsqueeze', [ins[0], ax], [out])


def _squeeze(ctx, s, ins, out):
    axis = s.attrs.get('axis')
    if axis is None:
        ctx.emit('Squeeze', [ins[0]], [out])
    else:
        axes = [int(axis)] if isinstance(axis, int) else \
            [int(x) for x in axis]
        ax = ctx.const('sq_axes', onp.array(axes, onp.int64))
        ctx.emit('Squeeze', [ins[0], ax], [out])


_TRANSLATIONS = {
    'convolution': _conv,
    'fully_connected': _fc,
    'activation': _act,
    'leaky_relu': _leaky,
    'batch_norm': _bn,
    'pooling': _pool,
    'flatten': _flatten,
    'reshape': _reshape,
    'transpose': _transpose,
    'concat': _concat,
    'dropout': _dropout,
    'embedding': _embedding,
    'layer_norm': _layer_norm,
    'softmax': _softmax,
    'log_softmax': _unary('LogSoftmax'),
    'relu': _unary('Relu'),
    'sigmoid': _unary('Sigmoid'),
    'tanh': _unary('Tanh'),
    'exp': _unary('Exp'),
    'log': _unary('Log'),
    'sqrt': _unary('Sqrt'),
    'abs': _unary('Abs'),
    'negative': _unary('Neg'),
    'erf': _unary('Erf'),
    'floor': _unary('Floor'),
    'ceil': _unary('Ceil'),
    'identity': _unary('Identity'),
    'broadcast_add': _binary('Add'), 'elemwise_add': _binary('Add'),
    'broadcast_sub': _binary('Sub'), 'elemwise_sub': _binary('Sub'),
    'broadcast_mul': _binary('Mul'), 'elemwise_mul': _binary('Mul'),
    'broadcast_div': _binary('Div'), 'elemwise_div': _binary('Div'),
    'broadcast_power': _binary('Pow'),
    'broadcast_maximum': _binary('Max'),
    'broadcast_minimum': _binary('Min'),
    'dot': _binary('MatMul'),
    'batch_dot': _binary('MatMul'),
    'plus_scalar': _scalar_arith('Add'),
    'minus_scalar': _scalar_arith('Sub'),
    'rminus_scalar': _scalar_arith('Sub', reverse=True),
    'mul_scalar': _scalar_arith('Mul'),
    'div_scalar': _scalar_arith('Div'),
    'rdiv_scalar': _scalar_arith('Div', reverse=True),
    'power_scalar': _scalar_arith('Pow'),
    'mean': _reduce('ReduceMean'),
    'sum': _reduce('ReduceSum_axesattr'),  # handled below
    'max': _reduce('ReduceMax'),
    'min': _reduce('ReduceMin'),
    'prod': _reduce('ReduceProd'),
    'clip': _clip,
    'cast': _cast,
    'expand_dims': _expand_dims,
    'squeeze': _squeeze,
}


def _emit_sum(ctx, s, ins, out):
    """ReduceSum: axes moved to an input at opset 13."""
    a = s.attrs
    axis = a.get('axis')
    attrs = {'keepdims': int(a.get('keepdims', False))}
    inputs = [ins[0]]
    if axis is not None:
        axes = [int(axis)] if isinstance(axis, int) else \
            [int(x) for x in axis]
        inputs.append(ctx.const('sum_axes', onp.array(axes, onp.int64)))
    ctx.emit('ReduceSum', inputs, [out], attrs)


_TRANSLATIONS['sum'] = _emit_sum


def export_model(sym, params, input_shapes=None, input_types=None,
                 onnx_file_path='model.onnx', input_names=('data',),
                 verbose=False, opset_version=17):
    """Export a Symbol (or HybridBlock) + params to an ONNX file
    (ref: mx2onnx/export_model.py export_model).

    sym: Symbol or HybridBlock; params: {name: NDArray}; input_shapes:
    list of shapes for each graph input. Returns onnx_file_path.
    """
    from ...gluon.block import HybridBlock
    from ...ndarray.ndarray import NDArray
    from ... import symbol as sym_mod

    if isinstance(sym, HybridBlock):
        block = sym
        params = {name: p.data()
                  for name, p in block.collect_params().items()}
        inputs = [sym_mod.var(n) for n in input_names]
        sym = block(*inputs)

    params = {k.split(':', 1)[-1]: v for k, v in params.items()}
    ctx = _Ctx(params)

    arg_names = sym.list_arguments()
    data_inputs = [n for n in arg_names if n not in params]

    # walk DAG in topo order, one ONNX node (or small group) per symbol node
    visited = {}

    def out_name(s):
        return s._name if s.num_outputs == 1 else \
            f"{s._name}_out{s.out_index}"

    def visit(s):
        key = (s._name, s.out_index)
        if key in visited:
            return visited[key]
        if s.op is None:
            visited[key] = s._name
            return s._name
        ins = [visit(i) for i in s.inputs]
        out = out_name(s)
        handler = _TRANSLATIONS.get(s.op)
        if handler is None:
            raise ValueError(
                f"ONNX export: no translation for op '{s.op}' "
                f"(node {s._name})")
        handler(ctx, s, ins, out)
        visited[key] = out
        return out

    final = visit(sym)

    for name, arr in params.items():
        if name in arg_names:
            val = arr.asnumpy() if isinstance(arr, NDArray) else \
                onp.asarray(arr)
            ctx.add_init(name, val)

    if input_shapes is None:
        input_shapes = [['N'] + ['?'] * 3] * len(data_inputs)
    graph_inputs = [O.value_info(n, list(shape))
                    for n, shape in zip(data_inputs, input_shapes)]
    graph_outputs = [O.value_info(final, None)]

    g = O.graph(ctx.nodes, 'mxnet_tpu_graph', ctx.initializers,
                graph_inputs, graph_outputs)
    m = O.model(g, opset=opset_version)
    with open(onnx_file_path, 'wb') as f:
        f.write(m)
    if verbose:
        print(f"exported {len(ctx.nodes)} nodes, "
              f"{len(ctx.initializers)} initializers -> {onnx_file_path}")
    return onnx_file_path
