"""Text utilities: vocabulary + token embeddings
(ref: python/mxnet/contrib/text/{vocab.py,embedding.py,utils.py})."""
from __future__ import annotations

import collections
import re

import numpy as onp

from ..ndarray.ndarray import NDArray, array as nd_array

__all__ = ['Vocabulary', 'CustomEmbedding', 'CompositeEmbedding',
           'count_tokens_from_str']


def count_tokens_from_str(source_str, token_delim=' ', seq_delim='\n',
                          to_lower=False, counter_to_update=None):
    """Count tokens in a delimited string (ref: text/utils.py)."""
    source_str = re.sub(
        f'[{re.escape(token_delim)}{re.escape(seq_delim)}]+', ' ',
        source_str).strip()
    if to_lower:
        source_str = source_str.lower()
    counter = counter_to_update if counter_to_update is not None \
        else collections.Counter()
    if source_str:
        counter.update(source_str.split(' '))
    return counter


class Vocabulary:
    """Token ↔ index mapping built from a counter
    (ref: text/vocab.py Vocabulary)."""

    def __init__(self, counter=None, most_freq_count=None, min_freq=1,
                 unknown_token='<unk>', reserved_tokens=None):
        if min_freq < 1:
            raise ValueError("min_freq must be >= 1")
        self._unknown_token = unknown_token
        reserved_tokens = list(reserved_tokens or [])
        if len(set(reserved_tokens)) != len(reserved_tokens) or \
                unknown_token in reserved_tokens:
            raise ValueError("reserved tokens must be unique and must not "
                             "contain the unknown token")
        self._idx_to_token = [unknown_token] + reserved_tokens
        self._reserved_tokens = reserved_tokens
        self._token_to_idx = {t: i for i, t in enumerate(self._idx_to_token)}
        if counter is not None:
            pairs = sorted(counter.items(), key=lambda kv: (-kv[1], kv[0]))
            if most_freq_count is not None:
                pairs = pairs[:most_freq_count]
            for token, freq in pairs:
                if freq < min_freq:
                    break
                if token not in self._token_to_idx:
                    self._token_to_idx[token] = len(self._idx_to_token)
                    self._idx_to_token.append(token)

    def __len__(self):
        return len(self._idx_to_token)

    @property
    def token_to_idx(self):
        return self._token_to_idx

    @property
    def idx_to_token(self):
        return self._idx_to_token

    @property
    def unknown_token(self):
        return self._unknown_token

    @property
    def reserved_tokens(self):
        return self._reserved_tokens

    def to_indices(self, tokens):
        single = isinstance(tokens, str)
        if single:
            tokens = [tokens]
        out = [self._token_to_idx.get(t, 0) for t in tokens]
        return out[0] if single else out

    def to_tokens(self, indices):
        single = isinstance(indices, int)
        if single:
            indices = [indices]
        out = []
        for i in indices:
            if not 0 <= i < len(self._idx_to_token):
                raise ValueError(f"token index {i} out of range")
            out.append(self._idx_to_token[i])
        return out[0] if single else out


class _TokenEmbedding(Vocabulary):
    """Base for pretrained/custom embeddings (ref: text/embedding.py)."""

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self._vec_len = 0
        self._idx_to_vec = None

    @property
    def vec_len(self):
        return self._vec_len

    @property
    def idx_to_vec(self):
        return self._idx_to_vec

    def get_vecs_by_tokens(self, tokens, lower_case_backup=False):
        single = isinstance(tokens, str)
        if single:
            tokens = [tokens]
        indices = []
        for t in tokens:
            if t in self._token_to_idx:
                indices.append(self._token_to_idx[t])
            elif lower_case_backup and t.lower() in self._token_to_idx:
                indices.append(self._token_to_idx[t.lower()])
            else:
                indices.append(0)
        vecs = self._idx_to_vec.asnumpy()[indices]
        out = nd_array(vecs)
        return NDArray(out._data[0]) if single else out

    def update_token_vectors(self, tokens, new_vectors):
        if isinstance(tokens, str):
            tokens = [tokens]
        vecs = onp.array(self._idx_to_vec.asnumpy())  # writable copy
        new_np = new_vectors.asnumpy() if isinstance(new_vectors, NDArray) \
            else onp.asarray(new_vectors)
        new_np = new_np.reshape(len(tokens), -1)
        for t, v in zip(tokens, new_np):
            if t not in self._token_to_idx:
                raise ValueError(f"token '{t}' is unknown")
            vecs[self._token_to_idx[t]] = v
        self._idx_to_vec = nd_array(vecs)

    def _load_embedding_txt(self, file_path, elem_delim=' ',
                            encoding='utf8', restrict_vocab=None):
        """Load `token v1 v2 ...` lines (glove/fasttext text format).
        A leading fastText `count dim` header line is skipped. When
        `restrict_vocab` is given, only its tokens are loaded and row
        indices follow the vocabulary's own order."""
        tokens, vecs = [], []
        with open(file_path, encoding=encoding) as f:
            for lineno, line in enumerate(f):
                parts = line.rstrip().split(elem_delim)
                if len(parts) < 2:
                    continue
                if lineno == 0 and len(parts) == 2:
                    try:  # fastText header: "<vocab_count> <dim>"
                        int(parts[0]), int(parts[1])
                        continue
                    except ValueError:
                        pass
                try:
                    vec = [float(x) for x in parts[1:]]
                except ValueError:
                    continue  # malformed / header-ish line
                if vecs and len(vec) != len(vecs[0]):
                    raise ValueError(
                        f"{file_path}:{lineno + 1}: vector has dim "
                        f"{len(vec)}, expected {len(vecs[0])}")
                if restrict_vocab is not None and \
                        parts[0] not in restrict_vocab.token_to_idx:
                    continue
                tokens.append(parts[0])
                vecs.append(vec)
        if not vecs:
            raise ValueError(f"no vectors found in {file_path}")
        self._vec_len = len(vecs[0])
        if restrict_vocab is not None:
            # adopt the vocabulary's index space verbatim
            self._idx_to_token = list(restrict_vocab.idx_to_token)
            self._token_to_idx = dict(restrict_vocab.token_to_idx)
        else:
            for t in tokens:
                if t not in self._token_to_idx:
                    self._token_to_idx[t] = len(self._idx_to_token)
                    self._idx_to_token.append(t)
        all_vecs = onp.zeros((len(self._idx_to_token), self._vec_len),
                             onp.float32)
        for t, v in zip(tokens, vecs):
            all_vecs[self._token_to_idx[t]] = v
        self._idx_to_vec = nd_array(all_vecs)


class CustomEmbedding(_TokenEmbedding):
    """Embedding loaded from a user text file of `token v1 v2 ...` lines
    (ref: text/embedding.py CustomEmbedding)."""

    def __init__(self, pretrained_file_path, elem_delim=' ',
                 encoding='utf8', vocabulary=None):
        super().__init__()
        self._load_embedding_txt(pretrained_file_path, elem_delim, encoding,
                                 restrict_vocab=vocabulary)


class CompositeEmbedding(_TokenEmbedding):
    """Concatenate several embeddings' vectors per token
    (ref: text/embedding.py CompositeEmbedding)."""

    def __init__(self, vocabulary, token_embeddings):
        super().__init__()
        if not isinstance(token_embeddings, (list, tuple)):
            token_embeddings = [token_embeddings]
        self._idx_to_token = list(vocabulary.idx_to_token)
        self._token_to_idx = dict(vocabulary.token_to_idx)
        parts = []
        for emb in token_embeddings:
            parts.append(emb.get_vecs_by_tokens(
                self._idx_to_token).asnumpy())
        cat = onp.concatenate(parts, axis=1)
        self._vec_len = cat.shape[1]
        self._idx_to_vec = nd_array(cat.astype(onp.float32))
