"""Attribute scoping for symbol construction (ref:
python/mxnet/attribute.py AttrScope).

`with mx.AttrScope(ctx_group='stage1', lr_mult='0.1'):` attaches the
given attributes to every Symbol created inside the block, stored under
dunder keys (`__ctx_group__`, `__lr_mult__`) exactly like the reference,
so graph passes — notably the group2ctxs manual model-parallel placement
in Module (module.py) — can read them back. Scopes nest; inner values
win."""
from __future__ import annotations

import threading

__all__ = ['AttrScope', 'current_attrs']

_local = threading.local()


def _stack():
    if not hasattr(_local, 'stack'):
        _local.stack = []
    return _local.stack


class AttrScope:
    """Attribute manager applying attrs to symbols created in scope
    (ref: python/mxnet/attribute.py:AttrScope)."""

    def __init__(self, **kwargs):
        for v in kwargs.values():
            if not isinstance(v, str):
                raise ValueError(
                    "AttrScope values must be strings (reference "
                    "convention); got %r" % (v,))
        self._attr = {f"__{k}__": v for k, v in kwargs.items()}

    def get(self, attr=None):
        """Merge THIS scope's attrs with explicitly-passed ones (explicit
        wins). Reference-API parity (AttrScope.get); symbol construction
        uses module-level current_attrs(), which merges the whole stack."""
        merged = dict(self._attr)
        if attr:
            merged.update(attr)
        return merged

    def __enter__(self):
        _stack().append(self)
        return self

    def __exit__(self, *exc):
        _stack().pop()


def current_attrs(attr=None):
    """Attrs from all active scopes (outer to inner) merged with `attr`."""
    merged = {}
    for scope in _stack():
        merged.update(scope._attr)
    if attr:
        merged.update(attr)
    return merged
