"""KVStore: gradient aggregation + parameter broadcast.

Ref: src/kvstore/ (KVStoreLocal kvstore_local.h:226, CommDevice comm.h:451,
KVStoreDist kvstore_dist.h:44) and python/mxnet/kvstore/kvstore.py.

TPU-native design: there are no parameter-server processes and no NCCL —
reduction across local device copies happens on-device (jax arrays summed;
XLA emits ICI all-reduce when arrays are sharded over a Mesh), and
cross-host reduction rides `jax.distributed` + global-device collectives.
The `local`/`device`/`dist_sync`/`dist_device_sync`/`dist_async` type names
are preserved so reference scripts run unchanged; `dist_async`'s PS
semantics collapse to sync allreduce (documented capability difference,
SURVEY §2.5).
"""
from __future__ import annotations

import pickle

import jax

from ..base import MXNetError, telem_flags as _telem
from ..ndarray.ndarray import NDArray
from .. import optimizer as opt
from .base import KVStoreBase


def _nbytes(arr):
    d = arr._data
    return int(d.size) * d.dtype.itemsize


def _telem_push(k, vlist):
    from .. import telemetry
    telemetry.inc('mxnet_tpu_kvstore_push_total', key=str(k))
    telemetry.counter('mxnet_tpu_kvstore_push_bytes_total').inc(
        sum(_nbytes(v) for v in vlist), key=str(k))


def _telem_pull(k, outs):
    from .. import telemetry
    telemetry.inc('mxnet_tpu_kvstore_pull_total', key=str(k))
    telemetry.counter('mxnet_tpu_kvstore_pull_bytes_total').inc(
        sum(_nbytes(o) for o in outs), key=str(k))


class KVStore(KVStoreBase):
    """In-process store covering 'local' and 'device' modes."""

    def __init__(self, kv_type='local'):
        self._type = kv_type
        self._store = {}
        self._updater = None
        self._optimizer = None
        self._update_on_kvstore = None
        self._compression = None

    # --- classic API (ref: include/mxnet/kvstore.h:59) ---------------------
    def init(self, key, value):
        keys, values = _key_value(key, value)
        for k, v in zip(keys, values):
            self._store[k] = v.copy() if isinstance(v, NDArray) else v

    def push(self, key, value, priority=0):
        keys, values = _key_value(key, value)
        for k, vlist in _group(keys, values):
            if _telem['on']:
                _telem_push(k, vlist)
            merged = _reduce(vlist)
            if self._compression is not None:
                merged = self._compression.compress_decompress(merged, k)
            if self._updater is not None:
                if k not in self._store:
                    raise MXNetError(f"key {k} not initialized")
                self._updater(_updater_key(k), merged, self._store[k])
            else:
                self._store[k] = merged

    def pull(self, key, out=None, priority=0, ignore_sparse=True):
        keys, outs = _key_value(key, out)
        for k, o in zip(keys, outs):
            if k not in self._store:
                raise MXNetError(f"key {k} not initialized")
            src = self._store[k]
            dsts = o if isinstance(o, (list, tuple)) else [o]
            if _telem['on']:
                _telem_pull(k, dsts)
            for dst in dsts:
                dst._data = jax.device_put(src._data,
                                           list(dst._data.devices())[0])

    def pushpull(self, key, value, out=None, priority=0):
        if _telem['on']:
            from .. import telemetry
            telemetry.inc('mxnet_tpu_kvstore_pushpull_total')
        self.push(key, value, priority)
        if out is not None:
            self.pull(key, out, priority)
        elif self._updater is None:
            # pure allreduce mode: write reduced value back into inputs
            keys, values = _key_value(key, value)
            for k, vlist in _group(keys, values):
                merged = self._store[k]
                for v in vlist:
                    v._data = merged._data

    def broadcast(self, key, value, out, priority=0):
        self.init(key, value)
        self.pull(key, out, priority)

    def row_sparse_pull(self, key, out=None, priority=0, row_ids=None):
        from ..ndarray import sparse as sp
        keys, outs = _key_value(key, out)
        row_keys, rows = _key_value(key, row_ids)
        for k, o, rid in zip(keys, outs, rows):
            if k not in self._store:
                raise MXNetError(f"key {k} not initialized")
            full = self._store[k]
            for dst, r in zip((o if isinstance(o, (list, tuple)) else [o]),
                              (rid if isinstance(rid, (list, tuple)) else [rid])):
                retained = sp.retain(full, r)
                dst._data = retained._data

    # --- updater / optimizer ----------------------------------------------
    def set_updater(self, updater):
        self._updater = updater

    _set_updater = set_updater

    def set_optimizer(self, optimizer):
        self._optimizer = optimizer
        self._updater = opt.get_updater(optimizer)

    def set_gradient_compression(self, compression_params):
        """Route pushes through the shared codec set
        (parallel/compression.py): '2bit' (reference absolute-threshold
        semantics by default), 'fp16', 'int8', 'none'. ``block_size``
        opts into per-block scales (the sharded-step default)."""
        from .gradient_compression import GradientCompression
        ctype = compression_params.get('type', '2bit')
        threshold = compression_params.get('threshold', 0.5)
        block = compression_params.get('block_size', 0)
        self._compression = GradientCompression(ctype, threshold, block)

    # --- distributed attributes --------------------------------------------
    @property
    def rank(self):
        return 0

    @property
    def num_workers(self):
        return 1

    @property
    def type(self):
        return self._type

    @staticmethod
    def is_capable(capability):
        return capability in ('optimizer',)

    def barrier(self):
        from ..resilience import faults as _faults
        _faults.fire('dist.barrier')
        from ..ndarray import waitall
        waitall()

    def save_optimizer_states(self, fname, dump_optimizer=False):
        if self._updater is None:
            raise MXNetError("no updater/optimizer set")
        with open(fname, 'wb') as f:
            f.write(self._updater.get_states(dump_optimizer))

    def load_optimizer_states(self, fname):
        if self._updater is None:
            raise MXNetError("no updater/optimizer set")
        with open(fname, 'rb') as f:
            self._updater.set_states(f.read())


KVStoreBase.register(KVStore)


class Local(KVStore):
    def __init__(self):
        super().__init__('local')


class Device(KVStore):
    def __init__(self):
        super().__init__('device')


class DistSync(KVStore):
    """Multi-process synchronous store over jax.distributed.

    Ref mapping: KVStoreDist worker + server (kvstore_dist.h:44,
    kvstore_dist_server.h:155) collapse into symmetric workers doing a
    global allreduce — on TPU pods the reduction is an XLA collective over
    ICI/DCN rather than ps-lite ZMQ traffic.
    """

    def __init__(self, kv_type='dist_sync'):
        super().__init__(kv_type)

    def push(self, key, value, priority=0):
        keys, values = _key_value(key, value)
        nproc = jax.process_count()
        if nproc > 1:
            self._check_peers()
        for k, vlist in _group(keys, values):
            if _telem['on']:
                _telem_push(k, vlist)
            merged = _reduce(vlist)
            if self._compression is not None:
                # compress BEFORE the cross-worker exchange — the
                # encoded push payload is what crosses DCN (ref:
                # kvstore_dist.h compresses the worker->server push;
                # the pull side stays full precision)
                merged = self._compression.compress_decompress(merged, k)
            if nproc > 1:
                from jax.experimental import multihost_utils
                summed = multihost_utils.process_allgather(merged._data)
                merged = NDArray(summed.sum(axis=0))
            if self._updater is not None:
                if k not in self._store:
                    raise MXNetError(f"key {k} not initialized")
                self._updater(_updater_key(k), merged, self._store[k])
            else:
                self._store[k] = merged

    @staticmethod
    def _check_peers():
        """Refuse to enter a cross-process reduction once the elastic
        membership layer has declared a peer lost — a collective missing
        a participant wedges forever; PeerLossError is recoverable
        (commit + re-form via resilience.ElasticController)."""
        from ..resilience.elastic import raise_if_peer_lost
        raise_if_peer_lost()

    def barrier(self):
        """Membership-level barrier when the elastic side channel is up
        (a rendezvous that skips lost/left peers instead of wedging),
        device-drain otherwise. The ``dist.barrier`` fault site fires
        exactly once either way (Membership.barrier carries its own)."""
        from ..parallel import dist as _dist
        ms = _dist.membership()
        if ms is not None and jax.process_count() > 1:
            ms.barrier('kvstore')
        else:
            from ..resilience import faults as _faults
            _faults.fire('dist.barrier')
        from ..ndarray import waitall
        waitall()

    @property
    def rank(self):
        return jax.process_index()

    @property
    def num_workers(self):
        return jax.process_count()


class DistDeviceSync(DistSync):
    def __init__(self):
        super().__init__('dist_device_sync')


class DistAsync(DistSync):
    def __init__(self):
        super().__init__('dist_async')


class Horovod(DistSync):
    """API-compat alias: the mesh store already provides allreduce."""

    def __init__(self):
        super().__init__('horovod')


def _updater_key(k):
    try:
        return int(k)
    except (TypeError, ValueError):
        return k


def _key_value(key, value):
    if isinstance(key, (list, tuple)):
        return list(key), list(value)
    return [key], [value]


def _group(keys, values):
    """Group (key, [values...]) preserving order (ref: kvstore_local.h:418)."""
    grouped = {}
    order = []
    for k, v in zip(keys, values):
        if k not in grouped:
            grouped[k] = []
            order.append(k)
        if isinstance(v, (list, tuple)):
            grouped[k].extend(v)
        else:
            grouped[k].append(v)
    return [(k, grouped[k]) for k in order]


def _reduce(vlist):
    """Sum device copies on the first copy's device (ref:
    CommDevice::Reduce, src/kvstore/comm.h:451 — gather-to-one then sum)."""
    from ..resilience import faults as _faults
    _faults.fire('collective.all_reduce')
    if len(vlist) == 1:
        return NDArray(vlist[0]._data)
    dev = list(vlist[0]._data.devices())[0]
    acc = vlist[0]._data
    for v in vlist[1:]:
        acc = acc + jax.device_put(v._data, dev)
    return NDArray(acc)


_TYPES = {
    'local': Local,
    'local_allreduce_cpu': Local,
    'local_allreduce_device': Device,
    'device': Device,
    'nccl': Device,            # NCCL mode maps to on-device reduction
    'dist_sync': DistSync,
    'dist_sync_device': DistDeviceSync,
    'dist_device_sync': DistDeviceSync,
    'dist_async': DistAsync,
    'dist': DistSync,
    'horovod': Horovod,
}


def create(name='local'):
    """Create a KVStore (ref: src/kvstore/kvstore.cc:41-84)."""
    if not isinstance(name, str):
        raise MXNetError("name must be a string")
    key = name.lower()
    if key not in _TYPES:
        raise MXNetError(f"unknown kvstore type {name!r}")
    return _TYPES[key]()
