"""2-bit gradient compression with error-feedback residual.

Ref: src/kvstore/gradient_compression.h:52-121 — quantize to {-threshold, 0,
+threshold} with residual accumulation. On TPU this runs as a fused XLA
elementwise pass over the gradient; it models exactly the reference's math
(compute_expected_2bit_quantization in tests/python/unittest/test_kvstore.py).
"""
from __future__ import annotations

import jax.numpy as jnp

from ..base import MXNetError
from ..ndarray.ndarray import NDArray


class GradientCompression:
    def __init__(self, ctype='2bit', threshold=0.5):
        if ctype not in ('none', '2bit'):
            # explicit rejection, not a bare assert: user scripts pass
            # e.g. type='fp16' (a later reference addition) and must get
            # an actionable error instead of an AssertionError
            raise MXNetError(
                f"gradient compression type {ctype!r} is not supported "
                f"(supported: 'none', '2bit'). The reference's fp16 "
                f"compression has no TPU-path implementation here.")
        self.type = ctype
        self.threshold = float(threshold)
        self._residual = {}

    def get_params(self):
        return {'type': self.type, 'threshold': self.threshold}

    def compress_decompress(self, grad: NDArray, key) -> NDArray:
        if self.type == 'none':
            return grad
        r = self._residual.get(key)
        g = grad._data.astype(jnp.float32)
        if r is None:
            r = jnp.zeros_like(g)
        acc = r + g
        t = self.threshold
        q = jnp.where(acc >= t, t, jnp.where(acc <= -t, -t, 0.0))
        self._residual[key] = acc - q
        return NDArray(q.astype(grad._data.dtype))
