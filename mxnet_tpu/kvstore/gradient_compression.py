"""2-bit gradient compression with error-feedback residual.

Ref: src/kvstore/gradient_compression.h:52-121 — quantize to {-threshold, 0,
+threshold} with residual accumulation. On TPU this runs as a fused XLA
elementwise pass over the gradient; it models exactly the reference's math
(compute_expected_2bit_quantization in tests/python/unittest/test_kvstore.py).
"""
from __future__ import annotations

import jax.numpy as jnp

from ..ndarray.ndarray import NDArray


class GradientCompression:
    def __init__(self, ctype='2bit', threshold=0.5):
        assert ctype in ('none', '2bit')
        self.type = ctype
        self.threshold = float(threshold)
        self._residual = {}

    def get_params(self):
        return {'type': self.type, 'threshold': self.threshold}

    def compress_decompress(self, grad: NDArray, key) -> NDArray:
        if self.type == 'none':
            return grad
        r = self._residual.get(key)
        g = grad._data.astype(jnp.float32)
        if r is None:
            r = jnp.zeros_like(g)
        acc = r + g
        t = self.threshold
        q = jnp.where(acc >= t, t, jnp.where(acc <= -t, -t, 0.0))
        self._residual[key] = acc - q
        return NDArray(q.astype(grad._data.dtype))
