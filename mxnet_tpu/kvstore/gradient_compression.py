"""Gradient compression with error-feedback residual (kvstore path).

Ref: src/kvstore/gradient_compression.h:52-121 — quantize to {-threshold,
0, +threshold} with residual accumulation. On TPU this runs as a fused
XLA elementwise pass over the gradient; it models exactly the
reference's math (compute_expected_2bit_quantization in
tests/python/unittest/test_kvstore.py).

The codecs themselves live in ``parallel/compression.py`` and are
SHARED with the GSPMD sharded-step epilogue
(``ShardedTrainStep(compression_params=...)``), so
``kvstore.set_gradient_compression`` routes to the same quantizers:
``2bit`` (absolute threshold here — ``block_size=0`` default preserves
the reference semantics), plus ``fp16`` and ``int8`` (per-block scale).
"""
from __future__ import annotations

import jax.numpy as jnp

from ..base import MXNetError
from ..ndarray.ndarray import NDArray
from ..parallel import compression as _codecs


class GradientCompression:
    def __init__(self, ctype='2bit', threshold=0.5, block_size=None):
        # ONE validator (codec names, threshold > 0, block >= 0):
        # parallel/compression.resolve — user scripts passing arbitrary
        # strings or a negative block get an actionable MXNetError here
        # instead of an opaque reshape failure mid-training.
        # block_size=0 (the kvstore default) keeps the reference's
        # ABSOLUTE-threshold 2bit semantics / per-tensor int8 scale;
        # pass a positive block for the per-block-scale variants the
        # sharded step uses.
        spec = _codecs.resolve({'type': ctype, 'threshold': threshold,
                                'block_size': int(block_size or 0)})
        if spec is None:
            self.type, self.threshold, self.block = 'none', \
                float(threshold), 0
        else:
            self.type = spec['type']
            self.threshold = spec['threshold']
            self.block = spec['block']
        self._residual = {}

    def get_params(self):
        return {'type': self.type, 'threshold': self.threshold,
                'block_size': self.block}

    def wire_bytes(self, shape):
        """Analytic encoded bytes of one pushed gradient (the
        ``mxnet_tpu_comm_compressed_bytes_total`` unit)."""
        return _codecs.wire_bytes(tuple(shape), self.type, self.block)

    def compress_decompress(self, grad: NDArray, key) -> NDArray:
        """Error-feedback round trip of one push: quantize
        ``grad + residual[key]``, carry the quantization error forward,
        return the decoded value the pull side would see."""
        if self.type == 'none':
            return grad
        g = grad._data.astype(jnp.float32)
        r = self._residual.get(key)
        if r is None:
            r = jnp.zeros_like(g)
        acc = r + g
        q = _codecs.encode_decode(acc, self.type, self.threshold,
                                  self.block)
        # residual writeback GATED on finiteness (on device, no host
        # sync): a transient Inf/NaN gradient propagates through the
        # decoded value — so the caller's guard / AMP loss scaler still
        # sees and skips it — but must never outlive that push in the
        # carried error state, or every later step decodes NaN and
        # training wedges permanently (same contract as the pjit step's
        # where-gated residual writeback).
        self._residual[key] = jnp.where(jnp.all(jnp.isfinite(acc)),
                                        acc - q, r)
        return NDArray(q.astype(grad._data.dtype))

    def reset(self):
        """Drop the carried residuals (deterministic reseed — e.g.
        after a checkpoint restore rewinds the weights, the old error
        state no longer describes the current trajectory)."""
        self._residual.clear()
