"""KVStoreBase plugin ABC (ref: python/mxnet/kvstore/base.py:74,220).

The reference allows alternative distributed backends (Horovod) to register
behind this interface; here the mesh/XLA-collective store registers the
same way, so `gluon.Trainer` is backend-agnostic.
"""
from __future__ import annotations

from ..base import MXNetError

_STORES = {}


class KVStoreBase:
    """Abstract key-value store interface."""

    def broadcast(self, key, value, out, priority=0):
        raise NotImplementedError

    def pushpull(self, key, value, out=None, priority=0):
        raise NotImplementedError

    def set_optimizer(self, optimizer):
        raise NotImplementedError

    @staticmethod
    def is_capable(capability):
        raise NotImplementedError

    def save_optimizer_states(self, fname, dump_optimizer=False):
        raise NotImplementedError

    def load_optimizer_states(self, fname):
        raise NotImplementedError

    @property
    def type(self):
        raise NotImplementedError

    @property
    def rank(self):
        raise NotImplementedError

    @property
    def num_workers(self):
        raise NotImplementedError

    OPTIMIZER = 'optimizer'

    @staticmethod
    def register(klass):
        """Register a KVStore backend (ref: base.py:220)."""
        name = klass.__name__.lower()
        _STORES[name] = klass
        return klass


def get_kvstore_class(name):
    key = name.lower()
    if key not in _STORES:
        raise MXNetError(f"unknown kvstore type {name!r}; registered: {sorted(_STORES)}")
    return _STORES[key]
