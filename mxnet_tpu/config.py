"""Environment-variable configuration tier (ref: docs/faq/env_var.md +
the dmlc GetEnv calls spread through src/).

The reference configures its runtime through ~60 documented MXNET_* env
vars read at first use. This module is the TPU-native registry: every
supported variable is declared once with a type, default and help string;
`config.get('MXNET_...')` reads the process environment through that
declaration, `describe()` prints the documented surface, and variables
whose CUDA-era meaning has no TPU analog are declared `inert=True` so
user scripts that set them keep working while `describe()` says why they
do nothing here (XLA owns scheduling/memory/fusion).
"""
from __future__ import annotations

import os
from typing import Any, Callable, Dict, NamedTuple, Optional

from .base import MXNetError

__all__ = ['EnvVar', 'register', 'get', 'set_env', 'describe', 'list_vars']


class EnvVar(NamedTuple):
    name: str
    type: Callable
    default: Any
    help: str
    inert: bool = False     # accepted but a no-op on TPU (documented why)


_REGISTRY: Dict[str, EnvVar] = {}


def register(name, type_, default, help_, inert=False):
    _REGISTRY[name] = EnvVar(name, type_, default, help_, inert)
    return _REGISTRY[name]


def _bool(s):
    return str(s).lower() not in ('0', 'false', 'off', '', 'no', 'n',
                                  'none', 'disabled')


def get(name, default=None):
    """Typed value of a declared env var (process env > declared default >
    `default`)."""
    var = _REGISTRY.get(name)
    if var is None:
        raise MXNetError(
            f"unknown config variable {name!r}; see "
            f"mxnet_tpu.config.list_vars()")
    raw = os.environ.get(name)
    if raw is None:
        return var.default if default is None else default
    try:
        return var.type(raw)
    except (TypeError, ValueError) as e:
        raise MXNetError(
            f"{name}={raw!r} is not a valid {var.type.__name__}") from e


def set_env(name, value):
    """Set a declared variable in the process environment (takes effect at
    the next read — matching the reference's read-at-first-use rule)."""
    if name not in _REGISTRY:
        raise MXNetError(f"unknown config variable {name!r}")
    os.environ[name] = str(value)


def list_vars():
    return sorted(_REGISTRY)


def describe(name: Optional[str] = None):
    """Documentation string for one or all declared variables."""
    names = [name] if name else list_vars()
    lines = []
    for n in names:
        v = _REGISTRY[n]
        cur = os.environ.get(n)
        tag = ' [inert on TPU]' if v.inert else ''
        lines.append(f"{v.name} (type={v.type.__name__}, "
                     f"default={v.default!r}"
                     + (f", set={cur!r}" if cur is not None else '')
                     + f"){tag}\n    {v.help}")
    return '\n'.join(lines)


# ---------------------------------------------------------------------------
# the supported surface
# ---------------------------------------------------------------------------

register('MXNET_HOME', str,
         os.path.join(os.path.expanduser('~'), '.mxnet'),
         'Data directory: model-store cache, datasets.')
register('MXNET_GLUON_REPO', str,
         'https://apache-mxnet.s3-accelerate.dualstack.amazonaws.com/',
         'Base URL (or local directory) for pretrained model downloads.')
register('MXNET_TEST_DEVICE', str, 'cpu',
         'Device used by test_utils.default_context().')
register('MXNET_STORAGE_FALLBACK_LOG_VERBOSE', _bool, True,
         'Log when a sparse op falls back to the dense implementation.')
register('MXNET_ENFORCE_DETERMINISM', _bool, False,
         'Restrict ops to deterministic algorithms. XLA on TPU is '
         'deterministic by default; this additionally pins the framework '
         'RNG seeding of data iterators.')
register('MXNET_SAFE_ACCUMULATION', _bool, True,
         'Accumulate reductions of low-precision inputs in float32 '
         '(layer norm / softmax statistics already do this on TPU).')
register('MXNET_TPU_JAX_TRACE_DIR', str, '',
         'Directory for the XLA device trace started by profiler.start().')
register('MXNET_PROFILER_AUTOSTART', _bool, False,
         'Start the profiler at import time.')
register('MXNET_KVSTORE_BIGARRAY_BOUND', int, 1000000,
         'Arrays above this element count use sharded collectives in the '
         'kvstore reduce path.')
register('MXNET_KVSTORE_USETREE', _bool, False,
         'Reference: tree reduction for multi-GPU. Collective layout on '
         'TPU is chosen by XLA over the ICI topology.', inert=True)
register('MXNET_ENABLE_GPU_P2P', _bool, True,
         'Reference: CUDA peer-to-peer. ICI links are always direct.',
         inert=True)
register('MXNET_ENGINE_TYPE', str, 'ThreadedEnginePerDevice',
         'Reference: dependency-engine selection. The XLA async runtime '
         'is the engine on TPU; accepted for script compatibility.',
         inert=True)
register('MXNET_EXEC_BULK_EXEC_TRAIN', _bool, True,
         'Reference: bulk execution of the graph. jit compilation '
         'subsumes it.', inert=True)
register('MXNET_EXEC_BULK_EXEC_INFERENCE', _bool, True,
         'Reference: bulk execution for inference. jit subsumes it.',
         inert=True)
register('MXNET_EXEC_ENABLE_INPLACE', _bool, True,
         'Reference: in-place graph optimization. XLA buffer donation '
         'subsumes it.', inert=True)
register('MXNET_GPU_MEM_POOL_TYPE', str, 'Naive',
         'Reference: CUDA memory pool strategy. Device memory on TPU is '
         'owned by PJRT/XLA.', inert=True)
register('MXNET_GPU_MEM_POOL_RESERVE', int, 5,
         'Reference: CUDA pool reserve percentage. PJRT-owned on TPU.',
         inert=True)
register('MXNET_CPU_WORKER_NTHREADS', int, 1,
         'Reference: CPU op worker threads. XLA:CPU threadpools are '
         'sized automatically.', inert=True)
register('MXNET_OMP_MAX_THREADS', int, 0,
         'Reference: OpenMP cap. XLA-managed on this stack.', inert=True)
register('MXNET_CUDNN_AUTOTUNE_DEFAULT', int, 1,
         'Reference: cuDNN autotuning. The XLA TPU compiler autotunes '
         'during compilation.', inert=True)
register('MXNET_ENABLE_OPERATOR_TUNING', int, 1,
         'Reference: CPU op tuning. XLA-managed.', inert=True)
register('MXNET_MEMORY_OPT', int, 0,
         'Reference: memory-optimization pass. Use jax.checkpoint / '
         'remat policies instead.', inert=True)
register('MXNET_SUBGRAPH_BACKEND', str, '',
         'Default subgraph partitioner applied by hybridize() when the '
         'call does not name one (see mxnet_tpu.subgraph).')
register('MXNET_SEED', int, 0,
         'Process-wide RNG seed applied at import when set.')
register('MXNET_TPU_COORDINATOR', str, '',
         'host:port of process 0 for multi-process init '
         '(parallel.dist.init / start_membership). Empty: fall back to '
         'the DMLC_PS_ROOT_URI/_PORT drop-in names, then '
         'localhost:12345 with a warning.')
register('MXNET_TPU_NUM_PROCS', int, 0,
         'Total process count for multi-process init. 0 (default): '
         'fall back to DMLC_NUM_WORKER, then single-process.')
register('MXNET_TPU_PROC_ID', int, -1,
         "This process's rank for multi-process init. -1 (default): "
         'fall back to DMLC_WORKER_ID, then 0.')
register('MXNET_TPU_IO_TRANSPORT', str, 'u8',
         "ImageRecordIter host->device transport: 'u8' moves raw uint8 "
         'NHWC and normalizes on device in one cached jitted program '
         "(~4x fewer host bytes); 'f32' materializes normalized "
         'float32 on the host (legacy path).')
register('MXNET_TPU_IO_DECODE_CACHE_MB', float, 256.0,
         'Byte budget (MB) of the cross-epoch decode cache: decoded + '
         'short-side-resized images reused across epochs (crop/mirror/'
         'normalize stay per-epoch). 0 disables the cache.')
register('MXNET_TPU_FUSED_DEBUG', _bool, False,
         "Print the traceback when an optimizer's update() fails to "
         'trace into the fused jitted update (the Trainer then falls '
         'back to the eager per-parameter loop with a warning).')
register('MXTPU_PALLAS_LN', _bool, False,
         'Route the transformer residual+LN epilogue through the fused '
         'Pallas kernel (ops/pallas_layernorm.py) when a TPU is '
         'present and the hidden dim is a multiple of 128. Default: '
         'the XLA path (flag-gated until measured on-chip).')
register('MXNET_TPU_MNIST_DIR', str, '',
         'Directory holding the MNIST idx files for '
         'test_utils.get_mnist(). Empty: a deterministic synthetic '
         'set (zero-egress environments cannot download).')
register('MXNET_TPU_NO_NATIVE_BUILD', _bool, False,
         'Never compile the native IO library on demand: missing '
         'prebuilt .so means the pure-Python pipeline fallback.')
register('MXNET_TPU_TELEMETRY', _bool, False,
         'Enable the runtime telemetry registry (mxnet_tpu.telemetry): '
         'op-dispatch/compile/kvstore/IO/step metrics with Prometheus, '
         'JSON and chrome-trace export. Off: instrumented paths take a '
         'single flag-check fast path.')
register('MXTPU_TRACE', _bool, False,
         'Enable step-level span tracing (mxnet_tpu.telemetry.trace): '
         'nested chrome-trace B/E spans over the step lifecycle (io, '
         'h2d, dispatch, collectives, optimizer, checkpoint) in '
         'lock-free per-thread ring buffers, plus the crash-time '
         'flight recorder. Off: every span site takes a single '
         'flag-check fast path and allocates nothing.')
register('MXTPU_TRACE_RING', int, 16384,
         'Span-trace ring capacity in events PER THREAD. A full ring '
         'overwrites its oldest events (dropped whole spans are '
         'counted in mxnet_tpu_trace_dropped_spans_total).')
register('MXTPU_FLIGHT_STEPS', int, 64,
         'Flight recorder depth: per-step span summaries (+ loss and '
         'guard flags) retained for the crash-time dump.')
register('MXTPU_FLIGHT_DIR', str, '',
         'Directory for flight-recorder post-mortem dumps '
         '(mxtpu_flight-<pid>.json). Empty (default): the system temp '
         'directory — the recorder never litters the CWD. Ignored when '
         'MXTPU_FLIGHT_PATH names an explicit file.')
register('MXTPU_FLIGHT_PATH', str, '',
         'Explicit path of the flight-recorder post-mortem JSON '
         '(watchdog stall, guard rollback, atexit/fatal-signal hook). '
         'Empty (default): MXTPU_FLIGHT_DIR/mxtpu_flight-<pid>.json.')
register('MXNET_TPU_RECOMPILE_WARN_THRESHOLD', int, 3,
         'Telemetry recompile detector: warn (once per compile site) '
         'when one site, e.g. a hybridized block, compiles more than '
         'this many times — churning input shapes/dtypes force an XLA '
         'recompile every step.')
register('MXTPU_FAULT', str, '',
         'Arm deterministic fault injection: comma-separated '
         'site:kind[:prob[:seed[:first-last]]] specs (kinds: raise, '
         'hang, corrupt, nan). See mxnet_tpu.resilience.faults.sites() '
         'for the registered sites. Read once at import; re-arm with '
         'resilience.faults.arm_from_env().')
register('MXTPU_FAULT_HANG_SECONDS', float, 300.0,
         'How long an armed "hang" fault sleeps at its site (long '
         'enough to trip the step watchdog, short enough for tests).')
register('MXTPU_GUARD_MAX_BAD_STEPS', int, 3,
         'NonFiniteGuard policy ladder: after this many CONSECUTIVE '
         'non-finite steps (each already skipped on device), '
         'auto-restore the newest committed checkpoint.')
register('MXTPU_WATCHDOG_SECONDS', float, 300.0,
         'StepWatchdog default deadline: with no training-step '
         'heartbeat for this long, dump all-thread stacks + a telemetry '
         'snapshot to the log (once per stall).')
register('MXTPU_CHECKPOINT_WRITE_RETRIES', int, 2,
         'Bounded retries (with backoff) of a checkpoint payload write '
         'after a transient filesystem error before the failure '
         'surfaces on the training thread.')
register('MXTPU_DATALOADER_WORKER_RETRIES', int, 2,
         'Bounded re-submissions of a gluon DataLoader batch fetch '
         'after a worker crash before a clear error is raised.')
register('MXNET_TPU_IO_CORRUPT_POLICY', str, 'error',
         "What ImageRecordIter does with a corrupt/truncated record "
         "mid-epoch: 'error' raises DataError naming the record index "
         "and file offset; 'skip' substitutes the next good record and "
         "counts mxnet_tpu_io_corrupt_records_total.")
register('MXTPU_ELASTIC', _bool, False,
         'Enable the elastic-training membership layer: dist.init() '
         'starts the rank-0 heartbeat coordinator and a per-process '
         'heartbeat sender on a side-channel TCP socket (never the ICI '
         'collectives), so peer loss is detectable while a collective '
         'is wedged. Pairs with resilience.ElasticController for the '
         'commit -> re-form -> resume path.')
register('MXTPU_ELASTIC_PORT', int, 0,
         'TCP port of the elastic membership side channel on the '
         'coordinator host. 0 (default) derives jax-coordinator port '
         '+ 1000 so launch.py-style multi-job hosts do not collide.')
register('MXTPU_HEARTBEAT_SECONDS', float, 1.0,
         'Elastic membership heartbeat period. Each process beats the '
         'rank-0 coordinator this often over the side channel '
         '(piggybacking its last completed step).')
register('MXTPU_PEER_DEADLINE_SECONDS', float, 10.0,
         'Elastic membership peer deadline: a peer whose last heartbeat '
         'is older than this is declared LOST — the survivors commit a '
         'checkpoint, re-form the mesh at the new world size and '
         'resume. Also the window after which a worker that cannot '
         'reach the coordinator considers the coordinator itself lost.')
register('MXTPU_DIST_INIT_RETRIES', int, 3,
         'Bounded retries (exponential backoff) of '
         'jax.distributed.initialize in dist.init() — workers that '
         'start before the coordinator is listening see a transient '
         'connection error, not a fatal one.')
register('MXTPU_BARRIER_TIMEOUT_SECONDS', float, 60.0,
         'Timeout of the elastic membership barrier (dist.barrier): '
         'how long a rank waits for every live peer to arrive at the '
         'same tag before raising.')
register('MXTPU_JOIN_TIMEOUT_SECONDS', float, 120.0,
         'Timeout of the elastic scale-up admission rendezvous: how '
         'long a joiner (after its JOIN announcement) and the '
         'quiesced survivors wait for each other at the admit barrier '
         'before the admission is abandoned. Also bounds how long an '
         'unadmitted JOIN announcement survives on the coordinator '
         'without joiner heartbeats.')
register('MXTPU_AUTOSCALE_COOLDOWN_SECONDS', float, 30.0,
         'Autoscaler hysteresis: minimum spacing between decisions of '
         'the same kind (per rank for evicts, global for capacity '
         'requests) so one noisy detector window cannot thrash the '
         'fleet.')
register('MXTPU_AUTOSCALE_STRIKES', int, 3,
         'Autoscaler hysteresis: a FleetMonitor detector flag '
         '(chronic straggler, memory imbalance, step regression) must '
         'persist for this many CONSECUTIVE observe() polls before it '
         'escalates to an evict/request-capacity decision; a cleared '
         'flag resets the count.')
register('MXTPU_AUTOSCALE_MAX_WORLD', int, 0,
         'Upper bound on the world size the autoscaler will request '
         'capacity toward (its target is clamped to this). 0 '
         '(default): unbounded — the target is the nominal world '
         'observed at the first poll.')
register('MXTPU_CHECKPOINT_REPLICAS', int, 1,
         'Checkpoint survivability: how many PEER hosts each committed '
         'checkpoint step is replicated to over the membership side '
         'channel (ring order over the live ranks). 0 disables '
         'replication. Replication runs entirely off the training '
         'thread — a dead or slow peer can never stall a commit.')
register('MXTPU_REPLICA_PORT_BASE', int, 0,
         'Base TCP port of the per-rank checkpoint replica servers '
         '(rank r listens on base + r). 0 (default) derives the elastic '
         'side-channel port + 100, so parallel jobs on one host do not '
         'collide.')
register('MXTPU_REPLICA_BANDWIDTH_MBPS', float, 0.0,
         'Cap on checkpoint replication transfer bandwidth in MB/s '
         '(paced per chunk on the sending side, so a replication push '
         'never saturates the NIC a training job shares). 0 (default) '
         'is uncapped.')
register('MXTPU_REPLICA_TIMEOUT_SECONDS', float, 10.0,
         'Socket timeout of every replica-transport op (file_put / '
         'file_get / inventory / commit / delete). Bounds how long a '
         'dead peer can hold a replication worker or a replica-restore '
         'fetch — never the training thread.')
register('MXTPU_COMPRESSION', str, '',
         "Error-feedback gradient compression codec of the GSPMD "
         "sharded step when no explicit compression_params are given: "
         "'' or 'none' (off, the default), 'fp16' (truncate, 2x wire "
         "shrink), 'int8' (per-block scale, ~3.9x) or '2bit' (the "
         "reference kvstore's sign+threshold quantizer, ~15x). The "
         "quantization residual is carried per-param as sharded "
         "optimizer-side state, so the error is re-offered next step "
         "instead of lost.")
register('MXTPU_COMPRESSION_THRESHOLD', float, 0.5,
         "2-bit gradient compression threshold (the reference's "
         "pos_threshold/neg_threshold magnitude): values quantize to "
         "{-t*s, 0, +t*s} against the per-block scale s (s=1 when the "
         "block knob is 0 — absolute-threshold reference semantics).")
register('MXTPU_COMPRESSION_BLOCK', int, 256,
         'Per-block scale granularity (elements along the last dim) of '
         'the int8/2bit gradient codecs. 0: one per-tensor scale '
         '(2bit then uses the absolute threshold with no wire '
         'overhead). Each block adds one fp32 scale to the encoded '
         'payload.')
register('MXTPU_HIERARCHICAL_DP', int, 0,
         'Hierarchy-aware decomposition of the dp axis into (cross-'
         'host, intra-host) sub-axes: ZeRO shards and param '
         'all-gathers then stay on the fast intra-host ICI hop and '
         'only the (compressible) gradient exchange crosses the slow '
         'DCN hop. 0 (default): auto-detect host groups from the '
         'device->process topology; 1: force flat (single hop); N>=2: '
         'force N equal host groups (CPU simulation / drills).')
register('MXTPU_METRICS_PORT', int, 0,
         'Base TCP port of the per-process observability endpoint '
         '(telemetry.server): rank r serves GET /metrics (Prometheus '
         'exposition), /healthz (membership view + stall verdict + '
         'last committed step) and /flight (on-demand flight-recorder '
         'dump) on base + r. 0 (default): no server — the step path is '
         'untouched. The server binds localhost-only unless '
         'MXTPU_METRICS_BIND says otherwise, never touches the ICI '
         'collectives, and answers with bounded handler threads.')
register('MXTPU_METRICS_BIND', str, '127.0.0.1',
         'Bind address of the observability endpoint. The default '
         'stays loopback-only; set 0.0.0.0 deliberately when a fleet '
         'scraper lives off-host.')
register('MXTPU_FLEET_WINDOW', int, 32,
         'Rolling window (snapshots per rank) the fleet anomaly '
         'detectors baseline over: step-time regression and loss-spike '
         'statistics are computed against this many recent snapshots.')
register('MXTPU_FLEET_REGRESSION_FACTOR', float, 2.0,
         "Fleet detector: a rank's step wall time above this multiple "
         'of its own rolling baseline is flagged as a step-time '
         'regression (flight note fleet.step_regression).')
register('MXTPU_FLEET_STRAGGLER_FACTOR', float, 1.5,
         "Fleet detector: a rank's step wall time above this multiple "
         'of the fleet median is flagged as a straggler (flight note '
         'fleet.straggler; the watchdog verdict names the rank).')
register('MXTPU_FLEET_STALE_SECONDS', float, 0.0,
         'Fleet detector: a rank whose newest telemetry snapshot is '
         'older than this is flagged as stale/straggling even if its '
         'last reported step time was healthy. 0 (default): 3x the '
         'membership heartbeat period.')
register('MXTPU_FLEET_LOSS_SPIKE_SIGMA', float, 6.0,
         'Fleet detector: a reported loss above the rolling mean plus '
         'this many rolling standard deviations (window '
         'MXTPU_FLEET_WINDOW, minimum 8 samples) is flagged as a loss '
         'spike (flight note fleet.loss_spike).')
register('MXTPU_FLEET_IMBALANCE_FACTOR', float, 1.5,
         'Fleet detector: max/min ratio of per-rank comm bytes per '
         'step above this is flagged as a collective imbalance '
         '(flight note fleet.comm_imbalance).')
register('MXTPU_MEMORY', _bool, False,
         'Enable memory watermark sampling (telemetry.memory): per-step '
         'live/peak device-memory samples — jax device.memory_stats() '
         'where the backend exposes it, else the deterministic fallback '
         'summing per-device bytes over the tracked live arrays (params, '
         'masters, moments, residuals, device-prefetch leases) — plus '
         'host RSS, into a bounded ring, mxnet_tpu_memory_* gauges, the '
         'flight-recorder step records and the fleet snapshots. Off: '
         'the per-step hook is one dict check and allocates nothing. '
         'The OOM forensics guard is always armed regardless.')
register('MXTPU_MEMORY_RING', int, 256,
         'Watermark ring depth: memory samples retained for the OOM '
         'post-mortem and /healthz (bounded; oldest overwritten).')
register('MXTPU_MEMORY_EVERY', int, 1,
         'Memory sampling cadence: record one watermark sample every '
         'this many steps (1 = every step). Raise it when the fallback '
         'pool walk over very large parameter sets is measurable.')
register('MXTPU_MEMORY_LEAK_STEPS', int, 8,
         'Leak detector: this many CONSECUTIVE samples of monotonic '
         'live-bytes growth (see MXTPU_MEMORY_LEAK_BYTES) latch one '
         'memory.leak_suspected flight note; a non-growing sample '
         'clears the latch.')
register('MXTPU_MEMORY_LEAK_BYTES', int, 1 << 20,
         'Leak detector: minimum total live-bytes growth over the '
         'MXTPU_MEMORY_LEAK_STEPS window before the latch fires (1 MB '
         'default — step-to-step allocator noise must not page anyone).')
register('MXTPU_FLEET_MEMORY_IMBALANCE_FACTOR', float, 1.5,
         'Fleet detector: max/min ratio of per-rank live device memory '
         '(from the heartbeat-piggybacked memory snapshots) above this '
         'is flagged as an HBM imbalance on the fattest rank (flight '
         'note fleet.memory_imbalance).')
register('MXTPU_SCRUB_SECONDS', float, 300.0,
         'Background checkpoint scrubber cadence: every this many '
         'seconds the scrubber re-hashes one pass over the committed '
         'local steps and hosted peer replicas, quarantines mismatches '
         'and repairs them from a healthy replica. 0 disables the '
         'scrubber thread (scrub_once() remains callable).')


def _zero_stage(s):
    """MXTPU_ZERO value -> ZeRO stage int: 0/off/false -> 0, 1/on/true
    -> 1, 3 -> 3 (stage 2 has no separate meaning on the GSPMD path —
    grads already reduce-scatter under stage 1)."""
    raw = str(s).strip().lower()
    if raw in ('3',):
        return 3
    if raw in ('1', 'true', 'on', 'yes', 'y', 'enabled'):
        return 1
    if raw in ('0', 'false', 'off', '', 'no', 'n', 'none', 'disabled'):
        return 0
    raise ValueError(f"MXTPU_ZERO={s!r}: expected 0 (off), 1 (sharded "
                     f"optimizer state) or 3 (sharded params + grads + "
                     f"state / FSDP)")


register('MXTPU_ZERO', _zero_stage, 1,
         'ZeRO stage of the sharded update on the GSPMD data-parallel '
         'path. 1 (default whenever a dp axis with >1 devices is '
         'present): gradients reduce-scatter over dp, each device runs '
         'the optimizer on its 1/dp slice of the fp32 masters and '
         'moments, and updated params all-gather back to the compute '
         'dtype — all inside the one pjit step so XLA overlaps the '
         'collectives with backward compute. 3 (ZeRO-3/FSDP): the '
         'persistent params and masters ALSO live 1/dp-sharded; each '
         "layer's params all-gather on first use inside the step "
         '(prefetched one layer ahead), are rematerialized for '
         'backward instead of kept, and grads reduce-scatter straight '
         'into the shard-local update. 0 forces the fully replicated '
         'update.')

register('MXTPU_COMPILE_LEDGER', str, '',
         'Arm the compile ledger (telemetry.compile): every jit/pjit '
         'build site appends a structured signature + trace/lower/'
         'backend-compile timing entry to a bounded in-memory ring and '
         'an on-disk JSONL ledger. Empty (default): disarmed — build '
         'sites take a single flag-check fast path. "1"/"on": ledger '
         'at MXTPU_FLIGHT_DIR/mxtpu_compile_ledger-<pid>.jsonl; any '
         'other value: an explicit ledger path (share one path across '
         'processes to estimate persistent-cache saved-seconds from '
         'prior runs). Validate with tools/check_compile_ledger.py.')
register('MXTPU_COMPILE_CACHE_DIR', str, '',
         'Persistent XLA compilation-cache directory, wired through '
         'jax.config (jax_compilation_cache_dir + the min-entry-size/'
         'min-compile-time gates dropped to zero so every program is '
         'eligible). Warm processes reuse cold-process binaries: '
         'hit/miss/saved-seconds land in mxnet_tpu_compile_persistent_'
         'cache_* counters and the compile ledger. Empty (default): '
         "jax's own defaults (cache off unless configured elsewhere).")

# -- inference serving (mxnet_tpu.serving) ---------------------------------

register('MXTPU_SERVE_BATCH_DEADLINE_MS', float, 5.0,
         'Continuous-batcher formation deadline: a batch dispatches '
         'when its sequence bucket fills to the largest batch bucket '
         'or when its OLDEST request has waited this long, whichever '
         'comes first. 0 dispatches immediately (lowest p50, worst '
         'device efficiency); larger values trade queue latency for '
         'fuller batches.')
register('MXTPU_SERVE_BUCKETS', str, '32,64,128',
         'Sequence-length buckets (comma-separated, ascending). Every '
         'request pads up to the smallest bucket that fits; requests '
         'longer than the largest bucket are rejected with 400. '
         'Together with MXTPU_SERVE_BATCH_BUCKETS this fixes the '
         'compiled-shape universe the warmup pass pre-builds — steady '
         'state never compiles.')
register('MXTPU_SERVE_BATCH_BUCKETS', str, '1,2,4,8',
         'Batch-size buckets (comma-separated, ascending). A formed '
         'batch pads its row count up to the smallest bucket that '
         'fits; the largest bucket is the fill target that dispatches '
         'a batch early.')
register('MXTPU_SERVE_QUEUE_LIMIT', int, 256,
         'Admission bound on total queued predict requests; beyond it '
         'submissions shed with 503 (mxnet_tpu_serving_shed_total, '
         'reason=queue_full) instead of growing an unbounded backlog.')
register('MXTPU_SERVE_PORT', int, 0,
         'Predict-endpoint base port (rank r serves on base + r, the '
         'same collision-avoidance scheme as MXTPU_METRICS_PORT). '
         '0 = serving disarmed.')
register('MXTPU_SERVE_QUANTIZE', str, '',
         "Weight quantization for the predict path: '' (default, "
         "full precision), 'bf16' (cast parameters to bfloat16 — 2x "
         "residency), or 'int8' (snap float weights to the PR 11 "
         "codec's block-scaled int8 value grid — the accuracy of an "
         'int8-weights deployment, stored in float on this backend).')
register('MXTPU_SERVE_MEMORY_LIMIT_MB', float, 0.0,
         'Admission control from memory observability: when live '
         'device bytes (telemetry.memory.health_fields) exceed this, '
         'predicts shed with 503 until pressure clears. 0 = off.')
register('MXTPU_SERVE_WATCHDOG_SECONDS', float, 0.0,
         'Arm a StepWatchdog over the batcher: a dispatch that '
         'produces no completed batch for this long dumps a stall '
         'report (classified COMPILING vs EXECUTING via the compile '
         'window) and notes serving.stuck. 0 = off.')
register('MXTPU_SERVE_EJECT_FAILURES', int, 2,
         'Router ejection threshold: this many CONSECUTIVE failed '
         'predicts (connect refused, 5xx, shed) ejects a replica from '
         'rotation for MXTPU_SERVE_READMIT_SECONDS.')
register('MXTPU_SERVE_READMIT_SECONDS', float, 5.0,
         'How long an ejected replica sits out before the router '
         'probes it back in (the next routed predict is the probe).')
register('MXTPU_SERVE_DRAIN_SECONDS', float, 10.0,
         'Graceful-drain budget: how long a draining replica waits '
         'for in-flight requests to flush before closing.')

# -- kernel autotuning + remat policy (ISSUE 18) ---------------------------

register('MXTPU_FA_G', int, 0,
         'Explicit flash-attention FORWARD head-group size (the G '
         'batch*head slices one kernel invocation processes). Highest '
         'rung of the ops/autotune precedence ladder: env override > '
         'tuning-DB winner > built-in defaults. 0 (default) = unset; '
         'the value is still clamped to a divisor of batch*heads and '
         'to the scoped-VMEM budget.')
register('MXTPU_FA_BQ', int, 0,
         'Explicit flash-attention forward query-sequence block size. '
         '0 = unset (tuning DB, then defaults). Must satisfy the '
         'Mosaic trailing-tile rule (multiple of 8 rows for f32, 16 '
         'for bf16) — autotune.check_candidate validates shapes.')
register('MXTPU_FA_BK', int, 0,
         'Explicit flash-attention forward key-sequence block size. '
         '0 = unset (tuning DB, then defaults).')
register('MXTPU_FA_BWD_G', int, 0,
         'Explicit flash-attention BACKWARD head-group size (the dq '
         'and dk/dv kernels). 0 = unset; same clamps as MXTPU_FA_G.')
register('MXTPU_FA_BWD_BQ', int, 0,
         'Explicit flash-attention backward query block size. '
         '0 = unset (tuning DB, then defaults).')
register('MXTPU_FA_BWD_BK', int, 0,
         'Explicit flash-attention backward key block size. '
         '0 = unset (tuning DB, then defaults).')
register('MXTPU_AUTOTUNE_DIR', str, '',
         'Directory of the kernel-autotuner tuning DB '
         '(mxtpu_autotune.json, atomic JSON keyed by device kind + '
         'kernel + shape signature). When set, _block_sizes consults '
         'the DB winner for each kernel instance (env overrides still '
         'win); populate it with tools/tune_bert_step.py --autotune or '
         'ops.autotune.sweep_flash_attention(). Empty (default): DB '
         'lookups off, built-in defaults apply.')
register('MXTPU_AUTOTUNE_REPS', int, 5,
         'Measured-sweep repetitions per candidate: each surviving '
         'block-shape candidate is AOT-compiled once (compile time '
         'excluded, phases recorded in the compile ledger) and timed '
         'this many times; the median decides the winner.')
register('MXTPU_PALLAS_FFN', _bool, False,
         'Route the BERT FFN1 GELU+bias epilogue through the fused '
         'Pallas matmul kernel (ops/pallas_ffn.py) when a TPU is '
         'present and the hidden/intermediate dims are multiples of '
         '128. Default: the XLA path (flag-gated until measured '
         'on-chip, like MXTPU_PALLAS_LN).')


def _remat_policy(s):
    """MXTPU_REMAT value -> policy name: none (save everything XLA
    wants), layer (save only matmul outputs), aggressive (save nothing
    — recompute the whole forward in backward)."""
    raw = str(s).strip().lower()
    if raw in ('', '0', 'off', 'false', 'no', 'n', 'none', 'disabled'):
        return 'none'
    if raw in ('layer', '1', 'on', 'true', 'yes', 'y'):
        return 'layer'
    if raw in ('aggressive', 'full', '2'):
        return 'aggressive'
    raise ValueError(f"MXTPU_REMAT={s!r}: expected none (default), "
                     f"layer, or aggressive")


register('MXTPU_REMAT', _remat_policy, 'none',
         "Rematerialization policy of the sharded train step's forward "
         "(parallel/step.py): 'none' (default) keeps XLA's own choice "
         'of saved activations (under ZeRO-3 the gathered params are '
         'still always recomputed, never kept); '
         "'layer' wraps the forward in jax.checkpoint saving only "
         'matmul outputs without batch dims '
         '(dots_with_no_batch_dims_saveable — the classic per-layer '
         "checkpoint spend: ~1 extra forward of FLOPs for O(layers) "
         "activation memory); 'aggressive' saves nothing "
         '(nothing_saveable — minimum HBM, maximum recompute). '
         'Sweep + HBM cross-validation: tools/tune_bert_step.py '
         '--autotune.')

# sparse embedding fast path (ISSUE 19) — parallel/step.py RowSparse
# gradients + live-rows-only optimizer updates
register('MXTPU_SPARSE', _bool, True,
         'Enable the RowSparse fast path in the sharded train step: '
         "parameters declared grad_stype='row_sparse' (Embedding("
         'sparse_grad=True)) backpropagate (unique row ids, row-block '
         'values) instead of a dense table-shaped gradient, and the '
         'optimizer updates only the gathered live rows inside the one '
         'pjit step. Off: such tables fall back to the dense path '
         '(identical trajectories under exact mode, see '
         'MXTPU_SPARSE_EXACT).')
register('MXTPU_SPARSE_ROWS', int, 0,
         'Per-table live-row budget ceiling for the sparse fast path. '
         "A table whose worst-case unique-row budget (min(batch ids, "
         'vocab), discovered at trace time) exceeds this falls back to '
         'the dense path — the sparse win only exists when the budget '
         'is well under the vocab. 0 (default) = no ceiling.')
register('MXTPU_SPARSE_EXACT', _bool, False,
         'Force EXACT (non-lazy) sparse semantics: the deduped row '
         'block densifies into a table-shaped gradient and the regular '
         'dense optimizer kernel runs — bit-identical trajectories to '
         'the dense path (the parity oracle; ref lazy_update=False). '
         'Default off = lazy semantics per the reference: momentum/'
         'Adam moments of absent rows stay frozen and weight decay '
         'applies only to live rows.')
register('MXTPU_SPARSE_TABLE_AXIS', str, '',
         "Mesh axis name to model-parallel-shard row_sparse embedding "
         "tables over (e.g. 'tp'): the table rows shard P(axis) and "
         'XLA inserts the all-to-all feature exchange for ids that '
         'hash to remote shards. Tables whose vocab does not divide '
         'the axis extent keep a replicated compute copy and shard '
         "only their fp32 state over ZeRO's flat padded stores. "
         'Empty (default) = tables replicate like other params.')
