"""mx.npx — numpy extension ops (ref: python/mxnet/numpy_extension/).

NN ops that have no numpy equivalent, operating on mx.np.ndarray.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..numpy import ndarray, _unwrap
from ..ops import nn as _nn, index as _idx, sequence as _seq
from ..util import (set_np, reset_np, is_np_array, is_np_shape,  # noqa: F401
                    use_np, use_np_array, use_np_shape)
from ..context import cpu, gpu, num_gpus  # noqa: F401


def _wrap_out(out):
    if isinstance(out, tuple):
        return tuple(ndarray(o) for o in out)
    return ndarray(out)


def softmax(data, axis=-1, length=None, temperature=None):
    return _wrap_out(_nn.softmax(_unwrap(data), axis=axis,
                                 temperature=temperature,
                                 length=_unwrap(length) if length is not None else None))


def log_softmax(data, axis=-1, temperature=None):
    return _wrap_out(_nn.log_softmax(_unwrap(data), axis=axis,
                                     temperature=temperature))


def relu(data):
    return _wrap_out(jnp.maximum(_unwrap(data), 0))


def sigmoid(data):
    return _wrap_out(jax.nn.sigmoid(_unwrap(data)))


def activation(data, act_type='relu'):
    return _wrap_out(_nn.activation(_unwrap(data), act_type=act_type))


def fully_connected(x, weight, bias=None, num_hidden=None, no_bias=False,
                    flatten=True):
    return _wrap_out(_nn.fully_connected(
        _unwrap(x), _unwrap(weight),
        _unwrap(bias) if bias is not None else None,
        num_hidden=num_hidden, no_bias=no_bias, flatten=flatten))


def convolution(data=None, weight=None, bias=None, **kwargs):
    return _wrap_out(_nn.convolution(
        _unwrap(data), _unwrap(weight),
        _unwrap(bias) if bias is not None else None, **kwargs))


def pooling(data=None, **kwargs):
    return _wrap_out(_nn.pooling(_unwrap(data), **kwargs))


def batch_norm(x, gamma, beta, running_mean, running_var, **kwargs):
    out, m, v = _nn.batch_norm(_unwrap(x), _unwrap(gamma), _unwrap(beta),
                               _unwrap(running_mean), _unwrap(running_var),
                               **kwargs)
    return ndarray(out)


def layer_norm(data, gamma, beta, axis=-1, eps=1e-5):
    return _wrap_out(_nn.layer_norm(_unwrap(data), _unwrap(gamma),
                                    _unwrap(beta), axis=axis, eps=eps))


def embedding(data, weight, input_dim=None, output_dim=None, dtype='float32',
              sparse_grad=False):
    return _wrap_out(_nn.embedding(_unwrap(data), _unwrap(weight)))


def topk(data, axis=-1, k=1, ret_typ='indices', is_ascend=False,
         dtype='float32'):
    from ..ops.matrix import topk as _topk
    return _wrap_out(_topk(_unwrap(data), axis=axis, k=k, ret_typ=ret_typ,
                           is_ascend=is_ascend, dtype=dtype))


def pick(data, index, axis=-1, mode='clip', keepdims=False):
    return _wrap_out(_idx.pick(_unwrap(data), _unwrap(index), axis=axis,
                               keepdims=keepdims, mode=mode))


def one_hot(data, depth=None, on_value=1.0, off_value=0.0, dtype='float32'):
    return _wrap_out(_nn.one_hot(_unwrap(data), depth=depth,
                                 on_value=on_value, off_value=off_value,
                                 dtype=dtype))


def gather_nd(data, indices):
    return _wrap_out(_idx.gather_nd(_unwrap(data), _unwrap(indices)))


def reshape_like(lhs, rhs):
    return _wrap_out(jnp.reshape(_unwrap(lhs), _unwrap(rhs).shape))


def sequence_mask(data, sequence_length=None, use_sequence_length=False,
                  value=0., axis=0):
    return _wrap_out(_seq.sequence_mask(
        _unwrap(data),
        _unwrap(sequence_length) if sequence_length is not None else None,
        use_sequence_length=use_sequence_length, value=value, axis=axis))


def seed(s):
    from .. import random as _r
    _r.seed(s)


def waitall():
    from ..ndarray import waitall as _w
    _w()


def save(file, arr):
    """Save dict/list of np.ndarray in the binary .params container
    (ref: numpy_extension/utils.py save)."""
    from .. import ndarray as _nd
    if isinstance(arr, dict):
        _nd.save(file, {k: _nd.array(_unwrap(v)) for k, v in arr.items()})
    else:
        if not isinstance(arr, (list, tuple)):
            arr = [arr]
        _nd.save(file, [_nd.array(_unwrap(a)) for a in arr])


def load(file):
    """Load .params into np.ndarray (ref: numpy_extension/utils.py)."""
    from .. import ndarray as _nd
    out = _nd.load(file)
    if isinstance(out, dict):
        return {k: ndarray(v._data) for k, v in out.items()}
    return [ndarray(v._data) for v in out]


class random:
    """npx.random — sampler variants that draw one batch per parameter row
    (ref: numpy_extension/random.py bernoulli/normal_n/uniform_n)."""

    @staticmethod
    def bernoulli(prob=0.5, size=None, dtype='float32'):
        from ..base import get_op
        return ndarray(get_op('_npi_bernoulli').fn(
            _unwrap(prob), size=size, dtype=dtype))

    @staticmethod
    def normal_n(loc=0.0, scale=1.0, batch_shape=None, dtype='float32'):
        from ..base import get_op
        shp = None
        if batch_shape is not None:
            shp = tuple(batch_shape) + jnp.shape(_unwrap(loc))
        return ndarray(get_op('_npi_normal').fn(
            _unwrap(loc), _unwrap(scale), size=shp, dtype=dtype))

    @staticmethod
    def uniform_n(low=0.0, high=1.0, batch_shape=None, dtype='float32'):
        from ..base import get_op
        shp = None
        if batch_shape is not None:
            shp = tuple(batch_shape) + jnp.shape(_unwrap(low))
        return ndarray(get_op('_npi_uniform').fn(
            _unwrap(low), _unwrap(high), size=shp, dtype=dtype))

    seed = staticmethod(seed)


class image:
    """npx.image — image ops over np ndarrays
    (ref: numpy_extension/image.py, which re-exports the _npx__image_*
    registry ops). Deterministic + random augmenters, all backed by the
    registered image_* ops (HWC layout, float or uint8)."""

    @staticmethod
    def _op(name, *args, **kwargs):
        from ..base import get_op
        return _wrap_out(get_op(name).fn(
            *[_unwrap(a) for a in args],
            **{k: _unwrap(v) for k, v in kwargs.items()}))

    resize = staticmethod(lambda data, size, **kw:
                          image._op('image_resize', data, size=size, **kw))
    crop = staticmethod(lambda data, x, y, width, height:
                        image._op('image_crop', data, x=x, y=y,
                                  width=width, height=height))
    to_tensor = staticmethod(lambda data:
                             image._op('image_to_tensor', data))
    normalize = staticmethod(lambda data, mean=0.0, std=1.0:
                             image._op('image_normalize', data,
                                       mean=mean, std=std))
    flip_left_right = staticmethod(
        lambda data: image._op('image_flip_left_right', data))
    flip_top_bottom = staticmethod(
        lambda data: image._op('image_flip_top_bottom', data))
    random_flip_left_right = staticmethod(
        lambda data, p=0.5: image._op('_image_random_flip_left_right',
                                      data, p=p))
    random_flip_top_bottom = staticmethod(
        lambda data, p=0.5: image._op('_image_random_flip_top_bottom',
                                      data, p=p))
    random_brightness = staticmethod(
        lambda data, min_factor, max_factor:
        image._op('_image_random_brightness', data,
                  min_factor=min_factor, max_factor=max_factor))
    random_contrast = staticmethod(
        lambda data, min_factor, max_factor:
        image._op('_image_random_contrast', data,
                  min_factor=min_factor, max_factor=max_factor))
    random_saturation = staticmethod(
        lambda data, min_factor, max_factor:
        image._op('_image_random_saturation', data,
                  min_factor=min_factor, max_factor=max_factor))
    random_hue = staticmethod(
        lambda data, min_factor, max_factor:
        image._op('_image_random_hue', data,
                  min_factor=min_factor, max_factor=max_factor))
    random_color_jitter = staticmethod(
        lambda data, brightness=0.0, contrast=0.0, saturation=0.0,
        hue=0.0:
        image._op('_image_random_color_jitter', data,
                  brightness=brightness, contrast=contrast,
                  saturation=saturation, hue=hue))
    random_lighting = staticmethod(
        lambda data, alpha_std=0.05:
        image._op('_image_random_lighting', data, alpha_std=alpha_std))


def __getattr__(name):
    """Any registered operator is reachable as npx.<name> — the analog of
    the reference generating the npx namespace from the op registry
    (ref: python/mxnet/numpy_extension/_register.py). Explicit wrappers
    above take precedence; everything else resolves here on first use."""
    if name.startswith('_'):
        raise AttributeError(name)
    from ..base import get_op, MXNetError
    try:
        op = get_op(name)
    except MXNetError:
        raise AttributeError(
            f"module 'mxnet_tpu.numpy_extension' has no attribute "
            f"{name!r}") from None

    def f(*args, **kwargs):
        out = op.fn(*[_unwrap(a) for a in args],
                    **{k: _unwrap(v) for k, v in kwargs.items()})
        return _wrap_out(out)
    f.__name__ = name
    f.__qualname__ = name
    f.__doc__ = op.doc
    globals()[name] = f     # cache for subsequent lookups
    return f
