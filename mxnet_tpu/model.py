"""Checkpoint helpers for the symbolic API (ref: python/mxnet/model.py)."""
from __future__ import annotations

import pickle

from . import symbol as sym_mod
from .ndarray.ndarray import array


def save_checkpoint(prefix, epoch, symbol, arg_params, aux_params,
                    remove_amp_cast=True):
    """Ref: model.py save_checkpoint — writes prefix-symbol.json and
    prefix-XXXX.params."""
    if symbol is not None:
        symbol.save(f'{prefix}-symbol.json')
    payload = {f'arg:{k}': v.asnumpy() for k, v in arg_params.items()}
    payload.update({f'aux:{k}': v.asnumpy() for k, v in aux_params.items()})
    with open(f'{prefix}-{epoch:04d}.params', 'wb') as f:
        pickle.dump(payload, f, protocol=4)


def load_checkpoint(prefix, epoch):
    """Ref: model.py load_checkpoint."""
    symbol = sym_mod.load(f'{prefix}-symbol.json')
    with open(f'{prefix}-{epoch:04d}.params', 'rb') as f:
        payload = pickle.load(f)
    arg_params = {}
    aux_params = {}
    for k, v in payload.items():
        tp, name = k.split(':', 1)
        if tp == 'arg':
            arg_params[name] = array(v)
        else:
            aux_params[name] = array(v)
    return symbol, arg_params, aux_params


class BatchEndParam:
    def __init__(self, epoch, nbatch, eval_metric, locals=None):
        self.epoch = epoch
        self.nbatch = nbatch
        self.eval_metric = eval_metric
        self.locals = locals
