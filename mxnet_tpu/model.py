"""Checkpoint helpers for the symbolic API (ref: python/mxnet/model.py)."""
from __future__ import annotations

from . import symbol as sym_mod
from .ndarray.ndarray import array


def save_checkpoint(prefix, epoch, symbol, arg_params, aux_params,
                    remove_amp_cast=True):
    """Ref: model.py save_checkpoint — writes prefix-symbol.json and
    prefix-XXXX.params in the reference binary format (arg:/aux: keyed,
    ndarray.cc NDArray::Save container)."""
    from .serialization import atomic_write_file, save_ndarray_file
    if symbol is not None:
        symbol.save(f'{prefix}-symbol.json')
    payload = {f'arg:{k}': v.asnumpy() for k, v in arg_params.items()}
    payload.update({f'aux:{k}': v.asnumpy() for k, v in aux_params.items()})
    atomic_write_file(f'{prefix}-{epoch:04d}.params',
                      save_ndarray_file(payload))


def load_checkpoint(prefix, epoch):
    """Ref: model.py load_checkpoint. Reads reference-format binary params
    (round-1 pickle files still load via the restricted unpickler)."""
    from .serialization import load_params_dict
    symbol = sym_mod.load(f'{prefix}-symbol.json')
    with open(f'{prefix}-{epoch:04d}.params', 'rb') as f:
        # allow_pickle: legacy round-1 files (restricted unpickler)
        payload = load_params_dict(f.read(), allow_pickle=True,
                                   strip_arg_aux=False)
    arg_params = {}
    aux_params = {}
    for k, v in payload.items():
        tp, name = k.split(':', 1)
        if tp == 'arg':
            arg_params[name] = array(v)
        else:
            aux_params[name] = array(v)
    return symbol, arg_params, aux_params


class BatchEndParam:
    def __init__(self, epoch, nbatch, eval_metric, locals=None):
        self.epoch = epoch
        self.nbatch = nbatch
        self.eval_metric = eval_metric
        self.locals = locals
