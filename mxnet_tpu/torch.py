"""PyTorch interop bridge (ref: python/mxnet/torch.py, plugin/torch/).

The reference's legacy bridge wrapped Torch7 C functions as operators.
The modern equivalent: zero-copy tensor exchange over DLPack plus a
TorchOp adapter that runs a torch.nn.Module/function as a framework op
with gradients flowing through torch.autograd — useful for porting models
piecewise.

CPU tensors move zero-copy; accelerator tensors fall back to host copies
(torch here is CPU-only).
"""
from __future__ import annotations

import numpy as onp

from .ndarray.ndarray import NDArray, array as nd_array

__all__ = ['to_torch', 'from_torch', 'TorchOp']


def _torch():
    import torch as _t
    return _t


def to_torch(arr):
    """NDArray → torch.Tensor (zero-copy via DLPack when on CPU)."""
    t = _torch()
    if not isinstance(arr, NDArray):
        raise TypeError("to_torch expects an NDArray")
    try:
        return t.from_dlpack(arr._data)
    except Exception:
        return t.from_numpy(arr.asnumpy())


def from_torch(tensor):
    """torch.Tensor → NDArray (zero-copy via DLPack when possible)."""
    import jax
    t = _torch()
    if not isinstance(tensor, t.Tensor):
        raise TypeError("from_torch expects a torch.Tensor")
    tensor = tensor.detach().contiguous()
    try:
        return NDArray(jax.dlpack.from_dlpack(tensor))
    except Exception:
        return nd_array(tensor.cpu().numpy())


class TorchOp:
    """Run a torch callable (function or nn.Module) as a framework op.

    Forward converts inputs to torch tensors, runs the callable, and
    returns NDArrays; when autograd is recording, backward replays through
    torch.autograd — so a torch layer can sit inside a Gluon model while
    porting (ref: plugin/torch module bridge intent).
    """

    def __init__(self, fn):
        self.fn = fn

    def __call__(self, *inputs):
        from . import _imperative
        from .base import state
        t = _torch()

        recording = state.is_recording and \
            any(isinstance(a, NDArray) and a._in_graph for a in inputs)

        torch_in = []
        for a in inputs:
            ta = t.from_numpy(onp.asarray(
                a.asnumpy() if isinstance(a, NDArray) else a))
            ta.requires_grad_(recording and ta.is_floating_point())
            torch_in.append(ta)

        out = self.fn(*torch_in)
        tuple_out = isinstance(out, (tuple, list))
        outs = list(out) if tuple_out else [out]
        nd_outs = [nd_array(o.detach().cpu().numpy()) for o in outs]

        if recording:
            nd_inputs = [a for a in inputs if isinstance(a, NDArray)]
            grad_sources = [ta for a, ta in zip(inputs, torch_in)
                            if isinstance(a, NDArray)]
            # nn.Module weights: backward accumulates into their .grad
            # (standard torch semantics) so a torch optimizer can step them
            module_params = [p for p in self.fn.parameters()
                             if p.requires_grad] \
                if hasattr(self.fn, 'parameters') else []

            def vjp_fn(ct_struct):
                cts = ct_struct if isinstance(ct_struct, tuple) \
                    else (ct_struct,)
                torch_cts = [t.from_numpy(onp.asarray(c)) for c in cts]
                diff_inputs = [g for g in grad_sources if g.requires_grad]
                grads = t.autograd.grad(
                    outs, diff_inputs + module_params,
                    grad_outputs=torch_cts[:len(outs)],
                    retain_graph=True, allow_unused=True)
                in_grads = grads[:len(diff_inputs)]
                for p, g in zip(module_params, grads[len(diff_inputs):]):
                    if g is None:
                        continue
                    p.grad = g if p.grad is None else p.grad + g
                grad_iter = iter(in_grads)
                result = []
                for g_src in grad_sources:
                    if g_src.requires_grad:
                        g = next(grad_iter)
                        result.append(
                            onp.zeros(g_src.shape, onp.float32) if g is None
                            else g.cpu().numpy())
                    else:
                        result.append(onp.zeros(tuple(g_src.shape),
                                                onp.float32))
                import jax.numpy as jnp
                return tuple(jnp.asarray(r) for r in result)

            _imperative.record_node(nd_inputs, nd_outs, vjp_fn, fn=None,
                                    name=f"TorchOp[{type(self.fn).__name__}]",
                                    tuple_out=len(nd_outs) > 1)

        return tuple(nd_outs) if tuple_out else nd_outs[0]
