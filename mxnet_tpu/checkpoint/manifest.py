"""Checkpoint on-disk layout: atomic writes, JSON manifests, validation.

One committed checkpoint is one directory::

    <root>/step_0000000123/
        manifest.json             # index + sha256 content hashes + meta
        arrays/a00000.nd ...      # one reference-format .nd file per array
        blobs/trainer_states.bin  # opaque byte payloads (optimizer pickle)

The commit protocol makes a partial write invisible: everything is
written into ``step_0000000123.tmp-<pid>``, every file is fsync'd, the
manifest (which hashes every payload file) is written last, and a single
``os.replace`` renames the tmp dir onto the final name. A crash at ANY
point before the rename leaves only a ``*.tmp-*`` dir that readers
ignore and the next manager instance garbage-collects; a crash after the
rename leaves a fully-hashed, fully-fsync'd checkpoint.

This module is intentionally dependency-free (stdlib only, optional
package imports guarded) so ``tools/check_checkpoint_manifest.py`` can
load it standalone and validate a checkpoint dir without importing the
framework (or jax) at all.
"""
from __future__ import annotations

import hashlib
import json
import os
import re
import tempfile

try:  # packaged import; the standalone CLI loads this file without a package
    from ..base import MXNetError as _BaseError
except ImportError:  # pragma: no cover - exercised via the CLI tool
    _BaseError = ValueError

MANIFEST_NAME = 'manifest.json'
FORMAT_VERSION = 1
STEP_DIR_RE = re.compile(r'^step_(\d{10})$')
TMP_SUFFIX_RE = re.compile(r'^step_\d{10}\.tmp-\d+$')
# a committed dir retired aside while a re-save of the same step swaps in
# (recoverable: if the swap died, the old copy is renamed back on startup)
OLD_DIR_RE = re.compile(r'^(step_\d{10})\.old-\d+$')
# a committed dir the scrubber (or a replica repair) moved aside after a
# hash mismatch: evidence for the post-mortem, never a restore target
QUARANTINE_DIR_RE = re.compile(r'^(step_(\d{10}))\.quarantine-\d+$')
# directory holding replicas this host stores on behalf of PEER ranks
# (one <REPLICA_SUBDIR>/<ns>/step_* tree per owner); dot-prefixed so
# committed_steps / the retention GC never confuse it with local steps
REPLICA_SUBDIR = '.replicas'


class CorruptCheckpointError(_BaseError):
    """A committed checkpoint failed manifest/hash validation."""


def step_dir_name(step: int) -> str:
    if step < 0:
        raise ValueError(f"checkpoint step must be >= 0, got {step}")
    return f'step_{int(step):010d}'


def parse_step(name: str):
    """Step number for a committed dir name, None for anything else."""
    m = STEP_DIR_RE.match(name)
    return int(m.group(1)) if m else None


def sha256_bytes(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()


def sha256_file(path: str, chunk: int = 1 << 20) -> str:
    h = hashlib.sha256()
    with open(path, 'rb') as f:
        while True:
            b = f.read(chunk)
            if not b:
                break
            h.update(b)
    return h.hexdigest()


def fsync_dir(path: str) -> None:
    """Durably record directory entries (renames/creates) themselves."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:  # e.g. platforms without dir fds
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def atomic_write_bytes(path: str, data: bytes, durable: bool = True) -> None:
    """Write `data` to `path` so a crash never leaves a partial file: tmp
    file in the same directory (same filesystem), fsync, os.replace."""
    d = os.path.dirname(os.path.abspath(path))
    fd, tmp = tempfile.mkstemp(prefix=os.path.basename(path) + '.tmp-',
                               dir=d)
    try:
        with os.fdopen(fd, 'wb') as f:
            f.write(data)
            if durable:
                f.flush()
                os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    if durable:
        fsync_dir(d)


def write_bytes_durable(path: str, data: bytes) -> None:
    """Plain write + fsync, no tmp-file dance. For payload files inside
    an UNCOMMITTED checkpoint tmp dir: nothing there is visible until the
    directory-level os.replace commit, so per-file rename atomicity would
    be pure overhead (N renames + ~2N dir fsyncs per checkpoint); only
    durability before the commit rename matters."""
    with open(path, 'wb') as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())


def write_manifest(dirpath: str, doc: dict) -> None:
    doc = dict(doc)
    doc['format_version'] = FORMAT_VERSION
    atomic_write_bytes(os.path.join(dirpath, MANIFEST_NAME),
                       json.dumps(doc, indent=1, sort_keys=True)
                       .encode('utf-8'))


def read_manifest(dirpath: str) -> dict:
    path = os.path.join(dirpath, MANIFEST_NAME)
    try:
        with open(path, 'rb') as f:
            doc = json.loads(f.read().decode('utf-8'))
    except (OSError, ValueError, UnicodeDecodeError) as e:
        raise CorruptCheckpointError(
            f"checkpoint manifest {path} unreadable: {e}")
    if not isinstance(doc, dict) or \
            doc.get('format_version') != FORMAT_VERSION:
        raise CorruptCheckpointError(
            f"checkpoint manifest {path}: unknown format_version "
            f"{doc.get('format_version') if isinstance(doc, dict) else doc!r}")
    return doc


def scan_step_dir(dirpath: str, read_bytes=None):
    """Full integrity scan of one committed checkpoint dir.

    Re-hashes every payload file named by the manifest and checks byte
    counts. Returns ``(doc_or_None, [(kind, detail), ...])`` where
    ``kind`` classifies each problem as ``'missing'`` (a payload file
    the manifest names is absent) or ``'corrupt'`` (unreadable/
    malformed manifest, byte-count or content-hash mismatch) — the
    distinction the scrub CLI's exit codes report.

    ``read_bytes``: optional ``callable(path) -> bytes`` replacing the
    default streamed ``sha256_file`` — the ONE seam through which the
    background scrubber injects its ``checkpoint.read`` fault site and
    idle pacing, so there is exactly one integrity scanner over the
    manifest format. Exceptions it raises count as corrupt."""
    try:
        doc = read_manifest(dirpath)
    except CorruptCheckpointError as e:
        return None, [('corrupt', str(e))]
    problems = []
    entries = list(doc.get('arrays', [])) + list(doc.get('blobs', []))
    if not isinstance(doc.get('step'), int):
        problems.append(('corrupt', "manifest carries no integer 'step'"))
    for e in entries:
        rel = e.get('file')
        if not rel or '..' in rel.split('/'):
            problems.append(
                ('corrupt',
                 f"entry {e.get('name')!r}: bad file path {rel!r}"))
            continue
        path = os.path.join(dirpath, rel)
        if not os.path.isfile(path):
            problems.append(('missing', f"{rel}: missing"))
            continue
        if read_bytes is not None:
            try:
                data = read_bytes(path)
            except Exception as exc:  # read failure / injected fault
                problems.append(('corrupt', f"{rel}: {exc}"))
                continue
            size, digest = len(data), sha256_bytes(data)
        else:
            size, digest = os.path.getsize(path), None
        if size != e.get('bytes'):
            problems.append(
                ('corrupt',
                 f"{rel}: size {size} != manifest {e.get('bytes')}"))
            continue
        if digest is None:
            digest = sha256_file(path)
        if digest != e.get('sha256'):
            problems.append(
                ('corrupt',
                 f"{rel}: sha256 {digest[:12]}... != manifest "
                 f"{str(e.get('sha256'))[:12]}..."))
    return doc, problems


def validate_step_dir(dirpath: str):
    """Full integrity check of one committed checkpoint dir.

    Re-hashes every payload file named by the manifest and checks byte
    counts. Returns the parsed manifest; raises CorruptCheckpointError
    naming every problem found (all problems, not just the first, so the
    CLI tool's report is actionable)."""
    doc, problems = scan_step_dir(dirpath)
    if problems:
        raise CorruptCheckpointError(
            f"checkpoint {dirpath} corrupt: "
            + '; '.join(detail for _kind, detail in problems))
    return doc


def committed_steps(root: str):
    """Sorted ascending list of committed step numbers under `root`
    (tmp dirs and foreign names are ignored)."""
    try:
        names = os.listdir(root)
    except OSError:
        return []
    steps = []
    for n in names:
        s = parse_step(n)
        if s is not None and os.path.isdir(os.path.join(root, n)):
            steps.append(s)
    return sorted(steps)


def stale_tmp_dirs(root: str):
    """Leftover ``step_*.tmp-<pid>`` dirs from crashed/killed writers."""
    try:
        names = os.listdir(root)
    except OSError:
        return []
    return [os.path.join(root, n) for n in names if TMP_SUFFIX_RE.match(n)]


def quarantined_dirs(root: str):
    """[(path, step), ...] for ``step_*.quarantine-<pid>`` dirs — copies
    the scrubber (or a replica repair) retired after a hash mismatch.
    Kept as evidence until their step falls out of retention."""
    try:
        names = os.listdir(root)
    except OSError:
        return []
    out = []
    for n in names:
        m = QUARANTINE_DIR_RE.match(n)
        if m:
            out.append((os.path.join(root, n), int(m.group(2))))
    return out


def replica_namespaces(root: str):
    """Sorted owner namespaces (e.g. ``rank0``) with hosted replicas
    under ``<root>/.replicas``."""
    base = os.path.join(root, REPLICA_SUBDIR)
    try:
        names = os.listdir(base)
    except OSError:
        return []
    return sorted(n for n in names
                  if os.path.isdir(os.path.join(base, n)))


def stale_old_dirs(root: str):
    """[(old_path, final_path), ...] for ``step_*.old-<pid>`` dirs — a
    committed copy retired aside by a re-save of the same step. When the
    swap died before the new copy committed, `final_path` is missing and
    the old copy is the recovery source."""
    try:
        names = os.listdir(root)
    except OSError:
        return []
    out = []
    for n in names:
        m = OLD_DIR_RE.match(n)
        if m:
            out.append((os.path.join(root, n),
                        os.path.join(root, m.group(1))))
    return out
