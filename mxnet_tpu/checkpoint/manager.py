"""Fault-tolerant async CheckpointManager.

Orbax/Check-N-Run-style checkpointing for mxnet_tpu training loops:

- **Async**: ``save(step)`` snapshots params + optimizer state + step +
  RNG state to host memory on the calling (training) thread, then a
  background thread serializes, hashes and commits — the training step
  only pays the device→host copy (and any wait for a previous in-flight
  save). Telemetry reports both numbers so the overlap is auditable:
  ``mxnet_tpu_checkpoint_blocked_seconds`` (training thread) vs
  ``mxnet_tpu_checkpoint_save_seconds`` (end-to-end).
- **Atomic**: per-array reference-format files + a JSON manifest with
  sha256 content hashes are written into ``step_NNNNNNNNNN.tmp-<pid>``
  and committed with one ``os.replace`` (see manifest.py for the
  protocol). A kill at any instant leaves either the previous committed
  checkpoint intact or a tmp dir that readers never look at.
- **Retention**: keep-last-N plus keep-every-K-steps; GC deletes only
  committed-but-expired steps (never an in-flight tmp write) and sweeps
  stale tmp dirs left by killed processes.
- **Preemption-safe resume**: ``restore_latest()`` re-verifies every
  content hash and silently falls back to the previous committed step on
  corruption; ``install_preemption_hook()`` wires SIGTERM to an
  immediate synchronous ``save_now()``.
"""
from __future__ import annotations

import os
import shutil
import signal as _signal
import threading
import time as _time
import warnings
import weakref
from typing import Any, Dict, Optional

import numpy as onp

from ..base import MXNetError, telem_flags as _telem
from ..resilience import faults as _faults
from ..telemetry import trace as _trace
from ..resilience.faults import InjectedFault
from ..resilience.retry import retry_call
from . import manifest as mf
from .manifest import CorruptCheckpointError

__all__ = ['CheckpointManager', 'RestoredCheckpoint',
           'CorruptCheckpointError', 'last_committed_step']

# every live manager, weakly: the /healthz endpoint reports the newest
# committed step without holding a reference into any training loop
_live_managers: 'weakref.WeakSet' = weakref.WeakSet()


def _register_manager(mgr) -> None:
    _live_managers.add(mgr)


def last_committed_step() -> Optional[int]:
    """Newest committed step across every live CheckpointManager in
    this process (the /healthz "can this rank resume, and from where"
    answer). None when no manager exists or nothing is committed."""
    best = None
    for mgr in list(_live_managers):
        try:
            s = mgr.latest_step()
        except Exception:
            continue
        if s is not None and (best is None or s > best):
            best = s
    return best

# test-only fault-injection points (tests/test_checkpoint.py): name -> fn(path)
#   'after_arrays'  — payload files written, manifest not yet
#   'before_commit' — manifest written, final os.replace not yet
#   'during_write'  — once per payload file, before its bytes hit disk
_TEST_HOOKS: Dict[str, Any] = {}


def _run_hook(name: str, path: str) -> None:
    fn = _TEST_HOOKS.get(name)
    if fn is not None:
        fn(path)


def _snapshot_params(target) -> Dict[str, onp.ndarray]:
    """Normalize a params-like object into {name: host numpy array}.

    Accepts a gluon Block, a ParameterDict, a plain dict of
    Parameter/NDArray/numpy values, or a zero-arg callable returning any
    of those. This is the device→host copy — the only work the training
    thread pays for an async save."""
    if target is None:
        return {}
    if callable(target) and not hasattr(target, 'items') \
            and not hasattr(target, '_collect_params_with_prefix'):
        target = target()
    if hasattr(target, '_collect_params_with_prefix'):   # gluon Block
        target = target._collect_params_with_prefix()
    if not hasattr(target, 'items'):
        raise MXNetError(
            f"checkpoint params must be a Block, ParameterDict or dict, "
            f"got {type(target)}")
    out = {}
    for name, v in target.items():
        if hasattr(v, 'data') and hasattr(v, '_data'):   # Parameter
            if v._data is None:
                raise MXNetError(
                    f"checkpoint: parameter '{name}' is uninitialized")
            v = v.data()
        if hasattr(v, 'asnumpy'):                        # NDArray
            v = onp.asarray(v.asnumpy())
        else:
            # plain numpy is user-mutable in place: copy, or the async
            # writer serializes a torn mid-update state that still
            # hash-validates (NDArray paths are immutable snapshots)
            v = onp.array(v, copy=True)
        out[str(name)] = v
    return out


def _apply_params(target, loaded: Dict[str, onp.ndarray], strict: bool):
    """Write restored host arrays back into a params-like object."""
    from ..context import cpu
    from ..ndarray.ndarray import array
    if callable(target) and not hasattr(target, 'items') \
            and not hasattr(target, '_collect_params_with_prefix'):
        # a zero-arg provider is snapshot-only: writing into the dict it
        # RETURNS would be a silent no-op on the real model state
        raise MXNetError(
            "checkpoint restore: params are bound as a callable "
            "provider, which only supports saving — restore with "
            "apply=False and apply the arrays yourself (e.g. "
            "Module.set_params)")
    if hasattr(target, '_collect_params_with_prefix'):
        target = target._collect_params_with_prefix()
    for name, p in target.items():
        if name not in loaded:
            if strict:
                raise MXNetError(
                    f"checkpoint restore: parameter '{name}' missing from "
                    f"checkpoint (pass strict=False to skip)")
            continue
        v = loaded[name]
        if hasattr(p, 'set_data') and hasattr(p, '_data'):  # Parameter
            if p._data is None and not p._deferred_init:
                p.shape = v.shape
                p.initialize(ctx=[cpu(0)])
            p.set_data(array(v))
        elif hasattr(p, '_data'):                            # NDArray
            p._data = array(v)._data
        else:
            target[name] = array(v)


class RestoredCheckpoint:
    """What ``restore_latest()`` hands back: the committed step plus the
    validated payloads (host numpy params, opaque state blobs, manifest
    metadata, RNG state)."""

    def __init__(self, step, directory, params, blobs, metadata, rng):
        self.step = step
        self.directory = directory
        self.params = params          # {name: numpy}
        self.blobs = blobs            # {name: bytes} ('trainer_states', ...)
        self.metadata = metadata
        self.rng = rng

    @property
    def trainer_states(self) -> Optional[bytes]:
        return self.blobs.get('trainer_states')

    def __repr__(self):
        return (f"<RestoredCheckpoint step={self.step} "
                f"arrays={len(self.params)} blobs={sorted(self.blobs)}>")


class CheckpointManager:
    """Async, atomic, retained checkpoints for a training loop.

    ::

        mgr = checkpoint.CheckpointManager(
            'ckpts/', params=net, trainer=trainer,
            keep_last_n=3, keep_every_k_steps=1000,
            autosave_steps=500)
        mgr.install_preemption_hook()            # SIGTERM -> save_now()
        start = mgr.restore_latest() or 0        # resume (0 on fresh run)
        for step in range(start, total):
            ... train ...
            mgr.maybe_save(step + 1)             # autosave cadence
        mgr.close()

    ``restore_latest()`` returns the restored step number when ``params``
    / ``trainer`` are bound (state applied in place), or a
    ``RestoredCheckpoint`` when called with ``apply=False``.
    """

    def __init__(self, directory: str, params=None, trainer=None,
                 keep_last_n: int = 3, keep_every_k_steps: Optional[int] = None,
                 autosave_steps: Optional[int] = None,
                 autosave_seconds: Optional[float] = None,
                 async_save: bool = True, save_rng: bool = True,
                 replication: Optional[bool] = None):
        if keep_last_n < 1:
            raise MXNetError("keep_last_n must be >= 1 (the latest "
                             "checkpoint can never be retention-expired)")
        if keep_every_k_steps is not None and keep_every_k_steps < 1:
            raise MXNetError("keep_every_k_steps must be >= 1")
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self._params = params
        self._trainer = trainer
        self.keep_last_n = int(keep_last_n)
        self.keep_every_k_steps = keep_every_k_steps
        self.autosave_steps = autosave_steps
        self.autosave_seconds = autosave_seconds
        self.async_save = bool(async_save)
        self.save_rng = bool(save_rng)
        self.preempted = False
        self._current_step = None
        # elastic data resharding: an optional provider callable whose
        # dict (epoch position + per-rank shard assignment, e.g.
        # io.ElasticShard.state()) rides every manifest under
        # meta['data'] — see bind_data_state
        self._data_state = None
        self.last_restored_metadata = None
        self._last_autosave_time = _time.monotonic()
        self._pending: Optional[threading.Thread] = None
        self._error: Optional[BaseException] = None
        # RLock: a SIGTERM arriving while the main thread is inside save()
        # re-enters via the handler's save_now() on the same thread
        self._lock = threading.RLock()    # serializes save entry points
        self._in_signal_save = False
        self._in_save = False
        self._old_handlers = {}
        # a crashed predecessor may have left partial tmp writes (swept)
        # or a half-finished same-step re-save swap (recovered) behind;
        # nothing of ours is in flight yet, so pid-reuse leftovers go too
        self._recover_and_sweep(sweep_own=True)
        _register_manager(self)
        # peer replication (ISSUE 10): auto-attached when
        # MXTPU_CHECKPOINT_REPLICAS > 0 and an elastic membership world
        # is running (pass replication=False to force it off, or attach
        # an explicitly constructed ReplicaManager for custom worlds)
        self._replica = None
        if replication is None or replication:
            try:
                from .. import config as _config
                want = int(_config.get('MXTPU_CHECKPOINT_REPLICAS')) > 0 \
                    if replication is None else True
                if want:
                    from ..parallel import dist as _dist
                    ms = _dist.membership()
                    if ms is not None and ms.world > 1:
                        from .replica import ReplicaManager
                        self._replica = ReplicaManager(self, rank=ms.rank)
            except Exception as e:   # replication must never kill a run
                warnings.warn(
                    f"checkpoint replication unavailable: {e!r}",
                    RuntimeWarning)
                self._replica = None

    # -- introspection ----------------------------------------------------

    def all_steps(self):
        return mf.committed_steps(self.directory)

    def latest_step(self) -> Optional[int]:
        steps = self.all_steps()
        return steps[-1] if steps else None

    def step_dir(self, step: int) -> str:
        return os.path.join(self.directory, mf.step_dir_name(step))

    # -- replication -------------------------------------------------------

    @property
    def replica(self):
        """The attached ReplicaManager (None when replication is off)."""
        return self._replica

    @property
    def last_restore_source(self):
        """Where the last restore's bytes came from: None (plain local
        restore) or the replica source description (e.g.
        ``hosted:rank0``) when the any-replica fallback fetched them."""
        return self._replica.restore_source() \
            if self._replica is not None else None

    def attach_replication(self, replica_manager) -> None:
        """Attach an explicitly constructed
        ``checkpoint.replica.ReplicaManager`` (tests, drills, custom
        peer worlds). Replaces — and closes — any auto-attached one.
        The swap happens under the manager lock — the background writer
        reads ``_replica`` mid-commit under the same lock, and must see
        the old manager or the new one, never tear between the close
        and the rebind. close() runs after release (it joins the old
        push worker, which may itself be waiting on manager state)."""
        with self._lock:
            old, self._replica = self._replica, replica_manager
        if old is not None and old is not replica_manager:
            old.close()

    # -- data-position state (elastic resharding) --------------------------

    def bind_data_state(self, provider) -> None:
        """Bind a callable returning the data-position state dict
        (``io.ElasticShard.state()`` / ``DataLoader.data_state()``)
        recorded in every manifest under ``metadata['data']`` —
        alongside the ``world`` metadata, so a re-form at ANY world
        size resumes the sample stream exactly where the commit left
        it (no sample dropped or double-seen). Read it back after a
        restore from ``last_restored_metadata['data']``."""
        self._data_state = provider

    # -- save -------------------------------------------------------------

    def save(self, step: int, params=None, states: Optional[bytes] = None,
             metadata: Optional[dict] = None, block: bool = False,
             extra_blobs: Optional[Dict[str, bytes]] = None) -> None:
        """Checkpoint `step`. Snapshots state on the calling thread, then
        (async mode) hands the write to a background thread. `params` /
        `states` override the bound providers for this call only;
        `extra_blobs` adds opaque byte payloads (e.g. a symbol JSON) that
        ride in the manifest next to the trainer states."""
        t_blocked0 = _time.perf_counter()
        with self._lock:
            self._current_step = int(step)
            # back-pressure: at most one write in flight — a second save
            # waits for the first (that wait is honest blocked time)
            self._join_pending()
            # a previous async write's failure surfaces here, after its
            # thread is joined (reading _error earlier would race the
            # writer and could swallow the failure for good)
            self._reraise_write_error()
            self._in_save = True
            try:
                with _trace.span('checkpoint.snapshot', step=int(step)):
                    snapshot = self._snapshot(step, params, states,
                                              metadata, extra_blobs)
                if self.async_save and not block:
                    t = threading.Thread(
                        target=self._write_and_commit,
                        args=(snapshot, _time.perf_counter()),
                        name=f'ckpt-write-{step}', daemon=True)
                    self._pending = t
                    t.start()
                else:
                    self._write_and_commit(snapshot, _time.perf_counter())
                    self._reraise_write_error()
            finally:
                self._in_save = False
        blocked = _time.perf_counter() - t_blocked0
        self._last_autosave_time = _time.monotonic()
        if _telem['on']:
            from .. import telemetry as _telemetry
            _telemetry.observe('mxnet_tpu_checkpoint_blocked_seconds',
                               blocked)

    def save_now(self, step: Optional[int] = None, **kwargs) -> None:
        """Synchronous save (used by the SIGTERM hook): returns only once
        the checkpoint is committed and durable."""
        if step is None:
            step = self._current_step
        if step is None:
            raise MXNetError("save_now: no step given and no prior save/"
                             "maybe_save call to infer it from")
        self.save(step, block=True, **kwargs)

    def save_due(self, step: int) -> bool:
        """Would the autosave cadence save at `step`? (Factored out so
        resilience.NonFiniteGuard.maybe_save can gate the actual save on
        the step's non-finite flag without duplicating the cadence.)"""
        if self.autosave_steps and step % self.autosave_steps == 0:
            return True
        if self.autosave_seconds is not None and \
                _time.monotonic() - self._last_autosave_time \
                >= self.autosave_seconds:
            return True
        if self.preempted and self.latest_step() != int(step):
            return True
        return False

    def maybe_save(self, step: int, metadata: Optional[dict] = None) -> bool:
        """Autosave cadence: call once per training step. Saves when the
        steps/seconds cadence fires (or a preemption signal arrived before
        the hook could save synchronously). Returns True when it saved."""
        self._current_step = int(step)
        due = self.save_due(int(step))
        if due:
            self.save(step, metadata=metadata, block=self.preempted)
        return due

    def wait(self) -> None:
        """Block until any in-flight async write has committed."""
        with self._lock:
            self._join_pending()
        self._reraise_write_error()

    def _join_pending(self):
        t = self._pending
        if t is not None and t.is_alive():
            t.join()
        self._pending = None

    def _reraise_write_error(self):
        with self._lock:
            err, self._error = self._error, None
        if err is not None:
            raise MXNetError(
                f"checkpoint background write failed: {err!r}") from err

    def _snapshot(self, step, params, states, metadata,
                  extra_blobs=None) -> dict:
        arrays = _snapshot_params(
            params if params is not None else self._params)
        blobs = dict(extra_blobs or {})
        if states is not None:
            blobs['trainer_states'] = states
        elif self._trainer is not None:
            blobs['trainer_states'] = self._trainer.get_states_bytes()
        rng = None
        if self.save_rng:
            from .. import random as _random
            rng = _random.get_state()
        meta = dict(metadata or {})
        # elastic resumes are auditable: record the world this step was
        # committed UNDER (jax process world + side-channel membership
        # view when one is running). The payloads themselves are
        # host-gathered, so ANY survivor set can restore them — this is
        # bookkeeping, not a restore constraint.
        try:
            import jax as _jax
            world = {'processes': int(_jax.process_count()),
                     'rank': int(_jax.process_index())}
            from ..parallel import dist as _dist
            ms = _dist.membership()
            if ms is not None:
                world['membership'] = {'alive': ms.alive(),
                                       'world': ms.world_size()}
            meta.setdefault('world', world)
        except Exception:
            pass
        if self._data_state is not None:
            # data-position metadata (elastic resharding): where the
            # sample stream stood at this commit, plus the per-rank
            # shard assignment it was drawn under — the restore side
            # re-partitions the SAME global sequence at the new world
            try:
                ds = self._data_state()
                if ds is not None:
                    meta.setdefault('data', dict(ds))
            except Exception:
                pass
        if 'trainer_states' in blobs and self._trainer is not None:
            # The states payload is ALWAYS host-gathered fp32 (both
            # Trainer.get_states_bytes and ShardedTrainStep gather their
            # ZeRO shards), so a checkpoint restores at any dp degree and
            # into ZeRO or replicated trainers alike. Record the layout
            # it was written UNDER so cross-degree resumes are auditable.
            tr = self._trainer
            stage = int(getattr(tr, 'zero_stage',
                                getattr(tr, '_zero_stage', 0)) or 0)
            if stage == 0 and (getattr(tr, '_zero_active', False)
                               or getattr(tr, 'zero', False)):
                stage = 1
            layout = {
                'format': 'gathered-host',
                'zero1': stage >= 1,
                'stage': stage,
                'dp': int(getattr(tr, '_zero_dp', 0)
                          or getattr(tr, '_dp_size', 1)),
            }
            comp = getattr(tr, 'compression', None)
            if comp:
                # error-feedback residuals ride the states payload;
                # record the codec they were accumulated under so
                # cross-config resumes (restore with compression off ->
                # residuals deterministically reseed to zero) are
                # auditable from the manifest alone
                layout['compression'] = dict(comp)
            sp = getattr(tr, 'sparse_layout', None)
            sp = sp() if callable(sp) else None
            if sp:
                # RowSparse fast path (ISSUE 19): record update mode
                # (lazy/exact), table-shard axis and per-table row
                # budgets. Provenance only — sparse state tensors stay
                # table-shaped, so dense<->sparse and cross-dp restores
                # need no conversion
                layout['sparse'] = sp
            meta.setdefault('optimizer_state_layout', layout)
        return {'step': int(step), 'arrays': arrays, 'blobs': blobs,
                'rng': rng, 'metadata': meta}

    def _write_and_commit(self, snap: dict, t_start: float) -> None:
        try:
            # transient FS errors (and injected checkpoint.write raise
            # faults) get a bounded retry: _write_step rebuilds its tmp
            # dir from scratch every attempt, so a retry is idempotent
            from .. import config as _config
            with _trace.span('checkpoint.write', step=snap['step']):
                total_bytes = retry_call(
                    self._write_step, snap,
                    retries=_config.get('MXTPU_CHECKPOINT_WRITE_RETRIES'),
                    retry_on=(OSError, InjectedFault),
                    site='checkpoint.write')
        except BaseException as e:  # surfaced on the training thread
            self._error = e
            # a failed same-step re-save may have retired the committed
            # copy aside (.old-) — roll it back now so the LIVE manager
            # still sees the step (single writer: nothing else in flight)
            try:
                self._recover_and_sweep(sweep_own=True)
            except OSError:
                pass
            return
        if self._replica is not None:
            # hand the committed step to the background push worker:
            # one lock + list append — replication never blocks the
            # writer thread (let alone the training thread)
            self._replica.enqueue(snap['step'])
        if _telem['on']:
            from .. import telemetry as _telemetry
            _telemetry.observe('mxnet_tpu_checkpoint_save_seconds',
                               _time.perf_counter() - t_start)
            _telemetry.inc('mxnet_tpu_checkpoint_saves_total')
            _telemetry.set_gauge('mxnet_tpu_checkpoint_bytes', total_bytes)
            _telemetry.set_gauge('mxnet_tpu_checkpoint_last_step',
                                 snap['step'])

    def _write_step(self, snap: dict) -> int:
        from ..serialization import save_ndarray_file
        # fault site: 'raise' is retried by _write_and_commit as a
        # transient FS error; 'corrupt' mangles the first payload's
        # bytes AFTER hashing, producing a committed-but-invalid step
        # that restore_latest() must fall back past
        fault = _faults.fire('checkpoint.write')
        step = snap['step']
        final = self.step_dir(step)
        tmp = f'{final}.tmp-{os.getpid()}'
        if os.path.isdir(tmp):
            shutil.rmtree(tmp)
        os.makedirs(os.path.join(tmp, 'arrays'))
        os.makedirs(os.path.join(tmp, 'blobs'))
        total = 0
        arr_entries = []
        for i, (name, arr) in enumerate(snap['arrays'].items()):
            rel = f'arrays/a{i:05d}.nd'
            payload = save_ndarray_file({name: arr})
            _run_hook('during_write', os.path.join(tmp, rel))
            written = payload
            if fault == 'corrupt' and i == 0:
                written = _faults.corrupt_bytes(payload)
            mf.write_bytes_durable(os.path.join(tmp, rel), written)
            arr_entries.append({
                'name': name, 'file': rel, 'bytes': len(payload),
                'sha256': mf.sha256_bytes(payload),
                'shape': list(arr.shape), 'dtype': str(arr.dtype)})
            total += len(payload)
        blob_entries = []
        for name, data in snap['blobs'].items():
            if '/' in name or os.sep in name or name.startswith('.'):
                raise MXNetError(f"checkpoint blob name {name!r} must be "
                                 f"a plain filename component")
            rel = f'blobs/{name}.bin'
            _run_hook('during_write', os.path.join(tmp, rel))
            mf.write_bytes_durable(os.path.join(tmp, rel), data)
            blob_entries.append({
                'name': name, 'file': rel, 'bytes': len(data),
                'sha256': mf.sha256_bytes(data)})
            total += len(data)
        _run_hook('after_arrays', tmp)
        mf.write_manifest(tmp, {
            'step': step, 'arrays': arr_entries, 'blobs': blob_entries,
            'rng': snap['rng'], 'metadata': snap['metadata'],
            'save_time_unix': _time.time(), 'total_bytes': total})
        mf.fsync_dir(os.path.join(tmp, 'arrays'))
        mf.fsync_dir(os.path.join(tmp, 'blobs'))
        mf.fsync_dir(tmp)
        _run_hook('before_commit', tmp)
        # the commit point: one rename makes the whole step visible.
        # Re-saving an existing step cannot swap atomically (rename(2)
        # refuses non-empty targets), so the committed copy is retired
        # aside first and deleted only after the new copy commits — a
        # crash anywhere in between is recovered from the .old dir by
        # the next manager's _recover_and_sweep.
        old = None
        if os.path.isdir(final):
            old = f'{final}.old-{os.getpid()}'
            if os.path.isdir(old):
                shutil.rmtree(old)
            os.replace(final, old)
            _run_hook('after_retire_old', old)
        os.replace(tmp, final)
        mf.fsync_dir(self.directory)
        if old is not None:
            shutil.rmtree(old, ignore_errors=True)
        self._gc()
        return total

    # -- retention / GC ---------------------------------------------------

    def _retained(self, steps):
        keep = set(steps[-self.keep_last_n:])
        if self.keep_every_k_steps:
            keep.update(s for s in steps
                        if s % self.keep_every_k_steps == 0)
        return keep

    def _gc(self) -> int:
        """Delete committed-but-expired steps per the retention policy.
        Only ever touches committed dirs (and stale tmp dirs from dead
        writers) — never the in-flight write."""
        steps = self.all_steps()
        keep = self._retained(steps)
        expired = [s for s in steps if s not in keep]
        for s in expired:
            shutil.rmtree(self.step_dir(s), ignore_errors=True)
        removed = len(expired)
        if expired and self._replica is not None:
            # retention must also retire the steps' peer-hosted
            # replicas, or they grow unboundedly (background, bounded;
            # a peer's own orphan-GC scrub reconciles missed deletes)
            self._replica.retire(expired)
        # quarantined copies (scrub/restore corruption evidence) expire
        # with their step's retention: evidence for a RETAINED step is
        # kept (bounded by the keep-set size), everything else goes —
        # a min-step cutoff would never fire once keep_every_k_steps
        # pins an old step forever
        for qpath, qstep in mf.quarantined_dirs(self.directory):
            if qstep not in keep:
                shutil.rmtree(qpath, ignore_errors=True)
        removed_tmp = self._recover_and_sweep(sweep_own=True)
        if removed and _telem['on']:
            from .. import telemetry as _telemetry
            _telemetry.inc('mxnet_tpu_checkpoint_gc_total', removed)
        return removed + removed_tmp

    def _recover_and_sweep(self, sweep_own: bool = False) -> int:
        """Handle leftovers of dead writers: recover a committed step
        whose re-save swap died mid-way (``.old-`` dir present, final
        dir missing → rename the old copy back), then sweep stale
        ``.tmp-`` partial writes and superseded ``.old-`` copies."""
        n = 0
        for old, final in mf.stale_old_dirs(self.directory):
            if not os.path.isdir(final):
                try:
                    os.replace(old, final)   # the swap died: roll back
                    continue
                except OSError:
                    pass
            shutil.rmtree(old, ignore_errors=True)
            n += 1
        mine = f'.tmp-{os.getpid()}'
        for path in mf.stale_tmp_dirs(self.directory):
            if not sweep_own and path.endswith(mine):
                continue   # could be this process's own in-flight write
            shutil.rmtree(path, ignore_errors=True)
            n += 1
        return n

    # -- restore ----------------------------------------------------------

    def restore_latest(self, apply: bool = True, strict: bool = True,
                       restore_rng: bool = True):
        """Restore the newest committed checkpoint that passes full hash
        validation, falling back step by step on corruption.

        Returns None when the directory holds no committed checkpoint;
        raises CorruptCheckpointError when checkpoints exist but every
        one fails validation. With ``apply=True`` (default) the restored
        state is written into the bound ``params`` / ``trainer`` and the
        RNG stream, and the step number is returned; with ``apply=False``
        the raw ``RestoredCheckpoint`` is returned instead.

        With replication attached the scan gains an **any-replica
        fallback**: a corrupt local step is quarantined and repaired
        from a healthy replica BEFORE falling back to an older local
        step (the newest intact copy may be remote), and a missing or
        fully corrupt local directory inventories the live peers and
        fetches the newest commonly-committed step — hash-verified and
        committed locally — so a host that lost its disk still resumes."""
        self.wait()
        steps = self.all_steps()
        if not steps:
            fetched = self._replica_fetch_latest()
            if fetched is None:
                return None
            steps = [fetched]
        repaired = set()
        for step in reversed(steps):
            try:
                return self.restore(step, apply=apply, strict=strict,
                                    restore_rng=restore_rng)
            except CorruptCheckpointError as e:
                if _telem['on']:
                    from .. import telemetry as _telemetry
                    _telemetry.inc('mxnet_tpu_checkpoint_corrupt_total')
                if self._replica is not None and step not in repaired:
                    repaired.add(step)
                    warnings.warn(
                        f"checkpoint step {step} failed validation "
                        f"({e}); quarantining and repairing from a "
                        f"replica", RuntimeWarning)
                    if self._try_replica_repair(step):
                        try:
                            return self.restore(step, apply=apply,
                                                strict=strict,
                                                restore_rng=restore_rng)
                        except CorruptCheckpointError as e2:
                            e = e2
                warnings.warn(
                    f"checkpoint step {step} failed validation, falling "
                    f"back to the previous committed step: {e}",
                    RuntimeWarning)
        fetched = self._replica_fetch_latest()
        if fetched is not None:
            try:
                return self.restore(fetched, apply=apply, strict=strict,
                                    restore_rng=restore_rng)
            except CorruptCheckpointError:
                pass
        raise CorruptCheckpointError(
            f"no checkpoint under {self.directory} passed validation "
            f"(tried steps {list(reversed(steps))})"
            + ("" if self._replica is None
               else " and no peer replica was usable either"))

    def _replica_fetch_latest(self):
        """Fetch the newest step any replica source holds into the
        local directory (None without replication / nothing usable)."""
        if self._replica is None:
            return None
        try:
            return self._replica.fetch_latest_into_local()
        except Exception as e:
            warnings.warn(f"any-replica restore fallback failed: {e!r}",
                          RuntimeWarning)
            return None

    def _try_replica_repair(self, step) -> bool:
        """Quarantine one corrupt local step and re-fetch it from a
        healthy replica (restore-time twin of the scrubber's repair).
        True iff the step is intact again (the replica manager's
        source description is coerced — callers that want WHERE the
        repair came from use ``last_restore_source``)."""
        d = self.step_dir(step)
        q = f'{d}.quarantine-{os.getpid()}'
        try:
            if os.path.isdir(d):
                if os.path.isdir(q):
                    shutil.rmtree(q, ignore_errors=True)
                os.replace(d, q)
        except OSError:
            pass
        try:
            return bool(self._replica.repair_step(step))
        except Exception as e:
            warnings.warn(f"replica repair of step {step} failed: {e!r}",
                          RuntimeWarning)
            return False

    def restore(self, step: int, apply: bool = True, strict: bool = True,
                restore_rng: bool = True):
        """Restore one committed step (hash-verified). See restore_latest."""
        t0 = _time.perf_counter()
        with _trace.span('checkpoint.restore', step=int(step)):
            ck = self._load_step(step)
        # manifest metadata of the newest restore (world, optimizer
        # layout, data-position state): apply=True returns only the
        # step number, but a re-form still needs metadata['data'] to
        # re-seed its sample stream
        self.last_restored_metadata = dict(ck.metadata or {})
        if apply:
            target = self._params
            if target is not None:
                _apply_params(target, ck.params, strict)
            elif strict and ck.params:
                raise MXNetError(
                    "checkpoint restore: no params bound to this manager; "
                    "construct with params=... or call with apply=False")
            if self._trainer is not None and ck.trainer_states is not None:
                self._trainer.set_states_bytes(ck.trainer_states)
            if restore_rng and ck.rng:
                from .. import random as _random
                _random.set_state(ck.rng)
        if _telem['on']:
            from .. import telemetry as _telemetry
            _telemetry.observe('mxnet_tpu_checkpoint_restore_seconds',
                               _time.perf_counter() - t0)
        return ck.step if apply else ck

    def _load_step(self, step: int) -> RestoredCheckpoint:
        """Single-pass read + hash-verify of one committed step dir."""
        from ..serialization import load_ndarray_file
        d = self.step_dir(step)
        doc = mf.read_manifest(d)
        if doc.get('step') != int(step):
            raise CorruptCheckpointError(
                f"{d}: manifest step {doc.get('step')} != dir step {step}")

        def _read_verified(entry):
            path = os.path.join(d, entry['file'])
            # fault site: 'corrupt' mangles the bytes AFTER the disk
            # read so the hash check below rejects them (deterministic
            # corrupt-restore drills — no hand-flipped bytes); 'raise'
            # is wrapped like any other read failure, so the restore
            # scan falls back / repairs instead of aborting
            kind = _faults.fire('checkpoint.read')
            try:
                with open(path, 'rb') as f:
                    data = f.read()
            except OSError as e:
                raise CorruptCheckpointError(f"{path}: {e}")
            if kind == 'corrupt':
                data = _faults.corrupt_bytes(data)
            if len(data) != entry['bytes'] or \
                    mf.sha256_bytes(data) != entry['sha256']:
                raise CorruptCheckpointError(
                    f"{path}: content hash mismatch")
            return data

        # a manifest that parsed as JSON can still be garbage (truncated
        # then re-closed, bitrot inside a string, wrong-typed entries):
        # every structural surprise below is a CORRUPT STEP — the caller
        # (restore_latest) skips past it with a warning — never a raw
        # KeyError/TypeError that aborts the whole restore scan
        try:
            params = {}
            for entry in doc.get('arrays', []):
                arrays, names = load_ndarray_file(_read_verified(entry))
                params[entry['name']] = arrays[0]
            blobs = {entry['name']: _read_verified(entry)
                     for entry in doc.get('blobs', [])}
            step_no = doc['step']
        except CorruptCheckpointError:
            raise
        except Exception as e:
            raise CorruptCheckpointError(
                f"{d}: malformed manifest/payload structure: {e!r}")
        return RestoredCheckpoint(step_no, d, params, blobs,
                                  doc.get('metadata', {}), doc.get('rng'))

    # -- preemption -------------------------------------------------------

    def install_preemption_hook(self, signals=(_signal.SIGTERM,)) -> None:
        """On each signal: synchronously commit a checkpoint at the
        current step, set ``self.preempted`` and chain any previous python
        handler. The training loop should poll ``preempted`` and exit.
        Off the main thread (where CPython forbids signal handlers) this
        warns and becomes a no-op instead of killing the training run."""
        for sig in signals:
            try:
                old = _signal.signal(sig, self._on_signal)
            except ValueError:
                warnings.warn(
                    "checkpoint preemption hook not installed: signal "
                    "handlers can only be set from the main thread — "
                    "SIGTERM will not trigger save_now() in this run",
                    RuntimeWarning)
                return
            self._old_handlers.setdefault(sig, old)

    @property
    def hook_installed(self) -> bool:
        """Whether a preemption signal hook is currently installed."""
        return bool(self._old_handlers)

    def bind_params(self, params) -> None:
        """(Re)bind the params provider that save() snapshots: a Block,
        ParameterDict, dict, or a zero-arg callable returning one (None
        unbinds). Callable providers are snapshot-only — restore them
        with ``apply=False``."""
        self._params = params

    @property
    def params_bound(self) -> bool:
        return self._params is not None

    def uninstall_preemption_hook(self) -> None:
        for sig, old in self._old_handlers.items():
            _signal.signal(sig, old if old is not None else _signal.SIG_DFL)
        self._old_handlers.clear()

    def _on_signal(self, signum, frame):
        self.preempted = True
        # _in_save: the signal interrupted the main thread INSIDE save()
        # — re-entering would destroy that save's tmp dir mid-write; the
        # interrupted save commits this step when the handler returns
        if not self._in_save and not self._in_signal_save \
                and self._current_step is not None:
            self._in_signal_save = True
            try:
                # let an in-flight async write commit first: if it was
                # already saving this step, a second full write would
                # waste the preemption grace window
                try:
                    self.wait()
                except MXNetError:
                    pass   # the pending write failed — save fresh below
                if self.latest_step() != self._current_step:
                    self.save_now(self._current_step)
            finally:
                self._in_signal_save = False
        old = self._old_handlers.get(signum)
        if callable(old):
            old(signum, frame)

    # -- lifecycle --------------------------------------------------------

    def close(self) -> None:
        """Flush the in-flight write and unhook signals (and shut the
        replication worker + scrubber + replica server down)."""
        self.wait()
        # detach under the manager lock (the background writer reads
        # _replica mid-commit under it), close after release — close()
        # joins the push worker, which must not deadlock on our lock
        with self._lock:
            replica, self._replica = self._replica, None
        if replica is not None:
            replica.close()
        self.uninstall_preemption_hook()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
