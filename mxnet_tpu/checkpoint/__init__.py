"""Fault-tolerant async checkpointing.

``CheckpointManager`` snapshots params + optimizer state + step + RNG
state on the training thread, writes atomically (per-array files + a
hashed JSON manifest committed by one ``os.replace``) on a background
thread, enforces keep-last-N / keep-every-K retention, and resumes via
hash-verified ``restore_latest()`` with fallback to the previous
committed step on corruption. ``replica.ReplicaManager`` adds the
survivability layer: background peer replication of every committed
step over the membership side channel, an integrity scrubber with
quarantine + repair, and an any-replica restore fallback. See
manager.py / manifest.py / replica.py, the README "Checkpointing"
section, and ``tools/check_checkpoint_manifest.py``.
"""
from .manifest import (CorruptCheckpointError, atomic_write_bytes,
                       committed_steps, read_manifest, step_dir_name,
                       validate_step_dir)
from .manager import (CheckpointManager, RestoredCheckpoint,
                      last_committed_step)
from .replica import ReplicaManager, ReplicaPeer

__all__ = ['CheckpointManager', 'RestoredCheckpoint', 'ReplicaManager',
           'ReplicaPeer', 'CorruptCheckpointError', 'atomic_write_bytes',
           'committed_steps', 'last_committed_step', 'read_manifest',
           'step_dir_name', 'validate_step_dir']
