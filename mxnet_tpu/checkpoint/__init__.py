"""Fault-tolerant async checkpointing.

``CheckpointManager`` snapshots params + optimizer state + step + RNG
state on the training thread, writes atomically (per-array files + a
hashed JSON manifest committed by one ``os.replace``) on a background
thread, enforces keep-last-N / keep-every-K retention, and resumes via
hash-verified ``restore_latest()`` with fallback to the previous
committed step on corruption. See manager.py / manifest.py, the README
"Checkpointing" section, and ``tools/check_checkpoint_manifest.py``.
"""
from .manifest import (CorruptCheckpointError, atomic_write_bytes,
                       committed_steps, read_manifest, step_dir_name,
                       validate_step_dir)
from .manager import CheckpointManager, RestoredCheckpoint

__all__ = ['CheckpointManager', 'RestoredCheckpoint',
           'CorruptCheckpointError', 'atomic_write_bytes',
           'committed_steps', 'read_manifest', 'step_dir_name',
           'validate_step_dir']
