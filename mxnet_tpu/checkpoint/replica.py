"""Checkpoint survivability: peer replication, scrubbing, any-replica
restore (ISSUE 10).

PR 8's elastic story keeps training alive through a peer loss, but
every committed checkpoint is still ONE copy on ONE host's disk,
hash-verified only at restore time. A preemption that takes the disk
with it — or silent bit-rot inside a committed step — turns "resume
from step N" into "re-train from scratch". This module closes that gap
with three cooperating pieces, all off the training thread:

- **ReplicaManager** (this file): after each local commit,
  ``CheckpointManager`` hands the step to a background push worker that
  streams the per-array files + manifest to
  ``MXTPU_CHECKPOINT_REPLICAS`` peer hosts over the membership-style
  TCP side channel (``parallel.dist.file_put`` — never the ICI
  collectives a dead peer wedges). The receiver stages into a tmp dir
  and publishes with one ``os.replace`` (``dist.ReplicaServer``), so a
  kill -9 at any point mid-transfer leaves no partial replica visible.
  A dead or slow peer costs the push worker one bounded socket timeout
  per attempt — never the training thread, never a commit.
- **Scrubber**: an idle-paced background pass
  (``MXTPU_SCRUB_SECONDS``) re-hashes every committed local step and
  every hosted peer replica against its manifest, quarantines
  mismatches (``step_*.quarantine-<pid>`` — counted and flight-noted,
  never a restore target) and repairs them bit-identical from a
  healthy replica. The same pass garbage-collects orphaned replicas
  whose owner retired them while this host was down.
- **Any-replica restore**: ``CheckpointManager.restore_latest()``
  (and with it the elastic re-form path) falls back here when the
  local directory is missing, empty or corrupt — inventory the live
  peers plus the replicas this host stores for others, fetch the
  newest commonly-committed step, hash-verify every file and commit it
  locally before restoring, exactly like a local checkpoint.
"""
from __future__ import annotations

import contextlib
import logging
import os
import shutil
import threading
import time as _time

from ..base import MXNetError, telem_flags as _telem
from ..resilience.faults import InjectedFault
from ..resilience.retry import retry_call
from . import manifest as mf

__all__ = ['ReplicaManager', 'ReplicaPeer', 'active_fetches']

_log = logging.getLogger('mxnet_tpu.checkpoint')

# suffix of a replica-restore fetch staging dir. Deliberately NOT the
# manager's ``.tmp-<pid>`` shape: the manager's background writer
# sweeps its own stale tmp dirs after every GC, and a concurrent sweep
# must never race a fetch mid-flight. ReplicaManager sweeps these
# itself at construction.
_FETCH_SUFFIX = '.fetch-'


class ReplicaPeer:
    """One replication peer endpoint: (rank, host, port)."""

    def __init__(self, rank, host, port):
        self.rank = int(rank)
        self.host = str(host)
        self.port = int(port)

    def __repr__(self):
        return f"ReplicaPeer(rank={self.rank}, {self.host}:{self.port})"


# -- watchdog verdict support -------------------------------------------------

_fetch_lock = threading.Lock()
_active_fetches = 0


def active_fetches() -> int:
    """How many replica-transport fetches are in flight process-wide.
    ``resilience.elastic.stall_verdict`` consults this so a training
    stall DURING a replica fetch classifies as peer loss suspected
    (the serving peer is the prime suspect), not a bare local stall.
    Read under the same lock the counter mutates under — the callers
    are crash-time verdict paths where a torn read would misclassify
    the stall."""
    with _fetch_lock:
        return _active_fetches


@contextlib.contextmanager
def _fetching():
    global _active_fetches
    with _fetch_lock:
        _active_fetches += 1
    try:
        yield
    finally:
        with _fetch_lock:
            _active_fetches -= 1


def _note(kind, **info):
    from ..telemetry import flight as _flight
    _flight.note(kind, **info)


class ReplicaManager:
    """Background replication + scrubbing + any-replica restore for one
    ``CheckpointManager``.

    Normally constructed automatically by ``CheckpointManager`` when
    ``MXTPU_CHECKPOINT_REPLICAS`` > 0 and an elastic membership world
    is running; constructible directly (tests, drills, custom worlds)
    with an explicit peer list::

        rm = ReplicaManager(mgr, rank=0,
                            peers=[(1, '10.0.0.2', 23545)])
        mgr.attach_replication(rm)

    Parameters
    ----------
    manager : CheckpointManager
        Owns the local checkpoint directory this manager replicates
        FROM (and fetches INTO on an any-replica restore).
    rank : int, optional
        This host's rank (namespace ``rank<k>`` on the receivers).
        Defaults to the membership rank, else 0.
    peers : list of (rank, host, port) or ReplicaPeer, optional
        Explicit peer endpoints. Without it peers are derived from the
        live membership view in ring order after this rank, addressed
        via ``peer_addr_fn``.
    replicas : int, optional
        How many peers each committed step is pushed to (default
        ``MXTPU_CHECKPOINT_REPLICAS``).
    peer_addr_fn : callable(rank) -> (host, port), optional
        Resolves a rank's replica endpoint when peers are derived from
        the membership. Default: ``('127.0.0.1',
        dist.replica_port(rank))`` — correct for single-host worlds
        (the CPU drill); multi-host deployments must supply a resolver.
    serve : bool
        Run the receiving ``ReplicaServer`` (hosted replicas live under
        ``<ckpt_dir>/.replicas/<ns>/``). Default True.
    port : int, optional
        Port of this host's replica server (default
        ``dist.replica_port(rank)``; 0 binds an ephemeral port,
        readable back from ``rm.server.port``).
    """

    def __init__(self, manager, rank=None, peers=None, replicas=None,
                 peer_addr_fn=None, serve=True, port=None,
                 bandwidth_mbps=None, scrub_seconds=None, timeout=None,
                 max_pending=8, resync=True):
        from .. import config as _config
        from ..parallel import dist as _dist
        self.manager = manager
        if rank is None:
            ms = _dist.membership()
            rank = ms.rank if ms is not None else 0
        self.rank = int(rank)
        self.ns = f'rank{self.rank}'
        self.replicas = int(replicas) if replicas is not None \
            else int(_config.get('MXTPU_CHECKPOINT_REPLICAS'))
        self.bandwidth_mbps = bandwidth_mbps if bandwidth_mbps is not None \
            else float(_config.get('MXTPU_REPLICA_BANDWIDTH_MBPS'))
        self.timeout = float(timeout) if timeout is not None \
            else float(_config.get('MXTPU_REPLICA_TIMEOUT_SECONDS'))
        self.scrub_seconds = float(scrub_seconds) \
            if scrub_seconds is not None \
            else float(_config.get('MXTPU_SCRUB_SECONDS'))
        self.peer_addr_fn = peer_addr_fn
        self._peers = [p if isinstance(p, ReplicaPeer) else ReplicaPeer(*p)
                       for p in peers] if peers is not None else None
        self.max_pending = int(max_pending)
        self.last_restore_source = None   # guarded by self._cond
        self.push_failures = 0
        self.dropped = 0
        self._sweep_fetch_tmp()
        self.server = None
        if serve:
            if port is None:
                port = _dist.replica_port(self.rank)
            self.server = _dist.ReplicaServer(
                os.path.join(manager.directory, mf.REPLICA_SUBDIR),
                local_dir=manager.directory, port=port)
        # push queue: bounded, newest-wins — replication must never
        # apply back-pressure to the training thread, so when a slow
        # peer lets the queue grow past max_pending the OLDEST pending
        # step is dropped (counted; the newest checkpoint is the one a
        # restore wants anyway)
        self._queue = []
        self._cond = threading.Condition()
        self._busy = False
        self._stop = threading.Event()
        self._threads = []
        t = threading.Thread(target=self._push_loop, daemon=True,
                             name='mxtpu-ckpt-replicator')
        t.start()
        self._threads.append(t)
        if self.scrub_seconds > 0:
            t = threading.Thread(target=self._scrub_loop, daemon=True,
                                 name='mxtpu-ckpt-scrubber')
            t.start()
            self._threads.append(t)
        if resync:
            # a restarting host may have committed steps its peers never
            # received (killed between local commit and replication):
            # survey the peers in the background and re-push the missing
            self._enqueue_item(('resync',))

    # -- lifecycle ---------------------------------------------------------

    def close(self):
        self._stop.set()
        with self._cond:
            self._cond.notify_all()
        for t in self._threads:
            t.join(timeout=2.0)
        self._threads = []
        if self.server is not None:
            self.server.stop()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    def _sweep_fetch_tmp(self):
        """Remove stale ``*.fetch-*`` staging dirs a killed fetch left
        behind (nothing of ours is in flight at construction)."""
        try:
            names = os.listdir(self.manager.directory)
        except OSError:
            return
        for n in names:
            if _FETCH_SUFFIX in n:
                shutil.rmtree(os.path.join(self.manager.directory, n),
                              ignore_errors=True)

    # -- peer selection ----------------------------------------------------

    def _addr(self, rank):
        if self.peer_addr_fn is not None:
            return self.peer_addr_fn(rank)
        from ..parallel import dist as _dist
        return ('127.0.0.1', _dist.replica_port(rank))

    def _live_peers(self):
        """Every live peer endpoint (not just replication targets) —
        the inventory set an any-replica restore surveys."""
        if self._peers is not None:
            peers = list(self._peers)
        else:
            from ..parallel import dist as _dist
            ms = _dist.membership()
            if ms is None:
                return []
            peers = []
            for r in ms.alive():
                if r == self.rank:
                    continue
                host, port = self._addr(r)
                peers.append(ReplicaPeer(r, host, port))
        # filter through the membership when one is running: pushing to
        # a declared-lost peer wastes exactly the timeout budget a
        # bounded push tries to conserve
        from ..parallel import dist as _dist
        ms = _dist.membership()
        if ms is not None:
            try:
                lost = set(ms.lost_peers())
            except Exception:
                lost = set()
            peers = [p for p in peers if p.rank not in lost]
        return peers

    def _target_peers(self):
        """The replication fan-out: the first ``replicas`` live peers in
        ring order after this rank."""
        peers = sorted(self._live_peers(), key=lambda p: p.rank)
        if not peers or self.replicas <= 0:
            return []
        after = [p for p in peers if p.rank > self.rank] + \
                [p for p in peers if p.rank < self.rank]
        return after[:self.replicas]

    # -- push side ---------------------------------------------------------

    def _enqueue_item(self, item):
        with self._cond:
            if len(self._queue) >= self.max_pending:
                dropped = self._queue.pop(0)
                self.dropped += 1
                _log.warning(
                    "checkpoint replication queue full: dropping "
                    "pending %r (slow/dead peer?)", dropped)
                _note('checkpoint.replica_dropped', item=str(dropped))
            self._queue.append(item)
            self._cond.notify()

    def enqueue(self, step, committed_at=None):
        """Hand one freshly committed step to the background push
        worker. Called by ``CheckpointManager`` right after the local
        commit rename; costs one lock + list append."""
        self._enqueue_item(('step', int(step),
                            committed_at if committed_at is not None
                            else _time.perf_counter()))

    def retire(self, steps):
        """Retire the peer-hosted replicas of retention-expired steps
        (``CheckpointManager._gc`` calls this with what it deleted, so
        replicas can't grow unboundedly)."""
        steps = [int(s) for s in steps]
        if steps:
            self._enqueue_item(('gc', steps))

    def restore_source(self):
        """Where the newest replica restore/repair came from (e.g.
        ``hosted:rank0``), or None — read under the same lock the fetch
        paths (training-thread restore, scrubber repair) write it."""
        with self._cond:
            return self.last_restore_source

    def wait(self, timeout=30.0):
        """Block until the push queue is drained and the worker idle
        (drills/tests; never called on the training thread)."""
        deadline = _time.monotonic() + float(timeout)
        with self._cond:
            while self._queue or self._busy:
                remaining = deadline - _time.monotonic()
                if remaining <= 0:
                    return False
                self._cond.wait(min(remaining, 0.1))
        return True

    def _push_loop(self):
        while True:
            with self._cond:
                while not self._queue and not self._stop.is_set():
                    self._cond.wait(0.2)
                if self._stop.is_set() and not self._queue:
                    return
                item = self._queue.pop(0) if self._queue else None
                self._busy = item is not None
            if item is None:
                continue
            try:
                if item[0] == 'step':
                    self._replicate(item[1], item[2])
                elif item[0] == 'gc':
                    self._retire_remote(item[1])
                elif item[0] == 'resync':
                    self._resync()
            except Exception:
                _log.exception("checkpoint replication worker error "
                               "(item %r)", item)
            finally:
                with self._cond:
                    self._busy = False
                    self._cond.notify_all()

    def _replicate(self, step, t_commit):
        d = self.manager.step_dir(step)
        if not os.path.isdir(d):
            return      # retention already retired it — nothing to push
        peers = self._target_peers()
        if not peers:
            return
        for peer in peers:
            try:
                total = retry_call(
                    self._push_step_to, step, peer,
                    retries=1, retry_on=(MXNetError, OSError,
                                         InjectedFault),
                    site='checkpoint.replicate')
            except (MXNetError, OSError, InjectedFault) as e:
                self.push_failures += 1
                if _telem['on']:
                    from .. import telemetry as _telemetry
                    _telemetry.inc(
                        'mxnet_tpu_checkpoint_replica_failures_total',
                        peer=str(peer.rank))
                _log.warning(
                    "checkpoint replication of step %d to rank %d "
                    "(%s:%d) failed (local commit unaffected; the "
                    "resync on this or the peer's restart re-pushes): "
                    "%s", step, peer.rank, peer.host, peer.port, e)
                _note('checkpoint.replica_failed', step=int(step),
                      peer=peer.rank, error=str(e)[:200])
                continue
            lag = _time.perf_counter() - t_commit
            if _telem['on']:
                from .. import telemetry as _telemetry
                _telemetry.inc('mxnet_tpu_checkpoint_replica_pushes_total',
                               peer=str(peer.rank))
                _telemetry.inc('mxnet_tpu_checkpoint_replica_bytes_total',
                               total)
                _telemetry.observe(
                    'mxnet_tpu_checkpoint_replica_lag_seconds', lag)
            _note('checkpoint.replicated', step=int(step), peer=peer.rank,
                  bytes=int(total), lag_seconds=round(lag, 4))

    def _push_step_to(self, step, peer):
        """Stream every payload file + the manifest of one committed
        step to ``peer``, then publish it there with one commit op.
        Idempotent: a retry restages from scratch (the receiver's
        staging dir is keyed by (ns, step))."""
        from ..parallel import dist as _dist
        d = self.manager.step_dir(step)
        doc = mf.read_manifest(d)
        total = 0
        rels = [e['file'] for e in
                list(doc.get('arrays', [])) + list(doc.get('blobs', []))]
        for rel in rels + [mf.MANIFEST_NAME]:
            path = os.path.join(d, rel)
            with open(path, 'rb') as f:
                data = f.read()
            _dist.file_put(peer.host, peer.port, self.ns, step, rel,
                           data, timeout=self.timeout,
                           bandwidth_mbps=self.bandwidth_mbps)
            total += len(data)
        _dist.replica_commit(peer.host, peer.port, self.ns, step,
                             timeout=self.timeout)
        return total

    def _retire_remote(self, steps):
        from ..parallel import dist as _dist
        for peer in self._target_peers():
            for s in steps:
                try:
                    _dist.replica_delete(peer.host, peer.port, self.ns,
                                         s, timeout=self.timeout)
                except MXNetError as e:
                    # the peer's own orphan GC reconciles on its next
                    # scrub pass — retirement is best-effort
                    _log.debug("replica retire %d on rank %d failed "
                               "(peer scrub reconciles): %s",
                               s, peer.rank, e)

    def _resync(self):
        """Re-push committed local steps the peers are missing (a host
        killed between local commit and replication resumes here on
        restart)."""
        from ..parallel import dist as _dist
        local = mf.committed_steps(self.manager.directory)
        if not local:
            return
        missing = set()
        for peer in self._target_peers():
            try:
                inv = _dist.replica_inventory(peer.host, peer.port,
                                              ns=self.ns,
                                              timeout=self.timeout)
            except MXNetError:
                continue
            hosted = set(inv.get('hosted', {}).get(self.ns, []))
            missing |= set(local) - hosted
        for s in sorted(missing):
            self.enqueue(s)

    # -- any-replica restore ----------------------------------------------

    def restore_sources(self):
        """Survey every place a committed step could be fetched from:
        replicas this host stores for peers, the peers' hosted
        replicas, and the peers' own local checkpoints (every payload
        is host-gathered, so ANY rank's checkpoint of a step restores
        on any survivor). Returns ``[(desc, fetch_fn_factory, steps)]``
        sorted so newer steps are tried first by the callers."""
        from ..parallel import dist as _dist
        sources = []
        root = os.path.join(self.manager.directory, mf.REPLICA_SUBDIR)
        for ns in mf.replica_namespaces(self.manager.directory):
            steps = mf.committed_steps(os.path.join(root, ns))
            if steps:
                sources.append(('hosted:' + ns,
                                ('hosted', ns, None), steps))
        for peer in self._live_peers():
            try:
                inv = _dist.replica_inventory(peer.host, peer.port,
                                              timeout=self.timeout)
            except MXNetError:
                continue
            for ns, steps in sorted(inv.get('hosted', {}).items()):
                if steps:
                    sources.append((f'peer:rank{peer.rank}/{ns}',
                                    ('peer', ns, peer), steps))
            if inv.get('local'):
                sources.append((f'peer:rank{peer.rank}/local',
                                ('peer', 'local', peer), inv['local']))
        return sources

    def fetch_latest_into_local(self):
        """Fetch the newest step any healthy replica source holds into
        the LOCAL checkpoint directory (hash-verified file by file,
        committed by one os.replace) and return its number — the
        any-replica restore fallback. Falls back source by source and
        step by step on corruption; returns None when nothing usable
        exists anywhere."""
        with _fetching():
            sources = self.restore_sources()
            candidates = sorted({s for _, _, steps in sources
                                 for s in steps}, reverse=True)
            for step in candidates:
                if self._fetch_step(step, sources):
                    return step
        return None

    def repair_step(self, step):
        """Repair ONE local step from a healthy replica (scrubber /
        restore-time corruption): quarantine whatever is there, fetch,
        verify, commit. Returns the source description the repair came
        from (truthy) or None — callers that report the source use the
        RETURN value, not a re-read of ``last_restore_source`` (the
        training thread's restore path writes that attribute too)."""
        with _fetching():
            sources = self.restore_sources()
            return self._fetch_step(int(step), sources)

    def _fetch_step(self, step, sources):
        holders = [(desc, src) for desc, src, steps in sources
                   if step in steps]
        for desc, src in holders:
            try:
                total = self._fetch_step_into(
                    src, step, self.manager.step_dir(step))
            except (MXNetError, OSError, ValueError,
                    mf.CorruptCheckpointError) as e:
                _log.warning("replica fetch of step %d from %s failed, "
                             "trying next source: %s", step, desc, e)
                continue
            # under the queue condition lock: the scrubber thread and a
            # training-thread restore can both land here, and the drills
            # read the attribute after wait()
            with self._cond:
                self.last_restore_source = desc
            if _telem['on']:
                from .. import telemetry as _telemetry
                _telemetry.inc(
                    'mxnet_tpu_checkpoint_replica_fetches_total')
            _note('checkpoint.replica_restore', step=int(step),
                  source=desc, bytes=int(total))
            _log.warning(
                "checkpoint step %d restored from replica source %s "
                "(%d bytes, hash-verified)", step, desc, total)
            return desc
        return None

    def _fetch_step_into(self, src, step, final):
        """Fetch one step from one source into a staging dir next to
        ``final``, verify every file against the fetched manifest
        (paths sanitized — a corrupt or hostile manifest must never
        write outside the staging dir — plus byte counts and content
        hashes), and publish with one os.replace. The ONE copy of the
        fetch protocol: any-replica restore, local repair and hosted
        repair all run through here. Returns total payload bytes."""
        from ..parallel import dist as _dist
        kind, ns, peer = src
        parent = os.path.dirname(final)
        staging = final + f'{_FETCH_SUFFIX}{os.getpid()}'
        if os.path.isdir(staging):
            shutil.rmtree(staging)

        def _read(rel):
            if kind == 'hosted':
                path = os.path.join(self.manager.directory,
                                    mf.REPLICA_SUBDIR, ns,
                                    mf.step_dir_name(step), rel)
                with open(path, 'rb') as f:
                    return f.read()
            return _dist.file_get(peer.host, peer.port, ns, step, rel,
                                  timeout=self.timeout)

        total = 0
        try:
            raw_manifest = _read(mf.MANIFEST_NAME)
            import json as _json
            doc = _json.loads(raw_manifest.decode('utf-8'))
            if doc.get('step') != int(step):
                raise mf.CorruptCheckpointError(
                    f"replica manifest step {doc.get('step')} != {step}")
            os.makedirs(staging)
            for entry in (list(doc.get('arrays', []))
                          + list(doc.get('blobs', []))):
                rel = _dist._safe_rel(entry['file'])
                data = _read(rel)
                if len(data) != entry['bytes'] or \
                        mf.sha256_bytes(data) != entry['sha256']:
                    raise mf.CorruptCheckpointError(
                        f"replica payload {rel} of step {step} fails "
                        f"its manifest hash")
                path = os.path.join(staging, rel)
                os.makedirs(os.path.dirname(path), exist_ok=True)
                mf.write_bytes_durable(path, data)
                total += len(data)
            mf.write_bytes_durable(
                os.path.join(staging, mf.MANIFEST_NAME), raw_manifest)
            mf.validate_step_dir(staging)
            # same publish protocol as a local write: retire any
            # existing copy aside, one rename, durable dir entry
            old = None
            if os.path.isdir(final):
                old = f'{final}.old-{os.getpid()}'
                if os.path.isdir(old):
                    shutil.rmtree(old)
                os.replace(final, old)
            os.replace(staging, final)
            mf.fsync_dir(parent)
            if old is not None:
                shutil.rmtree(old, ignore_errors=True)
        except BaseException:
            shutil.rmtree(staging, ignore_errors=True)
            raise
        return total

    # -- scrubbing ---------------------------------------------------------

    def _scrub_loop(self):
        while not self._stop.wait(self.scrub_seconds):
            try:
                self.scrub_once()
            except Exception:
                _log.exception("checkpoint scrub pass failed")

    def _verify_step_dir(self, d, pace_seconds=0.0):
        """Re-hash one committed step against its manifest (the shared
        ``manifest.scan_step_dir`` scanner). Returns None when intact,
        else a problem string. The ``checkpoint.read`` fault site fires
        per payload file through the scanner's read hook (corrupt
        mangles the bytes after the read, raise counts as a read
        failure) so corrupt-at-rest drills need no hand-flipped bytes;
        the same hook paces reads so a big scrub does not compete with
        training-thread IO."""
        from ..resilience import faults as _faults

        def _read(path):
            kind = _faults.fire('checkpoint.read')
            with open(path, 'rb') as f:
                data = f.read()
            if kind == 'corrupt':
                data = _faults.corrupt_bytes(data)
            if pace_seconds:
                _time.sleep(pace_seconds)
            return data

        _doc, problems = mf.scan_step_dir(d, read_bytes=_read)
        if problems:
            return '; '.join(detail for _kind, detail in problems)
        return None

    def _quarantine_dir(self, d):
        q = f'{d}.quarantine-{os.getpid()}'
        if os.path.isdir(q):
            shutil.rmtree(q, ignore_errors=True)
        try:
            os.replace(d, q)
        except OSError:
            return None
        return q

    def scrub_once(self, pace_seconds=0.0):
        """One full integrity pass: local committed steps, then hosted
        peer replicas (repair + orphan GC). Returns a summary dict the
        drills assert on."""
        t0 = _time.perf_counter()
        summary = {'local_checked': 0, 'hosted_checked': 0,
                   'corrupt': 0, 'repaired': 0, 'orphans_gc': 0}
        if _telem['on']:
            from .. import telemetry as _telemetry
            _telemetry.inc('mxnet_tpu_checkpoint_scrub_passes_total')
        # -- local steps
        for step in mf.committed_steps(self.manager.directory):
            d = self.manager.step_dir(step)
            problem = self._verify_step_dir(d, pace_seconds)
            if problem is None:
                summary['local_checked'] += 1
                continue
            if not os.path.isdir(d):
                continue    # retention GC raced the scrub: not corrupt
            summary['corrupt'] += 1
            self._count_corrupt()
            _note('checkpoint.scrub', step=int(step), where='local',
                  verdict='corrupt', problem=problem[:200])
            _log.error("scrub: local checkpoint step %d corrupt (%s) — "
                       "quarantining and repairing from a replica",
                       step, problem)
            self._quarantine_dir(d)
            repaired_from = self.repair_step(step)
            if repaired_from:
                summary['repaired'] += 1
                self._count_repaired()
                _note('checkpoint.repair', step=int(step), where='local',
                      source=repaired_from)
        # -- hosted replicas (+ orphan GC against the owner's inventory)
        root = os.path.join(self.manager.directory, mf.REPLICA_SUBDIR)
        for ns in mf.replica_namespaces(self.manager.directory):
            owner_local = self._owner_local_steps(ns)
            nsdir = os.path.join(root, ns)
            # hosted quarantine expiry: once a healthy committed copy of
            # the step exists again (repair landed) the evidence is
            # redundant (the owner holds the original); a quarantine of
            # a step the owner retired goes with the orphan GC. A
            # quarantined copy with NO healthy replacement and a silent
            # owner is kept — it may be the last copy of anything.
            committed_now = set(mf.committed_steps(nsdir))
            for qpath, qstep in mf.quarantined_dirs(nsdir):
                if qstep in committed_now or (
                        owner_local and qstep not in owner_local
                        and qstep < max(owner_local)):
                    shutil.rmtree(qpath, ignore_errors=True)
            for step in mf.committed_steps(os.path.join(root, ns)):
                d = os.path.join(root, ns, mf.step_dir_name(step))
                if owner_local and step not in owner_local \
                        and step < max(owner_local):
                    # the owner committed newer steps and retired this
                    # one while we were down: orphaned replica
                    shutil.rmtree(d, ignore_errors=True)
                    summary['orphans_gc'] += 1
                    if _telem['on']:
                        from .. import telemetry as _telemetry
                        _telemetry.inc(
                            'mxnet_tpu_checkpoint_replica_gc_total')
                    continue
                problem = self._verify_step_dir(d, pace_seconds)
                if problem is None:
                    summary['hosted_checked'] += 1
                    continue
                if not os.path.isdir(d):
                    continue
                summary['corrupt'] += 1
                self._count_corrupt()
                _note('checkpoint.scrub', step=int(step),
                      where=f'hosted:{ns}', verdict='corrupt',
                      problem=problem[:200])
                _log.error("scrub: hosted replica %s/%d corrupt (%s) — "
                           "quarantining and re-fetching from its owner",
                           ns, step, problem)
                self._quarantine_dir(d)
                if self._repair_hosted(ns, step):
                    summary['repaired'] += 1
                    self._count_repaired()
                    _note('checkpoint.repair', step=int(step),
                          where=f'hosted:{ns}')
        dt = _time.perf_counter() - t0
        if _telem['on']:
            from .. import telemetry as _telemetry
            _telemetry.observe('mxnet_tpu_checkpoint_scrub_seconds', dt)
        summary['seconds'] = round(dt, 4)
        return summary

    def _count_corrupt(self):
        if _telem['on']:
            from .. import telemetry as _telemetry
            _telemetry.inc('mxnet_tpu_checkpoint_scrub_corrupt_total')

    def _count_repaired(self):
        if _telem['on']:
            from .. import telemetry as _telemetry
            _telemetry.inc('mxnet_tpu_checkpoint_scrub_repaired_total')

    def _owner_rank(self, ns):
        try:
            return int(ns[4:]) if ns.startswith('rank') else None
        except ValueError:
            return None

    def _owner_peer(self, ns):
        """The live peer endpoint of a namespace's owner (None when the
        owner is not in the live peer set)."""
        r = self._owner_rank(ns)
        if r is None:
            return None
        for p in self._live_peers():
            if p.rank == r:
                return p
        return None

    def _owner_local_steps(self, ns):
        """The owner's own committed steps (empty set when the owner is
        unreachable — then NOTHING is treated as orphaned: a replica
        whose owner lost its disk is precious, not garbage)."""
        from ..parallel import dist as _dist
        peer = self._owner_peer(ns)
        if peer is None:
            return set()
        try:
            inv = _dist.replica_inventory(peer.host, peer.port,
                                          timeout=self.timeout)
        except MXNetError:
            return set()
        return set(inv.get('local', []))

    def _repair_hosted(self, ns, step):
        """Re-fetch one hosted replica bit-identical from its owner's
        local copy (falling back to the owner's other replicas is the
        owner's scrubber's job). Same fetch protocol — path-sanitized,
        byte- and hash-verified, one-os.replace publish — as the
        any-replica restore (``_fetch_step_into``)."""
        peer = self._owner_peer(ns)
        if peer is None:
            return False
        final = os.path.join(self.manager.directory, mf.REPLICA_SUBDIR,
                             ns, mf.step_dir_name(step))
        try:
            with _fetching():
                self._fetch_step_into(('peer', 'local', peer), step,
                                      final)
        except (MXNetError, OSError, ValueError,
                mf.CorruptCheckpointError) as e:
            _log.warning("hosted replica repair %s/%d failed: %s",
                         ns, step, e)
            return False
        return True


def _serve_main(argv=None):   # pragma: no cover — subprocess entry
    """``python -m mxnet_tpu.checkpoint.replica --serve --root R --port
    P [--local-dir D]`` — a bare replica server, used by the kill -9
    receiver tests (the server process is SIGKILLed mid-transfer and
    restarted over the same root)."""
    import argparse
    import time
    ap = argparse.ArgumentParser()
    ap.add_argument('--serve', action='store_true', required=True)
    ap.add_argument('--root', required=True)
    ap.add_argument('--port', type=int, required=True)
    ap.add_argument('--local-dir', default=None)
    args = ap.parse_args(argv)
    from ..parallel import dist as _dist
    _dist.ReplicaServer(args.root, local_dir=args.local_dir,
                        port=args.port)
    print('ready', flush=True)
    while True:
        time.sleep(1)


if __name__ == '__main__':   # pragma: no cover
    _serve_main()
