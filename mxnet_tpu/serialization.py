"""Reference-format NDArray binary serialization (dmlc stream layout).

Implements the exact on-disk format of the reference's
``NDArray::Save/Load`` (ref: src/ndarray/ndarray.cc:1597-1868) so that
``.params`` / ``.ndarray`` files are interchangeable with the reference
ecosystem (model-zoo weights, released BERT params, C predict API blobs):

file := uint64 0x112 (list magic) | uint64 reserved
        | uint64 n   | n × ndarray
        | uint64 m   | m × (uint64 len | utf8 name)

ndarray := uint32 magic (V2 0xF993fac9 / V3 0xF993faca)
         | int32 stype                      (0 dense, 1 row_sparse, 2 csr)
         | [storage_shape: tshape]          (sparse only)
         | tshape shape
         | int32 dev_type | int32 dev_id    (context; loaded as cpu)
         | int32 type_flag                  (mshadow dtype enum)
         | sparse: n_aux × (int32 aux_type | tshape aux_shape)
         | raw data (little-endian, C order)
         | sparse: n_aux × raw aux data

tshape := int32 ndim | ndim × int64

Legacy V1 (0xF993fac8) and pre-V1 (magic = ndim, uint32 dims) streams are
also readable. Everything here is host-side numpy; placement on device
happens in the callers (ndarray.save/load).
"""
from __future__ import annotations

import io
import struct
from typing import Dict, List, Sequence, Tuple, Union

import numpy as onp

try:  # bf16 numpy dtype (ships with jax)
    import ml_dtypes
    _BF16 = onp.dtype(ml_dtypes.bfloat16)
except Exception:  # pragma: no cover
    _BF16 = None

NDARRAY_V1_MAGIC = 0xF993FAC8
NDARRAY_V2_MAGIC = 0xF993FAC9
NDARRAY_V3_MAGIC = 0xF993FACA
LIST_MAGIC = 0x112

# mshadow type flags (ref: 3rdparty/mshadow/mshadow/base.h:333-345)
_FLAG_TO_DTYPE = {
    0: onp.dtype(onp.float32), 1: onp.dtype(onp.float64),
    2: onp.dtype(onp.float16), 3: onp.dtype(onp.uint8),
    4: onp.dtype(onp.int32), 5: onp.dtype(onp.int8),
    6: onp.dtype(onp.int64), 7: onp.dtype(onp.bool_),
    8: onp.dtype(onp.int16),
}
if _BF16 is not None:
    _FLAG_TO_DTYPE[12] = _BF16
_DTYPE_TO_FLAG = {v: k for k, v in _FLAG_TO_DTYPE.items()}

_STYPE_NAUX = {0: 0, 1: 1, 2: 2}   # dense / row_sparse / csr
_STYPE_NAME = {0: 'default', 1: 'row_sparse', 2: 'csr'}


class FormatError(ValueError):
    pass


def _write_tshape(out: io.BytesIO, shape: Sequence[int]) -> None:
    out.write(struct.pack('<i', len(shape)))
    out.write(struct.pack(f'<{len(shape)}q', *[int(d) for d in shape]))


def _read_tshape(f) -> Tuple[int, ...]:
    ndim, = struct.unpack('<i', _read_exact(f, 4))
    if ndim < 0:
        return None  # unknown shape (np semantics none-array)
    return struct.unpack(f'<{ndim}q', _read_exact(f, 8 * ndim))


def _read_exact(f, n: int) -> bytes:
    b = f.read(n)
    if len(b) != n:
        raise FormatError("truncated NDArray stream")
    return b


def _as_le_bytes(arr: onp.ndarray) -> bytes:
    a = onp.ascontiguousarray(arr)
    if a.dtype.byteorder == '>':
        a = a.byteswap().view(a.dtype.newbyteorder('<'))
    return a.tobytes()


def write_ndarray(out: io.BytesIO, arr: onp.ndarray) -> None:
    """One dense ndarray. V2 layout (what every 1.x release writes); 0-d
    arrays use V3 (np-shape semantics) because in the legacy V2 layout an
    empty shape means "none array" and carries no data (ref:
    NDArray::Save is_np_shape branch, ndarray.cc:1607-1615)."""
    arr = onp.asarray(arr)
    flag = _DTYPE_TO_FLAG.get(arr.dtype)
    if flag is None:
        raise FormatError(f"dtype {arr.dtype} has no mshadow type flag")
    magic = NDARRAY_V3_MAGIC if arr.ndim == 0 else NDARRAY_V2_MAGIC
    out.write(struct.pack('<I', magic))
    out.write(struct.pack('<i', 0))               # kDefaultStorage
    _write_tshape(out, arr.shape)
    out.write(struct.pack('<ii', 1, 0))           # Context{kCPU, 0}
    out.write(struct.pack('<i', flag))
    out.write(_as_le_bytes(arr))


def read_ndarray(f):
    """One ndarray. Returns a dense numpy array, or for sparse payloads a
    tuple (stype_name, data, aux_arrays, shape)."""
    magic, = struct.unpack('<I', _read_exact(f, 4))
    if magic not in (NDARRAY_V2_MAGIC, NDARRAY_V3_MAGIC):
        return _read_legacy(f, magic)
    stype, = struct.unpack('<i', _read_exact(f, 4))
    if stype not in _STYPE_NAUX:
        raise FormatError(f"unknown storage type {stype}")
    naux = _STYPE_NAUX[stype]
    storage_shape = _read_tshape(f) if naux else None
    shape = _read_tshape(f)
    # none-array: unknown shape under V3, or empty shape under V2 — the
    # stream carries no further fields for it (ref: NDArray::Load early
    # return on shape_is_none / ndim()==0)
    if shape is None or (magic == NDARRAY_V2_MAGIC and len(shape) == 0):
        return None
    _read_exact(f, 8)                             # context (ignored: load cpu)
    flag, = struct.unpack('<i', _read_exact(f, 4))
    if flag not in _FLAG_TO_DTYPE:
        raise FormatError(f"unknown dtype flag {flag}")
    dtype = _FLAG_TO_DTYPE[flag]
    aux = []
    if naux:
        if storage_shape is None:
            raise FormatError("sparse ndarray with unknown storage_shape")
        aux_meta = []
        for _ in range(naux):
            aflag, = struct.unpack('<i', _read_exact(f, 4))
            ashape = _read_tshape(f)
            aux_meta.append((_FLAG_TO_DTYPE[aflag], ashape))
        data_shape = storage_shape
    else:
        data_shape = shape
    n = int(onp.prod(data_shape)) if len(data_shape) else 1
    data = onp.frombuffer(_read_exact(f, n * dtype.itemsize),
                          dtype=dtype.newbyteorder('<')
                          if dtype.itemsize > 1 else dtype).reshape(data_shape)
    data = data.astype(dtype) if data.dtype != dtype else data
    if naux:
        for adtype, ashape in aux_meta:
            an = int(onp.prod(ashape)) if len(ashape) else 1
            aux.append(onp.frombuffer(
                _read_exact(f, an * adtype.itemsize), dtype=adtype)
                .reshape(ashape))
        return (_STYPE_NAME[stype], data, aux, shape)
    return data


def _read_legacy(f, magic):
    """V1 and pre-V1 dense layouts (ref: NDArray::LegacyLoad)."""
    if magic == NDARRAY_V1_MAGIC:
        shape = _read_tshape(f)
    else:  # magic IS ndim; dims are uint32
        ndim = magic
        if ndim > 32:
            raise FormatError(f"bad NDArray magic 0x{magic:x}")
        shape = struct.unpack(f'<{ndim}I', _read_exact(f, 4 * ndim))
    # shape_is_none (ndim < 0) and empty shape are both none-arrays in the
    # reference's LegacyLoad
    if shape is None or len(shape) == 0:
        return None
    _read_exact(f, 8)                             # context
    flag, = struct.unpack('<i', _read_exact(f, 4))
    dtype = _FLAG_TO_DTYPE[flag]
    n = int(onp.prod(shape))
    return onp.frombuffer(_read_exact(f, n * dtype.itemsize),
                          dtype=dtype).reshape(shape)


def sparse_to_dense(stype: str, data: onp.ndarray, aux: List[onp.ndarray],
                    shape: Tuple[int, ...]) -> onp.ndarray:
    """Densify a deserialized CSR/RowSparse payload (this build keeps the
    sparse *API* over dense storage — ndarray/sparse.py)."""
    out = onp.zeros(shape, data.dtype)
    if stype == 'row_sparse':
        indices, = aux
        out[indices.astype(onp.int64)] = data
    elif stype == 'csr':
        indptr, indices = aux
        for r in range(shape[0]):
            cols = indices[indptr[r]:indptr[r + 1]].astype(onp.int64)
            out[r, cols] = data[indptr[r]:indptr[r + 1]]
    else:
        raise FormatError(f"unknown sparse stype {stype}")
    return out


def save_ndarray_file(data: Union[Dict[str, onp.ndarray],
                                  List[onp.ndarray], onp.ndarray]) -> bytes:
    """Serialize to the reference .params/.ndarray container format."""
    if isinstance(data, onp.ndarray):
        arrays, names = [data], []
    elif isinstance(data, dict):
        names = list(data.keys())
        arrays = [data[k] for k in names]
    else:
        arrays, names = list(data), []
    out = io.BytesIO()
    out.write(struct.pack('<QQ', LIST_MAGIC, 0))
    out.write(struct.pack('<Q', len(arrays)))
    for a in arrays:
        write_ndarray(out, onp.asarray(a))
    out.write(struct.pack('<Q', len(names)))
    for nm in names:
        b = nm.encode('utf-8')
        out.write(struct.pack('<Q', len(b)))
        out.write(b)
    return out.getvalue()


def load_ndarray_file(buf: bytes):
    """Parse a reference container. Returns (list_of_arrays, names).
    Sparse entries are returned as (stype, data, aux, shape) tuples."""
    f = io.BytesIO(buf)
    header, _reserved = struct.unpack('<QQ', _read_exact(f, 16))
    if header != LIST_MAGIC:
        raise FormatError(f"bad NDArray file magic 0x{header:x}")
    n, = struct.unpack('<Q', _read_exact(f, 8))
    arrays = [read_ndarray(f) for _ in range(n)]
    m, = struct.unpack('<Q', _read_exact(f, 8))
    names = []
    for _ in range(m):
        ln, = struct.unpack('<Q', _read_exact(f, 8))
        names.append(_read_exact(f, ln).decode('utf-8'))
    if names and len(names) != len(arrays):
        raise FormatError("name count mismatch in NDArray file")
    return arrays, names


def is_ndarray_file(buf: bytes) -> bool:
    return len(buf) >= 8 and struct.unpack('<Q', buf[:8])[0] == LIST_MAGIC


def atomic_write_file(path: str, data: bytes) -> None:
    """Crash-safe single-file write: tmp file in the same directory,
    fsync, then one ``os.replace`` — a kill mid-write leaves the previous
    file contents (or no file), never a truncated hybrid. Every .params /
    .states / .ndarray writer in the tree routes through this."""
    from .checkpoint.manifest import atomic_write_bytes
    atomic_write_bytes(path, data)


_pickle_fallback_warned = False


def load_params_dict(buf: bytes, allow_pickle: bool = False,
                     strip_arg_aux: bool = True):
    """Parse a .params blob into {name: dense numpy array}.

    The single decode path used by Block.load_parameters,
    ParameterDict.load, model.load_checkpoint, ndarray.load and the C
    predict ABI: binary container first. The restricted (numpy-only)
    unpickle fallback for round-1 files is OFF by default — the callers
    that still accept legacy files opt in with ``allow_pickle=True`` and
    a one-time warning fires when the fallback actually triggers. Sparse
    entries are densified; reference save_checkpoint-style 'arg:'/'aux:'
    prefixes are stripped when every key carries one."""
    if is_ndarray_file(buf):
        arrays, names = load_ndarray_file(buf)
        out = {}
        for k, v in zip(names, arrays):
            if isinstance(v, tuple):
                v = sparse_to_dense(*v)
            if v is None:
                raise FormatError(f"entry '{k}' is a none-array")
            out[k] = v
    elif allow_pickle:
        global _pickle_fallback_warned
        if not _pickle_fallback_warned:
            _pickle_fallback_warned = True
            import warnings
            warnings.warn(
                "params blob is not a reference-format NDArray file; "
                "falling back to the restricted (numpy-only) unpickler "
                "for a legacy round-1 file. Re-save with the current "
                "writer to drop the pickle dependency.", RuntimeWarning,
                stacklevel=2)
        loaded = safe_pickle_load(io.BytesIO(buf))
        # round-1 wrote either a bare dict or a ('dict', payload) pair
        if isinstance(loaded, tuple) and len(loaded) == 2 \
                and loaded[0] == 'dict':
            loaded = loaded[1]
        if not isinstance(loaded, dict):
            raise FormatError("params file does not hold a dict of arrays")
        out = dict(loaded)
    else:
        raise FormatError(
            "params blob is not a reference-format NDArray file "
            "(pickle params are not accepted on this path)")
    if strip_arg_aux and out and \
            all(k.startswith(('arg:', 'aux:')) for k in out):
        out = {k.split(':', 1)[1]: v for k, v in out.items()}
    return out


# ---------------------------------------------------------------------------
# restricted pickle (round-1 files were pickled; loading them must not be a
# code-execution surface — ADVICE r1)
# ---------------------------------------------------------------------------

import pickle as _pickle


class _SafeUnpickler(_pickle.Unpickler):
    _ALLOWED = {
        ('numpy.core.multiarray', '_reconstruct'),
        ('numpy._core.multiarray', '_reconstruct'),
        ('numpy.core.multiarray', 'scalar'),
        ('numpy._core.multiarray', 'scalar'),
        ('numpy', 'ndarray'),
        ('numpy', 'dtype'),
        ('numpy.dtypes', 'Float32DType'),
        ('numpy.dtypes', 'Float64DType'),
    }

    def find_class(self, module, name):
        if (module, name) in self._ALLOWED or module in ('numpy.dtypes',):
            return super().find_class(module, name)
        raise _pickle.UnpicklingError(
            f"global '{module}.{name}' is forbidden in params files")


def safe_pickle_load(f):
    """Unpickle allowing only numpy array reconstruction."""
    return _SafeUnpickler(f).load()
