"""Pretrained-weight store (ref: python/mxnet/gluon/model_zoo/model_store.py).

`get_model_file(name)` returns a local path to `<name>-<hash8>.params`,
downloading and unzipping from the model repo when the cached copy is
missing or fails its SHA-1 check. The checksum table is the reference's
own (model_store.py:34), so files published for the reference load here
byte-for-byte through the binary `.params` reader
(mxnet_tpu/serialization.py).

The repo URL comes from MXNET_GLUON_REPO; in airgapped environments point
it at a local directory (or file:// URL) holding `<file_name>.zip` or the
bare `<file_name>.params` — the download step then becomes a copy.
"""
from __future__ import annotations

import logging
import os
import shutil
import tempfile
import uuid
import zipfile

__all__ = ['get_model_file', 'purge']

_model_sha1 = {name: checksum for checksum, name in [
    ('44335d1f0046b328243b32a26a4fbd62d9057b45', 'alexnet'),
    ('f27dbf2dbd5ce9a80b102d89c7483342cd33cb31', 'densenet121'),
    ('b6c8a95717e3e761bd88d145f4d0a214aaa515dc', 'densenet161'),
    ('2603f878403c6aa5a71a124c4a3307143d6820e9', 'densenet169'),
    ('1cdbc116bc3a1b65832b18cf53e1cb8e7da017eb', 'densenet201'),
    ('ed47ec45a937b656fcc94dabde85495bbef5ba1f', 'inceptionv3'),
    ('9f83e440996887baf91a6aff1cccc1c903a64274', 'mobilenet0.25'),
    ('8e9d539cc66aa5efa71c4b6af983b936ab8701c3', 'mobilenet0.5'),
    ('529b2c7f4934e6cb851155b22c96c9ab0a7c4dc2', 'mobilenet0.75'),
    ('6b8c5106c730e8750bcd82ceb75220a3351157cd', 'mobilenet1.0'),
    ('36da4ff1867abccd32b29592d79fc753bca5a215', 'mobilenetv2_1.0'),
    ('e2be7b72a79fe4a750d1dd415afedf01c3ea818d', 'mobilenetv2_0.75'),
    ('aabd26cd335379fcb72ae6c8fac45a70eab11785', 'mobilenetv2_0.5'),
    ('ae8f9392789b04822cbb1d98c27283fc5f8aa0a7', 'mobilenetv2_0.25'),
    ('a0666292f0a30ff61f857b0b66efc0228eb6a54b', 'resnet18_v1'),
    ('48216ba99a8b1005d75c0f3a0c422301a0473233', 'resnet34_v1'),
    ('0aee57f96768c0a2d5b23a6ec91eb08dfb0a45ce', 'resnet50_v1'),
    ('d988c13d6159779e907140a638c56f229634cb02', 'resnet101_v1'),
    ('671c637a14387ab9e2654eafd0d493d86b1c8579', 'resnet152_v1'),
    ('a81db45fd7b7a2d12ab97cd88ef0a5ac48b8f657', 'resnet18_v2'),
    ('9d6b80bbc35169de6b6edecffdd6047c56fdd322', 'resnet34_v2'),
    ('ecdde35339c1aadbec4f547857078e734a76fb49', 'resnet50_v2'),
    ('18e93e4f48947e002547f50eabbcc9c83e516aa6', 'resnet101_v2'),
    ('f2695542de38cf7e71ed58f02893d82bb409415e', 'resnet152_v2'),
    ('264ba4970a0cc87a4f15c96e25246a1307caf523', 'squeezenet1.0'),
    ('33ba0f93753c83d86e1eb397f38a667eaf2e9376', 'squeezenet1.1'),
    ('dd221b160977f36a53f464cb54648d227c707a05', 'vgg11'),
    ('ee79a8098a91fbe05b7a973fed2017a6117723a8', 'vgg11_bn'),
    ('6bc5de58a05a5e2e7f493e2d75a580d83efde38c', 'vgg13'),
    ('7d97a06c3c7a1aecc88b6e7385c2b373a249e95e', 'vgg13_bn'),
    ('e660d4569ccb679ec68f1fd3cce07a387252a90a', 'vgg16'),
    ('7f01cf050d357127a73826045c245041b0df7363', 'vgg16_bn'),
    ('ad2f660d101905472b83590b59708b71ea22b2e5', 'vgg19'),
    ('f360b758e856f1074a85abd5fd873ed1d98297c3', 'vgg19_bn')]}

_url_format = '{repo_url}gluon/models/{file_name}.zip'


def _data_dir():
    from ... import config
    return config.get('MXNET_HOME')


def short_hash(name):
    if name not in _model_sha1:
        raise ValueError(
            f'Pretrained model for {name} is not available.')
    return _model_sha1[name][:8]


def _fetch(url, path, name):
    """Download/copy `url` to `path`. Supports http(s), file:// and plain
    filesystem paths (the airgapped MXNET_GLUON_REPO case)."""
    if url.startswith('file://'):
        url = url[len('file://'):]
    if os.path.exists(url):
        shutil.copyfile(url, path)
        return path
    from ..utils import download
    from ...base import MXNetError
    try:
        return download(url, path=path, overwrite=True)
    except Exception as e:
        raise MXNetError(
            f"could not fetch pretrained weights for {name!r} from "
            f"{url}. In an airgapped environment, set MXNET_GLUON_REPO "
            f"to a local directory holding gluon/models/"
            f"{name}-{short_hash(name)}.params (or .zip), or call "
            f"net.load_parameters() with a local file.") from e


def load_pretrained(net, name, root=None, ctx=None):
    """Fetch `name`'s published weights via the store and load them into
    `net` through the binary .params reader (the shared tail of every
    vision get_* loader, ref: model_zoo/vision/__init__.py)."""
    net.load_parameters(get_model_file(name, root=root), ctx=ctx)
    return net


def get_model_file(name, root=None):
    """Local path of the pretrained `.params` for `name`, downloading into
    `root` (default $MXNET_HOME/models) on miss or checksum mismatch."""
    from ..utils import check_sha1, replace_file
    if root is None:
        root = os.path.join(_data_dir(), 'models')
    file_name = f'{name}-{short_hash(name)}'
    root = os.path.expanduser(root)
    file_path = os.path.join(root, file_name + '.params')
    sha1_hash = _model_sha1[name]
    if os.path.exists(file_path):
        if check_sha1(file_path, sha1_hash):
            return file_path
        logging.warning('Mismatch in the content of model file detected. '
                        'Downloading again.')
    else:
        logging.info('Model file not found. Downloading to %s.', file_path)

    os.makedirs(root, exist_ok=True)
    from ... import config
    repo_url = config.get('MXNET_GLUON_REPO')
    if repo_url[-1] != '/':
        repo_url += '/'
    src = _url_format.format(repo_url=repo_url, file_name=file_name)

    # airgapped repos may hold the bare .params next to (or instead of)
    # the zip
    bare = src[:-len('.zip')] + '.params'
    bare_fs = bare[len('file://'):] if bare.startswith('file://') else bare
    if os.path.exists(bare_fs):
        shutil.copyfile(bare_fs, file_path)
    else:
        temp_zip = os.path.join(root, file_name + '.zip' + str(uuid.uuid4()))
        try:
            _fetch(src, temp_zip, name)
            with zipfile.ZipFile(temp_zip) as zf:
                temp_dir = tempfile.mkdtemp(dir=root)
                try:
                    zf.extractall(temp_dir)
                    replace_file(os.path.join(temp_dir,
                                              file_name + '.params'),
                                 file_path)
                finally:
                    shutil.rmtree(temp_dir, ignore_errors=True)
        finally:
            if os.path.exists(temp_zip):
                os.remove(temp_zip)

    if check_sha1(file_path, sha1_hash):
        return file_path
    raise ValueError('Downloaded file has different hash. Please try again.')


def purge(root=None):
    """Remove every cached model file (ref: model_store.py purge)."""
    if root is None:
        root = os.path.join(_data_dir(), 'models')
    root = os.path.expanduser(root)
    if not os.path.isdir(root):
        return
    for f in os.listdir(root):
        if f.endswith('.params'):
            os.remove(os.path.join(root, f))
