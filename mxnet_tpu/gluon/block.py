"""Block / HybridBlock / CachedOp-equivalent compiled execution.

Ref: python/mxnet/gluon/block.py:229 (Block), :827 (HybridBlock),
src/imperative/cached_op.cc (CachedOp).

TPU-native hybridize: instead of building an NNVM graph, `hybridize()`
wraps the block's forward in a `jax.jit`-compiled function of
(param arrays, input arrays, rng key) → (outputs, updated aux states).
Static-alloc/static-shape modes of the reference map to XLA's AOT compile +
buffer donation; the compile cache is keyed on input shapes/dtypes and
train/predict mode, which reproduces CachedOp's shape-specialised graphs.
Mutable aux states (BatchNorm running stats) are detected during tracing as
rebound parameter proxies and threaded out as functional outputs.
"""
from __future__ import annotations

import re
import threading
import time as _time

import jax
import numpy as onp

from ..base import MXNetError, state, telem_flags as _telem
from ..context import Context, cpu, current_context
from ..ndarray.ndarray import NDArray, array, _wrap
from .. import ndarray as nd
from .. import _imperative
from .. import random as _random
from ..telemetry import compile as _compile
from .parameter import Parameter, ParameterDict, DeferredInitializationError


class _BlockScope:
    """Name scope manager (ref: block.py _BlockScope)."""

    _current = threading.local()

    def __init__(self, block):
        self._block = block
        self._counter = {}
        self._old_scope = None

    _global_counter = {}

    @staticmethod
    def create(prefix, params, hint):
        current = getattr(_BlockScope._current, 'value', None)
        if current is None:
            if prefix is None:
                count = _BlockScope._global_counter.get(hint, 0)
                _BlockScope._global_counter[hint] = count + 1
                prefix = f"{hint}{count}_"
            if params is None:
                params = ParameterDict(prefix)
            else:
                params = ParameterDict(params.prefix, params)
            return prefix, params
        if prefix is None:
            count = current._counter.get(hint, 0)
            prefix = f"{hint}{count}_"
            current._counter[hint] = count + 1
        if params is None:
            parent = current._block.params
            params = ParameterDict(parent.prefix + prefix, parent._shared)
        else:
            params = ParameterDict(params.prefix, params)
        return current._block.prefix + prefix, params

    def __enter__(self):
        if self._block._empty_prefix:
            return self
        self._old_scope = getattr(_BlockScope._current, 'value', None)
        _BlockScope._current.value = self
        return self

    def __exit__(self, *exc):
        if self._block._empty_prefix:
            return
        _BlockScope._current.value = self._old_scope


class Block:
    """Base building block (ref: gluon/block.py:229)."""

    def __init__(self, prefix=None, params=None):
        self._empty_prefix = prefix == ''
        self._prefix, self._params = _BlockScope.create(
            prefix, params, self._alias())
        self._name = self._prefix[:-1] if self._prefix.endswith('_') else self._prefix
        self._scope = _BlockScope(self)
        self._children = {}
        self._reg_params = {}
        self._forward_hooks = []
        self._forward_pre_hooks = []

    def _alias(self):
        return self.__class__.__name__.lower()

    @property
    def prefix(self):
        return self._prefix

    @property
    def name(self):
        return self._name

    def name_scope(self):
        return self._scope

    @property
    def params(self):
        return self._params

    def collect_params(self, select=None) -> ParameterDict:
        ret = ParameterDict(self._params.prefix)
        if not select:
            ret.update(self.params)
        else:
            pattern = re.compile(select)
            ret.update({name: value for name, value in self.params.items()
                        if pattern.match(name)})
        for child in self._children.values():
            ret.update(child.collect_params(select=select))
        return ret

    def __setattr__(self, name, value):
        if isinstance(value, Block):
            existing = getattr(self, '_children', None)
            if existing is not None:
                self._children[name] = value
        elif isinstance(value, Parameter):
            if hasattr(self, '_reg_params'):
                self._reg_params[name] = value
        super().__setattr__(name, value)

    def register_child(self, block, name=None):
        if name is None:
            name = str(len(self._children))
        self._children[name] = block

    def register_forward_hook(self, hook):
        self._forward_hooks.append(hook)

    def register_forward_pre_hook(self, hook):
        self._forward_pre_hooks.append(hook)

    def apply(self, fn):
        for child in self._children.values():
            child.apply(fn)
        fn(self)
        return self

    def initialize(self, init=None, ctx=None, verbose=False, force_reinit=False):
        self.collect_params().initialize(init, ctx, verbose, force_reinit)

    def hybridize(self, active=True, **kwargs):
        for child in self._children.values():
            child.hybridize(active, **kwargs)

    def cast(self, dtype):
        for child in self._children.values():
            child.cast(dtype)
        for _, param in self.params.items():
            param.cast(dtype)

    def __call__(self, *args):
        for hook in self._forward_pre_hooks:
            hook(self, args)
        out = self.forward(*args)
        for hook in self._forward_hooks:
            hook(self, args, out)
        return out

    def forward(self, *args):
        raise NotImplementedError

    def summary(self, *inputs):
        summary_lines = [f"{type(self).__name__} summary:"]
        params = self.collect_params()
        total = 0
        for name, p in params.items():
            n = int(onp.prod(p.shape)) if p.shape else 0
            total += n
            summary_lines.append(f"  {name}: {p.shape} ({n} params)")
        summary_lines.append(f"Total params: {total}")
        print('\n'.join(summary_lines))

    # --- serialization (ref: block.py:417,473) -----------------------------
    def save_parameters(self, filename, deduplicate=False):
        """Writes the reference's binary .params format (ref: gluon/block.py
        save_parameters → ndarray.cc NDArray::Save) — loadable by the
        reference and vice versa."""
        from ..serialization import atomic_write_file, save_ndarray_file
        params = self._collect_params_with_prefix()
        if deduplicate:
            # shared Parameter objects are stored once, under the first
            # structured name that reaches them (reference deduplicate
            # contract); load with allow_missing for the aliased names
            seen = set()
            uniq = {}
            for key, val in params.items():
                if id(val) in seen:
                    continue
                seen.add(id(val))
                uniq[key] = val
            params = uniq
        arg_dict = {key: val._reduce_np() if hasattr(val, '_reduce_np')
                    else val.data().asnumpy() for key, val in params.items()}
        atomic_write_file(filename, save_ndarray_file(arg_dict))

    def _collect_params_with_prefix(self, prefix=''):
        if prefix:
            prefix += '.'
        ret = {prefix + key: val for key, val in self._reg_params.items()}
        for name, child in self._children.items():
            ret.update(child._collect_params_with_prefix(prefix + name))
        return ret

    def load_parameters(self, filename, ctx=None, allow_missing=False,
                        ignore_extra=False, cast_dtype=False,
                        dtype_source='current'):
        from ..serialization import load_params_dict
        with open(filename, 'rb') as f:
            # allow_pickle: legacy round-1 .params files are still loadable
            # (restricted numpy-only unpickler; warns once when hit)
            loaded = load_params_dict(f.read(), allow_pickle=True)
        params = self._collect_params_with_prefix()
        if not loaded and not params:
            return
        for name, param in params.items():
            if name not in loaded:
                if not allow_missing:
                    raise MXNetError(
                        f"Parameter '{name}' is missing in file '{filename}'")
                continue
            val = loaded[name]
            if param._data is None:
                if param._deferred_init:
                    param.shape = val.shape
                    param._finish_deferred_init()
                else:
                    param.initialize(ctx=ctx or [cpu(0)])
            param.set_data(array(val))
        if not ignore_extra:
            extra = set(loaded) - set(params)
            if extra:
                raise MXNetError(f"extra parameters in file: {sorted(extra)}")

    save_params = save_parameters
    load_params = load_parameters

    def __repr__(self):
        s = f"{type(self).__name__}("
        for name, child in self._children.items():
            s += f"\n  ({name}): {repr(child)}"
        return s + (")" if not self._children else "\n)")


class HybridBlock(Block):
    """Block compilable into one XLA executable (ref: block.py:827)."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix, params)
        self._active = False
        self._cached_op = None
        self._flags = {}
        self._subgraph_backend = None

    def hybridize(self, active=True, backend=None, clear=True, **kwargs):
        """Ref: block.py:1043. `backend` names a registered subgraph
        partitioner (mxnet_tpu.subgraph) that pattern-matches the traced
        graph and swaps matched regions for fused kernels — the analog of
        the reference's SubgraphProperty backends
        (src/operator/subgraph/subgraph_property.h:252). None keeps the
        plain XLA compilation path."""
        self._active = active
        if backend is None:
            from .. import config as _config
            backend = _config.get('MXNET_SUBGRAPH_BACKEND') or None
        if backend is not None:
            from .. import subgraph as _subgraph
            self._subgraph_backend = _subgraph.get_backend(backend)
        elif clear:
            self._subgraph_backend = None
        self._flags.update(kwargs)
        if clear:
            self._cached_op = None
        super().hybridize(active, **kwargs)

    def cast(self, dtype):
        self._cached_op = None
        super().cast(dtype)

    def infer_shape(self, *args):
        self._deferred_infer(args)

    def _deferred_infer(self, args):
        """Run forward once with recording off to trigger deferred param
        init via the layers' own shape inference."""
        pass

    def __call__(self, *args):
        from .. import symbol as sym_mod
        if args and isinstance(args[0], sym_mod.Symbol):
            # symbolic trace (export path) bypasses the compiled cache
            return self.forward(*args)
        if self._active:
            try:
                out = self._call_cached_op(*args)
            except DeferredInitializationError:
                self._init_deferred(args)
                out = self._call_cached_op(*args)
            for hook in self._forward_hooks:
                hook(self, args, out)
            return out
        try:
            return super().__call__(*args)
        except DeferredInitializationError:
            self._init_deferred(args)
            return super().__call__(*args)

    def _init_deferred(self, args):
        # finish deferred init by running shape inference in eager mode
        for child in self._children.values():
            pass
        # layers resolve their own deferred params in forward; run once eagerly
        from ..base import state as _st
        rec = _st.is_recording
        _st.is_recording = False
        try:
            self.forward(*args)
        finally:
            _st.is_recording = rec

    def _call_cached_op(self, *args):
        if self._cached_op is None:
            self._cached_op = CachedOp(self, self._flags)
        return self._cached_op(*args)

    def __deepcopy__(self, memo):
        """Copies drop the compiled trace cache (it closes over the original
        block's parameter objects and jitted executables)."""
        import copy as _copy
        new = object.__new__(type(self))
        memo[id(self)] = new
        for k, v in self.__dict__.items():
            if k == '_cached_op':
                new._cached_op = None
            else:
                setattr(new, k, _copy.deepcopy(v, memo))
        return new

    def forward(self, x, *args):
        """Dispatch to hybrid_forward with params (ref: block.py:1156).
        Symbol inputs trace the block into a Symbol DAG (params become
        named variables) — the export / mx2onnx path."""
        from .. import symbol as sym_mod
        if isinstance(x, sym_mod.Symbol):
            params = {i: sym_mod.var(j.name)
                      for i, j in self._reg_params.items()}
            return self.hybrid_forward(sym_mod, x, *args, **params)
        ctx = x.context if isinstance(x, NDArray) else current_context()
        try:
            params = {i: j.data(ctx) for i, j in self._reg_params.items()}
        except DeferredInitializationError:
            self._infer_param_shapes(x, args)
            params = {i: j.data(ctx) for i, j in self._reg_params.items()}
        return self.hybrid_forward(nd, x, *args, **params)

    def _infer_param_shapes(self, x, args):
        raise DeferredInitializationError(
            f"{type(self).__name__} has uninitialized parameters and no "
            "shape inference; initialize with explicit in_units/in_channels")

    def hybrid_forward(self, F, x, *args, **kwargs):
        raise NotImplementedError

    def export(self, path, epoch=0, remove_amp_cast=True,
               input_names=('data',)):
        """Export to `path-symbol.json` + `path-####.params`
        (ref: block.py:1106): the block is traced into a Symbol DAG and
        parameters are saved in the arg:/aux: keyed NDArray format, so the
        pair reloads via SymbolBlock.imports — same deployment contract as
        the reference."""
        from .. import symbol as sym_mod
        from ..ndarray import save as nd_save
        inputs = [sym_mod.var(n) for n in input_names]
        out = self(*inputs)
        if isinstance(out, (list, tuple)):
            raise MXNetError(
                "export supports single-output blocks; group outputs first")
        sym_file = f"{path}-symbol.json"
        out.save(sym_file)
        arg_names = set(out.list_arguments()) - set(input_names)
        payload = {}
        for name, p in self.collect_params().items():
            if name not in arg_names:
                continue
            key = ('aux:' if p.grad_req == 'null' else 'arg:') + name
            payload[key] = p.data()
        fname = f"{path}-{epoch:04d}.params"
        nd_save(fname, payload)
        return sym_file, fname

    def optimize_for(self, x, *args, backend=None, **kwargs):
        """Partition for `backend` and build the cached op in one step
        (ref: block.py optimize_for)."""
        self.hybridize(True, backend=backend, **kwargs)
        return self(x, *args)


class CachedOp:
    """Compiled executable for a HybridBlock (ref: src/imperative/cached_op.cc).

    Traces block.forward with tracer-backed parameter proxies, compiles with
    jax.jit, caches per (shapes, dtypes, mode). Parameter mutations during
    trace (BatchNorm running stats) are returned functionally and written
    back after each call.
    """

    def __init__(self, block, flags=None):
        self.block = block
        self.flags = flags or {}
        self._cache = {}

    def _params_for(self, ctx):
        params = []
        for name, p in sorted(self.block.collect_params().items()):
            params.append((name, p))
        return params

    def __call__(self, *inputs):
        ctx = None
        for x in inputs:
            if isinstance(x, NDArray):
                ctx = x.context
                break
        params = self._params_for(ctx)
        # force deferred-init resolution before tracing
        for _, p in params:
            if p._data is None:
                raise DeferredInitializationError(
                    f"Parameter '{p.name}' is deferred")
        from ..amp import amp as _amp
        key = (tuple((x.shape, str(x.dtype)) if isinstance(x, NDArray) else None
                     for x in inputs),
               state.is_training,
               # autocast state: a trace compiled before amp.init() must not
               # be reused after it (and vice versa)
               _amp.patch_epoch(),
               tuple(name for name, _ in params))
        entry = self._cache.get(key)
        compiled_now = False
        cctx = None
        site = f"cachedop:{self.block.name}"
        if entry is None:
            cctx = _compile.begin(site)
            t0 = _time.perf_counter()
            try:
                entry = self._build(params, inputs, state.is_training)
            except BaseException:
                _compile.abort(cctx)
                raise
            if cctx is not None:
                # the compile ledger takes over the counters: end(cctx)
                # below feeds record_compile with the structured
                # signature and the measured trace/lower/backend split
                _compile.set_signature(
                    cctx, self._compile_signature(params, inputs))
                compiled_now = True
            elif _telem['on']:
                from .. import telemetry as _telemetry
                _telemetry.record_compile(
                    site, repr(key[0]), _time.perf_counter() - t0)
                compiled_now = True
            self._cache[key] = entry
        elif _telem['on']:
            from .. import telemetry as _telemetry
            _telemetry.record_cache_hit(site)
        jitted, aux_names = entry

        rng = _random.next_key()

        # one taped node for the whole compiled call
        param_arrs = [p.data(ctx) for _, p in params]
        input_arrs = [x for x in inputs if isinstance(x, NDArray)]

        def run(*datas):
            n = len(params)
            outs, aux = jitted(list(datas[:n]), list(datas[n:]), rng)
            return tuple(outs) + tuple(aux)

        all_inputs = param_arrs + input_arrs
        t0 = _time.perf_counter()
        try:
            out_data, tensor_inputs, vjp_fn, gfn = _imperative.invoke(
                run, tuple(all_inputs), {})
        except BaseException:
            _compile.abort(cctx)
            raise
        if compiled_now:
            # _build only traced (jit is lazy): the first execution is
            # where XLA actually lowers and compiles — that is the cost
            # the recompile counters must show, not the trace time
            if cctx is not None:
                _compile.end(cctx)
            else:
                from .. import telemetry as _telemetry
                _telemetry.counter('mxnet_tpu_compile_seconds_total').inc(
                    _time.perf_counter() - t0, site=site)
        n_aux = len(aux_names)
        if n_aux:
            outs_flat, aux = out_data[:-n_aux], out_data[-n_aux:]
        else:
            outs_flat, aux = out_data, ()
        # write back mutated aux states (running stats). Inside an outer
        # trace, write to the outer proxy so the mutation is threaded out
        # functionally; otherwise update the real storage.
        name_to_param = dict(params)
        for name, new_val in zip(aux_names, aux):
            p = name_to_param[name]
            proxy = p._trace_proxy
            if proxy is not None:
                proxy._data = new_val
            else:
                for d in p._data:
                    d._data = jax.device_put(new_val, d._data.sharding)

        out_arrs = [_wrap(o) for o in outs_flat]
        if vjp_fn is not None:
            aux_arrs = [_wrap(a) for a in aux]
            _imperative.record_node(tensor_inputs, out_arrs + aux_arrs,
                                    vjp_fn, gfn,
                                    f"cachedop_{self.block.name}",
                                    tuple_out=True)
        if len(out_arrs) == 1:
            return out_arrs[0]
        return tuple(out_arrs)

    def _compile_signature(self, params, inputs):
        """Compile-ledger signature of one CachedOp variant: per-input
        shape/dtype rows plus the mode knobs baked into the cache key."""
        from ..amp import amp as _amp
        args = [_compile.array_sig(f'in{i}', x)
                for i, x in enumerate(inputs) if isinstance(x, NDArray)]
        return _compile.signature(args=args, flags={
            'training': bool(state.is_training),
            'amp_epoch': _amp.patch_epoch(),
            'params': len(params),
        })

    def _build(self, params, example_inputs, is_training):
        block = self.block
        aux_names_holder = []

        # param_datas is a positional LIST (sorted-name order), not a
        # name-keyed dict: dict keys land in the lowered module's arg
        # metadata, and gluon's auto-naming counter (dense0_, dense3_,
        # ...) would churn the persistent XLA cache key across processes
        # for structurally identical blocks. Names stay in this closure.
        def fn(param_datas, input_datas, rng):
            proxies = {}
            for (name, p), data in zip(params, param_datas):
                proxies[name] = NDArray(data)
                p._set_trace_proxy(proxies[name])
            orig_ids = {name: id(proxies[name]._data) for name, _ in params}
            wrapped = []
            it = iter(input_datas)
            for x in example_inputs:
                if isinstance(x, NDArray):
                    wrapped.append(NDArray(next(it)))
                else:
                    wrapped.append(x)
            prev_training = state.is_training
            state.is_training = is_training
            try:
                with _random.key_provider(_random.TraceKeyProvider(rng)):
                    out = block.forward(*wrapped)
            finally:
                state.is_training = prev_training
                for _, p in params:
                    p._clear_trace_proxy()
            outs = [out] if isinstance(out, NDArray) else list(out)
            out_datas = [o._data for o in outs]
            aux = []
            aux_names = []
            for name, _ in params:
                if id(proxies[name]._data) != orig_ids[name]:
                    aux_names.append(name)
                    aux.append(proxies[name]._data)
            aux_names_holder.clear()
            aux_names_holder.extend(aux_names)
            return out_datas, aux

        backend = getattr(self.block, '_subgraph_backend', None)
        if backend is not None:
            fn = backend.rewrite(fn)
        jitted = jax.jit(fn)
        # trace once now to discover aux names (jit caches the trace)
        ctx = None
        param_datas = [p.data(ctx)._data for _, p in params]
        input_datas = [x._data for x in example_inputs if isinstance(x, NDArray)]
        rng = jax.random.PRNGKey(0)
        _ = jax.eval_shape(jitted, param_datas, input_datas, rng)
        return jitted, list(aux_names_holder)


class SymbolBlock(HybridBlock):
    """Construct a block from a saved symbol+params (ref: block.py:1218)."""

    @staticmethod
    def imports(symbol_file, input_names, param_file=None, ctx=None):
        from .. import symbol as sym_mod
        s = sym_mod.load(symbol_file)
        if isinstance(input_names, str):
            input_names = [input_names]
        inputs = [sym_mod.var(n) for n in input_names]
        ret = SymbolBlock(s, inputs)
        if param_file is not None:
            from ..ndarray import load as nd_load
            ret._load_arg_dict(nd_load(param_file), ctx=ctx)
        return ret

    def _load_arg_dict(self, loaded, ctx=None):
        """Load {\"arg:name\"/\"aux:name\"/name: NDArray} into this block's
        symbol parameters (shared by imports and the ONNX importer)."""
        input_names = {i.name for i in self._sym_inputs}
        arg_names = set(self._sym_outputs.list_arguments()) - input_names
        for key, arr in loaded.items():
            name = key.split(':', 1)[1] if ':' in key else key
            if name not in arg_names:
                continue
            p = self.params.get(name)
            p.shape = tuple(arr.shape)
            p.initialize(init='zeros', ctx=ctx)
            p.set_data(arr)

    def __init__(self, outputs, inputs, params=None):
        super().__init__(prefix='', params=params)
        if isinstance(outputs, (list, tuple)) and len(outputs) == 1:
            outputs = outputs[0]
        self._sym_outputs = outputs
        self._sym_inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        input_names = {i.name for i in self._sym_inputs}
        for name in outputs.list_arguments():
            if name not in input_names:
                self.params.get(name, allow_deferred_init=True)

    def forward(self, *args):
        from .. import symbol as sym_mod
        bindings = {i.name: x for i, x in zip(self._sym_inputs, args)}
        ctx = args[0].context if isinstance(args[0], NDArray) else None
        for name, p in self.params.items():
            if p._data is not None:
                bindings[name] = p.data(ctx)
        return self._sym_outputs.eval_dict(bindings)
