"""Basic neural network layers (ref: python/mxnet/gluon/nn/basic_layers.py)."""
from __future__ import annotations

import numpy as onp

from ...base import MXNetError
from ...ndarray.ndarray import NDArray
from ..block import Block, HybridBlock
from ..parameter import Parameter


class Sequential(Block):
    """Stack of blocks (ref: basic_layers.py Sequential)."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix, params)

    def add(self, *blocks):
        for block in blocks:
            self.register_child(block)

    def forward(self, x):
        for block in self._children.values():
            x = block(x)
        return x

    def __getitem__(self, key):
        layers = list(self._children.values())[key]
        if isinstance(layers, list):
            net = type(self)(prefix=self._prefix)
            with net.name_scope():
                net.add(*layers)
            return net
        return layers

    def __len__(self):
        return len(self._children)

    def __iter__(self):
        return iter(self._children.values())

    def hybridize(self, active=True, **kwargs):
        super().hybridize(active, **kwargs)


class HybridSequential(HybridBlock):
    """Hybridizable stack (ref: basic_layers.py HybridSequential)."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix, params)

    def add(self, *blocks):
        for block in blocks:
            self.register_child(block)

    def forward(self, x):
        for block in self._children.values():
            x = block(x)
        return x

    hybrid_forward = None  # containers override forward directly

    def __getitem__(self, key):
        layers = list(self._children.values())[key]
        if isinstance(layers, list):
            net = type(self)(prefix=self._prefix)
            with net.name_scope():
                net.add(*layers)
            return net
        return layers

    def __len__(self):
        return len(self._children)

    def __iter__(self):
        return iter(self._children.values())


class Dense(HybridBlock):
    """Fully-connected layer (ref: basic_layers.py Dense)."""

    def __init__(self, units, activation=None, use_bias=True, flatten=True,
                 dtype='float32', weight_initializer=None,
                 bias_initializer='zeros', in_units=0, **kwargs):
        super().__init__(**kwargs)
        self._units = units
        self._flatten = flatten
        self._act_type = activation
        with self.name_scope():
            self.weight = self.params.get(
                'weight', shape=(units, in_units), dtype=dtype,
                init=weight_initializer, allow_deferred_init=True)
            if use_bias:
                self.bias = self.params.get(
                    'bias', shape=(units,), dtype=dtype,
                    init=bias_initializer, allow_deferred_init=True)
            else:
                self.bias = None

    def _infer_param_shapes(self, x, args):
        in_units = int(onp.prod(x.shape[1:])) if self._flatten else x.shape[-1]
        self.weight._finish_deferred_init((self._units, in_units))
        if self.bias is not None and self.bias._data is None:
            self.bias._finish_deferred_init((self._units,))

    def hybrid_forward(self, F, x, weight, bias=None):
        out = F.fully_connected(x, weight, bias, num_hidden=self._units,
                                no_bias=bias is None, flatten=self._flatten)
        if self._act_type is not None:
            out = F.activation(out, act_type=self._act_type)
        return out

    def __repr__(self):
        shape = self.weight.shape
        return (f"Dense({shape[1] if shape and len(shape) > 1 else None} -> "
                f"{self._units}, "
                f"{'linear' if self._act_type is None else self._act_type})")


class Dropout(HybridBlock):
    def __init__(self, rate, axes=(), **kwargs):
        super().__init__(**kwargs)
        self._rate = rate
        self._axes = axes

    def hybrid_forward(self, F, x):
        if self._rate > 0:
            return F.dropout(x, p=self._rate, axes=self._axes)
        return F.identity(x)

    def __repr__(self):
        return f"Dropout(p = {self._rate}, axes={self._axes})"


class BatchNorm(HybridBlock):
    """Ref: basic_layers.py BatchNorm; running stats are functional outputs
    of the batch_norm op, written back by set_data/trace write-back."""

    def __init__(self, axis=1, momentum=0.9, epsilon=1e-5, center=True,
                 scale=True, use_global_stats=False, beta_initializer='zeros',
                 gamma_initializer='ones', running_mean_initializer='zeros',
                 running_variance_initializer='ones', in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self._kwargs = {'axis': axis, 'eps': epsilon, 'momentum': momentum,
                        'fix_gamma': not scale,
                        'use_global_stats': use_global_stats}
        self._axis = axis
        self.gamma = self.params.get(
            'gamma', grad_req='write' if scale else 'null',
            shape=(in_channels,), init=gamma_initializer,
            allow_deferred_init=True, differentiable=scale)
        self.beta = self.params.get(
            'beta', grad_req='write' if center else 'null',
            shape=(in_channels,), init=beta_initializer,
            allow_deferred_init=True, differentiable=center)
        self.running_mean = self.params.get(
            'running_mean', grad_req='null', shape=(in_channels,),
            init=running_mean_initializer, allow_deferred_init=True,
            differentiable=False)
        self.running_var = self.params.get(
            'running_var', grad_req='null', shape=(in_channels,),
            init=running_variance_initializer, allow_deferred_init=True,
            differentiable=False)

    def _infer_param_shapes(self, x, args):
        c = x.shape[self._axis]
        for p in (self.gamma, self.beta, self.running_mean, self.running_var):
            if p._data is None:
                p._finish_deferred_init((c,))

    def hybrid_forward(self, F, x, gamma, beta, running_mean, running_var):
        out, new_mean, new_var = F.batch_norm(
            x, gamma, beta, running_mean, running_var, **self._kwargs)
        if isinstance(new_mean, NDArray):
            # write back running statistics (mutation threaded out under
            # trace; symbolic trace exports the inference graph, no update)
            running_mean._data = new_mean._data
            running_var._data = new_var._data
        return out

    def __repr__(self):
        in_channels = self.gamma.shape[0] if self.gamma.shape else None
        return f"BatchNorm(axis={self._axis}, in_channels={in_channels})"


class SyncBatchNorm(BatchNorm):
    """Cross-device BatchNorm (ref: src/operator/contrib/sync_batch_norm.cc).

    On TPU, when the compiled step runs under shard_map/pjit over a mesh with
    a data axis, batch statistics are reduced with psum over that axis; in
    eager single-device mode it equals BatchNorm.
    """

    def __init__(self, in_channels=0, num_devices=None, **kwargs):
        super().__init__(in_channels=in_channels, **kwargs)
        self._num_devices = num_devices

    def hybrid_forward(self, F, x, gamma, beta, running_mean, running_var):
        from ...parallel import collectives
        axis_name = collectives.current_data_axis()
        kwargs = dict(self._kwargs)
        if axis_name is not None:
            out, new_mean, new_var = F.sync_batch_norm_op(
                x, gamma, beta, running_mean, running_var,
                axis_name=axis_name, **kwargs)
        else:
            out, new_mean, new_var = F.batch_norm(
                x, gamma, beta, running_mean, running_var, **kwargs)
        if isinstance(new_mean, NDArray):
            running_mean._data = new_mean._data
            running_var._data = new_var._data
        return out


class LayerNorm(HybridBlock):
    def __init__(self, axis=-1, epsilon=1e-5, center=True, scale=True,
                 beta_initializer='zeros', gamma_initializer='ones',
                 in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self._axis = axis
        self._epsilon = epsilon
        self.gamma = self.params.get('gamma', grad_req='write' if scale else 'null',
                                     shape=(in_channels,),
                                     init=gamma_initializer,
                                     allow_deferred_init=True)
        self.beta = self.params.get('beta', grad_req='write' if center else 'null',
                                    shape=(in_channels,),
                                    init=beta_initializer,
                                    allow_deferred_init=True)

    def _infer_param_shapes(self, x, args):
        c = x.shape[self._axis]
        for p in (self.gamma, self.beta):
            if p._data is None:
                p._finish_deferred_init((c,))

    def hybrid_forward(self, F, x, gamma, beta):
        return F.layer_norm(x, gamma, beta, axis=self._axis, eps=self._epsilon)


class GroupNorm(HybridBlock):
    def __init__(self, num_groups=1, epsilon=1e-5, center=True, scale=True,
                 beta_initializer='zeros', gamma_initializer='ones',
                 in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self._num_groups = num_groups
        self._epsilon = epsilon
        self.gamma = self.params.get('gamma', grad_req='write' if scale else 'null',
                                     shape=(in_channels,), init=gamma_initializer,
                                     allow_deferred_init=True)
        self.beta = self.params.get('beta', grad_req='write' if center else 'null',
                                    shape=(in_channels,), init=beta_initializer,
                                    allow_deferred_init=True)

    def _infer_param_shapes(self, x, args):
        c = x.shape[1]
        for p in (self.gamma, self.beta):
            if p._data is None:
                p._finish_deferred_init((c,))

    def hybrid_forward(self, F, x, gamma, beta):
        return F.group_norm(x, gamma, beta, num_groups=self._num_groups,
                            eps=self._epsilon)


class InstanceNorm(HybridBlock):
    def __init__(self, axis=1, epsilon=1e-5, center=True, scale=False,
                 beta_initializer='zeros', gamma_initializer='ones',
                 in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self._epsilon = epsilon
        self._axis = axis
        self.gamma = self.params.get('gamma', grad_req='write' if scale else 'null',
                                     shape=(in_channels,), init=gamma_initializer,
                                     allow_deferred_init=True)
        self.beta = self.params.get('beta', grad_req='write' if center else 'null',
                                    shape=(in_channels,), init=beta_initializer,
                                    allow_deferred_init=True)

    def _infer_param_shapes(self, x, args):
        c = x.shape[self._axis]
        for p in (self.gamma, self.beta):
            if p._data is None:
                p._finish_deferred_init((c,))

    def hybrid_forward(self, F, x, gamma, beta):
        return F.instance_norm(x, gamma, beta, eps=self._epsilon)


class Embedding(HybridBlock):
    """Ref: basic_layers.py Embedding."""

    def __init__(self, input_dim, output_dim, dtype='float32',
                 weight_initializer=None, sparse_grad=False, **kwargs):
        super().__init__(**kwargs)
        self._input_dim = input_dim
        self._output_dim = output_dim
        self._sparse_grad = sparse_grad
        self.weight = self.params.get(
            'weight', shape=(input_dim, output_dim), dtype=dtype,
            init=weight_initializer, allow_deferred_init=True,
            grad_stype='row_sparse' if sparse_grad else 'default')

    def hybrid_forward(self, F, x, weight):
        return F.embedding(x, weight, input_dim=self._input_dim,
                           output_dim=self._output_dim,
                           sparse_grad=self._sparse_grad)

    def __repr__(self):
        return f"Embedding({self._input_dim} -> {self._output_dim})"


class Flatten(HybridBlock):
    def hybrid_forward(self, F, x):
        return F.flatten(x)

    def __repr__(self):
        return "Flatten"


class Lambda(Block):
    """Wrap a function as a Block (ref: basic_layers.py Lambda)."""

    def __init__(self, function, prefix=None):
        super().__init__(prefix=prefix)
        if isinstance(function, str):
            from ... import ndarray as nd_mod
            if not hasattr(nd_mod, function):
                raise MXNetError(f"Function name {function} is not found in nd.")
            self._func_impl = getattr(nd_mod, function)
            self._func_name = function
        elif callable(function):
            self._func_impl = function
            self._func_name = function.__name__
        else:
            raise ValueError("Unrecognized function in lambda")

    def forward(self, *args):
        return self._func_impl(*args)

    def __repr__(self):
        return f"Lambda({self._func_name})"


class HybridLambda(HybridBlock):
    def __init__(self, function, prefix=None):
        super().__init__(prefix=prefix)
        from ... import ndarray as nd_mod
        if isinstance(function, str):
            if not hasattr(nd_mod, function):
                raise MXNetError(f"Function name {function} is not found in nd.")
            fname = function
            self._func = lambda F, *args: getattr(F, fname)(*args)
            self._func_name = function
        elif callable(function):
            self._func = function
            self._func_name = function.__name__
        else:
            raise ValueError("Unrecognized function in lambda")

    def hybrid_forward(self, F, x, *args):
        return self._func(F, x, *args)

    def __repr__(self):
        return f"HybridLambda({self._func_name})"
