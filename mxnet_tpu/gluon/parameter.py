"""Parameter / ParameterDict (ref: python/mxnet/gluon/parameter.py).

Deferred init (shape resolved at first forward, ref: parameter.py:114-116,
229-234) is preserved. Parameters keep per-context NDArray copies like the
reference (the copies are how single-process multi-device DP tests work);
on a TPU pod the compiled training path instead shards one copy over the
mesh (mxnet_tpu.parallel) — both views are supported.
"""
from __future__ import annotations

import threading

import numpy as onp

from ..base import MXNetError
from ..context import Context, cpu, current_context
from ..ndarray.ndarray import NDArray, array, zeros as nd_zeros
from .. import initializer as init_mod


class DeferredInitializationError(MXNetError):
    pass


class Parameter:
    """A Block parameter (ref: gluon/parameter.py Parameter)."""

    def __init__(self, name, grad_req='write', shape=None, dtype='float32',
                 lr_mult=1.0, wd_mult=1.0, init=None, allow_deferred_init=False,
                 differentiable=True, stype='default', grad_stype='default'):
        self.name = name
        self._grad_req = grad_req if differentiable else 'null'
        if isinstance(shape, int):
            shape = (shape,)
        self.shape = tuple(shape) if shape is not None else None
        self.dtype = dtype
        self.lr_mult = lr_mult
        self.wd_mult = wd_mult
        self.init = init
        self.allow_deferred_init = allow_deferred_init
        self._stype = stype
        self._grad_stype = grad_stype
        self._data = None          # list of per-ctx NDArray
        self._grad = None
        self._ctx_list = None
        self._deferred_init = ()
        self._trace_tls = threading.local()

    def __deepcopy__(self, memo):
        """Deep-copy everything except the thread-local proxy stack (fresh
        per copy) — required for amp.convert_hybrid_block's model clone."""
        import copy as _copy
        new = object.__new__(type(self))
        memo[id(self)] = new
        for k, v in self.__dict__.items():
            if k == '_trace_tls':
                new._trace_tls = threading.local()
            else:
                setattr(new, k, _copy.deepcopy(v, memo))
        return new

    # --- trace override: CachedOp substitutes tracer-backed proxies.
    # A stack, because hybridized blocks nest (a child CachedOp traces
    # inside its parent's trace and must restore the parent's proxies).
    def _set_trace_proxy(self, arr):
        if not hasattr(self._trace_tls, 'proxies'):
            self._trace_tls.proxies = []
        self._trace_tls.proxies.append(arr)

    def _clear_trace_proxy(self):
        stack = getattr(self._trace_tls, 'proxies', None)
        if stack:
            stack.pop()

    @property
    def _trace_proxy(self):
        stack = getattr(self._trace_tls, 'proxies', None)
        return stack[-1] if stack else None

    # ------------------------------------------------------------------
    @property
    def grad_req(self):
        return self._grad_req

    @grad_req.setter
    def grad_req(self, req):
        assert req in ('write', 'add', 'null')
        self._grad_req = req
        if req == 'null':
            self._grad = None
        elif self._data is not None and self._grad is None:
            self._init_grad()

    @property
    def stype(self):
        return self._stype

    def _shape_complete(self):
        return self.shape is not None and all(s > 0 for s in self.shape)

    def initialize(self, init=None, ctx=None, default_init=None,
                   force_reinit=False):
        """Ref: parameter.py initialize."""
        if default_init is None:
            default_init = init_mod.Uniform()
        if self._data is not None and not force_reinit:
            return
        if ctx is None:
            ctx = [current_context()]
        if isinstance(ctx, Context):
            ctx = [ctx]
        self._ctx_list = list(ctx)
        if not self._shape_complete():
            if self.allow_deferred_init:
                self._deferred_init = (init, ctx, default_init)
                return
            raise MXNetError(
                f"Cannot initialize Parameter '{self.name}' because it has "
                f"invalid shape: {self.shape}.")
        self._finish_init(init, ctx, default_init)

    def _finish_init(self, init, ctx, default_init):
        initializer = init or self.init or default_init
        host = nd_zeros(self.shape, dtype=self.dtype)
        init_mod.create(initializer)(
            init_mod.InitDesc(self.name, {'__init_name__': self.name}), host)
        self._data = [host.as_in_context(c) if c != cpu(0) else
                      NDArray(host._data, c) for c in ctx]
        self._ctx_list = list(ctx)
        self._deferred_init = ()
        if self._grad_req != 'null':
            self._init_grad()

    def _init_grad(self):
        self._grad = []
        for d in self._data:
            d.attach_grad(self._grad_req, stype=self._grad_stype)
            self._grad.append(d.grad)

    def _finish_deferred_init(self, shape=None):
        if shape is not None:
            new_shape = tuple(shape)
            if self.shape is not None:
                merged = []
                for old, new in zip(self.shape, new_shape):
                    if old > 0 and new > 0 and old != new:
                        raise MXNetError(
                            f"deferred shape mismatch for {self.name}: "
                            f"{self.shape} vs {new_shape}")
                    merged.append(old if old > 0 else new)
                self.shape = tuple(merged)
            else:
                self.shape = new_shape
        if not self._deferred_init:
            raise DeferredInitializationError(
                f"Parameter '{self.name}' has not been initialized")
        init, ctx, default_init = self._deferred_init
        self._finish_init(init, ctx, default_init)

    def _check_initialized(self, ctx=None):
        if self._data is None:
            if self._deferred_init:
                raise DeferredInitializationError(
                    f"Parameter '{self.name}' has not been initialized yet "
                    "because initialization was deferred. Call net(data) once "
                    "or initialize with a complete shape.")
            raise MXNetError(
                f"Parameter '{self.name}' has not been initialized. You "
                "should initialize parameters and create Trainer first.")

    def _ctx_index(self, ctx):
        if ctx is None:
            return 0
        for i, c in enumerate(self._ctx_list):
            if c == ctx:
                return i
        if len(self._data) == 1:
            # single copy serves every context (it may be mesh-sharded and
            # thus not owned by any single logical device)
            return 0
        raise MXNetError(f"Parameter '{self.name}' was not initialized on "
                         f"context {ctx}; it is on {self._ctx_list}")

    def data(self, ctx=None) -> NDArray:
        proxy = self._trace_proxy
        if proxy is not None:
            return proxy
        self._check_initialized(ctx)
        return self._data[self._ctx_index(ctx)]

    def list_data(self):
        self._check_initialized()
        return list(self._data)

    def grad(self, ctx=None) -> NDArray:
        self._check_initialized(ctx)
        if self._grad is None:
            raise MXNetError(f"Parameter '{self.name}' does not have gradient "
                             "(grad_req='null')")
        return self._data[self._ctx_index(ctx)].grad

    def list_grad(self):
        self._check_initialized()
        return [d.grad for d in self._data]

    def list_ctx(self):
        if self._data is None and self._deferred_init:
            return self._deferred_init[1]
        self._check_initialized()
        return list(self._ctx_list)

    def set_data(self, data):
        if not isinstance(data, NDArray):
            data = array(data)
        if self._data is None:
            if self._deferred_init:
                self.shape = data.shape
                self._finish_deferred_init()
            else:
                raise MXNetError(f"Parameter '{self.name}' not initialized")
        import jax
        if tuple(data.shape) != tuple(self._data[0].shape):
            raise MXNetError(
                f"Parameter '{self.name}': shape mismatch in set_data: "
                f"expected {tuple(self._data[0].shape)}, got {tuple(data.shape)}")
        src = data._data
        if src.dtype != self._data[0]._data.dtype:
            src = src.astype(self._data[0]._data.dtype)
        for d in self._data:
            # preserve each copy's placement/sharding (a single copy may be
            # mesh-sharded after a pjit step — don't gather it to one device)
            d._data = jax.device_put(src, d._data.sharding)
        return self

    def zero_grad(self):
        if self._grad is None:
            return
        import jax.numpy as jnp
        for d in self._data:
            if d.grad is not None:
                d.grad._data = jnp.zeros_like(d.grad._data)

    def reset_ctx(self, ctx):
        if isinstance(ctx, Context):
            ctx = [ctx]
        if self._data is not None:
            host = self._data[0]
            self._data = [host.as_in_context(c) for c in ctx]
            self._ctx_list = list(ctx)
            if self._grad_req != 'null':
                self._init_grad()

    def cast(self, dtype):
        self.dtype = dtype
        if self._data is None:
            return
        for d in self._data:
            d._data = d._data.astype(onp.dtype(dtype))
        if self._grad is not None:
            for g in self._grad:
                g._data = g._data.astype(onp.dtype(dtype))

    def var(self):
        from .. import symbol
        return symbol.var(self.name, shape=self.shape, dtype=self.dtype)

    def row_sparse_data(self, row_id):
        from ..ndarray import sparse
        return sparse.retain(self.data(), row_id)

    def list_row_sparse_data(self, row_id):
        return [self.row_sparse_data(row_id)]

    def __repr__(self):
        return f"Parameter {self.name} (shape={self.shape}, dtype={self.dtype})"


class Constant(Parameter):
    """Non-differentiable constant parameter (ref: parameter.py Constant)."""

    def __init__(self, name, value):
        if not isinstance(value, NDArray):
            value = array(value)
        self.value = value

        class CInit(init_mod.Initializer):
            def _init_weight(self2, _, arr):
                arr[:] = value.asnumpy()
            _init_default = _init_weight

        super().__init__(name, grad_req='null', shape=value.shape,
                         dtype=str(value.dtype), init=CInit())


class ParameterDict:
    """Ref: gluon/parameter.py ParameterDict."""

    def __init__(self, prefix='', shared=None):
        self._prefix = prefix
        self._params = {}
        self._shared = shared

    @property
    def prefix(self):
        return self._prefix

    def __getitem__(self, key):
        return self._params[key]

    def __iter__(self):
        return iter(self._params)

    def __len__(self):
        return len(self._params)

    def __repr__(self):
        s = f"{type(self).__name__}(\n"
        for p in self._params.values():
            s += f"  {p}\n"
        return s + ")"

    def items(self):
        return self._params.items()

    def keys(self):
        return self._params.keys()

    def values(self):
        return self._params.values()

    def _get_impl(self, name):
        if name in self._params:
            return self._params[name]
        if self._shared is not None and name in self._shared._params:
            self._params[name] = self._shared._params[name]
            return self._params[name]
        return None

    def get(self, name, **kwargs) -> Parameter:
        name = self._prefix + name
        param = self._get_impl(name)
        if param is None:
            param = Parameter(name, **kwargs)
            self._params[name] = param
        else:
            for k, v in kwargs.items():
                if hasattr(param, k) and getattr(param, k) is not None:
                    existing = getattr(param, k)
                    if k == 'shape' and v is not None and existing is not None:
                        v = tuple(v)
                        if len(v) == len(existing):
                            merged = tuple(
                                e if e > 0 else n for e, n in zip(existing, v))
                            param.shape = merged
                        continue
                else:
                    setattr(param, k, v)
        return param

    def get_constant(self, name, value=None):
        name = self._prefix + name
        param = self._get_impl(name)
        if param is None:
            if value is None:
                raise MXNetError(f"No constant named '{name}'")
            param = Constant(name, value)
            self._params[name] = param
        return param

    def update(self, other):
        for k, v in other.items():
            if k in self._params and self._params[k] is not v:
                raise MXNetError(f"duplicate parameter name {k}")
            self._params[k] = v

    def initialize(self, init=None, ctx=None, verbose=False,
                   force_reinit=False):
        if init is None:
            init = init_mod.Uniform()
        for _, v in self.items():
            v.initialize(None, ctx, init, force_reinit=force_reinit)

    def zero_grad(self):
        for p in self.values():
            p.zero_grad()

    def reset_ctx(self, ctx):
        for p in self.values():
            p.reset_ctx(ctx)

    def list_ctx(self):
        s = set()
        for p in self.values():
            s.update(p.list_ctx())
        return list(s)

    def setattr(self, name, value):
        for p in self.values():
            setattr(p, name, value)

    def save(self, filename, strip_prefix=''):
        """Reference binary .params container (ndarray.cc NDArray::Save)."""
        from ..serialization import atomic_write_file, save_ndarray_file
        arg_dict = {}
        for p in self.values():
            if p._data is None:
                raise MXNetError(
                    f"Parameter '{p.name}' is uninitialized; initialize "
                    "before save")
            name = p.name
            if name.startswith(strip_prefix):
                name = name[len(strip_prefix):]
            arg_dict[name] = p.data().asnumpy()
        atomic_write_file(filename, save_ndarray_file(arg_dict))

    def load(self, filename, ctx=None, allow_missing=False,
             ignore_extra=False, restore_prefix=''):
        from ..serialization import load_params_dict
        with open(filename, 'rb') as f:
            # allow_pickle: legacy round-1 files (restricted unpickler)
            arg_dict = load_params_dict(f.read(), allow_pickle=True)
        if restore_prefix:
            arg_dict = {restore_prefix + k: v for k, v in arg_dict.items()}
        for name, p in self.items():
            if name not in arg_dict:
                if not allow_missing:
                    raise MXNetError(f"Parameter {name} missing in file")
                continue
            if p._data is None and p._deferred_init:
                p.shape = arg_dict[name].shape
                p._finish_deferred_init()
            elif p._data is None:
                p.initialize(ctx=ctx or [cpu(0)])
            p.set_data(array(arg_dict[name]))
        if not ignore_extra:
            extra = set(arg_dict) - set(self._params)
            if extra:
                raise MXNetError(f"extra parameters in file: {sorted(extra)}")
