"""RNN cells (ref: python/mxnet/gluon/rnn/rnn_cell.py)."""
from __future__ import annotations

from ...base import MXNetError
from ...ndarray.ndarray import NDArray
from ... import ndarray as nd
from ..block import Block, HybridBlock
from ..parameter import Parameter


class RecurrentCell(Block):
    """Base recurrent cell (ref: rnn_cell.py RecurrentCell)."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._modified = False
        self.reset()

    def reset(self):
        self._init_counter = -1
        self._counter = -1
        for cell in self._children.values():
            if isinstance(cell, RecurrentCell):
                cell.reset()

    def state_info(self, batch_size=0):
        raise NotImplementedError

    def begin_state(self, batch_size=0, func=nd.zeros, **kwargs):
        assert not self._modified
        states = []
        for info in self.state_info(batch_size):
            self._init_counter += 1
            if info is not None:
                info.update(kwargs)
            else:
                info = kwargs
            state = func(**info)
            states.append(state)
        return states

    def __call__(self, inputs, states):
        self._counter += 1
        return self.forward(inputs, states)

    def forward(self, inputs, states):
        raise NotImplementedError

    def unroll(self, length, inputs, begin_state=None, layout='NTC',
               merge_outputs=None, valid_length=None):
        """Ref: rnn_cell.py unroll."""
        axis = layout.find('T')
        batch_axis = layout.find('N')
        batch_size = inputs.shape[batch_axis]
        if begin_state is None:
            begin_state = self.begin_state(batch_size)
        states = begin_state
        outputs = []
        if axis == 1:
            seq = [nd._invoke(lambda d, t=t: d[:, t], inputs) for t in range(length)]
        else:
            seq = [nd._invoke(lambda d, t=t: d[t], inputs) for t in range(length)]
        for t in range(length):
            out, states = self(seq[t], states)
            outputs.append(out)
        if valid_length is not None:
            from ...ops import sequence as seq_ops
            stacked = nd.stack(*outputs, axis=axis)
            stacked = nd._invoke(seq_ops.sequence_mask, stacked, valid_length,
                                 use_sequence_length=True, axis=axis)
            if merge_outputs is False:
                outputs = [nd._invoke(lambda d, t=t: d[:, t] if axis == 1 else d[t],
                                      stacked) for t in range(length)]
            else:
                outputs = stacked
        elif merge_outputs is not False:
            outputs = nd.stack(*outputs, axis=axis)
        return outputs, states


class HybridRecurrentCell(RecurrentCell):
    pass


class RNNCell(HybridRecurrentCell):
    """Elman RNN cell (ref: rnn_cell.py RNNCell)."""

    def __init__(self, hidden_size, activation='tanh',
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer='zeros', h2h_bias_initializer='zeros',
                 input_size=0, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._hidden_size = hidden_size
        self._activation = activation
        self._input_size = input_size
        self.i2h_weight = self.params.get('i2h_weight',
                                          shape=(hidden_size, input_size),
                                          init=i2h_weight_initializer,
                                          allow_deferred_init=True)
        self.h2h_weight = self.params.get('h2h_weight',
                                          shape=(hidden_size, hidden_size),
                                          init=h2h_weight_initializer,
                                          allow_deferred_init=True)
        self.i2h_bias = self.params.get('i2h_bias', shape=(hidden_size,),
                                        init=i2h_bias_initializer,
                                        allow_deferred_init=True)
        self.h2h_bias = self.params.get('h2h_bias', shape=(hidden_size,),
                                        init=h2h_bias_initializer,
                                        allow_deferred_init=True)

    def state_info(self, batch_size=0):
        return [{'shape': (batch_size, self._hidden_size), '__layout__': 'NC'}]

    def _alias(self):
        return 'rnn'

    def _finish_deferred(self, inputs):
        if self.i2h_weight._data is None:
            self.i2h_weight._finish_deferred_init(
                (self._hidden_size, inputs.shape[-1]))
        for p in (self.h2h_weight, self.i2h_bias, self.h2h_bias):
            if p._data is None:
                p._finish_deferred_init()

    def forward(self, inputs, states):
        self._finish_deferred(inputs)
        i2h = nd.fully_connected(inputs, self.i2h_weight.data(),
                                 self.i2h_bias.data(),
                                 num_hidden=self._hidden_size)
        h2h = nd.fully_connected(states[0], self.h2h_weight.data(),
                                 self.h2h_bias.data(),
                                 num_hidden=self._hidden_size)
        output = nd.activation(i2h + h2h, act_type=self._activation)
        return output, [output]


class LSTMCell(HybridRecurrentCell):
    """Ref: rnn_cell.py LSTMCell. Gate order i, f, g, o (MXNet convention)."""

    def __init__(self, hidden_size, i2h_weight_initializer=None,
                 h2h_weight_initializer=None, i2h_bias_initializer='zeros',
                 h2h_bias_initializer='zeros', input_size=0, prefix=None,
                 params=None, activation='tanh',
                 recurrent_activation='sigmoid'):
        super().__init__(prefix=prefix, params=params)
        self._hidden_size = hidden_size
        self._input_size = input_size
        self._activation = activation
        self._recurrent_activation = recurrent_activation
        nh = hidden_size
        self.i2h_weight = self.params.get('i2h_weight', shape=(4 * nh, input_size),
                                          init=i2h_weight_initializer,
                                          allow_deferred_init=True)
        self.h2h_weight = self.params.get('h2h_weight', shape=(4 * nh, nh),
                                          init=h2h_weight_initializer,
                                          allow_deferred_init=True)
        self.i2h_bias = self.params.get('i2h_bias', shape=(4 * nh,),
                                        init=i2h_bias_initializer,
                                        allow_deferred_init=True)
        self.h2h_bias = self.params.get('h2h_bias', shape=(4 * nh,),
                                        init=h2h_bias_initializer,
                                        allow_deferred_init=True)

    def state_info(self, batch_size=0):
        return [{'shape': (batch_size, self._hidden_size), '__layout__': 'NC'},
                {'shape': (batch_size, self._hidden_size), '__layout__': 'NC'}]

    def _alias(self):
        return 'lstm'

    def _finish_deferred(self, inputs):
        if self.i2h_weight._data is None:
            self.i2h_weight._finish_deferred_init(
                (4 * self._hidden_size, inputs.shape[-1]))
        for p in (self.h2h_weight, self.i2h_bias, self.h2h_bias):
            if p._data is None:
                p._finish_deferred_init()

    def forward(self, inputs, states):
        self._finish_deferred(inputs)
        nh = self._hidden_size
        i2h = nd.fully_connected(inputs, self.i2h_weight.data(),
                                 self.i2h_bias.data(), num_hidden=4 * nh)
        h2h = nd.fully_connected(states[0], self.h2h_weight.data(),
                                 self.h2h_bias.data(), num_hidden=4 * nh)
        gates = i2h + h2h
        slice_gates = gates.split(4, axis=1)
        in_gate = nd.activation(slice_gates[0], act_type=self._recurrent_activation)
        forget_gate = nd.activation(slice_gates[1], act_type=self._recurrent_activation)
        in_transform = nd.activation(slice_gates[2], act_type=self._activation)
        out_gate = nd.activation(slice_gates[3], act_type=self._recurrent_activation)
        next_c = forget_gate * states[1] + in_gate * in_transform
        next_h = out_gate * nd.activation(next_c, act_type=self._activation)
        return next_h, [next_h, next_c]


class GRUCell(HybridRecurrentCell):
    """Ref: rnn_cell.py GRUCell. Gate order r, z, n (MXNet convention)."""

    def __init__(self, hidden_size, i2h_weight_initializer=None,
                 h2h_weight_initializer=None, i2h_bias_initializer='zeros',
                 h2h_bias_initializer='zeros', input_size=0, prefix=None,
                 params=None):
        super().__init__(prefix=prefix, params=params)
        self._hidden_size = hidden_size
        self._input_size = input_size
        nh = hidden_size
        self.i2h_weight = self.params.get('i2h_weight', shape=(3 * nh, input_size),
                                          init=i2h_weight_initializer,
                                          allow_deferred_init=True)
        self.h2h_weight = self.params.get('h2h_weight', shape=(3 * nh, nh),
                                          init=h2h_weight_initializer,
                                          allow_deferred_init=True)
        self.i2h_bias = self.params.get('i2h_bias', shape=(3 * nh,),
                                        init=i2h_bias_initializer,
                                        allow_deferred_init=True)
        self.h2h_bias = self.params.get('h2h_bias', shape=(3 * nh,),
                                        init=h2h_bias_initializer,
                                        allow_deferred_init=True)

    def state_info(self, batch_size=0):
        return [{'shape': (batch_size, self._hidden_size), '__layout__': 'NC'}]

    def _alias(self):
        return 'gru'

    def _finish_deferred(self, inputs):
        if self.i2h_weight._data is None:
            self.i2h_weight._finish_deferred_init(
                (3 * self._hidden_size, inputs.shape[-1]))
        for p in (self.h2h_weight, self.i2h_bias, self.h2h_bias):
            if p._data is None:
                p._finish_deferred_init()

    def forward(self, inputs, states):
        self._finish_deferred(inputs)
        nh = self._hidden_size
        prev_state_h = states[0]
        i2h = nd.fully_connected(inputs, self.i2h_weight.data(),
                                 self.i2h_bias.data(), num_hidden=3 * nh)
        h2h = nd.fully_connected(prev_state_h, self.h2h_weight.data(),
                                 self.h2h_bias.data(), num_hidden=3 * nh)
        i2h_r, i2h_z, i2h = i2h.split(3, axis=1)
        h2h_r, h2h_z, h2h = h2h.split(3, axis=1)
        reset_gate = nd.sigmoid(i2h_r + h2h_r)
        update_gate = nd.sigmoid(i2h_z + h2h_z)
        next_h_tmp = nd.tanh(i2h + reset_gate * h2h)
        next_h = (1. - update_gate) * next_h_tmp + update_gate * prev_state_h
        return next_h, [next_h]


class SequentialRNNCell(RecurrentCell):
    """Ref: rnn_cell.py SequentialRNNCell."""

    def add(self, cell):
        self.register_child(cell)

    def state_info(self, batch_size=0):
        infos = []
        for cell in self._children.values():
            infos.extend(cell.state_info(batch_size))
        return infos

    def begin_state(self, batch_size=0, **kwargs):
        states = []
        for cell in self._children.values():
            states.extend(cell.begin_state(batch_size, **kwargs))
        return states

    def forward(self, inputs, states):
        next_states = []
        p = 0
        for cell in self._children.values():
            n = len(cell.state_info())
            cell_states = states[p:p + n]
            p += n
            inputs, cell_states = cell(inputs, cell_states)
            next_states.extend(cell_states)
        return inputs, next_states

    def __len__(self):
        return len(self._children)


class DropoutCell(HybridRecurrentCell):
    def __init__(self, rate, axes=(), prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._rate = rate
        self._axes = axes

    def state_info(self, batch_size=0):
        return []

    def _alias(self):
        return 'dropout'

    def forward(self, inputs, states):
        if self._rate > 0:
            inputs = nd.dropout(inputs, p=self._rate, axes=self._axes)
        return inputs, states


class ModifierCell(HybridRecurrentCell):
    def __init__(self, base_cell):
        super().__init__(prefix=base_cell.prefix + 'mod_')
        base_cell._modified = True
        self.base_cell = base_cell

    @property
    def params(self):
        return self.base_cell.params

    def state_info(self, batch_size=0):
        return self.base_cell.state_info(batch_size)

    def begin_state(self, batch_size=0, func=nd.zeros, **kwargs):
        self.base_cell._modified = False
        begin = self.base_cell.begin_state(batch_size, func=func, **kwargs)
        self.base_cell._modified = True
        return begin


class ZoneoutCell(ModifierCell):
    def __init__(self, base_cell, zoneout_outputs=0., zoneout_states=0.):
        super().__init__(base_cell)
        self._zoneout_outputs = zoneout_outputs
        self._zoneout_states = zoneout_states
        self._prev_output = None

    def _alias(self):
        return 'zoneout'

    def reset(self):
        super().reset()
        self._prev_output = None

    def forward(self, inputs, states):
        next_output, next_states = self.base_cell(inputs, states)
        p_outputs, p_states = self._zoneout_outputs, self._zoneout_states

        def mask(p, like):
            return nd.dropout(nd.ones_like(like), p=p)

        prev_output = self._prev_output if self._prev_output is not None \
            else nd.zeros_like(next_output)
        output = (nd.where(mask(p_outputs, next_output), next_output, prev_output)
                  if p_outputs != 0. else next_output)
        new_states = ([nd.where(mask(p_states, new_s), new_s, old_s)
                       for new_s, old_s in zip(next_states, states)]
                      if p_states != 0. else next_states)
        self._prev_output = output
        return output, new_states


class ResidualCell(ModifierCell):
    def __init__(self, base_cell):
        super().__init__(base_cell)

    def forward(self, inputs, states):
        output, states = self.base_cell(inputs, states)
        output = output + inputs
        return output, states


class BidirectionalCell(HybridRecurrentCell):
    """Ref: rnn_cell.py BidirectionalCell."""

    def __init__(self, l_cell, r_cell, output_prefix='bi_'):
        super().__init__(prefix='', params=None)
        self.register_child(l_cell, 'l_cell')
        self.register_child(r_cell, 'r_cell')
        self._output_prefix = output_prefix

    def __call__(self, inputs, states):
        raise MXNetError("Bidirectional cannot be stepped. Please use unroll")

    def state_info(self, batch_size=0):
        infos = []
        for cell in self._children.values():
            infos.extend(cell.state_info(batch_size))
        return infos

    def begin_state(self, batch_size=0, **kwargs):
        states = []
        for cell in self._children.values():
            states.extend(cell.begin_state(batch_size, **kwargs))
        return states

    def unroll(self, length, inputs, begin_state=None, layout='NTC',
               merge_outputs=None, valid_length=None):
        axis = layout.find('T')
        batch_size = inputs.shape[layout.find('N')]
        if begin_state is None:
            begin_state = self.begin_state(batch_size)
        l_cell = self._children['l_cell']
        r_cell = self._children['r_cell']
        n_l = len(l_cell.state_info())
        l_outputs, l_states = l_cell.unroll(
            length, inputs, begin_state[:n_l], layout, merge_outputs=True,
            valid_length=valid_length)
        from ...ops import sequence as seq_ops
        rev_inputs = nd.flip(inputs, axis=(axis,)) if valid_length is None else \
            nd._invoke(seq_ops.sequence_reverse, inputs, valid_length,
                       use_sequence_length=True, axis=axis)
        r_outputs, r_states = r_cell.unroll(
            length, rev_inputs, begin_state[n_l:], layout, merge_outputs=True,
            valid_length=valid_length)
        if valid_length is None:
            r_outputs = nd.flip(r_outputs, axis=(axis,))
        else:
            from ...ops import sequence as seq_ops
            r_outputs = nd._invoke(seq_ops.sequence_reverse, r_outputs,
                                   valid_length, use_sequence_length=True,
                                   axis=axis)
        outputs = nd.concat(l_outputs, r_outputs, dim=2)
        return outputs, l_states + r_states
