"""Fused RNN layers (ref: python/mxnet/gluon/rnn/rnn_layer.py:306).

Backed by the fused `rnn` op (ops/nn.py, lax.scan over time) — the TPU
analog of the reference's cuDNN fused RNN kernel.
"""
from __future__ import annotations

import numpy as onp

from ...base import MXNetError
from ...ndarray.ndarray import NDArray, _invoke
from ... import ndarray as nd
from ...ops import nn as nn_ops
from ..block import HybridBlock
from . import rnn_cell


class _RNNLayer(HybridBlock):
    def __init__(self, hidden_size, num_layers, layout, dropout, bidirectional,
                 input_size, i2h_weight_initializer, h2h_weight_initializer,
                 i2h_bias_initializer, h2h_bias_initializer, mode,
                 projection_size=None, **kwargs):
        super().__init__(**kwargs)
        assert layout in ('TNC', 'NTC'), \
            f"Invalid layout {layout}; must be one of ['TNC' or 'NTC']"
        self._hidden_size = hidden_size
        self._projection_size = projection_size
        self._num_layers = num_layers
        self._mode = mode
        self._layout = layout
        self._dropout = dropout
        self._dir = 2 if bidirectional else 1
        self._input_size = input_size
        self._gates = {'rnn_relu': 1, 'rnn_tanh': 1, 'lstm': 4, 'gru': 3}[mode]
        ng, ni, nh = self._gates, input_size, hidden_size
        # register per-layer parameters exactly like the reference so that
        # saved parameter dicts line up (rnn_layer.py parameter naming)
        self._layer_params = []
        for j in range(num_layers):
            for d in ['l', 'r'][:self._dir]:
                size = ni if j == 0 else nh * self._dir
                w_i2h = self.params.get(f'{d}{j}_i2h_weight',
                                        shape=(ng * nh, size),
                                        init=i2h_weight_initializer,
                                        allow_deferred_init=True)
                w_h2h = self.params.get(f'{d}{j}_h2h_weight',
                                        shape=(ng * nh, nh),
                                        init=h2h_weight_initializer,
                                        allow_deferred_init=True)
                b_i2h = self.params.get(f'{d}{j}_i2h_bias', shape=(ng * nh,),
                                        init=i2h_bias_initializer,
                                        allow_deferred_init=True)
                b_h2h = self.params.get(f'{d}{j}_h2h_bias', shape=(ng * nh,),
                                        init=h2h_bias_initializer,
                                        allow_deferred_init=True)
                setattr(self, f'{d}{j}_i2h_weight', w_i2h)
                setattr(self, f'{d}{j}_h2h_weight', w_h2h)
                setattr(self, f'{d}{j}_i2h_bias', b_i2h)
                setattr(self, f'{d}{j}_h2h_bias', b_h2h)
                self._layer_params.append((w_i2h, w_h2h, b_i2h, b_h2h))

    def state_info(self, batch_size=0):
        raise NotImplementedError

    def _finish_deferred(self, inputs):
        ni = inputs.shape[-1]
        ng, nh = self._gates, self._hidden_size
        idx = 0
        for j in range(self._num_layers):
            for _ in range(self._dir):
                size = ni if j == 0 else nh * self._dir
                w_i2h, w_h2h, b_i2h, b_h2h = self._layer_params[idx]
                if w_i2h._data is None:
                    w_i2h._finish_deferred_init((ng * nh, size))
                for p in (w_h2h, b_i2h, b_h2h):
                    if p._data is None:
                        p._finish_deferred_init()
                idx += 1

    def begin_state(self, batch_size=0, func=nd.zeros, **kwargs):
        states = []
        for i, info in enumerate(self.state_info(batch_size)):
            if info is not None:
                info.update(kwargs)
            else:
                info = kwargs
            states.append(func(**info))
        return states

    def __call__(self, inputs, states=None, **kwargs):
        self._finish_deferred(inputs if self._layout == 'TNC'
                              else inputs)
        batch_size = inputs.shape[self._layout.find('N')]
        skip_states = states is None
        if skip_states:
            states = self.begin_state(batch_size)
        if isinstance(states, NDArray):
            states = [states]
        out = self.forward(inputs, states)
        if skip_states:
            return out[0]
        return out

    def forward(self, inputs, states):
        if self._layout == 'NTC':
            inputs = inputs.swapaxes(0, 1)
        ctx = inputs.context
        # pack parameters into the canonical flat vector
        flat_ws = []
        for w_i2h, w_h2h, _, _ in self._layer_params:
            flat_ws.append(w_i2h.data(ctx).reshape(-1))
            flat_ws.append(w_h2h.data(ctx).reshape(-1))
        for _, _, b_i2h, b_h2h in self._layer_params:
            flat_ws.append(b_i2h.data(ctx).reshape(-1))
            flat_ws.append(b_h2h.data(ctx).reshape(-1))
        params_vec = nd.concat(*flat_ws, dim=0)
        if self._mode == 'lstm':
            out = _invoke(nn_ops.rnn, inputs, params_vec, states[0], states[1],
                          state_size=self._hidden_size,
                          num_layers=self._num_layers, mode=self._mode,
                          bidirectional=self._dir == 2, p=self._dropout)
            output, h, c = out
            new_states = [h, c]
        else:
            out = _invoke(nn_ops.rnn, inputs, params_vec, states[0],
                          state_size=self._hidden_size,
                          num_layers=self._num_layers, mode=self._mode,
                          bidirectional=self._dir == 2, p=self._dropout)
            output, h = out
            new_states = [h]
        if self._layout == 'NTC':
            output = output.swapaxes(0, 1)
        return output, new_states

    def _unfuse(self):
        """Return the SequentialRNNCell equivalent (ref: rnn_layer.py:147)."""
        get_cell = {
            'rnn_relu': lambda **kw: rnn_cell.RNNCell(
                self._hidden_size, activation='relu', **kw),
            'rnn_tanh': lambda **kw: rnn_cell.RNNCell(
                self._hidden_size, activation='tanh', **kw),
            'lstm': lambda **kw: rnn_cell.LSTMCell(self._hidden_size, **kw),
            'gru': lambda **kw: rnn_cell.GRUCell(self._hidden_size, **kw),
        }[self._mode]
        stack = rnn_cell.SequentialRNNCell(prefix=self.prefix, params=self.params)
        with stack.name_scope():
            ni = self._input_size
            for i in range(self._num_layers):
                kwargs = {'input_size': ni}
                if self._dir == 2:
                    stack.add(rnn_cell.BidirectionalCell(
                        get_cell(prefix=f'l{i}_', **kwargs),
                        get_cell(prefix=f'r{i}_', **kwargs)))
                else:
                    stack.add(get_cell(prefix=f'l{i}_', **kwargs))
                if self._dropout > 0 and i != self._num_layers - 1:
                    stack.add(rnn_cell.DropoutCell(self._dropout))
                ni = self._hidden_size * self._dir
        return stack

    def __repr__(self):
        return (f"{type(self).__name__}({self._input_size} -> "
                f"{self._hidden_size}, {self._layout}, "
                f"num_layers={self._num_layers})")


class RNN(_RNNLayer):
    """Ref: rnn_layer.py RNN."""

    def __init__(self, hidden_size, num_layers=1, activation='relu',
                 layout='TNC', dropout=0, bidirectional=False,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer='zeros', h2h_bias_initializer='zeros',
                 input_size=0, **kwargs):
        super().__init__(hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size, i2h_weight_initializer,
                         h2h_weight_initializer, i2h_bias_initializer,
                         h2h_bias_initializer, 'rnn_' + activation, **kwargs)

    def state_info(self, batch_size=0):
        return [{'shape': (self._num_layers * self._dir, batch_size,
                           self._hidden_size), '__layout__': 'LNC'}]


class LSTM(_RNNLayer):
    """Ref: rnn_layer.py LSTM."""

    def __init__(self, hidden_size, num_layers=1, layout='TNC', dropout=0,
                 bidirectional=False, input_size=0,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer='zeros', h2h_bias_initializer='zeros',
                 projection_size=None, **kwargs):
        super().__init__(hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size, i2h_weight_initializer,
                         h2h_weight_initializer, i2h_bias_initializer,
                         h2h_bias_initializer, 'lstm', projection_size,
                         **kwargs)

    def state_info(self, batch_size=0):
        return [{'shape': (self._num_layers * self._dir, batch_size,
                           self._hidden_size), '__layout__': 'LNC'},
                {'shape': (self._num_layers * self._dir, batch_size,
                           self._hidden_size), '__layout__': 'LNC'}]


class GRU(_RNNLayer):
    """Ref: rnn_layer.py GRU."""

    def __init__(self, hidden_size, num_layers=1, layout='TNC', dropout=0,
                 bidirectional=False, input_size=0,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer='zeros', h2h_bias_initializer='zeros',
                 **kwargs):
        super().__init__(hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size, i2h_weight_initializer,
                         h2h_weight_initializer, i2h_bias_initializer,
                         h2h_bias_initializer, 'gru', **kwargs)

    def state_info(self, batch_size=0):
        return [{'shape': (self._num_layers * self._dir, batch_size,
                           self._hidden_size), '__layout__': 'LNC'}]
