"""Gluon utilities (ref: python/mxnet/gluon/utils.py)."""
from __future__ import annotations

import hashlib
import os

from ..base import MXNetError
from ..ndarray.ndarray import NDArray, array
from ..ndarray.utils import split_data, split_and_load  # noqa: F401


def clip_global_norm(arrays, max_norm, check_isfinite=True):
    """Ref: gluon/utils.py clip_global_norm."""
    import math
    import jax.numpy as jnp

    assert len(arrays) > 0
    total = 0.0
    for arr in arrays:
        total = total + jnp.sum(jnp.square(arr._data.astype(jnp.float32)))
    total_norm = jnp.sqrt(total)
    scale = jnp.minimum(1.0, max_norm / (total_norm + 1e-8))
    tn = float(total_norm)
    if check_isfinite and not math.isfinite(tn):
        import warnings
        warnings.warn(UserWarning('nan or inf is detected.'))
        return tn
    for arr in arrays:
        arr._data = (arr._data * scale).astype(arr._data.dtype)
    return tn


def replace_file(src, dst):
    """Atomically move src over dst (ref: gluon/utils.py replace_file)."""
    os.replace(src, dst)


def check_sha1(filename, sha1_hash):
    sha1 = hashlib.sha1()
    with open(filename, 'rb') as f:
        while True:
            data = f.read(1048576)
            if not data:
                break
            sha1.update(data)
    return sha1.hexdigest() == sha1_hash


def download(url, path=None, overwrite=False, sha1_hash=None, retries=5,
             verify_ssl=True):
    """Download stub — this environment has no egress; provide files locally
    (ref: gluon/utils.py download)."""
    fname = path if path and not os.path.isdir(path) else \
        os.path.join(path or '.', url.split('/')[-1])
    if os.path.exists(fname) and not overwrite and \
            (not sha1_hash or check_sha1(fname, sha1_hash)):
        return fname
    raise MXNetError(
        f"download({url}) unavailable: no network egress. Place the file at "
        f"{fname} manually.")


def shape_is_known(shape):
    if shape is None:
        return False
    for dim_size in shape:
        if dim_size == 0 or dim_size is None:
            return False
    return True


class HookHandle:
    def __init__(self):
        self._hooks_dict_ref = None
        self._id = None

    def attach(self, hooks_list, hook):
        hooks_list.append(hook)
        self._hooks_dict_ref = hooks_list
        self._id = len(hooks_list) - 1

    def detach(self):
        if self._hooks_dict_ref:
            self._hooks_dict_ref.pop(self._id)
