from . import estimator
from . import nn
