"""Contrib layers (ref: python/mxnet/gluon/contrib/nn/basic_layers.py)."""
from __future__ import annotations

from ..block import HybridBlock, Block
from .. import nn as _nn


class HybridConcurrent(HybridBlock):
    """Apply children to same input, concat outputs (ref: contrib
    HybridConcurrent)."""

    def __init__(self, axis=-1, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self.axis = axis

    def add(self, *blocks):
        for block in blocks:
            self.register_child(block)

    def forward(self, x):
        from ... import ndarray as F
        out = [block(x) for block in self._children.values()]
        return F.concat(*out, dim=self.axis)


class Concurrent(HybridConcurrent):
    pass


class Identity(HybridBlock):
    def hybrid_forward(self, F, x):
        return F.identity(x)


class SparseEmbedding(Block):
    """Embedding with row-sparse grad semantics; dense gather on TPU
    (ref: contrib SparseEmbedding — see SURVEY §7(e))."""

    def __init__(self, input_dim, output_dim, dtype='float32',
                 weight_initializer=None, **kwargs):
        super().__init__(**kwargs)
        self._input_dim = input_dim
        self._output_dim = output_dim
        self.weight = self.params.get(
            'weight', shape=(input_dim, output_dim), dtype=dtype,
            init=weight_initializer, grad_stype='row_sparse')

    def forward(self, x):
        from ... import ndarray as F
        return F.embedding(x, self.weight.data(x.context),
                           input_dim=self._input_dim,
                           output_dim=self._output_dim, sparse_grad=True)


class PixelShuffle2D(HybridBlock):
    def __init__(self, factor):
        super().__init__()
        self._factor = int(factor)

    def hybrid_forward(self, F, x):
        return F.depth_to_space(x, block_size=self._factor)
