"""Estimator fit loop + event handlers.

Ref: python/mxnet/gluon/contrib/estimator/{estimator.py,event_handler.py}.
"""
from __future__ import annotations

import logging
import time

from ... import metric as metric_mod
from ...base import MXNetError
from ...context import cpu, num_gpus, gpu
from .. import Trainer
from ..loss import Loss as BaseLoss
from ...ndarray.utils import split_and_load
from ... import autograd


class TrainBegin:
    def train_begin(self, estimator, *args, **kwargs):
        pass


class TrainEnd:
    def train_end(self, estimator, *args, **kwargs):
        pass


class EpochBegin:
    def epoch_begin(self, estimator, *args, **kwargs):
        pass


class EpochEnd:
    def epoch_end(self, estimator, *args, **kwargs):
        pass


class BatchBegin:
    def batch_begin(self, estimator, *args, **kwargs):
        pass


class BatchEnd:
    def batch_end(self, estimator, *args, **kwargs):
        pass


class StoppingHandler(TrainBegin, BatchEnd, EpochEnd):
    """Stop after max_epoch/max_batch (ref: event_handler.py StoppingHandler)."""

    def __init__(self, max_epoch=None, max_batch=None):
        self.max_epoch = max_epoch
        self.max_batch = max_batch
        self.current_batch = 0
        self.current_epoch = 0
        self.stop_training = False

    def train_begin(self, estimator, *args, **kwargs):
        self.max_epoch = estimator.max_epoch
        self.max_batch = estimator.max_batch
        self.current_batch = 0
        self.current_epoch = 0

    def batch_end(self, estimator, *args, **kwargs):
        self.current_batch += 1
        if self.current_batch == self.max_batch:
            estimator.stop_training = True

    def epoch_end(self, estimator, *args, **kwargs):
        self.current_epoch += 1
        if self.current_epoch == self.max_epoch:
            estimator.stop_training = True


class MetricHandler(EpochBegin, BatchEnd):
    def __init__(self, train_metrics):
        self.train_metrics = train_metrics or []

    def epoch_begin(self, estimator, *args, **kwargs):
        for m in self.train_metrics:
            m.reset()

    def batch_end(self, estimator, *args, **kwargs):
        pred = kwargs['pred']
        label = kwargs['label']
        loss = kwargs['loss']
        for m in self.train_metrics:
            if isinstance(m, metric_mod.Loss):
                m.update(0, loss)
            else:
                m.update(label, pred)


class ValidationHandler(TrainBegin, BatchEnd, EpochEnd):
    def __init__(self, val_data, eval_fn, epoch_period=1, batch_period=None,
                 priority=-1000):
        self.val_data = val_data
        self.eval_fn = eval_fn
        self.epoch_period = epoch_period
        self.batch_period = batch_period
        self.current_batch = 0
        self.current_epoch = 0
        self.priority = priority

    def train_begin(self, estimator, *args, **kwargs):
        self.current_batch = 0
        self.current_epoch = 0

    def batch_end(self, estimator, *args, **kwargs):
        self.current_batch += 1
        if self.batch_period and self.current_batch % self.batch_period == 0:
            self.eval_fn(val_data=self.val_data)

    def epoch_end(self, estimator, *args, **kwargs):
        self.current_epoch += 1
        if self.epoch_period and self.current_epoch % self.epoch_period == 0:
            self.eval_fn(val_data=self.val_data)


class LoggingHandler(TrainBegin, TrainEnd, EpochBegin, EpochEnd, BatchBegin,
                     BatchEnd):
    """Ref: event_handler.py LoggingHandler."""

    LOG_PER_EPOCH = 1
    LOG_PER_BATCH = 2

    def __init__(self, log_interval='epoch', metrics=None, priority=-10000):
        self.metrics = metrics or []
        self.batch_index = 0
        self.current_epoch = 0
        self.processed_samples = 0
        self.log_interval = log_interval
        self.priority = priority
        self.logger = logging.getLogger('estimator')

    def train_begin(self, estimator, *args, **kwargs):
        self.train_start = time.time()
        self.logger.info("Training begin")

    def train_end(self, estimator, *args, **kwargs):
        train_time = time.time() - self.train_start
        msg = f'Train finished using total {train_time:.2f}s at epoch {self.current_epoch}. '
        for m in self.metrics:
            name, value = m.get()
            msg += f'{name}: {value:.4f}, '
        self.logger.info(msg.rstrip(', '))

    def batch_begin(self, estimator, *args, **kwargs):
        if self.log_interval == 'batch' or self.log_interval == self.LOG_PER_BATCH:
            self.batch_start = time.time()

    def batch_end(self, estimator, *args, **kwargs):
        if self.log_interval == 'batch' or self.log_interval == self.LOG_PER_BATCH:
            batch_time = time.time() - self.batch_start
            msg = f'[Epoch {self.current_epoch}][Batch {self.batch_index}]'
            cur_batches = kwargs.get('batch')
            if cur_batches is not None:
                self.processed_samples += cur_batches.data[0].shape[0] \
                    if hasattr(cur_batches, 'data') else 0
            msg += f' time/batch: {batch_time:.3f}s '
            for m in self.metrics:
                name, value = m.get()
                msg += f'{name}: {value:.4f}, '
            self.logger.info(msg.rstrip(', '))
        self.batch_index += 1

    def epoch_begin(self, estimator, *args, **kwargs):
        self.epoch_start = time.time()

    def epoch_end(self, estimator, *args, **kwargs):
        epoch_time = time.time() - self.epoch_start
        msg = f'[Epoch {self.current_epoch}] finished in {epoch_time:.3f}s: '
        for m in self.metrics:
            name, value = m.get()
            msg += f'{name}: {value:.4f}, '
        self.logger.info(msg.rstrip(', '))
        self.current_epoch += 1
        self.batch_index = 0


class CheckpointHandler(TrainBegin, BatchEnd, EpochEnd, TrainEnd):
    """Ref: event_handler.py CheckpointHandler — backed by
    ``checkpoint.CheckpointManager``: atomic manifests, async background
    writes, keep-last-``max_checkpoints`` retention, and optional
    preemption-safe resume (``resume_from_checkpoint=True`` restores the
    newest hash-verified checkpoint — params, optimizer state and RNG
    stream — before training starts)."""

    def __init__(self, model_dir, model_prefix='model', monitor=None,
                 verbose=0, save_best=False, mode='auto', epoch_period=1,
                 batch_period=None, max_checkpoints=5,
                 resume_from_checkpoint=False):
        import os
        if monitor is not None or save_best:
            import warnings
            warnings.warn(
                "CheckpointHandler: monitor/save_best are not supported "
                "by the manager-backed handler yet — checkpoints are "
                "retained by recency (keep-last-max_checkpoints), not by "
                "metric. These arguments are ignored.", RuntimeWarning,
                stacklevel=2)
        # checkpoints land in CheckpointManager step_* dirs under
        # model_dir, not {model_prefix}-epochN.params files; model_prefix
        # is retained for signature compatibility only
        self.model_dir = model_dir
        self.model_prefix = model_prefix
        self.epoch_period = epoch_period
        self.batch_period = batch_period
        self.max_checkpoints = max_checkpoints
        self.resume_from_checkpoint = resume_from_checkpoint
        self.current_batch = 0
        self.current_epoch = 0
        self.resumed_step = None
        self._last_saved_step = None
        self.manager = None
        os.makedirs(model_dir, exist_ok=True)

    def train_begin(self, estimator, *args, **kwargs):
        from ... import checkpoint as _checkpoint
        self.current_batch = 0
        self.current_epoch = 0
        self.manager = _checkpoint.CheckpointManager(
            self.model_dir, params=estimator.net, trainer=estimator.trainer,
            keep_last_n=max(1, self.max_checkpoints))
        # SIGTERM (preemption) commits a synchronous checkpoint at the
        # current step; the fit loop polls manager.preempted and exits
        # cleanly with a "resumable from step N" message
        self.manager.install_preemption_hook()
        if self.resume_from_checkpoint:
            self.resumed_step = self.manager.restore_latest()
            if self.resumed_step is not None:
                self.current_batch = self.resumed_step
                logging.getLogger('estimator').info(
                    'CheckpointHandler: resumed from step %d',
                    self.resumed_step)

    def batch_end(self, estimator, *args, **kwargs):
        self.current_batch += 1
        if self.batch_period and self.current_batch % self.batch_period == 0:
            self._save(metadata={'epoch': self.current_epoch})

    def epoch_end(self, estimator, *args, **kwargs):
        self.current_epoch += 1
        if self.epoch_period and self.current_epoch % self.epoch_period == 0:
            self._save(metadata={'epoch': self.current_epoch})

    def _save(self, metadata):
        # batch_period dividing the epoch's batch count makes epoch_end
        # land on the step batch_end just wrote — skip the duplicate
        # full serialize/hash/commit of a byte-identical checkpoint
        if self._last_saved_step == self.current_batch:
            return
        self._last_saved_step = self.current_batch
        self.manager.save(self.current_batch, metadata=metadata)

    def save_now(self):
        """Synchronously commit a checkpoint at the current step (the
        interrupt/preemption path). Returns the step saved, or None when
        train_begin has not run yet."""
        if self.manager is None:
            return None
        if self.manager.latest_step() != self.current_batch:
            self.manager.save_now(self.current_batch)
        self._last_saved_step = self.current_batch
        return self.current_batch

    def train_end(self, estimator, *args, **kwargs):
        if self.manager is not None:
            self.manager.close()


class WatchdogHandler(TrainBegin, BatchEnd, EpochBegin, EpochEnd,
                      TrainEnd):
    """Wires a ``resilience.StepWatchdog`` into the fit loop: one
    heartbeat per batch (plus epoch boundaries, so checkpoint saves
    between epochs don't read as stalls); when a step stalls past the
    deadline the watchdog dumps all-thread stacks + a telemetry
    snapshot to the log (and, with ``save_on_stall`` and a
    CheckpointHandler present, attempts an emergency checkpoint through
    its manager). Work that legitimately exceeds the deadline with no
    batch_end in between — a long validation pass, or the FIRST step's
    XLA trace+compile on a large model — needs a larger
    ``deadline_seconds`` or its own ``watchdog.beat()`` calls: the
    watchdog cannot see inside it and will report a (false) stall."""

    def __init__(self, deadline_seconds=None, save_on_stall=False,
                 on_stall=None):
        self.deadline_seconds = deadline_seconds
        self.save_on_stall = save_on_stall
        self.on_stall = on_stall
        self.watchdog = None
        self._step = 0

    def train_begin(self, estimator, *args, **kwargs):
        from ...resilience import StepWatchdog
        self._step = 0
        self.watchdog = StepWatchdog(
            deadline_seconds=self.deadline_seconds, manager=None,
            save_on_stall=self.save_on_stall, on_stall=self.on_stall)
        self.watchdog.start()

    def _bind_manager(self, estimator):
        # called by Estimator.fit right after every train_begin has run
        # (a CheckpointHandler listed AFTER this handler creates its
        # manager there) and BEFORE the first data fetch — the canonical
        # stall — so save_on_stall works from the very first moment
        if self.watchdog is not None and self.watchdog.manager is None:
            for h in getattr(estimator, '_event_handlers', []):
                if isinstance(h, CheckpointHandler) and \
                        h.manager is not None:
                    self.watchdog.manager = h.manager
                    break

    def batch_end(self, estimator, *args, **kwargs):
        self._step += 1
        if self.watchdog is not None:
            self.watchdog.beat(self._step)

    def epoch_begin(self, estimator, *args, **kwargs):
        if self.watchdog is not None:
            self.watchdog.beat(self._step)

    def epoch_end(self, estimator, *args, **kwargs):
        if self.watchdog is not None:
            self.watchdog.beat(self._step)

    def train_end(self, estimator, *args, **kwargs):
        if self.watchdog is not None:
            self.watchdog.stop()
            self.watchdog = None


class EarlyStoppingHandler(TrainBegin, EpochEnd, TrainEnd):
    """Ref: event_handler.py EarlyStoppingHandler."""

    def __init__(self, monitor, min_delta=0, patience=0, mode='auto',
                 baseline=None):
        import numpy as onp
        self.monitor = monitor
        self.min_delta = min_delta
        self.patience = patience
        self.baseline = baseline
        self.wait = 0
        self.stopped_epoch = 0
        self.current_epoch = 0
        self.stop_training = False
        if mode == 'min' or (mode == 'auto' and 'acc' not in monitor.get()[0]):
            self.monitor_op = onp.less
            self.min_delta *= -1
        else:
            self.monitor_op = onp.greater

    def train_begin(self, estimator, *args, **kwargs):
        import numpy as onp
        self.wait = 0
        self.stopped_epoch = 0
        self.current_epoch = 0
        self.stop_training = False
        self.best = onp.inf if self.monitor_op == onp.less else -onp.inf

    def epoch_end(self, estimator, *args, **kwargs):
        monitor_name, monitor_value = self.monitor.get()
        if monitor_value is None or monitor_value != monitor_value:
            self.current_epoch += 1
            return
        if self.monitor_op(monitor_value - self.min_delta, self.best):
            self.best = monitor_value
            self.wait = 0
        else:
            self.wait += 1
            if self.wait >= self.patience:
                self.stopped_epoch = self.current_epoch
                estimator.stop_training = True
        self.current_epoch += 1

    def train_end(self, estimator, *args, **kwargs):
        if self.stopped_epoch > 0:
            logging.getLogger('estimator').info(
                'Epoch %d: early stopping', self.stopped_epoch)


class Estimator:
    """Training loop driver (ref: estimator.py Estimator)."""

    def __init__(self, net, loss, metrics=None, initializer=None,
                 trainer=None, context=None):
        self.net = net
        self.loss = loss if isinstance(loss, (list, tuple)) else [loss]
        self.train_metrics = metrics if isinstance(metrics, list) else \
            ([metrics] if metrics else [metric_mod.Accuracy()])
        self.context = context or self._check_context()
        self._initialize(initializer)
        self.trainer = trainer or Trainer(
            self.net.collect_params(), 'sgd', {'learning_rate': 0.001})
        self.stop_training = False
        self.max_epoch = None
        self.max_batch = None

    def _check_context(self):
        if num_gpus() > 0:
            return [gpu(0)]
        return [cpu()]

    def _initialize(self, initializer):
        params = self.net.collect_params()
        uninit = any(p._data is None and not p._deferred_init
                     for p in params.values())
        try:
            self.net.initialize(init=initializer, ctx=self.context)
        except Exception:
            pass

    def evaluate(self, val_data, val_metrics=None, batch_axis=0):
        val_metrics = val_metrics or self.train_metrics
        for m in val_metrics:
            m.reset()
        for batch in val_data:
            data, label = self._get_data_and_label(batch, self.context,
                                                   batch_axis)
            pred = [self.net(x) for x in data]
            for m in val_metrics:
                if isinstance(m, metric_mod.Loss):
                    losses = [self.loss[0](yhat, y)
                              for yhat, y in zip(pred, label)]
                    m.update(0, losses)
                else:
                    m.update(label, pred)
        return val_metrics

    def _get_data_and_label(self, batch, ctx, batch_axis=0):
        if hasattr(batch, 'data'):
            data, label = batch.data[0], batch.label[0]
        else:
            data, label = batch
        data = split_and_load(data, ctx, batch_axis=batch_axis)
        label = split_and_load(label, ctx, batch_axis=batch_axis)
        return data, label

    def fit(self, train_data, val_data=None, epochs=None, event_handlers=None,
            batches=None, batch_axis=0):
        """Ref: estimator.py fit."""
        self.max_epoch = epochs
        self.max_batch = batches
        if not self.max_epoch and not self.max_batch:
            raise MXNetError("Either epochs or batches must be specified")
        event_handlers = self._prepare_default_handlers(val_data,
                                                        event_handlers)
        self._event_handlers = event_handlers
        train_begin, epoch_begin, batch_begin, batch_end, epoch_end, \
            train_end = self._categorize_handlers(event_handlers)
        self.stop_training = False
        ckpt_handler = next((h for h in event_handlers
                             if isinstance(h, CheckpointHandler)), None)
        interrupted = None
        begun = set()
        try:
            # inside the try: a later handler's train_begin raising must
            # not leak what an earlier one installed (SIGTERM hook,
            # watchdog thread)
            for handler in train_begin:
                handler.train_begin(self)
                begun.add(id(handler))
            # all managers exist now: bind them into any watchdog BEFORE
            # the first data fetch (a hung first next(train_data) is the
            # canonical stall, and save_on_stall must work for it)
            for handler in event_handlers:
                if isinstance(handler, WatchdogHandler):
                    handler._bind_manager(self)
            while not self.stop_training:
                for handler in epoch_begin:
                    handler.epoch_begin(self)
                for batch in train_data:
                    data, label = self._get_data_and_label(
                        batch, self.context, batch_axis)
                    batch_size = data[0].shape[batch_axis] * len(data)
                    for handler in batch_begin:
                        handler.batch_begin(self, batch=batch)
                    with autograd.record():
                        pred = [self.net(x) for x in data]
                        losses = [self.loss[0](yhat, y)
                                  for yhat, y in zip(pred, label)]
                    for l in losses:
                        l.backward()
                    self.trainer.step(batch_size)
                    for handler in batch_end:
                        handler.batch_end(self, batch=batch, pred=pred,
                                          label=label, loss=losses)
                    if ckpt_handler is not None and \
                            ckpt_handler.manager is not None and \
                            ckpt_handler.manager.preempted:
                        # SIGTERM: the preemption hook already committed
                        # a synchronous checkpoint — exit the loop clean
                        interrupted = 'SIGTERM'
                        self.stop_training = True
                    if self.stop_training:
                        break
                if interrupted is not None:
                    # preemption: the grace window is for the final save,
                    # not for epoch-end work (a ValidationHandler would
                    # run a full eval pass here) — save first, exit clean
                    break
                for handler in epoch_end:
                    handler.epoch_end(self)
        except KeyboardInterrupt:
            # one final synchronous save + a clean, resumable exit —
            # never a raw traceback mid-epoch
            interrupted = 'KeyboardInterrupt'
        except BaseException:
            self._emergency_teardown(event_handlers, ckpt_handler)
            raise
        try:
            if interrupted is not None:
                self._report_interrupted(interrupted, ckpt_handler)
            for handler in train_end:
                # an interrupt during the train_begin phase leaves later
                # handlers un-begun: their train_end would read state
                # their train_begin never set
                if isinstance(handler, TrainBegin) and \
                        id(handler) not in begun:
                    continue
                handler.train_end(self)
            if any(isinstance(h, TrainBegin) and id(h) not in begun
                   for h in event_handlers):
                # the interrupt landed INSIDE some train_begin: its
                # train_end was skipped above, so whatever the partial
                # train_begin already installed (SIGTERM hook, watchdog
                # thread) must still be torn down
                self._emergency_teardown(event_handlers, ckpt_handler)
        except BaseException:
            # a SECOND Ctrl-C during the final save / teardown must not
            # leak either — same cleanup as an escaping training error
            self._emergency_teardown(event_handlers, ckpt_handler)
            raise

    def _emergency_teardown(self, event_handlers, ckpt_handler):
        """train_end never runs on an escaping error, so nothing
        process-global may outlive fit: the SIGTERM handler (a later
        signal would save stale state through the abandoned manager) and
        any watchdog thread (its heartbeats stopped — it would keep
        reporting false stalls forever)."""
        if ckpt_handler is not None and ckpt_handler.manager is not None:
            ckpt_handler.manager.uninstall_preemption_hook()
        for h in event_handlers:
            if isinstance(h, WatchdogHandler) and h.watchdog is not None:
                h.watchdog.stop()
                h.watchdog = None

    def _report_interrupted(self, why, ckpt_handler):
        log = logging.getLogger('estimator')
        if ckpt_handler is None or ckpt_handler.manager is None:
            log.warning(
                'training interrupted (%s); no CheckpointHandler bound, '
                'nothing saved — add one to make interrupts resumable',
                why)
            return
        try:
            step = ckpt_handler.save_now()
            log.warning(
                'training interrupted (%s); checkpoint committed — '
                'resumable from step %s', why, step)
        except Exception:
            log.exception(
                'training interrupted (%s) but the final checkpoint '
                'save failed', why)

    def _prepare_default_handlers(self, val_data, event_handlers):
        event_handlers = list(event_handlers or [])
        added_default = []
        if not any(isinstance(h, StoppingHandler) for h in event_handlers):
            event_handlers.append(StoppingHandler(self.max_epoch,
                                                  self.max_batch))
            added_default.append('StoppingHandler')
        if not any(isinstance(h, MetricHandler) for h in event_handlers):
            event_handlers.append(MetricHandler(self.train_metrics))
            added_default.append('MetricHandler')
        if not any(isinstance(h, LoggingHandler) for h in event_handlers):
            event_handlers.append(LoggingHandler(metrics=self.train_metrics))
            added_default.append('LoggingHandler')
        if val_data is not None and \
                not any(isinstance(h, ValidationHandler) for h in event_handlers):
            event_handlers.append(ValidationHandler(val_data, self.evaluate))
        return event_handlers

    def _categorize_handlers(self, event_handlers):
        train_begin = [h for h in event_handlers if isinstance(h, TrainBegin)]
        epoch_begin = [h for h in event_handlers if isinstance(h, EpochBegin)]
        batch_begin = [h for h in event_handlers if isinstance(h, BatchBegin)]
        batch_end = [h for h in event_handlers if isinstance(h, BatchEnd)]
        epoch_end = [h for h in event_handlers if isinstance(h, EpochEnd)]
        train_end = [h for h in event_handlers if isinstance(h, TrainEnd)]
        return (train_begin, epoch_begin, batch_begin, batch_end, epoch_end,
                train_end)
