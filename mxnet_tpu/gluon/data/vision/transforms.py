"""Image transforms (ref: python/mxnet/gluon/data/vision/transforms.py)."""
from __future__ import annotations

import numpy as onp

from ...block import Block, HybridBlock
from ...nn import Sequential, HybridSequential
from ....ndarray.ndarray import NDArray, array, _invoke
from ....ops import contrib as _c


class Compose(Sequential):
    """Ref: transforms.py Compose."""

    def __init__(self, transforms):
        super().__init__()
        with self.name_scope():
            hybrid = []
            for i in transforms:
                if isinstance(i, HybridBlock):
                    hybrid.append(i)
                    continue
                elif len(hybrid) == 1:
                    self.add(hybrid[0])
                    hybrid = []
                elif len(hybrid) > 1:
                    hblock = HybridSequential()
                    with hblock.name_scope():
                        for j in hybrid:
                            hblock.add(j)
                    self.add(hblock)
                    hybrid = []
                self.add(i)
            if len(hybrid) == 1:
                self.add(hybrid[0])
            elif len(hybrid) > 1:
                hblock = HybridSequential()
                with hblock.name_scope():
                    for j in hybrid:
                        hblock.add(j)
                self.add(hblock)


class Cast(HybridBlock):
    def __init__(self, dtype='float32'):
        super().__init__()
        self._dtype = dtype

    def hybrid_forward(self, F, x):
        return F.cast(x, dtype=self._dtype)


class ToTensor(HybridBlock):
    """HWC uint8 → CHW float32 [0,1] (ref: transforms.py ToTensor)."""

    def hybrid_forward(self, F, x):
        return F.image_to_tensor(x)


class Normalize(HybridBlock):
    def __init__(self, mean=0.0, std=1.0):
        super().__init__()
        self._mean = mean if isinstance(mean, (tuple, list)) else (mean,) * 3
        self._std = std if isinstance(std, (tuple, list)) else (std,) * 3

    def hybrid_forward(self, F, x):
        return F.image_normalize(x, mean=self._mean, std=self._std)


class Resize(Block):
    def __init__(self, size, keep_ratio=False, interpolation=1):
        super().__init__()
        self._size = size
        self._keep = keep_ratio
        self._interpolation = interpolation

    def forward(self, x):
        return _invoke(_c.image_resize, x, size=self._size,
                       keep_ratio=self._keep, interp=self._interpolation)


class CenterCrop(Block):
    def __init__(self, size, interpolation=1):
        super().__init__()
        self._size = size if isinstance(size, (tuple, list)) else (size, size)

    def forward(self, x):
        w, h = self._size
        ih, iw = x.shape[-3], x.shape[-2]
        y0 = max(0, (ih - h) // 2)
        x0 = max(0, (iw - w) // 2)
        return _invoke(_c.image_crop, x, x=x0, y=y0, width=w, height=h)


class RandomResizedCrop(Block):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4., 4 / 3.),
                 interpolation=1):
        super().__init__()
        self._size = size if isinstance(size, (tuple, list)) else (size, size)
        self._scale = scale
        self._ratio = ratio

    def forward(self, x):
        import math
        ih, iw = x.shape[-3], x.shape[-2]
        area = ih * iw
        for _ in range(10):
            target_area = onp.random.uniform(*self._scale) * area
            aspect = math.exp(onp.random.uniform(math.log(self._ratio[0]),
                                                 math.log(self._ratio[1])))
            w = int(round(math.sqrt(target_area * aspect)))
            h = int(round(math.sqrt(target_area / aspect)))
            if w <= iw and h <= ih:
                x0 = onp.random.randint(0, iw - w + 1)
                y0 = onp.random.randint(0, ih - h + 1)
                out = _invoke(_c.image_crop, x, x=x0, y=y0, width=w, height=h)
                return _invoke(_c.image_resize, out, size=self._size)
        return _invoke(_c.image_resize, x, size=self._size)


class RandomFlipLeftRight(HybridBlock):
    def hybrid_forward(self, F, x):
        if onp.random.rand() < 0.5:
            return F.image_flip_left_right(x)
        return F.identity(x)


class RandomFlipTopBottom(HybridBlock):
    def hybrid_forward(self, F, x):
        if onp.random.rand() < 0.5:
            return F.image_flip_top_bottom(x)
        return F.identity(x)


class RandomCrop(Block):
    def __init__(self, size, pad=None, interpolation=1):
        super().__init__()
        self._size = size if isinstance(size, (tuple, list)) else (size, size)
        self._pad = pad

    def forward(self, x):
        w, h = self._size
        data = x
        if self._pad:
            p = self._pad
            import jax.numpy as jnp
            data = NDArray(jnp.pad(x._data, ((p, p), (p, p), (0, 0))))
        ih, iw = data.shape[-3], data.shape[-2]
        y0 = onp.random.randint(0, max(1, ih - h + 1))
        x0 = onp.random.randint(0, max(1, iw - w + 1))
        return _invoke(_c.image_crop, data, x=x0, y=y0, width=w, height=h)


class RandomBrightness(Block):
    def __init__(self, brightness):
        super().__init__()
        self._brightness = brightness

    def forward(self, x):
        alpha = 1.0 + onp.random.uniform(-self._brightness, self._brightness)
        return x * alpha


class RandomContrast(Block):
    def __init__(self, contrast):
        super().__init__()
        self._contrast = contrast

    def forward(self, x):
        alpha = 1.0 + onp.random.uniform(-self._contrast, self._contrast)
        gray = x.mean()
        return x * alpha + gray * (1 - alpha)


class RandomSaturation(Block):
    def __init__(self, saturation):
        super().__init__()
        self._saturation = saturation

    def forward(self, x):
        alpha = 1.0 + onp.random.uniform(-self._saturation, self._saturation)
        import jax.numpy as jnp
        coef = jnp.asarray([[[0.299]], [[0.587]], [[0.114]]], dtype=x._data.dtype)
        if x.ndim == 3 and x.shape[-1] == 3:
            coef = coef.reshape(1, 1, 3)
        gray = NDArray((x._data * coef).sum(axis=-1 if x.shape[-1] == 3 else 0,
                                            keepdims=True))
        return x * alpha + gray * (1 - alpha)


class RandomColorJitter(Sequential):
    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0):
        super().__init__()
        with self.name_scope():
            if brightness:
                self.add(RandomBrightness(brightness))
            if contrast:
                self.add(RandomContrast(contrast))
            if saturation:
                self.add(RandomSaturation(saturation))
