"""Vision datasets (ref: python/mxnet/gluon/data/vision/datasets.py).

No network egress in this environment: datasets read standard files from
`root` if present (idx-format MNIST, CIFAR binary batches); otherwise a
deterministic synthetic fallback with the right shapes/classes is generated
so examples and tests run hermetically.
"""
from __future__ import annotations

import gzip
import os
import struct

import numpy as onp

from ..dataset import Dataset, ArrayDataset
from ....ndarray.ndarray import array


class _DownloadableDataset(Dataset):
    def __init__(self, root, train, transform):
        self._root = os.path.expanduser(root)
        self._train = train
        self._transform = transform
        self._data = None
        self._label = None
        self._get_data()

    def __getitem__(self, idx):
        if self._transform is not None:
            return self._transform(array(self._data[idx]), self._label[idx])
        return array(self._data[idx]), self._label[idx]

    def __len__(self):
        return len(self._label)


def _synthetic(n, shape, num_classes, seed):
    rng = onp.random.RandomState(seed)
    data = (rng.rand(n, *shape) * 255).astype(onp.uint8)
    label = rng.randint(0, num_classes, n).astype(onp.int32)
    return data, label


class MNIST(_DownloadableDataset):
    """MNIST; reads idx files from root if available (ref: datasets.py MNIST)."""

    _train_files = ('train-images-idx3-ubyte', 'train-labels-idx1-ubyte')
    _test_files = ('t10k-images-idx3-ubyte', 't10k-labels-idx1-ubyte')
    _synth_n = 1024

    def __init__(self, root=os.path.join('~', '.mxnet', 'datasets', 'mnist'),
                 train=True, transform=None):
        super().__init__(root, train, transform)

    def _read_idx(self, path):
        opener = gzip.open if path.endswith('.gz') else open
        with opener(path, 'rb') as f:
            magic = struct.unpack('>HBB', f.read(4))
            dims = struct.unpack('>' + 'I' * magic[2], f.read(4 * magic[2]))
            return onp.frombuffer(f.read(), dtype=onp.uint8).reshape(dims)

    def _get_data(self):
        files = self._train_files if self._train else self._test_files
        img_path = None
        for suffix in ('', '.gz'):
            cand = os.path.join(self._root, files[0] + suffix)
            if os.path.exists(cand):
                img_path = cand
                lab_path = os.path.join(self._root, files[1] + suffix)
                break
        if img_path:
            data = self._read_idx(img_path)
            label = self._read_idx(lab_path)
            self._data = data.reshape(-1, 28, 28, 1)
            self._label = label.astype(onp.int32)
        else:
            self._data, self._label = _synthetic(
                self._synth_n, (28, 28, 1), 10, 42 if self._train else 43)


class FashionMNIST(MNIST):
    def __init__(self, root=os.path.join('~', '.mxnet', 'datasets',
                                         'fashion-mnist'),
                 train=True, transform=None):
        super().__init__(root, train, transform)


class CIFAR10(_DownloadableDataset):
    """CIFAR-10 from binary batches (ref: datasets.py CIFAR10)."""

    _synth_n = 1024

    def __init__(self, root=os.path.join('~', '.mxnet', 'datasets', 'cifar10'),
                 train=True, transform=None):
        self._num_classes = 10
        super().__init__(root, train, transform)

    def _read_batch(self, filename):
        with open(filename, 'rb') as fin:
            raw = onp.frombuffer(fin.read(), dtype=onp.uint8)
        row = 3072 + self._label_bytes()
        data = raw.reshape(-1, row)
        label = data[:, self._label_bytes() - 1].astype(onp.int32)
        img = data[:, self._label_bytes():].reshape(-1, 3, 32, 32)
        return img.transpose(0, 2, 3, 1), label

    def _label_bytes(self):
        return 1

    def _get_data(self):
        if self._train:
            files = [f'data_batch_{i}.bin' for i in range(1, 6)]
        else:
            files = ['test_batch.bin']
        paths = [os.path.join(self._root, f) for f in files]
        if all(os.path.exists(p) for p in paths):
            data, label = zip(*(self._read_batch(p) for p in paths))
            self._data = onp.concatenate(data)
            self._label = onp.concatenate(label)
        else:
            self._data, self._label = _synthetic(
                self._synth_n, (32, 32, 3), self._num_classes,
                44 if self._train else 45)


class CIFAR100(CIFAR10):
    def __init__(self, root=os.path.join('~', '.mxnet', 'datasets', 'cifar100'),
                 fine_label=False, train=True, transform=None):
        self._fine_label = fine_label
        self._num_classes = 100
        _DownloadableDataset.__init__(self, root, train, transform)

    def _label_bytes(self):
        return 2

    def _get_data(self):
        files = ['train.bin'] if self._train else ['test.bin']
        paths = [os.path.join(self._root, f) for f in files]
        if all(os.path.exists(p) for p in paths):
            data, label = zip(*(self._read_batch(p) for p in paths))
            self._data = onp.concatenate(data)
            self._label = onp.concatenate(label)
        else:
            self._data, self._label = _synthetic(
                self._synth_n, (32, 32, 3), 100, 46 if self._train else 47)


class ImageRecordDataset(Dataset):
    """Dataset over a RecordIO of packed images (ref: datasets.py
    ImageRecordDataset)."""

    def __init__(self, filename, flag=1, transform=None):
        from .... import recordio
        self._transform = transform
        self._flag = flag
        idx_file = os.path.splitext(filename)[0] + '.idx'
        self._record = recordio.MXIndexedRecordIO(idx_file, filename, 'r')

    def __getitem__(self, idx):
        from .... import recordio
        record = self._record.read_idx(self._record.keys[idx])
        header, img = recordio.unpack_img(record)
        label = header.label
        if self._transform is not None:
            return self._transform(array(img), label)
        return array(img), label

    def __len__(self):
        return len(self._record.keys)


class ImageFolderDataset(Dataset):
    """Images arranged in class folders (ref: datasets.py ImageFolderDataset)."""

    def __init__(self, root, flag=1, transform=None):
        self._root = os.path.expanduser(root)
        self._flag = flag
        self._transform = transform
        self._exts = ['.jpg', '.jpeg', '.png']
        self._list_images(self._root)

    def _list_images(self, root):
        self.synsets = []
        self.items = []
        for folder in sorted(os.listdir(root)):
            path = os.path.join(root, folder)
            if not os.path.isdir(path):
                continue
            label = len(self.synsets)
            self.synsets.append(folder)
            for filename in sorted(os.listdir(path)):
                if os.path.splitext(filename)[1].lower() in self._exts:
                    self.items.append((os.path.join(path, filename), label))

    def __getitem__(self, idx):
        from PIL import Image
        img = onp.asarray(Image.open(self.items[idx][0]).convert(
            'RGB' if self._flag else 'L'))
        label = self.items[idx][1]
        if self._transform is not None:
            return self._transform(array(img), label)
        return array(img), label

    def __len__(self):
        return len(self.items)
