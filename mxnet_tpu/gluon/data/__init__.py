from .dataset import (Dataset, SimpleDataset, ArrayDataset,
                      RecordFileDataset)
from .sampler import (Sampler, SequentialSampler, RandomSampler,
                      FilterSampler, BatchSampler, ElasticSampler,
                      IntervalSampler)
from .dataloader import DataLoader
from . import vision
