"""DataLoader (ref: python/mxnet/gluon/data/dataloader.py).

The reference uses multiprocessing workers with shared-memory NDArray
pickling (dataloader.py:121-186). Host decode on TPU VMs is plentiful, and
jax arrays don't share across fork, so num_workers maps to a PERSISTENT
thread pool (one executor for the loader's lifetime, not one per epoch) —
decode/augment release the GIL in PIL/numpy, and with pin_memory=True
batches are device_put from the workers so host->device copies overlap
the training step.
"""
from __future__ import annotations

from concurrent.futures import ThreadPoolExecutor

import numpy as onp

from ...base import DataError, MXNetError, telem_flags as _telem
from ...ndarray.ndarray import NDArray, array
from ...resilience import faults as _faults
from ...telemetry import trace as _trace
from .sampler import SequentialSampler, RandomSampler, BatchSampler


def default_batchify_fn(data):
    """Stack samples into a batch (ref: dataloader.py default_batchify_fn)."""
    if isinstance(data[0], NDArray):
        return array(onp.stack([d.asnumpy() for d in data]))
    if isinstance(data[0], tuple):
        data = zip(*data)
        return [default_batchify_fn(i) for i in data]
    data = onp.asarray(data)
    return array(data)


def default_mp_batchify_fn(data):
    return default_batchify_fn(data)


class DataLoader:
    """Ref: dataloader.py DataLoader."""

    def __init__(self, dataset, batch_size=None, shuffle=False, sampler=None,
                 last_batch=None, batch_sampler=None, batchify_fn=None,
                 num_workers=0, pin_memory=False, pin_device_id=0,
                 prefetch=None, thread_pool=False, timeout=120,
                 worker_retries=None):
        self._dataset = dataset
        self._pin_memory = pin_memory
        if worker_retries is None:
            from ... import config as _config
            worker_retries = _config.get('MXTPU_DATALOADER_WORKER_RETRIES')
        self._worker_retries = max(0, int(worker_retries))
        if batch_sampler is None:
            if batch_size is None:
                raise ValueError("batch_size must be specified unless "
                                 "batch_sampler is specified")
            if sampler is None:
                if shuffle:
                    sampler = RandomSampler(len(dataset))
                else:
                    sampler = SequentialSampler(len(dataset))
            elif shuffle:
                raise ValueError("shuffle must not be specified if sampler is "
                                 "specified")
            batch_sampler = BatchSampler(sampler, batch_size,
                                         last_batch or 'keep')
        elif batch_size is not None or shuffle or sampler is not None or \
                last_batch is not None:
            raise ValueError("batch_size, shuffle, sampler and last_batch must "
                             "not be specified if batch_sampler is specified.")
        self._batch_sampler = batch_sampler
        self._num_workers = num_workers if num_workers >= 0 else 0
        self._prefetch = max(0, int(prefetch) if prefetch is not None
                             else 2 * self._num_workers)
        if batchify_fn is None:
            batchify_fn = default_batchify_fn
        self._batchify_fn = batchify_fn
        self._pin_device_id = pin_device_id
        # persistent worker pool: created on first multi-worker epoch and
        # reused for the loader's lifetime — per-epoch executor spin-up
        # (thread creation x num_workers, every epoch) was pure overhead
        self._pool = None

    def _worker_pool(self):
        if self._pool is None:
            self._pool = ThreadPoolExecutor(
                max_workers=self._num_workers,
                thread_name_prefix='mxtpu-dataloader')
        return self._pool

    def _fetch(self, batch):
        # worker-thread span: overlapped work, reported in the span
        # table but excluded from attribution's wall-time buckets
        with _trace.span('io.worker_fetch', batch_len=len(batch)):
            _faults.fire('dataloader.worker')
            out = self._batchify_fn([self._dataset[idx] for idx in batch])
            if self._pin_memory:
                with _trace.span('h2d.pin'):
                    out = self._device_put(out)
        return out

    def _result_with_respawn(self, future, batch, batch_idx):
        """Surface a worker future's result; a crashed worker (any
        exception) gets the batch re-submitted to the pool — the shared
        ``resilience.retry_call`` bounded policy, counted in telemetry —
        before a clear error names the batch that kept failing.
        DataError (deterministic input corruption) propagates unchanged
        and unretried so callers keep the index/offset/path context (the
        iterator-level corrupt_policy stays the skip knob)."""
        from ...resilience import retry_call
        first = {'f': future}

        def fetch_result():
            f = first.pop('f', None)
            if f is None:           # respawn: re-submit the same batch
                if _telem['on']:
                    from ... import telemetry as _telemetry
                    _telemetry.inc(
                        'mxnet_tpu_resilience_worker_respawns_total')
                f = self._worker_pool().submit(self._fetch, batch)
            return f.result()

        try:
            # consumer-side wait on the worker future: input-bound time
            with _trace.span('io.wait'):
                return retry_call(fetch_result,
                                  retries=self._worker_retries,
                                  backoff_seconds=0, retry_on=(Exception,),
                                  give_up_on=(DataError,),
                                  site='dataloader.worker')
        except DataError:
            raise
        except Exception as e:
            raise MXNetError(
                f"DataLoader worker failed {self._worker_retries + 1}x "
                f"on batch {batch_idx} (respawn budget "
                f"{self._worker_retries} exhausted): "
                f"{type(e).__name__}: {e}") from e

    @staticmethod
    def _device_put(out):
        """Stage a batchified sample on device from the worker thread —
        jax dispatch is async, so the host->device copy overlaps the
        consumer's compute (the TPU analog of pinned-memory staging)."""
        import jax
        if isinstance(out, NDArray):
            return NDArray(jax.device_put(out._data))
        if isinstance(out, (list, tuple)):
            return type(out)(DataLoader._device_put(o) for o in out)
        return out

    def __iter__(self):
        if self._num_workers == 0:
            for batch in self._batch_sampler:
                # same fetch body as the worker path (incl. the
                # dataloader.worker fault site), minus pool + respawn
                yield self._fetch(batch)
            return

        pool = self._worker_pool()
        batches = list(self._batch_sampler)
        depth = max(1, self._prefetch)
        futures = []
        it = iter(enumerate(batches))
        for _ in range(depth):
            try:
                i, b = next(it)
                futures.append((pool.submit(self._fetch, b), b, i))
            except StopIteration:
                break
        while futures:
            f, b, i = futures.pop(0)
            try:
                j, nb = next(it)
                futures.append((pool.submit(self._fetch, nb), nb, j))
            except StopIteration:
                pass
            yield self._result_with_respawn(f, b, i)

    def data_state(self):
        """Manifest-ready data-position state when the batch sampler is
        elastic (``ElasticSampler`` / anything with ``state()``), else
        None. Bind to a CheckpointManager via ``bind_data_state`` so
        every commit records where the sample stream stood — the half
        of a re-form that makes resumes exactly-once."""
        st = getattr(self._batch_sampler, 'state', None)
        return st() if callable(st) else None

    def reshard(self, rank, world):
        """Re-partition an elastic batch sampler after a re-form
        (shrink or grow): same global position, new per-rank block."""
        rs = getattr(self._batch_sampler, 'reshard', None)
        if not callable(rs):
            raise MXNetError(
                "DataLoader: batch sampler is not elastic (pass "
                "batch_sampler=ElasticSampler(...) for world-indexed "
                "deterministic assignment)")
        rs(rank, world)
        return self

    def close(self):
        if self._pool is not None:
            self._pool.shutdown(wait=False)
            self._pool = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    def __len__(self):
        return len(self._batch_sampler)
