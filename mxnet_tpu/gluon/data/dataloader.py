"""DataLoader (ref: python/mxnet/gluon/data/dataloader.py).

The reference uses multiprocessing workers with shared-memory NDArray
pickling (dataloader.py:121-186). Host decode on TPU VMs is plentiful, and
jax arrays don't share across fork, so num_workers maps to a PERSISTENT
thread pool (one executor for the loader's lifetime, not one per epoch) —
decode/augment release the GIL in PIL/numpy, and with pin_memory=True
batches are device_put from the workers so host->device copies overlap
the training step.
"""
from __future__ import annotations

from concurrent.futures import ThreadPoolExecutor

import numpy as onp

from ...ndarray.ndarray import NDArray, array
from .sampler import SequentialSampler, RandomSampler, BatchSampler


def default_batchify_fn(data):
    """Stack samples into a batch (ref: dataloader.py default_batchify_fn)."""
    if isinstance(data[0], NDArray):
        return array(onp.stack([d.asnumpy() for d in data]))
    if isinstance(data[0], tuple):
        data = zip(*data)
        return [default_batchify_fn(i) for i in data]
    data = onp.asarray(data)
    return array(data)


def default_mp_batchify_fn(data):
    return default_batchify_fn(data)


class DataLoader:
    """Ref: dataloader.py DataLoader."""

    def __init__(self, dataset, batch_size=None, shuffle=False, sampler=None,
                 last_batch=None, batch_sampler=None, batchify_fn=None,
                 num_workers=0, pin_memory=False, pin_device_id=0,
                 prefetch=None, thread_pool=False, timeout=120):
        self._dataset = dataset
        self._pin_memory = pin_memory
        if batch_sampler is None:
            if batch_size is None:
                raise ValueError("batch_size must be specified unless "
                                 "batch_sampler is specified")
            if sampler is None:
                if shuffle:
                    sampler = RandomSampler(len(dataset))
                else:
                    sampler = SequentialSampler(len(dataset))
            elif shuffle:
                raise ValueError("shuffle must not be specified if sampler is "
                                 "specified")
            batch_sampler = BatchSampler(sampler, batch_size,
                                         last_batch or 'keep')
        elif batch_size is not None or shuffle or sampler is not None or \
                last_batch is not None:
            raise ValueError("batch_size, shuffle, sampler and last_batch must "
                             "not be specified if batch_sampler is specified.")
        self._batch_sampler = batch_sampler
        self._num_workers = num_workers if num_workers >= 0 else 0
        self._prefetch = max(0, int(prefetch) if prefetch is not None
                             else 2 * self._num_workers)
        if batchify_fn is None:
            batchify_fn = default_batchify_fn
        self._batchify_fn = batchify_fn
        self._pin_device_id = pin_device_id
        # persistent worker pool: created on first multi-worker epoch and
        # reused for the loader's lifetime — per-epoch executor spin-up
        # (thread creation x num_workers, every epoch) was pure overhead
        self._pool = None

    def _worker_pool(self):
        if self._pool is None:
            self._pool = ThreadPoolExecutor(
                max_workers=self._num_workers,
                thread_name_prefix='mxtpu-dataloader')
        return self._pool

    def _fetch(self, batch):
        out = self._batchify_fn([self._dataset[idx] for idx in batch])
        if self._pin_memory:
            out = self._device_put(out)
        return out

    @staticmethod
    def _device_put(out):
        """Stage a batchified sample on device from the worker thread —
        jax dispatch is async, so the host->device copy overlaps the
        consumer's compute (the TPU analog of pinned-memory staging)."""
        import jax
        if isinstance(out, NDArray):
            return NDArray(jax.device_put(out._data))
        if isinstance(out, (list, tuple)):
            return type(out)(DataLoader._device_put(o) for o in out)
        return out

    def __iter__(self):
        if self._num_workers == 0:
            for batch in self._batch_sampler:
                out = self._batchify_fn(
                    [self._dataset[idx] for idx in batch])
                yield self._device_put(out) if self._pin_memory else out
            return

        pool = self._worker_pool()
        batches = list(self._batch_sampler)
        depth = max(1, self._prefetch)
        futures = []
        it = iter(batches)
        for _ in range(depth):
            try:
                futures.append(pool.submit(self._fetch, next(it)))
            except StopIteration:
                break
        while futures:
            f = futures.pop(0)
            try:
                futures.append(pool.submit(self._fetch, next(it)))
            except StopIteration:
                pass
            yield f.result()

    def close(self):
        if self._pool is not None:
            self._pool.shutdown(wait=False)
            self._pool = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    def __len__(self):
        return len(self._batch_sampler)
