"""Samplers (ref: python/mxnet/gluon/data/sampler.py)."""
from __future__ import annotations

import numpy as onp


class Sampler:
    def __iter__(self):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class SequentialSampler(Sampler):
    def __init__(self, length, start=0):
        self._length = length
        self._start = start

    def __iter__(self):
        return iter(range(self._start, self._start + self._length))

    def __len__(self):
        return self._length


class RandomSampler(Sampler):
    def __init__(self, length):
        self._length = length

    def __iter__(self):
        indices = onp.arange(self._length)
        onp.random.shuffle(indices)
        return iter(indices.tolist())

    def __len__(self):
        return self._length


class FilterSampler(Sampler):
    def __init__(self, fn, dataset):
        self._indices = [i for i, sample in enumerate(dataset) if fn(sample)]

    def __iter__(self):
        return iter(self._indices)

    def __len__(self):
        return len(self._indices)


class BatchSampler(Sampler):
    """Ref: sampler.py BatchSampler; last_batch in {keep, discard, rollover}."""

    def __init__(self, sampler, batch_size, last_batch='keep'):
        self._sampler = sampler
        self._batch_size = batch_size
        self._last_batch = last_batch
        self._prev = []

    def __iter__(self):
        batch, self._prev = self._prev, []
        for i in self._sampler:
            batch.append(i)
            if len(batch) == self._batch_size:
                yield batch
                batch = []
        if batch:
            if self._last_batch == 'keep':
                yield batch
            elif self._last_batch == 'discard':
                return
            elif self._last_batch == 'rollover':
                self._prev = batch
            else:
                raise ValueError(f"last_batch must be one of 'keep', 'discard', "
                                 f"or 'rollover', but got {self._last_batch}")

    def __len__(self):
        if self._last_batch == 'keep':
            return (len(self._sampler) + self._batch_size - 1) // self._batch_size
        if self._last_batch == 'discard':
            return len(self._sampler) // self._batch_size
        if self._last_batch == 'rollover':
            return (len(self._prev) + len(self._sampler)) // self._batch_size
        raise ValueError(f"last_batch must be one of 'keep', 'discard', or "
                         f"'rollover', but got {self._last_batch}")


class ElasticSampler(Sampler):
    """Batch sampler with world-indexed deterministic sample
    assignment for elastic data parallelism. Wraps
    ``io.ElasticShard``: each ``__iter__`` pass yields this rank's
    block of successive GLOBAL batches (so it plugs into
    ``DataLoader(batch_sampler=...)``), the global position is stream
    state that survives ``reset``/re-iteration and round-trips through
    the checkpoint manifest (``state()``/``from_state``), and
    ``reshard(rank, world)`` re-partitions the same global sequence
    after a shrink or grow — no sample dropped or double-seen across
    any world-size history."""

    def __init__(self, length, global_batch, rank=0, world=1, seed=0,
                 position=0, shuffle=True, shard=None):
        from ...io.io import ElasticShard
        self._shard = shard if shard is not None else ElasticShard(
            length, global_batch, rank=rank, world=world, seed=seed,
            position=position, shuffle=shuffle)

    @property
    def shard(self):
        return self._shard

    def __iter__(self):
        for _ in range(len(self)):
            yield self._shard.next_batch()

    def __len__(self):
        # batches per pass: one epoch's worth of GLOBAL batches (the
        # stream itself is unbounded — epoch wrap re-permutes)
        return max(1, self._shard.num_samples // self._shard.global_batch)

    def reshard(self, rank, world):
        self._shard.reshard(rank, world)
        return self

    def state(self):
        return self._shard.state()

    @classmethod
    def from_state(cls, state, rank=None, world=None):
        from ...io.io import ElasticShard
        return cls(1, 1, shard=ElasticShard.from_state(
            state, rank=rank, world=world))


class IntervalSampler(Sampler):
    def __init__(self, length, interval, rollover=True):
        self._length = length
        self._interval = interval
        self._rollover = rollover

    def __iter__(self):
        for i in range(self._interval if self._rollover else 1):
            for j in range(i, self._length, self._interval):
                yield j

    def __len__(self):
        return self._length
